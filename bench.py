#!/usr/bin/env python3
"""Benchmark: word-count throughput on a synthetic Zipf corpus (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

The reference publishes no numbers and physically caps at ~5.8 KB of input
(SURVEY §6), so the baseline here is the natural host-CPU implementation a
user would reach for (``collections.Counter(data.split())``), measured on a
slice of the same corpus; ``vs_baseline`` is our GB/s over its GB/s.

Headline metric = the device MapReduce pipeline (tokenize + hash + count +
merge) on device-resident chunks, i.e. the part of the stack this framework
owns.  Host->device staging is measured and reported separately
(``h2d_gbps``): in this harness the chip sits behind a network tunnel whose
~15 MB/s H2D link would otherwise be the only thing measured; on a real TPU
host, local DMA far exceeds the pipeline rate and the headline number is the
end-to-end bound.

Measurement shape: the whole corpus is staged on device once, then the timed
window is ONE ``Engine.step_many(..., repeats=R)`` dispatch that cycles the
resident chunks R times (epoch semantics) — processing corpus*R bytes of
map+combine work in a single program.  Measured through the tunnel, each
dispatch costs ~0.6 s in link latency against ~9 ms/chunk of real compute;
folding the repeat loop inside the compiled scan is what keeps the link out
of the measurement.

Env knobs: BENCH_MB (corpus size, default 256 — sized so H2D staging
through the ~4-20 MB/s tunnel stays within the driver budget; the timed
window is corpus*BENCH_REPEATS regardless), BENCH_CHUNK_MB (per-device
step size, default 32 — the measured sweet spot on v5e), BENCH_REPEATS
(device passes over the resident corpus in the timed dispatch, default 8),
BENCH_SUPERSTEP (override chunks per dispatch; default: all resident),
BENCH_BASELINE_MB (CPU baseline slice, default 16), BENCH_SORT_MODE /
BENCH_SORT_IMPL / BENCH_MAP_IMPL / BENCH_COMBINER / BENCH_GEOMETRY /
BENCH_MERGE_EVERY /
BENCH_MERGE_STRATEGY (tree / gather / keyrange / hier-kr-tree /
hier-tree-tree / auto — the reduction seam the static planner
`tools/redplan.py` ranks; keyrange is the planner's skew-sensitive
alternative, the hier-* 2-D programs need a fleet mesh, and 'auto'
warm-starts from the planner's freshest tuned.json profile via
resolve_prior — the resolved strategy is stamped, never 'auto';
BENCH_MERGE_PROFILE overrides the profile path) /
BENCH_MERGE_OVERLAP (1 = drain local tables into a resident accumulator
at window boundaries on the STREAMED pass — async partial collectives
overlapped with the map stream, bit-identical results, op='partial'
ledger records; ISSUE 20 leg 2) /
BENCH_COMPACT_SLOTS /
BENCH_INFLIGHT / BENCH_PREFETCH_DEPTH (A/B knobs — measurement-altering,
so BENCH_LAST_GOOD refuses them; BENCH_INFLIGHT=1 is the serialized
dispatch-window control, see Config.inflight_groups; BENCH_MAP_IMPL=fused
runs the ISSUE 6 fused map kernel, see Config.map_impl;
BENCH_COMBINER=hot-cache runs the ISSUE 11 map-side combiner on top of
it, see Config.combiner).

BENCH JSON carries a `cost` record: the static hbm-cost pricing
(`effective_input_passes`) of the benched map path's registry twin
(wordcount_fused vs wordcount_pallas), so every bench row states the
predicted HBM-pass count next to the measured GB/s — the fused-vs-split
A/B rows in benchwatch read the predicted delta and the measured delta
from the same JSON.

BENCH_LAST_GOOD.json additionally carries per-metric BEST-KNOWN records
(headline / streamed / h2d, each timestamped) alongside the last run; a
metric regressing >25% under an otherwise-equal config cannot displace its
best-known record unless BENCH_FORCE_LAST_GOOD=1 deliberately re-baselines
(VERDICT r5 #2: a collapsed streamed number silently clobbered the only
durable streamed evidence).  Every refused write logs to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

# Written on every successful run, read back into the `last_good` field of any
# failure JSON — a wedged-relay window still carries the last measured
# evidence instead of a bare 0.0.
LAST_GOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_LAST_GOOD.json")


def _read_last_good():
    try:
        with open(LAST_GOOD_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fail_json(reason: str, attempts=None) -> None:
    out = {
        "metric": "zipf_wordcount_device_throughput",
        "value": 0.0, "unit": "GB/s", "vs_baseline": 0.0,
        "error": reason,
    }
    last_good = _read_last_good()
    if last_good:
        out["last_good"] = last_good
    if attempts:
        out["probe_attempts"] = attempts
    print(json.dumps(out), flush=True)


def make_zipf_corpus(n_bytes: int, vocab: int = 50_000, a: float = 1.3,
                     seed: int = 7) -> bytes:
    rng = np.random.default_rng(seed)
    words = np.array([b"w%d" % i for i in range(vocab)], dtype=object)
    # Zipf draws skew short (w1, w2, ...), so bytes-per-word is corpus-
    # dependent: generate in slabs until the requested size is reached.
    parts, have = [], 0
    while have < n_bytes:
        idx = rng.zipf(a, size=1 << 20).astype(np.int64) % vocab
        slab = b" ".join(words[idx]) + b" "
        parts.append(slab)
        have += len(slab)
    blob = b"".join(parts)
    return blob[:n_bytes].rsplit(b" ", 1)[0] + b"\n"


# ~200 high-frequency English words: the head of a realistic unigram
# distribution (the tail is synthesized below with rarer, longer forms).
_COMMON = ("the of and to in a is that it was for on are as with his they at"
           " be this have from or one had by word but not what all were we"
           " when your can said there use an each which she do how their if"
           " will up other about out many then them these so some her would"
           " make like him into time has look two more write go see number"
           " no way could people my than first water been call who oil its"
           " now find long down day did get come made may part over new sound"
           " take only little work know place year live me back give most"
           " very after thing our just name good sentence man think say great"
           " where help through much before line right too mean old any same"
           " tell boy follow came want show also around form three small set"
           " put end does another well large must big even such because turn"
           " here why ask went men read need land different home us move try"
           " kind hand picture again change off play spell air away animal"
           " house point page letter mother answer found study still learn"
           " should america world high every near add food between own below"
           " country plant last school father keep tree never start city"
           " earth eye light thought head under story saw left dont few while"
           " along might close something seem next hard open example begin"
           " life always those both paper together got group often run").split()


def make_natural_corpus(n_bytes: int, seed: int = 11) -> bytes:
    """English-like text proxy (enwik8 stand-in: nothing real is mountable).

    Unlike the pure-Zipf corpus, this has the statistics that stress the
    pipeline the way natural text does: punctuation attached to words (so
    ``word`` / ``word,`` / ``word.`` are distinct tokens), sentence-initial
    capitalization (more distinct casings), a heavy head of short common
    words plus a long tail of rarer coined forms, variable sentence and
    paragraph lengths, and occasional markup-ish tokens.  Fully vectorized
    per slab (numpy choice + np.char ops): generation must not dominate the
    run at BENCH_MB=256+.
    """
    rng = np.random.default_rng(seed)
    head = np.array(_COMMON)
    tail = np.array([f"{head[i % len(head)]}{head[(i * 7 + 3) % len(head)]}"
                     + ("ing" if i % 3 else "s") for i in range(20_000)])
    parts: list[bytes] = []
    have = 0
    slab_n = 200_000  # words per vectorized slab (~1.1 MB)
    while have < n_bytes:
        words = np.where(rng.random(slab_n) < 0.18,
                         tail[rng.integers(0, len(tail), size=slab_n)],
                         head[rng.integers(0, len(head), size=slab_n)])
        # Sentence ends (~every 12 words); the following word starts a
        # sentence and is capitalized.
        ends = rng.random(slab_n) < (1 / 12)
        starts = np.concatenate([[True], ends[:-1]])
        words[starts] = np.char.capitalize(words[starts])
        # Markup-ish tokens replace ~0.5% of words.
        mk = rng.random(slab_n) < 0.005
        words[mk] = np.where(rng.random(int(mk.sum())) < 0.5,
                             "[[link]]", "&quot;")
        # Punctuation: terminal . / ? at ends, commas mid-sentence.
        r = rng.random(slab_n)
        suffix = np.where(ends, np.where(r < 0.9, ".", "?"),
                          np.where(r < 0.06, ",", ""))
        # Paragraph breaks after ~12% of sentence ends.
        sep = np.where(ends & (rng.random(slab_n) < 0.12), "\n", " ")
        slab = "".join(np.char.add(np.char.add(words, suffix), sep).tolist()) \
            .encode()
        parts.append(slab)
        have += len(slab)
    return b"".join(parts)[:n_bytes].rsplit(b" ", 1)[0] + b"\n"


def make_webby_corpus(n_bytes: int, seed: int = 23) -> bytes:
    """Natural-text proxy with an enwik-like long-token tail.

    enwik8 (wikipedia XML) carries URLs, wiki-link paths and attribute blobs
    far beyond the pallas kernel's W=32 window; WET Common-Crawl text adds
    base64-ish junk.  ~0.3% of words here become such tokens (enwik8
    ballpark: 0.1-0.5% of whitespace-delimited tokens exceed 32 bytes),
    lengths log-uniform in [33, 300] — the corpus that exercises the
    overlong-rescue path (ops/rescue.py) under benchmark load, where the
    other generators never fire its cond.
    """
    rng = np.random.default_rng(seed)
    words = make_natural_corpus(n_bytes, seed=seed).split(b" ")
    alpha = np.frombuffer(b"abcdefghijklmnopqrstuvwxyz0123456789./_-=&?",
                          np.uint8)
    # Splice URLs at ~0.3% of sites: touch only the chosen sites (one draw
    # of all URL bytes up front), not every word — corpus generation runs
    # inside the scarce live-relay bench window.
    sites = np.flatnonzero(rng.random(len(words)) < 0.003)
    lengths = np.exp(rng.uniform(np.log(33), np.log(300),
                                 size=len(sites))).astype(np.int64)
    blob = alpha[rng.integers(0, len(alpha), int(lengths.sum()))].tobytes()
    ends = np.cumsum(lengths)
    for i, site in enumerate(sites):
        words[site] = b"http://" + blob[ends[i] - lengths[i]:ends[i]]
    return b" ".join(words)[:n_bytes]


def make_markup_corpus(n_bytes: int, seed: int = 31) -> bytes:
    """enwik-like markup proxy: the hostile-input stand-in (VERDICT r4
    missing #3 — the other generators are clean ASCII).

    Structured like wikipedia XML dumps: nested tags with attribute blobs,
    ``[[wiki links|display text]]``, ``&entities;``, UTF-8 MULTIBYTE words
    (Latin-1 accents, Greek, CJK — continuation bytes >= 0x80 must never
    split tokens), URLs past the W=32 window, and occasional very long
    separator-free attribute runs that exercise the reader's force-split.
    Tokens here are what the framework's whitespace semantics see — e.g.
    ``<title>Αθήνα</title>`` is ONE token — matching how the reference
    would tokenize the same bytes.
    """
    rng = np.random.default_rng(seed)
    latin = ["café", "naïve", "über", "résumé",
             "Zürich", "élève"]
    greek = ["Αθήνα", "λόγος"]
    cjk = ["東京", "中文", "日本語"]
    plain = _COMMON
    ents = ["&amp;", "&lt;", "&gt;", "&quot;", "&#945;"]
    parts, have = [], 0
    while have < n_bytes:
        page = ["<page>\n  <title>",
                str(rng.choice(plain)).capitalize(),
                "</title>\n  <revision id=\"",
                str(int(rng.integers(1e6, 1e8))), "\">\n    <text>"]
        for _ in range(int(rng.integers(40, 120))):
            r = rng.random()
            if r < 0.72:
                page.append(str(rng.choice(plain)))
            elif r < 0.82:
                page.append(str(rng.choice(latin + greek + cjk)))
            elif r < 0.88:
                page.append("[[" + str(rng.choice(plain)) + "|"
                            + str(rng.choice(plain)) + "]]")
            elif r < 0.93:
                page.append(str(rng.choice(ents)))
            elif r < 0.97:
                page.append("http://example.org/wiki/"
                            + "/".join(str(rng.choice(plain))
                                       for _ in range(int(rng.integers(2, 7)))))
            else:  # long separator-free attribute blob (force-split fodder)
                n = int(rng.integers(40, 400))
                page.append("style=\"" + "a" * n + "\"")
            page.append("\n" if rng.random() < 0.1 else " ")
        page.append("</text>\n  </revision>\n</page>\n")
        slab = "".join(page).encode("utf-8")
        parts.append(slab)
        have += len(slab)
    return b"".join(parts)[:n_bytes].rsplit(b" ", 1)[0] + b"\n"


def cpu_baseline_gbps(data: bytes, repeats: int = 1) -> float:
    from collections import Counter

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        counts = Counter(data.split())
        dt = time.perf_counter() - t0
        best = min(best, dt)
        assert counts  # keep it honest
    return len(data) / 1e9 / best


def _log(msg: str, t0: float) -> None:
    """Phase progress to stderr (stdout stays the single JSON line)."""
    print(f"[bench +{time.perf_counter() - t0:6.1f}s] {msg}", file=sys.stderr)


# Headline result recorded the moment the device-resident window completes.
# The round-3 failure mode this kills: the timed window SUCCEEDED at +252s,
# then the optional streamed phase hung past the watchdog, and the recorded
# round number was 0.0 — a real measurement thrown away.  The watchdog now
# emits this partial record (and the main flow writes BENCH_LAST_GOOD.json
# the moment it exists), so optional post-phases can only ever ADD data.
_PARTIAL_RESULT: dict | None = None
_WATCHDOG_DEADLINE: list = []  # single mutable slot: absolute deadline


# The metrics LAST_GOOD tracks value-aware best-known records for
# (VERDICT r5 #2): record name -> (result field, lower_is_better).
# `streamed_ratio` (ISSUE 5) is the tunnel-invariant streamed evidence in
# its time form — streamed wall-clock over the same-run H2D floor
# (`streamed_vs_h2d_time_ratio`, 1.0 = ingest at the link floor) — the
# only LOWER-is-better record; the GB/s-over-GB/s `streamed_vs_h2d_ratio`
# field stays in the JSON as its reciprocal.
_BEST_METRICS = {"headline": ("value", False),
                 "streamed": ("streamed_ingest_gbps", False),
                 "h2d": ("h2d_gbps", False),
                 "streamed_ratio": ("streamed_vs_h2d_time_ratio", True)}
# Context keys that must match for two records to count as "an
# otherwise-equal config" (the corpus/knob gates above already exclude
# cross-corpus and A/B-knob writes entirely).
_BEST_CONTEXT = ("input", "devices", "backend", "corpus_mb")
# A metric this far below its best-known record under an equal config is a
# regression, not noise: the write of that record is refused (r5 shipped
# exactly this — streamed 0.0088 -> 0.0028 at an equal 0.4276 headline —
# and the regressed record clobbered the only durable streamed evidence).
_REGRESSION_FRAC = 0.25


def _log_refused(msg: str) -> None:
    """Every refused last-good write leaves a stderr trace (ADVICE r5): a
    missing record update must be diagnosable from the run log."""
    print(f"[bench] last-good write refused: {msg}", file=sys.stderr,
          flush=True)


def _same_config(rec: dict, result: dict) -> bool:
    return all(rec.get(k) == result.get(k) for k in _BEST_CONTEXT)


def _seed_best(prev: dict) -> dict:
    """Bootstrap best-known records from a pre-round-6 (value-blind)
    LAST_GOOD file so its evidence joins the new per-metric ledger."""
    best = {}
    for name, (field, _) in _BEST_METRICS.items():
        if prev.get(field) is not None:
            best[name] = {"value": prev[field],
                          "recorded_at": prev.get("recorded_at"),
                          **{k: prev.get(k) for k in _BEST_CONTEXT}}
    return best


def _write_last_good(result: dict) -> None:
    if result.get("backend") == "cpu":
        # A CPU smoke run must not clobber the TPU evidence a wedged later
        # round needs to fall back on.
        _log_refused("cpu backend (smoke run, not TPU evidence)")
        return
    # A/B rows are evidence for BENCHMARKS.md, not the headline: letting
    # them overwrite LAST_GOOD makes the record look like a regression (a
    # markup run clobbered the 0.4275 zipf record this round; round 4 had
    # to restore the headline the same way).  Closed as a CLASS: any
    # BENCH_* knob that alters the measured run refuses the write — only
    # the listed harness knobs (which leave the measurement itself
    # unchanged) are headline-safe, so a future knob is refused by
    # default instead of silently clobbering.
    # BENCH_LEDGER only redirects where telemetry is written; the probe
    # budget/timeout knobs only shape pre-measurement reachability retries
    # (documented measurement-neutral at wait_for_device's call site);
    # BENCH_FORCE_LAST_GOOD only changes what THIS function does.
    # BENCH_TRACE only gates whether the timed pass's ledger is ALSO
    # rendered to a trace file after the fact — pure post-processing of
    # records already written, measurement-neutral like BENCH_LEDGER.
    # BENCH_HISTORY (ISSUE 14) only redirects where the run-history
    # warehouse ingests the already-written ledger — post-processing
    # again, and the warehouse never feeds back into LAST_GOOD.
    harness_only = {"BENCH_WATCHDOG_S", "BENCH_PROBE",
                    "BENCH_PROBE_BUDGET_S", "BENCH_COMPILE_CACHE",
                    "BENCH_LEDGER", "BENCH_RETRY_BUDGET_S",
                    "BENCH_PROBE_TIMEOUT_S", "BENCH_FORCE_LAST_GOOD",
                    "BENCH_TRACE", "BENCH_HISTORY"}
    if result.get("input") != "synthetic-zipf":
        _log_refused(f"non-headline corpus {result.get('input')!r} "
                     "(A/B evidence belongs in BENCHMARKS.md)")
        return
    knobs = sorted(k for k in os.environ
                   if k.startswith("BENCH_") and k not in harness_only
                   and os.environ.get(k))
    if knobs:
        _log_refused(f"measurement-altering knob(s) set: {', '.join(knobs)}")
        return
    prev = _read_last_good() or {}
    best = dict(prev.get("best") or _seed_best(prev))
    force = os.environ.get("BENCH_FORCE_LAST_GOOD") == "1"
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    for name, (field, lower) in _BEST_METRICS.items():
        val = result.get(field)
        if val is None:
            continue
        rec = best.get(name)
        new_rec = {"value": val, "recorded_at": now,
                   **{k: result.get(k) for k in _BEST_CONTEXT}}
        old = rec.get("value", float("inf") if lower else 0.0) \
            if rec is not None else None
        better = rec is None or (val <= old if lower else val >= old)
        regressed = rec is not None and (
            val > (1.0 + _REGRESSION_FRAC) * old if lower
            else val < (1.0 - _REGRESSION_FRAC) * old)
        if better:
            best[name] = new_rec
        elif force:
            # Deliberate re-baseline (e.g. after a harness change made old
            # records incomparable): the operator owns the downgrade.
            best[name] = new_rec
        elif regressed and _same_config(rec, result):
            _log_refused(
                f"metric '{name}' regressed {rec['value']} -> {val} "
                f"(> {_REGRESSION_FRAC:.0%}) under an otherwise-equal "
                "config; best-known record kept "
                "(BENCH_FORCE_LAST_GOOD=1 overrides)")
        # Milder regressions (or config drift): best-known silently keeps
        # the best — last-run fields below still record this run honestly.
    try:
        with open(LAST_GOOD_PATH, "w") as f:
            json.dump({**result, "recorded_at": now, "best": best}, f)
            f.write("\n")
    except OSError:
        pass  # read-only checkout: the caller already has the line


def _arm_watchdog(seconds: int, wall0: float) -> None:
    """Fail fast with an explicit JSON line if the device hangs.

    The bench chip sits behind a shared relay that can wedge indefinitely
    (a killed client leaving a claimed session blocks every subsequent
    device op, including jax.devices()).  A hung device_put is not
    interruptible from Python, so a daemon timer hard-exits — with the
    PARTIAL headline result if the timed window already completed (exit 0),
    else a machine-readable failure (exit 3).  Re-arm by appending a new
    absolute deadline to ``_WATCHDOG_DEADLINE`` (each optional post-phase
    gets its own budget).  BENCH_WATCHDOG_S overrides (0 disables).
    """
    import threading

    _WATCHDOG_DEADLINE.append(time.monotonic() + seconds)

    def fire():
        now = time.monotonic()
        if now < _WATCHDOG_DEADLINE[-1] - 0.5:
            t = threading.Timer(_WATCHDOG_DEADLINE[-1] - now, fire)
            t.daemon = True
            t.start()
            return
        if _PARTIAL_RESULT is not None:
            _log("WATCHDOG: post-window phase hung — emitting the partial "
                 "headline result instead of discarding it", wall0)
            # Self-describing partial: consumers must be able to tell
            # "streamed intentionally skipped" from "streamed wedged"
            # without reading stderr (ADVICE r4).
            print(json.dumps({**_PARTIAL_RESULT, "partial": True,
                              "streamed_phase": "hung"}), flush=True)
            os._exit(0)
        _log(f"WATCHDOG: no completion after {seconds}s — device tunnel "
             "wedged or unreachable; aborting", wall0)
        _fail_json(f"device unreachable: bench exceeded {seconds}s "
                   "(wedged TPU relay?); see BENCHMARKS.md for last "
                   "measured numbers")
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()


def _rearm_watchdog(seconds: int, wall0: float) -> None:
    """Give the next phase its own budget (the timer chain re-checks)."""
    if _WATCHDOG_DEADLINE:
        _WATCHDOG_DEADLINE[-1] = max(_WATCHDOG_DEADLINE[-1],
                                     time.monotonic() + seconds)


def main() -> int:
    wall0 = time.perf_counter()

    # Cheap reachability probe BEFORE staging (and before the watchdog arms,
    # so probe retries don't trip it).  On an unreachable device this spends
    # the probe budget producing a structured retry record + last_good JSON
    # instead of one 480 s silent death; worst case (device down the whole
    # window, then up at the last probe) is budget + watchdog ≈ 12 min.
    # BENCH_PROBE=0 disables; budget/timeout via BENCH_RETRY_BUDGET_S /
    # BENCH_PROBE_TIMEOUT_S.
    if os.environ.get("BENCH_PROBE", "1") != "0":
        from mapreduce_tpu.runtime.probe import wait_for_device

        # BENCH_PROBE_BUDGET_S (alias: BENCH_RETRY_BUDGET_S) sizes the probe
        # budget to the caller's — a driver with a 20-min budget can spend
        # most of it catching a relay-recovery window instead of giving up
        # at the 4-min default.
        budget = float(os.environ.get("BENCH_PROBE_BUDGET_S")
                       or os.environ.get("BENCH_RETRY_BUDGET_S", "240"))
        probe_t = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "45"))
        platform, attempts = wait_for_device(
            budget, probe_t, log=lambda m: _log(m, wall0))
        if platform is None:
            _fail_json(
                f"device unreachable: {len(attempts)} probe attempts over a "
                f"{budget:.0f}s retry budget all failed (wedged TPU relay?)",
                attempts)
            return 3
        _log(f"device probe ok: backend={platform} "
             f"({len(attempts)} attempt(s))", wall0)

    watchdog_s = int(os.environ.get("BENCH_WATCHDOG_S", "480"))
    if watchdog_s:
        _arm_watchdog(watchdog_s, wall0)
    mb = int(os.environ.get("BENCH_MB", "256"))
    chunk_mb = int(os.environ.get("BENCH_CHUNK_MB", "32"))
    superstep = int(os.environ.get("BENCH_SUPERSTEP", "0"))  # 0 = all chunks
    base_mb = int(os.environ.get("BENCH_BASELINE_MB", "16"))

    # BENCH_INPUT: bench a real corpus file (e.g. enwik8/enwik9 per
    # BASELINE.md) instead of synthetic text.  BENCH_CORPUS=natural selects
    # the English-text proxy (punctuated, cased, headed+tailed vocabulary)
    # over the default Zipf word soup.
    input_path = os.environ.get("BENCH_INPUT")
    corpus_kind = os.environ.get("BENCH_CORPUS", "zipf")
    if input_path:
        with open(input_path, "rb") as f:
            corpus = f.read(mb << 20)
        corpus_name = os.path.basename(input_path)
    elif corpus_kind == "natural":
        corpus = make_natural_corpus(mb << 20)
        corpus_name = "synthetic-natural"
    elif corpus_kind == "webby":
        corpus = make_webby_corpus(mb << 20)
        corpus_name = "synthetic-webby"
    elif corpus_kind == "markup":
        corpus = make_markup_corpus(mb << 20)
        corpus_name = "synthetic-markup"
    else:
        corpus = make_zipf_corpus(mb << 20)
        corpus_name = "synthetic-zipf"
    _log(f"corpus ready: {len(corpus) >> 20} MB ({corpus_name})", wall0)

    # CPU baseline BEFORE any device work: it is pure host numpy and it
    # makes vs_baseline available the moment the timed window lands (the
    # headline record must never wait on an optional post-phase).
    base = cpu_baseline_gbps(corpus[: base_mb << 20], repeats=3)
    _log(f"cpu baseline: {base:.4f} GB/s over {base_mb} MB", wall0)

    import jax

    from mapreduce_tpu.runtime import profiling

    # Persistent compile cache: repeated bench runs (and later rounds) skip
    # the multi-minute first compile when shapes are unchanged.
    # (BENCH_COMPILE_CACHE overrides; empty disables.)
    profiling.enable_compile_cache(os.environ.get("BENCH_COMPILE_CACHE"))

    from mapreduce_tpu.config import Config
    from mapreduce_tpu.data import reader
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.parallel.mapreduce import Engine
    from mapreduce_tpu.parallel.mesh import data_mesh

    # Capacities sized to the corpus: 50K-word Zipf vocab fits comfortably in
    # a 256K-slot table and 64K distinct-per-chunk batch extraction.
    # BENCH_SORT_MODE switches the aggregation sort strategy (sort3/segmin,
    # bit-identical results) and BENCH_MERGE_EVERY the table-merge cadence,
    # so live windows can A/B the sort floor and the merge amortization.
    # BENCH_SORT_IMPL A/Bs the Pallas radix partition/sort against the XLA
    # sort floor (BENCHMARKS.md round-6 pricing note; bit-identical
    # results) — a measurement-altering knob, so LAST_GOOD refuses it.
    # BENCH_COMBINER A/Bs the ISSUE 11 map-side combiner (hot-cache /
    # salt; pairs with BENCH_MAP_IMPL=fused) — measurement-altering, so
    # LAST_GOOD's class-based knob gate refuses it like every other A/B.
    # BENCH_GEOMETRY A/Bs a searched kernel-geometry set (ISSUE 12): a
    # preset name, or a JSON field dict for non-preset shortlist winners
    # — measurement-altering, refused by the same class gate.
    geom_env = os.environ.get("BENCH_GEOMETRY") or None
    if geom_env and geom_env.lstrip().startswith("{"):
        geom_env = json.loads(geom_env)
    # BENCH_MERGE_STRATEGY=auto warm-starts from the static reduction
    # planner's freshest tuned.json profile (tools/redplan.py --out),
    # through the run-history warehouse's resolve_prior — the RESOLVED
    # strategy reaches the Engine, the streamed config and the run_start
    # stamp (never the literal 'auto'); no matching profile falls back
    # LOUDLY to tree.  The bench mesh is 1-D, so only single-axis
    # strategies are eligible — a hier-* winner planned over a 2-D fleet
    # mesh is skipped, not mis-run.
    merge_strategy = os.environ.get("BENCH_MERGE_STRATEGY", "tree")
    if merge_strategy == "auto":
        from mapreduce_tpu.config import MERGE_STRATEGIES
        from mapreduce_tpu.obs import history

        prior = history.resolve_prior(
            profile_path=os.environ.get("BENCH_MERGE_PROFILE", "tuned.json"),
            merge_allowed=tuple(s for s in MERGE_STRATEGIES
                                if not s.startswith("hier-")))
        merge_strategy = prior["merge_strategy"]
        _log("merge-strategy: auto -> " + merge_strategy
             + ("" if prior["merge_strategy_profile"]
                else " (no redplan profile; tree)"), wall0)
    merge_overlap = os.environ.get("BENCH_MERGE_OVERLAP", "0") == "1"
    cfg = Config(chunk_bytes=chunk_mb << 20, table_capacity=1 << 18,
                 batch_unique_capacity=1 << 16,
                 sort_mode=os.environ.get("BENCH_SORT_MODE",
                                          Config.sort_mode),
                 sort_impl=os.environ.get("BENCH_SORT_IMPL",
                                          Config.sort_impl),
                 map_impl=os.environ.get("BENCH_MAP_IMPL",
                                         Config.map_impl),
                 combiner=os.environ.get("BENCH_COMBINER",
                                         Config.combiner),
                 geometry=geom_env,
                 merge_every=int(os.environ.get("BENCH_MERGE_EVERY", "1")),
                 merge_strategy=merge_strategy,
                 compact_slots=(int(os.environ["BENCH_COMPACT_SLOTS"])
                                if "BENCH_COMPACT_SLOTS" in os.environ
                                else None))
    mesh = data_mesh()
    n_dev = mesh.devices.size
    engine = Engine(WordCountJob(cfg), mesh, merge_strategy=merge_strategy)

    with tempfile.NamedTemporaryFile(dir="/tmp", suffix=".txt", delete=False) as f:
        f.write(corpus)
        path = f.name
    repeats = int(os.environ.get("BENCH_REPEATS", "8"))
    try:
        batches = list(reader.iter_batches(path, n_dev, cfg.chunk_bytes))
        # All full-size chunks stay device-resident; the timed dispatch
        # cycles them `repeats` times (see module docstring).
        if not batches:
            raise SystemExit("no full chunks: corpus smaller than one "
                             f"{chunk_mb} MB chunk; raise BENCH_MB or check "
                             "BENCH_INPUT, or lower BENCH_CHUNK_MB")
        k = max(1, min(superstep or len(batches), len(batches)))
        group = batches[:k]
        state = engine.init_states()

        # Stage the group once, timing the H2D transfer by itself (host-side
        # stacking stays outside the window).  A host fetch is the only
        # reliable sync point (block_until_ready is not a real barrier under
        # remote-device tunnels).
        stacked = np.stack([b.data for b in group], axis=1)
        t0 = time.perf_counter()
        staged = jax.device_put(stacked, engine.sharding)
        jax.block_until_ready(staged)
        np.asarray(staged[..., -1:])
        h2d_gbps = staged.nbytes / 1e9 / (time.perf_counter() - t0)
        _log(f"staged {staged.nbytes >> 20} MB on device "
             f"({h2d_gbps:.3f} GB/s H2D); k={k}, repeats={repeats}", wall0)

        # Warm-up: pays the XLA compiles (one for the (k, repeats) program,
        # one for finish -- finish does not donate, so the state stays valid).
        t_c0 = time.perf_counter()
        state = engine.step_many(state, staged, 0, repeats=repeats)
        # Generic ONE-ELEMENT host fetch: the state may be a bare CountTable
        # or (with BENCH_MERGE_EVERY > 1) a buffered pytree around one.  A
        # fetch, not jax.block_until_ready — that is not a real barrier
        # under remote-device tunnels (BENCHMARKS.md "Measurement rules").
        np.asarray(jax.tree.leaves(state)[0].ravel()[:1])
        compile_s = time.perf_counter() - t_c0
        _log("warm-up dispatch done (compile paid)", wall0)
        np.asarray(engine.finish(state).dropped_count)
        _log("warm finish done", wall0)

        group_bytes = int(sum(b.lengths.sum() for b in group))
        t0 = time.perf_counter()
        state = engine.step_many(state, staged, k * repeats, repeats=repeats)
        table = engine.finish(state)
        np.asarray(table.dropped_count)  # barrier: fetch an existing leaf
        dt = time.perf_counter() - t0
        steady_bytes = group_bytes * repeats
        _log(f"timed window done: {dt:.3f}s over {steady_bytes >> 20} MB "
             f"({repeats} passes)", wall0)
        total_words = int(np.asarray(table.total_count()))
        processed_bytes = group_bytes * 2 * repeats  # warm-up + timed
        gbps = steady_bytes / 1e9 / dt
        words_per_s = total_words * (steady_bytes / processed_bytes) / dt

        # The headline is now a fact: record it durably BEFORE the optional
        # streamed phase (whose fresh compiles through a slow tunnel are
        # exactly what blew the round-3 watchdog and zeroed the round).
        global _PARTIAL_RESULT
        _PARTIAL_RESULT = {
            "metric": "zipf_wordcount_device_throughput",
            "input": corpus_name,
            "h2d_gbps": round(h2d_gbps, 4),
            "value": round(gbps, 4),
            "unit": "GB/s",
            "vs_baseline": round(gbps / base, 3) if base else 0.0,
            "corpus_mb": round(group_bytes / (1 << 20), 1),
            "devices": n_dev,
            "backend": jax.devices()[0].platform,
            "total_words": total_words,
            "cpu_baseline_gbps": round(base, 4),
            "words_per_s": round(words_per_s, 0),
        }
        _write_last_good(_PARTIAL_RESULT)
        # The streamed phase's own fresh compiles (step + step_many +
        # finish at the streamed shapes) scale with relay-window quality
        # like the headline compile just measured — but the headline is
        # often a persistent-cache HIT while the streamed shapes compile
        # fresh (observed: 50 s headline, ~615 s streamed compiles, same
        # window), so the proportional term alone is not enough.  The
        # device was provably alive seconds ago and a late watchdog still
        # emits the partial headline, while an early one throws the
        # streamed row away — the risk is asymmetric, so the floor is
        # generous (observed worst case: streamed > 1500 s in a 565-s-
        # compile window, BENCHMARKS.md round 5).
        streamed_budget = max(watchdog_s or 480, int(3 * compile_s) + 300,
                              1800)
        _rearm_watchdog(streamed_budget, wall0)

        # End-to-end STREAMED ingest (VERDICT r3 #7): reader + prefetch +
        # H2D + compute + collective finish through the executor's run_job
        # path — the BASELINE.md "GB/s ingest" metric proper, where the
        # device-resident window above isolates device compute.  One full
        # pass over the corpus file; superstep amortizes dispatch latency
        # the same way production runs do.  BENCH_STREAMED=0 skips.
        streamed_gbps = None
        streamed_ledger = None
        streamed_metrics = None
        streamed_pipeline = None
        if os.environ.get("BENCH_STREAMED", "1") != "0":
            try:
                import dataclasses

                from mapreduce_tpu.runtime import executor

                # BENCH_INFLIGHT / BENCH_PREFETCH_DEPTH: the ISSUE 5
                # dispatch-window A/B knobs (1 = the serialized control;
                # measurement-altering, so LAST_GOOD refuses them).
                from mapreduce_tpu.config import Config as _Config

                s_cfg = dataclasses.replace(
                    cfg, superstep=int(os.environ.get(
                        "BENCH_STREAM_SUPERSTEP", "4")),
                    inflight_groups=int(os.environ.get(
                        "BENCH_INFLIGHT", str(_Config.inflight_groups))),
                    prefetch_depth=(
                        int(os.environ["BENCH_PREFETCH_DEPTH"])
                        if os.environ.get("BENCH_PREFETCH_DEPTH") else None),
                    # BENCH_MERGE_OVERLAP=1: window-boundary partial
                    # collectives on the streamed pass (ISSUE 20 leg 2;
                    # bit-identical, measurement-altering like every A/B
                    # knob, so LAST_GOOD's class gate refuses it).
                    merge_overlap=merge_overlap)
                # Warm-up: a short-range run pays the XLA compiles for the
                # streamed shapes (the persistent compile cache makes the
                # timed run's identical programs cache hits), so the timed
                # window measures ingest, not compilation.
                warm_hi = min(len(corpus),
                              n_dev * s_cfg.chunk_bytes
                              * (s_cfg.superstep + 1))
                executor.run_job(WordCountJob(s_cfg), path, config=s_cfg,
                                 mesh=mesh, byte_range=(0, warm_hi))
                _log("streamed warm-up done (compile paid)", wall0)
                _rearm_watchdog(streamed_budget, wall0)
                # Telemetry on the TIMED pass only: the run ledger (one
                # JSONL record per step: phase deltas, bytes, device mem,
                # compile events) makes a bench row attributable after the
                # fact — summarize with tools/obs_report.py.  BENCH_LEDGER
                # overrides the path (benchwatch points it next to its
                # per-step logs).
                from mapreduce_tpu import obs

                ledger_path = os.environ.get("BENCH_LEDGER") or os.path.join(
                    tempfile.gettempdir(), f"bench_ledger.{os.getpid()}.jsonl")
                tel = obs.Telemetry.create(ledger_path=ledger_path)
                # The registry is process-global and already holds the
                # headline + warm-up activity; snapshot here so the
                # reported metrics are the DELTA over the timed pass only.
                snap_before = obs.get_registry().snapshot()
                t0 = time.perf_counter()
                try:
                    rr = executor.run_job(WordCountJob(s_cfg), path,
                                          config=s_cfg, mesh=mesh,
                                          telemetry=tel)
                finally:
                    tel.close()
                np.asarray(jax.tree.leaves(rr.value)[0].ravel()[:1])
                s_dt = time.perf_counter() - t0
                streamed_gbps = rr.metrics.bytes_processed / 1e9 / s_dt
                # Decomposition (VERDICT r4 next #2): where the streamed
                # seconds actually went — read_wait (reader behind),
                # stage (host assembly + H2D placement), dispatch
                # (program enqueue; large = device queue full =
                # compute-bound), drain (queued compute at stream end).
                streamed_phases = {k: round(v, 3)
                                   for k, v in rr.metrics.phases.items()}
                streamed_ledger = ledger_path
                streamed_metrics = _metrics_delta(
                    snap_before, obs.get_registry().snapshot())
                streamed_pipeline = rr.pipeline
                _log(f"streamed ingest pass done: {s_dt:.3f}s over "
                     f"{rr.metrics.bytes_processed >> 20} MB "
                     f"({streamed_gbps:.4f} GB/s end-to-end); "
                     f"phases={streamed_phases}; pipeline={rr.pipeline}; "
                     f"ledger={ledger_path}", wall0)
            except Exception as e:  # noqa: BLE001 — headline must survive
                _log(f"streamed phase failed ({e!r}); keeping headline", wall0)
    finally:
        os.unlink(path)

    result = dict(_PARTIAL_RESULT)
    # Static cost pricing of the benched map path (ISSUE 6): predicted HBM
    # passes next to the measured GB/s, so the fused-vs-split A/B rows
    # carry the prediction and the measurement in one JSON.
    result["map_impl"] = cfg.map_impl
    result["combiner"] = cfg.resolved_combiner
    # The reduction placement next to the measurement (ISSUE 20): the
    # RESOLVED strategy (never 'auto') + whether the streamed pass
    # overlapped its partial collectives with the map stream.
    result["merge_strategy"] = merge_strategy
    if merge_overlap:
        result["merge_overlap"] = True
    cost = _cost_record(cfg.map_impl, cfg.resolved_combiner)
    if cost is not None:
        result["cost"] = cost
    if streamed_gbps is not None:
        result["streamed_ingest_gbps"] = round(streamed_gbps, 4)
        result["streamed_phases"] = streamed_phases
        ratio = _streamed_ratio(result)
        if ratio is not None:
            result["streamed_vs_h2d_ratio"] = ratio
            time_ratio = _time_ratio(ratio)
            if time_ratio is not None:
                result["streamed_vs_h2d_time_ratio"] = time_ratio
        if streamed_pipeline is not None:
            # Window forensics for the A/B rows: configured/observed
            # in-flight depth and the overlap fraction (1 - blocked/stream).
            result["streamed_overlap_fraction"] = \
                streamed_pipeline.get("overlap_fraction")
            result["streamed_pipeline"] = {
                k: streamed_pipeline.get(k)
                for k in ("inflight_groups", "prefetch_depth", "depth_mean",
                          "depth_max", "window_filled", "full_frac")}
        if streamed_ledger:
            result["ledger"] = streamed_ledger
            # Timeline forensics (ISSUE 7): reconstruct the timed pass's
            # per-group lifecycle into the critical-path `bottleneck`
            # verdict and export a Perfetto-viewable trace NEXT TO the
            # ledger — the queued pipeline A/B rows land with measured
            # timelines attached, not just two scalar ratios.  Advisory:
            # post-processing of records already on disk, so any failure
            # is logged and skipped (the measured row must survive).
            # BENCH_TRACE=0 skips (harness knob, measurement-neutral).
            if os.environ.get("BENCH_TRACE", "1") != "0":
                try:
                    from mapreduce_tpu.obs import timeline as tl_mod

                    recs = list(obs.read_ledger(streamed_ledger))
                    art = tl_mod.reconstruct(recs)
                    if art is not None:
                        result["bottleneck"] = art["bottleneck"]
                        trace_path = streamed_ledger + ".trace.json"
                        with open(trace_path, "w") as tf:
                            json.dump(tl_mod.to_chrome_trace(recs), tf)
                        result["trace"] = trace_path
                        _log("trace exported: "
                             f"{trace_path} (bottleneck="
                             f"{art['bottleneck']['resource']}, device idle "
                             f"{art['device_idle']['total_s']:.3f}s)", wall0)
                except Exception as e:  # noqa: BLE001 — advisory only
                    print(f"[bench] trace export skipped ({e!r})",
                          file=sys.stderr)
            # Data-plane summary (ISSUE 8): the timed pass's `data` record
            # + its health classification ride BENCH JSON, so the A/B rows
            # carry the corpus-and-backend shape signals (skew, spill
            # fallbacks, window occupancy) the autotuner needs next to
            # the bottleneck verdict.  Advisory and LAST_GOOD-neutral
            # (the value-aware ledger tracks only its named metrics).
            try:
                from mapreduce_tpu.obs import datahealth as dh_mod

                recs = list(obs.read_ledger(streamed_ledger))
                # Keyed to THIS pass's run_id: BENCH_LEDGER may point at an
                # appended multi-run file (benchwatch reuses one path per
                # suite step), and the summary must describe the timed
                # pass, not whichever run landed first.
                data_rec = dh_mod.data_record(recs, run_id=tel.run_id)
                if data_rec is not None:
                    result["data"] = {
                        k: v for k, v in data_rec.items()
                        if k not in ("ts", "run_id", "kind")}
                    health = dh_mod.classify(result["data"])
                    result["data_health"] = health
                    _log("data health: "
                         f"{health['verdict']} (top_mass="
                         f"{health['signals'].get('top_mass')}, "
                         "fallback_frac="
                         f"{health['signals'].get('fallback_frac')})",
                         wall0)
            except Exception as e:  # noqa: BLE001 — advisory only
                print(f"[bench] data summary skipped ({e!r})",
                      file=sys.stderr)
            # Run-history registration (ISSUE 14 satellite BUGFIX): an
            # append-mode BENCH_LEDGER accumulates many timed passes but
            # nothing ever ingested them — every pass now lands in the
            # run-history warehouse (BENCH_HISTORY overrides the index
            # dir, 0 disables; default next to the ledger), and the row
            # carries this pass's config key + the key group's drift
            # verdict (regressing/improving/steady/config-drift).
            # Harness-neutral and advisory: post-processing of records
            # already on disk, never measurement-altering, and
            # LAST_GOOD is untouched (the value-aware ledger stays the
            # regression gate; the warehouse is the longitudinal view).
            hist_env = os.environ.get("BENCH_HISTORY", "")
            if hist_env != "0":
                try:
                    from mapreduce_tpu.obs import history as history_mod

                    hdir = hist_env or (streamed_ledger + ".history")
                    index = history_mod.ingest([streamed_ledger], hdir)
                    mine = [r for r in index["runs"].values()
                            if r.get("run_id") == tel.run_id]
                    row = max(mine, key=lambda r: r.get("instance") or 0) \
                        if mine else None
                    drift = history_mod.classify_drift(
                        history_mod.group_rows(index, row["group"])) \
                        if row else None
                    result["history"] = {
                        "index": history_mod.index_path(hdir),
                        "runs": len(index.get("runs", {})),
                        "key": row.get("key") if row else None,
                        "drift": (drift or {}).get("verdict"),
                    }
                    _log("history: registered run under "
                         f"{result['history']['key']} "
                         f"({result['history']['runs']} runs indexed, "
                         f"drift={result['history']['drift']})", wall0)
                except Exception as e:  # noqa: BLE001 — advisory only
                    print(f"[bench] history registration skipped ({e!r})",
                          file=sys.stderr)
        # Registry DELTA over the timed streamed pass (the registry is
        # process-global, so an absolute snapshot would fold in the
        # headline + warm-up activity): steps/dispatches/prefetches and
        # where the seconds pooled, machine-readable per round.
        if streamed_metrics is not None:
            result["metrics"] = streamed_metrics
    print(json.dumps(result))
    _write_last_good(result)
    return 0


def _streamed_ratio(result: dict) -> float | None:
    """Streamed GB/s over the SAME-RUN H2D floor — the tunnel-invariant
    form of the streamed metric (VERDICT r5 #3): relay weather moves both
    numerator and denominator, so the ratio survives window quality where
    the absolute GB/s does not.  None when either leg is missing/zero."""
    streamed = result.get("streamed_ingest_gbps")
    h2d = result.get("h2d_gbps")
    if not streamed or not h2d:
        return None
    return round(streamed / h2d, 4)


def _time_ratio(ratio: float | None) -> float | None:
    """The same evidence in time form (ISSUE 5's falsifiable target):
    streamed wall-clock over the same-run H2D floor, lower is better,
    1.0 = ingest at the link floor.  A near-hung streamed pass can round
    the GB/s ratio all the way to 0.0 — the time form is then
    unrepresentable, not infinite: return None rather than crash the
    headline result this late (this is the run most worth keeping)."""
    if not ratio:
        return None
    return round(1.0 / ratio, 4)


def _cost_record(map_impl: str, combiner: str = "off") -> dict | None:
    """Static hbm-cost pricing of the benched map path (ISSUE 6/11): run
    the analysis cost pass over the registry twin of the benched config
    (wordcount_combiner when the hot-key combiner is on, wordcount_fused
    when BENCH_MAP_IMPL=fused, else wordcount_pallas) and surface
    `effective_input_passes` — plus the fused-vs-split / combiner-vs-off
    gap the pass certifies — in BENCH JSON.  Pure tracing, no device
    work; any failure is logged and skipped (the measured row must
    survive)."""
    try:
        from mapreduce_tpu import analysis, models
        from mapreduce_tpu.analysis.passes.cost import CostPass

        if combiner == "hot-cache" and map_impl == "fused":
            name = "wordcount_combiner"
        elif map_impl == "fused":
            name = "wordcount_fused"
        else:
            name = "wordcount_pallas"
        rep = analysis.analyze_job(models.build_model(name), name,
                                   passes=[CostPass()])
        art = rep.artifacts.get(name, {}).get("cost")
        if not art:
            return None
        rec = {"model": name,
               "effective_input_passes": art.get("effective_input_passes")}
        if "fused_vs_split" in art:
            rec["fused_vs_split"] = art["fused_vs_split"]
        if "combiner_vs_off" in art:
            rec["combiner_vs_off"] = art["combiner_vs_off"]
        return rec
    except Exception as e:  # noqa: BLE001 — advisory, never fatal
        print(f"[bench] cost artifact skipped ({e!r})", file=sys.stderr)
        return None


def _metrics_delta(before: dict, after: dict) -> dict:
    """Counter and histogram count/sum deltas between two registry
    snapshots (gauges are last-write-wins and pass through).  Histogram
    min/max are window-less and deliberately dropped."""
    b_c = before.get("counters", {})
    counters = {k: round(v - b_c.get(k, 0), 6)
                for k, v in after.get("counters", {}).items()
                if v != b_c.get(k, 0)}
    b_h = before.get("histograms", {})
    hists = {}
    for k, h in after.get("histograms", {}).items():
        prev = b_h.get(k, {"count": 0, "sum": 0.0})
        if h["count"] != prev["count"]:
            hists[k] = {"count": h["count"] - prev["count"],
                        "sum": round(h["sum"] - prev["sum"], 6)}
    return {"counters": counters, "gauges": after.get("gauges", {}),
            "histograms": hists}


if __name__ == "__main__":
    sys.exit(main())
