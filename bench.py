#!/usr/bin/env python3
"""Benchmark: word-count throughput on a synthetic Zipf corpus (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

The reference publishes no numbers and physically caps at ~5.8 KB of input
(SURVEY §6), so the baseline here is the natural host-CPU implementation a
user would reach for (``collections.Counter(data.split())``), measured on a
slice of the same corpus; ``vs_baseline`` is our GB/s over its GB/s.

Headline metric = the device MapReduce pipeline (tokenize + hash + count +
merge) on device-resident chunks, i.e. the part of the stack this framework
owns.  Host->device staging is measured and reported separately
(``h2d_gbps``): in this harness the chip sits behind a network tunnel whose
~15 MB/s H2D link would otherwise be the only thing measured; on a real TPU
host, local DMA far exceeds the pipeline rate and the headline number is the
end-to-end bound.

Env knobs: BENCH_MB (corpus size, default 512), BENCH_CHUNK_MB (per-device
step size, default 32 — the measured sweet spot on v5e), BENCH_SUPERSTEP
(chunks folded per dispatch via lax.scan, default 8 — fewer, larger
dispatches dilute per-dispatch link latency), BENCH_BASELINE_MB (CPU
baseline slice, default 16).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def make_zipf_corpus(n_bytes: int, vocab: int = 50_000, a: float = 1.3,
                     seed: int = 7) -> bytes:
    rng = np.random.default_rng(seed)
    words = np.array([b"w%d" % i for i in range(vocab)], dtype=object)
    # Average word ~6 bytes + separator; oversample then trim.
    n_words = int(n_bytes / 6.5) + 1024
    idx = rng.zipf(a, size=n_words).astype(np.int64) % vocab
    blob = b" ".join(words[idx])
    return blob[:n_bytes].rsplit(b" ", 1)[0] + b"\n"


def cpu_baseline_gbps(data: bytes, repeats: int = 1) -> float:
    from collections import Counter

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        counts = Counter(data.split())
        dt = time.perf_counter() - t0
        best = min(best, dt)
        assert counts  # keep it honest
    return len(data) / 1e9 / best


def main() -> int:
    mb = int(os.environ.get("BENCH_MB", "512"))
    chunk_mb = int(os.environ.get("BENCH_CHUNK_MB", "32"))
    superstep = int(os.environ.get("BENCH_SUPERSTEP", "8"))
    base_mb = int(os.environ.get("BENCH_BASELINE_MB", "16"))

    # BENCH_INPUT: bench a real corpus file (e.g. enwik8/enwik9 per
    # BASELINE.md) instead of the synthetic Zipf text.
    input_path = os.environ.get("BENCH_INPUT")
    if input_path:
        with open(input_path, "rb") as f:
            corpus = f.read(mb << 20)
    else:
        corpus = make_zipf_corpus(mb << 20)

    import jax

    from mapreduce_tpu.runtime import profiling

    # Persistent compile cache: repeated bench runs (and later rounds) skip
    # the multi-minute first compile when shapes are unchanged.
    # (BENCH_COMPILE_CACHE overrides; empty disables.)
    profiling.enable_compile_cache(os.environ.get("BENCH_COMPILE_CACHE"))

    from mapreduce_tpu.config import Config
    from mapreduce_tpu.data import reader
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.parallel.mapreduce import Engine
    from mapreduce_tpu.parallel.mesh import data_mesh

    # Capacities sized to the corpus: 50K-word Zipf vocab fits comfortably in
    # a 256K-slot table and 64K distinct-per-chunk batch extraction.
    cfg = Config(chunk_bytes=chunk_mb << 20, table_capacity=1 << 18,
                 batch_unique_capacity=1 << 16)
    mesh = data_mesh()
    n_dev = mesh.devices.size
    engine = Engine(WordCountJob(cfg), mesh)

    with tempfile.NamedTemporaryFile(dir="/tmp", suffix=".txt", delete=False) as f:
        f.write(corpus)
        path = f.name
    try:
        batches = list(reader.iter_batches(path, n_dev, cfg.chunk_bytes))
        # Group K chunks per dispatch; drop any remainder so every dispatch
        # reuses one compiled superstep program.
        k = max(1, min(superstep, len(batches) // 2))
        groups = [batches[i:i + k] for i in range(0, len(batches) - k + 1, k)]
        if len(groups) < 2:
            raise SystemExit("BENCH_MB too small: need >= 2 supersteps "
                             "(warm-up + timed); raise BENCH_MB or lower "
                             "BENCH_CHUNK_MB/BENCH_SUPERSTEP")
        state = engine.init_states()

        # Stage every superstep's chunks on device up front, timing the H2D
        # transfer by itself (see module docstring; host-side stacking stays
        # outside the window).  A host fetch is the only reliable sync point
        # (block_until_ready is not a real barrier under remote-device
        # tunnels).
        stacked = [np.stack([b.data for b in g], axis=1) for g in groups]
        t0 = time.perf_counter()
        staged = [jax.device_put(s, engine.sharding) for s in stacked]
        jax.block_until_ready(staged)
        np.asarray(staged[-1][..., -1:])
        h2d_gbps = sum(s.nbytes for s in staged) / 1e9 / (time.perf_counter() - t0)

        # Warm-up superstep: pays XLA compile; excluded from steady timing.
        state = engine.step_many(state, staged[0], 0)
        np.asarray(state.dropped_count)
        # Warm finish too (it does not donate, so the state stays valid):
        # its one-time compile otherwise lands inside the timed window.
        np.asarray(engine.finish(state).dropped_count)
        t0 = time.perf_counter()
        steady_bytes = 0
        for i, group in enumerate(groups[1:]):
            state = engine.step_many(state, staged[i + 1], (i + 1) * k)
            steady_bytes += int(sum(b.lengths.sum() for b in group))
        table = engine.finish(state)
        np.asarray(table.dropped_count)  # barrier: fetch an existing leaf
        dt = time.perf_counter() - t0
        total_words = int(np.asarray(table.total_count()))
        processed_bytes = int(sum(b.lengths.sum() for g in groups for b in g))
        gbps = steady_bytes / 1e9 / dt
        words_per_s = total_words * (steady_bytes / processed_bytes) / dt
    finally:
        os.unlink(path)

    base = cpu_baseline_gbps(corpus[: base_mb << 20], repeats=3)

    print(json.dumps({
        "metric": "zipf_wordcount_device_throughput",
        "input": os.path.basename(input_path) if input_path else "synthetic-zipf",
        "h2d_gbps": round(h2d_gbps, 4),
        "value": round(gbps, 4),
        "unit": "GB/s",
        "vs_baseline": round(gbps / base, 3) if base else 0.0,
        "corpus_mb": round(len(corpus) / (1 << 20), 1),  # actual, not requested
        "devices": n_dev,
        "backend": jax.devices()[0].platform,
        "total_words": total_words,
        "cpu_baseline_gbps": round(base, 4),
        "words_per_s": round(words_per_s, 0),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
