"""The newer model families in one pass: n-grams, frequency sketch, grep.

    python examples/analytics.py [path]

- Bigram counts: `--ngram 2` semantics (order-sensitive token pairs, reported
  as their exact first-occurrence source spans).
- Count-Min frequency estimates: query ANY word or phrase after the run,
  including ones the exact table spilled past capacity.
- Distributed grep: overlapping occurrences + matching lines of a pattern.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import grep
from mapreduce_tpu.runtime import executor

if len(sys.argv) > 1:
    path = sys.argv[1]
else:  # demo corpus
    f = tempfile.NamedTemporaryFile(suffix=".txt", delete=False)
    f.write(b"the quick brown fox jumps over the lazy dog\n" * 200
            + b"the quick red fox naps\n" * 50)
    f.close()
    path = f.name

cfg = Config(chunk_bytes=1 << 20, table_capacity=1 << 14)

# Bigrams, top 5 by frequency.
bi = executor.count_file(path, config=cfg, ngram=2, top_k=5)
print("top bigrams:")
for span, count in bi.as_dict().items():
    print(f"  {span.decode()!r}\t{count}")

# Frequency sketch: estimates survive table overflow.  The sketch keys
# match the run's gram order: query words on a unigram run, spans on an
# n-gram run.
r = executor.count_file(path, config=cfg, count_sketch=True)
for q in (b"the", b"fox", b"not-in-corpus"):
    print(f"estimate {q.decode()!r}: {r.estimate_count(q)}")
r2 = executor.count_file(path, config=cfg, ngram=2, count_sketch=True)
print(f"estimate 'quick brown' (bigram run): {r2.estimate_count(b'quick brown')}")

# Grep.
g = grep.grep_file(path, b"quick", config=cfg)
print(f"grep 'quick': {g.matches} matches on {g.lines} lines")

# Multi-pattern grep: P patterns share ONE pass over the corpus.
for pat, res in zip(["quick", "fox", "zebra"],
                    grep.grep_file_multi(path, [b"quick", b"fox", b"zebra"],
                                         config=cfg)):
    print(f"multigrep {pat!r}: {res.matches} matches on {res.lines} lines")

# Regex-lite byte classes: fixed-length per-position allowed-sets.
c = grep.grep_file(path, b"[a-z]o[gx]", config=cfg, syntax="class")
print(f"grep class '[a-z]o[gx]': {c.matches} matches ('dog'/'fox' tails)")

# Uniform sampling: a mergeable bottom-k sketch over token occurrences.
from mapreduce_tpu.models import sample

s = sample.sample_file(path, 8, config=cfg)
print(f"uniform sample of {len(s.tokens)} from {s.total} tokens: "
      + " ".join(t.decode() for t in s.tokens))
