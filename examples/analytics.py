"""The newer model families in one pass: n-grams, frequency sketch, grep.

    python examples/analytics.py [path]

- Bigram counts: `--ngram 2` semantics (order-sensitive token pairs, reported
  as their exact first-occurrence source spans).
- Count-Min frequency estimates: query ANY word or phrase after the run,
  including ones the exact table spilled past capacity.
- Distributed grep: overlapping occurrences + matching lines of a pattern.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import grep
from mapreduce_tpu.runtime import executor

if len(sys.argv) > 1:
    path = sys.argv[1]
else:  # demo corpus
    f = tempfile.NamedTemporaryFile(suffix=".txt", delete=False)
    f.write(b"the quick brown fox jumps over the lazy dog\n" * 200
            + b"the quick red fox naps\n" * 50)
    f.close()
    path = f.name

cfg = Config(chunk_bytes=1 << 20, table_capacity=1 << 14)

# Bigrams, top 5 by frequency.
bi = executor.count_file(path, config=cfg, ngram=2, top_k=5)
print("top bigrams:")
for span, count in bi.as_dict().items():
    print(f"  {span.decode()!r}\t{count}")

# Frequency sketch: estimates survive table overflow.  The sketch keys
# match the run's gram order: query words on a unigram run, spans on an
# n-gram run.
r = executor.count_file(path, config=cfg, count_sketch=True)
for q in (b"the", b"fox", b"not-in-corpus"):
    print(f"estimate {q.decode()!r}: {r.estimate_count(q)}")
r2 = executor.count_file(path, config=cfg, ngram=2, count_sketch=True)
print(f"estimate 'quick brown' (bigram run): {r2.estimate_count(b'quick brown')}")

# Grep.
g = grep.grep_file(path, b"quick", config=cfg)
print(f"grep 'quick': {g.matches} matches on {g.lines} lines")
