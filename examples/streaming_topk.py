"""Streaming pipeline over a file: sharded ingest, top-k, checkpointing, and
a distinct-count sketch that stays accurate past table capacity.

    python examples/streaming_topk.py [path]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

from mapreduce_tpu.config import Config
from mapreduce_tpu.runtime import executor

if len(sys.argv) > 1:
    path = sys.argv[1]
else:  # demo corpus
    f = tempfile.NamedTemporaryFile(suffix=".txt", delete=False)
    f.write(b"the quick brown fox jumps over the lazy dog " * 5000)
    f.close()
    path = f.name

config = Config(chunk_bytes=1 << 20, table_capacity=1 << 16)
result = executor.count_file(
    path, config=config,
    top_k=5,                        # device-side top-k selection
    distinct_sketch=True,           # HyperLogLog rides the same collectives
    checkpoint_path=path + ".ck.npz",
    checkpoint_every=50,            # snapshot every 50 streaming steps
)

for word, count in zip(result.words, result.counts):
    print(f"{word.decode()}\t{count}")
print(f"total={result.total} distinct~={result.distinct_estimate:.0f} "
      f"(exact-table distinct={result.distinct})")
