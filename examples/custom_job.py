"""The Engine is a general MapReduce framework, not just word count: define a
custom job by implementing the five hooks (init_state / map_chunk / combine /
merge / finalize) with pure, static-shaped JAX, and the same SPMD machinery —
sharded streaming, superstep scan dispatch, collective tree merge — runs it.

This example: a byte-class histogram (letters / digits / whitespace / other)
over a corpus, an *additive* accumulator (contrast the count table's sorted
monoid and the sketch's max monoid).

    python examples/custom_job.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from mapreduce_tpu.parallel.mapreduce import Engine, MapReduceJob
from mapreduce_tpu.parallel.mesh import data_mesh


class ByteClassHistogramJob(MapReduceJob):
    CLASSES = ("letter", "digit", "whitespace", "other")

    def init_state(self):
        return jnp.zeros((4,), jnp.uint32)

    def map_chunk(self, chunk, chunk_id):
        letter = ((chunk | 0x20) >= ord("a")) & ((chunk | 0x20) <= ord("z"))
        digit = (chunk >= ord("0")) & (chunk <= ord("9"))
        space = (chunk == 0x20) | ((chunk >= 0x09) & (chunk <= 0x0D))
        pad = chunk == 0  # don't count the chunk padding as data
        other = ~(letter | digit | space | pad)
        return jnp.stack([c.astype(jnp.uint32).sum()
                          for c in (letter, digit, space, other)])

    def combine(self, state, update):
        return state + update

    def merge(self, a, b):  # additive: the collective could equally be psum
        return a + b


corpus = b"Call me Ishmael. Some years ago - never mind how long precisely - " \
         b"having little or no money in my purse... " * 400

mesh = data_mesh()
engine = Engine(ByteClassHistogramJob(), mesh)
n = mesh.size

# Shard the corpus into one row per device (pad the tail to a static shape).
chunk = -(-len(corpus) // n)
chunk += -chunk % 128
buf = np.zeros((n, chunk), np.uint8)
flat = np.frombuffer(corpus, np.uint8)
for i in range(n):
    row = flat[i * chunk:(i + 1) * chunk]
    buf[i, : row.shape[0]] = row

state = engine.init_states()
state = engine.step(state, buf, 0)
hist = np.asarray(engine.finish(state))

for name, count in zip(ByteClassHistogramJob.CLASSES, hist):
    print(f"{name}\t{int(count)}")
assert hist.sum() == len(corpus)
