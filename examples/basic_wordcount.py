"""Smallest possible use: exact word counts for an in-memory buffer.

    python examples/basic_wordcount.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mapreduce_tpu.models import wordcount

text = b"to be or not to be that is the question"
result = wordcount.count_words(text)

for word, count in zip(result.words, result.counts):  # insertion order
    print(f"{word.decode()}\t{count}")
print(f"total={result.total} distinct={result.distinct}")
assert result.as_dict()[b"to"] == 2
