"""Config validation and 'auto' backend resolution.

The CPU-pinned suite (conftest forces JAX_PLATFORMS=cpu) never sees a real
TPU, so the TPU branches of ``resolved_backend`` are exercised here by
monkeypatching ``jax.default_backend`` — the resolution logic is pure given
(platform, chunk_bytes).
"""

from __future__ import annotations

import jax
import pytest

from mapreduce_tpu.config import Config


def test_default_backend_is_auto():
    assert Config().backend == "auto"


def test_auto_resolves_to_xla_off_tpu():
    assert jax.default_backend() != "tpu"  # conftest pins CPU
    assert Config().resolved_backend() == "xla"


def test_auto_resolves_to_pallas_on_tpu(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    cfg = Config(chunk_bytes=1 << 20)
    assert cfg.chunk_bytes >= cfg.pallas_min_chunk
    assert cfg.resolved_backend() == "pallas"


def test_auto_falls_back_to_xla_for_small_chunks_on_tpu(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    cfg = Config(chunk_bytes=1 << 10)  # below pallas_min_chunk (8448 @ W=32)
    assert cfg.chunk_bytes < cfg.pallas_min_chunk
    assert cfg.resolved_backend() == "xla"


def test_explicit_backends_resolve_to_themselves(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert Config(backend="xla").resolved_backend() == "xla"
    assert Config(backend="pallas").resolved_backend() == "pallas"


def test_pallas_max_token_validated_for_auto_and_pallas():
    with pytest.raises(ValueError, match="pallas_max_token"):
        Config(backend="auto", pallas_max_token=0)
    with pytest.raises(ValueError, match="pallas_max_token"):
        Config(backend="pallas", pallas_max_token=0)
    Config(backend="xla", pallas_max_token=0)  # xla never consults it


def test_pallas_chunk_floor_enforced_only_for_explicit_pallas():
    with pytest.raises(ValueError, match="chunk_bytes"):
        Config(backend="pallas", chunk_bytes=1 << 10)
    Config(backend="auto", chunk_bytes=1 << 10)  # auto falls back instead


def test_chunk_bytes_alignment():
    with pytest.raises(ValueError, match="multiple of 128"):
        Config(chunk_bytes=1000)
