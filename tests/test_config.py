"""Config validation and 'auto' backend resolution.

The CPU-pinned suite (conftest forces JAX_PLATFORMS=cpu) never sees a real
TPU, so the TPU branches of ``resolved_backend`` are exercised here by
monkeypatching ``jax.default_backend`` — the resolution logic is pure given
(platform, chunk_bytes).
"""

from __future__ import annotations

import jax
import pytest

from mapreduce_tpu.config import Config

# Pure-host validation logic: the cheapest module in the fast tier.
pytestmark = pytest.mark.smoke


def test_default_backend_is_auto():
    assert Config().backend == "auto"


def test_auto_resolves_to_xla_off_tpu():
    assert jax.default_backend() != "tpu"  # conftest pins CPU
    assert Config().resolved_backend() == "xla"


def test_auto_resolves_to_pallas_on_tpu(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    cfg = Config(chunk_bytes=1 << 20)
    assert cfg.chunk_bytes >= cfg.pallas_min_chunk
    assert cfg.resolved_backend() == "pallas"


def test_auto_falls_back_to_xla_for_small_chunks_on_tpu(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    cfg = Config(chunk_bytes=1 << 10)  # below pallas_min_chunk (8448 @ W=32)
    assert cfg.chunk_bytes < cfg.pallas_min_chunk
    assert cfg.resolved_backend() == "xla"


def test_explicit_backends_resolve_to_themselves(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert Config(backend="xla").resolved_backend() == "xla"
    assert Config(backend="pallas").resolved_backend() == "pallas"


def test_pallas_max_token_validated_for_auto_and_pallas():
    with pytest.raises(ValueError, match="pallas_max_token"):
        Config(backend="auto", pallas_max_token=0)
    with pytest.raises(ValueError, match="pallas_max_token"):
        Config(backend="pallas", pallas_max_token=0)
    Config(backend="xla", pallas_max_token=0)  # xla never consults it


def test_pallas_chunk_floor_enforced_only_for_explicit_pallas():
    with pytest.raises(ValueError, match="chunk_bytes"):
        Config(backend="pallas", chunk_bytes=1 << 10)
    Config(backend="auto", chunk_bytes=1 << 10)  # auto falls back instead


def test_chunk_bytes_alignment():
    with pytest.raises(ValueError, match="multiple of 128"):
        Config(chunk_bytes=1000)


def test_defaults_match_measured_decisions():
    """Pin the production defaults to the round-4 on-chip measurements
    (BENCHMARKS.md "Round 4: the full suite"): 32 MB chunks beat both 1 MB
    (dispatch-bound) and 64 MB (sort superlinear + HBM pressure); slot
    compaction default-on at 88 (+25%); merge_every=1 (batching measured a
    loss on top of compaction); sort3 (segmin wedges the chip).  A default
    drifting from the measured winner should fail loudly here (VERDICT r4
    weak #2: "production defaults ignore the round's own measurements")."""
    cfg = Config()
    assert cfg.chunk_bytes == 1 << 25  # 32 MB
    assert cfg.sort_mode == "stable2"  # round-5 on-chip A/B: +5.9% zipf
    # Round-6 pricing note (BENCHMARKS.md): the radix partition loses 2-3x
    # from measured rates — xla stays default until a live window says
    # otherwise.
    assert cfg.sort_impl == "xla"
    assert cfg.resolved_compact_slots == 128  # lane-major 384-byte windows
    assert cfg.resolved_block_rows == 384
    assert cfg.merge_every == 1
    assert cfg.rescue_slots == 1024

    # The CLI must hand users the same measured-optimal shape with no flags.
    from mapreduce_tpu.cli import build_parser

    args = build_parser().parse_args([])
    assert args.chunk_bytes == cfg.chunk_bytes
    assert args.merge_every == cfg.merge_every
    assert args.sort_mode == cfg.sort_mode
    assert args.sort_impl == cfg.sort_impl
    assert args.compact_slots is None  # auto -> resolved_compact_slots


def test_segmin_refused_on_tpu(monkeypatch):
    """The segmin TPU wedge guard (VERDICT r4 weak #3): tracing the packed
    aggregation with sort_mode='segmin' while the default backend is TPU
    must refuse, unless MAPREDUCE_ALLOW_SEGMIN opts in deliberately."""
    import jax.numpy as jnp

    from mapreduce_tpu.ops import table as table_ops

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("MAPREDUCE_ALLOW_SEGMIN", raising=False)
    k = jnp.zeros((8,), jnp.uint32)
    p = jnp.full((8,), 0xFFFFFFFF, dtype=jnp.uint32)
    with pytest.raises(ValueError, match="segmin.*disabled|disabled.*segmin"):
        table_ops.from_packed_rows(k, k, p, jnp.uint32(0), 4, 0,
                                   sort_mode="segmin")
    monkeypatch.setenv("MAPREDUCE_ALLOW_SEGMIN", "1")
    table_ops.from_packed_rows(k, k, p, jnp.uint32(0), 4, 0,
                               sort_mode="segmin")  # override path stays alive


def test_inflight_groups_validation():
    with pytest.raises(ValueError, match="inflight_groups"):
        Config(inflight_groups=0)
    with pytest.raises(ValueError, match="inflight_groups"):
        Config(inflight_groups=-2)
    assert Config(inflight_groups=1).inflight_groups == 1  # serial fallback
    assert Config().inflight_groups >= 1


def test_prefetch_depth_validation_and_resolution():
    with pytest.raises(ValueError, match="prefetch_depth"):
        Config(prefetch_depth=0)
    # explicit depth wins verbatim
    assert Config(prefetch_depth=7).resolved_prefetch_depth == 7
    # auto: co-tuned with the window (superstep * inflight), clamped [2, 16]
    assert Config(superstep=1, inflight_groups=1).resolved_prefetch_depth == 2
    assert Config(superstep=2, inflight_groups=3).resolved_prefetch_depth == 6
    assert Config(superstep=8, inflight_groups=8).resolved_prefetch_depth == 16
