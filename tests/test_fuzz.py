"""Adversarial/fuzz equivalence: both device backends vs the NumPy oracle on
hostile byte content — every byte value, pathological separator runs, words
at exactly the capacity/length envelopes (SURVEY §4 property tests)."""

from __future__ import annotations

import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.utils import oracle

XLA = Config(chunk_bytes=1 << 12, table_capacity=1 << 12, backend="xla")
PALLAS = Config(chunk_bytes=128 * 66, table_capacity=1 << 12, backend="pallas")


def _check(data: bytes, config: Config) -> None:
    got = wordcount.count_words(data, config).as_dict()
    assert got == oracle.word_counts(data)


@pytest.mark.parametrize("config", [XLA, PALLAS], ids=["xla", "pallas"])
@pytest.mark.parametrize("seed", range(3))
@pytest.mark.slow
def test_random_full_alphabet(config, seed):
    """Random bytes over the FULL 0-255 alphabet: punctuation, UTF-8
    continuation bytes, NULs, and every separator class."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, size=2000, dtype=np.uint8)
    # Raise separator density so tokens stay within the pallas W bound.
    data[rng.random(2000) < 0.3] = 0x20
    _check(bytes(data), config)


# pallas id @slow (the ">= ~10 s carries @slow" rebalance, ISSUE 8 round:
# 27 s — nine interpret-mode kernel executions): the xla sweep keeps every
# pathology fast-tier, the pallas kernel keeps its randomized fast-tier
# equivalence via test_backend_oracle_equivalence; the pallas pathology
# sweep runs in the full suite.
@pytest.mark.parametrize("config", [
    pytest.param(XLA, id="xla"),
    pytest.param(PALLAS, id="pallas", marks=pytest.mark.slow)])
def test_separator_pathologies(config):
    for data in (b"", b" ", b"   \n\t\r  ", b"\x00\x00\x00", b"x",
                 b" x", b"x ", b"\nx\n", b"a \t\r\n\x0b\x0c b"):
        _check(data, config)


@pytest.mark.parametrize(
    "config",
    [XLA,
     # ~30 s on the one-core box; tier-1 budget rule
     pytest.param(PALLAS, marks=pytest.mark.slow)],
    ids=["xla", "pallas"])
def test_words_at_length_envelope(config):
    """1-byte words, W-byte words (the pallas fast-path bound), and high-bit
    bytes that would sign-extend if the kernel widened incorrectly."""
    w31, w32 = b"a" * 31, b"b" * 32
    hi = bytes([0xFF, 0xFE, 0x80]) * 4
    data = b" ".join([b"x", w31, w32, hi, w31, b"x", hi])
    _check(data, config)


@pytest.mark.slow
def test_pallas_drops_only_overlong(rng):
    """Mixed stream: with rescue off, pallas == oracle minus tokens longer
    than W (the accounting contract); the default rescue counts them too
    (tests/test_rescue.py owns that surface)."""
    words = [b"ok", b"c" * 33, b"fine", b"d" * 100, b"ok"]
    data = b" ".join(words)
    import dataclasses

    r = wordcount.count_words(
        data, dataclasses.replace(PALLAS, rescue_overlong=0))
    assert r.as_dict() == {b"ok": 2, b"fine": 1}
    assert r.dropped_count == 2 and r.total == 5
    # Default config: the same stream counts exactly.
    r2 = wordcount.count_words(data, PALLAS)
    assert r2.as_dict() == {b"ok": 2, b"c" * 33: 1, b"fine": 1, b"d" * 100: 1}
    assert r2.dropped_count == 0 and r2.total == 5


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.slow
def test_streamed_capacity_pressure_keeps_exact_totals(tmp_path, seed):
    """Randomized soak slice: under table-capacity pressure a streamed run
    keeps exact totals and every reported count exact (drops are accounted,
    never miscounted)."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(500, 6000))
    data = rng.integers(0, 256, size=n, dtype=np.uint8)
    data[rng.random(n) < float(rng.uniform(0.1, 0.5))] = rng.choice(
        np.array([0x20, 0x0A, 0x09, 0x0D], np.uint8))
    blob = bytes(data)
    want = oracle.word_counts(blob)
    cap = int(rng.choice([64, 256]))

    path = tmp_path / "f.txt"
    path.write_bytes(blob)
    r = executor.count_file(str(path),
                            Config(chunk_bytes=512, table_capacity=cap,
                                   backend="xla"), mesh=data_mesh(4))
    assert r.total == oracle.total_count(blob)
    for w, c in r.as_dict().items():
        assert want.get(w) == c, w
    # Under spill `distinct` is the table's KMV estimate (unbiased, stderr
    # ~1/sqrt(capacity)) — not an upper bound.  4-sigma tolerance at these
    # tiny fuzz capacities; never below the exactly-kept word count.
    assert r.distinct >= len(r.words)
    if r.dropped_uniques:
        assert abs(r.distinct - len(want)) / len(want) <= 4.0 / np.sqrt(cap)
    else:
        assert r.distinct == len(want)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_class_grep_vs_re(seed):
    """Regex-lite class patterns on hostile byte content vs Python re with
    overlapping-match semantics."""
    import re

    from mapreduce_tpu.models import grep

    rng = np.random.default_rng(100 + seed)
    data = bytes(rng.integers(1, 256, size=3000, dtype=np.uint8))
    cases = [
        (b"[a-z][0-9]", rb"[a-z][0-9]"),
        (b".[A-F]", rb"[^\n\x00][A-F]"),
        (b"[^a-z]x", rb"[^a-z\x00]x"),
    ]
    for spec, regex in cases:
        r = grep.grep_bytes(data, spec, syntax="class")
        want = sum(1 for _ in re.finditer(b"(?=" + regex + b")", data,
                                          re.DOTALL))
        assert r.matches == want, (seed, spec)
        want_lines = sum(1 for line in data.split(b"\n")
                         if re.search(regex, line, re.DOTALL))
        assert r.lines == want_lines, (seed, spec)


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_sample_totals_and_membership(tmp_path, seed):
    """Sampling under random chunk geometries: total always exact, every
    sampled token is a real corpus token, k honored."""
    from tests.conftest import make_corpus
    from mapreduce_tpu.models import sample as sample_mod
    from mapreduce_tpu.parallel.mesh import data_mesh

    rng = np.random.default_rng(200 + seed)
    corpus = make_corpus(rng, n_words=int(rng.integers(300, 2500)),
                         vocab=int(rng.integers(20, 300)))
    path = tmp_path / f"s{seed}.txt"
    path.write_bytes(corpus)
    k = int(rng.integers(1, 60))
    cfg = Config(chunk_bytes=128 * int(rng.integers(1, 6)),
                 table_capacity=1 << 10)
    r = sample_mod.sample_file(str(path), k, config=cfg,
                               mesh=data_mesh(int(rng.integers(1, 5))))
    assert r.total == oracle.total_count(corpus)
    assert len(r.tokens) == min(k, r.total)
    words = set(oracle.split_words(corpus))
    for t in r.tokens:
        assert t in words


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.slow
def test_fuzz_multigrep_singles_agreement(tmp_path, seed):
    """Random pattern sets over random corpora: the fused multi-pass must
    equal per-pattern runs, streamed, under random geometry."""
    from tests.conftest import make_corpus
    from mapreduce_tpu.models import grep
    from mapreduce_tpu.parallel.mesh import data_mesh

    rng = np.random.default_rng(300 + seed)
    corpus = make_corpus(rng, n_words=1500, vocab=80)
    path = tmp_path / f"m{seed}.txt"
    path.write_bytes(corpus)
    vocab_words = [b"w%x" % i for i in range(80)]
    pats = [vocab_words[int(i)] for i in rng.integers(0, 80, size=4)]
    pats.append(b"\n")  # separator byte as a pattern
    cfg = Config(chunk_bytes=128 * int(rng.integers(1, 5)))
    mesh = data_mesh(int(rng.integers(1, 4)))
    multi = grep.grep_file_multi(str(path), pats, config=cfg, mesh=mesh)
    for p, r in zip(pats, multi):
        single = grep.grep_file(str(path), p, config=cfg, mesh=mesh)
        assert (r.matches, r.lines) == (single.matches, single.lines), p


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.slow
def test_fuzz_streamed_ngrams_exact_random_geometry(tmp_path, seed):
    """Streamed n-grams == single-buffer under random corpus geometry:
    random chunk size, mesh width, gram order, separator statistics —
    every chunk-seam shape the carry monoid must handle (tiny chunks,
    seam-straddling grams, separator runs)."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file

    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(2, 5))
    words = [f"w{i}" for i in range(int(rng.integers(5, 60)))]
    parts = []
    for _ in range(int(rng.integers(200, 1200))):
        parts.append(words[int(rng.integers(0, len(words)))])
        # Occasional long separator runs so some chunks hold few/no tokens.
        sep = " " if rng.random() < 0.9 else \
            " " * int(rng.integers(2, 200)) + "\n"
        parts.append(sep)
    corpus = "".join(parts).encode()
    path = tmp_path / "fz.txt"
    path.write_bytes(corpus)
    chunk = int(rng.choice([128, 256, 512, 1024]))
    mesh = data_mesh(int(rng.choice([1, 2, 4, 8])))
    cfg = Config(chunk_bytes=chunk, table_capacity=1 << 14, backend="xla")
    streamed = count_file(str(path), config=cfg, mesh=mesh, ngram=n)
    single = wordcount.count_ngrams(
        corpus, n, Config(table_capacity=1 << 14, backend="xla"))
    assert streamed.total == single.total, (n, chunk, mesh.size)
    assert streamed.as_dict() == single.as_dict(), (n, chunk, mesh.size)
    assert streamed.words == single.words, (n, chunk, mesh.size)
