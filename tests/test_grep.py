"""Distributed grep: device counts vs. a pure-Python oracle.

Oracle semantics (module docstring of :mod:`mapreduce_tpu.models.grep`):
overlapping occurrences; matching lines = lines containing >= 1 occurrence.
"""

import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import grep


def occurrences(data: bytes, pat: bytes) -> int:
    return sum(1 for i in range(len(data) - len(pat) + 1)
               if data[i: i + len(pat)] == pat)


def matching_lines(data: bytes, pat: bytes) -> int:
    return sum(1 for line in data.split(b"\n") if pat in line)


def test_overlapping_occurrences():
    r = grep.grep_bytes(b"aaaa\n", b"aa")
    assert r.matches == 3  # overlapping, unlike bytes.count's 2
    assert r.lines == 1


@pytest.mark.parametrize("pat", [b"w1", b"w23", b"w1 w", b"zqx"])
def test_matches_oracle(small_corpus, pat):
    r = grep.grep_bytes(small_corpus, pat)
    assert r.matches == occurrences(small_corpus, pat)
    # Patterns without newline: matching-lines oracle applies exactly.
    assert r.lines == matching_lines(small_corpus, pat)


def test_multiple_matches_one_line_count_once():
    r = grep.grep_bytes(b"x y x y x\nplain\nx\n", b"x")
    assert r.matches == 4
    assert r.lines == 2


def test_empty_and_oversized_pattern_rejected():
    with pytest.raises(ValueError):
        grep.GrepJob(b"")
    with pytest.raises(ValueError):
        grep.GrepJob(b"a" * 257)


def test_pattern_longer_than_data():
    r = grep.grep_bytes(b"hi\n", b"this-pattern-is-longer-than-the-data")
    assert r.matches == 0 and r.lines == 0


def test_streamed_grep_matches_oracle(tmp_path, small_corpus):
    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024)
    r = grep.grep_file(str(path), b"w1", config=cfg)
    # Separator-free patterns cannot span the separator-aligned chunk seams:
    # occurrence counts are exact under sharding.
    assert r.matches == occurrences(small_corpus, b"w1")
    # Lines are exact even when logical lines split across rows: the per-step
    # summary all_gather + carry chain dedups continuation segments.
    assert r.lines == matching_lines(small_corpus, b"w1")


def test_streamed_grep_line_split_across_rows_exact(tmp_path):
    """VERDICT r1 #9 'done' case: a matching line whose segments land in
    different chunk rows (and different steps) must count once."""
    # Lines far longer than chunk_bytes, separated by spaces so the reader
    # cuts mid-line at separator boundaries; matches in several segments.
    line1 = b"MATCH " + b"x " * 150 + b"MATCH " + b"y " * 150 + b"MATCH"
    line2 = b"z " * 200  # no match
    line3 = b"a " * 100 + b"MATCH " + b"b " * 250  # match mid-line
    corpus = line1 + b"\n" + line2 + b"\n" + line3 + b"\n"
    path = tmp_path / "long.txt"
    path.write_bytes(corpus)
    for chunk_bytes in (128, 256, 512):
        cfg = Config(chunk_bytes=chunk_bytes)
        r = grep.grep_file(str(path), b"MATCH", config=cfg)
        assert r.matches == occurrences(corpus, b"MATCH"), chunk_bytes
        assert r.lines == matching_lines(corpus, b"MATCH") == 2, chunk_bytes


def test_streamed_grep_transparent_middle_rows_exact(tmp_path):
    """A line spanning 3+ rows with an unmatched middle row: the transparent
    (newline-free, matchless) row must pass the carry through unchanged."""
    corpus = (b"MATCH " + b"q " * 800 + b"MATCH\n" +  # one line, many rows
              b"plain line\n")
    path = tmp_path / "t.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=128)
    r = grep.grep_file(str(path), b"MATCH", config=cfg)
    assert r.matches == 2
    assert r.lines == 1


@pytest.mark.slow
def test_streamed_grep_lines_exact_fuzz(tmp_path, rng):
    """Randomized cross-check of the exact-lines carry chain against the
    pure-Python oracle under many row geometries."""
    words = [b"MATCH", b"aa", b"b", b"ccc dd", b"ee\nff", b"\n", b"gg hh ii"]
    for trial in range(6):
        parts = [words[int(i)] for i in rng.integers(0, len(words), size=600)]
        corpus = b" ".join(parts) + b"\n"
        path = tmp_path / f"f{trial}.txt"
        path.write_bytes(corpus)
        cfg = Config(chunk_bytes=128 * int(rng.integers(1, 4)))
        r = grep.grep_file(str(path), b"MATCH", config=cfg)
        assert r.matches == occurrences(corpus, b"MATCH")
        assert r.lines == matching_lines(corpus, b"MATCH"), \
            (trial, cfg.chunk_bytes)


def test_64bit_carry_accumulation():
    """The lo/hi carry math is exact where a uint32 would wrap."""
    import jax.numpy as jnp
    import numpy as np

    job = grep.GrepJob(b"x")
    near = jnp.uint32(0xFFFFFFF0)
    state = grep.GrepState(near, jnp.uint32(0), near, jnp.uint32(0))
    other = grep.GrepState(jnp.uint32(0x20), jnp.uint32(0),
                           jnp.uint32(0x20), jnp.uint32(0))
    merged = job.merge(state, other)
    result = grep._state_result(b"x", merged)
    assert result.matches == 0xFFFFFFF0 + 0x20  # > 2**32
    assert result.lines == 0xFFFFFFF0 + 0x20


@pytest.mark.slow
def test_grep_cli(tmp_path, capsys):
    from mapreduce_tpu import cli

    path = tmp_path / "c.txt"
    path.write_bytes(b"the cat\nthe dog\nno match\n")
    assert cli.main([str(path), "--grep", "the"]) == 0
    out = capsys.readouterr().out
    assert "Matches:2\nMatching Lines:2\n" in out
    assert cli.main([str(path), "--grep", "the", "--format", "json"]) == 0
    assert '"matches": 2' in capsys.readouterr().out
    assert cli.main([str(path), "--grep", "the", "--stream",
                     "--format", "tsv"]) == 0
    assert "matches\t2" in capsys.readouterr().out


def test_grep_checkpoint_resume(tmp_path, small_corpus):
    """Grep's scalar state rides the generic pytree snapshot format."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import checkpoint as ckpt

    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024)
    ck = str(tmp_path / "grep.npz")
    full = grep.grep_file(str(path), b"w1", config=cfg, mesh=data_mesh(2))
    r1 = grep.grep_file(str(path), b"w1", config=cfg, mesh=data_mesh(2),
                        checkpoint_path=ck, checkpoint_every=1)
    assert ckpt.exists(ck)
    r2 = grep.grep_file(str(path), b"w1", config=cfg, mesh=data_mesh(2),
                        checkpoint_path=ck, checkpoint_every=1)
    assert r1.matches == r2.matches == full.matches
    assert r1.lines == r2.lines == full.lines

    # A word-count run must refuse grep's snapshot (different structure).
    import pytest
    from mapreduce_tpu.runtime import executor

    with pytest.raises(ckpt.CheckpointMismatch):
        executor.count_file(str(path), config=cfg, mesh=data_mesh(2),
                            checkpoint_path=ck, checkpoint_every=1)


def test_grep_checkpoint_pattern_mismatch(tmp_path, small_corpus):
    """Same state SHAPE, different pattern: the job identity in the
    fingerprint refuses the resume (the review's silent-corruption case)."""
    import pytest
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import checkpoint as ckpt

    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024)
    ck = str(tmp_path / "grep.npz")
    grep.grep_file(str(path), b"w1", config=cfg, mesh=data_mesh(2),
                   checkpoint_path=ck, checkpoint_every=1)
    with pytest.raises(ckpt.CheckpointMismatch, match="job"):
        grep.grep_file(str(path), b"w2", config=cfg, mesh=data_mesh(2),
                       checkpoint_path=ck, checkpoint_every=1)


def test_grep_exact_lines_2d_mesh(tmp_path):
    """The seam-correction all_gather must order rows identically on a 2-D
    ('replica','data') mesh (row-major over the axes, matching
    Engine._device_index) — exactness would break if gather order and row
    order diverged."""
    import jax

    from mapreduce_tpu.data import reader
    from mapreduce_tpu.parallel.mapreduce import Engine
    from mapreduce_tpu.parallel.mesh import two_level_mesh

    line = b"MATCH " + b"w " * 600 + b"MATCH"  # spans many 128-byte rows
    corpus = line + b"\nplain\nMATCH line\n"
    path = tmp_path / "m.txt"
    path.write_bytes(corpus)

    eng = Engine(grep.GrepJob(b"MATCH"), two_level_mesh(2, 4),
                 axis=("replica", "data"))
    state = eng.init_states()
    for b in reader.iter_batches(str(path), 8, 128):
        state = eng.step(state, b.data, b.step)
    r = grep._state_result(b"MATCH", eng.finish(state))
    assert r.matches == occurrences(corpus, b"MATCH")
    assert r.lines == matching_lines(corpus, b"MATCH") == 2


def test_streamed_multi_file_grep_no_carry_leak(tmp_path):
    """Files are independent corpora: the open-line carry from a file with
    no trailing newline must not suppress (or join) the next file's first
    line.  Streamed and non-stream per-file semantics must agree."""
    a, b = tmp_path / "a.txt", tmp_path / "b.txt"
    a.write_bytes(b"x MATCH")  # no trailing newline: line stays open at EOF
    b.write_bytes(b"MATCH y\n")
    r = grep.grep_file([str(a), str(b)], b"MATCH",
                       config=Config(chunk_bytes=128))
    assert r.matches == 2
    assert r.lines == 2  # one matching line in each file
    # And with a multi-row continuation before the boundary.
    c = tmp_path / "c.txt"
    c.write_bytes(b"MATCH " + b"q " * 200)  # open line spanning rows, no \n
    r2 = grep.grep_file([str(c), str(b)], b"MATCH",
                        config=Config(chunk_bytes=128))
    assert r2.matches == 2
    assert r2.lines == 2


@pytest.mark.slow
def test_multi_pattern_grep_matches_singles(tmp_path, small_corpus):
    """MultiGrepJob: P patterns in one pass must equal P single runs."""
    pats = [b"w1", b"w23", b"zqx", b"w1 w"]
    multi = grep.grep_bytes_multi(small_corpus, pats)
    for p, r in zip(pats, multi):
        single = grep.grep_bytes(small_corpus, p)
        assert (r.matches, r.lines) == (single.matches, single.lines), p

    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024)
    streamed = grep.grep_file_multi(str(path), pats, config=cfg)
    for p, r in zip(pats, streamed):
        single = grep.grep_file(str(path), p, config=cfg)
        assert (r.matches, r.lines) == (single.matches, single.lines), p


def test_multi_pattern_grep_exact_lines_across_rows(tmp_path):
    """The [P]-shaped carry chain stays exact per pattern when lines span
    rows (each pattern has its own open-line bit)."""
    corpus = (b"AAA " + b"x " * 100 + b"BBB\n" +  # one long line: AAA & BBB
              b"BBB solo\n" + b"q " * 200 + b"\n")
    path = tmp_path / "m.txt"
    path.write_bytes(corpus)
    rs = grep.grep_file_multi(str(path), [b"AAA", b"BBB", b"q"],
                              config=Config(chunk_bytes=128))
    assert (rs[0].matches, rs[0].lines) == (1, 1)
    assert (rs[1].matches, rs[1].lines) == (2, 2)
    assert rs[2].lines == 1  # all q's on one (newline-terminated) line


def test_multi_grep_checkpoint_identity(tmp_path, small_corpus):
    """Different pattern SETS share state shapes only if P matches; the job
    identity must still refuse cross-resume."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import checkpoint as ckpt

    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024)
    ck = str(tmp_path / "g.npz")
    grep.grep_file_multi(str(path), [b"w1", b"w2"], config=cfg,
                         mesh=data_mesh(2), checkpoint_path=ck,
                         checkpoint_every=1)
    with pytest.raises(ckpt.CheckpointMismatch, match="job"):
        grep.grep_file_multi(str(path), [b"w1", b"w3"], config=cfg,
                             mesh=data_mesh(2), checkpoint_path=ck,
                             checkpoint_every=1)


def test_multi_grep_cli(tmp_path, capsys):
    from mapreduce_tpu import cli

    path = tmp_path / "c.txt"
    path.write_bytes(b"the cat sat\nthe dog\nno match here\n")
    assert cli.main([str(path), "--grep", "the", "--grep", "cat"]) == 0
    out = capsys.readouterr().out
    assert "Pattern:the\nMatches:2\nMatching Lines:2\n" in out
    assert "Pattern:cat\nMatches:1\nMatching Lines:1\n" in out
    assert cli.main([str(path), "--grep", "the", "--grep", "dog",
                     "--format", "json"]) == 0
    import json as _json

    obj = _json.loads(capsys.readouterr().out)
    assert obj["patterns"][1] == {"pattern": "dog", "matches": 1, "lines": 1}
    # Single-pattern output shape is unchanged.
    assert cli.main([str(path), "--grep", "the"]) == 0
    assert capsys.readouterr().out == "Matches:2\nMatching Lines:2\n"


# --- regex-lite byte classes (--grep-syntax class) -------------------------

def re_overlapping(data: bytes, regex: bytes) -> int:
    import re

    return sum(1 for _ in re.finditer(b"(?=" + regex + b")", data, re.DOTALL))


def re_matching_lines(data: bytes, regex: bytes) -> int:
    import re

    return sum(1 for line in data.split(b"\n")
               if re.search(regex, line, re.DOTALL))


@pytest.mark.parametrize("spec,regex", [
    (b"[0-9][0-9]", rb"[0-9][0-9]"),
    (b"w.x", rb"w[^\n\x00]x"),
    (b"[a-cx]1", rb"[a-cx]1"),
    (b"[^ 0-9]z", rb"[^ 0-9\x00]z"),
    (rb"a\.b", rb"a\.b"),
])
def test_class_patterns_match_re_oracle(spec, regex):
    data = (b"w1x w9x 42 73 a1 b1 c1 x1 d1 qz 9z\n"
            b"a.b a,b axb\nw\nx 10 99 [z] .z\n")
    r = grep.grep_bytes(data, spec, syntax="class")
    assert r.matches == re_overlapping(data, regex), spec
    assert r.lines == re_matching_lines(data, regex), spec


def test_class_pattern_overlapping_and_dotall():
    # '.' matches any byte except newline (and the NUL pad).
    r = grep.grep_bytes(b"aaa\naaa\n", b"a.a", syntax="class")
    assert r.matches == 2  # one per line; '.' never crosses the newline
    r2 = grep.grep_bytes(b"aaaa\n", b"a.a", syntax="class")
    assert r2.matches == 2  # overlapping starts at 0 and 1


def test_class_pattern_parse_errors():
    for bad in (b"[abc", b"[]x", b"a\\", b"[z-a]"):
        with pytest.raises(ValueError):
            grep.ClassPattern(bad)
    with pytest.raises(ValueError, match="NUL"):
        grep.ClassPattern(b"[\x00-\x05]")
    # Negated classes are fine: NUL stays excluded automatically.
    grep.ClassPattern(b"[^abc]")


def test_class_pattern_streamed_matches_single_buffer(tmp_path):
    corpus = (b"id42 and id73 overlap 1234 here\n" * 30
              + b"no digits on this line\n" * 10)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    single = grep.grep_bytes(corpus, b"[0-9][0-9]", syntax="class")
    streamed = grep.grep_file(str(path), b"[0-9][0-9]",
                              config=Config(chunk_bytes=128), syntax="class")
    assert (streamed.matches, streamed.lines) == (single.matches, single.lines)
    assert single.matches == re_overlapping(corpus, rb"[0-9][0-9]")
    assert single.lines == re_matching_lines(corpus, rb"[0-9][0-9]")


def test_class_pattern_multi_and_identity(tmp_path, small_corpus):
    """Class + multi compose; literal and class jobs for byte-identical
    specs have distinct checkpoint identities."""
    rs = grep.grep_bytes_multi(small_corpus, [b"w[0-9]", b"[a-z]1"],
                               syntax="class")
    assert rs[0].matches == re_overlapping(small_corpus, rb"w[0-9]")
    assert rs[1].matches == re_overlapping(small_corpus, rb"[a-z]1")
    lit = grep.GrepJob(b"w.x")  # literal dot: 3 exact bytes
    cls = grep.GrepJob(b"w.x", syntax="class")
    assert lit.identity() != cls.identity()


def test_class_pattern_cli(tmp_path, capsys):
    from mapreduce_tpu import cli

    path = tmp_path / "c.txt"
    path.write_bytes(b"ab1 cd2 xyz\nno digits\n")
    assert cli.main([str(path), "--grep", "[a-d][a-d][0-9]",
                     "--grep-syntax", "class", "--format", "json"]) == 0
    import json as _json

    obj = _json.loads(capsys.readouterr().out)
    assert obj["matches"] == 2 and obj["lines"] == 1
    # --grep-syntax without --grep is an honest error.
    with pytest.raises(SystemExit):
        cli.main([str(path), "--grep-syntax", "class"])


def test_grep_resume_across_file_seam_keeps_boundary_reset(tmp_path, monkeypatch):
    """Advisor round 2 (medium): the flush that ends a file checkpoints
    BEFORE the boundary hook resets the line carry, so the snapshot holds a
    set carry and sits exactly at the seam.  A resumed run must still fire
    on_input_boundary on the next file's first batch — without the persisted
    file index it silently never did, and the resumed count diverged from
    the uninterrupted one (lines=1 vs lines=2)."""
    from mapreduce_tpu.parallel import mapreduce as mr
    from mapreduce_tpu.parallel.mesh import data_mesh

    a, b = tmp_path / "a.txt", tmp_path / "b.txt"
    a.write_bytes(b"x MATCH")   # unterminated matching line: carry=1 at EOF
    b.write_bytes(b"MATCH y\n")
    cfg = Config(chunk_bytes=128)
    paths = [str(a), str(b)]
    mesh = data_mesh(2)

    full = grep.grep_file(paths, b"MATCH", config=cfg, mesh=mesh)
    assert (full.matches, full.lines) == (2, 2)

    # checkpoint_every=1 puts a snapshot right after file A's only step;
    # the injected crash hits file B's first step, so the resumed run
    # starts exactly at the seam with the pre-reset carry.
    ck = str(tmp_path / "ck.npz")
    original = mr.Engine.step
    fired = []

    def crash_at_seam(self, state, chunks, step_index):
        if step_index == 1 and not fired:
            fired.append(step_index)
            raise RuntimeError("injected crash at file seam")
        return original(self, state, chunks, step_index)

    monkeypatch.setattr(mr.Engine, "step", crash_at_seam)
    with pytest.raises(RuntimeError, match="injected crash"):
        grep.grep_file(paths, b"MATCH", config=cfg, mesh=mesh,
                       checkpoint_path=ck, checkpoint_every=1)
    assert fired, "injection never fired; test is vacuous"

    resumed = grep.grep_file(paths, b"MATCH", config=cfg, mesh=mesh,
                             checkpoint_path=ck, checkpoint_every=1)
    assert (resumed.matches, resumed.lines) == (full.matches, full.lines)


def test_bare_map_chunk_sequential_exact_lines():
    """VERDICT r3 #8: the no-axis map_chunk fallback must be exact when rows
    are driven sequentially (map_chunk + combine, no mesh) — the single-row
    transfer terms make lines match the oracle even for lines spanning rows."""
    import jax.numpy as jnp

    from mapreduce_tpu.ops.tokenize import pad_to

    corpus = (b"MATCH " + b"x " * 100 + b"MATCH\n" +  # one line, many rows
              b"plain\n" + b"a " * 60 + b"MATCH " + b"b " * 90 + b"\n")
    job = grep.GrepJob(b"MATCH")
    for row_bytes in (128, 256):
        state = job.init_state()
        # Rows cut at separator boundaries like the reader does (a pattern
        # split mid-row is out of envelope; separators only here).
        off = 0
        while off < len(corpus):
            hi = min(off + row_bytes, len(corpus))
            if hi < len(corpus):
                while hi > off and corpus[hi - 1] not in b" \n\t\r":
                    hi -= 1
            row = np.frombuffer(corpus[off:hi], dtype=np.uint8)
            off = hi
            padded = pad_to(row, max(128, -(-row.shape[0] // 128) * 128))
            state = job.combine(state, job.map_chunk(jnp.asarray(padded),
                                                     jnp.uint32(0)))
        result = grep._state_result(b"MATCH", state)
        assert result.matches == occurrences(corpus, b"MATCH"), row_bytes
        assert result.lines == matching_lines(corpus, b"MATCH") == 2, row_bytes


def test_bare_map_chunk_multi_sequential_exact_lines():
    """Same exactness through MultiGrepJob's [P]-shaped fallback."""
    import jax.numpy as jnp

    from mapreduce_tpu.ops.tokenize import pad_to

    corpus = b"AB " + b"q " * 200 + b"CD\nAB CD\nplain\n"
    pats = [b"AB", b"CD", b"zz"]
    job = grep.MultiGrepJob(pats)
    state = job.init_state()
    off = 0
    while off < len(corpus):
        hi = min(off + 128, len(corpus))
        if hi < len(corpus):
            while hi > off and corpus[hi - 1] not in b" \n\t\r":
                hi -= 1
        row = np.frombuffer(corpus[off:hi], dtype=np.uint8)
        off = hi
        padded = pad_to(row, 128)
        state = job.combine(state, job.map_chunk(jnp.asarray(padded),
                                                 jnp.uint32(0)))
    for res, pat in zip(grep._multi_results(pats, state), pats):
        assert res.matches == occurrences(corpus, pat), pat
        assert res.lines == matching_lines(corpus, pat), pat
