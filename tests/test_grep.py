"""Distributed grep: device counts vs. a pure-Python oracle.

Oracle semantics (module docstring of :mod:`mapreduce_tpu.models.grep`):
overlapping occurrences; matching lines = lines containing >= 1 occurrence.
"""

import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import grep


def occurrences(data: bytes, pat: bytes) -> int:
    return sum(1 for i in range(len(data) - len(pat) + 1)
               if data[i: i + len(pat)] == pat)


def matching_lines(data: bytes, pat: bytes) -> int:
    return sum(1 for line in data.split(b"\n") if pat in line)


def test_overlapping_occurrences():
    r = grep.grep_bytes(b"aaaa\n", b"aa")
    assert r.matches == 3  # overlapping, unlike bytes.count's 2
    assert r.lines == 1


@pytest.mark.parametrize("pat", [b"w1", b"w23", b"w1 w", b"zqx"])
def test_matches_oracle(small_corpus, pat):
    r = grep.grep_bytes(small_corpus, pat)
    assert r.matches == occurrences(small_corpus, pat)
    # Patterns without newline: matching-lines oracle applies exactly.
    assert r.lines == matching_lines(small_corpus, pat)


def test_multiple_matches_one_line_count_once():
    r = grep.grep_bytes(b"x y x y x\nplain\nx\n", b"x")
    assert r.matches == 4
    assert r.lines == 2


def test_empty_and_oversized_pattern_rejected():
    with pytest.raises(ValueError):
        grep.GrepJob(b"")
    with pytest.raises(ValueError):
        grep.GrepJob(b"a" * 257)


def test_pattern_longer_than_data():
    r = grep.grep_bytes(b"hi\n", b"this-pattern-is-longer-than-the-data")
    assert r.matches == 0 and r.lines == 0


def test_streamed_grep_matches_oracle(tmp_path, small_corpus):
    from mapreduce_tpu.data import reader

    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024)
    r = grep.grep_file(str(path), b"w1", config=cfg)
    # Separator-free patterns cannot span the separator-aligned chunk seams:
    # occurrence counts are exact under sharding.
    assert r.matches == occurrences(small_corpus, b"w1")
    # Lines may split across rows: exact-to-upper-bound envelope, with the
    # bound derived from the ACTUAL row count (separator-aligned cuts make
    # rows shorter than chunk_bytes, so ceil(len/chunk) undercounts rows).
    n_rows = sum(int((b.lengths > 0).sum())
                 for b in reader.iter_batches(str(path), 8, cfg.chunk_bytes))
    exact_lines = matching_lines(small_corpus, b"w1")
    assert exact_lines <= r.lines <= exact_lines + n_rows - 1


def test_64bit_carry_accumulation():
    """The lo/hi carry math is exact where a uint32 would wrap."""
    import jax.numpy as jnp
    import numpy as np

    job = grep.GrepJob(b"x")
    near = jnp.uint32(0xFFFFFFF0)
    state = grep.GrepState(near, jnp.uint32(0), near, jnp.uint32(0))
    update = grep.GrepState(jnp.uint32(0x20), jnp.uint32(0),
                            jnp.uint32(0x20), jnp.uint32(0))
    merged = job.combine(state, update)
    result = grep._state_result(b"x", merged)
    assert result.matches == 0xFFFFFFF0 + 0x20  # > 2**32
    assert result.lines == 0xFFFFFFF0 + 0x20


def test_grep_cli(tmp_path, capsys):
    from mapreduce_tpu import cli

    path = tmp_path / "c.txt"
    path.write_bytes(b"the cat\nthe dog\nno match\n")
    assert cli.main([str(path), "--grep", "the"]) == 0
    out = capsys.readouterr().out
    assert "Matches:2\nMatching Lines:2\n" in out
    assert cli.main([str(path), "--grep", "the", "--format", "json"]) == 0
    assert '"matches": 2' in capsys.readouterr().out
    assert cli.main([str(path), "--grep", "the", "--stream",
                     "--format", "tsv"]) == 0
    assert "matches\t2" in capsys.readouterr().out


def test_grep_checkpoint_resume(tmp_path, small_corpus):
    """Grep's scalar state rides the generic pytree snapshot format."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import checkpoint as ckpt

    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024)
    ck = str(tmp_path / "grep.npz")
    full = grep.grep_file(str(path), b"w1", config=cfg, mesh=data_mesh(2))
    r1 = grep.grep_file(str(path), b"w1", config=cfg, mesh=data_mesh(2),
                        checkpoint_path=ck, checkpoint_every=1)
    assert ckpt.exists(ck)
    r2 = grep.grep_file(str(path), b"w1", config=cfg, mesh=data_mesh(2),
                        checkpoint_path=ck, checkpoint_every=1)
    assert r1.matches == r2.matches == full.matches
    assert r1.lines == r2.lines == full.lines

    # A word-count run must refuse grep's snapshot (different structure).
    import pytest
    from mapreduce_tpu.runtime import executor

    with pytest.raises(ckpt.CheckpointMismatch):
        executor.count_file(str(path), config=cfg, mesh=data_mesh(2),
                            checkpoint_path=ck, checkpoint_every=1)


def test_grep_checkpoint_pattern_mismatch(tmp_path, small_corpus):
    """Same state SHAPE, different pattern: the job identity in the
    fingerprint refuses the resume (the review's silent-corruption case)."""
    import pytest
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import checkpoint as ckpt

    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024)
    ck = str(tmp_path / "grep.npz")
    grep.grep_file(str(path), b"w1", config=cfg, mesh=data_mesh(2),
                   checkpoint_path=ck, checkpoint_every=1)
    with pytest.raises(ckpt.CheckpointMismatch, match="job"):
        grep.grep_file(str(path), b"w2", config=cfg, mesh=data_mesh(2),
                       checkpoint_path=ck, checkpoint_every=1)
