"""Run-history warehouse + live run watching (ISSUE 14).

Covers the jax-free warehouse (ingest across ledger versions v2..v8,
instance-aware dedupe on the crash+relaunch pattern, drift verdicts
against hand-computed series, resolve_prior parity with the three
resolvers it replaced, byte-stable re-ingest) and the live half (the
v8 ``progress`` heartbeat emitted by a real CPU streamed run, its <1 ms
host-side bound, and ``tools/obswatch.py`` tailing a growing file
written by that run).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tools" / "fixtures"

from mapreduce_tpu.obs import datahealth, history  # noqa: E402

sys.path.insert(0, str(REPO / "tools"))
try:
    import obs_report  # noqa: E402
    import obswatch  # noqa: E402
finally:
    sys.path.pop(0)


def _read(path) -> list:
    return history.read_jsonl(str(path))


# -- selftest entries (the tier-1/smoke shell gates, importable too) ---------

@pytest.mark.smoke
def test_history_selftest():
    assert history.selftest() == 0


@pytest.mark.smoke
def test_obswatch_selftest():
    assert obswatch.selftest() == 0


# -- ingest across ledger versions -------------------------------------------

def test_ingest_across_versions(tmp_path):
    """One warehouse over the whole fixture zoo: v2-v5 mini runs, the v6
    geometry run, the v7 fleet shards (fleet verdict attached), the v8
    in-flight run, the v9 chaotic run (fault/degrade records
    skip-or-consume), and the v99 future ledger — every version ingests,
    none errors (the forward-compat contract)."""
    idx = history.ingest([str(FIXTURES / "mini_ledger.jsonl"),
                          str(FIXTURES / "mini_ledger_b.jsonl"),
                          str(FIXTURES / "fleet_ledger.jsonl"),
                          str(FIXTURES / "future_ledger.jsonl")],
                         str(tmp_path))
    rows = {r["run_id"]: r for r in idx["runs"].values()}
    assert len(idx["runs"]) == 13  # 10 mini + 1 b + 1 fleet + 1 future
    assert rows["fixture11"]["completed"] is True  # degraded, alive (v9)
    assert rows["fixture01"]["completed"] is True
    assert rows["fixture05"]["data_verdict"] == "spill-bound"
    assert rows["fixture06"]["geometry"] == "tall512"
    assert rows["fleet01"]["fleet_bottleneck"] == "straggler-bound"
    assert rows["future01"]["completed"] is True
    # The in-flight v8 run keeps its last heartbeat in the digest.
    w = rows["fixture10"]
    assert w["completed"] is False and w["crashed"] is False
    dig = history.read_digest(str(tmp_path), w["id"])
    assert dig["progress"]["frac"] == 0.5 and dig["progress"]["eta_s"] == 2.0
    # Every run landed under a config key and its digest file exists.
    for r in idx["runs"].values():
        assert r["key"].count("/") == 5, r
        assert history.read_digest(str(tmp_path), r["id"]) is not None


def test_instance_aware_dedupe(tmp_path):
    """The crash+relaunch pattern (the documented multi-host contract:
    one shared run_id, append-mode file): two run_starts under one id
    ingest as two INSTANCES — crashed attempt and recovery never fuse —
    and re-ingest never duplicates them."""
    led = tmp_path / "crash.jsonl"
    recs = [
        {"ts": 1.0, "run_id": "shared", "kind": "run_start",
         "ledger_version": 8, "job": "wordcount", "backend": "xla",
         "driver": "run_job", "chunk_bytes": 4096},
        {"ts": 2.0, "run_id": "shared", "kind": "step", "step_first": 0,
         "step_last": 0, "steps": 1, "group_bytes": 4096,
         "cursor_bytes": 4096, "phases": {"dispatch": 0.1}},
        {"ts": 3.0, "run_id": "shared", "kind": "failure", "step": 1,
         "cursor_bytes": 4096, "error": "boom"},
        {"ts": 4.0, "run_id": "shared", "kind": "run_start",
         "ledger_version": 8, "job": "wordcount", "backend": "xla",
         "driver": "run_job", "chunk_bytes": 4096},
        {"ts": 5.0, "run_id": "shared", "kind": "step", "step_first": 0,
         "step_last": 1, "steps": 2, "group_bytes": 8192,
         "cursor_bytes": 8192, "phases": {"dispatch": 0.2}},
        {"ts": 6.0, "run_id": "shared", "kind": "run_end", "bytes": 8192,
         "elapsed_s": 0.5, "phases": {"dispatch": 0.2}},
    ]
    led.write_text("".join(json.dumps(r) + "\n" for r in recs))
    idx = history.ingest([str(led)], str(tmp_path / "h"))
    rows = sorted(idx["runs"].values(), key=history._row_order)
    assert len(rows) == 2, rows
    assert [r["instance"] for r in rows] == [0, 1]
    assert rows[0]["crashed"] is True and rows[0]["completed"] is False
    assert rows[1]["crashed"] is False and rows[1]["completed"] is True
    idx2 = history.ingest([str(led)], str(tmp_path / "h"))
    assert len(idx2["runs"]) == 2, "re-ingest must not duplicate instances"


def test_byte_stable_reingest(tmp_path):
    """Same ledgers in -> byte-identical index AND digest files out."""
    srcs = [str(FIXTURES / "history_ledger.jsonl"),
            str(FIXTURES / "fleet_ledger.jsonl")]
    d = str(tmp_path / "h")
    history.ingest(srcs, d)

    def fingerprint():
        out = {}
        for root, _, files in os.walk(d):
            for f in sorted(files):
                p = os.path.join(root, f)
                out[os.path.relpath(p, d)] = open(p, "rb").read()
        return out

    first = fingerprint()
    history.ingest(srcs, d)
    assert fingerprint() == first, "re-ingest must rewrite identical bytes"


# -- drift verdicts -----------------------------------------------------------

def _row(i, gbps, key="wc/x/b20-c4096/default/off/split", **kw):
    r = {"id": f"r{i}", "ts": float(i), "run_id": f"r{i}", "instance": 0,
         "key": key, "group": "/".join(key.split("/")[:3]),
         "geometry": key.split("/")[3], "combiner": key.split("/")[4],
         "map_impl": key.split("/")[5], "gb_per_s": gbps}
    r.update(kw)
    return r


def test_drift_hand_series():
    """The rule table against hand-computed series (the datahealth
    fixture discipline)."""
    # regressing: baseline median(0.10, 0.12, 0.11) = 0.11; latest 0.09
    # is 18.2% below the 10% gate.
    v = history.classify_drift(
        [_row(i, g) for i, g in enumerate([0.10, 0.12, 0.11, 0.09])])
    assert v["verdict"] == "regressing"
    assert v["signals"]["baseline_gbps"] == 0.11
    assert v["signals"]["delta_frac"] == round((0.09 - 0.11) / 0.11, 4)
    # improving: 0.14 vs median(0.10, 0.10) = +40%.
    v = history.classify_drift(
        [_row(i, g) for i, g in enumerate([0.10, 0.10, 0.14])])
    assert v["verdict"] == "improving"
    # steady: +5% is under the gate.
    v = history.classify_drift(
        [_row(i, g) for i, g in enumerate([0.10, 0.10, 0.105])])
    assert v["verdict"] == "steady"
    # config-drift outranks the throughput compare: the stamp moved.
    rows = [_row(0, 0.10), _row(1, 0.05,
                                key="wc/x/b20-c4096/tall512/off/split")]
    v = history.classify_drift(rows)
    assert v["verdict"] == "config-drift"
    assert "geometry" in v["flags"][0]["detail"]
    # no-history: one run is not a trend; an empty group even less so.
    assert history.classify_drift([_row(0, 0.1)])["verdict"] == "no-history"
    assert history.classify_drift([])["verdict"] == "no-history"
    # The baseline window slides: only the last DRIFT_WINDOW priors vote
    # (an ancient fast run must not regress every future forever).
    old = [_row(i, 9.9) for i in range(2)]
    recent = [_row(2 + i, 0.10) for i in range(history.DRIFT_WINDOW)]
    v = history.classify_drift(old + recent + [_row(99, 0.10)])
    assert v["verdict"] == "steady", v


def test_drift_on_fixture_series(tmp_path):
    """The checked-in 4-run series: median(0.100, 0.098, 0.101) = 0.100
    baseline, latest 0.085 -> regressing at 15%."""
    idx = history.ingest([str(FIXTURES / "history_ledger.jsonl")],
                         str(tmp_path))
    v = history.classify_drift(
        history.group_rows(idx, "wordcount/pallas/b28-c4194304"))
    assert v["verdict"] == "regressing"
    assert v["signals"]["baseline_gbps"] == 0.1
    assert v["signals"]["latest_gbps"] == 0.085
    rep = history.drift_report(idx)
    assert rep["wordcount/xla/b28-c4194304"]["verdict"] == "config-drift"
    # Longitudinal queries: the series and the verdict streak.
    key = "wordcount/pallas/b28-c4194304/default/off/split"
    assert [v for _, v in history.series(idx, key)] \
        == [0.1, 0.098, 0.101, 0.085]
    assert history.verdict_streak(idx, key) \
        == {"value": "skew-hot", "length": 4, "runs": 4}
    shares = history.phase_share_series(str(tmp_path), idx, key, "dispatch")
    assert len(shares) == 4 and all(0.7 < s < 0.9 for _, s in shares)


# -- resolve_prior parity -----------------------------------------------------

def test_resolve_prior_combiner_parity():
    """resolve_prior(records=...) reproduces datahealth.resolve_combiner
    bit-for-bit — including the append-mode latest-record semantics."""
    skew = {"kind": "data", "run_id": "a", "tokens": 1000,
            "top_count": 200, "chunks": 1}
    clean = {"kind": "data", "run_id": "b", "tokens": 1000,
             "top_count": 10, "chunks": 1}
    cases = [[skew], [clean], [], [clean, skew], [skew, clean],
             _read(FIXTURES / "mini_ledger.jsonl"),
             _read(FIXTURES / "mini_ledger_b.jsonl"),
             _read(FIXTURES / "future_ledger.jsonl")]
    for recs in cases:
        assert history.resolve_prior(records=recs)["combiner"] \
            == datahealth.resolve_combiner(recs)


def test_resolve_prior_geometry_parity(tmp_path):
    """resolve_prior(profile_path=...) reproduces the resolve_auto
    semantics — and resolve_auto itself now routes through it."""
    from mapreduce_tpu.analysis.geometry import resolve_auto
    from mapreduce_tpu.config import GEOMETRY_PRESETS

    spec = GEOMETRY_PRESETS["tall512"].as_dict()
    prof = tmp_path / "tuned.json"
    prof.write_text(json.dumps({"profiles": {
        "wordcount-geometry/a": {"recorded_at": "2026-01-01",
                                 "config": {"geometry": "tall512"}},
        "wordcount-geometry/b": {"recorded_at": "2026-02-01",
                                 "config": {"geometry": spec}},
        "wordcount-geometry/c": {"recorded_at": "2026-03-01",
                                 "config": {"geometry": "default"}},
    }}))
    # Freshest non-default entry wins: the spec dict (c is default).
    assert resolve_auto(str(prof)) == spec
    # A future-shaped spec dict is skipped, falling back to the preset.
    prof.write_text(json.dumps({"profiles": {
        "wordcount-geometry/a": {"recorded_at": "2026-01-01",
                                 "config": {"geometry": "tall512"}},
        "wordcount-geometry/b": {"recorded_at": "2026-02-01",
                                 "config": {"geometry": {"warp": 9}}},
    }}))
    assert resolve_auto(str(prof)) == "tall512"
    # Missing file / no usable entry degrade to 'default'.
    assert resolve_auto(str(tmp_path / "missing.json")) == "default"


def test_resolve_prior_run_view_parity():
    """derive_signals' run selection is resolve_prior's run view now:
    same chosen run, and the merged-fleet host anchoring holds (the
    chimera regression of PR 13)."""
    from mapreduce_tpu import tuning

    for fx in ("tuner_reader_bound", "tuner_device_bound",
               "tuner_skewhot", "tuner_geometry"):
        recs = _read(FIXTURES / f"{fx}.jsonl")
        sig = tuning.derive_signals(recs)
        prior = history.resolve_prior(records=recs)
        assert sig["run_id"] == prior["run_id"], fx
    # The merged-fleet anchor: host-1 records drop out of the run view.
    merged = [
        {"run_id": "m", "kind": "run_start", "host": 0, "backend": "xla"},
        {"run_id": "m", "kind": "run_start", "host": 1, "backend": "xla"},
        {"run_id": "m", "kind": "group", "host": 1, "step_first": 0,
         "staged_at": 1.0, "dispatched_at": 1.1, "token_ready_at": 2.0,
         "retired_at": 2.1},
        {"run_id": "m", "kind": "fleet",
         "fleet_bottleneck": {"verdict": "straggler-bound"}},
    ]
    prior = history.resolve_prior(records=merged)
    assert prior["fleet"] is not None
    assert all(r.get("host") in (0, None) for r in prior["run_records"])
    sig = tuning.derive_signals(merged)
    assert sig["fleet_bottleneck"] == "straggler-bound"
    assert sig["bottleneck"] is None  # host 1's group never reconstructs


def test_resolve_prior_warehouse_read(tmp_path):
    """The index-backed prior: latest row + group drift for a key — the
    warm-start read ROADMAP item 2's service bills from."""
    idx = history.ingest([str(FIXTURES / "history_ledger.jsonl")],
                         str(tmp_path))
    assert len(idx["runs"]) == 6
    key = "wordcount/pallas/b28-c4194304/default/off/split"
    p = history.resolve_prior(index_dir=str(tmp_path), config_key=key)
    assert p["history"]["rows"] == 4
    assert p["history"]["latest"]["run_id"] == "h4"
    assert p["history"]["drift"]["verdict"] == "regressing"
    # An unknown key is an honest empty prior, not an error.
    p = history.resolve_prior(index_dir=str(tmp_path), config_key="no/such"
                              "/key/default/off/split")
    assert p["history"]["rows"] == 0 and p["history"]["latest"] is None


# -- the v8 progress heartbeat on a real CPU streamed run ---------------------

@pytest.fixture(scope="module")
def streamed_ledger(tmp_path_factory):
    """One real telemetered CPU streamed run with the heartbeat cadence
    at 0 (every opportunity), plus a SECOND run appended to the same
    ledger file — the append-mode shape bench.py's BENCH_LEDGER
    produces.  Shared by the heartbeat/obswatch/warehouse tests below
    (one compile, many asserts)."""
    from mapreduce_tpu import obs
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.runtime import executor

    d = tmp_path_factory.mktemp("heartbeat")
    path = d / "in.txt"
    path.write_text("the quick brown fox jumps over the lazy dog " * 1800)
    led = str(d / "run.jsonl")
    # Small chunk + table keep the one-off XLA compile cheap (this setup
    # was the fast tier's single slowest item at production shapes); the
    # heartbeat/obswatch/warehouse asserts below only read record shapes.
    cfg = Config(chunk_bytes=4096, backend="xla", superstep=2,
                 table_capacity=1 << 12)
    run_ids = []
    for _ in range(2):
        tel = obs.Telemetry.create(ledger_path=led, progress_every_s=0.0)
        try:
            executor.run_job(WordCountJob(cfg), str(path), config=cfg,
                             telemetry=tel)
        finally:
            tel.close()
        run_ids.append(tel.run_id)
    return {"ledger": led, "run_ids": run_ids,
            "corpus_bytes": os.path.getsize(path)}


def test_progress_records_on_real_run(streamed_ledger):
    """The ledger-v8 contract: flushed `progress` records with cursor/
    total/fraction/rate, monotone within a run, total == the corpus
    size, and the run accounted to 100%."""
    from mapreduce_tpu import obs

    recs = list(obs.read_ledger(streamed_ledger["ledger"]))
    assert recs[0]["ledger_version"] == obs.LEDGER_VERSION == 10
    rid = streamed_ledger["run_ids"][0]
    prog = [r for r in recs
            if r["kind"] == "progress" and r["run_id"] == rid]
    assert prog, "heartbeats must land at cadence 0"
    cursors = [p["cursor_bytes"] for p in prog]
    assert cursors == sorted(cursors)
    assert all(p["total_bytes"] == streamed_ledger["corpus_bytes"]
               for p in prog)
    assert prog[-1]["frac"] == 1.0
    assert prog[-1]["groups_retired"] >= 1
    assert {"step", "streamed_bytes", "elapsed_s",
            "inflight_depth"} <= set(prog[-1])
    # The heartbeat never displaced the per-step/group records.
    steps = [r for r in recs
             if r["kind"] == "step" and r["run_id"] == rid]
    assert steps and steps[-1]["cursor_bytes"] == cursors[-1]


def test_progress_cadence_and_overhead(tmp_path):
    """The wall-clock gate holds (a large cadence emits exactly the
    first record) and one due emission stays under the 1 ms host bound —
    the PR-7/8 overhead-bound extension the acceptance criteria name."""
    from mapreduce_tpu import obs

    led = str(tmp_path / "hb.jsonl")
    tel = obs.Telemetry.create(ledger_path=led, progress_every_s=3600.0)
    try:
        wrote = [tel.progress(step=i, cursor_bytes=i * 10,
                              streamed_bytes=i * 10, total_bytes=1000)
                 for i in range(100)]
        assert wrote[0] is True and not any(wrote[1:]), \
            "only the first call inside the cadence window may write"
        # The not-due path: one monotonic read + compare.
        t0 = time.perf_counter()
        for i in range(1000):
            tel.progress(step=i, cursor_bytes=i, streamed_bytes=i)
        not_due = (time.perf_counter() - t0) / 1000
        assert not_due < 1e-3, f"not-due heartbeat cost {not_due:.6f}s"
        # The due path (force): a full record build + flushed append.
        t0 = time.perf_counter()
        n = 50
        for i in range(n):
            assert tel.progress(step=i, cursor_bytes=i * 100,
                                streamed_bytes=i * 100, total_bytes=10000,
                                groups_dispatched=i, groups_retired=i,
                                inflight_depth=2, force=True)
        per = (time.perf_counter() - t0) / n
        assert per < 1e-3, f"due heartbeat emission cost {per:.6f}s"
    finally:
        tel.close()
    # A ledgerless handle has nothing to tail: no write, no error.
    bare = obs.Telemetry(enabled=True, progress_every_s=0.0)
    assert bare.progress(step=0, cursor_bytes=0, streamed_bytes=0) is False
    assert obs.Telemetry.disabled().progress(
        step=0, cursor_bytes=0, streamed_bytes=0) is False


def test_obswatch_tails_growing_real_ledger(streamed_ledger, tmp_path):
    """The acceptance walk: obswatch renders a live IN-FLIGHT run AND
    the finished ledger.  A writer thread replays the real run's records
    into a growing file at the executor's flush granularity while the
    main thread tails it — every snapshot must parse, the cursor must be
    monotone, in-flight states must be observed mid-stream, and the
    final snapshot must read completed with the run's own facts."""
    rid = streamed_ledger["run_ids"][0]
    lines = [ln for ln in open(streamed_ledger["ledger"], encoding="utf-8")
             if json.loads(ln).get("run_id") == rid]
    live = str(tmp_path / "live.jsonl")
    stop_at = len(lines)
    written = threading.Event()
    done = threading.Event()

    def writer():
        with open(live, "w", encoding="utf-8") as f:
            for i, ln in enumerate(lines):
                f.write(ln)
                f.flush()
                written.set()
                time.sleep(0.003)
        done.set()

    t = threading.Thread(target=writer)
    t.start()
    written.wait(5.0)
    statuses, cursors = [], []
    while not done.is_set() or len(statuses) < 1:
        s = obswatch.snapshot(live)
        if s is not None:
            statuses.append(s["status"])
            if s.get("cursor_bytes") is not None:
                cursors.append(s["cursor_bytes"])
        time.sleep(0.002)
    t.join(10.0)
    assert stop_at == len(lines) and cursors, cursors
    assert cursors == sorted(cursors), "tailer cursor must be monotone"
    assert "in-flight" in statuses, statuses
    final = obswatch.snapshot(live)
    assert final["status"] == "completed" and final["frac"] == 1.0
    assert final["run_id"] == rid
    assert final["bound"] is not None
    # The finished REAL ledger renders through the same path (both runs
    # enumerable via obs_report --list-runs, the satellite surface).
    full = obswatch.snapshot(streamed_ledger["ledger"])
    assert full["status"] == "completed"
    rows = obs_report.list_runs(streamed_ledger["ledger"])
    assert [r["run_id"] for r in rows] == streamed_ledger["run_ids"]
    assert all(r["status"] == "completed" for r in rows)


def test_warehouse_ingests_append_mode_bench_ledger(streamed_ledger,
                                                    tmp_path):
    """The bench BUGFIX shape: one append-mode file, many timed passes —
    ingest registers EVERY run under one shared config key (same family/
    backend/corpus/config), which is exactly what the drift series needs."""
    idx = history.ingest([streamed_ledger["ledger"]], str(tmp_path / "h"))
    rows = sorted(idx["runs"].values(), key=history._row_order)
    assert [r["run_id"] for r in rows] == streamed_ledger["run_ids"]
    keys = {r["key"] for r in rows}
    assert len(keys) == 1, f"same-config passes must share a key: {keys}"
    assert all(r["completed"] for r in rows)
    v = history.classify_drift(rows)
    assert v["verdict"] in ("steady", "regressing", "improving"), v
    assert v["signals"]["runs"] == 2
