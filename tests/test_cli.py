"""CLI parity tests: stdout contract of main.cu:166-218 (SURVEY §7 'Exact CLI parity')."""

import json
import pytest
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

EXPECTED_REFERENCE_STDOUT = (
    "Input Data:\n"
    "Hello World EveryOne\n"
    "World Good News\n"
    "Good Morning Hello\n"
    "--------------------------\n"
    "Hello\t2\n"
    "World\t2\n"
    "EveryOne\t1\n"
    "Good\t2\n"
    "News\t1\n"
    "Morning\t1\n"
    "--------------------------\n"
    "Total Count:9\n"
)


def _run(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, str(REPO / "main"), *args],
        capture_output=True, text=True, cwd=cwd,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu", "HOME": "/root"},
    )


@pytest.mark.smoke
def test_reference_stdout_parity(tmp_path):
    fixture = tmp_path / "test.txt"
    fixture.write_text("Hello World EveryOne\nWorld Good News\nGood Morning Hello\n")
    r = _run([str(fixture)])
    assert r.returncode == 0, r.stderr
    assert r.stdout == EXPECTED_REFERENCE_STDOUT


def test_default_filename_is_test_txt(tmp_path):
    """argv-less run reads ./test.txt, matching the hardcoded name (main.cu:167)."""
    (tmp_path / "test.txt").write_text("a b a\n")
    r = _run([], cwd=tmp_path)
    assert r.returncode == 0, r.stderr
    assert "a\t2" in r.stdout and "Total Count:3" in r.stdout


@pytest.mark.smoke
def test_missing_file_is_an_error(tmp_path):
    """The reference silently prints an empty result on fopen failure
    (main.cu:174); we surface the failure (SURVEY §5 failure detection)."""
    r = _run([str(tmp_path / "nope.txt")])
    assert r.returncode == 2
    assert "cannot read" in r.stderr


@pytest.mark.smoke
def test_json_format(tmp_path):
    f = tmp_path / "in.txt"
    f.write_text("x y x z\n")
    r = _run([str(f), "--format", "json"])
    assert r.returncode == 0, r.stderr
    obj = json.loads(r.stdout)
    assert obj["counts"] == [["x", 2], ["y", 1], ["z", 1]]
    assert obj["total"] == 4 and obj["distinct"] == 3


def test_json_distinct_bytes_stay_distinct(tmp_path):
    """Two invalid-UTF8 byte words must not collapse into one JSON entry."""
    f = tmp_path / "in.bin"
    f.write_bytes(b"\xff \xfe\n")
    r = _run([str(f), "--format", "json"])
    assert r.returncode == 0, r.stderr
    obj = json.loads(r.stdout)
    assert len(obj["counts"]) == 2 and obj["distinct"] == 2


def test_bad_chunk_bytes_is_clean_error(tmp_path):
    f = tmp_path / "in.txt"
    f.write_text("a\n")
    r = _run([str(f), "--chunk-bytes", "1000"])
    assert r.returncode == 2
    assert "chunk_bytes" in r.stderr and "Traceback" not in r.stderr


@pytest.mark.smoke
def test_top_k(tmp_path):
    f = tmp_path / "in.txt"
    f.write_text("a a a b b c\n")
    r = _run([str(f), "--top-k", "2", "--format", "tsv"])
    assert r.returncode == 0, r.stderr
    assert r.stdout == "a\t3\nb\t2\n"


@pytest.mark.slow
def test_max_token_bytes_flag_on_pallas_backend(tmp_path):
    """--max-token-bytes reaches the pallas config: a token longer than W is
    rescued exactly by default (ops/rescue.py), and dropped into the
    accounting with --rescue-overlong 0 (the round-3 contract)."""
    f = tmp_path / "in.txt"
    f.write_text("short " + "L" * 40 + " short\n")
    base = [str(f), "--format", "json", "--backend", "pallas",
            "--chunk-bytes", str(128 * 18), "--max-token-bytes", "8"]
    r = _run(base)
    assert r.returncode == 0, r.stderr
    obj = json.loads(r.stdout)
    assert obj["counts"] == [["short", 2], ["L" * 40, 1]]
    assert obj["total"] == 3 and obj["dropped_count"] == 0

    r = _run(base + ["--rescue-overlong", "0"])
    assert r.returncode == 0, r.stderr
    obj = json.loads(r.stdout)
    assert obj["counts"] == [["short", 2]]
    assert obj["total"] == 3 and obj["dropped_count"] == 1


def test_multiple_input_files(tmp_path):
    a, b = tmp_path / "a.txt", tmp_path / "b.txt"
    a.write_text("x y x\n")
    b.write_text("y z\n")
    for extra in ([], ["--stream", "--chunk-bytes", "1024",
                       "--table-capacity", "2048"]):
        r = _run([str(a), str(b), "--format", "json", "--no-echo"] + extra)
        assert r.returncode == 0, r.stderr
        obj = json.loads(r.stdout)
        assert dict(map(tuple, obj["counts"])) == {"x": 2, "y": 2, "z": 1}
        assert obj["total"] == 5


def test_distinct_sketch_requires_stream(tmp_path):
    """Honest failure beats a flag silently ignored: the non-stream path
    never consults the sketch."""
    f = tmp_path / "in.txt"
    f.write_text("a b\n")
    r = _run([str(f), "--distinct-sketch"])
    assert r.returncode == 2
    assert "--distinct-sketch requires --stream" in r.stderr


@pytest.mark.slow
def test_multi_file_grep_no_cross_file_seam_match(tmp_path):
    """A newline-bearing pattern must not match across the artificial seam
    between joined input files (only NUL is rejected in patterns)."""
    a, b = tmp_path / "a.txt", tmp_path / "b.txt"
    a.write_text("x b")  # no trailing newline: the old join fabricated "b\na"
    b.write_text("a y\n")
    r = _run([str(a), str(b), "--grep", "b\na", "--format", "json"])
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["matches"] == 0
    # Control: the same pattern in ONE file does match.
    c = tmp_path / "c.txt"
    c.write_text("x b\na y\n")
    r = _run([str(c), "--grep", "b\na", "--format", "json"])
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["matches"] == 1


def test_cli_fails_fast_when_device_unreachable(tmp_path):
    """Under an unreachable device platform the CLI must exit nonzero within
    the MAPREDUCE_WATCHDOG_S deadline with a clear message, not hang
    (VERDICT round 1: the reference at least runs unattended)."""
    f = tmp_path / "in.txt"
    f.write_text("a b\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "main"), str(f)],
        capture_output=True, text=True, timeout=60,
        env={"PATH": "/usr/bin:/bin", "HOME": "/root",
             "JAX_PLATFORMS": "bogus_platform", "MAPREDUCE_WATCHDOG_S": "3"},
    )
    assert r.returncode == 3
    assert "device unreachable" in r.stderr


def test_cpu_escape_hatch_overrides_pinned_platform_config(tmp_path):
    """JAX_PLATFORMS=cpu must reach the host CPU even when something pinned
    jax.config.jax_platforms to a remote platform at interpreter startup
    (VERDICT round 2: the recommended escape hatch hung forever because the
    watchdog gate read only the env var while the run dialed the pinned
    config).  Simulates the sitecustomize pin, then runs the full CLI."""
    fixture = tmp_path / "test.txt"
    fixture.write_text("Hello World EveryOne\nWorld Good News\nGood Morning Hello\n")
    code = (
        "import jax\n"
        # Simulated sitecustomize: pins a platform that does not exist, so
        # any device use that honors the pin fails loudly (and without the
        # fix, a REAL pin would hang on the wedged relay instead).
        "jax.config.update('jax_platforms', 'nosuchplatform,cpu')\n"
        "from mapreduce_tpu.cli import main\n"
        f"raise SystemExit(main([{str(fixture)!r}]))\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "HOME": "/root",
             "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO)},
    )
    assert r.returncode == 0, r.stderr
    assert "Total Count:9" in r.stdout


def test_platform_flag_forces_cpu_under_pinned_config(tmp_path):
    """--platform cpu is the flag form of the same escape hatch."""
    fixture = tmp_path / "test.txt"
    fixture.write_text("a b a\n")
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'nosuchplatform,cpu')\n"
        "from mapreduce_tpu.cli import main\n"
        f"raise SystemExit(main(['--platform', 'cpu', {str(fixture)!r}]))\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "HOME": "/root",
             "PYTHONPATH": str(REPO)},  # no JAX_PLATFORMS at all
    )
    assert r.returncode == 0, r.stderr
    assert "Total Count:3" in r.stdout


def test_watchdog_gate_reads_config_not_env(monkeypatch):
    """The probe gate keys off the EFFECTIVE platform (jax.config), not the
    raw env var: here the env var claims an accelerator but the config (what
    JAX will actually dial) says cpu, so no probe must run."""
    from mapreduce_tpu import cli

    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    # conftest forced jax.config.jax_platforms to "cpu" for the whole suite;
    # _apply_platform must report that config value, not the env var.
    assert cli._apply_platform("auto") == "cpu"


def test_sample_zero_is_an_error(tmp_path):
    """--sample 0 must error, not silently fall through to word-count mode
    (advisor round 2: the old 0-default made an explicit 0 indistinguishable
    from the flag being absent)."""
    f = tmp_path / "in.txt"
    f.write_text("a b a\n")
    r = _run([str(f), "--sample", "0"])
    assert r.returncode == 2
    assert "--sample must be >= 1" in r.stderr
    # And a valid sample still works.
    r2 = _run([str(f), "--sample", "2", "--format", "json"])
    assert r2.returncode == 0, r2.stderr
    assert len(json.loads(r2.stdout)["sample"]) == 2


@pytest.mark.slow
def test_merge_every_flag_validation(tmp_path):
    """--merge-every must error where it would be a silent no-op: without
    --stream, with --grep/--sample, and with --ngram (pairwise combine)."""
    f = tmp_path / "in.txt"
    f.write_text("a b a\n")
    for args, msg in (
        ([str(f), "--merge-every", "4"], "requires --stream"),
        ([str(f), "--stream", "--merge-every", "4", "--grep", "a"],
         "not supported"),
        ([str(f), "--stream", "--merge-every", "4", "--sample", "1"],
         "not supported"),
        ([str(f), "--stream", "--merge-every", "4", "--ngram", "2"],
         "word-count runs only"),
    ):
        r = _run(args)
        assert r.returncode == 2, args
        assert msg in r.stderr, args
    # And the valid form still runs.
    r = _run([str(f), "--stream", "--merge-every", "2", "--format", "json"])
    assert r.returncode == 0, r.stderr
    assert '"total": 3' in r.stdout


@pytest.mark.smoke
def test_inflight_and_prefetch_depth_flags_validate(tmp_path):
    """ISSUE 5: the window knobs validate at the parser (clean exit 2
    before any device work, not a mid-run traceback).  The streamed
    pipelined-vs-serial identity itself is covered in test_executor.py —
    no subprocess compile paid here."""
    f = tmp_path / "in.txt"
    f.write_text("a b a c\n")
    for args in ([str(f), "--inflight", "0"],
                 [str(f), "--prefetch-depth", "0"]):
        r = _run(args)
        assert r.returncode == 2, args
        assert "must be >= 1" in r.stderr, args


def test_batch_ledger_without_stream(tmp_path, capsys):
    """ISSUE 8 satellite: --ledger/--metrics-out no longer require
    --stream.  A batch (single-buffer) run emits run_start + a
    result-derived data record + run_end, and the registry snapshot
    lands; the ledger classifies through obs_report's data-health path.
    In-process (no subprocess jax startup): the tier-1 budget rule."""
    import sys as _sys

    from mapreduce_tpu import cli

    _sys.path.insert(0, str(REPO / "tools"))
    try:
        import obs_report
    finally:
        _sys.path.pop(0)
    f = tmp_path / "in.txt"
    f.write_text("aa bb aa cc aa\n")
    led = tmp_path / "run.jsonl"
    met = tmp_path / "metrics.json"
    assert cli.main([str(f), "--no-echo", "--format", "json",
                     "--ledger", str(led), "--metrics-out",
                     str(met)]) == 0
    capsys.readouterr()
    recs = obs_report.read_ledger(str(led))
    assert [x["kind"] for x in recs] == ["run_start", "data", "run_end"]
    start, data, end = recs
    assert start["driver"] == "single_buffer" and start["job"] == "wordcount"
    assert start["ledger_version"] == 10
    assert data["tokens"] == 5 and data["table_valid"] == 3
    assert data["top_count"] == 3 and data["dropped_tokens"] == 0
    assert end["words"] == 5 and end["elapsed_s"] > 0
    assert json.loads(met.read_text())  # registry snapshot written
    runs = obs_report.analyze(str(led))
    assert len(runs) == 1 and runs[0]["completed"]
    # top mass 3/5: the report's data-health section classifies it.
    assert runs[0]["data_health"]["verdict"] == "skew-hot"
    # Batch grep runs bracket the ledger too (run_start/run_end; no data
    # record — grep has no table to summarize).
    g = tmp_path / "g.txt"
    g.write_text("abc abc\nxyz\n")
    gled = tmp_path / "grep.jsonl"
    assert cli.main([str(g), "--grep", "abc", "--ledger", str(gled)]) == 0
    capsys.readouterr()
    grecs = obs_report.read_ledger(str(gled))
    assert [x["kind"] for x in grecs] == ["run_start", "run_end"]
    assert grecs[0]["job"] == "grep" and grecs[1]["words"] == 2


def test_env_fault_plan_skipped_off_stream(tmp_path, capsys, monkeypatch):
    """Exporting MAPREDUCE_FAULT_PLAN to chaos-test a streamed service
    must not hard-error unrelated batch-mode invocations: the env
    default binds only to --stream runs; off-stream it warns and runs
    clean.  An EXPLICIT --fault-plan without --stream still errors."""
    from mapreduce_tpu import cli

    f = tmp_path / "in.txt"
    f.write_bytes(b"alpha beta alpha\n")
    monkeypatch.setenv("MAPREDUCE_FAULT_PLAN", "seed=1,rate=0.5")
    rc = cli.main([str(f), "--format", "json", "--no-echo"])
    out = capsys.readouterr()
    assert rc == 0, out.err
    assert "fault injection skipped" in out.err
    with pytest.raises(SystemExit) as ei:
        cli.main([str(f), "--fault-plan", "seed=1", "--format", "json",
                  "--no-echo"])
    assert ei.value.code == 2
    capsys.readouterr()


def test_preemption_exits_resumable_75(tmp_path, capsys):
    """ISSUE 15: a preemption-classed fault is an ORDERLY shutdown on the
    CLI surface — drain, checkpoint, one-line `preempted:` stderr, exit
    75 (EX_TEMPFAIL: relaunch the same command to resume) — never a
    traceback.  The relaunch-resumes-exactly half lives at the executor
    level (test_faults.test_preemption_drains_checkpoints_and_resumes);
    paying a second streamed run here would only re-prove it.
    In-process (no subprocess jax startup): the tier-1 budget rule."""
    from mapreduce_tpu import cli
    from mapreduce_tpu.runtime import checkpoint as ckpt_mod

    corpus = b"alpha beta alpha gamma beta alpha delta\n" * 300
    f = tmp_path / "in.txt"
    f.write_bytes(corpus)
    ck = tmp_path / "ck.npz"
    rc = cli.main([str(f), "--stream", "--chunk-bytes", "512",
                   "--retry", "1", "--checkpoint", str(ck),
                   "--checkpoint-every", "2", "--format", "json",
                   "--no-echo", "--fault-plan",
                   "at=dispatch:1:preemption"])
    out = capsys.readouterr()
    assert rc == 75, (rc, out.err)
    assert "preempted:" in out.err and "Traceback" not in out.err
    assert ck.exists(), "the drain must leave a resumable snapshot"
    assert ckpt_mod.verify(str(ck)) is True, \
        "the preemption snapshot must carry a passing integrity sidecar"
