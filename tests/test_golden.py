"""Golden-semantics tests against the reference fixture (SURVEY §2, §4).

Expected result for test.txt: Hello 2, World 2, EveryOne 1, Good 2, News 1,
Morning 1; Total Count 9 — in first-occurrence order, matching the reference
report loop (main.cu:212-218).
"""

import pytest

from mapreduce_tpu.config import SMALL_CONFIG
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.utils import oracle

# The whole golden module rides in the fast iteration tier (tools/smoke.sh).
pytestmark = pytest.mark.smoke

GOLDEN = [(b"Hello", 2), (b"World", 2), (b"EveryOne", 1), (b"Good", 2), (b"News", 1), (b"Morning", 1)]


def test_fixture_counts(fixture_text):
    r = wordcount.count_words(fixture_text, SMALL_CONFIG)
    assert list(zip(r.words, r.counts)) == GOLDEN
    assert r.total == 9
    assert r.dropped_uniques == 0 and r.dropped_count == 0


def test_fixture_matches_oracle(fixture_text):
    r = wordcount.count_words(fixture_text, SMALL_CONFIG)
    assert r.as_dict() == oracle.word_counts(fixture_text)
    assert r.total == oracle.total_count(fixture_text)


def test_empty_input():
    r = wordcount.count_words(b"", SMALL_CONFIG)
    assert r.words == [] and r.total == 0


def test_only_separators():
    r = wordcount.count_words(b"  \n\t \r\n  ", SMALL_CONFIG)
    assert r.words == [] and r.total == 0


def test_single_word_no_newline():
    r = wordcount.count_words(b"hello", SMALL_CONFIG)
    assert list(zip(r.words, r.counts)) == [(b"hello", 1)]
    assert r.total == 1


def test_reference_defects_fixed(fixture_text):
    """Defects from SURVEY §2 must be FIXED, not replicated."""
    # Defect 2: prefix comparator — "Good" must not merge into "Goodness".
    r = wordcount.count_words(b"Goodness Good Goodness Good Good", SMALL_CONFIG)
    assert r.as_dict() == {b"Goodness": 2, b"Good": 3}
    # Defect 5: a line shorter than 2 chars must NOT terminate ingestion.
    r = wordcount.count_words(b"alpha beta\nx\ngamma delta\n", SMALL_CONFIG)
    assert r.as_dict() == {b"alpha": 1, b"beta": 1, b"x": 1, b"gamma": 1, b"delta": 1}
    # Defect 4/5: >10 lines, >10 distinct words, words >=20 chars, >20 words
    # per line, lines >=100 chars all work.
    long_word = b"a" * 64
    lines = [b" ".join(b"w%d" % (i * 30 + j) for j in range(30)) for i in range(20)]
    data = b"\n".join(lines) + b"\n" + long_word + b"\n"
    r = wordcount.count_words(data, SMALL_CONFIG)
    assert r.total == 20 * 30 + 1
    assert r.as_dict()[long_word] == 1
    assert len(r.words) == 601


def test_tabs_are_separators():
    r = wordcount.count_words(b"a\tb\tc a", SMALL_CONFIG)
    assert r.as_dict() == {b"a": 2, b"b": 1, b"c": 1}
