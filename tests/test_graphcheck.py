"""graphcheck (mapreduce_tpu.analysis): the static analyzer's contract.

Each of the four passes is demonstrated by a known-bad fixture job that
must produce an error-severity finding (non-commutative merge, un-paired
32-bit counter, callback-in-jit, collective over a mismatched axis), and a
clean run over every built-in model must produce ZERO error findings —
the acceptance criteria of the graphcheck issue, wired into tier-1.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_tpu import analysis
from mapreduce_tpu import models as models_mod
from mapreduce_tpu.analysis import core as acore
from mapreduce_tpu.analysis.passes.algebra import AlgebraPass
from mapreduce_tpu.analysis.passes.hostsync import HostSyncPass
from mapreduce_tpu.analysis.passes.overflow import OverflowPass
from mapreduce_tpu.analysis.passes.sharding import ShardingPass
from mapreduce_tpu.parallel.mesh import data_mesh


@pytest.fixture(scope="module")
def mesh8():
    return data_mesh(8)


# -- known-bad fixture jobs (duck-typed MapReduceJobs) ----------------------


class _ScalarJob:
    """Minimal correct job: count non-pad bytes into one uint32 scalar.

    Deliberately NOT named like a counter (state is a bare leaf), so the
    overflow lint stays quiet and each fixture below isolates one pass.
    """

    def init_state(self):
        return jnp.zeros((), jnp.uint32)

    def map_chunk(self, chunk, chunk_id):
        return jnp.sum((chunk != 0).astype(jnp.uint32))

    def combine(self, state, update):
        return state + update

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return state

    def identity(self):
        return type(self).__name__.lower()


class NonCommutativeMergeJob(_ScalarJob):
    """merge = a - b: the reducer-algebra property check must refuse it."""

    def merge(self, a, b):
        return a - b


class Int32CounterState(NamedTuple):
    count: jax.Array  # uint32 scalar, deliberately NOT lane-paired


class Int32CounterJob(_ScalarJob):
    """A corpus-scale counter in one un-paired uint32: the overflow lint
    must flag it against a >2**32-token corpus bound."""

    def init_state(self):
        return Int32CounterState(count=jnp.zeros((), jnp.uint32))

    def map_chunk(self, chunk, chunk_id):
        return Int32CounterState(
            count=jnp.sum((chunk != 0).astype(jnp.uint32)))

    def combine(self, state, update):
        return Int32CounterState(count=state.count + update.count)

    def merge(self, a, b):
        return Int32CounterState(count=a.count + b.count)


class CallbackJob(_ScalarJob):
    """A host callback inside the jitted map: the host-sync pass must
    flag the per-dispatch device->host round trip."""

    def map_chunk(self, chunk, chunk_id):
        total = jnp.sum((chunk != 0).astype(jnp.uint32))
        return jax.pure_callback(
            lambda x: np.asarray(x, dtype=np.uint32),
            jax.ShapeDtypeStruct((), np.uint32), total)


class BadAxisJob(_ScalarJob):
    """Reduces over a hardcoded axis name the mesh does not carry (the
    mismatched-PartitionSpec case): the sharding lint must flag it."""

    def map_chunk_sharded(self, chunk, chunk_id, axis, device_index):
        return jax.lax.psum(self.map_chunk(chunk, chunk_id), "replica")


def _errors(report, pass_id):
    return [f for f in report.errors if f.pass_id == pass_id]


# -- one failing fixture per pass -------------------------------------------


def test_algebra_pass_flags_noncommutative_merge(mesh8):
    report = analysis.analyze_job(NonCommutativeMergeJob(), "bad-merge",
                                  mesh=mesh8, passes=[AlgebraPass()])
    errs = _errors(report, "reducer-algebra")
    assert errs, report.format_text()
    assert any("commutative" in f.message for f in errs)
    assert report.exit_code != 0


def test_algebra_pass_accepts_additive_merge(mesh8):
    report = analysis.analyze_job(_ScalarJob(), "ok-merge", mesh=mesh8,
                                  passes=[AlgebraPass()])
    assert not report.errors, report.format_text()


def test_overflow_pass_flags_unpaired_uint32_counter(mesh8):
    report = analysis.analyze_job(Int32CounterJob(), "bad-counter",
                                  mesh=mesh8, passes=[OverflowPass()],
                                  corpus_bytes=1 << 40)  # ~2**39 tokens
    errs = _errors(report, "overflow-dtype")
    assert errs, report.format_text()
    assert any("count" in f.location for f in errs)
    assert report.exit_code != 0


def test_overflow_pass_quiet_within_dtype_range(mesh8):
    # A 1 GB corpus bound fits uint32 with room: no error, no warning.
    report = analysis.analyze_job(Int32CounterJob(), "small-counter",
                                  mesh=mesh8, passes=[OverflowPass()],
                                  corpus_bytes=1 << 30)
    assert not report.errors, report.format_text()
    assert not report.by_severity(acore.WARNING), report.format_text()


def test_overflow_pass_accepts_lane_paired_counters(mesh8):
    job = models_mod.build_model("wordcount")
    report = analysis.analyze_job(job, "wordcount", mesh=mesh8,
                                  passes=[OverflowPass()],
                                  corpus_bytes=1 << 50)  # 1 PiB
    assert not report.errors, report.format_text()


def test_hostsync_pass_flags_callback_in_jit(mesh8):
    report = analysis.analyze_job(CallbackJob(), "bad-callback",
                                  mesh=mesh8, passes=[HostSyncPass()])
    errs = _errors(report, "host-sync")
    assert errs, report.format_text()
    assert any("callback" in f.message for f in errs)
    assert report.exit_code != 0


def test_sharding_pass_flags_mismatched_axis(mesh8):
    report = analysis.analyze_job(BadAxisJob(), "bad-axis", mesh=mesh8,
                                  passes=[ShardingPass()])
    errs = _errors(report, "sharding-lint")
    assert errs, report.format_text()
    assert report.exit_code != 0


def test_sharding_pass_accepts_engine_collectives(mesh8):
    job = models_mod.build_model("grep")
    report = analysis.analyze_job(job, "grep", mesh=mesh8,
                                  passes=[ShardingPass()])
    assert not report.errors, report.format_text()


# -- clean run over every built-in model (the CI gate) ----------------------


@pytest.mark.slow
def test_all_builtin_models_are_clean(mesh8):
    """Zero error-severity findings over the whole shipped model zoo.

    @slow (the PR-3 ">= ~10 s carries @slow" rebalance, applied when the
    ISSUE 8 telemetry twins pushed this sweep past 60 s): tier-1 still
    runs this EXACT gate — ``tools/tier1.sh`` executes ``python -m
    mapreduce_tpu.analysis --all-models --min-severity error`` before
    pytest, under its own 240 s budget — so the fast tier keeps the
    clean-zoo guarantee without paying for it twice; the full suite runs
    this in-pytest copy for bare-pytest users.  Per-model/per-pass
    coverage stays fast-tier via the dedicated ctx tests in
    test_costcheck.py and the known-bad fixtures here."""
    full = analysis.Report()
    for name in models_mod.model_names():
        job = models_mod.build_model(name)
        one = analysis.analyze_job(job, model=name, mesh=mesh8)
        full.models.extend(one.models)
        full.extend(one.findings)
    assert full.models == models_mod.model_names()
    assert not full.errors, full.format_text()
    assert full.exit_code == 0


def test_cli_exits_zero_on_shipped_models(capsys):
    from mapreduce_tpu.analysis.cli import main

    rc = main(["wordcount", "--min-severity", "error"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "graphcheck" in out


def test_cli_list(capsys):
    from mapreduce_tpu.analysis.cli import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "wordcount" in out and "reducer-algebra" in out


def test_cli_json_shape(capsys):
    import json

    from mapreduce_tpu.analysis.cli import main

    rc = main(["grep", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == payload["exit_code"] == 0
    # The CLI certifies shipped kernel geometries once per run, appended
    # as the pseudo-model <kernels>.
    assert payload["models"] == ["grep", "<kernels>"]
    assert "artifacts" in payload
    for f in payload["findings"]:
        assert {"severity", "pass_id", "model", "hook", "message",
                "location", "hint"} <= set(f)


# -- the pluggable registry -------------------------------------------------


def test_custom_pass_registration(mesh8):
    calls = []

    class ProbePass:
        pass_id = "probe"
        description = "test-only"

        def run(self, ctx):
            calls.append(ctx.model)
            return [acore.Finding(severity=acore.INFO, pass_id="probe",
                                  model=ctx.model, hook="merge",
                                  message="probe ran")]

    report = analysis.analyze_job(_ScalarJob(), "probed", mesh=mesh8,
                                  passes=[ProbePass()])
    assert calls == ["probed"]
    assert [f.pass_id for f in report.findings] == ["probe"]
    assert report.exit_code == 0


def test_report_ordering_and_severity_gate():
    r = analysis.Report(models=["m"])
    r.extend([
        acore.Finding(severity=acore.INFO, pass_id="p", model="m",
                      hook="h", message="i"),
        acore.Finding(severity=acore.ERROR, pass_id="p", model="m",
                      hook="h", message="e"),
        acore.Finding(severity=acore.WARNING, pass_id="p", model="m",
                      hook="h", message="w"),
    ])
    assert [f.severity for f in r.sorted_findings()] == \
        ["error", "warning", "info"]
    assert r.exit_code == 1
