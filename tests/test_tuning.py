"""Closed-loop autotuner tests (ISSUE 10): the jax-free rule engine on
synthetic ledgers (convergence to hand-computed targets, the oscillation
guard, Config validation of every proposal), CLI ``--autotune``
validation, and the end-to-end CPU hint run + tuned-vs-default
byte-identity."""

import json
import os
import sys

import pytest

from mapreduce_tpu import obs
from mapreduce_tpu.config import Config
from mapreduce_tpu.tuning import engine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tools", "fixtures")


def _fixture(name: str) -> list:
    with open(os.path.join(FIXTURES, name + ".jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


def _knobs(**kw) -> dict:
    base = {"chunk_bytes": 1 << 25, "superstep": 1,
            "inflight_groups": 4, "prefetch_depth": 4, "combiner": "off",
            "geometry": "default", "merge_strategy": "tree",
            "merge_overlap": "off"}
    base.update(kw)
    return base


# -- the rule table on synthetic ledgers (jax-free) --------------------------

@pytest.mark.smoke
def test_reader_bound_converges_to_higher_prefetch():
    """ISSUE 10 acceptance: the reader-bound fixture walks prefetch_depth
    4 -> 8 -> 16 and converges at the hand-computed target, nothing else
    moved."""
    reader, conv = _fixture("tuner_reader_bound"), _fixture("tuner_converged")

    r = engine.search(
        lambda k: reader if k["prefetch_depth"] < 16 else conv,
        _knobs(), budget=6)
    assert r["stopped"] == "converged"
    assert r["winner"] == _knobs(prefetch_depth=16), r["winner"]
    assert [p["rule"] for p in r["trail"]] == \
        ["raise-prefetch", "raise-prefetch", "converged"]


@pytest.mark.smoke
def test_device_bound_stops_raising_inflight():
    """Device-bound + window-always-full: superstep doubles, and
    inflight_groups is provably never raised — the rule that keeps the
    tuner from deepening a window the device already saturates."""
    device, conv = _fixture("tuner_device_bound"), \
        _fixture("tuner_converged")

    r = engine.search(lambda k: device if k["superstep"] < 4 else conv,
                      _knobs(), budget=6)
    assert r["stopped"] == "converged"
    assert r["winner"]["superstep"] == 4
    assert r["winner"]["inflight_groups"] == 4
    assert not any(p["rule"] == "raise-inflight" for p in r["trail"])


def test_data_rules_move_chunk_bytes():
    occ = engine.propose(_fixture("tuner_occupancy"))
    assert occ["rule"] == "grow-chunk"
    assert occ["changed"] == {"chunk_bytes": [2 << 20, 4 << 20]}
    tbl = engine.propose(_fixture("tuner_tablepressure"))
    assert tbl["rule"] == "shrink-chunk"
    assert tbl["changed"] == {"chunk_bytes": [4 << 20, 2 << 20]}


def test_oscillation_guard_terminates():
    """Two data verdicts pulling chunk_bytes in opposite directions must
    stop the walk the moment a proposed config was already visited —
    never ping-pong to budget — and the tie between two verdict-rejected
    configs breaks on measured run_end throughput."""
    occ, tbl = _fixture("tuner_occupancy"), _fixture("tuner_tablepressure")

    def sim(k):
        return occ if k["chunk_bytes"] <= (2 << 20) else tbl

    r = engine.search(sim, _knobs(chunk_bytes=2 << 20), budget=10)
    assert r["stopped"] == "oscillation"
    assert r["passes"] == 2
    assert r["trail"][-1]["oscillation"] is True
    # The 4 MB pass measured faster (16 MB / 1.6 s vs 8 MB / 1.4 s in the
    # fixtures' run_end records): it wins the tie, and the recorded
    # winner/GB-s pair comes from that same pass.
    assert r["winner"]["chunk_bytes"] == 4 << 20, r["winner"]
    assert r["winner_gbps"] == round(16777216 / 1e9 / 1.6, 6), r
    # Flip the throughputs: slow the table-pressure arm 10x and the
    # 2 MB start must win instead.
    slow_tbl = [dict(rec, elapsed_s=16.0) if rec.get("kind") == "run_end"
                else rec for rec in tbl]
    r2 = engine.search(
        lambda k: occ if k["chunk_bytes"] <= (2 << 20) else slow_tbl,
        _knobs(chunk_bytes=2 << 20), budget=10)
    assert r2["stopped"] == "oscillation"
    assert r2["winner"]["chunk_bytes"] == 2 << 20, r2["winner"]


def test_budget_exhaustion_winner_was_measured():
    """A final proposal the budget left no pass to run must stay in the
    trail, never become the winner: the recorded winner/GB-s pair has to
    describe a config that was actually observed."""
    device = _fixture("tuner_device_bound")
    measured = []

    def measure(k):
        measured.append(dict(k))
        return device  # always proposes superstep x2: never converges

    r = engine.search(measure, _knobs(), budget=3)
    assert r["stopped"] == "budget-exhausted" and r["passes"] == 3
    assert r["winner"] == measured[-1], (r["winner"], measured[-1])
    assert r["winner"]["superstep"] == 4  # 1 -> 2 -> 4 measured; 8 only proposed
    assert r["trail"][-1]["proposal"]["superstep"] == 8
    # The winner's throughput is its own pass's run_end figure.
    assert r["winner_gbps"] == round(6291456 / 1e9 / 3.3, 6), r


def test_every_proposal_passes_config_validation():
    """Acceptance: every emitted config passes the REAL
    Config.__post_init__ rules, per fixture and along every walk."""
    names = ("tuner_reader_bound", "tuner_device_bound", "tuner_converged",
             "tuner_occupancy", "tuner_tablepressure")
    for name in names:
        p = engine.propose(_fixture(name))
        engine.validate_knobs(p["proposal"])
        Config(chunk_bytes=p["proposal"]["chunk_bytes"],
               superstep=p["proposal"]["superstep"],
               inflight_groups=p["proposal"]["inflight_groups"],
               prefetch_depth=p["proposal"]["prefetch_depth"])


def test_phase_fallback_h2d_raises_inflight():
    """A ledger with no group records (batch ledgers, pre-v2 ledgers)
    still tunes: the phase-delta fallback classifies the resource.  An
    h2d_tail-heavy run with a FED window deepens it."""
    recs = [
        {"run_id": "x", "kind": "run_start", "chunk_bytes": 1 << 21,
         "superstep": 1, "backend": "xla"},
        {"run_id": "x", "kind": "run_end",
         "phases": {"read_wait": 0.1, "stage": 0.2, "h2d_tail": 3.0},
         "pipeline": {"inflight_groups": 4, "prefetch_depth": 4,
                      "depth_max": 4, "full_frac": 0.5}},
    ]
    p = engine.propose(recs)
    assert p["rule"] == "raise-inflight"
    assert p["changed"] == {"inflight_groups": [4, 8]}
    assert p["signals"]["resource_source"] == "phases"


def test_phase_fallback_compute_tail_is_device():
    """compute_tail (queued device work at stream end) blames the device
    in the fallback classifier: a compute-dominated ledgerless run must
    get the device rules, not a prefetch raise off its minor read_wait
    share (the exact ledgerless-hint repro from review)."""
    recs = [
        {"run_id": "x", "kind": "run_start", "chunk_bytes": 1 << 21,
         "superstep": 1, "backend": "xla"},
        {"run_id": "x", "kind": "run_end",
         "phases": {"read_wait": 0.3, "stage": 0.1, "dispatch": 0.1,
                    "compute_tail": 8.0},
         "pipeline": {"inflight_groups": 4, "prefetch_depth": 4,
                      "depth_max": 4, "full_frac": 1.0}},
    ]
    p = engine.propose(recs)
    assert p["signals"]["resource"] == "device", p["signals"]
    assert p["rule"] == "try-superstep", p["rule"]


def test_window_never_filled_feeds_prefetch_first():
    """h2d-bound with depth_max below the configured window: deepening a
    window the feed side never fills buys nothing — prefetch moves
    first."""
    recs = [
        {"run_id": "x", "kind": "run_start", "chunk_bytes": 1 << 21,
         "superstep": 1, "backend": "xla"},
        {"run_id": "x", "kind": "run_end",
         "phases": {"read_wait": 0.1, "stage": 0.2, "h2d_tail": 3.0},
         "pipeline": {"inflight_groups": 4, "prefetch_depth": 4,
                      "depth_max": 2, "full_frac": 0.0}},
    ]
    p = engine.propose(recs)
    assert p["rule"] == "feed-window"
    assert p["changed"] == {"prefetch_depth": [4, 8]}


def test_raising_rules_converge_at_their_caps():
    """At each knob's cap the rule converges with an explicit at-cap
    reason instead of proposing a no-op (or sailing past the envelope)."""
    reader = _fixture("tuner_reader_bound")
    p = engine.propose(reader, current=_knobs(prefetch_depth=16))
    assert p["rule"] == "raise-prefetch-at-cap" and p["converged"]
    device = _fixture("tuner_device_bound")
    p2 = engine.propose(device, current=_knobs(superstep=32))
    assert p2["rule"] == "try-superstep-at-cap" and p2["converged"]


def test_no_signal_and_determinism():
    """An empty/recordless run stops honestly; and the engine is a pure
    function — same records in, same proposal out."""
    p = engine.propose([{"run_id": "x", "kind": "run_start"}])
    assert p["rule"] == "no-signal" and p["converged"]
    reader = _fixture("tuner_reader_bound")
    assert engine.propose(reader) == engine.propose(reader)


def test_trail_is_machine_readable():
    p = engine.propose(_fixture("tuner_device_bound"))
    assert p["tuner_version"] == engine.TUNER_VERSION
    assert all(set(t) == {"rule", "fired", "why"} for t in p["trail"])
    assert sum(t["fired"] for t in p["trail"]) == 1
    assert set(p["proposal"]) == set(engine.KNOBS)


def test_config_autotune_validation():
    assert Config(autotune="hint").autotune == "hint"
    assert Config().autotune == "off"
    with pytest.raises(ValueError, match="autotune"):
        Config(autotune="bogus")


# -- CLI validation ----------------------------------------------------------

@pytest.mark.smoke
def test_cli_autotune_requires_stream(tmp_path, capsys):
    from mapreduce_tpu import cli

    f = tmp_path / "in.txt"
    f.write_text("a b a\n")
    with pytest.raises(SystemExit) as exc:
        cli.main([str(f), "--autotune"])
    assert exc.value.code == 2
    assert "--autotune requires --stream" in capsys.readouterr().err


@pytest.mark.slow
def test_cli_autotune_reports_hint_without_ledger(tmp_path, capsys):
    """--autotune without --ledger must still surface the recommendation
    (rule + reason on stderr): the CLI path drops the RunResult, so the
    hint rides the telemetry handle the flag forces into existence.
    @slow (fresh streamed compile, ~30 s); the fast tier covers the
    print path + note_tune wiring in the unit test below and the hint
    fixture asserts note_tune end-to-end."""
    from mapreduce_tpu import cli

    f = tmp_path / "in.txt"
    f.write_text("aa bb aa cc aa dd ee ff\n" * 200)
    assert cli.main([str(f), "--no-echo", "--format", "json", "--stream",
                     "--chunk-bytes", "1024", "--autotune"]) == 0
    err = capsys.readouterr().err
    assert "autotune: " in err, err


@pytest.mark.smoke
def test_print_tune_renders_hint_and_absence(capsys):
    """The CLI's stderr hint renderer: a noted recommendation prints
    rule + moves + reason; a handle the hint path never reached prints
    the honest absence line (jax-free unit of the @slow CLI drive)."""
    from mapreduce_tpu import cli

    tel = obs.Telemetry(enabled=True, sample_device_stats=False)
    tel.note_tune({"rule": "raise-prefetch",
                   "changed": {"prefetch_depth": [4, 8]},
                   "converged": False, "reason": "reader is the path"})
    cli._print_tune(tel)
    err = capsys.readouterr().err
    assert "autotune: raise-prefetch — prefetch_depth 4 -> 8" in err, err
    assert "reader is the path" in err
    cli._print_tune(obs.Telemetry.disabled())
    assert "no recommendation" in capsys.readouterr().err


def test_cli_autotune_grep_refused(tmp_path, capsys):
    from mapreduce_tpu import cli

    f = tmp_path / "in.txt"
    f.write_text("a b a\n")
    with pytest.raises(SystemExit) as exc:
        cli.main([str(f), "--stream", "--autotune", "--grep", "a"])
    assert exc.value.code == 2
    assert "word-count runs only" in capsys.readouterr().err


# -- end-to-end on CPU -------------------------------------------------------

@pytest.fixture(scope="module")
def hint_run(tmp_path_factory):
    """One telemetered streamed CPU run with autotune='hint' ->
    (RunResult, ledger records).  Module-scoped: the streamed run is the
    expensive part (tier-1 budget)."""
    import numpy as np

    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    from conftest import make_corpus

    tmp = tmp_path_factory.mktemp("tune_hint")
    corpus = make_corpus(np.random.default_rng(20260804), 2500, 120)
    path = tmp / "data.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=512, table_capacity=2048, inflight_groups=3,
                 autotune="hint")
    led = str(tmp / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        rr = executor.run_job(WordCountJob(cfg), str(path), cfg,
                              mesh=data_mesh(4), telemetry=tel)
    return rr, list(obs.read_ledger(led)), tel.last_tune


@pytest.mark.smoke
def test_hint_run_emits_one_tune_record(hint_run):
    """ISSUE 10 mode (b): exactly one `tune` record, written between the
    data summary and run_end, carrying a Config-valid proposal, the fired
    rule, and the decision trail; the same payload rides the RunResult
    AND the telemetry handle (the CLI's result-dropping surface)."""
    rr, recs, last_tune = hint_run
    tunes = [r for r in recs if r["kind"] == "tune"]
    assert len(tunes) == 1
    kinds = [r["kind"] for r in recs]
    assert kinds.index("tune") == len(kinds) - 2, kinds  # before run_end
    assert kinds[-1] == "run_end"
    t = tunes[0]
    assert t["mode"] == "hint" and t["tuner_version"] == engine.TUNER_VERSION
    assert t["current"] == {"chunk_bytes": 512, "superstep": 1,
                            "inflight_groups": 3, "prefetch_depth": 3,
                            "combiner": "off", "geometry": "default",
                            "merge_strategy": "tree",
                            "merge_overlap": "off"}
    engine.validate_knobs(t["proposal"])
    assert t["rule"] and t["trail"] and "signals" in t
    assert rr.tune is not None and rr.tune["rule"] == t["rule"]
    assert rr.tune["proposal"] == t["proposal"]
    assert last_tune is not None and last_tune["rule"] == t["rule"]
    # The hint derives from THIS run's ledger: its signals must agree
    # with the timeline reconstruction of the same records.
    from mapreduce_tpu.obs import timeline

    art = timeline.reconstruct(recs)
    assert t["signals"]["resource"] == art["bottleneck"]["resource"]
    # run_start stamps the v4 schema the tune record rides on.
    start = next(r for r in recs if r["kind"] == "run_start")
    assert start["ledger_version"] == obs.LEDGER_VERSION == 10


@pytest.mark.slow
def test_hint_never_changes_the_run(hint_run, tmp_path):
    """Byte-identity: an autotune='hint' run and a plain run produce
    identical results (the hint is advisory), and applying a TUNED config
    (deeper window/prefetch, superstep up) still matches — the tuned-vs-
    default byte-identity acceptance.  @slow per the >=10 s line (two
    extra streamed compiles); the PR-5 suite keeps window/superstep
    byte-identity in the fast tier."""
    import numpy as np

    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    from conftest import make_corpus

    corpus = make_corpus(np.random.default_rng(20260804), 2500, 120)
    path = tmp_path / "data.txt"
    path.write_bytes(corpus)
    mesh = data_mesh(4)

    def counts(cfg):
        rr = executor.run_job(WordCountJob(cfg), str(path), cfg, mesh=mesh)
        tbl = rr.value
        return (np.asarray(tbl.count).tolist(),
                np.asarray(tbl.pos_lo).tolist(),
                int(tbl.total_count()))

    default = counts(Config(chunk_bytes=512, table_capacity=2048,
                            inflight_groups=3))
    tuned = counts(Config(chunk_bytes=512, table_capacity=2048,
                          inflight_groups=8, prefetch_depth=8,
                          superstep=2))
    assert default == tuned
    # And the hint run's own result matches the plain default run's.
    rr_hint, _, _ = hint_run
    hint_tbl = rr_hint.value
    assert np.asarray(hint_tbl.count).tolist() == default[0]
    assert int(hint_tbl.total_count()) == default[2]


def test_selftest_entry(tmp_path):
    """The tools/autotune.py selftest (the tier-1/smoke gate) passes from
    pytest too — one entry point, wherever it is invoked from."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import autotune
    finally:
        sys.path.pop(0)
    assert autotune.selftest() == 0
