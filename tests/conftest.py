"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

This is the standard JAX fake-backend idiom (SURVEY §4): multi-chip sharding
paths are exercised on CPU without TPUs.  Must run before any jax import.
"""

import os
import sys

# HARD-disable the persistent XLA compile cache for the whole suite: the
# XLA:CPU executable serialization segfaults the process on the cache
# WRITE (reproduced round 4 and again round 5 — the round-5 crash came via
# test_cli running cli.main() in-process, which enabled the cache for
# every LATER test's fresh compiles; empty MAPREDUCE_COMPILE_CACHE makes
# enable_compile_cache a no-op).  The CLI/bench keep their cache outside
# pytest — it is exercised mostly on TPU, where serialization is solid.
os.environ["MAPREDUCE_COMPILE_CACHE"] = ""

# The force-CPU idiom (config.update after import — env vars alone are too
# late because sitecustomize may import jax at interpreter startup) lives in
# one place: __graft_entry__._force_cpu_mesh.  It also bumps a too-small
# ambient xla_force_host_platform_device_count, which the old inline copy
# here could not.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from __graft_entry__ import _force_cpu_mesh

jax = _force_cpu_mesh(8)

# NOTE: do NOT enable the persistent compile cache here.  Tried in round 4
# to absorb the compact-default compile growth; the XLA:CPU executable
# serialization in the cache WRITE path segfaults the whole pytest process
# on this box (reproduced twice, faulthandler stack through
# jax compilation_cache.put_executable_and_time while compiling the segmin
# end-to-end program).  The CLI/bench keep their cache — it is exercised
# mostly on TPU, where serialization is solid.

import numpy as np
import pytest


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """XLA:CPU segfaults nondeterministically deep into a single-process
    run of the whole suite (~280 cumulative compiles in; observed twice in
    round 5, both times while COMPILING a fresh program inside
    test_sketch — the same test passes in isolation and in any small
    batch).  No forked/xdist plugin is available in this image, so the
    mitigation is to drop compiled-executable and tracing caches at module
    boundaries, keeping the compiler's in-process footprint bounded.  The
    cost is cross-module cache misses for shared shapes (~minutes over the
    suite), which beats a segfaulted run with no report."""
    yield
    jax.clear_caches()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(20260729)


@pytest.fixture(scope="session")
def fixture_text() -> bytes:
    """The reference's bundled fixture (test.txt:1-3)."""
    return b"Hello World EveryOne\nWorld Good News\nGood Morning Hello\n"


def make_corpus(rng, n_words: int, vocab: int, zipf_a: float = 1.3, seed_words=None) -> bytes:
    """Random Zipf-distributed corpus, whitespace-joined."""
    words = seed_words or [f"w{i:x}" for i in range(vocab)]
    idx = rng.zipf(zipf_a, size=n_words) % len(words)
    seps = np.array([" ", "\n", "\t", "  ", " \r\n"])
    parts = []
    for i in idx:
        parts.append(words[int(i)])
        parts.append(str(seps[int(rng.integers(0, len(seps)))]))
    return "".join(parts).encode()


@pytest.fixture(scope="session")
def small_corpus(rng) -> bytes:
    return make_corpus(rng, n_words=2000, vocab=150)


def pallas_interpret_mode():
    """Force pallas interpret mode, on any jax (single owner of the shim).

    Newer jax has a global switch; older jax has none, but the kernel
    wrapper already auto-interprets off-TPU (ops/pallas/tokenize.py
    resolves interpret=None to "not on tpu"), so a no-op context preserves
    semantics for CPU runs.
    """
    from jax.experimental.pallas import tpu as pltpu

    if hasattr(pltpu, "force_tpu_interpret_mode"):
        return pltpu.force_tpu_interpret_mode()
    import contextlib

    return contextlib.nullcontext()
