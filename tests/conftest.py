"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

This is the standard JAX fake-backend idiom (SURVEY §4): multi-chip sharding
paths are exercised on CPU without TPUs.  Must run before any jax import.
"""

import os

# Force, don't setdefault: the ambient environment pins JAX_PLATFORMS to the
# real TPU tunnel, and running the whole suite through one remote chip both
# crawls and wedges other JAX clients.  The interpreter startup may import jax
# before this conftest runs (sitecustomize), so env vars alone are too late for
# jax_platforms — but the *backend* initializes lazily, so config.update plus
# XLA_FLAGS still land as long as no jax.devices()/computation ran yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) >= 8, (
    "tests require the 8-device virtual CPU mesh; either a JAX backend was "
    "initialized before conftest.py could configure it, or the ambient "
    "XLA_FLAGS already pins xla_force_host_platform_device_count below 8"
)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(20260729)


@pytest.fixture(scope="session")
def fixture_text() -> bytes:
    """The reference's bundled fixture (test.txt:1-3)."""
    return b"Hello World EveryOne\nWorld Good News\nGood Morning Hello\n"


def make_corpus(rng, n_words: int, vocab: int, zipf_a: float = 1.3, seed_words=None) -> bytes:
    """Random Zipf-distributed corpus, whitespace-joined."""
    words = seed_words or [f"w{i:x}" for i in range(vocab)]
    idx = rng.zipf(zipf_a, size=n_words) % len(words)
    seps = np.array([" ", "\n", "\t", "  ", " \r\n"])
    parts = []
    for i in idx:
        parts.append(words[int(i)])
        parts.append(str(seps[int(rng.integers(0, len(seps)))]))
    return "".join(parts).encode()


@pytest.fixture(scope="session")
def small_corpus(rng) -> bytes:
    return make_corpus(rng, n_words=2000, vocab=150)
