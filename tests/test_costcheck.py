"""costcheck (cost / vmem-budget / kernel-race passes): the contract.

Each new pass is demonstrated by a known-bad fixture that must produce an
error-severity finding — an injected VMEM-overflow kernel, a broken cost
baseline, a seeded cross-iteration ref race, an unreachable spill
fallback — and the shipped models must come back clean (the all-models
gate lives in test_graphcheck and now runs these passes too).  The
round-6 sort pricing (2.6-3.4 effective HBM passes) is asserted as a
machine-checked artifact of the production-shaped wordcount_pallas model.
"""

import json

import jax
import jax.numpy as jnp
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mapreduce_tpu import analysis
from mapreduce_tpu import models as models_mod
from mapreduce_tpu.analysis import core as acore
from mapreduce_tpu.analysis.passes.cost import CostPass
from mapreduce_tpu.analysis.passes.kernelrace import KernelRacePass
from mapreduce_tpu.analysis.passes.vmem import (VmemPass,
                                                certify_production_kernels)
from mapreduce_tpu.ops.pallas import meta
from mapreduce_tpu.parallel.mesh import data_mesh


@pytest.fixture(scope="module")
def mesh8():
    return data_mesh(8)


@pytest.fixture(scope="module")
def pallas_ctx(mesh8):
    """One shared context for the production-shaped stable2 model: the
    engine trace is the expensive part, so every pass test reuses it."""
    job = models_mod.build_model("wordcount_pallas")
    return acore.AnalysisContext(job, "wordcount_pallas", mesh=mesh8)


# -- known-bad fixture jobs --------------------------------------------------


class _ScalarJob:
    """Minimal correct job (see test_graphcheck): one uint32 scalar."""

    def init_state(self):
        return jnp.zeros((), jnp.uint32)

    def map_chunk(self, chunk, chunk_id):
        return jnp.sum((chunk != 0).astype(jnp.uint32))

    def combine(self, state, update):
        return state + update

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return state


def _copy_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


class VmemHogJob(_ScalarJob):
    """A kernel whose double-buffered blocks blow Mosaic's 16 MB default
    VMEM budget: two (2048, 2048) f32 blocks x 2 (in+out) x 2 (pipeline
    double-buffering) = 64 MiB.  The vmem pass must refuse it."""

    def map_chunk(self, chunk, chunk_id):
        big = jnp.zeros((2048, 2048), jnp.float32) + chunk[0]
        out = pl.pallas_call(
            _copy_kernel,
            grid=(1,),
            in_specs=[pl.BlockSpec((2048, 2048), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((2048, 2048), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((2048, 2048), jnp.float32),
            interpret=True,
        )(big)
        return out[0, 0].astype(jnp.uint32)


def _racy_kernel(x_ref, o_ref):
    # Blind unconditional write to a block every grid iteration revisits:
    # iteration i+1 clobbers iteration i (no read, no pl.when guard).
    o_ref[:] = x_ref[:] * jnp.uint32(2)


class RefRaceJob(_ScalarJob):
    """Seeded cross-iteration write/write hazard: 4 grid iterations all
    write the SAME output block unconditionally."""

    def map_chunk(self, chunk, chunk_id):
        x = (chunk[: 8 * 128].reshape(8, 128)).astype(jnp.uint32)
        out = pl.pallas_call(
            _racy_kernel,
            grid=(4,),
            in_specs=[pl.BlockSpec((2, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((2, 128), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((2, 128), jnp.uint32),
            interpret=True,
        )(x)
        return out[0, 0]


def _spilly_kernel(x_ref, o_ref, spill_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        spill_ref[0, 0] = jnp.uint32(0)

    o_ref[:] = x_ref[:]
    spill_ref[0, 0] = spill_ref[0, 0] + jnp.uint32(1)


class NoFallbackJob(_ScalarJob):
    """A spill-emitting kernel whose caller never branches on the spill
    counter: the exactness fallback is statically unreachable."""

    def map_chunk(self, chunk, chunk_id):
        x = (chunk[: 8 * 128].reshape(8, 128)).astype(jnp.uint32)
        out, _spill = pl.pallas_call(
            _spilly_kernel,
            grid=(4,),
            in_specs=[pl.BlockSpec((2, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=[pl.BlockSpec((2, 128), lambda i: (i, 0),
                                    memory_space=pltpu.VMEM),
                       pl.BlockSpec((1, 1), lambda i: (0, 0),
                                    memory_space=pltpu.SMEM)],
            out_shape=[jax.ShapeDtypeStruct((8, 128), jnp.uint32),
                       jax.ShapeDtypeStruct((1, 1), jnp.uint32)],
            interpret=True,
        )(x)
        return out[0, 0]


def _errors(report, pass_id):
    return [f for f in report.errors if f.pass_id == pass_id]


# -- vmem pass ---------------------------------------------------------------


@pytest.mark.smoke
def test_vmem_pass_flags_overflowing_kernel(mesh8):
    report = analysis.analyze_job(VmemHogJob(), "vmem-hog", mesh=mesh8,
                                  passes=[VmemPass()])
    errs = _errors(report, "vmem-budget")
    assert errs, report.format_text()
    assert any("exceeds" in f.message and "VMEM" in f.message
               for f in errs)
    assert report.exit_code != 0


def test_vmem_pass_flags_unreachable_spill_fallback(mesh8):
    meta.register(meta.KernelMeta(name="_spilly_kernel",
                                  spills=lambda n_out: True,
                                  description="test fixture"))
    report = analysis.analyze_job(NoFallbackJob(), "no-fallback",
                                  mesh=mesh8, passes=[VmemPass()])
    errs = _errors(report, "vmem-budget")
    assert any("fallback" in f.message and "unreachable"
               in f.message for f in errs), report.format_text()


def test_vmem_pass_certifies_pallas_model(pallas_ctx):
    report = acore.run_pipeline(pallas_ctx, [VmemPass()])
    assert not report.errors, report.format_text()
    kernels = report.artifacts["wordcount_pallas"]["vmem"]
    assert any(k["kernel"] == "_tokenize_kernel" for k in kernels)
    for k in kernels:
        assert k["vmem_bytes"] <= (k["vmem_limit_bytes"]
                                   or meta.VMEM_DEFAULT_LIMIT)


def test_production_kernel_plans_certified():
    findings = certify_production_kernels()
    assert findings  # every shipped geometry reports
    assert not [f for f in findings if f.severity == acore.ERROR], \
        "\n".join(f.format() for f in findings)
    # All three kernel families covered.
    msgs = " ".join(f.message for f in findings)
    assert "_tokenize_kernel" in msgs and "_partition_kernel" in msgs
    assert "lane-major" in msgs  # the stable2 geometry


# -- kernel-race pass --------------------------------------------------------


@pytest.mark.smoke
def test_kernelrace_pass_flags_seeded_race(mesh8):
    report = analysis.analyze_job(RefRaceJob(), "ref-race", mesh=mesh8,
                                  passes=[KernelRacePass()])
    errs = _errors(report, "kernel-race")
    assert errs, report.format_text()
    assert any("write/write" in f.message for f in errs)
    assert report.exit_code != 0


def test_kernelrace_pass_accepts_shipped_kernels(pallas_ctx):
    report = acore.run_pipeline(pallas_ctx, [KernelRacePass()])
    assert not report.errors, report.format_text()
    # The SMEM accumulator + carry-scratch discipline is recognized, not
    # merely unseen.
    assert any("read-modify-write" in f.message for f in report.findings)


# -- cost pass ---------------------------------------------------------------


def test_cost_pass_certifies_sort_pricing(pallas_ctx):
    report = acore.run_pipeline(pallas_ctx, [CostPass()])
    assert not report.errors, report.format_text()
    art = report.artifacts["wordcount_pallas"]["cost"]
    sort = art["aggregation_sort"]
    # The static leg: traced rows == geometry formula, and the production
    # extrapolation reproduces the measured 11.2M-row stream.
    assert sort["traced_rows"] == sort["expected_rows"]
    assert sort["production_rows"] == 11206656
    lo, hi = sort["derived_passes"]
    claimed_lo, claimed_hi = sort["claimed_passes"]
    tol = sort["tolerance"]
    assert abs(lo - claimed_lo) <= tol * claimed_lo
    assert abs(hi - claimed_hi) <= tol * claimed_hi


def test_cost_pass_flags_broken_baseline(mesh8, tmp_path, pallas_ctx):
    # A baseline claiming far fewer HBM passes than the program predicts
    # is a regression the gate must catch.  Populate the cost artifact
    # here rather than relying on an earlier test having run the pass on
    # the shared fixture (order-independence; the trace is memoized so
    # the re-run is cheap).
    if "cost" not in pallas_ctx.artifacts:
        acore.run_pipeline(pallas_ctx, [CostPass()])
    real = pallas_ctx.artifacts["cost"]["effective_input_passes"]
    (tmp_path / "wordcount_pallas.json").write_text(json.dumps(
        {"model": "wordcount_pallas",
         "effective_input_passes": real / 10}))
    ctx = acore.AnalysisContext(pallas_ctx.job, "wordcount_pallas",
                                mesh=mesh8, baselines_dir=str(tmp_path))
    ctx._engine_traces = pallas_ctx.engine_traces  # reuse the trace
    report = acore.run_pipeline(ctx, [CostPass()])
    errs = _errors(report, "hbm-cost")
    assert any("regressed" in f.message for f in errs), report.format_text()
    assert report.exit_code != 0


def test_cost_pass_write_then_gate_roundtrip(mesh8, tmp_path, pallas_ctx):
    wctx = acore.AnalysisContext(pallas_ctx.job, "wordcount_pallas",
                                 mesh=mesh8, baselines_dir=str(tmp_path),
                                 write_baselines=True)
    wctx._engine_traces = pallas_ctx.engine_traces
    report = acore.run_pipeline(wctx, [CostPass()])
    assert not report.errors, report.format_text()
    assert (tmp_path / "wordcount_pallas.json").exists()
    # Gate against what was just written: clean.
    gctx = acore.AnalysisContext(pallas_ctx.job, "wordcount_pallas",
                                 mesh=mesh8, baselines_dir=str(tmp_path))
    gctx._engine_traces = pallas_ctx.engine_traces
    report2 = acore.run_pipeline(gctx, [CostPass()])
    assert not report2.errors, report2.format_text()
    assert not [f for f in report2.findings
                if "no cost baseline" in f.message]


def test_checked_in_baselines_cover_all_models():
    from mapreduce_tpu.analysis.passes.cost import load_baseline

    for name in models_mod.model_names():
        base = load_baseline(name)
        assert base is not None, f"missing analysis/baselines/{name}.json"
        assert base["effective_input_passes"] > 0


# -- fusion-opportunity pass (ISSUE 6) ---------------------------------------


class SmallAdjacentJob(_ScalarJob):
    """Two adjacent materializing eqns with a VMEM-sized intermediate —
    cumsum feeds (through a fusible +1) a sort: the canonical candidate
    the fusion-opportunity pass exists to surface."""

    def map_chunk(self, chunk, chunk_id):
        x = chunk[:128].astype(jnp.uint32)
        y = jnp.cumsum(x)
        z = jnp.sort(y + 1)
        return z[0]


class HugeAdjacentJob(_ScalarJob):
    """The same adjacency shape, but the pair's combined working set
    (two 64 MiB f32 planes in flight) dwarfs Mosaic's 16 MB VMEM
    envelope: NOT a candidate — flagging it would send someone chasing a
    fusion that cannot be a kernel (the known-bad fixture of the pass)."""

    def map_chunk(self, chunk, chunk_id):
        big = jnp.zeros((4096, 4096), jnp.float32) + chunk[0]
        y = jnp.cumsum(big, axis=0)
        z = jnp.sort(y, axis=0)
        return z[0, 0].astype(jnp.uint32)


def _fusion_candidates(report, model):
    art = report.artifacts[model]["fusion"]
    return [c for prog in art["programs"].values() for c in prog]


@pytest.mark.smoke
def test_fusion_pass_flags_adjacent_pair(mesh8):
    from mapreduce_tpu.analysis.passes.fusion import FusionPass

    report = analysis.analyze_job(SmallAdjacentJob(), "small-adjacent",
                                  mesh=mesh8, passes=[FusionPass()])
    assert not report.errors, report.format_text()  # candidates are leads
    cands = _fusion_candidates(report, "small-adjacent")
    pair = [c for c in cands
            if c["producer"] == "cumsum" and c["consumer"] == "sort"]
    assert pair, cands
    assert pair[0]["hbm_bytes_saved"] == 2 * pair[0]["intermediate_bytes"]
    assert pair[0]["combined_vmem_bytes"] <= meta.VMEM_DEFAULT_LIMIT
    assert any("candidate fusion" in f.message for f in report.findings)


def test_fusion_pass_respects_vmem_envelope(mesh8):
    """Adjacent materializing eqns whose combined footprint exceeds the
    VMEM envelope must NOT be flagged."""
    from mapreduce_tpu.analysis.passes.fusion import FusionPass

    report = analysis.analyze_job(HugeAdjacentJob(), "huge-adjacent",
                                  mesh=mesh8, passes=[FusionPass()])
    assert not report.errors, report.format_text()
    cands = _fusion_candidates(report, "huge-adjacent")
    assert not [c for c in cands
                if c["producer"] == "cumsum" and c["consumer"] == "sort"], \
        cands
    # The envelope invariant holds for every candidate the pass emits.
    assert all(c["combined_vmem_bytes"] <= meta.VMEM_DEFAULT_LIMIT
               for c in cands), cands


# -- combiner-vs-off cost gate (ISSUE 11) ------------------------------------


@pytest.fixture(scope="module")
def combiner_ctx(mesh8):
    job = models_mod.build_model("wordcount_combiner")
    return acore.AnalysisContext(job, "wordcount_combiner", mesh=mesh8)


def test_cost_gate_certifies_combiner_below_off(combiner_ctx):
    """ISSUE 11 acceptance: the hot-key-combiner model prices strictly
    below its combiner-off twin's checked-in baseline, the artifact
    carries the gap, and the fused-vs-split gate stays out of the way
    (the pair is exempt — its fused-ness is wordcount_fused's
    certificate)."""
    report = acore.run_pipeline(combiner_ctx, [CostPass()])
    assert not report.errors, report.format_text()
    art = report.artifacts["wordcount_combiner"]["cost"]
    gap = art["combiner_vs_off"]
    assert gap["off_model"] == "wordcount_nocombiner"
    assert gap["combiner_effective_input_passes"] \
        < gap["off_effective_input_passes"]
    assert gap["passes_saved"] > 0
    assert "fused_vs_split" not in art
    assert any("combiner certified" in f.message for f in report.findings)


def test_cost_gate_flags_combiner_that_stopped_winning(mesh8, tmp_path,
                                                       combiner_ctx):
    """An off baseline priced BELOW the combiner program = the cache
    stopped deleting sort traffic: ERROR, and no gap is published."""
    if "cost" not in combiner_ctx.artifacts:
        acore.run_pipeline(combiner_ctx, [CostPass()])
    passes = combiner_ctx.artifacts["cost"]["effective_input_passes"]
    chunk = combiner_ctx.artifacts["cost"]["traced_chunk_bytes"]
    (tmp_path / "wordcount_nocombiner.json").write_text(json.dumps(
        {"model": "wordcount_nocombiner",
         "effective_input_passes": passes / 2,
         "traced_chunk_bytes": chunk}))
    (tmp_path / "wordcount_combiner.json").write_text(json.dumps(
        {"model": "wordcount_combiner",
         "effective_input_passes": passes,
         "traced_chunk_bytes": chunk}))
    ctx = acore.AnalysisContext(combiner_ctx.job, "wordcount_combiner",
                                mesh=mesh8, baselines_dir=str(tmp_path))
    ctx._engine_traces = combiner_ctx.engine_traces  # reuse the trace
    report = acore.run_pipeline(ctx, [CostPass()])
    errs = _errors(report, "hbm-cost")
    assert any("NOT strictly below" in f.message for f in errs), \
        report.format_text()
    assert report.exit_code != 0


def test_cost_gate_refuses_combiner_incomparable_geometry(mesh8, tmp_path,
                                                          combiner_ctx):
    """An off baseline priced at a different chunk cannot gate the
    combiner model, and the incomparable gap must not be published."""
    if "cost" not in combiner_ctx.artifacts:
        acore.run_pipeline(combiner_ctx, [CostPass()])
    passes = combiner_ctx.artifacts["cost"]["effective_input_passes"]
    chunk = combiner_ctx.artifacts["cost"]["traced_chunk_bytes"]
    (tmp_path / "wordcount_nocombiner.json").write_text(json.dumps(
        {"model": "wordcount_nocombiner",
         "effective_input_passes": passes * 2,
         "traced_chunk_bytes": chunk * 2}))
    (tmp_path / "wordcount_combiner.json").write_text(json.dumps(
        {"model": "wordcount_combiner",
         "effective_input_passes": passes,
         "traced_chunk_bytes": chunk}))
    ctx = acore.AnalysisContext(combiner_ctx.job, "wordcount_combiner",
                                mesh=mesh8, baselines_dir=str(tmp_path))
    ctx._engine_traces = combiner_ctx.engine_traces
    report = acore.run_pipeline(ctx, [CostPass()])
    errs = _errors(report, "hbm-cost")
    assert any("not comparable" in f.message for f in errs), \
        report.format_text()
    assert "combiner_vs_off" not in \
        report.artifacts["wordcount_combiner"]["cost"]


# -- fused-vs-split cost gate (ISSUE 6) --------------------------------------


@pytest.fixture(scope="module")
def fused_ctx(mesh8):
    job = models_mod.build_model("wordcount_fused")
    return acore.AnalysisContext(job, "wordcount_fused", mesh=mesh8)


@pytest.mark.smoke
def test_cost_gate_certifies_fused_below_split(fused_ctx):
    """The machine-checked before/after: the fused model prices strictly
    below the split-path baseline, and the artifact carries the gap."""
    report = acore.run_pipeline(fused_ctx, [CostPass()])
    assert not report.errors, report.format_text()
    art = report.artifacts["wordcount_fused"]["cost"]
    gap = art["fused_vs_split"]
    assert gap["split_model"] == "wordcount_pallas"
    assert gap["fused_effective_input_passes"] \
        < gap["split_effective_input_passes"]
    assert gap["passes_saved"] > 0
    assert any("fusion certified" in f.message for f in report.findings)


def test_cost_gate_flags_fusion_that_stopped_winning(mesh8, tmp_path,
                                                     fused_ctx):
    """A split baseline priced BELOW the fused program = the fusion
    stopped deleting traffic: ERROR."""
    if "cost" not in fused_ctx.artifacts:
        acore.run_pipeline(fused_ctx, [CostPass()])
    fused_passes = fused_ctx.artifacts["cost"]["effective_input_passes"]
    chunk = fused_ctx.artifacts["cost"]["traced_chunk_bytes"]
    (tmp_path / "wordcount_pallas.json").write_text(json.dumps(
        {"model": "wordcount_pallas",
         "effective_input_passes": fused_passes / 2,
         "traced_chunk_bytes": chunk}))
    # The fused model's own baseline must still gate clean from tmp_path.
    (tmp_path / "wordcount_fused.json").write_text(json.dumps(
        {"model": "wordcount_fused",
         "effective_input_passes": fused_passes,
         "traced_chunk_bytes": chunk}))
    ctx = acore.AnalysisContext(fused_ctx.job, "wordcount_fused",
                                mesh=mesh8, baselines_dir=str(tmp_path))
    ctx._engine_traces = fused_ctx.engine_traces  # reuse the trace
    report = acore.run_pipeline(ctx, [CostPass()])
    errs = _errors(report, "hbm-cost")
    assert any("NOT strictly below" in f.message for f in errs), \
        report.format_text()
    assert report.exit_code != 0


def test_cost_gate_refuses_incomparable_chunk_geometry(mesh8, tmp_path,
                                                       fused_ctx):
    """A split baseline priced at a DIFFERENT chunk geometry cannot gate
    the fused model: passes are per-chunk, comparing them is nonsense."""
    if "cost" not in fused_ctx.artifacts:
        acore.run_pipeline(fused_ctx, [CostPass()])
    fused_passes = fused_ctx.artifacts["cost"]["effective_input_passes"]
    chunk = fused_ctx.artifacts["cost"]["traced_chunk_bytes"]
    (tmp_path / "wordcount_pallas.json").write_text(json.dumps(
        {"model": "wordcount_pallas",
         "effective_input_passes": fused_passes * 2,
         "traced_chunk_bytes": chunk * 2}))
    (tmp_path / "wordcount_fused.json").write_text(json.dumps(
        {"model": "wordcount_fused",
         "effective_input_passes": fused_passes,
         "traced_chunk_bytes": chunk}))
    ctx = acore.AnalysisContext(fused_ctx.job, "wordcount_fused",
                                mesh=mesh8, baselines_dir=str(tmp_path))
    ctx._engine_traces = fused_ctx.engine_traces
    report = acore.run_pipeline(ctx, [CostPass()])
    errs = _errors(report, "hbm-cost")
    assert any("not" in f.message and "comparable" in f.message
               for f in errs), report.format_text()
    # The rejected gap must NOT be published: bench._cost_record copies
    # the artifact verbatim into BENCH JSON.
    assert "fused_vs_split" not in report.artifacts["wordcount_fused"]["cost"]


def test_cost_gate_flags_malformed_split_baseline(mesh8, tmp_path,
                                                  fused_ctx):
    """A split baseline with a zero/missing effective_input_passes AND a
    different chunk geometry must name the broken BASELINE — not publish
    a nonsense gap, and not misdiagnose as 'the fusion stopped deleting
    traffic' (the old `split_ref > 0` guard skipped the geometry check
    on exactly this input)."""
    if "cost" not in fused_ctx.artifacts:
        acore.run_pipeline(fused_ctx, [CostPass()])
    fused_passes = fused_ctx.artifacts["cost"]["effective_input_passes"]
    chunk = fused_ctx.artifacts["cost"]["traced_chunk_bytes"]
    (tmp_path / "wordcount_pallas.json").write_text(json.dumps(
        {"model": "wordcount_pallas",
         "effective_input_passes": 0.0,
         "traced_chunk_bytes": chunk * 2}))
    (tmp_path / "wordcount_fused.json").write_text(json.dumps(
        {"model": "wordcount_fused",
         "effective_input_passes": fused_passes,
         "traced_chunk_bytes": chunk}))
    ctx = acore.AnalysisContext(fused_ctx.job, "wordcount_fused",
                                mesh=mesh8, baselines_dir=str(tmp_path))
    ctx._engine_traces = fused_ctx.engine_traces
    report = acore.run_pipeline(ctx, [CostPass()])
    errs = _errors(report, "hbm-cost")
    assert any("no usable effective_input_passes" in f.message
               for f in errs), report.format_text()
    assert not any("NOT strictly below" in f.message for f in errs)
    assert "fused_vs_split" not in report.artifacts["wordcount_fused"]["cost"]


def test_cost_gate_refuses_baseline_missing_geometry(mesh8, tmp_path,
                                                     fused_ctx):
    """A split baseline that never recorded traced_chunk_bytes cannot be
    certified geometry-comparable: missing must gate like mismatched,
    not wildcard-match and publish the gap."""
    if "cost" not in fused_ctx.artifacts:
        acore.run_pipeline(fused_ctx, [CostPass()])
    fused_passes = fused_ctx.artifacts["cost"]["effective_input_passes"]
    chunk = fused_ctx.artifacts["cost"]["traced_chunk_bytes"]
    (tmp_path / "wordcount_pallas.json").write_text(json.dumps(
        {"model": "wordcount_pallas",
         "effective_input_passes": fused_passes * 2}))
    (tmp_path / "wordcount_fused.json").write_text(json.dumps(
        {"model": "wordcount_fused",
         "effective_input_passes": fused_passes,
         "traced_chunk_bytes": chunk}))
    ctx = acore.AnalysisContext(fused_ctx.job, "wordcount_fused",
                                mesh=mesh8, baselines_dir=str(tmp_path))
    ctx._engine_traces = fused_ctx.engine_traces
    report = acore.run_pipeline(ctx, [CostPass()])
    errs = _errors(report, "hbm-cost")
    assert any("not comparable" in f.message for f in errs), \
        report.format_text()
    assert "fused_vs_split" not in report.artifacts["wordcount_fused"]["cost"]


class SharedJaxprJob(_ScalarJob):
    """Two same-shaped jnp.sort calls: JAX's pjit cache hands both the
    SAME inner jaxpr (and Var objects).  A later cumsum consumes the
    FIRST sort's result — NOT adjacent (the second sort sits between) —
    so no sort->cumsum candidate may appear.  Guards the value-id
    canonicalization in _scan_scope: keying on shared Vars would alias
    the two calls' results and fabricate exactly that candidate."""

    def map_chunk(self, chunk, chunk_id):
        x = chunk[:128].astype(jnp.uint32)
        a = jnp.sort(x)
        b = jnp.sort(x + 2)
        z = jnp.cumsum(a)
        return z[0] + b[0]


def test_fusion_pass_does_not_alias_cached_jaxpr_calls(mesh8):
    from mapreduce_tpu.analysis.passes.fusion import FusionPass

    report = analysis.analyze_job(SharedJaxprJob(), "shared-jaxpr",
                                  mesh=mesh8, passes=[FusionPass()])
    cands = _fusion_candidates(report, "shared-jaxpr")
    assert not [c for c in cands
                if c["producer"] == "sort" and c["consumer"] == "cumsum"], \
        cands


class DowncastChainJob(_ScalarJob):
    """cumsum(uint32) -> astype(uint8) -> sort: the value round-tripping
    HBM is cumsum's 4-byte-per-element OUTPUT, not the 1-byte derived
    operand the sort consumes — pricing the consumer-side aval would
    report the saved traffic 4x too small."""

    def map_chunk(self, chunk, chunk_id):
        x = chunk[:128].astype(jnp.uint32)
        y = jnp.cumsum(x)
        z = jnp.sort((y & 0xFF).astype(jnp.uint8))
        return z[0].astype(jnp.uint32)


def test_fusion_pass_prices_materialized_producer_output(mesh8):
    from mapreduce_tpu.analysis.passes.fusion import FusionPass

    report = analysis.analyze_job(DowncastChainJob(), "downcast-chain",
                                  mesh=mesh8, passes=[FusionPass()])
    pair = [c for c in _fusion_candidates(report, "downcast-chain")
            if c["producer"] == "cumsum" and c["consumer"] == "sort"]
    assert pair, _fusion_candidates(report, "downcast-chain")
    # 128 x uint32 = 512 bytes materialized (NOT 128 x uint8 = 128).
    assert pair[0]["intermediate_bytes"] == 512, pair


class FanoutIntermediateJob(_ScalarJob):
    """cumsum feeds the adjacent sort AND a later equation: the fused
    kernel deletes the sort's READ of the intermediate, but its WRITE
    must stay for the other consumer — crediting 2x here would inflate
    the candidate over genuinely single-consumer fusions."""

    def map_chunk(self, chunk, chunk_id):
        x = chunk[:128].astype(jnp.uint32)
        y = jnp.cumsum(x)
        z = jnp.sort(y)
        return z[0] + y[0]  # y escapes the chain


def test_fusion_pass_keeps_write_for_fanout_intermediate(mesh8):
    from mapreduce_tpu.analysis.passes.fusion import FusionPass

    report = analysis.analyze_job(FanoutIntermediateJob(), "fanout-inter",
                                  mesh=mesh8, passes=[FusionPass()])
    pair = [c for c in _fusion_candidates(report, "fanout-inter")
            if c["producer"] == "cumsum" and c["consumer"] == "sort"]
    assert pair, _fusion_candidates(report, "fanout-inter")
    # Read saved, write preserved: 1x the intermediate, not 2x.
    assert pair[0]["hbm_bytes_saved"] == pair[0]["intermediate_bytes"], pair


# -- telemetry-overhead cost gate (ISSUE 8) ----------------------------------


@pytest.fixture(scope="module")
def telemetry_ctx(mesh8):
    """Context for the data-stats-instrumented stable2 model: the traced
    step is the INSTRUMENTED program telemetered runs dispatch."""
    job = models_mod.build_model("wordcount_telemetry")
    return acore.AnalysisContext(job, "wordcount_telemetry", mesh=mesh8)


@pytest.mark.smoke
def test_cost_gate_certifies_telemetry_overhead(telemetry_ctx):
    """ISSUE 8 acceptance: the instrumented model prices within 1% of the
    uninstrumented twin's checked-in baseline, and the artifact carries
    the measured overhead."""
    report = acore.run_pipeline(telemetry_ctx, [CostPass()])
    assert not report.errors, report.format_text()
    art = report.artifacts["wordcount_telemetry"]["cost"]
    ov = art["telemetry_overhead"]
    assert ov["plain_model"] == "wordcount_pallas"
    assert abs(ov["overhead_frac"]) <= ov["tolerance"] == 0.01, ov
    assert ov["instrumented_effective_input_passes"] \
        >= ov["plain_effective_input_passes"], \
        "instrumentation can only add traffic"
    assert any("telemetry overhead certified" in f.message
               for f in report.findings)


def test_cost_gate_flags_telemetry_overhead_regression(mesh8, tmp_path,
                                                       telemetry_ctx):
    """A plain baseline priced well below the instrumented program =
    observability grew the HBM bill past the 1% gate: ERROR."""
    if "cost" not in telemetry_ctx.artifacts:
        acore.run_pipeline(telemetry_ctx, [CostPass()])
    instr = telemetry_ctx.artifacts["cost"]["effective_input_passes"]
    chunk = telemetry_ctx.artifacts["cost"]["traced_chunk_bytes"]
    (tmp_path / "wordcount_pallas.json").write_text(json.dumps(
        {"model": "wordcount_pallas",
         "effective_input_passes": instr / 1.5,
         "traced_chunk_bytes": chunk}))
    # Own regression baseline stays clean so only the overhead gate fires.
    (tmp_path / "wordcount_telemetry.json").write_text(json.dumps(
        {"model": "wordcount_telemetry",
         "effective_input_passes": instr,
         "traced_chunk_bytes": chunk}))
    ctx = acore.AnalysisContext(telemetry_ctx.job, "wordcount_telemetry",
                                mesh=mesh8, baselines_dir=str(tmp_path))
    ctx._engine_traces = telemetry_ctx.engine_traces  # reuse the trace
    report = acore.run_pipeline(ctx, [CostPass()])
    errs = _errors(report, "hbm-cost")
    assert any("observability is regressing" in f.message for f in errs), \
        report.format_text()
    assert report.exit_code != 0


def test_cost_gate_flags_missing_plain_counterpart(mesh8, tmp_path,
                                                   telemetry_ctx):
    """No uninstrumented baseline = the overhead cannot be gated: ERROR
    (mirrors the fused gate's missing-counterpart contract)."""
    if "cost" not in telemetry_ctx.artifacts:
        acore.run_pipeline(telemetry_ctx, [CostPass()])
    instr = telemetry_ctx.artifacts["cost"]["effective_input_passes"]
    chunk = telemetry_ctx.artifacts["cost"]["traced_chunk_bytes"]
    (tmp_path / "wordcount_telemetry.json").write_text(json.dumps(
        {"model": "wordcount_telemetry",
         "effective_input_passes": instr,
         "traced_chunk_bytes": chunk}))
    ctx = acore.AnalysisContext(telemetry_ctx.job, "wordcount_telemetry",
                                mesh=mesh8, baselines_dir=str(tmp_path))
    ctx._engine_traces = telemetry_ctx.engine_traces
    report = acore.run_pipeline(ctx, [CostPass()])
    errs = _errors(report, "hbm-cost")
    assert any("telemetry overhead cannot be gated" in f.message
               for f in errs), report.format_text()
