"""N-gram counting: device results vs. a pure-Python oracle.

The reference has no n-gram capability (its map UDF emits single words only,
``mapper`` ``main.cu:37-54``); this family is beyond-parity, so the oracle is
the standard definition: sliding windows of n consecutive tokens of the
whitespace-split stream, keyed by the exact source span (separators between
tokens included).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.utils import oracle


def ngram_oracle(data: bytes, n: int) -> dict[bytes, int]:
    """{first-occurrence source span: count} per distinct n-token window.

    Grams are keyed by their *token sequence* (the device semantics: the gram
    hash mixes the token hashes, not the separator bytes between them), and
    each is displayed as the source span of its first occurrence — so
    ``b"w1 w1"`` and ``b"w1\\tw1"`` are the same bigram, reported under
    whichever span came first.
    """
    # Token spans (start, end) in order, replicating oracle.split_words.
    spans = []
    start = None
    seps = bytes(__import__("mapreduce_tpu").constants.SEPARATOR_BYTES)
    for i, b in enumerate(data):
        if b in seps:
            if start is not None:
                spans.append((start, i))
                start = None
        elif start is None:
            start = i
    if start is not None:
        spans.append((start, len(data)))
    counts: dict[tuple, int] = {}
    first_span: dict[tuple, bytes] = {}
    for i in range(len(spans) - n + 1):
        window = spans[i: i + n]
        key = tuple(data[s:e] for s, e in window)
        counts[key] = counts.get(key, 0) + 1
        first_span.setdefault(key, data[window[0][0]: window[-1][1]])
    return {first_span[k]: c for k, c in counts.items()}


def ngram_counts_by_tokens(data: bytes, n: int) -> dict[tuple, int]:
    """Oracle counts keyed by the token tuple itself (separator-independent).

    Streamed comparisons must use THIS keying: if a gram's true first
    occurrence straddles a chunk seam (dropped per the documented envelope),
    the streamed run reports a later occurrence's span, whose separator
    bytes may differ — span-keyed dict lookups would miss spuriously.
    """
    toks = oracle.split_words(data)
    counts: dict[tuple, int] = {}
    for i in range(len(toks) - n + 1):
        key = tuple(toks[i: i + n])
        counts[key] = counts.get(key, 0) + 1
    return counts


@pytest.mark.parametrize("n", [2, 3])
@pytest.mark.slow
def test_ngrams_match_oracle(small_corpus, n):
    cfg = Config(table_capacity=1 << 14)
    result = wordcount.count_ngrams(small_corpus, n, cfg)
    expected = ngram_oracle(small_corpus, n)
    assert result.as_dict() == expected
    assert result.total == sum(expected.values())
    assert result.dropped_count == 0


def test_bigram_fixture(fixture_text):
    result = wordcount.count_ngrams(fixture_text, 2)
    expected = ngram_oracle(fixture_text, 2)
    # 9 tokens -> 8 bigrams, all distinct except none repeat in the fixture.
    assert result.total == 8
    assert result.as_dict() == expected
    # Spans carry the real separator bytes (here the fixture's spaces and
    # newlines), e.g. the first bigram is the literal source text.
    assert result.words[0] == b"Hello World"


def test_unigram_order_matches_wordcount(fixture_text):
    uni = wordcount.count_ngrams(fixture_text, 1)
    base = wordcount.count_words(fixture_text)
    assert uni.as_dict() == base.as_dict()


@pytest.mark.slow  # 27 s measured round 6 (3 configs compiled): past the
# tier-1 >=10 s line; gram totals stay covered by test_ngrams_match_oracle.
def test_total_grams_is_tokens_minus_n_plus_1(small_corpus):
    tokens = oracle.total_count(small_corpus)
    for n in (1, 2, 3):
        result = wordcount.count_ngrams(small_corpus, n, Config(table_capacity=1 << 14))
        assert result.total == max(tokens - n + 1, 0)


def test_order_sensitive_keys():
    r = wordcount.count_ngrams(b"a b b a", 2)
    # 'a b', 'b b', 'b a' — order matters, all three distinct.
    assert r.as_dict() == {b"a b": 1, b"b b": 1, b"b a": 1}


def test_fewer_tokens_than_n():
    r = wordcount.count_ngrams(b"only two", 3)
    assert r.total == 0
    assert r.words == []


def test_gram_table_sentinel_boundary_at_max_pos_gate():
    """The packed gram build's sentinel-collision envelope (ADVICE r5):
    at the gate boundary max_pos == 2**25 a live row packs to
    _SENT_PACKED only with pos == 2**25-1 AND len7 == 127 together —
    unreachable, since a >=127-byte span cannot start within 127 bytes of
    max_pos (gram_table documents the proof; this pins its premises).

    Mechanically: rows AT the two extremes — the largest admissible pos
    with a short span, and the latest-starting >=127-byte span — must
    both survive the packed build with their identities intact."""
    from mapreduce_tpu import constants
    from mapreduce_tpu.ops import ngram as ngram_ops
    from mapreduce_tpu.ops.tokenize import TokenStream

    max_pos = 1 << 25
    sent = np.uint32(0xFFFFFFFF)
    n = 8
    khi = np.full(n, sent, np.uint32)
    klo = np.full(n, sent, np.uint32)
    cnt = np.zeros(n, np.uint32)
    pos = np.full(n, constants.POS_INF, np.uint32)
    length = np.zeros(n, np.uint32)
    # Row 0: a >=127-byte span at the latest start the invariant admits.
    khi[0], klo[0], cnt[0] = 7, 11, 1
    pos[0], length[0] = max_pos - 127, np.uint32(constants.SEAM_GRAM_LENGTH)
    # Row 1: the largest admissible pos, 1-byte span (packed = 0xFFFFFF81).
    khi[1], klo[1], cnt[1] = 13, 17, 1
    pos[1], length[1] = max_pos - 1, 1
    gs = TokenStream(key_hi=jnp.asarray(khi), key_lo=jnp.asarray(klo),
                     count=jnp.asarray(cnt), pos=jnp.asarray(pos),
                     length=jnp.asarray(length))
    t = ngram_ops.gram_table(gs, 8, 0, max_pos=max_pos)
    occ = np.asarray(t.occupied())
    assert int(occ.sum()) == 2  # neither row collided with the sentinel
    got = {(int(h), int(l)): (int(p), int(ln)) for h, l, p, ln in zip(
        np.asarray(t.key_hi)[occ], np.asarray(t.key_lo)[occ],
        np.asarray(t.pos_lo)[occ], np.asarray(t.length)[occ])}
    assert got[(7, 11)] == (max_pos - 127,
                            int(constants.SEAM_GRAM_LENGTH))
    assert got[(13, 17)] == (max_pos - 1, 1)
    assert int(np.asarray(t.dropped_count)) == 0


@pytest.mark.slow
def test_streamed_ngrams_single_device_exact(tmp_path):
    """Streamed == single-buffer, bit-exact, on a one-device mesh whose
    2 KB chunks force grams to straddle every row seam (VERDICT r2 #5:
    the old (n-1)*(chunks-1) undercount envelope is gone — the seam carry
    forms every crossing window exactly once)."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file

    from tests.conftest import make_corpus

    # Hermetic corpus (private rng): the shared session rng makes fixture
    # content depend on test-collection order, turning envelope assertions
    # into order-dependent flakes.
    corpus = make_corpus(np.random.default_rng(77), n_words=2000, vocab=150)

    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=2048, table_capacity=1 << 14, backend="xla")
    mesh = data_mesh(1)
    result = count_file(str(path), config=cfg, mesh=mesh, ngram=2)
    single = wordcount.count_ngrams(corpus, 2, Config(table_capacity=1 << 14,
                                                      backend="xla"))
    assert result.total == single.total
    assert result.as_dict() == single.as_dict()
    assert result.words == single.words  # identical insertion order + spans


@pytest.mark.parametrize("n", [2, 3])
@pytest.mark.slow
def test_streamed_ngrams_multi_device_exact(tmp_path, n):
    """Streamed == single-buffer across an 8-device mesh: seams between
    devices within a step AND between steps, all exact."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file
    from tests.conftest import make_corpus

    corpus = make_corpus(np.random.default_rng(78), n_words=2000, vocab=150)
    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=1024, table_capacity=1 << 14, backend="xla")
    result = count_file(str(path), config=cfg, mesh=data_mesh(8), ngram=n)
    single = wordcount.count_ngrams(corpus, n, Config(table_capacity=1 << 14,
                                                      backend="xla"))
    assert result.total == single.total
    assert result.as_dict() == single.as_dict()
    assert result.words == single.words


@pytest.mark.slow
def test_streamed_ngrams_window_spans_three_chunks(tmp_path):
    """A separator run longer than a whole chunk leaves empty chunks between
    two tokens: the carry composes across them and the window completes at
    the right join (trigrams spanning 3+ chunks)."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file

    corpus = b"aa bb" + b" " * 700 + b"cc" + b" " * 700 + b"dd ee\n"
    path = tmp_path / "gap.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=128, table_capacity=1 << 10, backend="xla")
    result = count_file(str(path), config=cfg, mesh=data_mesh(2), ngram=3)
    single = wordcount.count_ngrams(corpus, 3, Config(table_capacity=1 << 10,
                                                      backend="xla"))
    assert result.total == single.total == 3
    assert result.as_dict() == single.as_dict()
    assert result.words == single.words  # spans include the 700-byte gaps


@pytest.mark.slow
def test_streamed_pallas_ngrams_exact_across_seams(tmp_path):
    """The pallas backend's streamed grams are exact across chunk seams too
    (summary extracted from the position-sorted packed stream)."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file
    from tests.conftest import make_corpus

    corpus = make_corpus(np.random.default_rng(81), n_words=8000, vocab=120)
    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=128 * 66, table_capacity=1 << 14, backend="pallas")
    result = count_file(str(path), config=cfg, mesh=data_mesh(2), ngram=2)
    single = wordcount.count_ngrams(corpus, 2, Config(table_capacity=1 << 14,
                                                      backend="xla"))
    assert result.total == single.total
    assert result.as_dict() == single.as_dict()
    assert result.words == single.words


@pytest.mark.slow
def test_ngram_checkpoint_order_mismatch(tmp_path, small_corpus):
    """Bigram and trigram states share shapes; job identity refuses the
    cross-resume."""
    import pytest
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import checkpoint as ckpt
    from mapreduce_tpu.runtime.executor import count_file

    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024, table_capacity=1 << 12, backend="xla")
    ck = str(tmp_path / "ng.npz")
    count_file(str(path), config=cfg, mesh=data_mesh(2), ngram=2,
               checkpoint_path=ck, checkpoint_every=1)
    with pytest.raises(ckpt.CheckpointMismatch, match="job"):
        count_file(str(path), config=cfg, mesh=data_mesh(2), ngram=3,
                   checkpoint_path=ck, checkpoint_every=1)


# --- pallas backend (position-sort path, mapreduce_tpu/ops/ngram.py) -------

PALLAS_CFG = Config(chunk_bytes=128 * 66, table_capacity=1 << 14,
                    backend="pallas")


@pytest.mark.parametrize("n", [2, 3])
@pytest.mark.slow
def test_pallas_ngrams_match_oracle_and_xla(small_corpus, n):
    """The position-sort path produces bit-identical results to the XLA
    scan path (same hashes, same spans, same order)."""
    pal = wordcount.count_ngrams(small_corpus, n, PALLAS_CFG)
    xla = wordcount.count_ngrams(small_corpus, n,
                                 Config(table_capacity=1 << 14, backend="xla"))
    assert pal.as_dict() == ngram_oracle(small_corpus, n)
    assert pal.as_dict() == xla.as_dict()
    assert pal.words == xla.words  # identical insertion order
    assert pal.total == xla.total


def test_pallas_gram_straddles_lane_seam():
    """VERDICT r1 #4 'done' case: the kernel's 128-lane seams cut the buffer
    every seg_len bytes; seam emissions are concatenated before the position
    sort, so grams whose tokens straddle a seam must form exactly.  The
    buffer is sized to one pallas chunk (seg_len = 66), so a corpus covering
    it crosses ~128 seams; exact dict equality proves no seam gram is lost."""
    words = [b"w%d" % (i % 37) for i in range(1800)]
    data = b" ".join(words)[: 128 * 66 - 2]  # fill the whole chunk
    data = data.rsplit(b" ", 1)[0]  # end on a whole token
    pal = wordcount.count_ngrams(data, 2, PALLAS_CFG)
    assert pal.as_dict() == ngram_oracle(data, 2)
    assert pal.total == oracle.total_count(data) - 1
    assert pal.dropped_count == 0


def test_pallas_ngram_overlong_poison(small_corpus):
    """A chunk containing a token longer than the kernel window W: poison
    rows break the pairing chain at the suppressed token, so its neighbors
    never pair into phantom grams; the grams it would have joined are
    dropped and accounted (VERDICT r2 #4 — this replaced the whole-chunk
    lax.cond XLA fallback that embedded a pathologically-slow-to-compile
    branch in every n-gram program)."""
    data = small_corpus[:4000] + b" " + b"x" * 40 + b" " + small_corpus[4000:]
    pal = wordcount.count_ngrams(data, 2, PALLAS_CFG)
    xla = wordcount.count_ngrams(data, 2,
                                 Config(table_capacity=1 << 14, backend="xla"))
    # total_count includes dropped grams: the closed-form total is shared.
    assert pal.total == xla.total
    # The long token joins exactly 2 bigrams; both dropped, never phantom.
    long_grams = {w for w in xla.words if b"x" * 40 in w}
    assert len(long_grams) == 2
    assert pal.dropped_count == sum(
        xla.counts[xla.words.index(w)] for w in long_grams)
    assert not any(b"x" * 40 in w for w in pal.words)
    # Every other gram is identical, with identical counts — and no phantom
    # gram (a span bridging the suppressed token) appears.
    pal_counts = dict(zip(pal.words, pal.counts))
    xla_counts = {w: c for w, c in zip(xla.words, xla.counts)
                  if w not in long_grams}
    assert pal_counts == xla_counts


def test_pallas_ngram_overlong_adjacent_grams_trigram():
    """Overlong tokens adjacent to real grams, n=3: the whole pairing window
    crossing the poison row invalidates (not just the immediate neighbor
    pair), and dense gram structure around the suppression stays exact."""
    data = b"aa bb " + b"y" * 50 + b" cc dd ee " + b"z" * 40 + b" ff gg"
    pal = wordcount.count_ngrams(data, 3, PALLAS_CFG)
    xla = wordcount.count_ngrams(data, 3,
                                 Config(table_capacity=1 << 14, backend="xla"))
    assert pal.total == xla.total  # closed-form total incl. dropped
    # Only trigrams fully inside a run of <=W tokens survive: "cc dd ee".
    assert pal.words == [b"cc dd ee"]
    # 9 tokens -> 7 trigrams total; 1 formed, 6 dropped (every window that
    # touches y*50 or z*40).
    assert pal.counts == [1]
    assert pal.dropped_count == 6
    # And the XLA backend counts all 7 exactly (any token length).
    assert xla.total == 7 and xla.dropped_count == 0


def test_pallas_ngram_program_has_no_cond_fallback():
    """The n-gram program must be straight-line: no lax.cond (both branches
    of a cond are always compiled, so an embedded XLA-scan fallback would
    poison every program's compile time at production chunk sizes)."""
    import jax

    from mapreduce_tpu.ops import ngram as ngram_ops

    def step(chunk):
        return ngram_ops.ngram_table(chunk, 2, 1 << 10, 0, PALLAS_CFG)

    jaxpr = str(jax.make_jaxpr(step)(
        jnp.zeros((PALLAS_CFG.chunk_bytes,), jnp.uint8)))
    # Exactly one cond exists: the kernel's own `pl.when(i == 0)` scratch
    # init INSIDE the pallas_call.  The deleted fallback was a top-level
    # two-branch cond whose branches each returned a whole CountTable; any
    # second cond appearing here means a fallback crept back in.
    assert jaxpr.count("cond[") == 1 and "pallas_call" in jaxpr


@pytest.mark.slow
def test_streamed_pallas_ngrams_match_xla_backend(tmp_path):
    """Streamed n-grams: pallas and xla backends over identical chunking
    must agree exactly (the per-chunk envelope is backend-independent)."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file
    from tests.conftest import make_corpus

    corpus = make_corpus(np.random.default_rng(79), n_words=6000, vocab=150)
    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    base = dict(chunk_bytes=128 * 66, table_capacity=1 << 14)
    rp = count_file(str(path), config=Config(**base, backend="pallas"),
                    mesh=data_mesh(2), ngram=2)
    rx = count_file(str(path), config=Config(**base, backend="xla"),
                    mesh=data_mesh(2), ngram=2)
    assert rp.as_dict() == rx.as_dict()
    assert rp.words == rx.words
    assert rp.total == rx.total


def test_seam_carry_monoid_and_poison():
    """Unit tests of the sliding-window monoid: compose_carry keeps the most
    recent n-1 entries across short chunks, and a poison entry (suppressed
    >W token) in the carry kills exactly the windows containing it."""
    from mapreduce_tpu.ops import ngram as ng

    def carry(entries, m):
        """Right-aligned carry from [(khi, kind), ...] (newest last)."""
        pad = [(0, ng.KIND_EMPTY)] * (m - len(entries)) + entries
        return ng.GramCarry(
            key_hi=jnp.array([e[0] for e in pad], jnp.uint32),
            key_lo=jnp.array([e[0] ^ 7 for e in pad], jnp.uint32),
            chunk_id=jnp.array([9] * m, jnp.uint32),
            pos=jnp.array(range(m), jnp.uint32),
            kind=jnp.array([e[1] for e in pad], jnp.uint32))

    m = 3  # n = 4
    t = ng.KIND_TOKEN
    a = carry([(1, t), (2, t), (3, t)], m)
    b_short = carry([(4, t)], m)  # a 1-token chunk
    c = ng.compose_carry(a, b_short)
    assert list(np.asarray(c.key_hi)) == [2, 3, 4]
    assert list(np.asarray(c.kind)) == [t, t, t]
    # A full replacement: 3+ new entries wipe the old carry.
    b_full = carry([(5, t), (6, t), (7, t)], m)
    c2 = ng.compose_carry(a, b_full)
    assert list(np.asarray(c2.key_hi)) == [5, 6, 7]
    # Empty chunk: identity.
    c3 = ng.compose_carry(a, carry([], m))
    assert list(np.asarray(c3.key_hi)) == [1, 2, 3]

    # Poison in the prefix: windows containing it exist but are dropped.
    n = 4
    prefix = carry([(1, t), (2, ng.KIND_POISON), (3, t)], m)
    first = carry([], m)._replace(  # left-aligned: 3 tokens
        key_hi=jnp.array([10, 11, 12], jnp.uint32),
        key_lo=jnp.array([20, 21, 22], jnp.uint32),
        kind=jnp.array([t, t, t], jnp.uint32))
    k_hi, k_lo, cid, pos, cnt, dropped = ng.seam_gram_rows(prefix, first, n)
    # j=1: [3,10,11,12] all tokens -> counted; j=2: contains poison ->
    # dropped; j=3: contains poison -> dropped.
    assert list(np.asarray(cnt)) == [1, 0, 0]
    assert int(dropped) == 2


@pytest.mark.slow
def test_streamed_sketched_ngrams_exact(tmp_path):
    """Sketch composition forwards the seam machinery: a distinct-sketch
    streamed bigram run still matches single-buffer totals exactly."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file
    from tests.conftest import make_corpus

    corpus = make_corpus(np.random.default_rng(82), n_words=1500, vocab=100)
    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=1024, table_capacity=1 << 14, backend="xla")
    result = count_file(str(path), config=cfg, mesh=data_mesh(4), ngram=2,
                        distinct_sketch=True)
    single = wordcount.count_ngrams(corpus, 2, Config(table_capacity=1 << 14,
                                                      backend="xla"))
    assert result.total == single.total
    assert result.as_dict() == single.as_dict()
    assert result.distinct_estimate == pytest.approx(single.distinct, rel=0.1)


@pytest.mark.slow
def test_streamed_ngrams_top_k_with_seam_entries(tmp_path):
    """Device-side top_k over the streamed NGramState: seam entries
    (SEAM_GRAM_LENGTH) survive the terminal reorder and recover real spans;
    counts match the single-buffer top-k multiset."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file
    from tests.conftest import make_corpus

    corpus = make_corpus(np.random.default_rng(83), n_words=2000, vocab=40)
    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=1024, table_capacity=1 << 14, backend="xla")
    result = count_file(str(path), config=cfg, mesh=data_mesh(4), ngram=2,
                        top_k=10)
    single = wordcount.count_ngrams(corpus, 2, Config(table_capacity=1 << 14,
                                                      backend="xla"))
    single_top = wordcount.apply_top_k(single, 10)
    assert len(result.words) == 10
    assert sorted(result.counts, reverse=True) == sorted(
        single_top.counts, reverse=True)
    assert result.total == single.total
    # Reported spans are real corpus grams with true counts.
    exact = single.as_dict()
    for w, c in zip(result.words, result.counts):
        assert exact.get(w) == c, w


@pytest.mark.slow
def test_streamed_ngrams_multi_file_no_cross_file_grams(tmp_path):
    """Files are independent corpora: the seam carry resets at file
    boundaries (stacked-state-shaped reset), so no gram spans two files and
    the result equals per-file single-buffer runs summed."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file

    a, b = tmp_path / "a.txt", tmp_path / "b.txt"
    a.write_bytes(b"p q r s")  # no trailing newline: seam right at file end
    b.write_bytes(b"t u v w\n")
    cfg = Config(chunk_bytes=128, table_capacity=1 << 10, backend="xla")
    result = count_file([str(a), str(b)], config=cfg, mesh=data_mesh(2),
                        ngram=2)
    xcfg = Config(table_capacity=1 << 10, backend="xla")
    ra = wordcount.count_ngrams(b"p q r s", 2, xcfg)
    rb = wordcount.count_ngrams(b"t u v w\n", 2, xcfg)
    assert result.total == ra.total + rb.total == 6
    assert result.as_dict() == {**ra.as_dict(), **rb.as_dict()}
    assert b"s t" not in result.as_dict()  # no cross-file gram


def test_seam_span_over_force_split_run(tmp_path):
    """A separator-free run longer than the reader's alignment window gets
    force-split at a row cut into two stream entries; a seam gram over the
    halves must recover a span ending at the cut-induced entry end, not
    swallow the run plus the next word (scan_gram_lengths cut_offsets)."""
    from mapreduce_tpu.data import reader

    run = b"x" * 5000
    corpus = run + b" next word\n"
    path = tmp_path / "r.txt"
    path.write_bytes(corpus)
    # Simulated device view: a cut at 4096 splits the run into two entries.
    lengths = reader.scan_gram_lengths(str(path), [0], 2, cut_offsets=[4096])
    # Entry 1 = run[:4096] (ends at the cut), entry 2 = run[4096:5000]
    # (ends at the separator): the 2-gram span is exactly the whole run.
    assert lengths == [5000]
    # Without the cut the two entries are run + "next": span reaches "next".
    assert reader.scan_gram_lengths(str(path), [0], 2) == [5000 + 5]


@pytest.mark.slow
def test_streamed_ngrams_superstep_exact(tmp_path):
    """Superstep (lax.scan) dispatch: each scan iteration is one step —
    its own summary gather + carry composition — so K-chunk supersteps
    keep streamed n-grams bit-exact."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file
    from tests.conftest import make_corpus

    corpus = make_corpus(np.random.default_rng(84), n_words=2500, vocab=120)
    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=1024, table_capacity=1 << 14, backend="xla",
                 superstep=3)
    result = count_file(str(path), config=cfg, mesh=data_mesh(2), ngram=2)
    single = wordcount.count_ngrams(corpus, 2, Config(table_capacity=1 << 14,
                                                      backend="xla"))
    assert result.total == single.total
    assert result.as_dict() == single.as_dict()
    assert result.words == single.words


@pytest.mark.slow
def test_streamed_ngrams_2d_mesh_exact(tmp_path):
    """Streamed n-grams on a 2-D ('replica','data') mesh: the summary
    all_gather over the axis TUPLE must order rows exactly like the
    engine's row-major device-index linearization, or seam windows pair
    the wrong chunks.  Exactness against single-buffer proves the order."""
    from mapreduce_tpu.parallel.mesh import two_level_mesh
    from mapreduce_tpu.runtime.executor import count_file
    from tests.conftest import make_corpus

    corpus = make_corpus(np.random.default_rng(85), n_words=2000, vocab=100)
    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=1024, table_capacity=1 << 14, backend="xla")
    result = count_file(str(path), config=cfg, mesh=two_level_mesh(2, 4),
                        ngram=2)
    single = wordcount.count_ngrams(corpus, 2, Config(table_capacity=1 << 14,
                                                      backend="xla"))
    assert result.total == single.total
    assert result.as_dict() == single.as_dict()
    assert result.words == single.words


@pytest.mark.slow
def test_long_span_grams_recovered_exactly(tmp_path):
    """Gram spans >= 127 bytes (unbounded separator runs between tokens)
    exceed the packed build's 7-bit length field: the table stores the
    SEAM_GRAM_LENGTH scan-forward sentinel and recovery rescans the span —
    single-buffer (scan_gram_lengths_bytes) and streamed
    (scan_gram_lengths) alike, on both backends, bit-identically."""
    from mapreduce_tpu.runtime.executor import count_file

    corpus = (b"alpha" + b" " * 200 + b"beta gamma ") * 3 + b"alpha beta"
    expect = ngram_oracle(corpus, 2)
    xla_cfg = Config(table_capacity=1 << 14, backend="xla")
    xla = wordcount.count_ngrams(corpus, 2, xla_cfg)
    pal = wordcount.count_ngrams(corpus, 2, PALLAS_CFG)
    assert xla.as_dict() == expect
    assert pal.as_dict() == expect
    assert pal.words == xla.words
    # The long-gap bigram's reported span really is the 200-separator one.
    assert any(len(w) > 200 for w in xla.words)
    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    streamed = count_file(str(path), config=Config(
        chunk_bytes=1024, table_capacity=1 << 14, backend="xla"), ngram=2)
    assert streamed.total == xla.total
    # Token-keyed comparison (the streamed-comparison caveat,
    # ngram_counts_by_tokens): a wrong host-rescanned span would split
    # into the wrong token tuple and miss here.
    by_tokens = {tuple(oracle.split_words(w)): c
                 for w, c in zip(streamed.words, streamed.counts)}
    assert by_tokens == ngram_counts_by_tokens(corpus, 2)
