"""Skew-adaptive map-side combiner (ISSUE 11).

Fast tier: kernel-level exactness of the hot-key cache against the XLA
oracle (occurrence multiset + first occurrences + eviction accounting),
the salt round-trip at the table level, the cache-flush table fold, the
'auto' resolver, config validation, and the autotuner's enable-combiner
rule.  @slow (the >=10 s line): end-to-end wordcount/ngram bit-identity
across Zipf / uniform / single-key corpora in pallas interpret mode, the
dense-corpus spill fallback, and the streamed telemetered run whose
`data` record carries the combiner counters.
"""

from __future__ import annotations

import functools
import json
from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_tpu import constants
from mapreduce_tpu.config import COMBINER_SALT_BITS, Config
from mapreduce_tpu.obs import datahealth
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.ops import tokenize as tok_ops
from mapreduce_tpu.tuning import engine as tuning_engine

SENT = int(constants.SENTINEL_KEY)
N = 128 * 132  # smallest-ish fused chunk: seg_len 132 >= 2W+2


def _corpus(kind: str, n: int = N) -> bytes:
    rng = np.random.default_rng(7)
    words = [b"aa", b"bb", b"c", b"ddd", b"ee", b"f", b"gg", b"hh",
             b"iii", b"jj", b"kk", b"lll", b"mm", b"n", b"oo", b"pp"]
    if kind == "zipf":
        p = np.array([1 / (i + 1) ** 1.3 for i in range(len(words))])
        toks = rng.choice(len(words), 3000, p=p / p.sum())
    elif kind == "uniform":
        toks = rng.integers(0, len(words), 3000)
    elif kind == "single":
        toks = np.zeros(3000, np.int64)
    else:
        raise ValueError(kind)
    data = b" ".join(words[t] for t in toks)
    return (data + b" " * n)[:n]


@functools.lru_cache(maxsize=None)
def _combined(data: bytes):
    """One jitted combiner-kernel pass (cached so every test shares the
    single ~7 s interpret-mode compile)."""
    from mapreduce_tpu.ops.pallas import tokenize as pallas_tok

    @jax.jit
    def run(arr):
        return pallas_tok.tokenize_fused(
            arr, compact_slots=128, lane_major=True, block_rows=512,
            combiner_slots=8)

    stream, overlong, spill, cache = run(
        jnp.asarray(np.frombuffer(data, np.uint8)))
    return (jax.tree.map(np.asarray, stream), int(overlong), int(spill),
            jax.tree.map(np.asarray, cache))


def _occurrences(stream) -> Counter:
    m = np.asarray(stream.count) > 0
    return Counter(zip(np.asarray(stream.key_hi)[m].tolist(),
                       np.asarray(stream.key_lo)[m].tolist()))


def _first_pos(stream) -> dict:
    m = np.asarray(stream.count) > 0
    out: dict = {}
    for k, l, p in zip(np.asarray(stream.key_hi)[m].tolist(),
                       np.asarray(stream.key_lo)[m].tolist(),
                       np.asarray(stream.pos)[m].tolist()):
        out[(k, l)] = min(out.get((k, l), 1 << 40), p)
    return out


@pytest.mark.smoke
def test_hot_cache_kernel_matches_xla_oracle():
    """The exactness core: stream + flushed cache together hold exactly
    the XLA oracle's occurrence multiset, and per-key first occurrences
    are preserved (the cache records each entry's first in-lane
    occurrence; the global min survives the fold)."""
    data = _corpus("zipf")
    stream, overlong, spill, cache = _combined(data)
    assert spill == 0 and overlong == 0
    oracle = tok_ops.tokenize(jnp.asarray(np.frombuffer(data, np.uint8)))
    want = _occurrences(oracle)
    got = _occurrences(stream)
    ck = cache.key_hi.ravel().tolist()
    cl = cache.key_lo.ravel().tolist()
    cc = cache.count.ravel().tolist()
    cp = cache.packed.ravel().tolist()
    for k, l, c in zip(ck, cl, cc):
        if c:
            got[(k, l)] += c
    assert got == want
    # The cache absorbed the dominant mass on a Zipf stream: most
    # occurrences never reach the sort.
    hits = sum(c for c in cc if c)
    assert hits > 0.8 * sum(want.values()), hits
    # First occurrences: min over (stream, cache) positions == oracle's.
    first = _first_pos(stream)
    for k, l, c, p in zip(ck, cl, cc, cp):
        if c:
            key = (k, l)
            first[key] = min(first.get(key, 1 << 40), p >> 6)
    assert first == _first_pos(oracle)


def test_hot_cache_eviction_accounting():
    """Every resident entry is evicted at the flush; count-1 entries are
    the cold ones (slots that bought nothing).  The fixture's long tail
    guarantees some, and exactness never depends on which keys went
    cold (the oracle-parity test above shares this cache)."""
    _, _, _, cache = _combined(_corpus("zipf"))
    cc = cache.count.ravel()
    flushes = int((cc > 0).sum())
    evicted = int((cc == 1).sum())
    assert flushes > 0 and 0 < evicted < flushes
    # Rows deleted from the sort input = hits - flush rows re-emitted.
    assert int(cc.sum()) - flushes > 0


def test_combiner_table_fold_is_exact():
    """merge(build(thinned stream), cache table) == build(oracle stream):
    the fold the fused map path runs, checked key-for-key at the table
    level (counts, first occurrence, dropped accounting)."""
    from mapreduce_tpu.models.wordcount import _combiner_table

    data = _corpus("zipf")
    stream, _, _, cache = _combined(data)
    cap = 512
    thin = table_ops.from_stream(
        jax.tree.map(jnp.asarray, stream), cap, pos_hi=0,
        max_token_bytes=32, max_pos=N, sort_mode="stable2")
    cache_tbl = _combiner_table(jax.tree.map(jnp.asarray, cache), 0)
    merged = table_ops.merge(thin, cache_tbl, capacity=cap)
    oracle = tok_ops.tokenize(jnp.asarray(np.frombuffer(data, np.uint8)))
    want = table_ops.from_stream(oracle, cap, pos_hi=0)
    for f in ("key_hi", "key_lo", "count", "count_hi", "pos_hi", "pos_lo",
              "length"):
        np.testing.assert_array_equal(np.asarray(getattr(merged, f)),
                                      np.asarray(getattr(want, f)), f)
    assert int(merged.dropped_count) == int(want.dropped_count)


def test_salt_round_trip_bit_identical():
    """from_packed_rows(salt_bits) == from_packed_rows() on packed rows
    with duplicate hot keys, poison rows, and dead filler — the de-salt
    re-reduce recovers exact counts and minimum first occurrences, and
    the poison-segment rescue extraction is untouched."""
    rng = np.random.default_rng(3)
    n = 4096
    # Keys separated by more than the 2**COMBINER_SALT_BITS XOR envelope
    # (adjacent key_lo values under one key_hi would legitimately
    # coalesce — that is the documented salt collision envelope, not a
    # round-trip bug).
    keys = [(0x1234, 0x9900), (0x1234, 0xA200), (SENT, SENT - 0x40),
            (7, 0x800), (9, 0x1000)]
    khi = np.full(n, SENT, np.uint32)
    klo = np.full(n, SENT, np.uint32)
    packed = np.full(n, 0xFFFFFFFF, np.uint32)
    live = 3000
    pick = rng.integers(0, len(keys), live)
    pick[:2000] = 0  # one scorching key — the salt scenario
    pos = np.sort(rng.choice(1 << 20, live, replace=False))
    for i in range(live):
        khi[i], klo[i] = keys[pick[i]]
        packed[i] = (pos[i] << 6) | 3
    # Two poison rows (reserved key, zero length bits).
    khi[live:live + 2] = SENT
    klo[live:live + 2] = SENT - 1
    packed[live] = (123 << 6)
    packed[live + 1] = (456 << 6)
    args = (jnp.asarray(khi), jnp.asarray(klo), jnp.asarray(packed),
            jnp.uint32(live), 256, 0)
    for mode in ("stable2", "sort3"):
        plain, resc_p = table_ops.from_packed_rows(
            *args, sort_mode=mode, rescue_slots=4)
        salted, resc_s = table_ops.from_packed_rows(
            *args, sort_mode=mode, rescue_slots=4,
            salt_bits=COMBINER_SALT_BITS)
        for f in plain._fields:
            np.testing.assert_array_equal(np.asarray(getattr(plain, f)),
                                          np.asarray(getattr(salted, f)),
                                          f"{mode}:{f}")
        np.testing.assert_array_equal(np.asarray(resc_p), np.asarray(resc_s))


def test_salt_refusals():
    with pytest.raises(ValueError, match="salt_bits"):
        table_ops.from_packed_rows(
            jnp.zeros(8, jnp.uint32), jnp.zeros(8, jnp.uint32),
            jnp.zeros(8, jnp.uint32), jnp.uint32(0), 4, 0,
            sort_mode="segmin", salt_bits=2)
    with pytest.raises(ValueError, match="salt_bits"):
        table_ops.from_stream(
            tok_ops.TokenStream(*[jnp.zeros(8, jnp.uint32)] * 5), 4,
            salt_bits=2)  # generic build has no salt


@pytest.mark.smoke
def test_auto_resolution_from_ledger():
    """Config(combiner='auto') acceptance: a skew-hot ledger flips the
    combiner on, a clean one (and no history) stays off, and an
    append-mode ledger resolves from the LATEST data record."""
    skew = {"kind": "data", "run_id": "a", "tokens": 60000,
            "top_count": 12000, "chunks": 4, "capacity": 1 << 16,
            "table_valid": 900}
    clean = {"kind": "data", "run_id": "b", "tokens": 60000,
             "top_count": 24, "chunks": 4, "capacity": 1 << 16,
             "table_valid": 900}
    assert datahealth.resolve_combiner([skew]) == "hot-cache"
    assert datahealth.resolve_combiner([clean]) == "off"
    assert datahealth.resolve_combiner([]) == "off"
    assert datahealth.resolve_combiner([clean, skew]) == "hot-cache"
    assert datahealth.resolve_combiner([skew, clean]) == "off"
    # An unresolved 'auto' traces as 'off' (library callers that never
    # resolve get the shipped behavior, not a surprise cache).
    cfg = Config(combiner="auto")
    assert cfg.resolved_combiner == "off"
    assert cfg.resolved_combiner_slots == 0


def test_config_surface():
    with pytest.raises(ValueError, match="combiner"):
        Config(combiner="always")
    with pytest.raises(ValueError, match="salt"):
        # Fail at construction, not mid-trace (the segmin payload scan
        # has no per-segment order to de-salt from).
        Config(combiner="salt", sort_mode="segmin")
    with pytest.raises(ValueError, match="combiner_slots"):
        Config(combiner="hot-cache", combiner_slots=12)
    with pytest.raises(ValueError, match="combiner_slots"):
        Config(combiner_slots=8)  # sizing a cache that is off
    base = dict(backend="pallas", map_impl="fused", chunk_bytes=1 << 15)
    on = Config(**base, combiner="hot-cache")
    assert on.resolved_combiner_slots == 8
    assert on.resolved_block_rows == 512
    off = Config(**base)
    assert off.resolved_combiner_slots == 0
    assert off.resolved_block_rows == 384
    # The cache only exists on the fused compact path: split mode (and
    # the xla backend) resolve to no cache — and keep the 384 geometry.
    split = Config(backend="pallas", combiner="hot-cache",
                   chunk_bytes=1 << 15)
    assert split.resolved_combiner_slots == 0
    assert split.resolved_block_rows == 384
    assert Config(combiner="salt").resolved_salt_bits == COMBINER_SALT_BITS
    assert Config().resolved_salt_bits == 0


def test_kernel_combiner_validation():
    from mapreduce_tpu.ops.pallas import tokenize as pallas_tok

    arr = jnp.zeros(N, jnp.uint8)
    with pytest.raises(ValueError, match="combiner_slots"):
        pallas_tok.tokenize_fused(arr, combiner_slots=8)  # pair mode
    with pytest.raises(ValueError, match="combiner_slots"):
        pallas_tok.tokenize_fused(arr, compact_slots=128, lane_major=True,
                                  combiner_slots=12)
    with pytest.raises(ValueError, match="base_offset"):
        # The cache flush records in-chunk positions; offsetting the
        # stream but not the cache would skew cached first occurrences.
        pallas_tok.tokenize_fused(arr, compact_slots=128, lane_major=True,
                                  combiner_slots=8, base_offset=128)


@pytest.mark.smoke
def test_tuner_enable_combiner_rule():
    """The skew-hot -> enable-combiner row: fires exactly when the data
    verdict is skew-hot and the combiner is off; an already-on run notes
    the fact in the trail and falls through."""
    skew_data = {"kind": "data", "run_id": "r", "tokens": 60000,
                 "top_count": 12000, "chunks": 4, "capacity": 1 << 16,
                 "table_valid": 900}
    start = {"kind": "run_start", "run_id": "r", "chunk_bytes": 1 << 21,
             "superstep": 1, "combiner": "off"}
    end = {"kind": "run_end", "run_id": "r", "bytes": 1 << 23,
           "elapsed_s": 1.0,
           "phases": {"read_wait": 0.1, "dispatch": 0.8}}
    p = tuning_engine.propose([start, skew_data, end])
    assert p["rule"] == "enable-combiner"
    assert p["changed"] == {"combiner": ["off", "hot-cache"]}
    tuning_engine.validate_knobs(p["proposal"])
    # Already on: the rule is considered, does not fire, and the trail
    # records why; no pipeline knob chases the (already answered) skew.
    start_on = dict(start, combiner="hot-cache")
    p2 = tuning_engine.propose([start_on, skew_data, end])
    assert p2["rule"] != "enable-combiner"
    noted = [t for t in p2["trail"] if t["rule"] == "enable-combiner"]
    assert noted and not noted[-1]["fired"]
    assert "already" in noted[-1]["why"]


def test_cli_combiner_auto_resolves_from_prior_ledger(tmp_path, capsys):
    """CLI acceptance: --combiner auto + a --ledger whose history says
    skew-hot resolves to hot-cache and stamps the RESOLVED mode into the
    new run's own records (xla backend: the cache is a no-op there, but
    the resolution contract is backend-independent)."""
    from mapreduce_tpu import cli

    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"hot hot hot cold\n" * 40)
    led = tmp_path / "run.jsonl"
    led.write_text(json.dumps(
        {"ts": 1.0, "run_id": "prev", "kind": "data", "tokens": 60000,
         "top_count": 12000, "chunks": 4, "capacity": 1 << 16,
         "table_valid": 900}) + "\n")
    rc = cli.main([str(corpus), "--combiner", "auto", "--ledger", str(led),
                   "--format", "json", "--no-echo", "--backend", "xla"])
    assert rc == 0
    assert "combiner: auto -> hot-cache" in capsys.readouterr().err
    recs = [json.loads(ln) for ln in led.read_text().splitlines()]
    start = [r for r in recs if r.get("kind") == "run_start"
             and r.get("run_id") != "prev"]
    assert start and start[0]["combiner"] == "hot-cache"
    data = [r for r in recs if r.get("kind") == "data"
            and r.get("run_id") == start[0]["run_id"]]
    assert data and data[0]["combiner"] == "hot-cache"
    # No history: resolves off, and says so.
    led2 = tmp_path / "fresh.jsonl"
    rc = cli.main([str(corpus), "--combiner", "auto", "--ledger",
                   str(led2), "--format", "json", "--no-echo",
                   "--backend", "xla"])
    assert rc == 0
    assert "combiner: auto -> off" in capsys.readouterr().err


# -- end-to-end parity (pallas interpret: >=10 s each -> @slow) --------------


def _parity_configs():
    base = dict(chunk_bytes=1 << 15, table_capacity=1 << 10,
                backend="pallas", map_impl="fused")
    return (Config(**base), Config(**base, combiner="hot-cache"),
            Config(**base, combiner="salt"))


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["zipf", "uniform", "single"])
def test_wordcount_bit_identity(kind):
    """Acceptance: combiner-on (hot-cache AND salt) output is
    bit-identical to combiner-off on every distribution."""
    from mapreduce_tpu.models import wordcount

    data = _corpus(kind, 1 << 15)
    off, on, salt = (wordcount.count_words(data, c)
                     for c in _parity_configs())
    assert off == on == salt


@pytest.mark.slow
def test_ngram_bit_identity():
    """Gram family: 'salt' rides the packed gram build, 'hot-cache' is a
    documented no-op (position-ordered consumers cannot delete rows) —
    either way, bit-identical."""
    from mapreduce_tpu.models import wordcount

    data = _corpus("zipf", 1 << 15)
    off, on, salt = (wordcount.count_ngrams(data, 2, c)
                     for c in _parity_configs())
    assert off == on == salt


@pytest.mark.slow
def test_dense_corpus_spill_fallback_stays_exact():
    """Adversarial density (single-letter tokens) overflows the taller
    combiner windows: the chunk must fall back to the combiner-free pair
    path and stay exact — and the stats counters must report the
    fallback with zeroed combiner counters."""
    from mapreduce_tpu.models import wordcount
    from mapreduce_tpu.models.wordcount import _map_stream

    # 64 distinct single-byte tokens at density 0.5 over a 64 KiB chunk
    # (512-byte lane segments = one FULL 512-row combiner window): the
    # cache holds only 8 of the 64 per lane, so ~7/8 of ~256 ends per
    # window stay live — far past the 128-slot budget.  (Fewer distinct
    # words than cache slots would NOT spill: the cache absorbs the whole
    # stream, which is the point of the combiner, not a fallback
    # scenario; and a chunk smaller than 128*512 bytes leaves the tall
    # window mostly padding.)
    alphabet = bytes(range(0x21, 0x61))
    data = (b" ".join(bytes([b]) for b in alphabet) + b" ") * 600
    data = data[: 1 << 16]
    base = dict(chunk_bytes=1 << 16, table_capacity=1 << 10,
                backend="pallas", map_impl="fused")
    off = Config(**base)
    on = Config(**base, combiner="hot-cache")
    assert wordcount.count_words(data, off) == \
        wordcount.count_words(data, on)
    chunk = jnp.asarray(np.frombuffer(data, np.uint8))
    (_, stats) = jax.jit(
        lambda c: _map_stream(c, on, 1 << 10, with_stats=True))(chunk)
    assert int(stats.fallback_chunks) == 1
    assert int(stats.spill_rows) > 0
    assert int(stats.combiner_hits) == 0
    assert int(stats.combiner_flushes) == 0


@pytest.mark.slow
def test_stats_counters_land_in_data_record(tmp_path):
    """Streamed telemetered combiner run: the kernel counters ride the
    completion token into the per-run `data` record (combiner mode, hits
    / flushes / evicted, hit rate), and the result is byte-identical to
    the combiner-off streamed run."""
    from mapreduce_tpu import obs
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.runtime import executor

    data = _corpus("zipf", 1 << 15) * 4
    path = tmp_path / "corpus.txt"
    path.write_bytes(data)
    off, on, _ = _parity_configs()
    results = {}
    for name, cfg in (("off", off), ("on", on)):
        led = tmp_path / f"{name}.jsonl"
        tel = obs.Telemetry.create(ledger_path=str(led))
        rr = executor.run_job(WordCountJob(cfg), str(path), config=cfg,
                              telemetry=tel)
        tel.close()
        results[name] = jax.tree.map(np.asarray, rr.value)
        recs = list(obs.read_ledger(str(led)))
        data_rec = next(r for r in recs if r["kind"] == "data")
        assert data_rec["combiner"] == \
            ("hot-cache" if name == "on" else "off")
        if name == "on":
            assert data_rec["combiner_hits"] > 0
            assert data_rec["combiner_flushes"] > 0
            assert data_rec["combiner_hit_rate"] == pytest.approx(
                data_rec["combiner_hits"] / data_rec["tokens"], abs=1e-6)
            assert data_rec["combiner_rows_deleted"] == \
                data_rec["combiner_hits"] - data_rec["combiner_flushes"]
            start = next(r for r in recs if r["kind"] == "run_start")
            assert start["combiner"] == "hot-cache"
        else:
            assert data_rec["combiner_hits"] == 0
    for a, b in zip(jax.tree.leaves(results["off"]),
                    jax.tree.leaves(results["on"])):
        np.testing.assert_array_equal(a, b)
