"""bench.py evidence guards: the value-aware BENCH_LAST_GOOD record
(VERDICT r5 #2 — the round-5 clobber replayed exactly) and the
link-normalized streamed metric (VERDICT r5 #3).

Pure host tests: bench.py's guard functions are jax-free, so these run in
milliseconds and live in tier-1.
"""

import json
import os

import pytest

import bench

# The round-5 records, verbatim shapes (BENCHMARKS.md round 5 / VERDICT
# r5 weak #1): the 11:55Z run held streamed 0.0088; the later driver run
# reproduced the headline (0.4276 vs 0.4275) but streamed collapsed 3.1x
# to 0.0028 — and overwrote the record.  The guard must keep 0.0088.
R5_GOOD = {
    "metric": "zipf_wordcount_device_throughput", "input": "synthetic-zipf",
    "h2d_gbps": 0.0194, "value": 0.4275, "unit": "GB/s", "devices": 1,
    "backend": "tpu", "corpus_mb": 256.0, "streamed_ingest_gbps": 0.0088,
}
R5_CLOBBER = {
    "metric": "zipf_wordcount_device_throughput", "input": "synthetic-zipf",
    "h2d_gbps": 0.0496, "value": 0.4276, "unit": "GB/s", "devices": 1,
    "backend": "tpu", "corpus_mb": 256.0, "streamed_ingest_gbps": 0.0028,
}


@pytest.fixture
def last_good(tmp_path, monkeypatch):
    """Redirect the record file and scrub ambient BENCH_* knobs so the
    knob gate judges only what each test sets."""
    path = tmp_path / "BENCH_LAST_GOOD.json"
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(path))
    for k in list(os.environ):
        if k.startswith("BENCH_"):
            monkeypatch.delenv(k)
    return path


def _read(path):
    with open(path) as f:
        return json.load(f)


def test_r5_clobber_replay_leaves_best_streamed_intact(last_good, capsys):
    """THE regression this guard exists for: streamed 0.0088 -> 0.0028
    under an equal 0.4276 headline must leave the 0.0088 record intact."""
    bench._write_last_good(R5_GOOD)
    bench._write_last_good(R5_CLOBBER)
    rec = _read(last_good)
    assert rec["best"]["streamed"]["value"] == 0.0088  # evidence intact
    assert rec["best"]["headline"]["value"] == 0.4276  # better value kept
    assert rec["best"]["h2d"]["value"] == 0.0496
    assert rec["streamed_ingest_gbps"] == 0.0028  # last-run stays honest
    err = capsys.readouterr().err
    assert "refused" in err and "streamed" in err and "0.0088" in err


def test_force_last_good_rebaselines_deliberately(last_good, monkeypatch):
    bench._write_last_good(R5_GOOD)
    monkeypatch.setenv("BENCH_FORCE_LAST_GOOD", "1")
    bench._write_last_good(R5_CLOBBER)
    rec = _read(last_good)
    assert rec["best"]["streamed"]["value"] == 0.0028  # operator-owned


def test_mild_regression_keeps_best_without_refusal(last_good, capsys):
    """<=25% down is relay noise, not a regression: best keeps the max,
    nothing is logged as refused."""
    bench._write_last_good(R5_GOOD)
    mild = {**R5_GOOD, "streamed_ingest_gbps": 0.0080}
    bench._write_last_good(mild)
    rec = _read(last_good)
    assert rec["best"]["streamed"]["value"] == 0.0088
    assert "refused" not in capsys.readouterr().err


def test_legacy_record_seeds_best_ledger(last_good):
    """A pre-round-6 (value-blind) file's evidence joins the per-metric
    ledger instead of being silently discarded."""
    legacy = {**R5_GOOD, "recorded_at": "2026-08-01T11:55:00Z"}
    last_good.write_text(json.dumps(legacy))
    bench._write_last_good(R5_CLOBBER)
    rec = _read(last_good)
    assert rec["best"]["streamed"]["value"] == 0.0088
    assert rec["best"]["streamed"]["recorded_at"] == "2026-08-01T11:55:00Z"


def test_ab_knob_write_refused_with_stderr_trace(last_good, capsys,
                                                 monkeypatch):
    """Measurement-altering BENCH_* knobs refuse the write — and say so on
    stderr (ADVICE r5: a missing record update must be diagnosable)."""
    monkeypatch.setenv("BENCH_SORT_IMPL", "radix_partition")
    bench._write_last_good(R5_GOOD)
    assert not last_good.exists()
    err = capsys.readouterr().err
    assert "refused" in err and "BENCH_SORT_IMPL" in err


def test_map_impl_knob_write_refused_with_stderr_trace(last_good, capsys,
                                                       monkeypatch):
    """BENCH_MAP_IMPL (the ISSUE 6 fused-map A/B knob) is measurement-
    altering: the class-based refusal must cover it without it ever being
    listed anywhere — the 'future knob refused by default' guarantee."""
    monkeypatch.setenv("BENCH_MAP_IMPL", "fused")
    bench._write_last_good(R5_GOOD)
    assert not last_good.exists()
    err = capsys.readouterr().err
    assert "refused" in err and "BENCH_MAP_IMPL" in err


def test_geometry_knob_write_refused_with_stderr_trace(last_good, capsys,
                                                       monkeypatch):
    """BENCH_GEOMETRY (the ISSUE 12 searched-geometry A/B knob) is
    measurement-altering: the class-based refusal covers it by default,
    like every other A/B knob."""
    monkeypatch.setenv("BENCH_GEOMETRY", "tall512")
    bench._write_last_good(R5_GOOD)
    assert not last_good.exists()
    err = capsys.readouterr().err
    assert "refused" in err and "BENCH_GEOMETRY" in err


def test_probe_knobs_are_headline_safe(last_good, monkeypatch):
    """BENCH_RETRY_BUDGET_S / BENCH_PROBE_TIMEOUT_S shape pre-measurement
    reachability retries only (measurement-neutral, ADVICE r5): a run
    under them must still record."""
    monkeypatch.setenv("BENCH_RETRY_BUDGET_S", "900")
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT_S", "60")
    bench._write_last_good(R5_GOOD)
    assert _read(last_good)["value"] == 0.4275


def test_non_zipf_corpus_refused_with_stderr_trace(last_good, capsys):
    bench._write_last_good({**R5_GOOD, "input": "synthetic-markup"})
    assert not last_good.exists()
    assert "refused" in capsys.readouterr().err


# -- the link-normalized streamed metric (VERDICT r5 #3) ---------------------


def test_streamed_ratio_on_checked_in_fixture():
    """The r5 driver capture, from the checked-in BENCH_r05.json: the
    tunnel-invariant form of its streamed row is 0.0028/0.0496."""
    fixture = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_r05.json")
    with open(fixture) as f:
        parsed = json.load(f)["parsed"]
    assert bench._streamed_ratio(parsed) == round(0.0028 / 0.0496, 4)


def test_streamed_ratio_missing_or_zero_legs():
    assert bench._streamed_ratio({}) is None
    assert bench._streamed_ratio({"h2d_gbps": 0.02}) is None
    assert bench._streamed_ratio({"streamed_ingest_gbps": 0.01}) is None
    assert bench._streamed_ratio(
        {"streamed_ingest_gbps": 0.01, "h2d_gbps": 0.0}) is None
    assert bench._streamed_ratio(
        {"streamed_ingest_gbps": 0.0088, "h2d_gbps": 0.0194}) == 0.4536


def test_time_ratio_zero_and_inverse():
    """A near-hung streamed pass rounds the GB/s ratio to 0.0; the time
    form must come back None (skipped from the result), never a
    ZeroDivisionError after all the timed work."""
    assert bench._time_ratio(None) is None
    assert bench._time_ratio(0.0) is None
    assert bench._time_ratio(0.0565) == round(1.0 / 0.0565, 4)
    assert bench._time_ratio(1.0) == 1.0


# -- the streamed time-ratio record (ISSUE 5: lower is better) ---------------


def test_time_ratio_lower_is_better_record(last_good, capsys):
    """streamed_vs_h2d_time_ratio (streamed wall over the same-run H2D
    floor) records best-known with LOWER winning; mild (<25%) worsening
    keeps the best silently."""
    bench._write_last_good({**R5_GOOD, "streamed_vs_h2d_time_ratio": 2.0})
    bench._write_last_good({**R5_GOOD, "streamed_vs_h2d_time_ratio": 1.4})
    assert _read(last_good)["best"]["streamed_ratio"]["value"] == 1.4
    bench._write_last_good({**R5_GOOD, "streamed_vs_h2d_time_ratio": 1.5})
    assert _read(last_good)["best"]["streamed_ratio"]["value"] == 1.4
    assert "refused" not in capsys.readouterr().err


def test_time_ratio_regression_refused_with_trace(last_good, capsys):
    """>25% WORSENING (ratio growing) under an equal config refuses the
    best-known displacement and says so on stderr — the same guard the
    GB/s metrics carry, direction-flipped."""
    bench._write_last_good({**R5_GOOD, "streamed_vs_h2d_time_ratio": 1.4})
    bench._write_last_good({**R5_GOOD, "streamed_vs_h2d_time_ratio": 2.0})
    rec = _read(last_good)
    assert rec["best"]["streamed_ratio"]["value"] == 1.4  # evidence intact
    assert rec["streamed_vs_h2d_time_ratio"] == 2.0  # last-run stays honest
    err = capsys.readouterr().err
    assert "refused" in err and "streamed_ratio" in err


def test_time_ratio_force_rebaseline(last_good, monkeypatch):
    bench._write_last_good({**R5_GOOD, "streamed_vs_h2d_time_ratio": 1.4})
    monkeypatch.setenv("BENCH_FORCE_LAST_GOOD", "1")
    bench._write_last_good({**R5_GOOD, "streamed_vs_h2d_time_ratio": 2.0})
    assert _read(last_good)["best"]["streamed_ratio"]["value"] == 2.0
