"""Config.sort_impl='radix*': the Pallas radix partition/sort vs XLA.

Contract under test (ISSUE 3 acceptance): the radix path is BIT-IDENTICAL
to the XLA sort path — stable tie order included — under interpret-mode
oracle parity, for wordcount, top-k, and n-gram states; adversarial bucket
skew falls back to the XLA sort exactly; config validation refuses the
impossible combinations.

Geometry and compile-budget notes (tier-1 runs on a one-core box):

* An autouse fixture shrinks the kernel to bits=1 / block_rows=32 — kernel
  jaxpr size, and so CPU compile cost, scales with B x log2(block_rows)
  while the SEMANTICS are geometry-free.  At that geometry the slab cap
  clamps to block_rows, so the partition branch is structurally spill-free
  and every end-to-end test deterministically exercises the radix path
  (never the fallback); the production geometry runs in the @slow tier.
* The end-to-end tests share ONE module corpus and ONE Config so they
  share one compiled program (the jit cache persists within a module).
  Tests that change geometry beyond the autouse fixture must also change
  a static Config field (capacity) — identical (shapes, config) under
  different monkeypatched geometry would replay a stale program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.ops.pallas import radix as radix_ops
from mapreduce_tpu.utils import oracle

CAP = 4096


def _cfg(sort_impl, **kw):
    kw.setdefault("chunk_bytes", 128 * (2 * 32 + 2))
    kw.setdefault("table_capacity", CAP)
    return Config(backend="pallas", sort_impl=sort_impl, **kw)


def _interpret():
    from tests.conftest import pallas_interpret_mode

    return pallas_interpret_mode()


@pytest.fixture(autouse=True)
def _small_radix_geometry(monkeypatch):
    """Shrink the kernel for CPU-interpret compile budgets (module
    docstring); bits/block_rows/slack are None-sentinel-resolved at call
    time, so the module constants are the single override point."""
    monkeypatch.setattr(radix_ops, "DEFAULT_BITS", 1)
    monkeypatch.setattr(radix_ops, "DEFAULT_BLOCK_ROWS", 32)


@pytest.fixture(scope="module")
def corpus():
    """UNIFORM word draws (not the zipf fixture): hash-uniform keys keep
    every per-(block, lane, bucket) occupancy far inside the slab budget,
    so the partition branch runs for certain (no silent fallback making
    the parity vacuous)."""
    r = np.random.default_rng(7)
    words = [f"w{i:x}" for i in range(150)]
    return " ".join(words[int(i)]
                    for i in r.integers(0, 150, size=3000)).encode()


def _mixed_planes(rng, n=5000, vocab=60, dead_frac=0.3, poison_frac=0.01):
    """A realistic packed stream: duplicate hashed keys (tie-order fodder),
    position-ascending packed, dead filler, poison rows."""
    keys = rng.integers(0, 0xFFFFFFF0, size=(vocab, 2), dtype=np.uint32)
    idx = rng.integers(0, vocab, size=n)
    khi = keys[idx, 0].copy()
    klo = keys[idx, 1].copy()
    pck = ((np.arange(n, dtype=np.uint64) << 6) | 5).astype(np.uint32)
    dead = rng.random(n) < dead_frac
    khi[dead] = 0xFFFFFFFF
    klo[dead] = 0xFFFFFFFF
    pck[dead] = 0xFFFFFFFF
    pois = ~dead & (rng.random(n) < poison_frac)
    khi[pois] = 0xFFFFFFFF
    klo[pois] = 0xFFFFFFFE  # the reserved poison key (sent, sent-1)
    pck[pois] = (np.arange(n, dtype=np.uint64)[pois] << 6).astype(np.uint32)
    return tuple(jnp.asarray(x) for x in (khi, klo, pck))


@pytest.mark.parametrize("impl", ["radix_partition", "radix"])
def test_radix_sort3_bit_identical_to_lax_sort(rng, impl):
    """The core contract at the sort seam: exact array equality with
    jax.lax.sort(num_keys=3) — duplicate keys' tie order (by packed),
    poison-segment order, and the trailing dead-filler segment included."""
    khi, klo, pck = _mixed_planes(rng)
    expect = jax.lax.sort((khi, klo, pck), num_keys=3)
    with _interpret():
        got = radix_ops.radix_sort3(khi, klo, pck, impl=impl, bits=2,
                                    block_rows=32)
    for g, e in zip(got, expect):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(e))


def test_radix_spill_counts_and_falls_back_exactly(rng):
    """All-one-bucket skew: the partition slab overflows, the spill scalar
    says so, and the lax.cond fallback reproduces the XLA sort exactly."""
    n = 5000
    khi = jnp.full((n,), jnp.uint32(0x12345678))
    klo = jnp.full((n,), jnp.uint32(0x9ABCDEF0))
    pck = jnp.asarray(((np.arange(n, dtype=np.uint64) << 6) | 3)
                      .astype(np.uint32))
    with _interpret():
        # Direct kernel-level check: one hot bucket past an 8-row budget.
        rows = jnp.asarray(np.full((64, 128), 0x12345678, np.uint32))
        _, _, _, hist, spill = radix_ops._partition_level(
            rows, rows, jnp.zeros_like(rows), shift=30, bits=2,
            block_rows=64, cap=8, n_groups=1, interpret=True)
        assert int(spill) > 0
        assert int(np.asarray(hist).sum()) == 64 * 128  # counted, not lost
        # End-to-end: same skew through radix_sort3 -> fallback, bit-exact.
        got = radix_ops.radix_sort3(khi, klo, pck, impl="radix_partition",
                                    bits=2, block_rows=32, slab_slack=1)
    expect = jax.lax.sort((khi, klo, pck), num_keys=3)
    for g, e in zip(got, expect):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(e))


@pytest.mark.slow  # ~37 s on the one-core box; tier-1 budget rule
def test_wordcount_radix_matches_oracle(corpus):
    """End-to-end wordcount through the radix aggregation seam: words,
    counts, insertion (first-occurrence) order, totals, and accounting all
    match the host oracle — the tie-order contract made user-visible."""
    with _interpret():
        r = wordcount.count_words(corpus, _cfg("radix_partition"))
    expected = oracle.word_counts(corpus)
    assert list(r.as_dict()) == list(expected)  # insertion order included
    assert r.as_dict() == expected
    assert r.total == oracle.total_count(corpus)
    assert r.dropped_count == 0


def test_topk_radix_matches_oracle(corpus):
    """top_k over a radix-built table: count-descending, ties by first
    occurrence — checked against the host-derived expectation.  (Same
    corpus + Config as the parity test: the device program is a cache
    hit; only top_k is new work.)"""
    with _interpret():
        tbl = wordcount.count_table(corpus, _cfg("radix_partition"))
        kt = table_ops.top_k(tbl, 16)
    counts = np.asarray(kt.count).astype(np.int64) \
        + (np.asarray(kt.count_hi).astype(np.int64) << 32)
    pos = np.asarray(kt.pos_lo)
    length = np.asarray(kt.length)
    got = [(bytes(corpus[int(p): int(p) + int(ln)]), int(c))
           for p, ln, c in zip(pos, length, counts) if c > 0]
    counts_by_word = oracle.word_counts(corpus)
    first_idx = {w: i for i, w in enumerate(counts_by_word)}
    expected = sorted(counts_by_word.items(),
                      key=lambda wc: (-wc[1], first_idx[wc[0]]))[:16]
    assert got == expected
    # Evicted mass is accounted: the table still explains every token.
    assert int(np.asarray(kt.total_count())) == oracle.total_count(corpus)


def test_ngram_radix_bit_identical_to_xla_impl(corpus):
    """Bigram tables through the packed gram build: radix vs XLA sort
    implementations must agree bit-for-bit (spans, counts, order)."""
    with _interpret():
        a = wordcount.count_ngrams(corpus, 2, _cfg("xla"))
        b = wordcount.count_ngrams(corpus, 2, _cfg("radix_partition"))
    assert a.words == b.words
    assert a.counts == b.counts
    assert a.total == b.total
    assert a.dropped_count == b.dropped_count


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["sort3", "stable2"])
def test_radix_serves_both_sort_modes(rng, mode):
    """One radix implementation serves sort3 (its definition) and stable2
    (ties by packed == tie order by position under the position-ordered
    input precondition): from_packed_rows output tables must be identical
    across (mode, impl) for a position-ordered packed stream."""
    n = 4096
    keys = rng.integers(0, 0xFFFFFFF0, size=(40, 2), dtype=np.uint32)
    idx = rng.integers(0, 40, size=n)
    khi = jnp.asarray(keys[idx, 0])
    klo = jnp.asarray(keys[idx, 1])
    pck = jnp.asarray(((np.arange(n, dtype=np.uint64) << 6) | 4)
                      .astype(np.uint32))
    total = jnp.uint32(n)
    with _interpret():
        base = table_ops.from_packed_rows(khi, klo, pck, total, 256, 0,
                                          sort_mode=mode, sort_impl="xla")
        radix = table_ops.from_packed_rows(khi, klo, pck, total, 256, 0,
                                           sort_mode=mode,
                                           sort_impl="radix_partition")
    for a, b in zip(base, radix):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sort_impl_validation():
    with pytest.raises(ValueError, match="sort_impl"):
        Config(sort_impl="bogus")
    with pytest.raises(ValueError, match="segmin"):
        Config(sort_mode="segmin", sort_impl="radix")
    with pytest.raises(ValueError, match="segmin"):
        table_ops.from_packed_rows(
            jnp.zeros((8,), jnp.uint32), jnp.zeros((8,), jnp.uint32),
            jnp.full((8,), 0xFFFFFFFF, dtype=jnp.uint32), jnp.uint32(0),
            4, 0, sort_mode="segmin", sort_impl="radix")
    with pytest.raises(ValueError, match="sort_impl"):
        table_ops.from_packed_rows(
            jnp.zeros((8,), jnp.uint32), jnp.zeros((8,), jnp.uint32),
            jnp.full((8,), 0xFFFFFFFF, dtype=jnp.uint32), jnp.uint32(0),
            4, 0, sort_impl="bogus")
    with pytest.raises(ValueError, match="impl"):
        radix_ops.radix_sort3(jnp.zeros((8,), jnp.uint32),
                              jnp.zeros((8,), jnp.uint32),
                              jnp.zeros((8,), jnp.uint32), impl="bogus")
    # The production default is pinned by the round-6 pricing note.
    assert Config().sort_impl == "xla"


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["radix_partition", "radix"])
def test_radix_sort3_production_geometry(rng, impl):
    """Sort-seam parity at the PRODUCTION kernel geometry (bits=3,
    block_rows=256, slack 4) — the tier-1 params shrink it for compile
    budget."""
    khi, klo, pck = _mixed_planes(rng, n=20000)
    expect = jax.lax.sort((khi, klo, pck), num_keys=3)
    with _interpret():
        got = radix_ops.radix_sort3(khi, klo, pck, impl=impl, bits=3,
                                    block_rows=256, slab_slack=4)
    for g, e in zip(got, expect):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(e))


@pytest.mark.slow
def test_wordcount_radix_full_mode_matches_xla_impl(rng):
    """The 2-level 'radix' mode end to end against the XLA impl (the
    tier-1 e2e tests run radix_partition; the 2-level path's sort-seam
    parity is in tier-1 above)."""
    words = [f"w{i:x}" for i in range(200)]
    corpus = " ".join(words[int(i)]
                      for i in rng.integers(0, 200, size=4000)).encode()
    with _interpret():
        a = wordcount.count_words(corpus, _cfg("xla", table_capacity=2048))
        b = wordcount.count_words(corpus,
                                  _cfg("radix", table_capacity=2048))
    assert a.words == b.words
    assert a.counts == b.counts
    assert a.total == b.total
    assert a.dropped_count == b.dropped_count
    assert a.as_dict() == oracle.word_counts(corpus)


@pytest.mark.slow
def test_wordcount_radix_hot_key_spills_into_exact_fallback(monkeypatch):
    """A corpus that is ONE word repeated concentrates every live row in a
    single digit bucket — the documented adversarial case for static
    slabs.  With slack shrunk below the hot-key mass the spill cond must
    take the fallback and still deliver exact counts.  (Fresh capacity:
    same shapes under different monkeypatched geometry must not reuse a
    cached program — module docstring.)"""
    monkeypatch.setattr(radix_ops, "DEFAULT_SLAB_SLACK", 1)
    corpus = b"aaa " * 1500
    with _interpret():
        r = wordcount.count_words(
            corpus, _cfg("radix_partition", table_capacity=CAP // 2))
    assert r.as_dict() == oracle.word_counts(corpus)
    assert r.total == 1500


@pytest.mark.slow
def test_overlong_rescue_radix_matches_xla_impl():
    """Overlong (>W) tokens — one crossing a lane seam — must be rescued
    identically under the radix sort: poison rows keep position order in
    the radix output (they sort by packed within the reserved-key
    segment), so the rescue extraction sees the same slice."""
    w = 32
    n = 128 * (2 * w + 2)
    seg = n // 128
    buf = np.full(n, 0x20, dtype=np.uint8)
    buf[seg - 20: seg + 20] = ord("u")  # crosses the first lane seam
    buf[10:50] = ord("v")
    words = b"aa bb cc aa "
    buf[60:60 + len(words)] = np.frombuffer(words, dtype=np.uint8)
    data = bytes(buf)
    with _interpret():
        a = wordcount.count_words(data, _cfg("xla", chunk_bytes=n))
        b = wordcount.count_words(data,
                                  _cfg("radix_partition", chunk_bytes=n))
    assert a.words == b.words
    assert a.counts == b.counts
    assert a.total == b.total
    assert a.dropped_count == b.dropped_count == 0
    assert a.as_dict() == oracle.word_counts(data)
