"""Host-side exact-recount verification (the key-collision detection path).

VERDICT r4 missing #4: the 64-bit key-collision envelope needed (1) stated
arithmetic (ops/table.py module docstring), (2) a detection tool, and (3) a
test that INJECTS a collision and shows the failure mode is visible.  The
injection here collapses the hash finalizer to 4 bits, guaranteeing many
distinct words share a key — exactly the (astronomically rare) real failure,
made reproducible.
"""


import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.ops import tokenize as tok_ops
from mapreduce_tpu.utils import oracle
from mapreduce_tpu.utils.verify import recount_exact, verify_result
from tests.conftest import make_corpus


def test_recount_exact_matches_oracle(tmp_path, rng):
    corpus = make_corpus(rng, n_words=5000, vocab=200)
    p = tmp_path / "c.txt"
    p.write_bytes(corpus)
    want = oracle.word_counts(corpus)
    some = list(want)[:50]
    got = recount_exact(str(p), some, chunk_bytes=512)  # many carry seams
    assert got == {w: want[w] for w in some}


def test_recount_exact_multi_file_and_unterminated_tail(tmp_path):
    a, b = tmp_path / "a.txt", tmp_path / "b.txt"
    a.write_bytes(b"x y x")  # no trailing separator: tail token counts
    b.write_bytes(b"x z")
    got = recount_exact([str(a), str(b)], [b"x", b"y", b"z"])
    assert got == {b"x": 3, b"y": 1, b"z": 1}


@pytest.mark.slow
def test_verify_result_passes_on_honest_run(tmp_path, rng):
    corpus = make_corpus(rng, n_words=4000, vocab=100)
    p = tmp_path / "c.txt"
    p.write_bytes(corpus)
    r = wordcount.count_words(corpus, Config(chunk_bytes=1 << 15,
                                             table_capacity=4096))
    assert verify_result(r.words, r.counts, str(p), sample=32) == []


def test_injected_collision_is_detected(tmp_path, rng, monkeypatch):
    """Collapse the hash finalizer to 4 bits: distinct words now share
    64-bit keys, the table silently merges them (summed counts under one
    identity) — and the exact recount flags it."""
    corpus = make_corpus(rng, n_words=3000, vocab=300)
    p = tmp_path / "c.txt"
    p.write_bytes(corpus)

    real_fmix = tok_ops._fmix32
    monkeypatch.setattr(tok_ops, "_fmix32", lambda x: real_fmix(x) & 0xF)
    # A chunk size no other test uses: the jit cache must not serve a
    # trace made with the honest hash.
    r = wordcount.count_words(corpus, Config(chunk_bytes=(1 << 15) + 128,
                                             table_capacity=4096,
                                             backend="xla"))
    monkeypatch.undo()

    # The collision itself: fewer reported identities than true distinct,
    # but totals conserved (merging never loses occurrences).
    true_counts = oracle.word_counts(corpus)
    assert len(r.words) < len(true_counts)
    assert r.total == sum(true_counts.values())

    mismatches = verify_result(r.words, r.counts, str(p), sample=64)
    assert mismatches, "collision went undetected"
    for w, reported, true in mismatches:
        # The absorber's reported count exceeds its exact recount.
        assert reported > true
