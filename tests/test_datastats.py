"""Data-plane telemetry tests (ISSUE 8): on-device spill/rescue/skew/
occupancy counters threaded from the map path through the Engine's stats
mode into `group`/`data` ledger records, the host-side aggregator's
arithmetic, the jax-free data-health classifier, byte-identity of
telemetered results, the per-group overhead bound, and the flight
recorder's data snapshot (fused map path included)."""

import json
import os
import time

import numpy as np
import pytest

from mapreduce_tpu import obs
from mapreduce_tpu.config import Config
from mapreduce_tpu.models.wordcount import WordCountJob
from mapreduce_tpu.obs import datahealth
from mapreduce_tpu.ops import datastats
from mapreduce_tpu.parallel.mesh import data_mesh
from mapreduce_tpu.runtime import executor

from conftest import make_corpus

CFG = Config(chunk_bytes=512, table_capacity=2048)


def _streamed(tmp_path, corpus: bytes, cfg=CFG, telemetry=True, name="c"):
    path = tmp_path / f"{name}.txt"
    path.write_bytes(corpus)
    if not telemetry:
        rr = executor.run_job(WordCountJob(cfg), str(path), cfg,
                              mesh=data_mesh(4))
        return rr, None
    led = str(tmp_path / f"{name}.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        rr = executor.run_job(WordCountJob(cfg), str(path), cfg,
                              mesh=data_mesh(4), telemetry=tel)
    return rr, list(obs.read_ledger(led))


@pytest.fixture(scope="module")
def zipf_run(tmp_path_factory, rng):
    """One telemetered streamed run over a Zipf corpus (module-scoped:
    streamed CPU runs are the expensive part of this module)."""
    tmp = tmp_path_factory.mktemp("ds_zipf")
    corpus = make_corpus(np.random.default_rng(20260804), 2500, 150)
    rr, recs = _streamed(tmp, corpus)
    return corpus, rr, recs, tmp


# -- executor emission -------------------------------------------------------


@pytest.mark.smoke
def test_data_record_and_group_data(zipf_run):
    """ISSUE 8 tentpole: every retired group's record carries its data
    dict, exactly one per-run `data` record lands before run_end, and its
    totals agree with the RESULT's own accounting (tokens and dropped are
    the same numbers the recovered WordCountResult reports)."""
    corpus, rr, recs, _ = zipf_run
    kinds = [r["kind"] for r in recs]
    assert kinds.count("data") == 1
    assert kinds.index("data") < kinds.index("run_end")
    groups = [r for r in recs if r["kind"] == "group"]
    assert groups and all("data" in g for g in groups)
    for g in groups:
        assert g["data"]["chunks"] >= 1
        assert 0.0 <= g["data"]["occupancy"] <= 1.0
    data = next(r for r in recs if r["kind"] == "data")
    # Totals vs the merged result: tokens (incl. dropped) and dropped
    # accounting must be the very numbers the result carries.
    tbl = rr.value
    assert data["tokens"] == int(np.asarray(tbl.total_count()))
    du, dc = tbl.dropped_totals()
    assert data["dropped_tokens"] == dc and data["dropped_cumulative"] == dc
    assert data["dropped_uniques"] == du
    # One chunk mapped per device per step.
    steps = sum(r["steps"] for r in recs if r["kind"] == "step")
    assert data["chunks"] == 4 * steps
    assert data["groups"] == len(groups)
    assert data["backend"] == "xla" and data["map_impl"] == "split"
    # capacity = per-device capacity x devices; occupancy consistent.
    assert data["capacity"] == 2048 * 4
    assert data["table_occupancy"] == round(
        data["table_valid"] / data["capacity"], 4)
    # Zipf corpus: the top key carries a fat share; gauges reflect it.
    assert data["top_count"] > 0 and data["top_mass"] > 0.05


@pytest.mark.smoke
def test_results_byte_identical_with_telemetry(zipf_run, tmp_path):
    """ISSUE 8 acceptance: data telemetry ON changes the step program's
    outputs (a stats pytree rides along) but never the results — the
    merged state is byte-identical to the untelemetered run."""
    import jax

    corpus, rr, _, _ = zipf_run
    rr2, _ = _streamed(tmp_path, corpus, telemetry=False)
    a, b = jax.tree.leaves(rr.value), jax.tree.leaves(rr2.value)
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert (np.asarray(x) == np.asarray(y)).all()


def test_zipf_vs_uniform_health_verdicts(zipf_run, tmp_path):
    """ISSUE 8 acceptance: a Zipf-hot-key run and a uniform run produce
    DISTINGUISHABLE data-health verdicts from the ledger alone, on the
    CPU path (no device hardware)."""
    _, _, zipf_recs, _ = zipf_run
    zipf_health = datahealth.classify_run(zipf_recs)
    assert zipf_health is not None
    assert any(f["flag"] == "skew-hot" for f in zipf_health["flags"]), \
        zipf_health
    # Uniform corpus: every word equally likely -> top mass ~ 1/vocab.
    uniform = " ".join(f"u{i % 150:03x}" for i in range(2500)).encode()
    _, recs = _streamed(tmp_path, uniform, name="uniform")
    uni_health = datahealth.classify_run(recs)
    assert uni_health is not None
    assert uni_health["verdict"] == "clean", uni_health
    assert uni_health["signals"]["top_mass"] < 0.02
    assert zipf_health["signals"]["top_mass"] \
        > 5 * uni_health["signals"]["top_mass"]


def test_registry_carries_data_instruments(zipf_run):
    """Retirement mirrors the data counters/gauges into the registry
    (`data.*`), next to the PR-7 lifecycle instruments."""
    _, _, _, _ = zipf_run
    snap = obs.get_registry().snapshot()
    assert "data.table_occupancy" in snap["gauges"]
    assert "data.top_mass" in snap["gauges"]
    assert 0.0 < snap["gauges"]["data.top_mass"] <= 1.0


def test_group_record_with_data_overhead_under_1ms(tmp_path):
    """ISSUE 8 acceptance (extends the PR-7 bound): the full per-group
    retirement path — host stats reduce + aggregator fold + registry +
    group record with data + JSONL append — averages far under 1 ms."""
    led = str(tmp_path / "overhead.jsonl")
    n = 300
    agg = datastats.DataAggregator(capacity=2048, devices=4, backend="xla",
                                   map_impl="split")
    host = datastats.DataStats(*[np.ones(4, np.uint32) for _ in
                                 datastats.DataStats._fields])
    with obs.Telemetry.create(ledger_path=led) as tel:
        t0 = time.perf_counter()
        for i in range(n):
            life = {"step_first": i, "step_last": i, "steps": 1,
                    "group_bytes": 2048,
                    "staged_at": time.perf_counter(),
                    "dispatched_at": time.perf_counter()}
            data = agg.group_data(host)
            tel.note_data(agg.snapshot())
            executor._group_record(tel, True, life,
                                   token_ready_at=life["staged_at"] + 0.01,
                                   retired_at=life["staged_at"] + 0.011,
                                   wait_s=0.005, data=data)
        dt = time.perf_counter() - t0
    assert dt / n < 1e-3, f"{1e3 * dt / n:.3f} ms per group with data"
    recs = list(obs.read_ledger(led, kind="group"))
    assert len(recs) == n and all("data" in r for r in recs)


# -- aggregator arithmetic ---------------------------------------------------


def test_aggregator_hand_arithmetic():
    """DataAggregator against arithmetic done by hand: counters sum over
    devices AND groups, 64-bit lane pairs reconstruct exactly, the top
    count is the cross-device max, and window occupancy divides tokens by
    chunks x slot capacity."""
    agg = datastats.DataAggregator(capacity=1000, devices=2, backend="pallas",
                                   map_impl="split",
                                   slot_capacity_per_chunk=1000)

    def stats(**kw):
        vals = {f: np.zeros(2, np.uint32) for f in datastats.DataStats._fields}
        for k, v in kw.items():
            vals[k] = np.asarray(v, np.uint32)
        return datastats.DataStats(**vals)

    g1 = agg.group_data(stats(chunks=[1, 1], overlong=[3, 4],
                              rescued=[2, 3], dropped_tokens=[1, 1],
                              fallback_chunks=[1, 0], spill_rows=[10, 0],
                              table_valid=[100, 200],
                              total_lo=[500, 600], top_lo=[50, 90],
                              dropped_lo=[1, 1]))
    assert g1["chunks"] == 2 and g1["overlong"] == 7 and g1["rescued"] == 5
    assert g1["fallback_chunks"] == 1 and g1["spill_rows"] == 10
    assert g1["occupancy"] == round(300 / 2000, 4)
    assert g1["top_mass"] == round(90 / 1100, 6)
    # A 64-bit gauge: hi lane = 1 -> +2**32 on that device.
    g2 = agg.group_data(stats(chunks=[1, 1], table_valid=[150, 250],
                              total_lo=[700, 800], total_hi=[1, 0],
                              top_lo=[60, 95], dropped_lo=[2, 2]))
    assert g2["chunks"] == 2
    rec = agg.run_record()
    assert rec["chunks"] == 4 and rec["groups"] == 2
    assert rec["overlong"] == 7 and rec["rescued"] == 5
    assert rec["tokens"] == 700 + 800 + (1 << 32)
    assert rec["top_count"] == 95 and rec["table_valid"] == 400
    assert rec["dropped_cumulative"] == 4
    assert rec["table_occupancy"] == round(400 / 2000, 4)
    # 4 chunks x 1000 slots; tokens >> would mean dense windows.
    assert rec["window_slot_capacity"] == 4000
    assert rec["window_occupancy"] == round(rec["tokens"] / 4000, 4)


def test_window_slot_capacity_geometry():
    """The stable2 window-occupancy denominator from config geometry:
    blocks(ceil(seg/block_rows)) x 128 lanes x slots; None off the
    compact pallas path."""
    cfg = Config(chunk_bytes=128 * 384, table_capacity=512,
                 backend="pallas")
    # seg = 384, block_rows = 384 (stable2) -> 1 block x 128 x 128 slots.
    assert datastats.window_slot_capacity(cfg) == 1 * 128 * 128
    assert datastats.window_slot_capacity(
        Config(chunk_bytes=1 << 20, table_capacity=512,
               backend="xla")) is None


# -- classifier rules --------------------------------------------------------


def _base_data(**kw):
    d = {"chunks": 100, "tokens": 100000, "overlong": 0, "rescued": 0,
         "dropped_tokens": 0, "dropped_uniques": 0, "rescue_invocations": 0,
         "rescue_escalations": 0, "fallback_chunks": 0, "spill_rows": 0,
         "table_valid": 5000, "top_count": 900, "capacity": 100000,
         "table_occupancy": 0.05}
    d.update(kw)
    return d


def test_classifier_clean_and_each_verdict():
    assert datahealth.classify(_base_data())["verdict"] == "clean"
    assert datahealth.classify(_base_data(
        fallback_chunks=10))["verdict"] == "spill-bound"
    assert datahealth.classify(_base_data(
        overlong=500, rescued=400, dropped_tokens=100))["verdict"] \
        == "rescue-heavy"
    assert datahealth.classify(_base_data(
        rescue_escalations=1))["verdict"] == "rescue-heavy"
    assert datahealth.classify(_base_data(
        top_count=20000))["verdict"] == "skew-hot"
    assert datahealth.classify(_base_data(
        window_occupancy=0.1))["verdict"] == "occupancy-starved"
    assert datahealth.classify(_base_data(
        dropped_uniques=5))["verdict"] == "table-pressure"
    # Priority: spill-bound outranks everything else that fires with it.
    both = datahealth.classify(_base_data(fallback_chunks=10,
                                          top_count=20000))
    assert both["verdict"] == "spill-bound"
    assert {f["flag"] for f in both["flags"]} == {"spill-bound", "skew-hot"}


def test_classifier_tolerates_missing_fields():
    """Forward compat: an empty/partial/future data record classifies
    (signals None where underived), never raises."""
    out = datahealth.classify({})
    assert out["verdict"] == "clean" and out["signals"]["top_mass"] is None
    out = datahealth.classify({"tokens": 10, "top_count": 8,
                               "quantum_flux": object()})
    assert out["verdict"] == "skew-hot"


def test_classify_run_selects_run_and_degrades():
    recs = [{"kind": "run_start", "run_id": "a"},
            {"kind": "data", "run_id": "a", "tokens": 100, "top_count": 50,
             "chunks": 1},
            {"kind": "data", "run_id": "b", "tokens": 100, "top_count": 1,
             "chunks": 1}]
    assert datahealth.classify_run(recs)["verdict"] == "skew-hot"
    assert datahealth.classify_run(recs, run_id="b")["verdict"] == "clean"
    assert datahealth.classify_run([{"kind": "step"}]) is None


# -- device-side counters (pallas interpret) ---------------------------------


@pytest.mark.slow
def test_map_stream_stats_pallas_counters():
    """The pallas split path's counters, in interpret mode: one overlong
    token is detected, the rescue cond fires and recovers it exactly, and
    the table update stays bit-identical to the stats-off trace."""
    import jax

    from mapreduce_tpu.models import wordcount as wc
    from tests.conftest import pallas_interpret_mode

    cfg = Config(chunk_bytes=128 * 66, table_capacity=512, backend="pallas",
                 compact_slots=88, sort_mode="sort3")
    data = b"averyoverlongtokenpastthewindowwidthxxxxxx " + b"a b c " * 200
    padded = wc._pad_for_backend(data, cfg)
    with pallas_interpret_mode():
        upd, stats = wc._map_stream(jax.device_put(padded), cfg, 512,
                                    with_stats=True)
        plain = wc._map_stream(jax.device_put(padded), cfg, 512)
    for x, y in zip(jax.tree.leaves(upd), jax.tree.leaves(plain)):
        assert (np.asarray(x) == np.asarray(y)).all()
    s = {f: int(np.asarray(v)) for f, v in zip(stats._fields, stats)}
    assert s["chunks"] == 1
    assert s["overlong"] == 1 and s["rescued"] == 1
    assert s["rescue_invocations"] == 1 and s["rescue_escalations"] == 0
    assert s["dropped_tokens"] == 0 and s["fallback_chunks"] == 0


# -- flight recorder: data snapshot on the fused map path --------------------


@pytest.mark.slow
def test_flight_dump_on_fused_path_carries_data_health(tmp_path, rng,
                                                       monkeypatch):
    """ISSUE 8 satellite: an injected failure on a FUSED streamed run
    (today only split-path failures were exercised) leaves a flight dump
    that carries the data-plane snapshot as of the crash plus its health
    classification."""
    from mapreduce_tpu.parallel import mapreduce as mr
    from tests.conftest import pallas_interpret_mode

    corpus = make_corpus(rng, 6000, 150)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    # 8448 = the pallas minimum chunk at W=32: 2 devices x 8448 per step
    # puts the injected fault on step 1 with step 0 already retired.
    cfg = Config(chunk_bytes=8448, table_capacity=2048, backend="pallas",
                 map_impl="fused", inflight_groups=1)
    original = mr.Engine.step

    def failing(self, state, chunks, step_index):
        if step_index >= 1:
            raise RuntimeError("injected fused fault")
        return original(self, state, chunks, step_index)

    monkeypatch.setattr(mr.Engine, "step", failing)
    led = str(tmp_path / "run.jsonl")
    with pallas_interpret_mode():
        with obs.Telemetry.create(ledger_path=led) as tel:
            with pytest.raises(RuntimeError, match="injected fused fault"):
                executor.run_job(WordCountJob(cfg), str(path), cfg,
                                 mesh=data_mesh(2), telemetry=tel)
    dump_path = led + ".flight.json"
    assert os.path.exists(dump_path)
    with open(dump_path) as f:
        dump = json.load(f)
    # inflight_groups=1: step 0 retired (with its stats) before step 1
    # failed, so the dump carries the data snapshot up to the crash.
    assert dump["data"]["groups"] == 1 and dump["data"]["chunks"] == 2
    assert dump["data"]["map_impl"] == "fused"
    assert dump["data_health"]["verdict"] in (
        "clean", "skew-hot", "table-pressure", "occupancy-starved")
    assert "signals" in dump["data_health"]
    # The group record written before the crash carries its data dict.
    groups = [r for r in obs.read_ledger(led, kind="group")]
    assert len(groups) == 1 and "data" in groups[0]
