"""Overlong-token rescue: the pallas backend must agree with the XLA oracle
on corpora with >W-byte tokens (VERDICT r3 #6; ops/rescue.py).

The XLA backend counts any token length exactly, so it IS the oracle: with
rescue on, pallas runs must match it bit-for-bit whenever every overlong
token fits the rescue window and budget — and degrade to the accounted
(dropped_*) envelope, never corruption, when they don't.
"""

import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount as wc


def _cfg(backend, **kw):
    base = dict(chunk_bytes=1 << 14, table_capacity=1 << 12, backend=backend)
    base.update(kw)
    return Config(**base)


def _mixed_text(rng, n_words=400, long_words=None):
    """Normal words interleaved with given overlong tokens, shuffled."""
    vocab = [b"the", b"quick", b"fox", b"jumps", b"count"]
    words = [vocab[i % len(vocab)] for i in range(n_words)]
    words += list(long_words or [])
    order = rng.permutation(len(words))
    return b" ".join(words[i] for i in order)


@pytest.fixture
def oracle():
    def run(text, **pallas_kw):
        rp = wc.count_words(text, _cfg("pallas", **pallas_kw))
        rx = wc.count_words(text, _cfg("xla"))
        return rp, rx

    return run


class TestRescueExact:
    @pytest.mark.slow
    def test_matches_xla_oracle_counts_and_order(self, rng, oracle):
        longs = [b"x" * 40, b"y" * 100, b"z" * 150] * 3 + [b"u" * 63]
        text = _mixed_text(rng, long_words=longs)
        rp, rx = oracle(text, rescue_overlong=64, rescue_window=192)
        assert rp.as_dict() == rx.as_dict()
        assert rp.words == rx.words  # insertion order identical
        assert rp.total == rx.total
        assert rp.dropped_count == 0 and rp.dropped_uniques == 0
        assert rp.distinct == rx.distinct

    @pytest.mark.slow  # 31 s measured round 6: past the tier-1 >=10 s line
    def test_repeated_overlong_word_accumulates(self, rng, oracle):
        url = b"http://example.com/a/very/long/path/segment/beyond-w"
        assert len(url) > 32
        text = _mixed_text(rng, long_words=[url] * 17)
        rp, rx = oracle(text, rescue_overlong=64, rescue_window=192)
        assert rp.as_dict()[url] == 17
        assert rp.as_dict() == rx.as_dict()

    @pytest.mark.slow  # ~30 s on the one-core box; tier-1 budget rule
    def test_exact_at_w_boundaries(self, rng, oracle):
        # 32 is in-kernel, 33 is the smallest rescued length, window-1 the
        # largest; window stays dropped (covered in TestRescueEnvelope).
        longs = [b"a" * 32, b"b" * 33, b"c" * 191]
        text = _mixed_text(rng, long_words=longs * 2)
        rp, rx = oracle(text, rescue_overlong=64, rescue_window=192)
        assert rp.as_dict() == rx.as_dict()
        assert rp.dropped_count == 0

    @pytest.mark.slow
    def test_overlong_crossing_lane_seams(self, oracle):
        # A chunk-sized text where overlong tokens land on many different
        # lane-segment offsets, including straddling 128-lane seam bytes:
        # seam-pass poisons must be rescued exactly like in-lane ones.
        rng = np.random.default_rng(5)
        words = []
        for i in range(2000):
            words.append(b"w%d" % (i % 37))
            if i % 29 == 0:
                words.append(bytes([97 + i % 26]) * (33 + i % 120))
        text = b" ".join(words)
        rp, rx = oracle(text, rescue_overlong=256, rescue_window=192)
        assert rp.as_dict() == rx.as_dict()
        assert rp.total == rx.total
        assert rp.dropped_count == 0

    @pytest.mark.slow
    def test_with_compact_slots(self, oracle):
        rng = np.random.default_rng(9)
        longs = [b"q" * 50] * 5 + [b"r" * 120] * 2
        text = _mixed_text(rng, long_words=longs)
        rp, rx = oracle(text, rescue_overlong=64, rescue_window=192,
                        sort_mode="sort3", compact_slots=88)
        assert rp.as_dict() == rx.as_dict()
        assert rp.dropped_count == 0


class TestRescueEnvelope:
    def test_token_longer_than_window_stays_accounted(self, rng, oracle):
        giant = b"g" * 500  # > rescue_window - 1
        text = _mixed_text(rng, long_words=[giant] * 3 + [b"m" * 40])
        rp, rx = oracle(text, rescue_overlong=64, rescue_window=192)
        d = rp.as_dict()
        assert giant not in d
        assert d[b"m" * 40] == 1  # within-window token still rescued
        assert rp.dropped_count == 3
        assert rp.dropped_uniques == 3  # upper bound: unhashed, undedupable
        assert rp.total == rx.total  # accounting keeps totals exact

    @pytest.mark.slow
    def test_budget_overflow_rescues_prefix_keeps_totals(self, rng):
        # More overlong tokens than BOTH tiers: the smallest positions win,
        # the rest stays accounted, totals stay exact.  Words are DISTINCT:
        # a duplicated word with only some occurrences inside the budget
        # would legitimately report a partial count (residual in dropped_*).
        # rescue_overlong_max pins the second tier to the primary budget so
        # this exercises the genuine-overflow envelope.
        longs = [b"%02d" % i + b"x" * 40 for i in range(30)]
        text = _mixed_text(rng, long_words=longs)
        cfg = _cfg("pallas", rescue_overlong=8, rescue_overlong_max=8,
                   rescue_window=192)
        rp = wc.count_words(text, cfg)
        rx = wc.count_words(text, _cfg("xla"))
        assert rp.total == rx.total
        assert rp.dropped_count == len(longs) - 8
        # Every rescued word is correct (subset of the oracle's counts).
        ox = rx.as_dict()
        for w, c in rp.as_dict().items():
            assert ox[w] == c

    @pytest.mark.slow
    def test_tier_escalates_past_primary_budget(self, rng):
        """VERDICT r4 weak #4: overlong counts past the primary budget
        escalate to the second tier under a lax.cond instead of silently
        leaving the residual in dropped_* — URL-dense chunks stay exact
        with no hand-sizing."""
        longs = [b"%02d" % i + b"u" * 40 for i in range(30)]
        text = _mixed_text(rng, long_words=longs)
        rp = wc.count_words(text, _cfg("pallas", rescue_overlong=8,
                                       rescue_overlong_max=64,
                                       rescue_window=192))
        rx = wc.count_words(text, _cfg("xla"))
        assert rp.as_dict() == rx.as_dict()
        assert rp.words == rx.words
        assert rp.dropped_count == 0

    @pytest.mark.slow  # 43 s measured round 6: past the tier-1 >=10 s line
    def test_tier_escalates_under_stable2_with_seam_poisons(self, rng):
        """The tiered path composes with stable2's split rescue sources
        (column poison segment + seam-stream poisons, re-sorted so the
        first-R1 slice keeps the globally smallest positions)."""
        longs = [b"%02d" % i + b"v" * 40 for i in range(25)]
        text = _mixed_text(rng, long_words=longs)
        rp = wc.count_words(text, _cfg("pallas", sort_mode="stable2",
                                       rescue_overlong=8,
                                       rescue_overlong_max=64,
                                       rescue_window=192))
        rx = wc.count_words(text, _cfg("xla"))
        assert rp.as_dict() == rx.as_dict()
        assert rp.dropped_count == 0

    def test_tier_auto_sizing_arithmetic(self):
        # Auto: chunk_bytes/1024 clamped to [rescue_slots, 65536].
        assert Config().rescue_slots_max == (1 << 25) >> 10  # 32768 @ 32 MB
        assert Config(chunk_bytes=1 << 14).rescue_slots_max == 1024  # floor
        assert Config(chunk_bytes=1 << 26).rescue_slots_max == 65536  # cap
        assert Config(rescue_overlong=0).rescue_slots_max == 0  # off = off
        assert Config(rescue_overlong_max=99,
                      rescue_overlong=8).rescue_slots_max == 99
        # An explicit primary budget above the auto cap is honored in full.
        assert Config(rescue_overlong=100000).rescue_slots_max == 100000

    @pytest.mark.slow
    def test_rescue_off_keeps_round3_accounting(self, rng, oracle):
        text = _mixed_text(rng, long_words=[b"n" * 40] * 4)
        rp, rx = oracle(text, rescue_overlong=0)
        assert b"n" * 40 not in rp.as_dict()
        assert rp.dropped_count == 4
        assert rp.total == rx.total

    @pytest.mark.slow  # ~19 s on the one-core box; tier-1 budget rule
    def test_no_overlong_bit_identical_to_rescue_off(self, rng):
        # The cond guard: overlong-free chunks must produce the same table
        # with rescue on or off (the branch never runs).
        text = _mixed_text(rng)
        t_on = wc.count_table(text, _cfg("pallas", rescue_overlong=64))
        t_off = wc.count_table(text, _cfg("pallas", rescue_overlong=0))
        for a, b in zip(t_on, t_off):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestRescueConfig:
    def test_segmin_combination_rejected(self):
        with pytest.raises(ValueError, match="sort3"):
            Config(sort_mode="segmin", rescue_overlong=64)

    def test_segmin_allowed_with_rescue_off(self):
        Config(sort_mode="segmin", rescue_overlong=0)

    def test_default_auto_resolves_by_sort_mode(self):
        # None (the default) = on under sort3, off under segmin — so
        # constructing a segmin Config (CLI --sort-mode, BENCH_SORT_MODE)
        # keeps working without touching the rescue knob.
        assert Config().rescue_slots == 1024
        assert Config(sort_mode="segmin").rescue_slots == 0
        assert Config(rescue_overlong=64).rescue_slots == 64
        assert Config(rescue_overlong=0).rescue_slots == 0

    def test_window_must_exceed_w(self):
        with pytest.raises(ValueError, match="rescue_window"):
            Config(rescue_overlong=64, rescue_window=32)

    @pytest.mark.slow
    def test_streamed_executor_rescues(self, tmp_path, rng):
        # The engine/executor path flows through the same _map_stream:
        # a multi-chunk streamed run must agree with the XLA oracle.
        from mapreduce_tpu.runtime import executor

        longs = [b"s" * 45] * 6 + [b"t" * 90] * 3
        text = _mixed_text(rng, n_words=3000, long_words=longs)
        p = tmp_path / "corpus.txt"
        p.write_bytes(text)
        cfg = _cfg("pallas", chunk_bytes=128 * 66, rescue_overlong=64,
                   rescue_window=128)
        got = executor.count_file(str(p), cfg)
        rx = wc.count_words(text, _cfg("xla"))
        assert got.as_dict() == rx.as_dict()
        assert got.total == rx.total
