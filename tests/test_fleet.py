"""Pod-scale observability (ISSUE 13): per-host ledger shards, the
clock-aligned fleet merge, straggler/collective accounting and the
fleet_bottleneck verdict — all falsified jax-free against crafted
records and the checked-in two-host fixtures (the real 2-process run is
tests/test_multihost.py's @slow half)."""

from __future__ import annotations

import json
import os

import pytest

from mapreduce_tpu import obs
from mapreduce_tpu.obs import datahealth, fleet, timeline

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "tools", "fixtures")


def _rs(host, wall, mono, run_id="r1"):
    return {"run_id": run_id, "kind": "run_start", "ledger_version": 7,
            "host": host, "processes": 2,
            "clock": {"wall": wall, "mono": mono}}


def _group(host, step, staged, disp, ready, run_id="r1", **extra):
    return {"run_id": run_id, "kind": "group", "host": host,
            "step_first": step, "step_last": step, "steps": 1,
            "group_bytes": 1024, "staged_at": staged, "dispatched_at": disp,
            "token_ready_at": ready, "retired_at": ready + 0.01, **extra}


def _coll(host, start, end, run_id="r1"):
    return {"run_id": run_id, "kind": "collective", "host": host,
            "op": "finish", "strategy": "tree", "started_at": start,
            "ended_at": end}


# -- shard naming ------------------------------------------------------------

def test_shard_naming():
    assert obs.shard_path("/x/run.jsonl", 3) == "/x/run.jsonl.h3.jsonl"
    assert obs.shard_flight_path("/x/run.jsonl", 1) \
        == "/x/run.jsonl.h1.flight.json"
    assert fleet.shard_paths("/nonexistent/run.jsonl") == {}


# -- clock alignment ---------------------------------------------------------

def test_clock_alignment_rebases_monotonic_stamps():
    """Two hosts with wildly different monotonic origins: the {wall, mono}
    pairs rebase their stamps onto one clock, and the hand-computed
    per-superstep skew falls out."""
    by_host = {
        0: [_rs(0, wall=100.0, mono=10.0),  # offset +90
            _group(0, 0, 10.5, 10.6, 11.0)],  # ready at wall 101.0
        1: [_rs(1, wall=100.0, mono=70.0),  # offset +30
            _group(1, 0, 70.5, 70.6, 71.25)],  # ready at wall 101.25
    }
    view = fleet.fleet_view(by_host)
    assert view["aligned"] is True
    assert [s["skew_s"] for s in view["supersteps"]] == [0.25]
    assert view["straggler"]["slowest_host"] == 1


def test_partial_clocks_degrade_to_unaligned():
    """One shard without the v7 clock pair: mixing rebased and raw stamps
    would fabricate skew, so the merge keeps raw monotonic values (valid
    on one box: CLOCK_MONOTONIC is system-wide) and says so."""
    by_host = {
        0: [_rs(0, 100.0, 10.0), _group(0, 0, 10.5, 10.6, 11.0)],
        1: [{"run_id": "r1", "kind": "run_start", "host": 1},
            _group(1, 0, 10.5, 10.6, 11.5)],
    }
    view = fleet.fleet_view(by_host)
    assert view["aligned"] is False
    # Raw stamps still compare (same origin here): 0.5 s skew.
    assert view["supersteps"][0]["skew_s"] == 0.5


# -- straggler decomposition -------------------------------------------------

def test_straggler_skew_and_attribution_hand_computed():
    by_host = {
        0: [_rs(0, 0.0, 0.0), _group(0, 0, 0.1, 0.2, 1.0),
            _group(0, 1, 1.0, 1.1, 2.0)],
        1: [_rs(1, 0.0, 0.0), _group(1, 0, 0.1, 0.2, 1.4),
            _group(1, 1, 1.4, 1.5, 2.6)],
    }
    view = fleet.fleet_view(by_host)
    st = view["straggler"]
    assert [s["skew_s"] for s in view["supersteps"]] == [0.4, 0.6]
    assert st["total_skew_s"] == 1.0
    assert st["slowest_host"] == 1 and st["slowest_wins"] == 2
    assert st["per_host_lag_s"] == {"0": 0.0, "1": 1.0}
    bn = view["fleet_bottleneck"]
    assert bn["verdict"] == "straggler-bound"
    # span 0.1 -> 2.61: skew 1.0 is 38% of it, saving = 1.0 (under span).
    assert bn["projected_saving_s"] == 1.0, bn


def test_straggler_saving_capped_at_span():
    """A consistently slow host accumulates more lag-seconds than the
    concurrent wall-clock could give back: the projected saving must not
    exceed the fleet span."""
    h0 = [_rs(0, 0.0, 0.0)] + [
        _group(0, i, i * 0.1, i * 0.1 + 0.01, i * 0.1 + 0.02)
        for i in range(10)]
    h1 = [_rs(1, 0.0, 0.0)] + [
        _group(1, i, i * 0.1, i * 0.1 + 0.01, i * 0.1 + 0.25)
        for i in range(10)]
    view = fleet.fleet_view({0: h0, 1: h1})
    bn = view["fleet_bottleneck"]
    assert bn["verdict"] == "straggler-bound"
    assert bn["straggler_s"] > view["span_s"]
    assert bn["projected_saving_s"] == view["span_s"]


def test_slowest_host_tie_prefers_lower_id():
    by_host = {
        0: [_rs(0, 0.0, 0.0), _group(0, 0, 0.1, 0.2, 1.0)],
        1: [_rs(1, 0.0, 0.0), _group(1, 0, 0.1, 0.2, 1.0)],
        2: [_rs(2, 0.0, 0.0), _group(2, 0, 0.1, 0.2, 0.5)],
    }
    view = fleet.fleet_view(by_host)
    assert view["supersteps"][0]["slowest_host"] == 0  # tie at 1.0 -> h0


# -- collective accounting ---------------------------------------------------

def test_collective_bound_verdict():
    by_host = {
        0: [_rs(0, 0.0, 0.0), _group(0, 0, 0.1, 0.2, 1.0),
            _coll(0, 1.05, 3.05)],
        1: [_rs(1, 0.0, 0.0), _group(1, 0, 0.1, 0.2, 1.02),
            _coll(1, 1.05, 3.05)],
    }
    view = fleet.fleet_view(by_host)
    assert view["collective"]["mean_s"] == 2.0
    bn = view["fleet_bottleneck"]
    assert bn["verdict"] == "collective-bound"
    assert bn["projected_saving_s"] == 2.0


def test_balanced_verdict_below_threshold():
    by_host = {
        0: [_rs(0, 0.0, 0.0), _group(0, 0, 0.1, 0.2, 2.0),
            _coll(0, 2.02, 2.06)],
        1: [_rs(1, 0.0, 0.0), _group(1, 0, 0.1, 0.2, 2.01),
            _coll(1, 2.02, 2.06)],
    }
    view = fleet.fleet_view(by_host)
    assert view["fleet_bottleneck"]["verdict"] == "balanced"


# -- host imbalance (datahealth) ---------------------------------------------

def test_classify_fleet_hand_arithmetic():
    out = datahealth.classify_fleet({0: {"bytes": 1000, "tokens": 100},
                                     1: {"bytes": 3000, "tokens": 110}})
    assert out["verdict"] == "host-imbalance"
    assert out["signals"]["bytes_ratio"] == 1.5  # 3000 / 2000
    assert out["signals"]["bytes_hot_host"] == 1
    # tokens ratio 110/105 well under the gate: only bytes flags.
    assert [f["counter"] for f in out["flags"]] == ["bytes"]


def test_classify_fleet_threshold_edge_and_degenerates():
    # Exactly at the 1.25 gate: NOT imbalanced (strict >).
    at = datahealth.classify_fleet({0: {"bytes": 750}, 1: {"bytes": 1250}})
    assert at["signals"]["bytes_ratio"] == 1.25
    assert at["verdict"] == "balanced"
    # One host / missing counters / zero totals: no signal, no crash.
    assert datahealth.classify_fleet({0: {"bytes": 10}})["verdict"] \
        == "balanced"
    assert datahealth.classify_fleet({0: {}, 1: {"x": 1}})["verdict"] \
        == "balanced"
    assert datahealth.classify_fleet({0: {"bytes": 0},
                                      1: {"bytes": 0}})["verdict"] \
        == "balanced"


def test_fleet_view_feeds_imbalance_from_host_bytes():
    by_host = {
        0: [_rs(0, 0.0, 0.0),
            _group(0, 0, 0.1, 0.2, 1.0, host_bytes=100)],
        1: [_rs(1, 0.0, 0.0),
            _group(1, 0, 0.1, 0.2, 1.0, host_bytes=300)],
    }
    view = fleet.fleet_view(by_host)
    assert view["per_host"]["1"]["host_bytes"] == 300
    assert view["imbalance"]["verdict"] == "host-imbalance"


# -- timeline collective lane + host filter ----------------------------------

def test_timeline_collective_lane_opt_in_and_excluded_from_bottleneck():
    recs = [_group(0, 0, 0.1, 0.2, 1.0), _coll(0, 1.05, 9.0)]
    plain = timeline.reconstruct(recs)
    assert "collective" not in {k for k, v in plain["lanes"].items() if v}
    art = timeline.reconstruct(recs, with_collective=True)
    assert art["lanes"]["collective"] == [[0.95, 8.9]]
    assert art["lane_busy_s"]["collective"] == 7.95
    # 7.95 s of collective-exclusive time, yet the verdict stays the
    # STREAM's bounding resource (device here) by design.
    assert art["bottleneck"]["resource"] == "device"
    assert "collective" in timeline.FLEET_LANES


def test_timeline_host_filter_on_mixed_records():
    """A mode-(a) style single file holding both hosts' stamped records:
    the host filter reconstructs one process's lanes."""
    recs = [_group(0, 0, 0.1, 0.2, 1.0), _group(1, 0, 0.1, 0.2, 2.0)]
    a0 = timeline.reconstruct(recs, host=0)
    a1 = timeline.reconstruct(recs, host=1)
    assert a0["groups"] == 1 and a1["groups"] == 1
    assert a0["lane_busy_s"]["device"] == 0.8
    assert a1["lane_busy_s"]["device"] == 1.8
    assert timeline.reconstruct(recs, host=7) is None


# -- merge determinism + merged stream ---------------------------------------

def test_fixture_merge_byte_stable_and_carries_fleet_record():
    main = os.path.join(FIXTURES, "fleet_ledger.jsonl")
    paths = fleet.shard_paths(main)
    assert sorted(paths) == [0, 1]

    def merge_once():
        by_host = {h: fleet.read_jsonl(p) for h, p in paths.items()}
        return "\n".join(json.dumps(r, sort_keys=True)
                         for r in fleet.merged_records(by_host))

    a, b = merge_once(), merge_once()
    assert a == b, "merged fleet stream must be byte-stable"
    last = json.loads(a.splitlines()[-1])
    assert last["kind"] == "fleet"
    assert last["fleet_bottleneck"]["verdict"] == "straggler-bound"
    assert last["straggler"]["total_skew_s"] == 2.0


def test_run_selection_pairs_last_runs_and_honors_run_id():
    old = [_rs(0, 0.0, 0.0, run_id="old"),
           _group(0, 0, 0.1, 0.2, 5.0, run_id="old")]
    new = [_rs(0, 0.0, 0.0, run_id="new"),
           _group(0, 0, 0.1, 0.2, 1.0, run_id="new")]
    by_host = {0: old + new,
               1: [_rs(1, 0.0, 0.0, run_id="new"),
                   _group(1, 0, 0.1, 0.2, 1.2, run_id="new")]}
    view = fleet.fleet_view(by_host)  # default: each shard's LAST run
    assert view["run_ids"] == {"0": "new", "1": "new"}
    assert view["supersteps"][0]["skew_s"] == 0.2
    old_view = fleet.fleet_view(by_host, run_id="old")
    assert old_view["hosts"] == [0]  # host 1 never ran it


def test_run_selection_splits_same_run_id_instances():
    """The crash+relaunch recovery appends a SECOND run under the same
    shared run_id (the documented multi-host contract + append-mode
    shards): every run_start opens a new instance, so the crashed
    attempt and its recovery never fuse into one fleet view."""
    crashed = [_rs(0, 0.0, 0.0, run_id="gw"),
               _group(0, 0, 0.1, 0.2, 9.0, run_id="gw")]
    recovery = [_rs(0, 100.0, 100.0, run_id="gw"),
                _group(0, 0, 100.1, 100.2, 100.5, run_id="gw"),
                _group(0, 1, 100.5, 100.6, 101.0, run_id="gw")]
    rid, recs = fleet.select_run(crashed + recovery)
    assert rid == "gw" and recs == recovery
    rid, recs = fleet.select_run(crashed + recovery, run_id="gw")
    assert recs == recovery, "an explicit id picks its LAST instance"
    view = fleet.fleet_view({0: crashed + recovery,
                             1: [_rs(1, 100.0, 100.0, run_id="gw"),
                                 _group(1, 0, 100.1, 100.2, 100.6,
                                        run_id="gw"),
                                 _group(1, 1, 100.5, 100.6, 101.2,
                                        run_id="gw")]})
    # Only the recovery instance merges: 2 supersteps, no 9.0 s stamp.
    assert view["per_host"]["0"]["groups"] == 2
    assert [s["skew_s"] for s in view["supersteps"]] == [0.1, 0.2]


# -- the tuner consumes fleet_bottleneck (trail note only) -------------------

def test_tuner_notes_fleet_verdict_without_chasing_it():
    from mapreduce_tpu import tuning

    main = os.path.join(FIXTURES, "fleet_ledger.jsonl")
    by_host = {h: fleet.read_jsonl(p)
               for h, p in fleet.shard_paths(main).items()}
    merged = fleet.merged_records(by_host)
    prop = tuning.propose(merged, run_id="fleet01")
    assert prop["signals"]["fleet_bottleneck"] == "straggler-bound"
    note = next(t for t in prop["trail"]
                if t["rule"] == "fleet-straggler-bound")
    assert note["fired"] is False and "outside the tuned set" in note["why"]
    # The fired rule is a normal single-host one — the fleet verdict
    # must never produce a knob move on its own.
    assert prop["rule"] != "fleet-straggler-bound"
    # Shardless ledgers carry no fleet signal at all.
    plain = tuning.propose([_rs(0, 0.0, 0.0),
                            _group(0, 0, 0.1, 0.2, 1.0)])
    assert plain["signals"]["fleet_bottleneck"] is None
    assert not any(t["rule"].startswith("fleet-") for t in plain["trail"])


def _collective_bound_merged(merge_strategy="tree", merge_overlap=False):
    """A merged two-host stream whose fleet verdict is collective-bound:
    negligible skew, a fat finish after the map lanes drain."""
    def start(h):
        rec = _rs(h, 50.0, 0.0, run_id="cb")
        rec["merge_strategy"] = merge_strategy
        if merge_overlap:
            rec["merge_overlap"] = True
        return rec

    by_host = {h: [start(h),
                   _group(h, 0, 0.99, 1.0, 2.0 + 0.01 * h, run_id="cb"),
                   _coll(h, 2.1, 3.6, run_id="cb")] for h in (0, 1)}
    return fleet.merged_records(by_host)


def test_tuner_fires_on_collective_bound_fleet():
    """ISSUE 20: collective-bound graduated from note to move.  The
    escalation ladder — overlap off: enable merge_overlap; overlap on +
    tree: switch to keyrange; both exhausted: note only."""
    from mapreduce_tpu import tuning
    from mapreduce_tpu.tuning import engine

    prop = tuning.propose(_collective_bound_merged(), run_id="cb")
    assert prop["signals"]["fleet_bottleneck"] == "collective-bound"
    assert prop["rule"] == "fleet-collective-bound"
    assert prop["changed"] == {"merge_overlap": ["off", "on"]}, prop
    fired = next(t for t in prop["trail"]
                 if t["rule"] == "fleet-collective-bound")
    assert fired["fired"] is True, fired
    engine.validate_knobs(prop["proposal"])

    prop2 = tuning.propose(_collective_bound_merged(merge_overlap=True),
                           run_id="cb")
    assert prop2["rule"] == "fleet-collective-bound"
    assert prop2["changed"] == {"merge_strategy": ["tree", "keyrange"]}
    engine.validate_knobs(prop2["proposal"])

    # Ladder exhausted: keyrange + overlap on -> a note, and the fired
    # rule falls through to the normal single-host table.
    prop3 = tuning.propose(
        _collective_bound_merged(merge_strategy="keyrange",
                                 merge_overlap=True), run_id="cb")
    assert prop3["rule"] != "fleet-collective-bound"
    notes = [t for t in prop3["trail"]
             if t["rule"] == "fleet-collective-bound"]
    assert notes and all(t["fired"] is False for t in notes), notes


def test_tuner_signals_anchor_on_one_host_in_merged_ledgers():
    """A merged fleet stream holds every host's records under one run_id:
    reconstructing a timeline from ALL of them would fuse the hosts'
    lanes into a chimera no host ran.  derive_signals must anchor the
    single-host signals on the coordinator's records (the fleet record
    marks the stream), so the fired rule reads a real host's view."""
    from mapreduce_tpu import tuning

    # Host 0 is device-bound; host 1's enormous reader interval would
    # dominate a fused timeline and misfire raise-prefetch.
    by_host = {
        0: [_rs(0, 0.0, 0.0),
            {"run_id": "r1", "kind": "group", "host": 0, "step_first": 0,
             "step_last": 0, "steps": 1, "group_bytes": 1024,
             "read_at": 0.0, "staged_at": 0.1, "dispatched_at": 0.2,
             "token_ready_at": 5.0, "retired_at": 5.01}],
        1: [_rs(1, 0.0, 0.0),
            {"run_id": "r1", "kind": "group", "host": 1, "step_first": 0,
             "step_last": 0, "steps": 1, "group_bytes": 1024,
             "read_at": 0.0, "staged_at": 6.0, "dispatched_at": 6.1,
             "token_ready_at": 6.3, "retired_at": 6.31}],
    }
    merged = fleet.merged_records(by_host)
    sig = tuning.derive_signals(merged, run_id="r1")
    assert sig["resource"] == "device", sig["resource"]
    # The unanchored chimera would have said reader (host 1's 6 s read
    # interval is the only exclusive time once the lanes fuse).
    chimera = timeline.reconstruct(
        [r for r in merged if r.get("kind") == "group"], run_id="r1")
    assert chimera["bottleneck"]["resource"] == "reader"


# -- telemetry shard writer --------------------------------------------------

def test_attach_host_suffixes_flight_path_without_a_ledger(tmp_path):
    """Shard mode with a flight path but NO ledger (Telemetry.create
    supports it): non-coordinators must still move to a host-suffixed
    dump path — N processes racing one flight.json would shred the
    failing host's forensics."""
    fp = str(tmp_path / "flight.json")
    tel = obs.Telemetry(flight_path=fp)
    tel.attach_host(1, 2)
    assert tel.flight_path == fp + ".h1"
    coord = obs.Telemetry(flight_path=fp)
    coord.attach_host(0, 2)
    assert coord.flight_path == fp  # the coordinator keeps the base path


def test_telemetry_attach_host_opens_shard_and_stamps(tmp_path):
    p = str(tmp_path / "run.jsonl")
    tel = obs.Telemetry.create(ledger_path=p, run_id="tshard")
    tel.attach_host(1, 2, local_devices=2,
                    clock={"wall": 10.0, "mono": 3.0})
    # Non-coordinator: the flight path moves to the host-suffixed file.
    assert tel.flight_path == obs.shard_flight_path(p, 1)
    tel.ledger_write("run_start", driver="t", write=False)  # gated off main
    tel.ledger_write("group", step_first=0, write=False)
    tel.ledger_write("checkpoint", step=1, write=True)
    tel.close()
    # Main file got only the gated record; the shard got everything,
    # host-stamped, with the topology + clock on run_start.
    main = list(obs.read_ledger(p))
    assert [r["kind"] for r in main] == ["checkpoint"]
    shard = list(obs.read_ledger(obs.shard_path(p, 1)))
    assert [r["kind"] for r in shard] == ["run_start", "group", "checkpoint"]
    assert all(r["host"] == 1 for r in shard)
    start = shard[0]
    assert start["ledger_version"] == obs.LEDGER_VERSION == 10
    assert start["processes"] == 2 and start["local_devices"] == 2
    assert start["clock"] == {"wall": 10.0, "mono": 3.0}
    assert "clock" not in shard[1], "topology rides run_start only"


def test_telemetry_attach_host_stamp_only_mode(tmp_path):
    """shard=False (the per-host-driven mode a): host stamps, no second
    file — the host's own ledger IS its shard."""
    p = str(tmp_path / "a.jsonl")
    tel = obs.Telemetry.create(ledger_path=p, run_id="tmodea")
    tel.attach_host(0, 3, clock={"wall": 1.0, "mono": 0.5}, shard=False)
    tel.ledger_write("run_start", driver="t")
    tel.close()
    assert tel.shard is None
    assert not os.path.exists(obs.shard_path(p, 0))
    rec = next(obs.read_ledger(p))
    assert rec["host"] == 0 and rec["processes"] == 3


def test_telemetry_disabled_attach_is_noop(tmp_path):
    tel = obs.Telemetry.disabled()
    tel.attach_host(1, 2)
    assert tel.shard is None and not tel.host


# -- forward compat ----------------------------------------------------------

def test_future_ledger_records_flow_through_fleet_consumers():
    """The v7-shaped records in the future fixture (host/clock topology,
    a collective with unknown fields, a `fleet` record with an unknown
    verdict) must be skipped-or-consumed by every reader, never fatal."""
    from mapreduce_tpu import tuning

    fut = os.path.join(FIXTURES, "future_ledger.jsonl")
    recs = fleet.read_jsonl(fut)
    art = timeline.reconstruct(recs, with_collective=True)
    assert art is not None and art["lanes"].get("collective"), art
    view = fleet.fleet_view(fleet.load_shards([fut]))
    assert view is not None and view["hosts"] == [0]
    prop = tuning.propose(recs, run_id="future01")
    assert prop["signals"]["fleet_bottleneck"] == "entanglement-bound"
    assert any(t["rule"] == "fleet-entanglement-bound"
               for t in prop["trail"])
    assert prop["rule"] != "fleet-entanglement-bound"


@pytest.mark.smoke
def test_fleet_selftest_entrypoint():
    """The tier-1/smoke gate in-process: the checked-in two-host shard
    fixtures through the full merge with hand-computed asserts."""
    assert fleet.selftest() == 0
