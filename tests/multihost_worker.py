"""Worker process for the TRUE multi-process multi-host test.

Drives multi-host mode (b) from ``parallel/distributed.py``: one global SPMD
program over the devices of every process — ``jax.distributed.initialize``,
a global mesh, per-process staging with ``device_put_local``, the Engine's
sharded step, and the collective finish.  Each process stages ONLY its own
shard rows; the result is replicated to every process by the finish
collective.

Launched by ``tests/test_multihost.py::test_true_multiprocess_spmd_run``
as N subprocesses; prints one JSON line (process 0: the counts) so the
parent can compare against a single-process oracle run.

Usage: python multihost_worker.py <process_id> <n_processes> <port> \
    <corpus_path> <chunk_bytes> <devices_per_process>
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    pid, n_proc = int(sys.argv[1]), int(sys.argv[2])
    port, path = sys.argv[3], sys.argv[4]
    chunk_bytes, dev_per_proc = int(sys.argv[5]), int(sys.argv[6])

    # EXACTLY dev_per_proc local devices (force_cpu's min_devices would keep
    # a larger ambient count, breaking the n_proc * dev_per_proc global mesh).
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={dev_per_proc}")
    from mapreduce_tpu.runtime.platform import force_cpu

    # verify=False: jax.distributed.initialize() below must run on a pristine
    # runtime; the platform assertions after it cover verification.
    jax = force_cpu(verify=False)
    # Cross-process CPU collectives (the CPU stand-in for ICI/DCN transport).
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from mapreduce_tpu.parallel import distributed as dist

    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=n_proc, process_id=pid, timeout_s=60)
    assert jax.process_count() == n_proc
    n_global = len(jax.devices())
    assert n_global == n_proc * dev_per_proc

    import jax.numpy as jnp
    import numpy as np

    from mapreduce_tpu.config import Config
    from mapreduce_tpu.data import reader
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.parallel.mapreduce import Engine

    cfg = Config(chunk_bytes=chunk_bytes, table_capacity=1 << 10)
    job = WordCountJob(cfg)
    mesh = dist.global_data_mesh()
    engine = Engine(job, mesh)

    # Device-resident init: in multi-controller SPMD no process can
    # device_put to another process's devices, so the initial state is
    # computed BY the global program (out_shardings places it).
    D = n_global

    def init():
        one = job.init_state()
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (D,) + x.shape), one)

    state = jax.jit(init, out_shardings=engine.sharding)()

    mine = list(dist.host_shards(n_global))
    for b in reader.iter_batches(path, n_global, cfg.chunk_bytes):
        local_rows = b.data[mine]  # this process stages ONLY its own rows
        global_batch = dist.device_put_local(local_rows, engine.sharding)
        state = engine.step(state, global_batch, b.step)

    table = engine.finish(state)  # collective merge; replicated result
    table = jax.tree.map(np.asarray, table)

    if dist.is_coordinator():
        live = table.count > 0
        counts = sorted(int(c) for c in table.count[live])
        print(json.dumps({"total": int(table.total_count()),
                          "counts": counts,
                          "distinct": int(live.sum()),
                          "processes": n_proc, "devices": n_global}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
