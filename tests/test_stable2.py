"""sort_mode='stable2': lane-major kernel layout + stable 2-key aggregation.

The round-5 sort-floor attack (VERDICT r4 next #1b): drop the third
comparator key from the aggregation sort — ~40% of the sort's compute on
the chip (BENCHMARKS.md round-4 sortbench) — by making the kernel emit its
compacted planes in global byte-position order (transposed lane-major
blocks) so a STABLE two-key sort recovers first occurrence from tie order.

Contract under test: stable2 is BIT-IDENTICAL to sort3 (and to the XLA
oracle) on every corpus shape — tokens, counts, first occurrences,
dropped accounting, overlong rescue, spill fallback, streamed runs.
"""

import jax
import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.ops import tokenize as tok
from mapreduce_tpu.ops.pallas import tokenize as ptok
from mapreduce_tpu.utils import oracle
from tests.conftest import make_corpus

W = 8  # small lookback: overlong paths exercised cheaply (see test_pallas)
CAP = 4096


def _pad(data: bytes, w: int = W) -> np.ndarray:
    n = max(128 * (2 * w + 2), -(-len(data) // 128) * 128)
    return tok.pad_to(data, n)


def _cfg(sort_mode: str, **kw) -> Config:
    kw.setdefault("chunk_bytes", 128 * (2 * 32 + 2))
    kw.setdefault("table_capacity", CAP)
    return Config(backend="pallas", sort_mode=sort_mode, **kw)


def _assert_results_equal(a, b):
    assert a.words == b.words
    assert a.counts == b.counts
    assert a.total == b.total
    assert a.dropped_count == b.dropped_count


def test_lane_major_planes_are_position_ordered(rng):
    """The stable2 precondition itself: the flattened lane-major packed
    plane's live rows (emissions AND poisons) carry strictly increasing
    positions — the property that lets sort stability stand in for the
    third comparator key."""
    corpus = make_corpus(rng, n_words=4000, vocab=300)
    buf = _pad(corpus)
    col, seam, overlong, spill = ptok.tokenize_split_compact(
        buf, 128, max_token_bytes=W, block_rows=384, lane_major=True,
        interpret=True)
    packed = np.asarray(col.packed)
    live = packed != 0xFFFFFFFF
    pos = (packed[live] >> 6).astype(np.int64)
    assert len(pos) > 100
    assert np.all(np.diff(pos) > 0)
    assert int(spill) == 0


@pytest.mark.slow
def test_lane_major_row_set_matches_slot_major(rng):
    """Lane-major changes only the ORDER of the compacted planes, never
    the row set: both layouts must contain exactly the same live
    (key, packed) multiset."""
    corpus = make_corpus(rng, n_words=3000, vocab=200)
    buf = _pad(corpus)
    a = ptok.tokenize_split_compact(buf, 128, max_token_bytes=W,
                                    block_rows=384, lane_major=False,
                                    interpret=True)[0]
    b = ptok.tokenize_split_compact(buf, 128, max_token_bytes=W,
                                    block_rows=384, lane_major=True,
                                    interpret=True)[0]

    def rows(s):
        k = np.stack([np.asarray(s.key_hi), np.asarray(s.key_lo),
                      np.asarray(s.packed)], axis=1)
        k = k[np.asarray(s.packed) != 0xFFFFFFFF]
        return k[np.lexsort(k.T)]

    np.testing.assert_array_equal(rows(a), rows(b))
    assert int(a.total) == int(b.total)


@pytest.mark.parametrize("vocab,n_words", [(50, 2000), (500, 8000)])
@pytest.mark.slow
def test_stable2_bit_identical_to_sort3(rng, vocab, n_words):
    corpus = make_corpus(rng, n_words=n_words, vocab=vocab)
    with _interpret():
        a = wordcount.count_words(corpus, _cfg("sort3"))
        b = wordcount.count_words(corpus, _cfg("stable2"))
    _assert_results_equal(a, b)
    assert a.as_dict() == oracle.word_counts(corpus)


@pytest.mark.slow
def test_stable2_overlong_rescue_matches(rng):
    """Overlong tokens (> W) — including one crossing a lane seam — must be
    rescued identically under both modes, with identical accounting.

    @slow (round 6): measured 55 s under the grown tier-1 suite — 5x past
    the PR-1 ">= ~10 s carries slow" line; tier-1 keeps rescue covered via
    test_rescue's boundary/envelope cases and production W=32 compiles."""
    w = 32  # production W here: the seam geometry below assumes min_chunk
    n = 128 * (2 * w + 2)
    seg = n // 128
    buf = np.full(n, 0x20, dtype=np.uint8)
    # An overlong run crossing the first lane seam (bytes seg-20 .. seg+20).
    buf[seg - 20: seg + 20] = ord("u")
    # A plain in-lane overlong run and some short words.
    buf[10:50] = ord("v")
    words = b"aa bb cc aa "
    buf[60:60 + len(words)] = np.frombuffer(words, dtype=np.uint8)
    data = bytes(buf)
    with _interpret():
        a = wordcount.count_words(data, _cfg("sort3", chunk_bytes=n))
        b = wordcount.count_words(data, _cfg("stable2", chunk_bytes=n))
    _assert_results_equal(a, b)
    # Both 40-byte runs rescued exactly: nothing left dropped.
    assert a.dropped_count == 0
    assert a.as_dict() == oracle.word_counts(data)


@pytest.mark.slow  # ~26 s on the one-core box; tier-1 budget rule
def test_stable2_spill_falls_back_exactly():
    """Windows denser than the slot budget must spill into the
    full-resolution fallback (which aggregates with sort3 — pair layout is
    not position-ordered) and stay exact."""
    data = b"a " * 4000  # density 0.5: overflows any 1/3 slot budget
    with _interpret():
        r = wordcount.count_words(data, _cfg("stable2"))
    assert r.as_dict() == oracle.word_counts(data)
    assert r.total == 4000


@pytest.mark.slow
def test_stable2_streamed_executor(tmp_path, rng):
    """Streamed sort3 (8-device mesh) == stable2 (4-device mesh).

    Mesh sizes differ deliberately: the lane-major kernel under an
    8-wide shard_map deadlocks JAX's pallas INTERPRET machinery on this
    one-core box (faulthandler dump, round 5: interpret threads wedged
    in _allocate_buffer/_barrier while run_job drains).  sort3's
    slot-major kernel streams fine 8-wide, stable2 is demonstrably fine
    4-wide (tests/test_pallas.py streams the stable2 default on a
    4-device mesh), and the REAL Mosaic kernel streams 8+ wide on-chip
    (the bench streamed phase runs exactly that).  Comparing across
    mesh widths additionally asserts mesh-size invariance of results.
    """
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file

    corpus = make_corpus(rng, n_words=6000, vocab=150)
    p = tmp_path / "c.txt"
    p.write_bytes(corpus)
    with _interpret():
        a = count_file([str(p)], config=_cfg("sort3", chunk_bytes=1 << 14),
                       mesh=data_mesh(8))
        b = count_file([str(p)], config=_cfg("stable2", chunk_bytes=1 << 14),
                       mesh=data_mesh(4))
    _assert_results_equal(a, b)
    assert a.as_dict() == oracle.word_counts(corpus)


# The exact jax release whose pallas INTERPRET machinery deadlocks in
# _allocate_buffer/_barrier under an 8-wide shard_map on a one-core box
# (round-5 faulthandler dump) — the reason test_stable2_streamed_executor
# runs stable2 on a 4-device mesh.  Pinned HERE so the workaround's
# coverage gap cannot outlive its cause (ADVICE r5).
_INTERPRET_DEADLOCK_JAX = "0.4.37"


@pytest.mark.slow
@pytest.mark.skipif(
    jax.__version__ == _INTERPRET_DEADLOCK_JAX,
    reason="pinned to the jax pallas INTERPRET _allocate_buffer/_barrier "
           "deadlock: stable2's lane-major kernel under an 8-wide "
           "shard_map wedges interpret threads on this jax version "
           "(round-5 faulthandler dump).  Any jax bump re-enables this "
           "test automatically; if it then deadlocks again, re-pin "
           "_INTERPRET_DEADLOCK_JAX to the new version and report "
           "upstream.  @slow keeps a possible hang out of tier-1's "
           "870 s budget either way.")
def test_stable2_streamed_executor_8wide(tmp_path, rng):
    """The coverage test_stable2_streamed_executor gives up to dodge the
    interpret deadlock: streamed stable2 on the FULL 8-device mesh, vs
    sort3 at the same width (on-chip Mosaic already streams this shape —
    the bench streamed phase — so a pass here closes the last emulated
    gap)."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file

    corpus = make_corpus(rng, n_words=6000, vocab=150)
    p = tmp_path / "c.txt"
    p.write_bytes(corpus)
    with _interpret():
        a = count_file([str(p)], config=_cfg("sort3", chunk_bytes=1 << 14),
                       mesh=data_mesh(8))
        b = count_file([str(p)], config=_cfg("stable2", chunk_bytes=1 << 14),
                       mesh=data_mesh(8))
    _assert_results_equal(a, b)
    assert a.as_dict() == oracle.word_counts(corpus)


def test_stable2_config_validation():
    with pytest.raises(ValueError, match="stable2"):
        Config(sort_mode="stable2", compact_slots=0)
    with pytest.raises(ValueError, match="128"):
        # Mosaic: lane-major puts slots in the 128-divisible block dim
        # (S=120 measured failing at lowering).
        Config(sort_mode="stable2", compact_slots=88)
    cfg = Config(sort_mode="stable2")
    assert cfg.resolved_compact_slots == 128
    assert cfg.resolved_block_rows == 384
    assert cfg.rescue_slots == 1024  # rescue rides stable2 too


@pytest.mark.slow
def test_stable2_first_occurrence_order(rng):
    """Insertion-order reporting (the reference's stdout contract) depends
    on exact first occurrences; construct a corpus where hot words first
    appear late in high lanes so a stability bug would misorder them."""
    words = [b"zz%d" % i for i in range(40)]
    # First occurrences deliberately scattered: emit each word once in
    # reverse order, then bulk repetitions.
    head = b" ".join(reversed(words))
    bulk = b" ".join(words[i % 40] for i in range(5000))
    corpus = head + b" " + bulk
    with _interpret():
        a = wordcount.count_words(corpus, _cfg("sort3"))
        b = wordcount.count_words(corpus, _cfg("stable2"))
    _assert_results_equal(a, b)
    assert a.words[:40] == list(reversed(words))


def _interpret():
    from tests.conftest import pallas_interpret_mode

    return pallas_interpret_mode()


@pytest.mark.slow
def test_gram_build_bit_identical_across_sort_modes(rng):
    """The packed gram build (ops/ngram.py gram_table) honors sort_mode:
    stable2 (tie-order first occurrence, the default) and sort3 (third
    comparator key) must produce identical results — including a
    >= 127-byte span riding the scan-forward length sentinel."""
    corpus = make_corpus(rng, n_words=1500, vocab=80) \
        + b" word" + b" " * 140 + b"pair tail"
    with _interpret():
        a = wordcount.count_ngrams(corpus, 2, _cfg("sort3"))
        b = wordcount.count_ngrams(corpus, 2, _cfg("stable2"))
    _assert_results_equal(a, b)
    assert any(len(w) > 140 for w in a.words)
