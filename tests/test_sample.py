"""Uniform reservoir sampling (bottom-k sketch): device results vs oracle
properties.  The sample is deterministic for a fixed corpus + chunking (the
priorities hash the occurrence's global identity), so distribution checks
assert concrete spread properties of that fixed draw."""

import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import sample as sample_mod
from mapreduce_tpu.utils import oracle


def test_sample_bytes_basic(small_corpus):
    k = 50
    r = sample_mod.sample_bytes(small_corpus, k)
    assert r.total == oracle.total_count(small_corpus)
    assert len(r.tokens) == k
    words = set(oracle.split_words(small_corpus))
    for t in r.tokens:
        assert t in words, t


def test_sample_smaller_population_returns_all():
    data = b"alpha beta gamma\n"
    r = sample_mod.sample_bytes(data, 10)
    assert r.total == 3
    assert sorted(r.tokens) == [b"alpha", b"beta", b"gamma"]


def test_sample_deterministic(small_corpus):
    a = sample_mod.sample_bytes(small_corpus, 20)
    b = sample_mod.sample_bytes(small_corpus, 20)
    assert a == b


def test_sample_k_validation():
    with pytest.raises(ValueError):
        sample_mod.ReservoirSampleJob(0)


def test_sample_spread_over_corpus():
    """1000 distinct single-occurrence tokens; the fixed 100-draw must be
    duplicate-free and touch every quarter of the corpus (a badly biased
    priority hash would fail this)."""
    tokens = [b"tok%04d" % i for i in range(1000)]
    data = b" ".join(tokens) + b"\n"
    r = sample_mod.sample_bytes(data, 100)
    assert len(set(r.tokens)) == 100  # without replacement
    idx = sorted(int(t[3:]) for t in r.tokens)
    for q in range(4):
        in_q = sum(1 for i in idx if q * 250 <= i < (q + 1) * 250)
        assert in_q >= 10, f"quarter {q} got only {in_q} of 100 draws"


@pytest.mark.slow
def test_merge_associative_commutative(small_corpus):
    """Bottom-k merge order must not change the result (collective safety)."""
    import jax

    cfg = Config(chunk_bytes=1024)
    job = sample_mod.ReservoirSampleJob(16, cfg)
    from mapreduce_tpu.ops.tokenize import pad_to

    thirds = [small_corpus[i::3] for i in range(3)]  # arbitrary split
    states = [job.map_chunk(jax.device_put(pad_to(t, 4096)), i)
              for i, t in enumerate(thirds)]
    a, b, c = states
    left = job.merge(job.merge(a, b), c)
    right = job.merge(a, job.merge(b, c))
    swapped = job.merge(c, job.merge(b, a))
    for l, r_, s in zip(jax.tree.leaves(left), jax.tree.leaves(right),
                        jax.tree.leaves(swapped)):
        np.testing.assert_array_equal(np.asarray(l), np.asarray(r_))
        np.testing.assert_array_equal(np.asarray(l), np.asarray(s))


def test_sample_file_streamed(tmp_path, small_corpus):
    from mapreduce_tpu.parallel.mesh import data_mesh

    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    cfg = Config(chunk_bytes=1024)
    r = sample_mod.sample_file(str(path), 25, config=cfg, mesh=data_mesh(4))
    assert r.total == oracle.total_count(small_corpus)
    assert len(r.tokens) == 25
    words = set(oracle.split_words(small_corpus))
    for t in r.tokens:
        assert t in words, t
    # Deterministic for fixed corpus + chunking.
    r2 = sample_mod.sample_file(str(path), 25, config=cfg, mesh=data_mesh(4))
    assert r.tokens == r2.tokens


def test_sample_cli(tmp_path, capsys):
    from mapreduce_tpu import cli

    path = tmp_path / "c.txt"
    path.write_bytes(b"aa bb cc dd ee ff gg hh\n")
    assert cli.main([str(path), "--sample", "3", "--format", "json"]) == 0
    import json

    obj = json.loads(capsys.readouterr().out)
    assert obj["total"] == 8 and len(obj["sample"]) == 3
    assert cli.main([str(path), "--sample", "3"]) == 0
    out = capsys.readouterr().out
    assert "Sampled:3 of 8\n" in out
    # Conflicting flags are honest errors.
    with pytest.raises(SystemExit):
        cli.main([str(path), "--sample", "3", "--top-k", "2"])
    with pytest.raises(SystemExit):
        cli.main([str(path), "--sample", "3", "--grep", "aa"])


def test_pallas_sample_matches_xla(small_corpus):
    """VERDICT r2 #6: the sample job honors config.resolved_backend(), and
    the pallas fused-kernel path draws the IDENTICAL sample (priorities
    depend only on (chunk_id, pos), shared by both backends)."""
    base = dict(chunk_bytes=1 << 14, table_capacity=1 << 10)
    sx = sample_mod.sample_bytes(small_corpus, 16, Config(**base, backend="xla"))
    sp = sample_mod.sample_bytes(small_corpus, 16, Config(**base, backend="pallas"))
    assert sx.tokens == sp.tokens
    assert sx.total == sp.total


def test_pallas_sample_streamed_deterministic(tmp_path, small_corpus):
    """Streamed pallas sampling: same corpus + chunking -> same sample, and
    it equals the streamed XLA sample (chunk ids and offsets agree)."""
    path = tmp_path / "c.txt"
    path.write_bytes(small_corpus)
    from mapreduce_tpu.parallel.mesh import data_mesh

    base = dict(chunk_bytes=128 * 66, table_capacity=1 << 10)
    sp1 = sample_mod.sample_file(str(path), 12,
                                 Config(**base, backend="pallas"),
                                 mesh=data_mesh(2))
    sp2 = sample_mod.sample_file(str(path), 12,
                                 Config(**base, backend="pallas"),
                                 mesh=data_mesh(2))
    sx = sample_mod.sample_file(str(path), 12, Config(**base, backend="xla"),
                                mesh=data_mesh(2))
    assert sp1.tokens == sp2.tokens  # deterministic
    assert sp1.tokens == sx.tokens  # backend-independent
    assert sp1.total == sx.total


def test_pallas_sample_excludes_overlong(tmp_path):
    """>W tokens are excluded from sample AND population (the family-wide
    pallas contract); the XLA backend samples them."""
    data = b"aa bb " + b"x" * 50 + b" cc dd ee ff gg hh\n"
    cfg = Config(chunk_bytes=1 << 14, table_capacity=1 << 10, backend="pallas")
    r = sample_mod.sample_bytes(data, 50, cfg)
    assert r.total == 8  # 9 tokens minus the overlong one
    assert all(b"x" * 50 != t for t in r.tokens)
