"""Multi-chip path tests on the emulated 8-device CPU mesh (SURVEY §4).

These exercise the real shard_map/collective code paths — the ones the driver
also dry-runs via __graft_entry__.dryrun_multichip — against the host oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models.wordcount import WordCountJob, TopKWordCountJob
from mapreduce_tpu.ops import table as table_ops
from mapreduce_tpu.parallel import collectives
from mapreduce_tpu.parallel.mapreduce import Engine
from mapreduce_tpu.parallel.mesh import data_mesh
from mapreduce_tpu.utils import oracle
from tests.conftest import make_corpus

CFG = Config(chunk_bytes=512, table_capacity=1024)


def _batches(data: bytes, n_dev: int, chunk: int):
    """Boundary-aligned [n_dev, chunk] batches via the reader, from memory."""
    from mapreduce_tpu.data import reader as r
    import tempfile, os

    with tempfile.NamedTemporaryFile(delete=False) as f:
        f.write(data)
        path = f.name
    try:
        yield from r.iter_batches(path, n_dev, chunk)
    finally:
        os.unlink(path)


def _table_dict(t):
    c = np.asarray(t.count)
    return {(int(h), int(l)): int(n) for h, l, n in
            zip(np.asarray(t.key_hi), np.asarray(t.key_lo), c) if n > 0}


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return data_mesh(8)


@pytest.mark.parametrize("strategy", ["tree", "gather"])
@pytest.mark.slow
def test_engine_wordcount_matches_oracle(mesh8, rng, strategy):
    corpus = make_corpus(rng, n_words=5000, vocab=300)
    eng = Engine(WordCountJob(CFG), mesh8, merge_strategy=strategy)
    batches = [b.data for b in _batches(corpus, 8, CFG.chunk_bytes)]
    assert len(batches) > 1  # actually exercises streaming
    result = eng.run(batches)
    expected = oracle.word_counts(corpus)
    assert int(result.n_valid()) == len(expected)
    assert sorted(_table_dict(result).values()) == sorted(expected.values())
    assert int(result.total_count()) == oracle.total_count(corpus)


@pytest.mark.slow
def test_mesh_sizes_agree(rng):
    """Same corpus, meshes of 1/2/4/8 devices: identical count multisets."""
    corpus = make_corpus(rng, n_words=2000, vocab=120)
    results = {}
    for d in (1, 2, 4, 8):
        eng = Engine(WordCountJob(CFG), data_mesh(d))
        batches = [b.data for b in _batches(corpus, d, CFG.chunk_bytes)]
        results[d] = _table_dict(eng.run(batches))
    assert results[1] == results[2] == results[4] == results[8]


@pytest.mark.slow
def test_gather_merge_non_power_of_two(rng):
    corpus = make_corpus(rng, n_words=1000, vocab=80)
    eng = Engine(WordCountJob(CFG), data_mesh(3), merge_strategy="tree")  # falls back
    batches = [b.data for b in _batches(corpus, 3, CFG.chunk_bytes)]
    result = eng.run(batches)
    assert sorted(_table_dict(result).values()) == \
        sorted(oracle.word_counts(corpus).values())


@pytest.mark.slow
def test_top_k_job(mesh8, rng):
    corpus = make_corpus(rng, n_words=3000, vocab=200)
    eng = Engine(TopKWordCountJob(10, CFG), mesh8)
    batches = [b.data for b in _batches(corpus, 8, CFG.chunk_bytes)]
    result = eng.run(batches)
    # Top-k finalize bundles the table with its pre-reorder KMV snapshot.
    tbl = result.table
    got = sorted(np.asarray(tbl.count)[np.asarray(tbl.count) > 0].tolist(), reverse=True)
    expected = sorted(oracle.word_counts(corpus).values(), reverse=True)[:10]
    assert got == expected
    # Nothing spilled here: occupancy below capacity, so no estimate — the
    # snapshot still reports the true occupancy.
    assert int(result.kmv_n_valid) == len(oracle.word_counts(corpus))


def test_psum_collective(mesh8):
    """Scalar totals ride the native psum path (the north-star collective)."""
    from jax.sharding import PartitionSpec as P

    from mapreduce_tpu.parallel.compat import shard_map

    def f(x):
        return collectives.psum(x.sum(), "data")

    fn = shard_map(f, mesh=mesh8, in_specs=(P("data"),), out_specs=P())
    out = jax.jit(fn)(np.arange(64, dtype=np.int32))
    assert int(out) == 64 * 63 // 2


@pytest.mark.slow
def test_step_many_equals_repeated_steps(mesh8, rng):
    """One superstep dispatch (lax.scan over K chunks) must produce exactly
    the same state as K individual steps, chunk_ids included."""
    corpus = make_corpus(rng, n_words=6000, vocab=250)
    batches = [b.data for b in _batches(corpus, 8, CFG.chunk_bytes)]
    k = len(batches)
    assert k >= 2

    eng_a = Engine(WordCountJob(CFG), mesh8)
    state_a = eng_a.init_states()
    for i, b in enumerate(batches):
        state_a = eng_a.step(state_a, b, i)
    final_a = eng_a.finish(state_a)

    eng_b = Engine(WordCountJob(CFG), mesh8)
    state_b = eng_b.init_states()
    stacked = np.stack(batches, axis=1)  # [D, K, C]
    state_b = eng_b.step_many(state_b, stacked, 0)
    final_b = eng_b.finish(state_b)

    for fa, fb in zip(final_a, final_b):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


@pytest.mark.slow
def test_step_many_mixed_with_single_steps(mesh8, rng):
    """step_many must compose with step() (remainder batches) seamlessly."""
    corpus = make_corpus(rng, n_words=6000, vocab=250)
    batches = [b.data for b in _batches(corpus, 8, CFG.chunk_bytes)]
    assert len(batches) >= 3
    head, tail = batches[:2], batches[2:]

    eng = Engine(WordCountJob(CFG), mesh8)
    state = eng.init_states()
    state = eng.step_many(state, np.stack(head, axis=1), 0)
    for j, b in enumerate(tail):
        state = eng.step(state, b, len(head) + j)
    result = eng.finish(state)

    expected = oracle.word_counts(corpus)
    assert sorted(_table_dict(result).values()) == sorted(expected.values())
    assert int(result.total_count()) == oracle.total_count(corpus)


@pytest.mark.slow
def test_two_level_mesh_engine_matches_oracle(rng):
    """2-D ('replica','data') mesh with hierarchical (ICI-then-DCN) merge:
    the multi-slice topology of SURVEY §7 step 4, emulated as 2x4 CPU."""
    from mapreduce_tpu.parallel.mesh import two_level_mesh

    corpus = make_corpus(rng, n_words=5000, vocab=300)
    mesh = two_level_mesh(2, 4)
    eng = Engine(WordCountJob(CFG), mesh, axis=("replica", "data"))
    assert eng.n_devices == 8
    batches = [b.data for b in _batches(corpus, 8, CFG.chunk_bytes)]
    result = eng.run(batches)
    expected = oracle.word_counts(corpus)
    assert int(result.n_valid()) == len(expected)
    assert sorted(_table_dict(result).values()) == sorted(expected.values())
    assert int(result.total_count()) == oracle.total_count(corpus)


@pytest.mark.slow
def test_two_level_matches_flat_mesh(rng):
    """Same devices, 1-D vs 2-D mesh: identical tables (chunk ids and all)."""
    from mapreduce_tpu.parallel.mesh import two_level_mesh

    corpus = make_corpus(rng, n_words=4000, vocab=150)
    batches = [b.data for b in _batches(corpus, 8, CFG.chunk_bytes)]

    flat = Engine(WordCountJob(CFG), data_mesh(8)).run(batches)
    two = Engine(WordCountJob(CFG), two_level_mesh(2, 4),
                 axis=("replica", "data")).run(batches)
    for fa, fb in zip(flat, two):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


@pytest.mark.slow
def test_count_file_over_two_level_mesh(tmp_path, rng):
    """The streaming executor must shard over ALL axes of a 2-D mesh (8
    shards from 2x4), not just the leading one."""
    from mapreduce_tpu.parallel.mesh import two_level_mesh
    from mapreduce_tpu.runtime import executor

    corpus = make_corpus(rng, n_words=4000, vocab=150)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    r = executor.count_file(str(path), config=CFG, mesh=two_level_mesh(2, 4))
    assert {w: c for w, c in zip(r.words, r.counts)} == oracle.word_counts(corpus)


@pytest.mark.slow
def test_step_many_repeats_equals_repeated_dispatch():
    """step_many(repeats=R) == R sequential step_many calls over the same
    chunks with advancing step indices (epoch semantics)."""
    import numpy as np
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.parallel.mapreduce import Engine
    from mapreduce_tpu.parallel.mesh import data_mesh

    cfg = Config(chunk_bytes=256, table_capacity=512, backend="xla")
    rng = np.random.default_rng(5)
    chunks = rng.integers(97, 110, size=(4, 2, 256), dtype=np.uint8)
    chunks[rng.random(chunks.shape) < 0.2] = 0x20

    eng1 = Engine(WordCountJob(cfg), data_mesh(4))
    s1 = eng1.init_states()
    s1 = eng1.step_many(s1, chunks, 0, repeats=3)
    t1 = eng1.finish(s1)

    eng2 = Engine(WordCountJob(cfg), data_mesh(4))
    s2 = eng2.init_states()
    for r in range(3):
        s2 = eng2.step_many(s2, chunks, r * 2)
    t2 = eng2.finish(s2)

    for a, b in zip(t1, t2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --- key-range all_to_all merge (VERDICT r3 #3) ------------------------------


@pytest.mark.slow
def test_keyrange_engine_matches_oracle(mesh8, rng):
    corpus = make_corpus(rng, n_words=5000, vocab=300)
    eng = Engine(WordCountJob(CFG), mesh8, merge_strategy="keyrange")
    batches = [b.data for b in _batches(corpus, 8, CFG.chunk_bytes)]
    result = eng.run(batches)
    expected = oracle.word_counts(corpus)
    assert sorted(_table_dict(result).values()) == sorted(expected.values())
    assert int(result.total_count()) == oracle.total_count(corpus)


@pytest.mark.slow
def test_keyrange_bit_identical_to_tree(mesh8, rng):
    """No-spill runs: keyrange and tree produce the same table, field for
    field (kept keys, counts, first occurrences, dropped scalars)."""
    corpus = make_corpus(rng, n_words=4000, vocab=200)
    batches = [b.data for b in _batches(corpus, 8, CFG.chunk_bytes)]
    tree = Engine(WordCountJob(CFG), mesh8, merge_strategy="tree").run(batches)
    keyr = Engine(WordCountJob(CFG), mesh8, merge_strategy="keyrange").run(batches)
    for fa, fb in zip(tree, keyr):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


@pytest.mark.slow
def test_keyrange_non_power_of_two(rng):
    """all_to_all has no power-of-two constraint (unlike the butterfly)."""
    corpus = make_corpus(rng, n_words=1500, vocab=90)
    eng = Engine(WordCountJob(CFG), data_mesh(3), merge_strategy="keyrange")
    batches = [b.data for b in _batches(corpus, 3, CFG.chunk_bytes)]
    result = eng.run(batches)
    assert sorted(_table_dict(result).values()) == \
        sorted(oracle.word_counts(corpus).values())


@pytest.mark.slow
def test_keyrange_two_level_mesh(rng):
    """Tuple axes: the keyrange round flattens the 2-D mesh."""
    from mapreduce_tpu.parallel.mesh import two_level_mesh

    corpus = make_corpus(rng, n_words=3000, vocab=150)
    batches = [b.data for b in _batches(corpus, 8, CFG.chunk_bytes)]
    flat = Engine(WordCountJob(CFG), data_mesh(8),
                  merge_strategy="keyrange").run(batches)
    two = Engine(WordCountJob(CFG), two_level_mesh(2, 4),
                 axis=("replica", "data"), merge_strategy="keyrange").run(batches)
    for fa, fb in zip(flat, two):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


def test_keyrange_unsupported_job_raises(mesh8):
    from mapreduce_tpu.models.grep import GrepJob

    with pytest.raises(ValueError, match="keyrange"):
        Engine(GrepJob(b"x"), mesh8, merge_strategy="keyrange")


def _crafted_tables(n_dev: int, cap: int, keys_per_dev, rng):
    """Stacked per-device tables with CHOSEN (key_hi, key_lo) rows (count 1
    each, distinct pos), built through the real _build path so invariants
    hold.  keys_per_dev: list of lists of (hi, lo) pairs."""
    stacked = []
    for d, keys in enumerate(keys_per_dev):
        n = max(len(keys), 1)
        pad = -(-n // 8) * 8
        khi = np.full((pad,), 0xFFFFFFFF, np.uint32)
        klo = np.full((pad,), 0xFFFFFFFF, np.uint32)
        cnt = np.zeros((pad,), np.uint32)
        for i, (hi, lo) in enumerate(keys):
            khi[i], klo[i], cnt[i] = hi, lo, 1
        phi = np.where(cnt > 0, np.uint32(d), np.uint32(0xFFFFFFFF)).astype(np.uint32)
        plo = np.arange(pad, dtype=np.uint32)
        plo = np.where(cnt > 0, plo, np.uint32(0xFFFFFFFF)).astype(np.uint32)
        ln = np.where(cnt > 0, np.uint32(3), np.uint32(0)).astype(np.uint32)
        z = jnp.uint32(0)
        t = table_ops._build(jnp.asarray(khi), jnp.asarray(klo),
                             jnp.asarray(phi), jnp.asarray(plo),
                             jnp.asarray(cnt), jnp.zeros((pad,), jnp.uint32),
                             jnp.asarray(ln), cap, z, z, z, z)
        stacked.append(t)
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stacked)


def _run_collective(mesh, fn, stacked):
    from jax.sharding import PartitionSpec as P

    from mapreduce_tpu.parallel.compat import shard_map

    def body(state):
        local = jax.tree.map(lambda x: x[0], state)
        return fn(local)

    wrapped = shard_map(body, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
                        check_vma=False)
    return jax.tree.map(np.asarray, jax.jit(wrapped)(stacked))


@pytest.mark.slow
def test_keyrange_budget_spill_never_partial(mesh8, rng):
    """Force one partition past the B = slack*C/D budget on one device: the
    spilled keys must be fully evicted everywhere (never reported with a
    partial count) and the mass exactly accounted in dropped_count."""
    cap, n_dev = 64, 8
    b = -(-2 * cap // n_dev)  # 16: the budget key_range_merge derives
    # Device 0: 3*b keys all landing in partition 3 (key_lo % 8 == 3).
    hot = [(0x1000 + i, 8 * i + 3) for i in range(3 * b)]
    # Device 1 holds copies of the 8 LARGEST hot keys (they will be budget-
    # spilled on device 0) plus its own distinct keys in other partitions.
    copies = hot[-8:]
    own = [(0x9000 + i, 8 * i + 5) for i in range(10)]
    tables = _crafted_tables(
        n_dev, cap, [hot, copies + own] + [[] for _ in range(n_dev - 2)], rng)

    merged = _run_collective(
        data_mesh(n_dev), lambda t: collectives.key_range_merge(t, "data"),
        tables)

    kept = {(int(h), int(l)): int(c) for h, l, c in
            zip(merged.key_hi, merged.key_lo, merged.count) if c}
    # True multiset: hot keys count 1 (dev0) except the 8 copied ones count 2.
    truth = {k: 1 for k in hot}
    for k in copies:
        truth[k] = 2
    for k in own:
        truth[k] = 1
    # Invariant: every kept key carries its FULL true count.
    for k, c in kept.items():
        assert truth[k] == c, (k, c)
    # The budget forced spill: some hot keys are gone, but all mass is
    # accounted — kept + dropped == total emitted.
    assert len(kept) < len(truth)
    _, dc = merged.dropped_totals()
    assert sum(kept.values()) + dc == sum(truth.values())
    # Spill is deterministic largest-first: every SURVIVING hot key is
    # smaller than every spilled one.
    spilled = sorted(set(truth) - set(kept))
    if spilled:
        surviving_hot = [k for k in kept if k[1] % 8 == 3]
        assert max(surviving_hot, default=(0, 0)) < min(spilled)


@pytest.mark.slow
def test_keyrange_count_file_end_to_end(tmp_path, rng):
    """merge_strategy plumbs through run_job/count_file."""
    from mapreduce_tpu.runtime import executor

    corpus = make_corpus(rng, n_words=3000, vocab=150)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    r = executor.count_file(str(path), config=CFG, mesh=data_mesh(8),
                            merge_strategy="keyrange")
    assert {w: c for w, c in zip(r.words, r.counts)} == oracle.word_counts(corpus)


@pytest.mark.slow
def test_keyrange_tiny_capacity_skewed_partitions(mesh8, rng):
    """The small-C/D budget regime (round-5 D=256 scale-dryrun bug): with
    capacity/D of order 1, balls-in-bins max partition load exceeds any
    purely multiplicative slack, so the old ``b = ceil(2C/D)`` budget
    spilled REAL keys and keyrange (correctly, per the spill contract)
    diverged from tree on the kept set.  The additive ``+ 8 + 4 log2 D``
    term must keep tiny tables bit-identical to tree — across many seeds
    so skewed ``key_lo % D`` partitions actually occur."""
    cfg = Config(chunk_bytes=512, table_capacity=16)
    # Engines hoisted out of the seed loop: each instance caches its own
    # jitted programs, and batch shapes are identical across seeds.
    eng_tree = Engine(WordCountJob(cfg), mesh8, merge_strategy="tree")
    eng_keyr = Engine(WordCountJob(cfg), mesh8, merge_strategy="keyrange")
    for seed in range(5):
        r2 = np.random.default_rng(1000 + seed)
        corpus = make_corpus(r2, n_words=600, vocab=40)
        batches = [b.data for b in _batches(corpus, 8, cfg.chunk_bytes)]
        tree = eng_tree.run(batches)
        keyr = eng_keyr.run(batches)
        for f in tree._fields:
            if f.startswith("dropped_uniques"):
                continue  # documented bound-looseness difference
            np.testing.assert_array_equal(
                np.asarray(getattr(tree, f)), np.asarray(getattr(keyr, f)),
                err_msg=f"{f} diverged at seed {seed}")
        assert keyr.dropped_totals()[0] <= tree.dropped_totals()[0]
