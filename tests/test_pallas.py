"""Pallas tokenize kernel vs. the XLA scan oracle (interpret mode on CPU).

SURVEY §4: kernel-level tests compare Pallas output to the pure-JAX oracle
under ``interpret=True``.  Tables built from either backend must be
field-for-field identical (same hashes, counts, first-occurrence positions)
for every token within the W-byte envelope; overlong tokens must be dropped
into exact ``dropped_*`` accounting.
"""

import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.ops import table as tbl
from mapreduce_tpu.ops import tokenize as tok
from mapreduce_tpu.ops.pallas import tokenize as ptok
from mapreduce_tpu.utils import oracle
from tests.conftest import make_corpus

W = 8  # small lookback so tests exercise the overlong path cheaply
CAP = 4096


def _pad(data: bytes, w: int = W) -> np.ndarray:
    n = max(128 * (2 * w + 2), -(-len(data) // 128) * 128)
    return tok.pad_to(data, n)


def _tables(data: bytes, w: int = W, block_rows: int = 64):
    buf = _pad(data, w)
    stream_x = tok.tokenize(buf)
    want = tbl.from_stream(stream_x, CAP)
    stream_p, overlong = ptok.tokenize(buf, max_token_bytes=w,
                                       block_rows=block_rows, interpret=True)
    got = tbl.from_stream(stream_p, CAP)
    return want, got, int(overlong)


def _assert_tables_equal(want, got):
    for f in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, f)), np.asarray(getattr(got, f)), err_msg=f)


@pytest.mark.smoke
def test_fixture_exact(fixture_text):
    want, got, overlong = _tables(fixture_text)
    assert overlong == 0
    _assert_tables_equal(want, got)


def test_random_corpus_exact(rng):
    corpus = make_corpus(rng, n_words=3000, vocab=200)  # words well under W
    want, got, overlong = _tables(corpus)
    assert overlong == 0
    _assert_tables_equal(want, got)


def test_tokens_at_exact_w_boundary():
    # length W is on the fast path; W+1 is overlong.
    data = (b"x" * W + b" " + b"y" * W + b"\n") * 40
    want, got, overlong = _tables(data)
    assert overlong == 0
    _assert_tables_equal(want, got)


def test_overlong_tokens_dropped_and_counted():
    data = b"short " * 50 + b"z" * (W + 1) + b" tail " + b"q" * (3 * W) + b"\n"
    buf = _pad(data)
    stream_p, overlong = ptok.tokenize(buf, max_token_bytes=W,
                                       block_rows=64, interpret=True)
    got = tbl.from_stream(stream_p, CAP)
    assert int(overlong) == 2  # the two overlong runs, once each
    # Every short token still counted exactly.
    counts = oracle.word_counts(data)
    short_total = sum(c for word, c in counts.items() if len(word) <= W)
    assert int(got.total_count()) == short_total
    assert int(got.n_valid()) == len([w for w in counts if len(w) <= W])


def test_lane_seam_tokens(rng):
    """Tokens placed to straddle the 128-lane segment seams exactly."""
    w = 8
    n = 128 * (2 * w + 2)  # minimum size: every seam is close to its neighbors
    seg = n // 128
    buf = np.full(n, 0x20, dtype=np.uint8)
    # A word crossing every seam j*seg for j=1..127, plus chunk start/end.
    for j in range(1, 128):
        s = j * seg - 3
        buf[s:s + 6] = np.frombuffer(b"abcdef", dtype=np.uint8)
    buf[:4] = np.frombuffer(b"head", dtype=np.uint8)
    buf[-4:] = np.frombuffer(b"tail", dtype=np.uint8)
    want = tbl.from_stream(tok.tokenize(buf), CAP)
    stream_p, overlong = ptok.tokenize(buf, max_token_bytes=w,
                                       block_rows=32, interpret=True)
    got = tbl.from_stream(stream_p, CAP)
    assert int(overlong) == 0
    _assert_tables_equal(want, got)


@pytest.mark.slow
def test_count_words_pallas_backend(rng):
    corpus = make_corpus(rng, n_words=1500, vocab=120)
    cfg = Config(chunk_bytes=128 * (2 * 32 + 2), table_capacity=CAP,
                 backend="pallas")
    with _interpret_mode():
        result = wordcount.count_words(corpus, cfg)
    assert result.as_dict() == oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)


@pytest.mark.slow
def test_streaming_executor_pallas_backend(tmp_path, rng):
    """The full sharded streaming path (shard_map-traced pallas_call, padded
    rows, overlong accounting through merge) with backend='pallas'."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    corpus = make_corpus(rng, n_words=4000, vocab=200)
    path = tmp_path / "corpus.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=128 * (2 * 32 + 2), table_capacity=CAP,
                 backend="pallas")
    result = executor.count_file(str(path), cfg, mesh=data_mesh(4))
    assert result.as_dict() == oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)


def _interpret_mode():
    from tests.conftest import pallas_interpret_mode

    return pallas_interpret_mode()


def test_packed_bounds_validation():
    """The packed sort payload pins the kernel's envelope: 26-bit positions
    (64 MB chunks) and 6-bit lengths (W <= 63); out-of-envelope requests
    fail loudly instead of wrapping."""
    import jax.numpy as jnp
    import pytest
    from mapreduce_tpu.ops.pallas import tokenize as pt

    data = jnp.zeros((1 << 12,), jnp.uint8)
    with pytest.raises(ValueError, match="64 MB"):
        pt.tokenize_split(jnp.zeros(((1 << 26) + 128,), jnp.uint8))
    with pytest.raises(ValueError, match="<= 63"):
        pt.tokenize_split(data, max_token_bytes=64)


def test_packed_stream_consistency(small_corpus):
    """PackedTokenStream's packed plane and total agree with its own
    reconstructed pos/length/count fields."""
    import numpy as np
    from mapreduce_tpu.ops import tokenize as tok_ops
    from mapreduce_tpu.ops.pallas import tokenize as pt

    # Lane segments must cover the 2W+2 seam window: >= 66*128 bytes.
    padded_len = max(-(-len(small_corpus) // 128) * 128, 128 * 128)
    buf = tok_ops.pad_to(np.frombuffer(small_corpus, np.uint8), padded_len)
    col, seam, over = pt.tokenize_split(buf)
    packed = np.asarray(col.packed)
    count = np.asarray(col.count)
    has = packed != 0xFFFFFFFF
    assert np.array_equal(has.astype(np.uint32), count)
    assert int(col.total) == int(count.sum())
    np.testing.assert_array_equal(np.asarray(col.pos)[has], (packed >> 6)[has])
    np.testing.assert_array_equal(np.asarray(col.length)[has],
                                  (packed & 63)[has])


# --- slot compaction (VERDICT r4 #2) -----------------------------------------


def _compact_table(data: bytes, slots: int, w: int = W, block_rows: int = 64):
    buf = _pad(data, w)
    col, seam, overlong, spill = ptok.tokenize_split_compact(
        buf, slots, max_token_bytes=w, block_rows=block_rows, interpret=True)
    stream = ptok.concat_streams(col, seam)
    t = tbl.from_stream(stream, CAP, max_token_bytes=w,
                        max_pos=int(buf.shape[0]))
    return t, int(overlong), int(spill)


def test_compact_bit_identical_when_no_spill(rng):
    corpus = make_corpus(rng, n_words=3000, vocab=200)
    want, got_full, _ = _tables(corpus)
    got, overlong, spill = _compact_table(corpus, slots=24)
    assert spill == 0
    _assert_tables_equal(want, got)


def test_compact_fixture_exact(fixture_text):
    want, _, _ = _tables(fixture_text)
    got, _, spill = _compact_table(fixture_text, slots=24)
    assert spill == 0
    _assert_tables_equal(want, got)


def test_compact_spill_detected_on_dense_text():
    """Alternating single-letter tokens: density 1/2 overflows any budget
    below block_rows/2, and the kernel must say so."""
    data = b"a " * 2048
    got, _, spill = _compact_table(data, slots=8, block_rows=64)
    assert spill > 0


@pytest.mark.slow
def test_compact_map_stream_falls_back_exactly(rng):
    """_map_stream's lax.cond: a spilling chunk silently reruns the full
    path — results must equal the XLA oracle for ANY density."""
    import jax
    import jax.numpy as jnp

    from mapreduce_tpu.models.wordcount import _map_stream

    for data in (b"a b " * 1024,          # density 1/2: always spills
                 make_corpus(np.random.default_rng(5), 2000, 150)):
        cfg = Config(backend="pallas", chunk_bytes=1 << 14, sort_mode="sort3",
                     compact_slots=8, pallas_max_token=32)
        buf = tok.pad_to(np.frombuffer(data, np.uint8),
                         max(cfg.pallas_min_chunk,
                             -(-len(data) // 128) * 128))
        t = jax.jit(lambda b: _map_stream(b, cfg, CAP))(jnp.asarray(buf))
        want = tbl.from_stream(tok.tokenize(jnp.asarray(buf)), CAP)
        _assert_tables_equal(want, t)


@pytest.mark.slow
def test_compact_density_sweep_bit_identical(rng):
    """Log-shift compaction across the density spectrum: separator-heavy
    (long movement distances), long runs (overlong poison rows riding the
    shift), and mixed densities — every no-spill case must equal the
    FULL-RESOLUTION pallas table bit for bit (the compaction invariant;
    the full path owns the W contract, so overlong mixes are in scope).
    Guards the shift algorithm's distance bookkeeping (movement = per-lane
    dead-row count, applied one binary bit per pass), whose failure modes
    are density-dependent in ways the two bench corpora never exercise."""
    cases = [
        b" " * 4000 + b"word " * 20,               # almost-empty lanes
        (b"a" * 30 + b" ") * 300,                  # overlong runs: poisons move
        b"ab " * 1500,                             # density 1/3
        b"abcd " * 1000,                           # density 1/5
        bytes(rng.integers(97, 100, 6000).tobytes())
        .replace(b"c", b" "),                      # random ~1/3 separators
        (b"w " * 10 + b"token " + b"\n") * 250,    # dense-but-fitting lanes
    ]
    for data in cases:
        _, got_full, overlong_full = _tables(data)
        got, overlong_c, spill = _compact_table(data, slots=24)
        assert spill == 0, data[:20]
        assert overlong_c == overlong_full
        _assert_tables_equal(got_full, got)


def test_compact_overlong_accounting(rng):
    """Overlong poison rows survive compaction: dropped_* match the full
    path's accounting bit for bit."""
    words = [b"x" * 3, b"y" * (W + 5), b"zz", b"q" * (2 * W)]
    corpus = b" ".join(words[int(i)] for i in rng.integers(0, 4, 600)) + b" "
    # Reference is the FULL-resolution pallas table (the XLA oracle keeps
    # >W words both pallas paths drop by contract).
    _, got_full, overlong_full = _tables(corpus)
    got, overlong_c, spill = _compact_table(corpus, slots=24)
    assert spill == 0
    assert overlong_c == overlong_full > 0
    _assert_tables_equal(got_full, got)


def test_compact_slots_validation():
    with pytest.raises(ValueError, match="compact_slots"):
        Config(compact_slots=12, sort_mode="sort3")  # not a multiple of 8
    with pytest.raises(ValueError, match="compact_slots"):
        Config(compact_slots=136, sort_mode="sort3")  # > 128
    with pytest.raises(ValueError, match="compact_slots"):
        ptok.tokenize_split_compact(
            tok.pad_to(b"hello world", 128 * 18), 48,
            max_token_bytes=8, block_rows=64, interpret=True)  # > block/2


@pytest.mark.slow
def test_natural_corpus_backends_agree():
    """VERDICT r3 #6: on the natural-proxy corpus the pallas and xla
    backends must produce the SAME table — tools/density.py measured zero
    >W tokens there (max 18 bytes), so the >W envelope costs nothing on
    the bench corpora (BENCHMARKS.md round-4 section quantifies this)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import make_natural_corpus

    corpus = make_natural_corpus(1 << 18)
    buf = tok.pad_to(corpus, -(-len(corpus) // 128) * 128)
    want = tbl.from_stream(tok.tokenize(buf), CAP)
    stream_p, overlong = ptok.tokenize(buf, max_token_bytes=32,
                                       interpret=True)
    assert int(overlong) == 0
    got = tbl.from_stream(stream_p, CAP)
    _assert_tables_equal(want, got)
    # And through the compact path, same story.
    col, seam, over_c, spill = ptok.tokenize_split_compact(
        buf, 88, max_token_bytes=32, interpret=True)
    assert int(spill) == 0 and int(over_c) == 0
    got_c = tbl.from_stream(ptok.concat_streams(col, seam), CAP,
                            max_token_bytes=32, max_pos=int(buf.shape[0]))
    _assert_tables_equal(want, got_c)
