"""Worker process for the run_job_global multi-process test.

Drives the EXECUTOR's global-SPMD entry point (``executor.run_job_global``,
VERDICT r3 #5) end-to-end: ``jax.distributed.initialize`` over gloo, a
global mesh spanning both processes, per-process ``host_shards`` staging,
coordinator-only checkpointing — and, when ``crash_at_step >= 0``, a
deterministic injected failure on EVERY process at that step (both raise
together, so no peer is left blocked in a collective), exercising the
checkpoint/resume recovery path a second launch completes.

Usage: python global_worker.py <process_id> <n_processes> <port> \
    <corpus_path> <chunk_bytes> <devices_per_process> <ckpt_path> \
    <crash_at_step> [ledger_path] [fault_plan]

``ledger_path`` (optional, ISSUE 13): attach full telemetry at that
shared path — every process then writes its own ``<ledger>.h<p>.jsonl``
shard (with a shared run_id, so fleet merges pair runs explicitly), the
coordinator the main file, and a crash dumps each host's flight recorder
to its host-suffixed path.

``fault_plan`` (optional, ISSUE 15): a ``Config.fault_plan`` spec fired
through the executor's real injection seams — ``at=process-kill:N:...``
is the multi-host hard-kill chaos scenario (``os._exit(113)`` between
dispatched groups on every process at the same deterministic crossing,
exactly like a synchronized platform reclaim; the relaunch resumes from
the coordinator's checkpoint).

``GW_MERGE_OVERLAP=1`` (env, ISSUE 20): window-boundary partial merges
with ``inflight_groups=1`` (a partial every retired group, so even the
tiny test corpus crosses several boundaries); ``GW_MERGE_STRATEGY``
overrides the collective strategy.  Env-carried so the positional argv
contract above stays stable.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    pid, n_proc = int(sys.argv[1]), int(sys.argv[2])
    port, path = sys.argv[3], sys.argv[4]
    chunk_bytes, dev_per_proc = int(sys.argv[5]), int(sys.argv[6])
    ckpt_path, crash_at = sys.argv[7], int(sys.argv[8])
    ledger_path = sys.argv[9] if len(sys.argv) > 9 else None
    fault_plan = sys.argv[10] if len(sys.argv) > 10 else None

    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={dev_per_proc}")
    from mapreduce_tpu.runtime.platform import force_cpu

    jax = force_cpu(verify=False)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from mapreduce_tpu.parallel import distributed as dist

    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=n_proc, process_id=pid, timeout_s=60)

    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.runtime import executor

    if crash_at >= 0:
        # Deterministic synchronized failure: every process raises before
        # dispatching step `crash_at`, after identical checkpoints exist.
        from mapreduce_tpu.parallel import mapreduce as mr

        original = mr.Engine.step

        def crashing_step(self, state, chunks, step_index):
            if int(step_index) >= crash_at:
                raise RuntimeError(f"injected crash at step {step_index}")
            return original(self, state, chunks, step_index)

        mr.Engine.step = crashing_step

    overlap = os.environ.get("GW_MERGE_OVERLAP") == "1"
    cfg = Config(chunk_bytes=chunk_bytes, table_capacity=1 << 10,
                 fault_plan=fault_plan or None,
                 merge_strategy=os.environ.get("GW_MERGE_STRATEGY",
                                               "tree"),
                 merge_overlap=overlap,
                 **({"inflight_groups": 1} if overlap else {}))
    telemetry = None
    if ledger_path:
        from mapreduce_tpu.obs import Telemetry

        # A shared run_id makes the shard pairing explicit (the fleet
        # merge's documented multi-host contract).
        telemetry = Telemetry.create(ledger_path=ledger_path,
                                     run_id="gw-fleet")
    try:
        rr = executor.run_job_global(WordCountJob(cfg), path, config=cfg,
                                     checkpoint_path=ckpt_path,
                                     checkpoint_every=1,
                                     telemetry=telemetry)
    except RuntimeError as e:
        if "injected crash" in str(e):
            print(json.dumps({"crashed": True, "process": pid}))
            return 17  # distinct code: the parent asserts the injection fired
        raise
    finally:
        if telemetry is not None:
            telemetry.close()

    table = rr.value
    if dist.is_coordinator():
        live = (table.count > 0) | (table.count_hi > 0)
        counts = sorted(int(c) for c in table.count[live])
        print(json.dumps({
            "total": int(table.total_count()),
            "counts": counts,
            "distinct": int(live.sum()),
            "resumed_bases_rows": int(rr.bases.shape[0]),
            "processes": n_proc,
            "devices": len(jax.devices()),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
