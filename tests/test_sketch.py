"""HyperLogLog sketch: accuracy, monoid laws, and end-to-end composition
with the streaming executor (the capacity-overflow case the exact table
cannot answer)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models.wordcount import SketchedWordCountJob, WordCountJob
from mapreduce_tpu.ops import sketch
from mapreduce_tpu.ops import tokenize as tok_ops
from mapreduce_tpu.runtime import executor
from mapreduce_tpu.utils import oracle


def _keys(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 2**32, size=n, dtype=np.uint32),
            rng.integers(0, 2**32, size=n, dtype=np.uint32))


def test_estimate_accuracy():
    for true_n in (100, 5_000, 50_000):
        hi, lo = _keys(true_n, seed=true_n)
        regs = sketch.update_from_keys(sketch.empty(), hi, lo,
                                       jnp.ones(true_n, bool))
        est = sketch.estimate(regs)
        assert abs(est - true_n) / true_n < 0.05, (true_n, est)


def test_update_is_idempotent():
    hi, lo = _keys(1000)
    r1 = sketch.update_from_keys(sketch.empty(), hi, lo, jnp.ones(1000, bool))
    r2 = sketch.update_from_keys(r1, hi, lo, jnp.ones(1000, bool))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))


def test_merge_monoid_laws():
    parts = [sketch.update_from_keys(sketch.empty(), *_keys(500, seed=s),
                                     jnp.ones(500, bool)) for s in range(3)]
    a, b, c = parts
    ab_c = sketch.merge(sketch.merge(a, b), c)
    a_bc = sketch.merge(a, sketch.merge(b, c))
    np.testing.assert_array_equal(np.asarray(ab_c), np.asarray(a_bc))
    np.testing.assert_array_equal(np.asarray(sketch.merge(a, b)),
                                  np.asarray(sketch.merge(b, a)))
    np.testing.assert_array_equal(np.asarray(sketch.merge(a, a)), np.asarray(a))


def test_merge_of_parts_equals_single_pass():
    hi, lo = _keys(4000)
    whole = sketch.update_from_keys(sketch.empty(), hi, lo, jnp.ones(4000, bool))
    halves = sketch.merge(
        sketch.update_from_keys(sketch.empty(), hi[:2000], lo[:2000], jnp.ones(2000, bool)),
        sketch.update_from_keys(sketch.empty(), hi[2000:], lo[2000:], jnp.ones(2000, bool)))
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(halves))


def test_invalid_rows_are_ignored():
    hi, lo = _keys(100)
    regs = sketch.update_from_keys(sketch.empty(), hi, lo, jnp.zeros(100, bool))
    assert int(np.asarray(regs).sum()) == 0


def test_precision_validation():
    with pytest.raises(ValueError):
        sketch.empty(2)


def test_sketched_run_survives_table_overflow(tmp_path, rng):
    """1500 distinct words through a 256-slot table: `distinct` is a loose
    bound, the sketch estimate stays within ~10% (p=14, small-range mode)."""
    words = [f"w{i:04d}".encode() for i in range(1500)]
    body = b" ".join([words[i] for i in rng.permutation(1500)] +
                     [words[i % 1500] for i in rng.integers(0, 1500, 3000)])
    path = tmp_path / "c.txt"
    path.write_bytes(body + b"\n")
    cfg = Config(chunk_bytes=512, table_capacity=256)
    r = executor.count_file(str(path), config=cfg, distinct_sketch=True)
    true_distinct = len(oracle.word_counts(bytes(body)))
    assert true_distinct == 1500
    assert r.distinct_estimate is not None
    assert abs(r.distinct_estimate - 1500) / 1500 < 0.1
    assert r.total == 4500  # exact totals survive overflow regardless


@pytest.mark.slow
def test_sketched_tokens_match_real_hashes(small_corpus):
    """The sketch keys are the tokenizer's real 64-bit hashes: duplicates
    across chunks must not inflate the estimate."""
    cfg = Config(chunk_bytes=1 << 10, table_capacity=1 << 10)
    job = SketchedWordCountJob(WordCountJob(cfg))
    state = job.init_state()
    padded_len = -(-len(small_corpus) // 128) * 128
    stream = tok_ops.tokenize(tok_ops.pad_to(
        np.frombuffer(small_corpus, np.uint8), padded_len))
    from mapreduce_tpu.ops import table as table_ops

    batch = table_ops.from_stream(stream, 512)
    state = job.combine(state, batch)
    state = job.combine(state, batch)  # same chunk twice
    est = sketch.estimate(state.registers)
    true_distinct = len(oracle.word_counts(small_corpus))
    assert abs(est - true_distinct) / true_distinct < 0.25  # small-n noise


# --- Count-Min Sketch --------------------------------------------------------


def test_hash_word_matches_device(small_corpus):
    """hash_word is the exact host mirror of the device tokenizer's keys."""
    from mapreduce_tpu.ops import table as table_ops

    padded_len = -(-len(small_corpus) // 128) * 128
    stream = tok_ops.tokenize(tok_ops.pad_to(
        np.frombuffer(small_corpus, np.uint8), padded_len))
    tbl = table_ops.from_stream(stream, 1 << 12)
    count = np.asarray(tbl.count)
    valid = count > 0
    hi, lo = np.asarray(tbl.key_hi)[valid], np.asarray(tbl.key_lo)[valid]
    pos, length = np.asarray(tbl.pos_lo)[valid], np.asarray(tbl.length)[valid]
    device_keys = {}
    for h, l, p, n in zip(hi, lo, pos, length):
        device_keys[bytes(small_corpus[int(p): int(p) + int(n)])] = (int(h), int(l))
    assert len(device_keys) >= 100
    for word, key in device_keys.items():
        assert sketch.hash_word(word) == key, word


def test_cms_never_underestimates_and_is_tight(small_corpus):
    exact = oracle.word_counts(small_corpus)
    hi = np.array([sketch.hash_word(w)[0] for w in exact], dtype=np.uint32)
    lo = np.array([sketch.hash_word(w)[1] for w in exact], dtype=np.uint32)
    counts = np.array(list(exact.values()), dtype=np.uint32)
    cms = np.asarray(sketch.cms_update(sketch.cms_empty(), hi, lo, jnp.asarray(counts)))
    total = counts.sum()
    for w, c in exact.items():
        est = sketch.cms_query(cms, w)
        assert est >= c
        assert est <= c + max(4 * total // (1 << sketch.CMS_WIDTH_LOG2), 2)


def test_cms_merge_is_sum_of_parts():
    hi, lo = _keys(1000)
    counts = jnp.ones(1000, jnp.uint32)
    whole = sketch.cms_update(sketch.cms_empty(), hi, lo, counts)
    halves = sketch.cms_merge(
        sketch.cms_update(sketch.cms_empty(), hi[:500], lo[:500], counts[:500]),
        sketch.cms_update(sketch.cms_empty(), hi[500:], lo[500:], counts[500:]))
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(halves))


def test_cms_validation():
    with pytest.raises(ValueError):
        sketch.cms_empty(depth=0)
    with pytest.raises(ValueError):
        sketch.cms_empty(width_log2=4)


def test_count_sketch_run_answers_spilled_words(tmp_path, rng):
    """1500 distinct words through a 256-slot table: every word's frequency —
    retained or spilled — stays queryable via the CMS within its error bound."""
    words = [f"w{i:04d}".encode() for i in range(1500)]
    body = b" ".join([words[i] for i in rng.permutation(1500)] +
                     [words[i % 1500] for i in rng.integers(0, 1500, 3000)])
    path = tmp_path / "c.txt"
    path.write_bytes(body + b"\n")
    cfg = Config(chunk_bytes=512, table_capacity=256)
    r = executor.count_file(str(path), config=cfg, count_sketch=True)
    assert r.cms is not None
    exact = oracle.word_counts(bytes(body))
    err_bound = max(4 * r.total // (1 << sketch.CMS_WIDTH_LOG2), 2)
    checked = 0
    for w, c in list(exact.items())[::37]:  # sample the vocabulary
        est = r.estimate_count(w)
        assert est >= c, (w, est, c)
        assert est <= c + err_bound, (w, est, c)
        checked += 1
    assert checked >= 30
    assert r.estimate_count(b"never-seen-word") <= err_bound


def test_count_sketch_and_distinct_sketch_are_exclusive(tmp_path):
    path = tmp_path / "c.txt"
    path.write_bytes(b"a b c\n")
    with pytest.raises(ValueError):
        executor.count_file(str(path), count_sketch=True, distinct_sketch=True)


def test_hash_word_matches_device_grams(small_corpus):
    """hash_word mirrors the device's *gram* keys for multi-token spans."""
    from mapreduce_tpu.ops import table as table_ops

    padded_len = -(-len(small_corpus) // 128) * 128
    stream = tok_ops.ngrams(tok_ops.tokenize(tok_ops.pad_to(
        np.frombuffer(small_corpus, np.uint8), padded_len)), 2)
    tbl = table_ops.from_stream(stream, 1 << 13)
    valid = np.asarray(tbl.count) > 0
    hi, lo = np.asarray(tbl.key_hi)[valid], np.asarray(tbl.key_lo)[valid]
    pos, length = np.asarray(tbl.pos_lo)[valid], np.asarray(tbl.length)[valid]
    assert valid.sum() >= 100
    for h, l, p, n in zip(hi, lo, pos, length):
        span = bytes(small_corpus[int(p): int(p) + int(n)])
        assert sketch.hash_word(span) == (int(h), int(l)), span


@pytest.mark.slow
def test_count_sketch_composes_with_ngrams(tmp_path):
    """The PARITY claim the review flagged: ngram x count-sketch estimates
    must honor the never-under-estimate contract for span queries."""
    body = b"hello world " * 200 + b"other words here\n"
    path = tmp_path / "c.txt"
    path.write_bytes(body)
    cfg = Config(chunk_bytes=1 << 14, table_capacity=1 << 10)
    r = executor.count_file(str(path), config=cfg, ngram=2, count_sketch=True)
    true = r.as_dict()[b"hello world"]
    assert true >= 199  # exact table agrees (one chunk, no seams at this size)
    est = r.estimate_count(b"hello world")
    assert est >= true
    assert est <= true + 4
    # Separator bytes don't change the gram key: tab-separated query matches.
    assert r.estimate_count(b"hello\tworld") == est


@pytest.mark.slow
def test_batched_sketch_updates_identical(tmp_path, rng):
    """sketch_flush_every=K stages updates and scatters every K steps; the
    final registers / CMS matrix must be bit-identical to K=1 (HLL max and
    CMS add see the same (key, count) multiset either way), including a
    partial buffer at end-of-stream and the collective merge flush."""
    from tests.conftest import make_corpus
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    corpus = make_corpus(rng, n_words=4000, vocab=700)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    base = dict(chunk_bytes=512, table_capacity=256)
    mesh = data_mesh(2)

    for sketch_kw in ({"distinct_sketch": True}, {"count_sketch": True}):
        ref = executor.count_file(str(path), Config(**base), mesh=mesh,
                                  **sketch_kw)
        for k in (3, 7):  # 7 does not divide the step count: partial flush
            got = executor.count_file(
                str(path), Config(**base, sketch_flush_every=k), mesh=mesh,
                **sketch_kw)
            assert got.as_dict() == ref.as_dict()
            if "distinct_sketch" in sketch_kw:
                assert got.distinct_estimate == ref.distinct_estimate
            else:
                np.testing.assert_array_equal(got.cms, ref.cms)


@pytest.mark.slow
def test_batched_sketch_checkpoint_resume(tmp_path, rng):
    """A checkpoint taken mid-pending-buffer resumes to the same result."""
    from tests.conftest import make_corpus
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    corpus = make_corpus(rng, n_words=3000, vocab=500)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=512, table_capacity=256, sketch_flush_every=4)
    mesh = data_mesh(2)
    full = executor.count_file(str(path), cfg, mesh=mesh, distinct_sketch=True)
    ck = str(tmp_path / "ck.npz")
    executor.count_file(str(path), cfg, mesh=mesh, distinct_sketch=True,
                        checkpoint_path=ck, checkpoint_every=1)
    resumed = executor.count_file(str(path), cfg, mesh=mesh,
                                  distinct_sketch=True,
                                  checkpoint_path=ck, checkpoint_every=1)
    assert resumed.distinct_estimate == full.distinct_estimate
    assert resumed.as_dict() == full.as_dict()


@pytest.mark.slow
def test_batched_sketch_with_superstep(tmp_path, rng):
    """Flush cadence composes with lax.scan supersteps (cond inside scan)."""
    from tests.conftest import make_corpus
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    corpus = make_corpus(rng, n_words=3000, vocab=500)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    base = dict(chunk_bytes=512, table_capacity=256)
    mesh = data_mesh(2)
    ref = executor.count_file(str(path), Config(**base), mesh=mesh,
                              distinct_sketch=True)
    got = executor.count_file(
        str(path), Config(**base, sketch_flush_every=2, superstep=3),
        mesh=mesh, distinct_sketch=True)
    assert got.distinct_estimate == ref.distinct_estimate
    assert got.as_dict() == ref.as_dict()
