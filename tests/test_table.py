"""Unit + property tests for CountTable (mapreduce_tpu/ops/table.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_tpu import constants
from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.ops import table as tbl
from mapreduce_tpu.ops import tokenize as tok
from mapreduce_tpu.utils import oracle
from tests.conftest import make_corpus


def _stream(data: bytes):
    return tok.tokenize(jnp.asarray(np.frombuffer(data, dtype=np.uint8)))


def _to_dict(t: tbl.CountTable):
    """{(key_hi, key_lo): count} for occupied slots."""
    c = np.asarray(t.count)
    hi, lo = np.asarray(t.key_hi), np.asarray(t.key_lo)
    return {(int(h), int(l)): int(n) for h, l, n in zip(hi, lo, c) if n > 0}


@pytest.mark.smoke
def test_empty_table():
    t = tbl.empty(16)
    assert int(t.n_valid()) == 0
    assert int(t.total_count()) == 0
    assert np.all(np.asarray(t.key_hi) == constants.SENTINEL_KEY)


@pytest.mark.slow
def test_from_stream_counts(small_corpus):
    t = tbl.from_stream(_stream(small_corpus), 1024)
    expected = oracle.word_counts(small_corpus)
    assert int(t.n_valid()) == len(expected)
    assert sorted(_to_dict(t).values(), reverse=True) == sorted(expected.values(), reverse=True)
    assert int(t.total_count()) == oracle.total_count(small_corpus)


def test_table_sorted_with_sentinel_tail(small_corpus):
    t = tbl.from_stream(_stream(small_corpus), 1024)
    hi, lo = np.asarray(t.key_hi), np.asarray(t.key_lo)
    keys = [(int(h) << 32) | int(l) for h, l in zip(hi, lo)]
    assert keys == sorted(keys)
    n = int(t.n_valid())
    assert np.all(np.asarray(t.count)[n:] == 0)


@pytest.mark.slow
def test_merge_equals_whole(rng):
    a = make_corpus(rng, 500, 80)
    b = make_corpus(rng, 700, 80)
    ta = tbl.from_stream(_stream(a), 512)
    tc = tbl.from_stream(_stream(b), 512)
    merged = tbl.merge(ta, tc, 512)
    whole = tbl.from_stream(_stream(a + b" " + b), 512)
    assert _to_dict(merged) == _to_dict(whole)
    assert int(merged.total_count()) == int(whole.total_count())


@pytest.mark.slow
def test_merge_associative_commutative(rng):
    parts = [make_corpus(rng, 300, 60) for _ in range(3)]
    t = [tbl.from_stream(_stream(p), 512) for p in parts]
    ab_c = tbl.merge(tbl.merge(t[0], t[1], 512), t[2], 512)
    a_bc = tbl.merge(t[0], tbl.merge(t[1], t[2], 512), 512)
    c_ba = tbl.merge(t[2], tbl.merge(t[1], t[0], 512), 512)
    assert _to_dict(ab_c) == _to_dict(a_bc) == _to_dict(c_ba)


@pytest.mark.smoke
def test_merge_with_empty_is_identity(small_corpus):
    t = tbl.from_stream(_stream(small_corpus), 512)
    m = tbl.merge(t, tbl.empty(512), 512)
    assert _to_dict(m) == _to_dict(t)
    assert np.asarray(m.pos_lo)[: int(m.n_valid())].tolist() == \
           np.asarray(t.pos_lo)[: int(t.n_valid())].tolist()


@pytest.mark.smoke
def test_overflow_accounting():
    """Past capacity: counts spill into dropped_*, never corrupt (cf. main.cu:103-104)."""
    data = " ".join(f"u{i}" for i in range(100)).encode()
    t = tbl.from_stream(_stream(data), 32)
    assert int(t.n_valid()) == 32
    assert int(t.dropped_uniques) == 68
    assert int(t.dropped_count) == 68
    # Conservation: kept + dropped == all tokens.
    assert int(t.total_count()) == 100


@pytest.mark.slow
def test_count_permutation_invariance(rng):
    """Counts are invariant under word permutation (SURVEY §4 property test)."""
    words = [f"w{i % 37}" for i in range(400)]
    a = " ".join(words).encode()
    perm = list(words)
    rng.shuffle(perm)
    b = " ".join(perm).encode()
    ta = tbl.from_stream(_stream(a), 128)
    tc = tbl.from_stream(_stream(b), 128)
    assert _to_dict(ta) == _to_dict(tc)


def test_first_occurrence_position(fixture_text):
    t = tbl.from_stream(_stream(fixture_text), 64)
    n = int(t.n_valid())
    pos = np.asarray(t.pos_lo)[:n]
    length = np.asarray(t.length)[:n]
    words = {fixture_text[p: p + l] for p, l in zip(pos, length)}
    assert words == {b"Hello", b"World", b"EveryOne", b"Good", b"News", b"Morning"}
    # "World" first occurs at offset 6; "Hello" at 0; "Good" at 27.
    d = {fixture_text[p: p + l]: int(p) for p, l in zip(pos, length)}
    assert d[b"Hello"] == 0 and d[b"World"] == 6 and d[b"Good"] == 27


@pytest.mark.slow
def test_update_streaming_equals_batch(rng):
    corpus = make_corpus(rng, 1000, 100)
    third = len(corpus) // 3
    # Split at separator boundaries for a fair comparison.
    cuts = []
    for c in (third, 2 * third):
        while corpus[c] not in b" \t\n\r":
            c += 1
        cuts.append(c)
    pieces = [corpus[: cuts[0]], corpus[cuts[0]: cuts[1]], corpus[cuts[1]:]]
    t = tbl.empty(512)
    for p in pieces:
        t = tbl.update(t, _stream(p), batch_capacity=512)
    whole = tbl.from_stream(_stream(corpus), 512)
    assert _to_dict(t) == _to_dict(whole)


@pytest.mark.smoke
def test_top_k(small_corpus):
    t = tbl.from_stream(_stream(small_corpus), 1024)
    k = tbl.top_k(t, 5)
    counts = np.asarray(k.count)
    assert list(counts) == sorted(counts, reverse=True)
    expected = sorted(oracle.word_counts(small_corpus).values(), reverse=True)[:5]
    assert counts.tolist() == expected


def test_top_k_preserves_totals(small_corpus):
    """Evicted entries fold into dropped_*; total_count() stays exact."""
    t = tbl.from_stream(_stream(small_corpus), 1024)
    k = tbl.top_k(t, 5)
    assert int(k.total_count()) == oracle.total_count(small_corpus)
    n_distinct = len(oracle.word_counts(small_corpus))
    assert int(k.dropped_uniques) == n_distinct - 5


@pytest.mark.smoke
def test_counts_dtype_uint32(small_corpus):
    t = tbl.from_stream(_stream(small_corpus), 256)
    assert t.count.dtype == jnp.uint32


def test_packed_fast_path_matches_build_with_overflow():
    """_from_stream_packed must equal the generic _build bit-for-bit,
    including the capacity-overflow branch (dropped_* accounting)."""
    data = (" ".join(f"u{i}" for i in range(100)) + " " +
            " ".join(f"u{i}" for i in range(0, 100, 2))).encode()
    stream = _stream(data)
    for cap in (32, 64, 256):  # overflow, overflow, headroom
        slow = tbl.from_stream(stream, cap)
        fast = tbl.from_stream(stream, cap, max_token_bytes=32,
                               max_pos=len(data))
        for field, a, b in zip(slow._fields, slow, fast):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"{field} cap={cap}")
        assert int(fast.total_count()) == 150


def test_merge_spill_drops_largest_keys_deterministically(rng):
    """When a merge exceeds capacity, the spilled uniques are the largest
    keys (sort order) — deterministic, and identical whichever side they
    came from (commutativity under spill)."""
    def table_of(words, cap):
        data = (" ".join(words)).encode()
        padded = tok.pad_to(np.frombuffer(data, np.uint8),
                            max(128, -(-len(data) // 128) * 128))
        return tbl.from_stream(tok.tokenize(jnp.asarray(padded)), cap)

    a = table_of([f"a{i}" for i in range(40)], 64)
    b = table_of([f"b{i}" for i in range(40)], 64)
    cap = 48  # 80 distinct keys -> 32 spill
    m1 = tbl.merge(a, b, capacity=cap)
    m2 = tbl.merge(b, a, capacity=cap)
    for f in tbl.CountTable._fields:
        np.testing.assert_array_equal(np.asarray(getattr(m1, f)),
                                      np.asarray(getattr(m2, f)))
    assert int(np.asarray(m1.dropped_uniques)) == 80 - cap
    # Exact totals survive the spill.
    assert int(np.asarray(m1.total_count())) == 80
    # Kept keys are exactly the `cap` smallest of the union, sorted.
    kept = np.asarray(m1.key_hi).astype(np.uint64) << 32 | np.asarray(m1.key_lo)
    union = np.sort(np.concatenate([
        (np.asarray(t.key_hi).astype(np.uint64) << 32 | np.asarray(t.key_lo))[
            np.asarray(t.count) > 0] for t in (a, b)]))
    np.testing.assert_array_equal(np.sort(kept), union[:cap])


def test_merge_associativity_under_spill(rng):
    """(a+b)+c == a+(b+c) for dropped accounting and totals even when
    intermediate merges spill (kept-key sets can differ transiently, but
    totals and the final kept set of smallest keys must agree)."""
    def table_of(seed, cap=64):
        words = [f"w{seed}_{i}" for i in range(30)]
        data = (" ".join(words)).encode()
        padded = tok.pad_to(np.frombuffer(data, np.uint8),
                            max(128, -(-len(data) // 128) * 128))
        return tbl.from_stream(tok.tokenize(jnp.asarray(padded)), cap)

    a, b, c = (table_of(s) for s in "abc")
    cap = 80  # 90 distinct -> spill of 10 at the final merge
    ab_c = tbl.merge(tbl.merge(a, b, capacity=cap), c, capacity=cap)
    a_bc = tbl.merge(a, tbl.merge(b, c, capacity=cap), capacity=cap)
    assert int(np.asarray(ab_c.total_count())) == 90
    assert int(np.asarray(a_bc.total_count())) == 90
    # a+b fits 60<=80 and b+c fits: no intermediate spill here, so the final
    # tables must be bit-identical.
    for f in tbl.CountTable._fields:
        np.testing.assert_array_equal(np.asarray(getattr(ab_c, f)),
                                      np.asarray(getattr(a_bc, f)))


def _random_packed_rows(rng, n, n_keys):
    """Random single-occurrence rows: live prefix density ~50%, Zipf-ish key
    duplication, sentinel dead rows, packed = pos << 6 | len."""
    sent = np.uint32(constants.SENTINEL_KEY)
    khi = np.full(n, sent, np.uint32)
    klo = np.full(n, sent, np.uint32)
    packed = np.full(n, 0xFFFFFFFF, np.uint32)
    n_live = n // 2
    live = np.sort(rng.choice(n, size=n_live, replace=False))
    keys = rng.integers(0, n_keys, size=n_live)
    khi[live] = (keys * 2654435761 % (1 << 32)).astype(np.uint32)
    klo[live] = (keys * 40503 + 17).astype(np.uint32)
    # Distinct positions per row; equal keys share a length (as real tokens do).
    lengths = (keys % 60 + 1).astype(np.uint32)
    packed[live] = (np.arange(n_live, dtype=np.uint32) * 2 << 6) | lengths
    # Shuffle live rows so positions are not sorted within a key.
    perm = rng.permutation(n_live)
    khi[live], klo[live], packed[live] = khi[live][perm], klo[live][perm], packed[live][perm]
    return jnp.asarray(khi), jnp.asarray(klo), jnp.asarray(packed), n_live


@pytest.mark.slow
def test_segmin_sort_mode_bit_identical(rng):
    """sort_mode='segmin' (2-key sort + segmented running-min) must equal
    sort_mode='sort3' leaf-for-leaf, including first-occurrence positions,
    spill accounting under capacity pressure, and sentinel handling."""
    for n, n_keys, cap in ((1 << 12, 200, 256), (1 << 12, 200, 64),
                           (1 << 10, 5, 16), (1 << 10, 1000, 1 << 11)):
        khi, klo, packed, n_live = _random_packed_rows(rng, n, n_keys)
        total = jnp.uint32(n_live)
        a = tbl.from_packed_rows(khi, klo, packed, total, cap, pos_hi=3,
                                 sort_mode="sort3")
        b = tbl.from_packed_rows(khi, klo, packed, total, cap, pos_hi=3,
                                 sort_mode="segmin")
        for la, lb, name in zip(a, b, a._fields):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb),
                                          err_msg=f"{name} n={n} cap={cap}")


@pytest.mark.slow
def test_segmin_end_to_end_equals_sort3(small_corpus):
    """The full pallas-path pipeline under sort_mode='segmin' produces the
    identical result object (interpret mode on CPU)."""
    base = dict(chunk_bytes=1 << 14, table_capacity=1 << 10, backend="pallas")
    r3 = wordcount.count_words(small_corpus, Config(**base, sort_mode="sort3"))
    rm = wordcount.count_words(small_corpus, Config(**base, sort_mode="segmin"))
    assert r3.as_dict() == rm.as_dict()
    assert r3.words == rm.words and r3.counts == rm.counts


@pytest.mark.slow
def test_kmv_distinct_under_capacity_pressure(rng):
    """VERDICT r2 #8: under table spill, ``distinct`` is the table's free
    KMV estimate (the full table's kept keys are the bottom-capacity key
    hashes), bounded ~1/sqrt(capacity) — not the summed per-chunk bound.
    At capacity 4096 over ~12x more distinct words, the error must be a few
    percent where the old bound overshot by an order of magnitude."""
    n_distinct = 50_000
    words = [f"u{i:05d}".encode() for i in range(n_distinct)]
    corpus = b" ".join(words) + b"\n"
    cap = 1 << 12
    cfg = Config(chunk_bytes=1 << 14, table_capacity=cap, backend="xla")
    r = wordcount.count_words(corpus, cfg)
    assert r.dropped_uniques > 0  # capacity pressure actually happened
    assert len(r.words) == cap
    err = abs(r.distinct - n_distinct) / n_distinct
    assert err < 0.05, f"KMV distinct {r.distinct} vs true {n_distinct}"
    # And an unspilled run stays exact.
    r2 = wordcount.count_words(corpus, Config(chunk_bytes=1 << 14,
                                              table_capacity=1 << 17,
                                              backend="xla"))
    assert r2.distinct == n_distinct


@pytest.mark.slow
def test_kmv_distinct_survives_topk_finalize(tmp_path, rng):
    """VERDICT r3 weak #6: top-k finalized runs keep the tight KMV distinct
    via the pre-reorder snapshot (TopKTable) — the Common-Crawl top-k
    config is exactly where spill is likely."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    n_distinct = 30_000
    words = [f"t{i:05d}".encode() for i in range(n_distinct)]
    corpus = b" ".join(words) + b"\n"
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=1 << 13, table_capacity=1 << 12, backend="xla")
    r = executor.count_file(str(path), cfg, mesh=data_mesh(2), top_k=3)
    assert len(r.words) == 3  # top-k of the kept (bottom-hash) keys
    assert r.dropped_uniques > 0  # spill happened
    err = abs(r.distinct - n_distinct) / n_distinct
    assert err < 0.05, f"top-k distinct {r.distinct} vs true {n_distinct}"
    # Without the snapshot the same run degrades to the summed bound —
    # make sure the estimate is genuinely tighter (the bound overshoots
    # by the respill factor, >1.5x here).
    assert r.distinct < 1.2 * n_distinct


@pytest.mark.slow
def test_kmv_distinct_streamed(tmp_path, rng):
    """The streamed path reports the same KMV-estimated distinct."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    n_distinct = 30_000
    words = [f"v{i:05d}".encode() for i in range(n_distinct)]
    corpus = b" ".join(words) + b"\n"
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=1 << 13, table_capacity=1 << 12, backend="xla")
    r = executor.count_file(str(path), cfg, mesh=data_mesh(2))
    assert r.dropped_uniques > 0
    err = abs(r.distinct - n_distinct) / n_distinct
    assert err < 0.05, f"KMV distinct {r.distinct} vs true {n_distinct}"


# --- 64-bit count lanes: forced-wrap coverage (VERDICT r3 #4) ----------------


def _seed_counts(t: tbl.CountTable, lo_vals, hi_vals=None) -> tbl.CountTable:
    """Craft large per-key counts directly (a 30 GB corpus in two lines):
    overwrite the first len(lo_vals) occupied slots' count lanes."""
    count = np.asarray(t.count).copy()
    count_hi = np.asarray(t.count_hi).copy()
    for i, v in enumerate(lo_vals):
        count[i] = v
    if hi_vals is not None:
        for i, v in enumerate(hi_vals):
            count_hi[i] = v
    return t._replace(count=jnp.asarray(count), count_hi=jnp.asarray(count_hi))


def test_merge_carries_past_2_32():
    """Two tables whose shared keys sum past 2**32 merge exactly."""
    a = tbl.from_stream(_stream(b"alpha beta gamma "), 16)
    b = tbl.from_stream(_stream(b"alpha beta gamma "), 16)
    near = 0xFFFFFFF0
    a = _seed_counts(a, [near, near, 7])
    b = _seed_counts(b, [0x20, near, 1])
    m = tbl.merge(a, b, capacity=16)
    counts = sorted(int(c) + (int(h) << 32) for c, h in
                    zip(np.asarray(m.count), np.asarray(m.count_hi))
                    if int(c) | int(h))
    assert counts == sorted([near + 0x20, near + near, 8])
    assert int(m.total_count()) == near + 0x20 + near + near + 8
    # No key lost, nothing spilled at this capacity.
    assert m.dropped_totals() == (0, 0)


def test_merge_count_exactly_2_32_stays_occupied():
    """A key at exactly 2**32 has count_lo == 0: occupancy, merge survival,
    and reporting must all treat it as live (the silent-loss trap)."""
    a = tbl.from_stream(_stream(b"word other "), 16)
    b = tbl.from_stream(_stream(b"word other "), 16)
    a = _seed_counts(a, [0xFFFFFFFF, 1])
    m = tbl.merge(a, b, capacity=16)  # word: 0xFFFFFFFF + 1 = 2**32 exactly
    occ = np.asarray(m.occupied())
    assert int(occ.sum()) == 2
    lo = np.asarray(m.count)
    hi = np.asarray(m.count_hi)
    totals = sorted(int(c) + (int(h) << 32) for c, h in zip(lo, hi) if c | h)
    assert totals == [2, 1 << 32]
    assert int(m.n_valid()) == 2
    # A further merge must not drop the lo==0 entry.
    m2 = tbl.merge(m, tbl.empty(16), capacity=16)
    assert int(m2.n_valid()) == 2
    assert int(m2.total_count()) == (1 << 32) + 2


def test_merge_batched_carries_past_2_32():
    """The K-way fold's prefix-sum reduce carries: a running table near wrap
    plus staged batches crosses 2**32 exactly."""
    run = tbl.from_stream(_stream(b"hot cold "), 16)
    batch = tbl.from_stream(_stream(b"hot hot hot hot cold "), 16)

    def by_key(t):
        out = {}
        for c, h, kh, kl in zip(np.asarray(t.count), np.asarray(t.count_hi),
                                np.asarray(t.key_hi), np.asarray(t.key_lo)):
            if int(c) | int(h):
                out[(int(kh), int(kl))] = int(c) + (int(h) << 32)
        return out

    # Seed so the slot whose key recurs 4x in the batch sits at
    # 0xFFFFFFFE — the fold then crosses 2**32 (slot order is hash order,
    # so pick by looking the keys up in the batch).
    run_keys = [(int(h), int(l)) for h, l in
                zip(np.asarray(run.key_hi)[:2], np.asarray(run.key_lo)[:2])]
    seeds = [0xFFFFFFFE if by_key(batch)[k] == 4 else 3 for k in run_keys]
    run = _seed_counts(run, seeds)

    m = tbl.merge_batched(run, batch.key_hi, batch.key_lo, batch.count,
                          batch.pos_hi, batch.pos_lo, batch.length, 16)
    expected = {k: v + by_key(batch)[k] for k, v in by_key(run).items()}
    assert by_key(m) == expected
    assert max(expected.values()) == 0xFFFFFFFE + 4  # > 2**32: carried
    assert int(m.total_count()) == 0xFFFFFFFE + 3 + 5


def test_top_k_orders_by_64bit_count():
    """top_k must rank by the full 64-bit count: a key with hi=1 outranks
    any 32-bit count, and evicted mass lands in 64-bit dropped_count."""
    t = tbl.from_stream(_stream(b"big mid small tiny "), 16)
    # big = 2**32 (lo 0!), mid = 0xFFFFFFFF, small = 7, tiny = 1
    t = _seed_counts(t, [0, 0xFFFFFFFF, 7, 1], hi_vals=[1, 0, 0, 0])
    # Which slot is which word is hash-order dependent; recover by count.
    k = tbl.top_k(t, 2)
    kept = [int(c) + (int(h) << 32) for c, h in
            zip(np.asarray(k.count), np.asarray(k.count_hi)) if int(c) | int(h)]
    assert sorted(kept, reverse=True) == [1 << 32, 0xFFFFFFFF]
    du, dc = k.dropped_totals()
    assert du == 2 and dc == 8
    assert int(k.total_count()) == (1 << 32) + 0xFFFFFFFF + 8


def test_dropped_count_scalar_carries_past_2_32():
    """Accumulated dropped_count crosses 2**32 without wrapping."""
    a = tbl.from_stream(_stream(b"x y "), 16)
    a = a._replace(dropped_count=jnp.uint32(0xFFFFFFF0))
    b = tbl.from_stream(_stream(b"x "), 16)
    b = b._replace(dropped_count=jnp.uint32(0x20))
    m = tbl.merge(a, b, capacity=16)
    _, dc = m.dropped_totals()
    assert dc == 0xFFFFFFF0 + 0x20  # > 2**32
    assert int(m.total_count()) == 3 + 0xFFFFFFF0 + 0x20  # x:2, y:1 live


def test_merge_three_way_equals_pairwise(rng):
    """merge(a, b, c=...) must fold three-row key runs exactly like two
    pairwise merges: same kept keys, counts, first occurrences, and
    dropped_count (dropped_uniques is a bound and may only TIGHTEN)."""
    mk = lambda text, ph: tbl.from_stream(_stream(text), 8, pos_hi=ph)
    a = mk(b"alpha beta gamma delta ", 0)
    b = mk(b"beta gamma epsilon ", 1)
    c = mk(b"alpha beta zeta eta theta ", 2)
    # Every input carries prior dropped accounting — the 3-way fold must
    # conserve c's too (a seam table can arrive with nonzero carries).
    import jax.numpy as jnp
    seed = lambda t, du, dc: t._replace(dropped_uniques=jnp.uint32(du),
                                        dropped_count=jnp.uint32(dc))
    a, b, c = seed(a, 1, 5), seed(b, 2, 7), seed(c, 3, 11)
    three = tbl.merge(a, b, capacity=8, c=c)
    pair = tbl.merge(tbl.merge(a, b, capacity=8), c, capacity=8)
    assert int(three.dropped_count) == 5 + 7 + 11
    for f in ("key_hi", "key_lo", "count", "count_hi", "pos_hi", "pos_lo",
              "length", "dropped_count", "dropped_count_hi"):
        np.testing.assert_array_equal(np.asarray(getattr(three, f)),
                                      np.asarray(getattr(pair, f)), err_msg=f)
    assert int(three.dropped_uniques) <= int(pair.dropped_uniques)


def test_merge_three_way_spill_accounting():
    """Under capacity pressure the 3-way fold keeps the smallest-cap keys
    of the union (the same kept set as any merge order) and accounts every
    spilled occurrence."""
    mk = lambda text, ph: tbl.from_stream(_stream(text), 4, pos_hi=ph)
    a = mk(b"a1 b2 c3 d4 ", 0)
    b = mk(b"b2 e5 f6 ", 1)
    c = mk(b"a1 g7 h8 ", 2)
    three = tbl.merge(a, b, capacity=4, c=c)
    pair = tbl.merge(tbl.merge(a, b, capacity=4), c, capacity=4)
    np.testing.assert_array_equal(np.asarray(three.key_hi),
                                  np.asarray(pair.key_hi))
    np.testing.assert_array_equal(np.asarray(three.count),
                                  np.asarray(pair.count))
    # Total occurrences conserved: kept + dropped == 10 tokens.
    assert int(three.total_count()) == 10


def test_total_count64_exact_past_2_31_under_jit():
    """The 32-bit count-path regression (graphcheck overflow lint): a
    synthetic total past 2**31 — the very next doubling of the recorded
    BENCH corpus — must survive the TRACED reporting path exactly.  The
    old traced total_count() summed low words only and wrapped at 2**32;
    total_count64() carries."""
    t = tbl.from_stream(_stream(b"alpha beta gamma "), 16)
    big = (1 << 31) + 12345  # > int32 max
    t = _seed_counts(t, [big & 0xFFFFFFFF, 0xFFFFFFF0, 3])

    lo, hi = jax.jit(lambda x: x.total_count64())(t)
    got = (int(hi) << 32) | int(lo)
    expected = big + 0xFFFFFFF0 + 3
    assert expected > (1 << 32)  # the pair crosses the uint32 boundary too
    assert got == expected
    # Host reconstruction agrees bit-for-bit.
    assert int(t.total_count()) == expected
    # dropped_* lanes fold in.
    t2 = t._replace(dropped_count=jnp.uint32(7),
                    dropped_count_hi=jnp.uint32(1))
    lo2, hi2 = jax.jit(lambda x: x.total_count64())(t2)
    assert ((int(hi2) << 32) | int(lo2)) == expected + 7 + (1 << 32)


def test_total_count_refuses_traced_callers():
    """Traced total_count() cannot be exact in one uint32 scalar (no
    device uint64 with x64 off): it must fail loudly toward
    total_count64(), never silently wrap again."""
    t = tbl.from_stream(_stream(b"x y "), 16)
    with pytest.raises(TypeError, match="total_count64"):
        jax.jit(lambda x: x.total_count())(t)
