"""Ingest tests: boundary alignment, streaming equivalence, recovery spans."""

import numpy as np
import pytest

from mapreduce_tpu import constants
from mapreduce_tpu.data import reader
from mapreduce_tpu.utils import oracle
from tests.conftest import make_corpus

SEPS = set(constants.SEPARATOR_BYTES)


def _write(tmp_path, data: bytes):
    p = tmp_path / "corpus.txt"
    p.write_bytes(data)
    return str(p)


def test_rows_end_at_separators(tmp_path, rng):
    corpus = make_corpus(rng, 3000, 100)
    path = _write(tmp_path, corpus)
    for batch in reader.iter_batches(path, 4, 256):
        for i in range(4):
            ln = int(batch.lengths[i])
            if ln == 0 or int(batch.base_offsets[i]) + ln >= len(corpus):
                continue  # empty row or end of file
            assert int(batch.data[i, ln - 1]) in SEPS, "row must end at a separator"


def test_batches_cover_file_exactly(tmp_path, rng):
    corpus = make_corpus(rng, 2000, 90)
    path = _write(tmp_path, corpus)
    reconstructed = bytearray()
    for batch in reader.iter_batches(path, 3, 128):
        for i in range(3):
            ln = int(batch.lengths[i])
            assert int(batch.base_offsets[i]) == len(reconstructed)
            reconstructed += bytes(batch.data[i, :ln])
    assert bytes(reconstructed) == corpus


def test_no_token_split_across_rows(tmp_path, rng):
    corpus = make_corpus(rng, 4000, 150)
    path = _write(tmp_path, corpus)
    words_streamed = []
    for batch in reader.iter_batches(path, 5, 192):
        for i in range(5):
            ln = int(batch.lengths[i])
            words_streamed.extend(oracle.split_words(bytes(batch.data[i, :ln])))
    assert words_streamed == oracle.split_words(corpus)


def test_force_split_monster_token(tmp_path):
    """A token longer than max_token_bytes is split, not a stall/overflow
    (the reference would smash its 20-byte stack buffer, main.cu:184)."""
    data = b"a" * 10_000 + b" end"
    path = _write(tmp_path, data)
    batches = list(reader.iter_batches(path, 2, 512, max_token_bytes=256))
    total = sum(int(b.lengths.sum()) for b in batches)
    assert total == len(data)


def test_empty_file(tmp_path):
    path = _write(tmp_path, b"")
    assert list(reader.iter_batches(path, 4, 128)) == []


def test_resume_cursor(tmp_path, rng):
    corpus = make_corpus(rng, 1000, 50)
    path = _write(tmp_path, corpus)
    full = list(reader.iter_batches(path, 2, 128))
    # Stop after 2 steps, resume from the reported cursor.
    consumed = sum(int(b.lengths.sum()) for b in full[:2])
    resumed = list(reader.iter_batches(path, 2, 128, start_offset=consumed, start_step=2))
    assert [b.step for b in resumed] == [b.step for b in full[2:]]
    for a, b in zip(resumed, full[2:]):
        np.testing.assert_array_equal(a.data, b.data)
        np.testing.assert_array_equal(a.base_offsets, b.base_offsets)


def test_read_words_at(tmp_path):
    path = _write(tmp_path, b"alpha beta gamma")
    assert reader.read_words_at(path, [(0, 5), (6, 4), (11, 5)]) == \
        [b"alpha", b"beta", b"gamma"]


def test_prefetch_preserves_stream(tmp_path, rng):
    """prefetch() must yield exactly the same batches, in order."""

    corpus = make_corpus(rng, n_words=2000, vocab=100)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    direct = list(reader.iter_batches(str(path), 2, 512))
    fetched = list(reader.prefetch(reader.iter_batches(str(path), 2, 512)))
    assert len(direct) == len(fetched) and len(direct) > 2
    for a, b in zip(direct, fetched):
        np.testing.assert_array_equal(a.data, b.data)
        np.testing.assert_array_equal(a.base_offsets, b.base_offsets)
        assert a.step == b.step


def test_prefetch_propagates_producer_errors():
    def gen():
        raise RuntimeError("disk on fire")
        yield  # pragma: no cover

    with pytest.raises(RuntimeError, match="disk on fire"):
        list(reader.prefetch(gen()))


def test_prefetch_abandoned_consumer_stops_producer(tmp_path, rng):
    """Dropping the generator early must release the producer thread."""
    import threading
    import time

    corpus = make_corpus(rng, n_words=5000, vocab=100)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    gen = reader.prefetch(reader.iter_batches(str(path), 2, 256), depth=1)
    next(gen)
    gen.close()  # consumer abandons mid-stream
    deadline = time.time() + 5
    while time.time() < deadline:
        if not any(t.name == "ingest-prefetch" and t.is_alive()
                   for t in threading.enumerate()):
            break
        time.sleep(0.05)
    assert not any(t.name == "ingest-prefetch" and t.is_alive()
                   for t in threading.enumerate())


def _write_files(tmp_path, blobs):
    paths = []
    for i, blob in enumerate(blobs):
        p = tmp_path / f"part{i}.txt"
        p.write_bytes(blob)
        paths.append(str(p))
    return paths


def test_multi_file_batches_cover_all_files(tmp_path, rng):
    from tests.conftest import make_corpus

    blobs = [make_corpus(rng, n_words=300, vocab=50) for _ in range(3)]
    blobs[1] = blobs[1].rstrip() + b"tail-no-newline"  # no trailing separator
    paths = _write_files(tmp_path, blobs)
    total = 0
    seen_bytes = bytearray()
    for b in reader.iter_batches_multi(paths, 2, 256):
        for row, base, ln in zip(b.data, b.base_offsets, b.lengths):
            total += int(ln)
            seen_bytes.extend(row[: int(ln)])
    assert total == sum(len(b) for b in blobs)
    assert bytes(seen_bytes) == b"".join(blobs)


def test_multi_file_virtual_offsets_recover_words(tmp_path):
    paths = _write_files(tmp_path, [b"alpha beta\n", b"gamma delta\n"])
    # virtual offsets: gamma starts at 11 (after file 0's 11 bytes)
    assert reader.read_words_at_multi(paths, [(0, 5), (11, 5), (17, 5)]) == \
        [b"alpha", b"gamma", b"delta"]


def test_multi_file_no_token_merge_at_file_boundary(tmp_path):
    """'abc' at end of file 0 and 'def' at start of file 1 stay two tokens."""
    paths = _write_files(tmp_path, [b"x abc", b"def y\n"])
    got = {}
    for b in reader.iter_batches_multi(paths, 1, 128):
        for row, ln in zip(b.data, b.lengths):
            for w in bytes(row[: int(ln)]).split():
                got[w] = got.get(w, 0) + 1
    assert got == {b"x": 1, b"abc": 1, b"def": 1, b"y": 1}


def test_multi_file_start_and_end_offsets(tmp_path):
    paths = _write_files(tmp_path, [b"aa bb \n", b"cc dd \n"])
    words = []
    for b in reader.iter_batches_multi(paths, 1, 128, start_offset=3,
                                       end_offset=10):
        for row, ln in zip(b.data, b.lengths):
            words += bytes(row[: int(ln)]).split()
    assert words == [b"bb", b"cc"]
