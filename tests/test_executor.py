"""End-to-end streaming executor tests: count_file, checkpoint/resume, metrics."""

import os

import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models.wordcount import WordCountJob
from mapreduce_tpu.parallel.mesh import data_mesh
from mapreduce_tpu.runtime import checkpoint as ckpt
from mapreduce_tpu.runtime import executor
from mapreduce_tpu.utils import oracle
from tests.conftest import make_corpus

CFG = Config(chunk_bytes=512, table_capacity=2048)


def _write(tmp_path, data: bytes):
    p = tmp_path / "corpus.txt"
    p.write_bytes(data)
    return str(p)


def test_count_file_matches_oracle(tmp_path, rng):
    corpus = make_corpus(rng, 4000, 250)
    path = _write(tmp_path, corpus)
    result = executor.count_file(path, CFG, mesh=data_mesh(8))
    assert result.as_dict() == oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)


def test_count_file_insertion_order(tmp_path):
    data = b"pear apple pear cherry apple pear\n"
    path = _write(tmp_path, data)
    result = executor.count_file(path, CFG, mesh=data_mesh(2))
    assert result.words == [b"pear", b"apple", b"cherry"]
    assert result.counts == [3, 2, 1]


@pytest.mark.slow
def test_count_file_top_k(tmp_path, rng):
    corpus = make_corpus(rng, 3000, 150)
    path = _write(tmp_path, corpus)
    result = executor.count_file(path, CFG, mesh=data_mesh(4), top_k=5)
    expected = sorted(oracle.word_counts(corpus).values(), reverse=True)[:5]
    assert result.counts == expected


def test_run_metrics(tmp_path, rng):
    corpus = make_corpus(rng, 2000, 100)
    path = _write(tmp_path, corpus)
    rr = executor.run_job(WordCountJob(CFG), path, CFG, mesh=data_mesh(4))
    assert rr.metrics.bytes_processed == len(corpus)
    assert rr.metrics.words_counted == oracle.total_count(corpus)
    assert rr.metrics.elapsed_s > 0 and rr.metrics.gb_per_s > 0
    assert "stream" in rr.metrics.phases and "reduce" in rr.metrics.phases


@pytest.mark.slow
def test_run_metrics_unwrap_topk_and_sketch(tmp_path, rng):
    """words_counted must survive every finalize result shape: the TopKTable
    wrapper (and its nesting inside sketch states) carries the table one
    level down — metrics reporting 0 there is a silent regression."""
    from mapreduce_tpu.models.wordcount import (SketchedWordCountJob,
                                                TopKWordCountJob)

    corpus = make_corpus(rng, 2000, 100)
    path = _write(tmp_path, corpus)
    total = oracle.total_count(corpus)
    rr = executor.run_job(TopKWordCountJob(5, CFG), path, CFG, mesh=data_mesh(4))
    assert rr.metrics.words_counted == total
    rr = executor.run_job(SketchedWordCountJob(TopKWordCountJob(5, CFG)),
                          path, CFG, mesh=data_mesh(4))
    assert rr.metrics.words_counted == total


@pytest.mark.slow
def test_checkpoint_resume_same_result(tmp_path, rng):
    """Kill-and-resume produces the identical count multiset (SURVEY §5)."""
    corpus = make_corpus(rng, 5000, 200)
    path = _write(tmp_path, corpus)
    mesh = data_mesh(4)
    ck = str(tmp_path / "state.npz")

    # Full run, no checkpointing: the golden answer.
    full = executor.count_file(path, CFG, mesh=mesh)

    # Run with checkpointing every step, then simulate a crash by reloading
    # from the last checkpoint and re-running.
    executor.count_file(path, CFG, mesh=mesh, checkpoint_path=ck, checkpoint_every=1)
    assert ckpt.exists(ck)
    state, step, offset, bases, _ = ckpt.load(ck)
    assert step > 1 and 0 < offset <= len(corpus)

    resumed = executor.count_file(path, CFG, mesh=mesh, checkpoint_path=ck,
                                  checkpoint_every=1)
    assert resumed.as_dict() == full.as_dict()
    assert resumed.total == full.total


def test_checkpoint_mismatch_rejected(tmp_path, rng):
    """Resuming against a replaced input file must fail loudly, not corrupt."""
    corpus = make_corpus(rng, 3000, 100)
    path = _write(tmp_path, corpus)
    ck = str(tmp_path / "state.npz")
    small = Config(chunk_bytes=256, table_capacity=1024)
    executor.count_file(path, small, mesh=data_mesh(2), checkpoint_path=ck,
                        checkpoint_every=1)
    # Replace the input: same path, different content.
    (tmp_path / "corpus.txt").write_bytes(make_corpus(rng, 3000, 100))
    with pytest.raises(ckpt.CheckpointMismatch):
        executor.count_file(path, small, mesh=data_mesh(2), checkpoint_path=ck,
                            checkpoint_every=1)
    # Different device count is also rejected.
    with pytest.raises(ckpt.CheckpointMismatch):
        executor.count_file(path, small, mesh=data_mesh(4), checkpoint_path=ck,
                            checkpoint_every=1)


def test_checkpoint_capacity_mismatch_rejected(tmp_path, rng):
    """Resuming with a different table_capacity would silently spill entries."""
    corpus = make_corpus(rng, 3000, 100)
    path = _write(tmp_path, corpus)
    ck = str(tmp_path / "state.npz")
    executor.count_file(path, Config(chunk_bytes=256, table_capacity=2048),
                        mesh=data_mesh(2), checkpoint_path=ck, checkpoint_every=1)
    with pytest.raises(ckpt.CheckpointMismatch):
        executor.count_file(path, Config(chunk_bytes=256, table_capacity=1024),
                            mesh=data_mesh(2), checkpoint_path=ck, checkpoint_every=1)


@pytest.mark.slow
def test_stream_and_single_buffer_top_k_agree(tmp_path):
    """Device-side and host-side top-k must break count ties identically
    (by first occurrence), so --stream --top-k and --top-k match."""
    # Five words, counts 3,2,2,2,1: the k=2 boundary lands inside the tie.
    data = b"aa bb aa cc dd aa bb cc dd bb cc dd ee\n" * 3
    path = _write(tmp_path, data)
    streamed = executor.count_file(path, CFG, mesh=data_mesh(2), top_k=2)

    from mapreduce_tpu.models.wordcount import apply_top_k, count_words

    single = apply_top_k(count_words(data), 2)
    assert streamed.words == single.words
    assert streamed.counts == single.counts


@pytest.mark.slow
def test_stream_top_k_total_is_exact(tmp_path, rng):
    """--stream --top-k must report the full token total, not the top-k sum."""
    corpus = make_corpus(rng, 2000, 120)
    path = _write(tmp_path, corpus)
    result = executor.count_file(path, CFG, mesh=data_mesh(2), top_k=3)
    assert result.total == oracle.total_count(corpus)
    assert result.distinct == len(oracle.word_counts(corpus))
    assert len(result.words) == 3


def test_checkpoint_roundtrip(tmp_path):
    from mapreduce_tpu.ops import table as tbl

    t = tbl.empty(16)
    import jax

    stacked = jax.tree.map(lambda x: np.broadcast_to(np.asarray(x)[None], (4,) + x.shape), t)
    p = str(tmp_path / "ck.npz")
    ckpt.save(p, stacked, step=3, offset=12345, bases=np.zeros((3, 4), np.int64))
    s2, step, offset, bases, _ = ckpt.load(p, template=stacked)
    assert step == 3 and offset == 12345 and bases.shape == (3, 4)
    for f in t._fields:
        np.testing.assert_array_equal(np.asarray(getattr(stacked, f)),
                                      np.asarray(getattr(s2, f)))


@pytest.mark.slow
def test_stream_superstep_matches_single_step(tmp_path, rng):
    """config.superstep>1 (scan-fused dispatches + remainder single steps)
    must produce the identical result and checkpoint-compatible bases."""
    from tests.conftest import make_corpus

    corpus = make_corpus(rng, n_words=4000, vocab=200)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    base = dict(table_capacity=1 << 10, chunk_bytes=512)
    r1 = executor.count_file(str(path), config=Config(**base))
    r3 = executor.count_file(str(path), config=Config(**base, superstep=3))
    assert r1.as_dict() == r3.as_dict()
    assert r1.words == r3.words and r1.total == r3.total


@pytest.mark.slow
def test_sketched_checkpoint_resume(tmp_path, rng):
    """Sketched runs checkpoint (table + HLL registers as extras) and resume
    to the same result; resuming across sketched/unsketched is rejected."""
    corpus = make_corpus(rng, n_words=4000, vocab=600)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=512, table_capacity=256)
    ck = str(tmp_path / "ck.npz")

    full = executor.count_file(str(path), config=cfg, distinct_sketch=True)
    # Run with frequent checkpoints; then resume from the on-disk snapshot.
    r1 = executor.count_file(str(path), config=cfg, distinct_sketch=True,
                             checkpoint_path=ck, checkpoint_every=2)
    assert ckpt.exists(ck)  # sketched state DID snapshot
    r2 = executor.count_file(str(path), config=cfg, distinct_sketch=True,
                             checkpoint_path=ck, checkpoint_every=2)
    assert r1.as_dict() == full.as_dict() == r2.as_dict()
    assert r2.distinct_estimate == pytest.approx(r1.distinct_estimate)

    with pytest.raises(ckpt.CheckpointMismatch, match="sketch"):
        executor.count_file(str(path), config=cfg, distinct_sketch=False,
                            checkpoint_path=ck, checkpoint_every=2)


@pytest.mark.slow
def test_multi_file_corpus_counts_and_recovery(tmp_path, rng):
    """Three files streamed as one corpus: counts equal the concatenation's
    oracle, words recover exactly, checkpoints resume across file seams."""
    blobs = [make_corpus(rng, n_words=1500, vocab=120) for _ in range(3)]
    paths = []
    for i, blob in enumerate(blobs):
        p = tmp_path / f"shard{i}.txt"
        p.write_bytes(blob)
        paths.append(str(p))
    expected = {}
    for blob in blobs:  # files are independent streams
        for w, c in oracle.word_counts(blob).items():
            expected[w] = expected.get(w, 0) + c

    cfg = Config(chunk_bytes=512, table_capacity=1024)
    r = executor.count_file(paths, config=cfg)
    assert {w: c for w, c in zip(r.words, r.counts)} == expected
    assert r.total == sum(expected.values())

    ck = str(tmp_path / "ck.npz")
    r2 = executor.count_file(paths, config=cfg, checkpoint_path=ck,
                             checkpoint_every=2)
    assert ckpt.exists(ck)
    r3 = executor.count_file(paths, config=cfg, checkpoint_path=ck,
                             checkpoint_every=2)  # resumes mid-corpus
    assert r2.as_dict() == r.as_dict() == r3.as_dict()


def test_step_failure_is_surfaced_with_resume_cursor(tmp_path, rng):
    """Failure detection (SURVEY §5): a failing step logs the resume cursor
    loudly and re-raises — never a silent partial result."""
    import logging

    corpus = make_corpus(rng, 2000, 100)
    path = _write(tmp_path, corpus)
    mesh = data_mesh(2)
    job = WordCountJob(CFG)

    class FailingEngine(executor.Engine):
        def step(self, state, chunks, step_index):
            if step_index >= 2:
                raise RuntimeError("injected device fault")
            return super().step(state, chunks, step_index)

    records: list[logging.LogRecord] = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = logging.getLogger("mapreduce_tpu")  # propagate=False: attach
    handler = Capture()
    logger.addHandler(handler)
    real_engine = executor.Engine
    executor.Engine = FailingEngine
    try:
        with pytest.raises(RuntimeError, match="injected device fault"):
            executor.run_job(job, path, config=CFG, mesh=mesh)
    finally:
        executor.Engine = real_engine
        logger.removeHandler(handler)
    failed = [r for r in records if "step failed" in r.getMessage()]
    assert failed, "the failure must be logged before re-raising"
    fields = getattr(failed[0], "fields", {})
    assert fields.get("step") == 2  # the resume cursor names the failed step
    assert "resume_hint" in fields


def test_checkpoint_legacy_format_named_cause(tmp_path):
    """A pre-versioning (field-named leaves) snapshot must be rejected with
    the real cause named, not a misleading '0 leaves' structure error."""
    p = str(tmp_path / "legacy.npz")
    np.savez(p, keys=np.zeros(4, np.uint32), counts=np.zeros(4, np.uint32),
             __step=np.int64(1), __offset=np.int64(0),
             __bases=np.zeros((1, 1), np.int64))
    with pytest.raises(ckpt.CheckpointMismatch, match="older version"):
        ckpt.load(p, template={"k": np.zeros(4, np.uint32)})


def test_checkpoint_future_format_rejected(tmp_path):
    import json as _json

    p = str(tmp_path / "future.npz")
    meta = np.frombuffer(_json.dumps({"format": 99}).encode(), dtype=np.uint8)
    np.savez(p, __leaf_0=np.zeros(4, np.uint32), __step=np.int64(0),
             __offset=np.int64(0), __bases=np.zeros((0, 1), np.int64),
             __meta=meta)
    with pytest.raises(ckpt.CheckpointMismatch, match="newer version"):
        ckpt.load(p, template={"k": np.zeros(4, np.uint32)})


@pytest.mark.slow
def test_step_retry_recovers_transient_failure(tmp_path, rng, monkeypatch):
    """VERDICT r1 #5 'done' case: an injected one-shot step failure recovers
    via the in-memory known-good snapshot, without a checkpoint file, and
    produces exact counts."""
    from mapreduce_tpu.parallel.mapreduce import Engine

    corpus = make_corpus(rng, n_words=3000, vocab=120)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)

    fired = set()  # one-shot per step: the retry of step 2 must succeed
    original = Engine.step

    def flaky(self, state, chunks, step_index):
        if step_index in (2, 5) and step_index not in fired:
            fired.add(step_index)
            raise RuntimeError("injected transient device failure")
        return original(self, state, chunks, step_index)

    from mapreduce_tpu.parallel import mapreduce as mr
    monkeypatch.setattr(mr.Engine, "step", flaky)

    cfg = Config(chunk_bytes=512, table_capacity=1 << 10)
    result = executor.count_file(str(path), cfg, mesh=data_mesh(2), retry=1)
    assert fired == {2, 5}, "injection never fired; test is vacuous"
    want = oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)
    assert dict(zip(result.words, result.counts)) == want


def test_step_retry_exhausted_surfaces(tmp_path, rng, monkeypatch):
    """A persistent failure still surfaces after the retries run out."""
    corpus = make_corpus(rng, n_words=500, vocab=50)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)

    def always_fail(self, state, chunks, step_index):
        raise RuntimeError("persistent device failure")

    from mapreduce_tpu.parallel import mapreduce as mr
    monkeypatch.setattr(mr.Engine, "step", always_fail)

    cfg = Config(chunk_bytes=512, table_capacity=1 << 10)
    with pytest.raises(RuntimeError, match="persistent"):
        executor.count_file(str(path), cfg, mesh=data_mesh(2), retry=2)


@pytest.mark.slow
def test_mid_superstep_checkpoint_granularity(tmp_path, rng, monkeypatch):
    """VERDICT r1 #10 'done' case: with checkpoint_every finer than the
    superstep, a kill mid-run resumes from the last per-step checkpoint —
    replaying at most checkpoint_every (=1 here) chunks per device, not a
    whole superstep."""
    from mapreduce_tpu.parallel.mapreduce import Engine

    corpus = make_corpus(rng, n_words=6000, vocab=150)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    ck = str(tmp_path / "ck.npz")
    cfg = Config(chunk_bytes=512, table_capacity=1 << 10, superstep=4)

    dispatched: list[int] = []
    orig_step, orig_many = Engine.step, Engine.step_many
    crash_at = {"step": 6, "armed": True}

    def rec_step(self, state, chunks, step_index):
        if crash_at["armed"] and step_index >= crash_at["step"]:
            raise RuntimeError("injected kill")
        dispatched.append(int(step_index))
        return orig_step(self, state, chunks, step_index)

    def rec_many(self, state, chunks, step_index, repeats=1):
        k = chunks.shape[1]
        if crash_at["armed"] and step_index + k > crash_at["step"]:
            raise RuntimeError("injected kill")
        dispatched.extend(range(int(step_index), int(step_index) + k))
        return orig_many(self, state, chunks, step_index, repeats)

    from mapreduce_tpu.parallel import mapreduce as mr
    monkeypatch.setattr(mr.Engine, "step", rec_step)
    monkeypatch.setattr(mr.Engine, "step_many", rec_many)

    # First run: checkpoint every step (finer than the 4-step superstep),
    # killed at step 6 — i.e. mid-way through the second superstep group.
    with pytest.raises(RuntimeError, match="injected kill"):
        executor.count_file(str(path), cfg, mesh=data_mesh(2),
                            checkpoint_path=ck, checkpoint_every=1)
    assert ckpt.exists(ck)
    completed = max(dispatched) + 1
    assert completed == crash_at["step"]  # steps 0..5 done and checkpointed

    # Resume: must start exactly at the crash step (replay < 1 chunk/device).
    crash_at["armed"] = False
    dispatched.clear()
    result = executor.count_file(str(path), cfg, mesh=data_mesh(2),
                                 checkpoint_path=ck, checkpoint_every=1)
    assert min(dispatched) == crash_at["step"], \
        f"resume replayed from step {min(dispatched)}, not {crash_at['step']}"
    assert result.total == oracle.total_count(corpus)
    assert dict(zip(result.words, result.counts)) == oracle.word_counts(corpus)


def test_ledger_one_record_per_step(tmp_path, rng):
    """ISSUE 2 acceptance: a telemetered run writes >= 1 JSONL step record
    per step, each with the phase decomposition (read_wait/stage/dispatch),
    byte counts, and device memory stats; run_start/run_end bracket them."""
    from mapreduce_tpu import obs

    corpus = make_corpus(rng, 2000, 100)
    path = _write(tmp_path, corpus)
    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        rr = executor.run_job(WordCountJob(CFG), path, CFG, mesh=data_mesh(4),
                              telemetry=tel)
    recs = list(obs.read_ledger(led))
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    steps = [r for r in recs if r["kind"] == "step"]
    assert steps, "at least one step record"
    # superstep=1: exactly one record per step, contiguous from 0.
    assert [r["step_first"] for r in steps] == list(range(len(steps)))
    assert all(r["steps"] == 1 for r in steps)
    assert sum(r["group_bytes"] for r in steps) == len(corpus)
    assert steps[-1]["cursor_bytes"] == len(corpus)
    for r in steps:
        assert r["phases"].get("dispatch", 0) > 0
        assert r["mem"].get("live_arrays", 0) > 0
        assert r["mem"].get("live_bytes", 0) > 0
    phase_keys = set().union(*(r["phases"] for r in steps))
    assert {"read_wait", "stage", "dispatch"} <= phase_keys
    # The step records decompose (within rounding) the run's stream phases.
    end = recs[-1]
    assert end["bytes"] == rr.metrics.bytes_processed == len(corpus)
    total_dispatch = sum(r["phases"].get("dispatch", 0) for r in steps)
    assert total_dispatch == pytest.approx(rr.metrics.phases["dispatch"],
                                           rel=0.05)


def test_flight_dump_on_injected_step_failure(tmp_path, rng, monkeypatch):
    """ISSUE 2 acceptance: an injected step failure leaves a flight-recorder
    dump (recent events + context + metrics) and a ledger failure record —
    forensics instead of nothing (the benchwatch wedge scenario)."""
    import json as _json

    from mapreduce_tpu import obs
    from mapreduce_tpu.parallel import mapreduce as mr

    corpus = make_corpus(rng, 2000, 100)
    path = _write(tmp_path, corpus)
    original = mr.Engine.step

    def failing(self, state, chunks, step_index):
        if step_index >= 2:
            raise RuntimeError("injected device fault")
        return original(self, state, chunks, step_index)

    monkeypatch.setattr(mr.Engine, "step", failing)
    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        with pytest.raises(RuntimeError, match="injected device fault"):
            executor.run_job(WordCountJob(CFG), path, CFG, mesh=data_mesh(2),
                             telemetry=tel)
    dump_path = led + ".flight.json"
    assert os.path.exists(dump_path), "failure must leave a flight dump"
    with open(dump_path) as f:
        dump = _json.load(f)
    assert dump["context"]["step"] == 2
    assert "injected device fault" in dump["context"]["error"]
    kinds = [e["kind"] for e in dump["events"]]
    assert "step" in kinds and "step_failed" in kinds
    assert dump["metrics"]["counters"]["executor.steps"] >= 2
    # The ledger names the failure and points at the dump.
    failures = list(obs.read_ledger(led, kind="failure"))
    assert len(failures) == 1 and failures[0]["step"] == 2
    assert failures[0]["flight_dump"] == dump_path
    # No run_end: the crash is visible to obs_report as DID NOT COMPLETE.
    assert not list(obs.read_ledger(led, kind="run_end"))


@pytest.mark.slow
def test_merge_every_batched_equals_pairwise(tmp_path, rng):
    """merge_every=K folds K staged batch tables in one reduce: results must
    equal the K=1 pairwise fold — words, counts, totals, order — including
    an end-of-stream flush of a partial buffer (chunk count not divisible
    by K) and a device-side top-k finalize."""
    corpus = make_corpus(rng, n_words=4000, vocab=200)
    path = _write(tmp_path, corpus)
    base = dict(chunk_bytes=512, table_capacity=1 << 12)
    r1 = executor.count_file(path, Config(**base), mesh=data_mesh(2))
    rk = executor.count_file(path, Config(**base, merge_every=3),
                             mesh=data_mesh(2))
    assert rk.words == r1.words and rk.counts == r1.counts
    assert rk.total == r1.total and rk.distinct == r1.distinct
    assert rk.dropped_count == r1.dropped_count

    t1 = executor.count_file(path, Config(**base), mesh=data_mesh(2), top_k=7)
    tk = executor.count_file(path, Config(**base, merge_every=4),
                             mesh=data_mesh(2), top_k=7)
    assert tk.as_dict() == t1.as_dict()


@pytest.mark.slow
def test_merge_every_under_capacity_pressure(tmp_path):
    """Under table spill the kept keys/counts and dropped_count stay
    identical; the dropped_uniques bound can only TIGHTEN (a respilled key
    counts once per flush, not once per step)."""
    words = [f"z{i:04d}" for i in range(3000)]
    corpus = (" ".join(words) + " " + " ".join(words)).encode()
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    base = dict(chunk_bytes=512, table_capacity=256)
    r1 = executor.count_file(str(path), Config(**base), mesh=data_mesh(2))
    rk = executor.count_file(str(path), Config(**base, merge_every=4),
                             mesh=data_mesh(2))
    assert rk.words == r1.words and rk.counts == r1.counts
    assert rk.total == r1.total
    assert rk.dropped_count == r1.dropped_count
    assert rk.dropped_uniques <= r1.dropped_uniques


@pytest.mark.slow
def test_merge_every_checkpoint_resume(tmp_path, rng):
    """The buffered state (pending arrays + cursor) snapshots and resumes
    exactly like any other state pytree."""
    corpus = make_corpus(rng, n_words=3000, vocab=100)
    path = _write(tmp_path, corpus)
    cfg = Config(chunk_bytes=512, table_capacity=1 << 12, merge_every=3)
    mesh = data_mesh(2)
    full = executor.count_file(path, cfg, mesh=mesh)
    ck = str(tmp_path / "ck.npz")
    from mapreduce_tpu.parallel import mapreduce as mr

    original = mr.Engine.step
    fired = []

    def crash_mid(self, state, chunks, step_index):
        if step_index == 4 and not fired:
            fired.append(1)
            raise RuntimeError("injected crash")
        return original(self, state, chunks, step_index)

    import pytest as _pytest

    try:
        mr.Engine.step = crash_mid
        with _pytest.raises(RuntimeError, match="injected"):
            executor.count_file(path, cfg, mesh=mesh, checkpoint_path=ck,
                                checkpoint_every=2)
    finally:
        mr.Engine.step = original
    assert fired, "injection never fired; test is vacuous"
    resumed = executor.count_file(path, cfg, mesh=mesh, checkpoint_path=ck,
                                  checkpoint_every=2)
    assert resumed.as_dict() == full.as_dict()
    assert resumed.total == full.total


# -- ISSUE 5: the bounded in-flight dispatch window ---------------------------


@pytest.mark.smoke
def test_pipelined_window_matches_serial(tmp_path, rng):
    """inflight_groups > 1 must be byte-identical to the serialized window
    (inflight_groups=1, the A/B control): words, counts, order, totals."""
    corpus = make_corpus(rng, 3000, 150)
    path = _write(tmp_path, corpus)
    base = dict(chunk_bytes=512, table_capacity=2048)
    serial = executor.count_file(path, Config(**base, inflight_groups=1),
                                 mesh=data_mesh(4))
    piped = executor.count_file(path, Config(**base, inflight_groups=4),
                                mesh=data_mesh(4))
    assert piped.as_dict() == serial.as_dict() == oracle.word_counts(corpus)
    assert piped.words == serial.words and piped.counts == serial.counts
    assert piped.total == serial.total == oracle.total_count(corpus)


@pytest.mark.smoke
def test_ledger_one_record_per_group_under_pipelining(tmp_path, rng):
    """ISSUE 5 acceptance: with the window active, telemetry still emits
    exactly ONE ledger step record per dispatched group, in step order,
    each carrying the observed in-flight depth; run_end carries the window
    statistics and the overlap fraction."""
    from mapreduce_tpu import obs

    corpus = make_corpus(rng, 2500, 120)
    path = _write(tmp_path, corpus)
    cfg = Config(chunk_bytes=512, table_capacity=2048, inflight_groups=3)
    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        rr = executor.run_job(WordCountJob(cfg), path, cfg, mesh=data_mesh(4),
                              telemetry=tel)
    recs = list(obs.read_ledger(led))
    steps = [r for r in recs if r["kind"] == "step"]
    # one record per group, order-preserving and contiguous from step 0
    assert [r["step_first"] for r in steps] == list(range(len(steps)))
    assert sum(r["group_bytes"] for r in steps) == len(corpus)
    for r in steps:
        assert 1 <= r["inflight_depth"] <= 3
    assert max(r["inflight_depth"] for r in steps) > 1, \
        "window never pipelined; the test corpus is too small"
    end = recs[-1]
    assert end["kind"] == "run_end"
    pipe = end["pipeline"]
    assert pipe["inflight_groups"] == 3
    assert pipe["dispatch_groups"] == len(steps)
    assert 1 <= pipe["depth_max"] <= 3
    assert 0.0 <= pipe["overlap_fraction"] <= 1.0
    assert rr.pipeline == pipe


def test_mid_window_async_failure_attributed_and_retried(tmp_path, rng,
                                                         monkeypatch):
    """ISSUE 5 acceptance: a failure that surfaces ASYNCHRONOUSLY at a
    completion token (emulated through the _wait_token seam — the CPU
    backend has no late-surfacing errors) is attributed to the group that
    caused it, not to a neighbor, and the run recovers from the window
    anchor to exact counts."""
    import jax as _jax

    from mapreduce_tpu import obs

    corpus = make_corpus(rng, 4000, 150)
    path = _write(tmp_path, corpus)
    cfg = Config(chunk_bytes=512, table_capacity=2048, inflight_groups=3)

    orig_token = executor._state_token
    made = []

    def tok(state):
        t = orig_token(state)
        made.append(t)
        if len(made) - 1 == 2:  # the step-2 group's token, poisoned once
            return ("poison", t)
        return t

    def wait(t):
        if isinstance(t, tuple) and t[0] == "poison":
            raise RuntimeError("injected async device fault")
        _jax.block_until_ready(t)

    monkeypatch.setattr(executor, "_state_token", tok)
    monkeypatch.setattr(executor, "_wait_token", wait)

    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        result = executor.count_file(path, cfg, mesh=data_mesh(2), retry=1,
                                     telemetry=tel)
    assert len(made) > 3, "window never pipelined past the poisoned group"
    # exact results despite the mid-window failure + replay
    assert result.as_dict() == oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)
    # attribution: the retry record names step 2 — the poisoned group —
    # even though the failure surfaced while draining a 3-deep window.
    retries = list(obs.read_ledger(led, kind="retry"))
    assert [r["step"] for r in retries] == [2]
    assert not list(obs.read_ledger(led, kind="failure"))
    # still exactly one step record per dispatched group
    steps = list(obs.read_ledger(led, kind="step"))
    assert [r["step_first"] for r in steps] == list(range(len(steps)))
    assert all(r["inflight_depth"] >= 1 for r in steps)


def test_mid_window_sync_failure_replays_from_anchor(tmp_path, rng,
                                                     monkeypatch):
    """The OTHER recover() entry: a failure raised by the dispatch call
    itself (not a completion token) mid-window.  The failed group was
    never enrolled, so recovery must replay from the anchor with exactly
    that group charged one attempt, account it exactly once (one step
    record per group, in order, inflight_depth >= 1 — the serialized
    replay is depth 1), and stay exact."""
    from mapreduce_tpu import obs
    from mapreduce_tpu.parallel import mapreduce as mr

    corpus = make_corpus(rng, 3000, 120)
    path = _write(tmp_path, corpus)
    cfg = Config(chunk_bytes=512, table_capacity=2048, inflight_groups=3)

    fired = []
    orig_step = mr.Engine.step

    def flaky(self, state, chunks, step_index):
        if step_index == 4 and not fired:
            fired.append(int(step_index))
            raise RuntimeError("injected sync device fault")
        return orig_step(self, state, chunks, step_index)

    monkeypatch.setattr(mr.Engine, "step", flaky)

    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        result = executor.count_file(path, cfg, mesh=data_mesh(2), retry=1,
                                     telemetry=tel)
    assert fired == [4], "injection never fired; test is vacuous"
    assert result.as_dict() == oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)
    retries = list(obs.read_ledger(led, kind="retry"))
    assert [r["step"] for r in retries] == [4]
    assert not list(obs.read_ledger(led, kind="failure"))
    steps = list(obs.read_ledger(led, kind="step"))
    assert [r["step_first"] for r in steps] == list(range(len(steps)))
    assert all(r["inflight_depth"] >= 1 for r in steps)
    # Only the recovered group's GROUP record carries the charged attempt
    # (ISSUE 15 satellite: the async path's step record is written at
    # dispatch, before any retry can exist, so the group record is the
    # one carrier both recovery paths charge consistently).
    groups = list(obs.read_ledger(led, kind="group"))
    assert [g["step_first"] for g in groups if g.get("retries")] == [4]
    assert not any(r.get("retries") for r in steps), \
        "step records must not charge replay retries on either path"


def test_window_checkpoint_replay_bounded(tmp_path, rng, monkeypatch):
    """ISSUE 5 acceptance: checkpoint boundaries force window drains, so a
    crash with the window active resumes with at most checkpoint_every
    chunks replayed per device — the window widens throughput, not the
    replay radius."""
    from mapreduce_tpu.parallel import mapreduce as mr

    corpus = make_corpus(rng, 6000, 150)
    path = _write(tmp_path, corpus)
    ck = str(tmp_path / "ck.npz")
    cfg = Config(chunk_bytes=512, table_capacity=1024, inflight_groups=4)
    every = 2

    dispatched: list[int] = []
    orig_step = mr.Engine.step
    crash = {"at": 5, "armed": True}

    def rec_step(self, state, chunks, step_index):
        if crash["armed"] and step_index >= crash["at"]:
            raise RuntimeError("injected kill")
        dispatched.append(int(step_index))
        return orig_step(self, state, chunks, step_index)

    monkeypatch.setattr(mr.Engine, "step", rec_step)

    with pytest.raises(RuntimeError, match="injected kill"):
        executor.count_file(path, cfg, mesh=data_mesh(2),
                            checkpoint_path=ck, checkpoint_every=every)
    assert ckpt.exists(ck)
    _, saved_step, _, _, _ = ckpt.load(ck)
    # the window drained at every boundary: the snapshot is the last
    # boundary at or before the crash step, never further back
    assert saved_step == (crash["at"] // every) * every

    crash["armed"] = False
    dispatched.clear()
    result = executor.count_file(path, cfg, mesh=data_mesh(2),
                                 checkpoint_path=ck, checkpoint_every=every)
    assert min(dispatched) == saved_step
    assert crash["at"] - min(dispatched) <= every, \
        f"resume replayed {crash['at'] - min(dispatched)} steps > {every}"
    assert result.total == oracle.total_count(corpus)
    assert dict(zip(result.words, result.counts)) == oracle.word_counts(corpus)


@pytest.mark.slow
def test_window_ab_identical_across_families(tmp_path, rng):
    """The CPU-proxy A/B of the acceptance criteria: grep, sample, and
    n-gram streamed runs are byte-identical with the window on vs off
    (wordcount is covered in the fast tier)."""
    from mapreduce_tpu.models import grep as grep_mod
    from mapreduce_tpu.models import sample as sample_mod

    corpus = make_corpus(rng, 4000, 150)
    path = _write(tmp_path, corpus)
    base = dict(chunk_bytes=512, table_capacity=2048)
    serial = Config(**base, inflight_groups=1)
    piped = Config(**base, inflight_groups=4)

    g1 = grep_mod.grep_file(path, b"w1", config=serial)
    g4 = grep_mod.grep_file(path, b"w1", config=piped)
    assert (g1.matches, g1.lines) == (g4.matches, g4.lines)

    s1 = sample_mod.sample_file(path, 7, config=serial)
    s4 = sample_mod.sample_file(path, 7, config=piped)
    assert s1.tokens == s4.tokens and s1.total == s4.total

    n1 = executor.count_file(path, serial, mesh=data_mesh(2), ngram=2)
    n4 = executor.count_file(path, piped, mesh=data_mesh(2), ngram=2)
    assert n1.as_dict() == n4.as_dict()
    assert n1.words == n4.words and n1.total == n4.total
