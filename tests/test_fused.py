"""Config.map_impl='fused': the one-kernel map path (ISSUE 6).

The fused kernel consumes RAW chunk bytes and emits hashed, window-sorted
rows in a single ``pallas_call`` — tokenize -> hash -> window compaction
in VMEM, lane seams resolved in-kernel from the seam-carry plane, no
token-plane round-trip to HBM before the aggregation sort.

Contract under test: fused is BIT-IDENTICAL to the split path (compact
kernel + XLA seam fix-up) on every corpus shape — tokens, counts, first
occurrences, dropped accounting, overlong rescue, spill fallback, n-gram
formation — and the lane-major fused stream preserves the stable2
position-order precondition without a seam concat.
"""

import numpy as np
import pytest

from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.ops import tokenize as tok
from mapreduce_tpu.ops.pallas import tokenize as ptok
from mapreduce_tpu.utils import oracle
from tests.conftest import make_corpus

W = 8  # small lookback: overlong/seam paths exercised cheaply
CAP = 4096


def _interpret():
    from tests.conftest import pallas_interpret_mode

    return pallas_interpret_mode()


def _pad(data: bytes, w: int = W) -> np.ndarray:
    n = max(128 * (2 * w + 2), -(-len(data) // 128) * 128)
    return tok.pad_to(data, n)


def _cfg(map_impl: str, **kw) -> Config:
    kw.setdefault("chunk_bytes", 128 * (2 * 32 + 2))
    kw.setdefault("table_capacity", CAP)
    return Config(backend="pallas", map_impl=map_impl, **kw)


def _assert_results_equal(a, b):
    assert a.words == b.words
    assert a.counts == b.counts
    assert a.total == b.total
    assert a.dropped_count == b.dropped_count


# -- kernel-level: the fused stream vs split col+seam ------------------------


@pytest.mark.smoke
def test_fused_stream_matches_split_row_set(rng):
    """The fused kernel's ONE stream holds exactly the split path's column
    rows PLUS its seam rows: same live (key, packed) multiset, same exact
    total — the no-deferral property that deletes the seam fix-up pass."""
    corpus = make_corpus(rng, n_words=3000, vocab=200)
    buf = _pad(corpus)
    col, seam, over_s = ptok.tokenize_split(buf, max_token_bytes=W,
                                            interpret=True)
    fused, over_f, spill = ptok.tokenize_fused(buf, max_token_bytes=W,
                                               interpret=True)

    def rows(key_hi, key_lo, packed, live):
        k = np.stack([np.asarray(key_hi), np.asarray(key_lo),
                      np.asarray(packed)], axis=1)[live]
        return k[np.lexsort(k.T)]

    # Seam rows are a TokenStream: dead rows carry pos=POS_INF/count=0
    # (NOT the packed sentinel), so liveness comes from `count`, and the
    # packed view is rebuilt in uint64 before the uint32 cut.
    seam_packed = ((np.asarray(seam.pos).astype(np.uint64) << 6)
                   | np.asarray(seam.length)).astype(np.uint32)
    n_seam = int((np.asarray(seam.count) != 0).sum())
    split_rows = np.concatenate([
        rows(col.key_hi, col.key_lo, col.packed,
             np.asarray(col.packed) != 0xFFFFFFFF),
        rows(seam.key_hi, seam.key_lo, seam_packed,
             np.asarray(seam.count) != 0)])
    split_rows = split_rows[np.lexsort(split_rows.T)]
    np.testing.assert_array_equal(
        rows(fused.key_hi, fused.key_lo, fused.packed,
             np.asarray(fused.packed) != 0xFFFFFFFF), split_rows)
    assert int(fused.total) == int(col.total) + n_seam
    assert int(over_f) == int(over_s)
    assert int(spill) == 0


def test_fused_lane_major_stream_is_position_ordered(rng):
    """The stable2 precondition holds WITHOUT a seam concat: the fused
    lane-major stream's live rows (cross-seam emissions included) carry
    strictly increasing positions."""
    corpus = make_corpus(rng, n_words=4000, vocab=300)
    buf = _pad(corpus)
    stream, _over, spill = ptok.tokenize_fused(
        buf, compact_slots=128, max_token_bytes=W, block_rows=384,
        lane_major=True, interpret=True)
    packed = np.asarray(stream.packed)
    live = packed != 0xFFFFFFFF
    pos = (packed[live] >> 6).astype(np.int64)
    assert len(pos) > 100
    assert np.all(np.diff(pos) > 0)
    assert int(spill) == 0


# -- model-level bit-identity ------------------------------------------------


@pytest.mark.slow
def test_fused_wordcount_bit_identical(rng):
    """Whole wordcount pipeline (compact stable2 default) fused vs split,
    plus the XLA oracle.

    @slow (round 9): 58 s measured — two full compact-pipeline compiles
    on this 1-core box, 6x past the PR-1 ">= ~10 s carries slow" line.
    Tier-1 keeps fused wordcount covered end-to-end by the oracle-exact
    rescue+spill test below (one compile, both cond branches executed)
    and split-vs-fused identity at the stream level by the row-set test
    above; this full split-parity twin runs in the pre-release suite."""
    corpus = make_corpus(rng, n_words=1500, vocab=150)
    with _interpret():
        a = wordcount.count_words(corpus, _cfg("split"))
        b = wordcount.count_words(corpus, _cfg("fused"))
    _assert_results_equal(a, b)
    assert a.as_dict() == oracle.word_counts(corpus)


@pytest.mark.smoke
def test_fused_rescue_and_spill_oracle_exact():
    """BOTH fused fallback semantics in one compile (tier-1's cheap
    coverage; the two-compile split-parity twins below are @slow per the
    PR-1 ">= ~10 s carries slow" line): chunk 1 is slot-budget-dense and
    must take the spill fallback (the SAME fused kernel in pair mode),
    chunk 2 is sparse with overlong runs — one crossing a lane seam —
    that the rescue pass must recover exactly.  One fused config, both
    lax.cond branches executed at runtime, oracle-exact end to end."""
    w = 32  # production W: the seam geometry below assumes min_chunk
    n = 128 * (2 * w + 2)
    seg = n // 128
    dense = (b"a " * (n // 2))[:n]  # density 0.5: overflows the slot budget
    buf = np.full(n, 0x20, dtype=np.uint8)
    buf[seg - 20: seg + 20] = ord("u")  # crosses the first lane seam
    buf[10:50] = ord("v")               # plain in-lane overlong
    words = b"aa bb cc aa "
    buf[60:60 + len(words)] = np.frombuffer(words, dtype=np.uint8)
    data = dense + bytes(buf)
    with _interpret():
        r = wordcount.count_words(
            data, _cfg("fused", chunk_bytes=n, rescue_overlong=8))
    assert r.dropped_count == 0  # both 40-byte runs rescued exactly
    assert r.as_dict() == oracle.word_counts(data)


@pytest.mark.slow
def test_fused_spill_fallback_parity():
    """Windows denser than the slot budget must spill into the fused
    full-resolution fallback and stay bit-identical to the split path's
    fallback (@slow: two full pipeline compiles, ~50 s on this box;
    tier-1 keeps the runtime spill path via the oracle test above)."""
    data = b"a " * 4000  # density 0.5: overflows any 1/3 slot budget
    with _interpret():
        a = wordcount.count_words(data, _cfg("split"))
        b = wordcount.count_words(data, _cfg("fused"))
    _assert_results_equal(a, b)
    assert b.as_dict() == oracle.word_counts(data)
    assert b.total == 4000


@pytest.mark.slow
def test_fused_overlong_rescue_parity():
    """Overlong tokens — including one crossing a lane seam — are rescued
    identically on the fused path, with identical accounting (@slow: two
    full pipeline compiles; tier-1 keeps rescue-on-fused via the oracle
    test above)."""
    w = 32  # production W: the seam geometry below assumes min_chunk
    n = 128 * (2 * w + 2)
    seg = n // 128
    buf = np.full(n, 0x20, dtype=np.uint8)
    buf[seg - 20: seg + 20] = ord("u")  # crosses the first lane seam
    buf[10:50] = ord("v")               # plain in-lane overlong
    words = b"aa bb cc aa "
    buf[60:60 + len(words)] = np.frombuffer(words, dtype=np.uint8)
    data = bytes(buf)
    with _interpret():
        a = wordcount.count_words(
            data, _cfg("split", chunk_bytes=n, rescue_overlong=8))
        b = wordcount.count_words(
            data, _cfg("fused", chunk_bytes=n, rescue_overlong=8))
    _assert_results_equal(a, b)
    assert b.dropped_count == 0  # both 40-byte runs rescued exactly
    assert b.as_dict() == oracle.word_counts(data)


@pytest.mark.smoke
def test_fused_ngram_bit_identical(rng):
    """The gram family's fused path (full-resolution pair stream straight
    into the position sort) vs the split col+seam concat."""
    corpus = make_corpus(rng, n_words=2500, vocab=120)
    with _interpret():
        a = wordcount.count_ngrams(corpus, 2, _cfg("split"))
        b = wordcount.count_ngrams(corpus, 2, _cfg("fused"))
    _assert_results_equal(a, b)


@pytest.mark.slow
def test_fused_dropped_accounting_parity(rng):
    """Without rescue, overlong runs land in dropped_* accounting — the
    fused kernel's in-kernel overlong count (no seam-pass share) must
    match the split path's two-source sum exactly."""
    head = b"x" * 50 + b" "  # overlong at W=32, dropped with rescue OFF
    corpus = head + make_corpus(rng, n_words=3000, vocab=150)
    with _interpret():
        a = wordcount.count_words(corpus, _cfg("split", rescue_overlong=0))
        b = wordcount.count_words(corpus, _cfg("fused", rescue_overlong=0))
    _assert_results_equal(a, b)
    assert b.dropped_count >= 1


@pytest.mark.slow
def test_fused_streamed_executor(tmp_path, rng):
    """Streamed fused run == streamed split run through the real executor
    (4-device mesh, see test_stable2_streamed_executor for the mesh-width
    note), byte-identical results and oracle-exact."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime.executor import count_file

    corpus = make_corpus(rng, n_words=6000, vocab=150)
    p = tmp_path / "c.txt"
    p.write_bytes(corpus)
    with _interpret():
        a = count_file([str(p)], config=_cfg("split", chunk_bytes=1 << 14),
                       mesh=data_mesh(4))
        b = count_file([str(p)], config=_cfg("fused", chunk_bytes=1 << 14),
                       mesh=data_mesh(4))
    _assert_results_equal(a, b)
    assert b.as_dict() == oracle.word_counts(corpus)


def test_map_impl_validation():
    with pytest.raises(ValueError, match="map_impl"):
        Config(backend="pallas", map_impl="bogus")
