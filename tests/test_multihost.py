"""Host-side multi-host partitioning logic (pure functions; the collective
side of multi-host is covered by the emulated-mesh tests in
test_distributed.py and the driver's dryrun_multichip)."""

from __future__ import annotations

import numpy as np
import pytest

from mapreduce_tpu.parallel import distributed as dist
from mapreduce_tpu.utils import oracle


def test_host_byte_ranges_partition_exactly():
    size = 1_000_003
    ranges = [dist.host_byte_range(size, p, 8) for p in range(8)]
    assert ranges[0][0] == 0 and ranges[-1][1] == size
    for (a_lo, a_hi), (b_lo, b_hi) in zip(ranges, ranges[1:]):
        assert a_hi == b_lo and a_lo < a_hi


def test_host_byte_range_validates_index():
    with pytest.raises(ValueError):
        dist.host_byte_range(100, 4, 4)


def test_aligned_ranges_count_every_token_once(tmp_path, rng):
    """Crucial seam property: snapping both ends with the same rule keeps
    ranges exactly adjacent, and summing per-range counts == global count."""
    from tests.conftest import make_corpus

    corpus = make_corpus(rng, n_words=3000, vocab=100)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    n_hosts = 4
    totals: dict[bytes, int] = {}
    prev_hi = 0
    for p in range(n_hosts):
        lo, hi = dist.host_byte_range(len(corpus), p, n_hosts)
        lo, hi = dist.align_range_to_separator(str(path), lo, hi)
        assert lo == prev_hi  # ranges stay a partition after snapping
        prev_hi = hi
        for w, c in oracle.word_counts(corpus[lo:hi]).items():
            totals[w] = totals.get(w, 0) + c
    assert prev_hi == len(corpus)
    assert totals == oracle.word_counts(corpus)


def test_align_handles_separator_free_file(tmp_path):
    blob = b"x" * 4096  # one giant token, no separators at all
    path = tmp_path / "b.txt"
    path.write_bytes(blob)
    lo, hi = dist.align_range_to_separator(str(path), 1024, 3072,
                                           max_token_bytes=256)
    assert (lo, hi) == (1024, 3072)  # falls back to force-split offsets


def test_host_shards_are_process_major():
    assert list(dist.host_shards(16, 1, 4)) == [4, 5, 6, 7]
    with pytest.raises(ValueError):
        dist.host_shards(10, 0, 4)


def test_initialize_is_noop_on_single_host(monkeypatch):
    for var in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    dist.initialize()  # must not raise or hang
    assert dist.is_coordinator()


@pytest.mark.slow
def test_per_host_byte_range_runs_merge_to_global_counts(tmp_path, rng):
    """The full documented multi-host flow, emulated in-process: each 'host'
    streams only its aligned [lo, hi) range (run_job byte_range), and the
    merged per-host tables equal a single global run."""
    from tests.conftest import make_corpus
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.ops import table as table_ops
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    corpus = make_corpus(rng, n_words=3000, vocab=120)
    path = tmp_path / "c.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=512, table_capacity=1024)
    mesh = data_mesh(2)
    job = WordCountJob(cfg)

    n_hosts = 3
    partials = []
    for p in range(n_hosts):
        lo, hi = dist.host_byte_range(len(corpus), p, n_hosts)
        lo, hi = dist.align_range_to_separator(str(path), lo, hi)
        rr = executor.run_job(job, str(path), config=cfg, mesh=mesh,
                              byte_range=(lo, hi))
        partials.append(rr.value)

    merged = partials[0]
    for t in partials[1:]:
        merged = table_ops.merge(merged, t, capacity=cfg.table_capacity)

    got = {(int(h), int(l)): int(c) for h, l, c in
           zip(np.asarray(merged.key_hi), np.asarray(merged.key_lo),
               np.asarray(merged.count)) if c > 0}
    expected = oracle.word_counts(corpus)
    assert sorted(got.values()) == sorted(expected.values())
    assert int(np.asarray(merged.total_count())) == oracle.total_count(corpus)


@pytest.mark.slow
def test_true_multiprocess_spmd_run(tmp_path):
    """VERDICT r1 #7: REAL multi-process multi-host — 2 worker processes
    join one JAX runtime via jax.distributed.initialize (gloo CPU
    collectives), build a 4-device global mesh, stage only their own shard
    rows via device_put_local, and drive the Engine's sharded step +
    collective finish.  The coordinator's replicated result must equal a
    single-process oracle count."""
    import json
    import os
    import socket
    import subprocess
    import sys
    from pathlib import Path

    corpus = (b"Hello World EveryOne\nWorld Good News\n"
              b"Good Morning Hello\n" * 40)
    path = tmp_path / "mh.txt"
    path.write_bytes(corpus)

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    repo = Path(__file__).resolve().parent.parent
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["PYTHONPATH"] = str(repo)
    worker = str(repo / "tests" / "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(p), "2", str(port), str(path), "256", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for p in range(2)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=300))
    finally:
        for p in procs:
            p.kill()
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"

    # Coordinator prints the one JSON line (gloo chatter precedes it).
    json_lines = [ln for out, _ in outs for ln in out.splitlines()
                  if ln.startswith("{")]
    assert len(json_lines) == 1, json_lines
    got = json.loads(json_lines[0])
    expected = oracle.word_counts(corpus)
    assert got["total"] == oracle.total_count(corpus)
    assert got["distinct"] == len(expected)
    assert got["counts"] == sorted(expected.values())
    assert got["processes"] == 2 and got["devices"] == 4


@pytest.mark.slow
def test_run_job_global_multiprocess_with_crash_resume(tmp_path):
    """VERDICT r3 #5 'done' case: the executor-level global-SPMD driver
    (run_job_global) runs REAL 2-process SPMD over gloo — global mesh,
    host_shards staging, coordinator-only checkpoints — survives a
    synchronized injected crash, and a relaunch RESUMES from the
    checkpoint to the exact oracle counts."""
    import json
    import os

    corpus = (b"Hello World EveryOne\nWorld Good News\n"
              b"Good Morning Hello\n" * 40)
    path = tmp_path / "gmh.txt"
    path.write_bytes(corpus)
    ckpt = str(tmp_path / "g.ck.npz")

    # Round 1: both processes crash (synchronously) before step 2; the
    # coordinator has checkpointed steps 1 and 2 by then.
    procs, outs = _launch_global_workers(path, ckpt, crash_at=2)
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 17, f"injection missing:\nrc={p.returncode}\n{err[-2000:]}"
    assert os.path.exists(ckpt), "no checkpoint written before the crash"

    # Round 2: fresh processes resume from the checkpoint and finish.
    procs, outs = _launch_global_workers(path, ckpt, crash_at=-1)
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"resume failed:\n{err[-2000:]}"
    json_lines = [ln for out, _ in outs for ln in out.splitlines()
                  if ln.startswith("{")]
    assert len(json_lines) == 1, json_lines
    got = json.loads(json_lines[0])
    expected = oracle.word_counts(corpus)
    assert got["total"] == oracle.total_count(corpus)
    assert got["distinct"] == len(expected)
    assert got["counts"] == sorted(expected.values())
    assert got["processes"] == 2 and got["devices"] == 4


@pytest.mark.slow
def test_run_job_global_host_kill_fault_resumes(tmp_path):
    """ISSUE 15 chaos matrix: the process-kill seam on the REAL
    2-process gloo harness.  A fault plan hard-kills every process
    (``os._exit(113)``) at the same deterministic crossing — a
    synchronized platform reclaim, fired through the executor's own
    injection seam rather than a monkeypatched step — after the
    coordinator has checkpointed; each process's ledger shard records
    the `fault` before dying; a plan-free relaunch resumes from the
    checkpoint to the exact oracle counts."""
    import json
    import os

    corpus = (b"Hello World EveryOne\nWorld Good News\n"
              b"Good Morning Hello\n" * 40)
    path = tmp_path / "gk.txt"
    path.write_bytes(corpus)
    ckpt = str(tmp_path / "gk.ck.npz")
    ledger = str(tmp_path / "gk.jsonl")

    # Round 1: the plan kills both processes at process-kill crossing 2
    # (the third dispatched group) — checkpoint_every=1 guarantees a
    # snapshot exists by then.
    procs, outs = _launch_global_workers(
        path, ckpt, crash_at=-1, ledger=ledger,
        fault_plan="at=process-kill:2:permanent")
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 113, \
            f"hard-kill missing:\nrc={p.returncode}\n{err[-2000:]}"
    assert os.path.exists(ckpt), "no checkpoint written before the kill"
    # Every process's shard recorded the typed fault before os._exit —
    # the flushed-ledger contract is what makes a kill diagnosable.
    from mapreduce_tpu import obs

    for proc_index in (0, 1):
        shard = f"{ledger}.h{proc_index}.jsonl"
        assert os.path.exists(shard), shard
        faults_recs = [r for r in obs.read_ledger(shard)
                       if r.get("kind") == "fault"]
        assert any(f.get("seam") == "process-kill" and f.get("injected")
                   for f in faults_recs), (proc_index, faults_recs)

    # Round 2: plan-free relaunch resumes and finishes exactly.
    procs, outs = _launch_global_workers(path, ckpt, crash_at=-1)
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"resume failed:\n{err[-2000:]}"
    json_lines = [ln for out, _ in outs for ln in out.splitlines()
                  if ln.startswith("{")]
    assert len(json_lines) == 1, json_lines
    got = json.loads(json_lines[0])
    expected = oracle.word_counts(corpus)
    assert got["total"] == oracle.total_count(corpus)
    assert got["distinct"] == len(expected)
    assert got["counts"] == sorted(expected.values())


@pytest.mark.slow
def test_run_job_global_host_kill_after_partial_merge_resumes(tmp_path):
    """ISSUE 20 chaos: hard-kill every process AFTER window-boundary
    partial merges have drained local tables into the replicated
    accumulator.  The shards must show op='partial' collective records
    preceding the process-kill fault; the plan-free relaunch (overlap
    still on) resumes from the coordinator's {state, accumulator}
    snapshot to the exact oracle counts — the partial-merge/checkpoint
    interaction the fast tier cannot cover with real collectives."""
    import json
    import os

    corpus = (b"Hello World EveryOne\nWorld Good News\n"
              b"Good Morning Hello\n" * 40)
    path = tmp_path / "gp.txt"
    path.write_bytes(corpus)
    ckpt = str(tmp_path / "gp.ck.npz")
    ledger = str(tmp_path / "gp.jsonl")

    # inflight_groups=1 (the worker's overlap mode) + checkpoint_every=1
    # fire a partial at every checkpoint boundary, so by process-kill
    # crossing 2 (the last dispatched group on this corpus) two partials
    # have merged and the latest snapshot holds the accumulator.
    procs, outs = _launch_global_workers(
        path, ckpt, crash_at=-1, ledger=ledger,
        fault_plan="at=process-kill:2:permanent", merge_overlap=True)
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 113, \
            f"hard-kill missing:\nrc={p.returncode}\n{err[-2000:]}"
    assert os.path.exists(ckpt), "no checkpoint written before the kill"
    from mapreduce_tpu import obs

    for proc_index in (0, 1):
        shard = f"{ledger}.h{proc_index}.jsonl"
        recs = list(obs.read_ledger(shard))
        partial_ts = [r["ts"] for r in recs if r.get("kind") == "collective"
                      and r.get("op") == "partial"]
        kill_ts = [r["ts"] for r in recs if r.get("kind") == "fault"
                   and r.get("seam") == "process-kill"]
        assert partial_ts and kill_ts, (proc_index, recs)
        assert min(partial_ts) < min(kill_ts), \
            "the kill must land AFTER a partial merge retired"

    # Plan-free relaunch, overlap still on: resume merges the snapshot's
    # accumulator + residual to the exact oracle counts.
    procs, outs = _launch_global_workers(path, ckpt, crash_at=-1,
                                         merge_overlap=True)
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"resume failed:\n{err[-2000:]}"
    json_lines = [ln for out, _ in outs for ln in out.splitlines()
                  if ln.startswith("{")]
    assert len(json_lines) == 1, json_lines
    got = json.loads(json_lines[0])
    expected = oracle.word_counts(corpus)
    assert got["total"] == oracle.total_count(corpus)
    assert got["distinct"] == len(expected)
    assert got["counts"] == sorted(expected.values())


def _launch_global_workers(path, ckpt, crash_at, ledger=None,
                           chunk_bytes=256, fault_plan=None,
                           merge_overlap=False):
    """Spawn the 2-process run_job_global gloo harness (global_worker.py);
    ``ledger`` attaches telemetry at a shared path (ISSUE 13);
    ``fault_plan`` arms the executor's injection seams (ISSUE 15 — the
    process-kill seam is the host-kill chaos scenario);
    ``merge_overlap`` turns on window-boundary partial merges at
    inflight_groups=1 (ISSUE 20)."""
    import os
    import socket
    import subprocess
    import sys
    from pathlib import Path

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    repo = Path(__file__).resolve().parent.parent
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["PYTHONPATH"] = str(repo)
    if merge_overlap:
        env["GW_MERGE_OVERLAP"] = "1"
    else:
        env.pop("GW_MERGE_OVERLAP", None)
    worker = str(repo / "tests" / "global_worker.py")
    argv = [sys.executable, worker, "PID", "2", str(port), str(path),
            str(chunk_bytes), "2", str(ckpt), str(crash_at)]
    if ledger is not None or fault_plan is not None:
        argv.append(ledger or "")
    if fault_plan is not None:
        argv.append(fault_plan)
    procs = [subprocess.Popen(argv[:2] + [str(p)] + argv[3:],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for p in range(2)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=300))
    finally:
        for p in procs:
            p.kill()
    return procs, outs


@pytest.mark.slow
def test_run_job_global_multiprocess_writes_host_shards(tmp_path):
    """ISSUE 13 tentpole, falsified on the real 2-process gloo harness: a
    telemetered run_job_global leaves one host-stamped shard ledger per
    process (one group record per retired group, the run-epoch clock on
    run_start, a collective record, per-host run_end phases) next to the
    coordinator's main file; obs/fleet.py merges the shards into a 2-host
    view with a fleet_bottleneck verdict, byte-stable across merges."""
    import json
    import os

    from mapreduce_tpu import obs
    from mapreduce_tpu.obs import fleet

    corpus = (b"Hello World EveryOne\nWorld Good News\n"
              b"Good Morning Hello\n" * 40)
    path = tmp_path / "fl.txt"
    path.write_bytes(corpus)
    ledger = str(tmp_path / "fl.jsonl")

    procs, outs = _launch_global_workers(path, tmp_path / "fl.ck.npz",
                                         crash_at=-1, ledger=ledger)
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"

    # The coordinator's main file: gated records, all host-0 stamped.
    main = list(obs.read_ledger(ledger))
    kinds = [r["kind"] for r in main]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    assert "collective" in kinds
    assert all(r.get("host") == 0 for r in main), \
        "only the coordinator writes the main file"
    n_groups_main = kinds.count("group")
    assert n_groups_main > 0

    # One shard per process, every record host-stamped, exactly one group
    # record per retired group (== the coordinator's count: SPMD lockstep),
    # topology + clock on run_start, per-host run_end.
    for h in (0, 1):
        sp = obs.shard_path(ledger, h)
        assert os.path.exists(sp), f"missing shard {sp}"
        recs = list(obs.read_ledger(sp))
        assert all(r.get("host") == h for r in recs)
        start = next(r for r in recs if r["kind"] == "run_start")
        assert start["ledger_version"] == obs.LEDGER_VERSION == 10
        assert start["processes"] == 2 and start["local_devices"] == 2
        assert set(start["clock"]) == {"wall", "mono"}
        groups = [r for r in recs if r["kind"] == "group"]
        assert len(groups) == n_groups_main
        assert all(g.get("host_bytes") is not None for g in groups), \
            "global-driver groups carry this host's staged bytes"
        assert all(g["host_bytes"] <= g["group_bytes"] for g in groups)
        assert [r["kind"] for r in recs].count("run_end") == 1
        assert any(r["kind"] == "collective" for r in recs)

    # Fleet merge: 2 hosts, aligned clocks, a verdict, stable bytes.
    by_host = {h: fleet.read_jsonl(p)
               for h, p in fleet.shard_paths(ledger).items()}
    view = fleet.fleet_view(by_host)
    assert view["hosts"] == [0, 1] and view["aligned"] is True
    assert view["processes"] == 2
    assert view["fleet_bottleneck"]["verdict"] in (
        "straggler-bound", "collective-bound", "balanced")
    assert view["per_host"]["0"]["groups"] == n_groups_main
    # Both hosts staged half the shard rows of the same global batches.
    assert view["per_host"]["0"]["group_bytes"] \
        == view["per_host"]["1"]["group_bytes"]
    twice = [json.dumps(fleet.fleet_view(by_host), sort_keys=True)
             for _ in range(2)]
    assert twice[0] == twice[1]


@pytest.mark.slow
def test_noncoordinator_failure_leaves_host_flight_dump(tmp_path):
    """ISSUE 13 satellite bugfix: pre-v7 the coordinator-only write_gate
    swallowed every non-coordinator flight dump.  An injected failure now
    leaves a dump from EACH host at its own path — the non-coordinator's
    at the host-suffixed one — plus a failure record in its shard."""
    import json
    import os

    from mapreduce_tpu import obs

    corpus = (b"Hello World EveryOne\nWorld Good News\n"
              b"Good Morning Hello\n" * 40)
    path = tmp_path / "flc.txt"
    path.write_bytes(corpus)
    ledger = str(tmp_path / "flc.jsonl")

    procs, outs = _launch_global_workers(path, tmp_path / "flc.ck.npz",
                                         crash_at=2, ledger=ledger)
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 17, \
            f"injection missing:\nrc={p.returncode}\n{err[-2000:]}"

    # Coordinator keeps the classic path; host 1 dumps to its own file.
    assert os.path.exists(ledger + ".flight.json")
    h1_dump = obs.shard_flight_path(ledger, 1)
    assert os.path.exists(h1_dump), \
        "non-coordinator failure must dump on that host"
    with open(h1_dump) as f:
        dump = json.load(f)
    assert "injected crash" in dump["context"]["error"]
    assert dump["events"], "the ring must carry the host's events"
    # The failure record lands in host 1's shard (the main file's copy
    # stays coordinator-gated).
    h1 = list(obs.read_ledger(obs.shard_path(ledger, 1)))
    fails = [r for r in h1 if r["kind"] == "failure"]
    assert len(fails) == 1 and fails[0]["host"] == 1
    assert fails[0]["flight_dump"] == h1_dump
    main_fails = [r for r in obs.read_ledger(ledger)
                  if r["kind"] == "failure"]
    assert all(r.get("host") == 0 for r in main_fails)


@pytest.mark.slow
def test_run_job_global_window_matches_serial(tmp_path, rng):
    """ISSUE 5 on the global-SPMD driver: run_job_global (single process —
    initialize() is a no-op, the global mesh is all local devices) with
    the dispatch window active produces the identical result as the
    serialized control, with pooled shard-row staging and the window
    statistics in the run result."""
    from tests.conftest import make_corpus
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.runtime import executor

    corpus = make_corpus(rng, n_words=2500, vocab=100)
    path = tmp_path / "g.txt"
    path.write_bytes(corpus)
    dist.initialize()
    totals = {}
    for inflight in (1, 3):
        cfg = Config(chunk_bytes=256, table_capacity=1024,
                     inflight_groups=inflight)
        rr = executor.run_job_global(WordCountJob(cfg), str(path), config=cfg)
        assert rr.metrics.bytes_processed == len(corpus)
        assert rr.pipeline["inflight_groups"] == inflight
        counts = sorted(int(c) for c in np.asarray(rr.value.count) if c > 0)
        totals[inflight] = (rr.metrics.words_counted, counts)
    assert totals[1] == totals[3]
    assert totals[1][0] == oracle.total_count(corpus)
    assert totals[1][1] == sorted(oracle.word_counts(corpus).values())
