"""Mesh-aware collective analysis (ISSUE 16): meshcost link model,
collective-cost pass + SPMD divergence lint, planner descriptors.

Three layers under test:

* ``analysis/meshcost.py`` — the alpha-beta schedule arithmetic against
  hand-computed values (crossover, budget rows, plan rankings), and the
  strategy-descriptor bijection with the runtime builders in
  ``parallel/collectives.py``;
* the ``collective-cost`` pass — the priced artifact over the fleet
  registry twins, the hbm-cost artifact's ``collective.priced`` marker
  flip, and one known-bad fixture per divergence-lint failure mode
  (collective under a device-varying cond, same collective over
  mismatched axis names, collective in one branch only), each an ERROR
  with a non-zero exit code;
* the planner surface — ``tools/redplan.py --selftest`` covers the
  jax-free half in tier-1/smoke; here the jax-side gate twins stay
  clean.
"""

import math

import jax
import jax.numpy as jnp
import pytest

from mapreduce_tpu import analysis
from mapreduce_tpu import models as models_mod
from mapreduce_tpu.analysis import meshcost
from mapreduce_tpu.analysis.passes.collective import CollectivePass
from mapreduce_tpu.analysis.passes.cost import CostPass
from mapreduce_tpu.parallel import collectives
from mapreduce_tpu.parallel.mesh import data_mesh


@pytest.fixture(scope="module")
def mesh8():
    return data_mesh(8)


# -- meshcost arithmetic (jax-free; the redplan selftest's pytest twin) ------


@pytest.mark.smoke
def test_ring_tree_crossover_hand_arithmetic():
    """M* = 8*alpha*beta at D=4 (the closed form's hand-checkable case):
    3.6 MB on the measured ICI rates, with ring == tree == 180us there."""
    ici = meshcost.load_link_rates()["levels"]["ici"]
    mstar = meshcost.ring_tree_crossover_bytes(4, ici)
    assert math.isclose(mstar, 8 * ici.alpha_s * ici.beta_bps)
    assert math.isclose(mstar, 3.6e6)
    assert math.isclose(meshcost.allreduce_ring(mstar, 4, ici),
                        meshcost.allreduce_tree(mstar, 4, ici))
    assert meshcost.allreduce_tree(mstar / 4, 4, ici) \
        < meshcost.allreduce_ring(mstar / 4, 4, ici)
    assert meshcost.allreduce_ring(4 * mstar, 4, ici) \
        < meshcost.allreduce_tree(4 * mstar, 4, ici)
    assert meshcost.ring_tree_crossover_bytes(2, ici) == math.inf


@pytest.mark.smoke
def test_plan_rankings_and_skew_derating():
    """The planner's two fixture shapes: latency-bound 229 KB payload ->
    gather tops; 917 KB -> tree's log2(D) ICI rounds win; Zipf top_mass
    0.3 derates keyrange by exactly 1.3x."""
    p = meshcost.plan(2, 4, 8192)
    assert [r["strategy"] for r in p["ranked"]] \
        == ["gather", "tree", "hier-tree-tree", "hier-kr-tree", "keyrange"]
    assert p["payload_bytes"] == 7 * 4 * 8192 == 229376
    # hier-tree-tree prices IDENTICAL to tree (same schedule, named
    # placement) and the declaration-order tie-break keeps tree first.
    by = {r["strategy"]: r["modeled_s"] for r in p["ranked"]}
    assert by["hier-tree-tree"] == by["tree"]
    p = meshcost.plan(2, 4, 32768, top_mass=0.3, table_occupancy=0.85,
                      incumbent="tree")
    assert [r["strategy"] for r in p["ranked"]] \
        == ["tree", "hier-tree-tree", "gather", "hier-kr-tree", "keyrange"]
    assert p["incumbent_is_top"] is True
    kr = next(r for r in p["ranked"] if r["strategy"] == "keyrange")
    levels = meshcost.load_link_rates()["levels"]
    m = meshcost.table_bytes(32768)
    base = meshcost.keyrange(m, 8, levels["dcn"], slack=2.0)
    assert math.isclose(kr["modeled_s"], base * 1.3, rel_tol=1e-6)
    # hier-kr-tree: skew derates the INNER keyrange leg only — the outer
    # DCN tree leg carries no hot-owner partition.
    hkt = next(r for r in p["ranked"] if r["strategy"] == "hier-kr-tree")
    inner = meshcost.keyrange(m, 4, levels["ici"], slack=2.0)
    outer = meshcost.allreduce_tree(m, 2, levels["dcn"])
    assert math.isclose(hkt["modeled_s"], inner * 1.3 + outer, rel_tol=1e-6)
    assert hkt["keyrange_budget_rows"] \
        == meshcost.keyrange_budget_rows(32768, 4, 2.0)
    # No keyrange hook -> skipped with a reason, never silently priced
    # (hier-kr-tree's inner leg is the same hook).
    p = meshcost.plan(8, 1, 8192, has_keyrange_hook=False)
    assert [s["strategy"] for s in p["skipped"]] \
        == ["keyrange", "hier-kr-tree"]
    # Single-axis meshes have nothing to place over: both hier
    # compositions are skipped, never priced as degenerates.
    p1 = meshcost.plan(1, 8, 8192)
    assert [s["strategy"] for s in p1["skipped"]] \
        == ["hier-kr-tree", "hier-tree-tree"]
    assert all("multi-axis" in s["why"] for s in p1["skipped"])


@pytest.mark.smoke
def test_strategy_descriptors_bijection_with_runtime():
    """The planner can never rank a strategy the runtime does not build
    (or miss one it does): names, builder functions, and feasibility
    constraints pinned equal across the jax-free mirror."""
    assert set(meshcost.STRATEGIES) == set(collectives.STRATEGIES)
    # The hierarchical 2-D compositions are first-class descriptors on
    # both sides, not runtime-only aliases.
    assert {"hier-kr-tree", "hier-tree-tree"} <= set(meshcost.STRATEGIES)
    # The jax-free Config mirror (the CLI/bench choices surface) names
    # exactly the runtime set — 'auto' stays a driver-side alias, never
    # a descriptor.
    from mapreduce_tpu.config import MERGE_STRATEGIES

    assert set(MERGE_STRATEGIES) == set(collectives.STRATEGIES)
    assert "auto" not in MERGE_STRATEGIES
    for name, strat in meshcost.STRATEGIES.items():
        runtime = collectives.STRATEGIES[name]
        assert strat.builder == runtime["builder"], name
        assert strat.power_of_two_only == runtime["power_of_two_only"], name
        assert strat.needs_keyrange_hook == runtime["needs_keyrange_hook"], \
            name
        # The dotted path names a real callable in collectives.
        fn_name = strat.builder.rsplit(".", 1)[-1]
        assert callable(getattr(collectives, fn_name)), strat.builder


@pytest.mark.smoke
def test_keyrange_budget_rows_matches_runtime_formula():
    """meshcost's spill arithmetic == key_range_merge's docstring budget
    B = min(cap, ceil(s*cap/D) + 8 + 4*ceil(log2 D))."""
    for cap, d in ((8192, 8), (32768, 8), (512, 4), (4096, 3), (8192, 1)):
        want = cap if d <= 1 else min(
            cap, -(-int(2.0 * cap) // d) + 8 + 4 * (d - 1).bit_length())
        assert meshcost.keyrange_budget_rows(cap, d, 2.0) == want, (cap, d)


# -- known-bad divergence fixtures (duck-typed MapReduceJobs) ----------------


class _ScalarJob:
    """Minimal correct job (the test_graphcheck fixture shape): count
    non-pad bytes into one bare uint32 scalar."""

    def init_state(self):
        return jnp.zeros((), jnp.uint32)

    def map_chunk(self, chunk, chunk_id):
        return jnp.sum((chunk != 0).astype(jnp.uint32))

    def combine(self, state, update):
        return state + update

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return state

    def identity(self):
        return type(self).__name__.lower()


class DivergentCollectiveJob(_ScalarJob):
    """Branches of a device-varying cond execute DIFFERENT collectives
    (psum vs pmax): participants diverge at the first mismatch — the
    generic distributed-hang fixture."""

    def map_chunk_sharded(self, chunk, chunk_id, axis, device_index):
        total = self.map_chunk(chunk, chunk_id)
        pred = jax.lax.axis_index(axis) == 0  # varying by construction
        return jax.lax.cond(pred,
                            lambda t: jax.lax.psum(t, axis),
                            lambda t: jax.lax.pmax(t, axis),
                            total)


class OneBranchCollectiveJob(_ScalarJob):
    """A collective in ONE branch of a device-varying cond (the other
    branch is collective-free): devices taking the empty branch never
    enter the psum — the canonical SPMD hang."""

    def map_chunk_sharded(self, chunk, chunk_id, axis, device_index):
        total = self.map_chunk(chunk, chunk_id)
        pred = jnp.sum(chunk.astype(jnp.uint32)) % 2 == 0  # data-varying
        return jax.lax.cond(pred,
                            lambda t: jax.lax.psum(t, axis),
                            lambda t: t + jnp.uint32(0),
                            total)


class AxisMismatchJob(_ScalarJob):
    """Both branches psum, but over DIFFERENT mesh axes of the 2-D fleet
    mesh: device groups disagree on who participates."""

    def map_chunk_sharded(self, chunk, chunk_id, axis, device_index):
        total = self.map_chunk(chunk, chunk_id)
        pred = jnp.sum(chunk.astype(jnp.uint32)) % 2 == 0
        return jax.lax.cond(pred,
                            lambda t: jax.lax.psum(t, "data"),
                            lambda t: jax.lax.psum(t, "replica"),
                            total)


def _errors(report):
    return [f for f in report.errors if f.pass_id == "collective-cost"]


def test_divergent_collectives_flagged(mesh8):
    report = analysis.analyze_job(DivergentCollectiveJob(),
                                  "divergent-collective", mesh=mesh8,
                                  passes=[CollectivePass()])
    errs = _errors(report)
    assert errs, report.format_text()
    assert any("different collective programs" in f.message for f in errs)
    assert report.exit_code != 0


def test_collective_in_one_branch_flagged(mesh8):
    report = analysis.analyze_job(OneBranchCollectiveJob(),
                                  "one-branch-collective", mesh=mesh8,
                                  passes=[CollectivePass()])
    errs = _errors(report)
    assert errs, report.format_text()
    assert any("never enter the collective" in f.message for f in errs)
    assert report.exit_code != 0


def test_axis_mismatch_across_branches_flagged(mesh8):
    job = AxisMismatchJob()
    job.analysis_fleet = {"processes": 2, "local_devices": 4}
    report = analysis.analyze_job(job, "axis-mismatch",
                                  passes=[CollectivePass()])
    errs = _errors(report)
    assert errs, report.format_text()
    assert any("MISMATCHED axis names" in f.message for f in errs)
    assert report.exit_code != 0


def test_uniform_cond_stays_quiet(mesh8):
    """The lint's negative space: asymmetric branches under a UNIFORM
    predicate (every device takes the same path — the spill-fallback
    shape every shipped model relies on) must not flag."""

    class UniformCondJob(_ScalarJob):
        def map_chunk_sharded(self, chunk, chunk_id, axis, device_index):
            total = self.map_chunk(chunk, chunk_id)
            # Reduced first: the predicate is identical on every device.
            reduced = jax.lax.psum(total, axis)
            return jax.lax.cond(reduced > 0,
                                lambda t: jax.lax.psum(t, axis),
                                lambda t: t + jnp.uint32(0),
                                total)

    report = analysis.analyze_job(UniformCondJob(), "uniform-cond",
                                  mesh=mesh8, passes=[CollectivePass()])
    assert not _errors(report), report.format_text()


# -- the priced artifact + fleet twins ---------------------------------------


def test_collective_cost_artifact_over_fleet_twin(mesh8):
    """The 2x4 fleet twin prices a real ICI/DCN program: artifact carries
    the mesh attribution (outer axis DCN), per-program modeled seconds,
    and a DCN share that dominates the ICI share (the 18x beta gap)."""
    job = models_mod.build_model("wordcount_fleet2")
    report = analysis.analyze_job(job, "wordcount_fleet2",
                                  passes=[CollectivePass()])
    art = report.artifacts["wordcount_fleet2"]["collective_cost"]
    assert art["mesh"]["label"] == "2dx4i"
    assert art["mesh"]["processes"] == 2 and art["mesh"]["devices"] == 8
    assert [a["level"] for a in art["mesh"]["axes"]] == ["dcn", "ici"]
    assert art["modeled_total_s"] > 0 and art["total_bytes"] > 0
    per_level: dict = {}
    for prog in art["programs"].values():
        for e in prog["collectives"]:
            for pa in e["per_axis"]:
                per_level[pa["level"]] = \
                    per_level.get(pa["level"], 0.0) + pa["seconds"]
    assert per_level.get("dcn", 0.0) > per_level.get("ici", 0.0)


def test_hbm_cost_artifact_surfaces_collective_family(mesh8):
    """The satellite marker: the hbm-cost artifact reports the collective
    byte family with priced=False alone, flipped priced=True (with
    modeled seconds) once the collective-cost pass runs after it."""
    job = models_mod.build_model("wordcount")
    report = analysis.analyze_job(job, "wordcount", mesh=mesh8,
                                  passes=[CostPass()])
    coll = report.artifacts["wordcount"]["cost"]["collective"]
    assert coll["priced"] is False and coll["total_bytes"] > 0
    report = analysis.analyze_job(models_mod.build_model("wordcount"),
                                  "wordcount", mesh=mesh8,
                                  passes=[CostPass(), CollectivePass()])
    coll = report.artifacts["wordcount"]["cost"]["collective"]
    assert coll["priced"] is True
    assert coll["priced_by"] == "collective-cost"
    assert coll["modeled_s"] > 0


@pytest.mark.slow
def test_fleet_twins_clean_under_full_pipeline():
    """All three fleet registry twins (2x4 tree, 2x4 hier-kr-tree, 8x1
    keyrange) carry zero error findings under the full default pipeline —
    the all-models gate extension the ISSUE requires, scoped to the new
    twins so the fast tier doesn't re-sweep the whole zoo (tier-1's
    --all-models run covers that)."""
    labels = {"wordcount_fleet2": "2dx4i", "wordcount_fleet2x4": "2dx4i",
              "wordcount_fleet8": "8d"}
    arts = {}
    for name, label in labels.items():
        job = models_mod.build_model(name)
        report = analysis.analyze_job(job, model=name)
        assert not report.errors, report.format_text()
        art = report.artifacts[name]["collective_cost"]
        assert art["mesh"]["label"] == label
        arts[name] = art
    # The placed 2-D program (keyrange confined to ICI + one tree leg
    # across DCN) prices BELOW the per-level tree twin over the identical
    # topology — the planner's tradeoff, certified on the traced programs
    # (the checked-in .collective.json baselines pin the same ordering).
    assert arts["wordcount_fleet2x4"]["modeled_total_s"] \
        < arts["wordcount_fleet2"]["modeled_total_s"]
