"""Unit tests for the segmented-scan tokenizer (mapreduce_tpu/ops/tokenize.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_tpu import constants
from mapreduce_tpu.ops import tokenize as tok
from mapreduce_tpu.utils import oracle


def _as_buf(data: bytes):
    return jnp.asarray(np.frombuffer(data, dtype=np.uint8))


def test_separator_mask():
    data = _as_buf(b"a b\tc\nd\re\x00f")
    mask = np.asarray(tok.separator_mask(data))
    expected = [False, True, False, True, False, True, False, True, False, True, False]
    assert mask.tolist() == expected


def test_token_count_matches_oracle(small_corpus):
    n = int(tok.token_count(_as_buf(small_corpus)))
    assert n == oracle.total_count(small_corpus)


def test_token_ends_positions_lengths():
    data = b"ab cde\nf"
    s = tok.tokenize(_as_buf(data))
    ends = np.flatnonzero(np.asarray(s.count))
    assert ends.tolist() == [1, 5, 7]
    pos = np.asarray(s.pos)[ends]
    length = np.asarray(s.length)[ends]
    assert pos.tolist() == [0, 3, 7]
    assert length.tolist() == [2, 3, 1]


def test_equal_tokens_equal_hashes():
    data = b"foo bar foo baz foo bar"
    s = tok.tokenize(_as_buf(data))
    ends = np.flatnonzero(np.asarray(s.count))
    hi = np.asarray(s.key_hi)[ends]
    lo = np.asarray(s.key_lo)[ends]
    words = oracle.split_words(data)
    seen = {}
    for w, h, l in zip(words, hi, lo):
        if w in seen:
            assert seen[w] == (h, l)
        else:
            seen[w] = (h, l)
    # distinct words -> distinct hashes
    assert len({v for v in seen.values()}) == len(seen)


def test_prefix_words_hash_differently():
    """The reference's prefix-compare defect (main.cu:57-67) must not recur."""
    data = b"Good Goodness Go Goo Good"
    s = tok.tokenize(_as_buf(data))
    ends = np.flatnonzero(np.asarray(s.count))
    keys = {(int(h), int(l)) for h, l in zip(np.asarray(s.key_hi)[ends], np.asarray(s.key_lo)[ends])}
    assert len(keys) == 4


@pytest.mark.slow
def test_hash_collision_rate(rng):
    """64-bit keys over a 50k-word vocabulary: no collisions expected."""
    vocab = [f"word{i}" for i in range(50_000)]
    data = (" ".join(vocab)).encode()
    s = tok.tokenize(_as_buf(data))
    ends = np.flatnonzero(np.asarray(s.count))
    pairs = set(zip(np.asarray(s.key_hi)[ends].tolist(), np.asarray(s.key_lo)[ends].tolist()))
    assert len(pairs) == len(vocab)


def test_non_token_positions_are_sentinel():
    s = tok.tokenize(_as_buf(b"a  b"))
    non_ends = np.asarray(s.count) == 0
    assert np.all(np.asarray(s.key_hi)[non_ends] == constants.SENTINEL_KEY)
    assert np.all(np.asarray(s.key_lo)[non_ends] == constants.SENTINEL_KEY)


def test_pad_bytes_do_not_create_tokens():
    raw = b"alpha beta"
    padded = tok.pad_to(raw, 128)
    n = int(tok.token_count(jnp.asarray(padded)))
    assert n == 2


def test_rejects_wrong_dtype():
    with pytest.raises(TypeError):
        tok.tokenize(jnp.zeros((8,), jnp.int32))


def test_base_offset_shifts_positions():
    s = tok.tokenize(_as_buf(b"ab cd"), base_offset=100)
    ends = np.flatnonzero(np.asarray(s.count))
    assert np.asarray(s.pos)[ends].tolist() == [100, 103]
