"""Distributed paths at D=64 virtual devices (VERDICT r4 missing #1).

The 8-device conftest mesh exercises correctness of the SPMD programs, but
SPMD *program bugs* — reshape/layout limits in ``all_to_all``, the keyrange
budget arithmetic ``b = 2C/D``, collective scheduling — characteristically
appear at larger D.  The driver's dryrun runs D=8; this test compiles and
runs the same full battery (tree/hierarchical/keyrange merges, keyrange-vs-
tree bit-identity, run_job_global staging, sketches, n-gram, grep, sample,
pallas rescue + top-k) at D=64 in a SUBPROCESS (the session's device count
is pinned at import time and cannot be raised in-process).

D=256 is available manually:
``MAPREDUCE_SCALE_DEVICES=256 python -m pytest tests/test_scale64.py``.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_at_64_devices():
    n = int(os.environ.get("MAPREDUCE_SCALE_DEVICES", "64"))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # A fresh process so the virtual-device flag lands before JAX init.
    proc = subprocess.run(
        [sys.executable, "-c",
         f"import sys; sys.path.insert(0, {REPO!r})\n"
         f"from __graft_entry__ import _force_cpu_mesh, dryrun_multichip\n"
         f"jax = _force_cpu_mesh({n})\n"
         f"assert len(jax.devices()) >= {n}, len(jax.devices())\n"
         f"dryrun_multichip({n})\n"
         f"print('scale-ok', {n})\n"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=3000)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert f"scale-ok {n}" in proc.stdout
