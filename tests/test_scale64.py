"""Distributed paths at D=64 virtual devices (VERDICT r4 missing #1).

The 8-device conftest mesh exercises correctness of the SPMD programs, but
SPMD *program bugs* — reshape/layout limits in ``all_to_all``, the keyrange
budget arithmetic ``b = 2C/D``, collective scheduling — characteristically
appear at larger D.  (Proven immediately: this test's first D=64 run caught
the keyrange-vs-tree ``dropped_uniques`` bound divergence under spill that
D=8 could never see.)  The driver's dryrun runs the FULL battery at D=8;
here the GEOMETRY-sensitive subset (tree/hierarchical/keyrange merges with
bit-identity checks, superstep scan, run_job_global staging) runs at D=64
in a SUBPROCESS (the session's device count is pinned at import time).

Manual wider runs: ``MAPREDUCE_SCALE_FULL=1`` adds every model family;
``MAPREDUCE_SCALE_DEVICES=256`` runs the pod-scale row.  Budget: the
geometry subset compiles in a few minutes on this one-core box; the full
battery at D=64 costs ~an hour of XLA compile and is not suite material.
"""

import os
import pytest
import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dryrun_multichip_at_64_devices(tmp_path):
    n = int(os.environ.get("MAPREDUCE_SCALE_DEVICES", "64"))
    # The geometry subset fits well inside 30 min; the documented manual
    # escape hatches (full battery / D=256) budget ~an hour of one-core
    # XLA compile and get a matching deadline.
    wide = os.environ.get("MAPREDUCE_SCALE_FULL", "0") == "1" or n > 64
    deadline_s = 7200 if wide else 1800
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "MAPREDUCE_COMPILE_CACHE": ""}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out_path = tmp_path / "scale.out"
    # File-backed output + its own session: no capture pipes to deadlock
    # on, and cleanup kills the whole process GROUP (a timed-out child's
    # own descendants included) — subprocess.run(capture_output=True) can
    # block forever in communicate() after an external kill.
    with open(out_path, "w") as out_f:
        proc = subprocess.Popen(
            [sys.executable, "-c",
             f"import sys; sys.path.insert(0, {REPO!r})\n"
             f"from __graft_entry__ import _force_cpu_mesh, dryrun_multichip\n"
             f"jax = _force_cpu_mesh({n})\n"
             f"assert len(jax.devices()) >= {n}, len(jax.devices())\n"
             f"dryrun_multichip({n})\n"
             f"print('scale-ok', {n})\n"],
            cwd=REPO, env=env, stdout=out_f, stderr=subprocess.STDOUT,
            start_new_session=True)
        try:
            rc = proc.wait(timeout=deadline_s)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait()
            rc = -9
    body = out_path.read_text()
    assert rc == 0, (f"(rc={rc}; -9 means the {deadline_s}s deadline "
                     f"expired)\n" + body[-4000:])
    assert f"scale-ok {n}" in body
