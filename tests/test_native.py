"""Native chunker vs. the pure-Python reader path: byte-identical batches."""

import numpy as np
import pytest

from mapreduce_tpu import native
from mapreduce_tpu.data import reader
from mapreduce_tpu.utils import oracle
from tests.conftest import make_corpus


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    if lib is None:
        pytest.skip("native chunker unavailable (no g++?)")
    return lib


def _write(tmp_path, data: bytes):
    p = tmp_path / "c.txt"
    p.write_bytes(data)
    return str(p)


@pytest.mark.parametrize("n_words,chunk,shards", [
    (500, 256, 4), (3000, 512, 8), (100, 4096, 2), (1, 128, 4),
])
def test_batch_parity(tmp_path, rng, lib, n_words, chunk, shards):
    corpus = make_corpus(rng, n_words, vocab=80)
    path = _write(tmp_path, corpus)
    nat = list(reader.iter_batches(path, shards, chunk, use_native=True))
    py = list(reader.iter_batches(path, shards, chunk, use_native=False))
    assert len(nat) == len(py)
    for a, b in zip(nat, py):
        np.testing.assert_array_equal(a.data, b.data)
        np.testing.assert_array_equal(a.base_offsets, b.base_offsets)
        np.testing.assert_array_equal(a.lengths, b.lengths)
        assert a.step == b.step


def test_batch_parity_force_split(tmp_path, lib):
    """A separator-free run longer than max_token_bytes force-splits
    identically in both implementations."""
    data = b"a" * 1000 + b" end\n"
    path = _write(tmp_path, data)
    nat = list(reader.iter_batches(path, 2, 256, max_token_bytes=64, use_native=True))
    py = list(reader.iter_batches(path, 2, 256, max_token_bytes=64, use_native=False))
    for a, b in zip(nat, py):
        np.testing.assert_array_equal(a.data, b.data)
        np.testing.assert_array_equal(a.lengths, b.lengths)


def test_token_count(lib, rng):
    corpus = make_corpus(rng, 2000, vocab=100)
    buf = np.frombuffer(corpus, dtype=np.uint8)
    assert native.token_count(buf) == oracle.total_count(corpus)


def test_token_count_edges(lib):
    assert native.token_count(np.frombuffer(b"", np.uint8)) == 0
    assert native.token_count(np.frombuffer(b"   ", np.uint8)) == 0
    assert native.token_count(np.frombuffer(b"x", np.uint8)) == 1
    assert native.token_count(np.frombuffer(b" x y", np.uint8)) == 2
