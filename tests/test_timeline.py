"""Pipeline timeline tests (ISSUE 7): per-group `group` lifecycle records
out of the executor, the jax-free timeline reconstruction (lanes, overlap
matrix, device-idle attribution, critical-path verdict), Chrome
trace-event export, ledger forward compatibility, and the <1 ms per-group
overhead bound."""

import json
import os
import subprocess
import sys
import time

import pytest

from mapreduce_tpu import obs
from mapreduce_tpu.config import Config
from mapreduce_tpu.models.wordcount import WordCountJob
from mapreduce_tpu.obs import timeline
from mapreduce_tpu.parallel.mesh import data_mesh
from mapreduce_tpu.runtime import executor

from conftest import make_corpus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tools", "fixtures")

sys.path.insert(0, os.path.join(REPO, "tools"))
try:
    import trace_export
finally:
    sys.path.pop(0)


def _streamed_ledger(tmp_path, inflight: int, n_words=2500):
    """One telemetered streamed CPU run -> (ledger records, corpus bytes).
    Module-scoped below: streamed runs are the expensive part of this
    module, so every test reads the same two ledgers (tier-1 budget)."""
    import numpy as np

    corpus = make_corpus(np.random.default_rng(20260729 + inflight),
                         n_words, 120)
    path = tmp_path / f"data_w{inflight}.txt"
    path.write_bytes(corpus)
    cfg = Config(chunk_bytes=512, table_capacity=2048,
                 inflight_groups=inflight)
    led = str(tmp_path / f"run_w{inflight}.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        executor.run_job(WordCountJob(cfg), str(path), cfg,
                         mesh=data_mesh(4), telemetry=tel)
    return list(obs.read_ledger(led)), len(corpus)


@pytest.fixture(scope="module")
def piped_ledger(tmp_path_factory):
    """Records of one pipelined (inflight=3) telemetered streamed run."""
    return _streamed_ledger(tmp_path_factory.mktemp("tl_piped"), inflight=3,
                            n_words=4000)


@pytest.fixture(scope="module")
def serial_ledger(tmp_path_factory):
    """Records of the serialized A/B control (inflight=1) run."""
    return _streamed_ledger(tmp_path_factory.mktemp("tl_serial"),
                            inflight=1)


# -- executor emission ------------------------------------------------------

@pytest.mark.smoke
def test_one_group_record_per_retired_group(piped_ledger):
    """ISSUE 7 acceptance: exactly one `group` record per retired group
    (= one per step record: every dispatched group retired), each with
    monotonically ordered lifecycle timestamps and sizes that agree with
    its step record."""
    recs, corpus_bytes = piped_ledger
    steps = [r for r in recs if r["kind"] == "step"]
    groups = [r for r in recs if r["kind"] == "group"]
    assert len(groups) == len(steps) > 1
    # Same identity + size as the step records (written at dispatch; the
    # group records are written at retirement — joinable by step_first).
    by_first = {r["step_first"]: r for r in steps}
    for g in groups:
        s = by_first[g["step_first"]]
        assert g["step_last"] == s["step_last"]
        assert g["steps"] == s["steps"]
        assert g["group_bytes"] == s["group_bytes"]
        assert (g["read_at"] <= g["staged_at"] <= g["dispatched_at"]
                <= g["token_ready_at"] <= g["retired_at"]), g
        assert g["retire_wait_s"] >= 0
    assert sum(g["group_bytes"] for g in groups) == corpus_bytes
    # run_start carries the stream schema version (forward-compat anchor).
    start = next(r for r in recs if r["kind"] == "run_start")
    assert start["ledger_version"] == obs.LEDGER_VERSION == 10


def test_serial_window_is_gap_free_control(serial_ledger):
    """inflight_groups=1 (the A/B control) degenerates to a serial
    timeline: device intervals never overlap (no merged concurrency),
    staging never runs under device compute, and every device-idle gap is
    attributed to measured host work — the timeline of a run with no
    pipeline to measure."""
    recs, _ = serial_ledger
    art = timeline.reconstruct(recs)
    assert art is not None and art["groups"] > 2
    groups = [r for r in recs if r["kind"] == "group"]
    # Serial contract: group N+1's staging starts only after N retired.
    for a, b in zip(groups, groups[1:]):
        assert b["staged_at"] >= a["retired_at"], (a, b)
    # So the staging lane can never run concurrently with the device lane.
    assert art["overlap_s"].get("staging+device", 0.0) == 0.0
    # Device busy == sum of per-group device intervals (nothing merged:
    # no two groups were ever in flight together).
    per_group = sum(g["token_ready_at"] - g["dispatched_at"]
                    for g in groups)
    assert art["lane_busy_s"]["device"] == pytest.approx(per_group,
                                                         abs=1e-4)
    # Every gap the device sat idle is attributed to a host lane (reader/
    # staging/retire) — "idle" (nothing measured) would mean the timeline
    # lost track of the serial loop's own work.
    for gap in art["device_idle"]["gaps"]:
        assert gap["blocking"] in ("reader", "staging", "retire"), gap


def test_pipelined_window_overlaps_lanes(piped_ledger):
    """inflight_groups>1: the reader lane measurably overlaps the device
    lane (prefetch + the window run ahead) — the measured counterpart of
    overlap_fraction the scalar stats could only assert indirectly."""
    recs, _ = piped_ledger
    art = timeline.reconstruct(recs)
    assert art is not None
    assert art["overlap_s"].get("reader+device", 0.0) > 0.0
    assert art["bottleneck"]["resource"] in timeline.LANES
    assert art["bottleneck"]["projected_saving_s"] <= art["span_s"]


def test_group_record_overhead_under_1ms(tmp_path):
    """ISSUE 7 acceptance: per-group recording is host-side timestamping
    only — the full emission path (_group_life stamps + registry + ledger
    JSONL append) must average far under 1 ms per group."""
    import numpy as np

    class _B:  # the two attributes _group_life reads off a Batch
        def __init__(self, step):
            self.step = step
            self.lengths = np.array([1024, 1024], np.int64)

    led = str(tmp_path / "overhead.jsonl")
    n = 300
    with obs.Telemetry.create(ledger_path=led) as tel:
        t0 = time.perf_counter()
        for i in range(n):
            life = executor._group_life([_B(i)], time.perf_counter(),
                                        int(_B(i).lengths.sum()))
            life["dispatched_at"] = life["staged_at"]
            executor._group_record(tel, True, life,
                                   token_ready_at=life["staged_at"] + 0.01,
                                   retired_at=life["staged_at"] + 0.011,
                                   wait_s=0.005)
        dt = time.perf_counter() - t0
    assert dt / n < 1e-3, f"{1e3 * dt / n:.3f} ms per group record"
    assert len(list(obs.read_ledger(led, kind="group"))) == n


# -- reconstruction on crafted records --------------------------------------

def _crafted_records():
    """The documented worked example: 4 groups, window depth 2,
    reader-bound with two 0.2 s device-idle gaps (mirrors fixture04)."""
    mk = lambda sf, sl, r, s, d, t, e, **kw: {
        "run_id": "craft", "kind": "group", "step_first": sf,
        "step_last": sl, "steps": sl - sf + 1, "group_bytes": 100,
        "read_at": r, "staged_at": s, "dispatched_at": d,
        "token_ready_at": t, "retired_at": e, "retire_wait_s": 0.1, **kw}
    return [
        {"run_id": "craft", "kind": "run_start", "ledger_version": 2},
        mk(0, 1, 10.0, 10.1, 10.2, 10.6, 10.62),
        mk(2, 3, 10.1, 10.3, 10.4, 11.0, 11.02),
        mk(4, 5, 10.4, 11.1, 11.2, 11.6, 11.62),
        mk(6, 7, 11.1, 11.72, 11.8, 12.0, 12.02, h2d_done_at=11.9),
        {"run_id": "craft", "kind": "run_end", "bytes": 400},
    ]


def test_crafted_overlap_matrix_and_verdict():
    """The overlap matrix, idle attribution and critical-path verdict of a
    hand-built overlapped window, checked against the arithmetic done on
    paper (docs/observability.md's worked example)."""
    art = timeline.reconstruct(_crafted_records())
    assert art["groups"] == 4
    assert round(art["span_s"], 4) == 2.02
    # Lane busy seconds.
    assert round(art["lane_busy_s"]["reader"], 4) == 1.62
    assert round(art["lane_busy_s"]["staging"], 4) == 0.38
    assert round(art["lane_busy_s"]["device"], 4) == 1.4
    assert round(art["lane_busy_s"]["retire"], 4) == 0.08
    assert round(art["lane_busy_s"]["h2d"], 4) == 0.18
    # The measured overlap matrix.
    ov = {k: round(v, 4) for k, v in art["overlap_s"].items()}
    assert ov["reader+device"] == 1.1
    assert ov["reader+staging"] == 0.2
    assert ov["staging+device"] == 0.1
    assert ov["h2d+device"] == 0.1
    assert ov["staging+h2d"] == 0.08
    assert ov["reader+retire"] == 0.06
    assert ov["device+retire"] == 0.02
    # Device idle: two 0.2 s gaps, both opened blocked on the reader.
    idle = art["device_idle"]
    assert round(idle["total_s"], 4) == 0.4
    assert [g["blocking"] for g in idle["gaps"]] == ["reader", "reader"]
    assert [round(g["s"], 4) for g in idle["gaps"]] == [0.2, 0.2]
    assert round(idle["blocked_on"]["reader"], 4) == 0.4
    # Critical path: 0.28 s of the span is reader-exclusive — more than
    # any other lane — so the reader is the bounding resource and an
    # infinitely fast reader is worth exactly those seconds.
    excl = {k: round(v, 4) for k, v in art["exclusive_s"].items()}
    assert excl == {"reader": 0.28, "staging": 0.0, "h2d": 0.0,
                    "device": 0.1, "retire": 0.02}
    bn = art["bottleneck"]
    assert bn["resource"] == "reader"
    assert round(bn["projected_saving_s"], 4) == 0.28
    assert round(bn["projected_span_s"], 4) == 1.74
    assert round(bn["device_idle_s"], 4) == 0.4


def test_reconstruct_requires_group_records():
    """Pre-ISSUE-7 ledgers (steps only) degrade to None, not an error."""
    recs = [{"run_id": "old", "kind": "run_start"},
            {"run_id": "old", "kind": "step", "step_first": 0},
            {"run_id": "old", "kind": "run_end"}]
    assert timeline.reconstruct(recs) is None
    assert timeline.to_chrome_trace(recs) is None


def test_reconstruct_picks_one_run():
    """Mixed-run ledgers reconstruct the requested run only (default: the
    first run carrying group records)."""
    recs = _crafted_records() + [
        dict(g, run_id="other") for g in _crafted_records()[1:5]]
    art = timeline.reconstruct(recs)
    assert art["run_id"] == "craft" and art["groups"] == 4
    art2 = timeline.reconstruct(recs, run_id="other")
    assert art2["run_id"] == "other" and art2["groups"] == 4


# -- forward compatibility ---------------------------------------------------

def test_future_ledger_skips_unknown_kinds_and_fields():
    """ISSUE 7 satellite: a future-versioned ledger (unknown kinds,
    unknown fields, a future group-record shape missing today's core
    fields) flows through read_ledger, the timeline reconstructor and the
    trace exporter without error, surfacing what IS understood."""
    path = os.path.join(FIXTURES, "future_ledger.jsonl")
    recs = list(obs.read_ledger(path))
    assert any(r["kind"] == "warp_stats" for r in recs)  # passed through
    start = next(r for r in recs if r["kind"] == "run_start")
    assert start["ledger_version"] == 99
    art = timeline.reconstruct(recs)
    # The well-formed group record reconstructs; the future-shaped one
    # (teleported_at only) is skipped, not fatal.
    assert art is not None and art["groups"] == 1
    trace = timeline.to_chrome_trace(recs)
    assert trace is not None and not trace_export.validate_trace(trace)
    # The future `data` record (ISSUE 8: extra unknown fields) passes
    # through read_ledger untouched and classifies — unknown fields
    # ignored, known signals surfaced.
    from mapreduce_tpu.obs import datahealth

    data = next(r for r in recs if r["kind"] == "data")
    assert data["qubit_decoherence"] == 0.4  # unknown field preserved
    health = datahealth.classify(data)
    assert health["verdict"] == "skew-hot"  # 48/64 top mass
    assert health["signals"]["top_mass"] == 0.75


def test_chrome_trace_carries_group_data_annotations():
    """ISSUE 8: group records with `data` dicts export slice args + an
    instant data marker (spill fallback / rescue escalation) on the
    device lane; groups without data export exactly as before."""
    recs = _crafted_records()
    recs[1]["data"] = {"chunks": 2, "fallback_chunks": 1, "spill_rows": 40,
                       "occupancy": 0.3}
    recs[2]["data"] = {"chunks": 2, "occupancy": 0.35}
    trace = timeline.to_chrome_trace(recs)
    assert trace_export.validate_trace(trace) == []
    evs = trace["traceEvents"]
    marks = [e for e in evs if e["ph"] == "i" and e.get("cat") == "data"]
    assert len(marks) == 1 and "1 spill fallback" in marks[0]["name"]
    assert marks[0]["args"]["spill_rows"] == 40
    with_data = [e for e in evs if e["ph"] == "X"
                 and "data" in e.get("args", {})]
    # Group 0 has 4 lifecycle slices (reader/staging/device/retire), group
    # 2 likewise — both carry the data dict on every slice.
    assert {e["args"]["data"]["occupancy"] for e in with_data} == {0.3, 0.35}


# -- trace export -------------------------------------------------------------

def test_chrome_trace_schema_and_structure():
    """The exported trace is schema-valid and structured one-pid-per-lane,
    one-tid-per-group, with paired flow events."""
    trace = timeline.to_chrome_trace(_crafted_records())
    assert trace_export.validate_trace(trace) == []
    evs = trace["traceEvents"]
    pnames = {e["pid"]: e["args"]["name"] for e in evs
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert sorted(pnames.values()) == sorted(timeline.LANES)
    slices = [e for e in evs if e["ph"] == "X"]
    assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in slices)
    dev_pid = next(p for p, n in pnames.items() if n == "device")
    assert {e["tid"] for e in slices if e["pid"] == dev_pid} == {0, 2, 4, 6}
    starts = {e["id"] for e in evs if e["ph"] == "s"}
    ends = {e["id"] for e in evs if e["ph"] == "f"}
    assert starts == ends == {0, 2, 4, 6}
    assert trace["otherData"]["bottleneck"]["resource"] == "reader"
    # Round-trips through JSON byte-identically.
    assert json.loads(json.dumps(trace)) == trace


def test_validate_trace_catches_breakage():
    trace = timeline.to_chrome_trace(_crafted_records())
    bad = json.loads(json.dumps(trace))
    for ev in bad["traceEvents"]:
        if ev["ph"] == "X":
            del ev["dur"]
            break
    assert trace_export.validate_trace(bad)
    assert trace_export.validate_trace({"traceEvents": "nope"})


@pytest.mark.smoke
def test_trace_export_cli_runs_without_jax(tmp_path):
    """The CLI path is jax-free (the box reading forensics need not be the
    box that produced them): a poisoned `jax` package on PYTHONPATH would
    fail the run if anything imported it."""
    poison = tmp_path / "poison" / "jax"
    poison.mkdir(parents=True)
    (poison / "__init__.py").write_text(
        "raise ImportError('trace_export must stay jax-free')")
    env = {**os.environ, "PYTHONPATH": str(tmp_path / "poison")}
    out = str(tmp_path / "trace.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_export.py"),
         os.path.join(FIXTURES, "mini_ledger.jsonl"), "--out", out],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "bottleneck reader" in proc.stdout
    with open(out) as f:
        trace = json.load(f)
    assert trace_export.validate_trace(trace) == []
    # --selftest under the same poison: the fixture gate itself is jax-free.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_export.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_trace_export_cli_declines_groupless_ledger(tmp_path):
    led = tmp_path / "old.jsonl"
    led.write_text('{"run_id": "x", "kind": "run_start"}\n'
                   '{"run_id": "x", "kind": "step", "step_first": 0}\n')
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_export.py"),
         str(led)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "no group records" in proc.stderr


# -- executor end-to-end: trace from a real run -------------------------------

def test_real_run_exports_valid_trace(piped_ledger):
    """Ledger from a real pipelined CPU run -> schema-valid Chrome trace
    whose verdict names a real lane — the full ISSUE 7 path end to end."""
    recs, _ = piped_ledger
    trace = timeline.to_chrome_trace(recs)
    assert trace is not None
    assert trace_export.validate_trace(trace) == []
    assert trace["otherData"]["bottleneck"]["resource"] in timeline.LANES
    n_groups = sum(1 for r in recs if r["kind"] == "group")
    assert trace["otherData"]["groups"] == n_groups
