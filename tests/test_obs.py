"""Unit tests for the obs/ telemetry subsystem (ISSUE 2): metrics
registry, run ledger, flight recorder, spans, the Telemetry facade, and
the obs_report tool's selftest path."""

import json
import os
import subprocess
import sys

import pytest

from mapreduce_tpu import obs
from mapreduce_tpu.obs.registry import MetricsRegistry
from mapreduce_tpu.runtime import metrics as metrics_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- registry ---------------------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(2.5)
    reg.gauge("g").set(7)
    reg.observe("h", 0.004)
    reg.observe("h", 30.0)
    reg.observe("h", 500.0)  # past the last bound -> +Inf bucket
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 3.5
    assert snap["gauges"]["g"] == 7.0
    h = snap["histograms"]["h"]
    assert h["count"] == 3 and h["min"] == 0.004 and h["max"] == 500.0
    assert h["buckets"]["+Inf"] == 1


def test_registry_labels_key_separately():
    reg = MetricsRegistry()
    reg.counter("builds", strategy="tree").inc()
    reg.counter("builds", strategy="keyrange").inc(2)
    snap = reg.snapshot()["counters"]
    assert snap["builds{strategy=tree}"] == 1
    assert snap["builds{strategy=keyrange}"] == 2


def test_registry_kind_conflict_raises():
    reg = MetricsRegistry()
    reg.counter("x").inc()
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x")


def test_registry_negative_counter_rejected():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match=">= 0"):
        reg.counter("c").inc(-1)


def test_registry_int_counters_snapshot_as_int():
    reg = MetricsRegistry()
    reg.counter("n").inc(3)
    assert reg.snapshot()["counters"]["n"] == 3
    assert isinstance(reg.snapshot()["counters"]["n"], int)


# -- ledger -----------------------------------------------------------------

def test_ledger_roundtrip_and_corrupt_line_skipped(tmp_path):
    p = str(tmp_path / "run.jsonl")
    with obs.RunLedger(p, run_id="r1") as led:
        led.write("run_start", devices=4)
        led.write("step", step_first=0, group_bytes=100)
    with open(p, "a") as f:
        f.write('{"truncated": \n')  # crash mid-write forensics
    with obs.RunLedger(p, run_id="r1") as led:  # append mode: resumes
        led.write("run_end", bytes=100)
    recs = list(obs.read_ledger(p))
    assert [r["kind"] for r in recs] == ["run_start", "step", "run_end"]
    assert all(r["run_id"] == "r1" for r in recs)
    steps = list(obs.read_ledger(p, kind="step"))
    assert len(steps) == 1 and steps[0]["group_bytes"] == 100


def test_ledger_run_start_carries_version(tmp_path):
    """Forward compat (ISSUE 7 satellite): every writer stamps the stream
    schema version on run_start — and only there — without call sites
    having to remember it."""
    p = str(tmp_path / "run.jsonl")
    with obs.RunLedger(p, run_id="r1") as led:
        led.write("run_start", devices=1)
        led.write("step", step_first=0)
    recs = list(obs.read_ledger(p))
    assert recs[0]["ledger_version"] == obs.LEDGER_VERSION == 10
    assert "ledger_version" not in recs[1]


def test_ledger_coerces_numpy_fields(tmp_path):
    """A ledger write must never take down the run: numpy scalars AND
    arrays coerce to JSON instead of raising out of json.dumps."""
    import numpy as np

    p = str(tmp_path / "run.jsonl")
    with obs.RunLedger(p, run_id="r1") as led:
        led.write("step", count=np.int64(7),
                  per_device=np.array([1, 2, 3], np.int64),
                  weird=object())
    rec = next(obs.read_ledger(p))
    assert rec["count"] == 7 and rec["per_device"] == [1, 2, 3]
    assert isinstance(rec["weird"], str)  # repr fallback


# -- flight recorder --------------------------------------------------------

def test_flight_ring_bounded_and_dump(tmp_path):
    fr = obs.FlightRecorder(capacity=4)
    for i in range(10):
        fr.record("step", step_first=i)
    assert fr.events_recorded == 10
    evs = fr.events()
    assert len(evs) == 4 and evs[0]["step_first"] == 6  # oldest evicted
    p = str(tmp_path / "crash.json")
    out = fr.dump(p, context={"error": "boom"})
    assert out == p and os.path.exists(p)
    with open(p) as f:
        dump = json.load(f)
    assert dump["context"]["error"] == "boom"
    assert dump["events_recorded"] == 10 and dump["events_kept"] == 4
    # Idempotent: a second failure in the same run must not overwrite the
    # first (most specific) dump.
    fr.record("unwind", step_first=99)
    assert fr.dump(str(tmp_path / "other.json")) == p
    assert not os.path.exists(str(tmp_path / "other.json"))


def test_flight_dump_write_failure_returns_none(tmp_path):
    """A failed dump must not claim a path that does not exist (the ledger
    failure record embeds the return value), and must not consume the
    one-dump-per-run slot."""
    fr = obs.FlightRecorder()
    fr.record("step", step_first=0)
    bad = str(tmp_path / "nodir")
    open(bad, "w").close()  # a FILE where a directory is needed
    assert fr.dump(os.path.join(bad, "crash.json")) is None
    assert fr.dumped_to is None
    good = str(tmp_path / "crash.json")
    assert fr.dump(good) == good  # a later good path still gets the dump


def test_flight_summarize_state_bounds_leaves():
    import numpy as np

    state = {"a": np.zeros((4, 8), np.uint32), "b": np.zeros(3, np.int64)}
    s = obs.summarize_state(state)
    assert s["n_leaves"] == 2
    assert s["total_nbytes"] == 4 * 8 * 4 + 3 * 8
    assert {"shape": [4, 8], "dtype": "uint32", "nbytes": 128} in s["leaves"]


# -- spans ------------------------------------------------------------------

def test_span_accumulates_timer_and_registry():
    reg = MetricsRegistry()
    timer = metrics_mod.PhaseTimer()
    with obs.span("work", timer, registry=reg):
        pass
    with obs.span("work", timer):
        pass
    assert timer["work"] > 0
    assert reg.snapshot()["histograms"]["span.work"]["count"] == 1


def test_span_records_on_exception():
    timer = metrics_mod.PhaseTimer()
    with pytest.raises(RuntimeError):
        with obs.span("fails", timer):
            raise RuntimeError("boom")
    assert timer["fails"] > 0


# -- telemetry facade -------------------------------------------------------

def test_telemetry_disabled_is_noop(tmp_path):
    tel = obs.Telemetry.disabled()
    assert not tel.enabled
    timer = metrics_mod.PhaseTimer()
    timer.start("dispatch")
    timer.stop("dispatch")
    # None of these may write or raise.
    tel.step_record(step_first=0, step_last=0, group_bytes=1, cursor_bytes=1,
                    timer=timer)
    tel.event("step", step_first=0)
    tel.ledger_write("run_start")
    assert tel.flight_dump(context={"x": 1}) is None


def test_telemetry_step_record_phase_deltas(tmp_path):
    p = str(tmp_path / "run.jsonl")
    timer = metrics_mod.PhaseTimer()
    reg = MetricsRegistry()
    with obs.Telemetry.create(ledger_path=p, registry=reg) as tel:
        timer.phases = {"dispatch": 1.0, "read_wait": 0.5}
        tel.step_record(step_first=0, step_last=0, group_bytes=10,
                        cursor_bytes=10, timer=timer)
        timer.phases = {"dispatch": 1.25, "read_wait": 0.5}
        tel.step_record(step_first=1, step_last=1, group_bytes=10,
                        cursor_bytes=20, timer=timer)
    recs = list(obs.read_ledger(p, kind="step"))
    assert recs[0]["phases"] == {"dispatch": 1.0, "read_wait": 0.5}
    # Second record carries DELTAS, and the unchanged phase is dropped.
    assert recs[1]["phases"] == {"dispatch": 0.25}
    assert recs[1]["elapsed_s"] > 0
    assert reg.snapshot()["counters"]["executor.steps"] == 2
    # Flight path defaults next to the ledger.
    assert tel.flight_path == p + ".flight.json"


def test_telemetry_nonwriter_advances_baseline(tmp_path):
    """A non-coordinator process (write=False) must still advance the phase
    baseline, or a later writing record would report a cumulative blob."""
    p = str(tmp_path / "run.jsonl")
    timer = metrics_mod.PhaseTimer()
    with obs.Telemetry.create(ledger_path=p,
                              registry=MetricsRegistry()) as tel:
        timer.phases = {"dispatch": 1.0}
        tel.step_record(step_first=0, step_last=0, group_bytes=1,
                        cursor_bytes=1, timer=timer, write=False)
        timer.phases = {"dispatch": 1.2}
        tel.step_record(step_first=1, step_last=1, group_bytes=1,
                        cursor_bytes=2, timer=timer, write=True)
    recs = list(obs.read_ledger(p, kind="step"))
    assert len(recs) == 1 and recs[0]["phases"] == {"dispatch": 0.2}


def test_device_memory_stats_host_side():
    stats = obs.device_memory_stats()
    # CPU backend: memory_stats() is unavailable, live-array aggregate is
    # the fallback signal — present and non-negative.
    assert stats.get("live_arrays", 0) >= 0
    assert stats.get("live_bytes", 0) >= 0


# -- obs_report -------------------------------------------------------------

def test_obs_report_selftest_fixture():
    """The committed reporting path runs (jax-free) against the checked-in
    miniature ledger + flight fixtures — ISSUE 2 satellite."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "selftest ok" in proc.stdout


def test_obs_report_analyzes_generated_ledger(tmp_path):
    """analyze() agrees with a ledger produced by the real writer."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    p = str(tmp_path / "run.jsonl")
    with obs.RunLedger(p, run_id="t1") as led:
        led.write("run_start", driver="run_job", job="wordcount", devices=2,
                  chunk_bytes=512, superstep=1, backend="xla",
                  merge_strategy="tree", input=["x"], retry=0)
        led.write("step", step_first=0, step_last=0, steps=1,
                  group_bytes=512, cursor_bytes=512,
                  phases={"read_wait": 0.3, "stage": 0.01, "dispatch": 0.1},
                  mem={"live_bytes": 1000, "live_arrays": 3})
        led.write("run_end", bytes=512, words=80, elapsed_s=0.5,
                  phases={"read_wait": 0.3, "stage": 0.01, "dispatch": 0.1})
    runs = obs_report.analyze(p)
    assert len(runs) == 1
    a = runs[0]
    assert a["completed"] and a["steps"] == 1 and a["bytes"] == 512
    assert a["classification"] == "read-bound"
    assert a["spikes"] == [] and a["mem_growth"] is None
