"""Hostile-input fixtures beyond clean ASCII (VERDICT r4 next #9).

The bench generators are clean ASCII; real corpora (enwik dumps, WET crawl
text) carry UTF-8 multibyte words, NUL bytes, markup, and very long
separator-free runs.  Each fixture here pins either exact backend agreement
(pallas vs the XLA oracle vs the host oracle) or the documented accounting
envelope where the semantics intentionally bound work (force-split, rescue
window).
"""


import pytest

from bench import make_markup_corpus
from mapreduce_tpu.config import Config
from mapreduce_tpu.models import wordcount
from mapreduce_tpu.utils import oracle

XLA = Config(chunk_bytes=1 << 13, table_capacity=1 << 12, backend="xla")
PALLAS = Config(chunk_bytes=1 << 14, table_capacity=1 << 12,
                backend="pallas")  # stable2 default: the production shape


def _agree(data: bytes, pallas_cfg: Config = PALLAS):
    rp = wordcount.count_words(data, pallas_cfg)
    rx = wordcount.count_words(data, XLA)
    want = oracle.word_counts(data)
    assert rx.as_dict() == want
    assert rp.as_dict() == want
    assert rp.words == rx.words  # first-occurrence order identical
    assert rp.total == rx.total
    return rp


@pytest.mark.slow
def test_utf8_multibyte_words():
    """Continuation bytes (>= 0x80) are never separators: multibyte words
    stay whole, stay distinct from their prefixes, and report byte-exact."""
    text = ("café naïve über résumé Αθήνα λόγος 東京 中文 "
            "caf café 日本語テスト emoji\U0001F600mix "
            "café").encode("utf-8")
    r = _agree(text)
    d = r.as_dict()
    assert d["café".encode()] == 2
    assert d["caf".encode()] == 1  # prefix is its own word
    # NFC vs NFD stay distinct (byte semantics, not unicode-normalized).
    assert "café".encode() in d


def test_utf8_words_crossing_chunk_seams(tmp_path):
    """Streamed runs must never split a multibyte word at a chunk seam
    (the reader cuts at separators only)."""
    from mapreduce_tpu.runtime.executor import count_file

    words = ["Αθήνα", "東京都庁", "naïveté", "plain"] * 400
    text = " ".join(words).encode("utf-8")
    p = tmp_path / "u.txt"
    p.write_bytes(text)
    cfg = Config(chunk_bytes=1 << 10, table_capacity=1 << 12, backend="xla")
    r = count_file([str(p)], config=cfg)
    assert r.as_dict() == oracle.word_counts(text)


@pytest.mark.slow
def test_nul_bearing_input():
    """NUL is a separator (the reference's memset-padding made it one
    implicitly, main.cu:178): embedded NULs split tokens exactly and
    tokens around them report byte-exact.

    @slow (the ">= ~10 s carries @slow" rebalance, ISSUE 8 round: 32 s —
    two fresh unique-shape compiles for a 5-token input): NUL-as-
    separator stays fast-tier via test_fuzz's separator-pathology sweep
    (NUL is in its separator set) and the pallas fixture tests; this
    byte-exact micro case runs in the full suite."""
    data = b"alpha\x00beta \x00\x00 gamma\x00\x00delta alpha"
    r = _agree(data)
    assert r.as_dict() == {b"alpha": 2, b"beta": 1, b"gamma": 1, b"delta": 1}


@pytest.mark.slow
def test_long_separator_free_run_force_split(tmp_path):
    """A separator-free run far beyond chunk_bytes: the reader force-splits
    (it must make progress), producing deterministic artificial token
    boundaries at the cut points — streamed totals stay exact and
    deterministic, and every reported word is a true substring count."""
    from mapreduce_tpu.runtime.executor import count_file

    run = b"Z" * 50_000  # no separator anywhere
    text = b"head " + run + b" tail"
    p = tmp_path / "r.txt"
    p.write_bytes(text)
    cfg = Config(chunk_bytes=1 << 12, table_capacity=1 << 12, backend="xla")
    r1 = count_file([str(p)], config=cfg)
    r2 = count_file([str(p)], config=cfg)
    assert r1.as_dict() == r2.as_dict()  # deterministic
    assert r1.as_dict()[b"head"] == 1 and r1.as_dict()[b"tail"] == 1
    # The run's bytes are all accounted: fragments sum to the run length.
    frag_bytes = sum(len(w) * c for w, c in r1.as_dict().items()
                     if w.startswith(b"Z"))
    assert frag_bytes == len(run)


@pytest.mark.slow
def test_markup_corpus_backends_agree():
    """The enwik-like markup generator (UTF-8, tags, entities, wiki links,
    URLs, long attribute blobs): pallas with DEFAULT flags (stable2 +
    tiered rescue) must match the XLA oracle exactly — every >W token
    rescued (the generator's longest run is 400+8 bytes < the 512-byte
    window used here)."""
    data = make_markup_corpus(120_000)
    cfg = Config(chunk_bytes=1 << 15, table_capacity=1 << 13,
                 backend="pallas", rescue_window=512)
    rp = wordcount.count_words(data, cfg)
    rx = wordcount.count_words(data, Config(chunk_bytes=1 << 15,
                                            table_capacity=1 << 13,
                                            backend="xla"))
    assert rp.as_dict() == rx.as_dict()
    assert rp.words == rx.words
    assert rp.dropped_count == 0
    assert rx.as_dict() == oracle.word_counts(data)
    # The fixture really is hostile: multibyte + overlong tokens present.
    assert any(max(w) >= 0x80 for w in rp.words)
    assert any(len(w) > 32 for w in rp.words)


@pytest.mark.slow
def test_markup_corpus_streamed_matches_buffered(tmp_path):
    from mapreduce_tpu.runtime.executor import count_file

    data = make_markup_corpus(80_000)
    p = tmp_path / "m.txt"
    p.write_bytes(data)
    cfg = Config(chunk_bytes=1 << 14, table_capacity=1 << 13, backend="xla")
    rs = count_file([str(p)], config=cfg)
    rb = wordcount.count_words(data, cfg)
    assert rs.as_dict() == rb.as_dict()
    assert rs.words == rb.words
