"""Kernel-geometry search tests (ISSUE 12): the jax-free enumerator/
certifier/pricing lattice against hand arithmetic, the Config.geometry
surface, bit-identity of results across certified geometries, the
tuner's geometry knob (try/revert + oscillation guard), and the
graphcheck certification of shortlisted candidates."""

import json
import os
import sys

import pytest

from mapreduce_tpu.analysis import geometry as geom_mod
from mapreduce_tpu.config import (DEFAULT_GEOMETRY, GEOMETRY_PRESETS,
                                  Config, Geometry)
from mapreduce_tpu.ops.pallas import meta
from mapreduce_tpu.tuning import engine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tools", "fixtures")


def _fixture(name: str) -> list:
    with open(os.path.join(FIXTURES, name + ".jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- the constructor is the single source of truth ---------------------------

@pytest.mark.smoke
def test_default_geometry_reproduces_shipped_plans():
    """Acceptance: the shipped default geometries are reproduced EXACTLY
    by the constructor — bit-identical vmem_plan footprints (the values
    are the pre-refactor hand-maintained production_plans list's)."""
    expected = [(508416, 12, 67108864), (352768, 12, 67108864),
                (475648, 8, None), (729600, 12, 67108864),
                (860672, 12, 67108864), (631296, 8, None),
                (3932160, 36, None), (3932160, 132, None)]
    plans = meta.production_plans()
    got = [(p.vmem_bytes, p.smem_bytes, p.vmem_limit_bytes) for p in plans]
    assert got == expected, got
    # production_plans IS geometry_plans(DEFAULT_GEOMETRY): one constructor.
    assert [p.as_dict() for p in plans] == \
        [p.as_dict() for p in meta.geometry_plans(DEFAULT_GEOMETRY)]
    # The kernel wrappers delegate to the same constructor.
    from mapreduce_tpu.ops.pallas import radix, tokenize

    assert tokenize.vmem_plan(block_rows=384, compact_slots=128,
                              lane_major=True).as_dict() == \
        plans[0].as_dict()
    assert radix.vmem_plan().as_dict() == plans[6].as_dict()


def test_enumerator_candidates_all_pass_vmem_budget():
    """Acceptance: every EMITTED candidate passes the static certifier by
    construction (over-budget lattice points are dropped, not flagged)."""
    cands = geom_mod.enumerate_candidates()
    assert len(cands) >= 30
    assert all(not geom_mod.certify(c.geometry) for c in cands)
    assert sum(c.axis == "default" for c in cands) == 1
    # Every candidate's plans stay within the budgets the vmem pass
    # enforces — re-checked against the raw plan arithmetic.
    for c in cands:
        for plan in meta.geometry_plans(c.geometry):
            assert plan.vmem_bytes <= plan.budget, (c.label, plan.geometry)
            assert plan.smem_bytes <= meta.SMEM_BUDGET


def test_known_overflow_candidate_rejected():
    """A tile-legal but over-budget geometry is rejected by the
    certifier, not the dataclass: radix B=32 slabs at a 2048-row block
    blow Mosaic's 16 MB default stack budget."""
    bad = Geometry(radix_bits=5, radix_block_rows=2048)
    errs = geom_mod.certify(bad)
    assert errs and any("16 MiB budget" in e for e in errs), errs
    assert all(c.geometry != bad for c in geom_mod.enumerate_candidates())


def test_cost_ranking_matches_pr11_hand_arithmetic():
    """The PR-11 measured pair is the free oracle: 384x128 windows give
    11,206,656 stable2 sort rows per 32 MB chunk, 512x128 give 8,404,992
    (−25%), so tall512 must price BELOW the default — with the spill
    risk flagged (114 ends / 384 bytes measured density → 152 > 128
    slots at the taller window)."""
    assert geom_mod.stable2_sort_rows(1 << 25, 384, 128) == 11206656
    assert geom_mod.stable2_sort_rows(1 << 25, 512, 128) == 8404992
    cands = geom_mod.enumerate_candidates()
    default = next(c for c in cands if c.axis == "default")
    tall = next(c for c in cands if c.label == "tall512")
    assert default.sort_rows == 11206656
    assert tall.sort_rows == 8404992
    assert tall.spill_risk and not default.spill_risk
    sl = geom_mod.shortlist(cands, 5)
    assert sl.index(tall) < len(sl)
    assert [c.sort_rows for c in sl] == sorted(c.sort_rows for c in sl)
    # The cost pass reads the same formula (the re-export contract).
    from mapreduce_tpu.analysis import costmodel

    assert costmodel.stable2_sort_rows is geom_mod.stable2_sort_rows
    # Radix slab write amplification derives the round-6 slack factor
    # from the candidate, not a quote.
    assert geom_mod.radix_slab_write_amplification(DEFAULT_GEOMETRY) == 4.0


# -- Config surface ----------------------------------------------------------

@pytest.mark.smoke
def test_config_geometry_validation_and_resolution():
    # Presets resolve; labels round-trip; dicts convert to the frozen
    # dataclass (Config stays hashable — a static jit argument).
    assert Config().geometry_label == "default"
    assert Config(geometry="auto").geometry_label == "default"
    c = Config(geometry="tall512")
    assert c.resolved_block_rows == 512 and c.geometry_label == "tall512"
    d = Config(geometry={"block_rows": 512})
    assert d.geometry == Geometry(block_rows=512)
    assert d.geometry_label == "custom" and hash(d)
    # Explicit default-valued dict reads as the default label.
    assert Config(geometry=Geometry()).geometry_label == "default"
    # combiner16 deepens the cache without touching windows.
    c16 = Config(geometry="combiner16", map_impl="fused",
                 combiner="hot-cache")
    assert c16.resolved_combiner_slots == 16
    assert c16.resolved_block_rows == 512  # combiner window unchanged
    # The None-sentinel contract: default geometry defers to kernel
    # defaults everywhere (the pre-ISSUE-12 traced programs exactly).
    base = Config()
    assert base.resolved_pair_block_rows is None
    assert base.resolved_aux_rows is None
    assert base.resolved_radix_geometry is None
    assert Config(sort_mode="sort3").resolved_block_rows is None
    # Non-default fields thread through the resolvers.
    g = Geometry(pair_block_rows=384, aux_rows=128, radix_bits=4,
                 sort3_block_rows=384, sort3_slots=128)
    cg = Config(geometry=g)
    assert cg.resolved_pair_block_rows == 384
    assert cg.resolved_aux_rows == 128
    assert cg.resolved_radix_geometry == (4, 256, 4)
    assert Config(geometry=g, sort_mode="sort3").resolved_block_rows == 384
    assert Config(geometry=g,
                  sort_mode="sort3").resolved_compact_slots == 128
    with pytest.raises(ValueError, match="geometry"):
        Config(geometry="bogus")
    with pytest.raises(ValueError, match="geometry"):
        Config(geometry=42)
    with pytest.raises(ValueError, match="compact_slots"):
        Config(geometry={"compact_slots": 120})
    for bad in (dict(block_rows=200), dict(aux_rows=64),
                dict(sort3_slots=100), dict(radix_bits=6),
                dict(combiner_slots=12), dict(block_rows=128),
                dict(radix_bits=5, radix_block_rows=64,
                     radix_slab_slack=1)):
        with pytest.raises(ValueError):
            Geometry(**bad)
    # Presets are themselves valid and include the documented pair arm.
    assert GEOMETRY_PRESETS["default"] == DEFAULT_GEOMETRY
    assert GEOMETRY_PRESETS["tall512"].block_rows == 512


def test_run_start_geometry_stamp_shapes():
    """The ledger stamp: label always; the full spec dict only on custom
    runs (a preset name already names its spec)."""
    from mapreduce_tpu.runtime.executor import _geometry_stamp

    assert _geometry_stamp(Config()) == {"geometry": "default"}
    assert _geometry_stamp(Config(geometry="tall512")) == \
        {"geometry": "tall512"}
    st = _geometry_stamp(Config(geometry={"block_rows": 640}))
    assert st["geometry"] == "custom"
    assert st["geometry_spec"]["block_rows"] == 640


# -- bit-identity across certified geometries --------------------------------

@pytest.mark.smoke
def test_kernel_stream_identity_across_geometries():
    """The fused kernel's live emission SEQUENCE (lane-major = global
    byte-position order) is identical across window heights and aux
    sizes — geometry only repartitions the windows and pads.  Kernel
    level with a small lookback (w=8) so two interpret compiles stay
    fast-tier; the full wordcount/ngram path identity is the @slow test
    below."""
    import numpy as np
    import jax.numpy as jnp

    from mapreduce_tpu.ops.pallas import tokenize as pt

    raw = (b"the quick brown fox honorificabilitudinitatibus jumps "
           b"over a lazy dog " * 150)[:8192]
    data = jnp.asarray(np.frombuffer(raw, np.uint8))

    def live(block_rows, aux_rows=None):
        s, overlong, spill = pt.tokenize_fused(
            data, compact_slots=128, lane_major=True,
            block_rows=block_rows, aux_rows=aux_rows, max_token_bytes=8)
        khi, klo, pk = map(np.asarray, (s.key_hi, s.key_lo, s.packed))
        keep = pk != 0xFFFFFFFF
        return (list(zip(khi[keep], klo[keep], pk[keep])),
                int(s.total), int(overlong), int(spill))

    base = live(384)
    tall = live(512, aux_rows=128)
    assert base == tall
    assert base[0] and base[2] > 0, "corpus must exercise poison rows"


@pytest.mark.slow
def test_wordcount_bit_identity_across_geometries():
    """Acceptance: a non-default certified candidate produces
    bit-identical wordcount results to the default geometry — the
    emission set, fallback exactness and accounting are geometry-
    independent; only the cost moves.  @slow per the >=10 s line (four
    interpret compiles of the full aggregation program); the fast tier
    keeps the kernel-level stream identity above."""
    from mapreduce_tpu.models import wordcount

    def counts(data: bytes, **cfg_kw):
        r = wordcount.count_words(
            data, Config(backend="pallas", chunk_bytes=1 << 14,
                         table_capacity=1 << 11, **cfg_kw))
        return r.words, r.counts, r.total, r.dropped_count

    data = (b"the quick brown fox jumps over the lazy dog " * 150
            + b"u" * 40 + b" tail words here ")
    base = counts(data)
    assert base == counts(data, geometry="tall512")
    assert base == counts(data, geometry={"block_rows": 256,
                                          "aux_rows": 128})


@pytest.mark.slow
def test_fused_and_ngram_bit_identity_across_geometries():
    """The fused map path and the gram family under a custom geometry
    (taller windows + taller aux plane + wider pair fallback) match the
    default bit-for-bit (the acceptance's ngram leg)."""
    from mapreduce_tpu.models import wordcount

    data = (b"alpha beta gamma alpha delta " * 200).rstrip()
    geom = {"block_rows": 512, "aux_rows": 128, "pair_block_rows": 384}

    def fused(geometry=None):
        r = wordcount.count_words(
            data, Config(backend="pallas", chunk_bytes=1 << 14,
                         table_capacity=1 << 11, map_impl="fused",
                         geometry=geometry))
        return r.words, r.counts, r.total

    assert fused() == fused(geom)

    def grams(geometry=None):
        r = wordcount.count_ngrams(
            data, 2, Config(backend="pallas", chunk_bytes=1 << 14,
                            table_capacity=1 << 11, map_impl="fused",
                            geometry=geometry))
        return r.words, r.counts, r.total

    assert grams() == grams(geom)


# -- the tuner's geometry knob (the second non-numeric knob) -----------------

@pytest.mark.smoke
def test_tuner_proposes_and_reverts_geometry():
    p = engine.propose(_fixture("tuner_geometry"))
    assert p["rule"] == "try-geometry"
    assert p["changed"] == {"geometry": ["default", "tall512"]}
    assert p["signals"]["window_occupancy"] == 0.55
    engine.validate_knobs(p["proposal"])
    p2 = engine.propose(_fixture("tuner_geomspill"))
    assert p2["rule"] == "revert-geometry"
    assert p2["changed"] == {"geometry": ["tall512", "default"]}
    engine.validate_knobs(p2["proposal"])
    # A default-geometry spill-bound run keeps the foreign-knob note
    # (its knob is --compact-slots, not a geometry this tuner set).
    spill_default = [dict(r, geometry="default")
                     for r in _fixture("tuner_geomspill")]
    pd = engine.propose(spill_default)
    assert pd["rule"] != "revert-geometry", pd["rule"]
    assert any(t["rule"] == "data-spill-bound" for t in pd["trail"])


def test_tuner_geometry_oscillation_guard():
    """Acceptance: the tuner can propose a geometry change that survives
    validate_knobs, and the oscillation guard stops the try/revert pair
    on the new non-numeric knob."""
    geom_recs, spill_recs = _fixture("tuner_geometry"), \
        _fixture("tuner_geomspill")
    r = engine.search(
        lambda k: geom_recs if k["geometry"] == "default" else spill_recs,
        {"chunk_bytes": 1 << 21, "superstep": 1, "inflight_groups": 4,
         "prefetch_depth": 4}, budget=8)
    assert r["stopped"] == "oscillation" and r["passes"] == 2
    assert [t["rule"] for t in r["trail"]] == \
        ["try-geometry", "revert-geometry"]
    for t in r["trail"]:
        engine.validate_knobs(t["proposal"])
    assert "geometry" in engine.KNOBS
    assert engine.default_knobs()["geometry"] == "default"


def test_tuner_geometry_gated_off_when_combiner_on():
    """With the hot-key cache on, windows are already tall (the
    combiner_block_rows geometry): try-geometry must not fire."""
    recs = [dict(r, combiner="hot-cache") if r.get("kind") == "run_start"
            else r for r in _fixture("tuner_geometry")]
    p = engine.propose(recs)
    assert p["rule"] != "try-geometry", p["rule"]


# -- CLI surface -------------------------------------------------------------

@pytest.mark.smoke
def test_cli_geometry_surface(tmp_path, capsys):
    from mapreduce_tpu import cli

    f = tmp_path / "in.txt"
    f.write_text("a b a c\n")
    with pytest.raises(SystemExit) as exc:
        cli.main([str(f), "--geometry", "bogus"])
    assert exc.value.code == 2
    capsys.readouterr()
    assert cli.main([str(f), "--no-echo", "--format", "json",
                     "--geometry", "tall512"]) == 0
    capsys.readouterr()
    # 'auto' with no profile resolves to the default, loudly.
    assert cli.main([str(f), "--no-echo", "--format", "json",
                     "--geometry", "auto", "--geometry-profile",
                     str(tmp_path / "missing.json")]) == 0
    assert "geometry: auto -> default" in capsys.readouterr().err
    # 'auto' against a searched profile resolves and stamps the ledger.
    prof = tmp_path / "tuned.json"
    prof.write_text(json.dumps({"profiles": {
        "wordcount-geometry/cpu/zipf": {
            "recorded_at": "2026-08-04T00:00:00Z",
            "config": {"geometry": "tall512"}}}}))
    led = tmp_path / "led.jsonl"
    assert cli.main([str(f), "--no-echo", "--format", "json",
                     "--geometry", "auto", "--geometry-profile",
                     str(prof), "--ledger", str(led)]) == 0
    assert "geometry: auto -> tall512" in capsys.readouterr().err
    from mapreduce_tpu import obs

    start = next(r for r in obs.read_ledger(str(led))
                 if r["kind"] == "run_start")
    assert start["geometry"] == "tall512"
    assert start["ledger_version"] == obs.LEDGER_VERSION == 10


# -- the search artifact / selftest entry ------------------------------------

def test_geomsearch_selftest_entry():
    """The tools/geomsearch.py selftest (the tier-1/smoke gate) passes
    from pytest too — one entry point, wherever it is invoked from."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import geomsearch
    finally:
        sys.path.pop(0)
    assert geomsearch.selftest() == 0


def test_search_artifact_schema():
    cands = geom_mod.enumerate_candidates()
    art = geom_mod.search_artifact(cands, 3)
    assert art["geometry_search_version"] == \
        geom_mod.GEOMETRY_SEARCH_VERSION
    assert art["candidates"] == len(cands)
    assert len(art["shortlist"]) == 3
    for entry in art["shortlist"]:
        assert set(entry) == {"label", "axis", "sort_rows",
                              "sort_pass_bytes", "vmem_peak_bytes",
                              "radix_amplification", "spill_risk",
                              "geometry"}
        Geometry(**entry["geometry"])  # the spec round-trips
    json.dumps(art)


# -- graphcheck certification of a candidate ---------------------------------

@pytest.mark.slow
def test_graphcheck_certifies_shortlist_candidate():
    """Acceptance: a shortlisted candidate passes the full baseline-free
    graphcheck pipeline (vmem-budget, kernel-race, spill-reachability,
    host-sync, sharding, algebra, overflow) with zero errors — the
    geometry changes static shapes, never the certified disciplines."""
    from mapreduce_tpu import analysis
    from mapreduce_tpu.models.wordcount import WordCountJob

    passes = [p for p in analysis.default_pipeline()
              if p.pass_id not in ("hbm-cost", "fusion-opportunity")]
    cfg = Config(chunk_bytes=128 * 512, table_capacity=512,
                 backend="pallas", map_impl="fused", geometry="tall512")
    report = analysis.analyze_job(WordCountJob(cfg), "<geometry:tall512>",
                                  passes=passes)
    assert not report.errors, report.format_text("error")


@pytest.mark.slow
def test_cost_pass_prices_candidate_geometry():
    """The hbm-cost pass re-derives stable2_sort_rows from the CANDIDATE
    geometry: the traced sort equation must match the candidate's own
    window arithmetic exactly, the artifact must carry the geometry
    label, and the measured-rates leg must be pinned to the default."""
    from mapreduce_tpu import analysis
    from mapreduce_tpu.models.wordcount import WordCountJob

    cfg = Config(chunk_bytes=128 * 512, table_capacity=512,
                 backend="pallas", geometry="tall512")
    report = analysis.analyze_job(WordCountJob(cfg), "<geom-cost>")
    errors = [f for f in report.findings if f.severity == "error"
              and f.pass_id == "hbm-cost"
              and "baseline" not in f.message]
    assert not errors, [f.message for f in errors]
    art = report.artifacts.get("<geom-cost>", {}).get("cost", {})
    assert art.get("geometry") == "tall512"
    sort_art = art.get("aggregation_sort", {})
    assert sort_art.get("traced_rows") == sort_art.get("expected_rows") \
        == geom_mod.stable2_sort_rows(128 * 512, 512, 128)
    assert "skipped" in sort_art.get("measured_leg", "")
