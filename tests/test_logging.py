"""Direct tests for runtime/logging.py (previously zero) and the
PhaseTimer safety fixes — ISSUE 2 satellites."""

import json
import logging

from mapreduce_tpu.runtime.logging import (JsonFormatter, get_logger,
                                           log_event)
from mapreduce_tpu.runtime.metrics import PhaseTimer


def _fmt(formatter, logger_name, msg, **fields):
    rec = logging.LogRecord(logger_name, logging.INFO, __file__, 1, msg,
                            None, None)
    if fields:
        rec.fields = fields
    return formatter.format(rec)


# -- JsonFormatter / log_event ---------------------------------------------

def test_json_formatter_core_fields():
    line = _fmt(JsonFormatter(), "t", "hello")
    obj = json.loads(line)
    assert obj["msg"] == "hello" and obj["level"] == "info"
    assert isinstance(obj["ts"], float)


def test_json_formatter_merges_event_fields():
    obj = json.loads(_fmt(JsonFormatter(), "t", "step failed",
                          step=3, offset=4096))
    assert obj["step"] == 3 and obj["offset"] == 4096
    assert obj["msg"] == "step failed"


def test_log_event_attaches_fields():
    logger = logging.getLogger("mapreduce_tpu.test_log_event")
    logger.propagate = False
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger.addHandler(Capture())
    logger.setLevel(logging.INFO)
    try:
        log_event(logger, "progress", step=7, bytes=123)
    finally:
        logger.handlers.clear()
    assert records and records[0].fields == {"step": 7, "bytes": 123}
    assert json.loads(JsonFormatter().format(records[0]))["step"] == 7


# -- get_logger reconfigure (the handler-caching bug) -----------------------

def _package_handler(logger):
    return [h for h in logger.handlers if getattr(h, "_mr_handler", False)][0]


def test_get_logger_honors_json_lines_after_first_call():
    name = "mapreduce_tpu.test_reconf_json"
    plain = get_logger(name)
    assert isinstance(_package_handler(plain).formatter, logging.Formatter)
    assert not isinstance(_package_handler(plain).formatter, JsonFormatter)
    # The regression: this second call was silently ignored before.
    jsonl = get_logger(name, json_lines=True)
    assert jsonl is plain
    assert isinstance(_package_handler(jsonl).formatter, JsonFormatter)
    # ...and back.
    get_logger(name, json_lines=False)
    assert not isinstance(_package_handler(plain).formatter, JsonFormatter)


def test_get_logger_honors_level_after_first_call():
    name = "mapreduce_tpu.test_reconf_level"
    logger = get_logger(name)
    assert logger.level == logging.INFO
    get_logger(name, level=logging.DEBUG)
    assert logger.level == logging.DEBUG


def test_get_logger_defaults_keep_configuration():
    """The None defaults must NOT clobber an explicit earlier choice — a
    library's bare get_logger() call after the CLI asked for JSON."""
    name = "mapreduce_tpu.test_reconf_keep"
    get_logger(name, json_lines=True, level=logging.WARNING)
    again = get_logger(name)  # defaults: keep, not reset
    assert isinstance(_package_handler(again).formatter, JsonFormatter)
    assert again.level == logging.WARNING


def test_get_logger_single_handler():
    name = "mapreduce_tpu.test_reconf_single"
    for _ in range(3):
        logger = get_logger(name, json_lines=True)
    assert len([h for h in logger.handlers
                if getattr(h, "_mr_handler", False)]) == 1


# -- PhaseTimer safety ------------------------------------------------------

def test_phase_timer_stop_never_started_is_safe():
    t = PhaseTimer()
    assert t.stop("ghost") == 0.0  # formerly a bare KeyError
    assert t["ghost"] == 0.0
    assert "ghost" not in t.phases


def test_phase_timer_double_stop_idempotent():
    t = PhaseTimer()
    t.start("a")
    first = t.stop("a")
    assert first >= 0.0
    assert t.stop("a") == 0.0  # second stop accumulates nothing
    assert t["a"] == first


def test_phase_timer_restart_last_wins():
    t = PhaseTimer()
    t.start("a")
    t.start("a")  # restart while open: earlier start discarded
    dt = t.stop("a")
    assert dt >= 0.0 and t["a"] == dt
    assert not t.running("a")


def test_phase_timer_nested_distinct_phases():
    t = PhaseTimer()
    t.start("outer")
    t.start("inner")
    assert t.running("outer") and t.running("inner")
    t.stop("inner")
    t.stop("outer")
    assert t["outer"] >= t["inner"] >= 0.0


def test_phase_timer_exception_path_preserves_cause():
    """The executor stops 'dispatch' on the failure path; the stop must not
    replace the propagating device error with a KeyError."""
    t = PhaseTimer()
    try:
        try:
            raise RuntimeError("device fault")
        finally:
            t.stop("dispatch")  # never started: start() itself failed
    except RuntimeError as e:
        assert "device fault" in str(e)
