"""Fault-injection harness + unified failure policy (ISSUE 15).

The chaos matrix: one fast test per named seam (injected fault -> typed
classification -> policy outcome), the taxonomy/backoff/ladder units next
to tools/chaos.py's jax-free selftest, checkpoint-integrity fallback, the
degradation ladder end-to-end, and the chaos-certification byte-identity
contract — a run under a seeded fault plan whose retry budget absorbs the
chaos produces results bit-identical to the fault-free run, and the run's
own ledger replays the identical fault sequence.
"""

import json
import os

import numpy as np
import pytest

from mapreduce_tpu import obs
from mapreduce_tpu.config import Config
from mapreduce_tpu.models.wordcount import WordCountJob
from mapreduce_tpu.parallel.mesh import data_mesh
from mapreduce_tpu.runtime import checkpoint as ckpt
from mapreduce_tpu.runtime import executor, faults
from mapreduce_tpu.utils import oracle
from tests.conftest import make_corpus

CFG = Config(chunk_bytes=512, table_capacity=2048)


def _write(tmp_path, data: bytes):
    p = tmp_path / "corpus.txt"
    p.write_bytes(data)
    return str(p)


def _chaos_cfg(plan: str, **kw) -> Config:
    return Config(chunk_bytes=512, table_capacity=2048, fault_plan=plan,
                  **kw)


# ---------------------------------------------------------------------------
# units: taxonomy / policy / plan / ladder (the chaos-selftest surface,
# re-checked through the real package import)
# ---------------------------------------------------------------------------


@pytest.mark.smoke
def test_classify_taxonomy():
    assert faults.classify(faults.TransientFault("x")) == "transient"
    assert faults.classify(faults.ResourceFault("x")) == "resource"
    assert faults.classify(faults.PermanentFault("x")) == "permanent"
    assert faults.classify(faults.PreemptionFault("x")) == "preemption"
    assert faults.classify(faults.TokenTimeout("hung")) == "transient"
    # Real exceptions: type beats message markers, markers beat the default.
    assert faults.classify(
        RuntimeError("RESOURCE_EXHAUSTED: failed to allocate")) == "resource"
    assert faults.classify(RuntimeError("VMEM limit exceeded")) == "resource"
    assert faults.classify(
        RuntimeError("host preempted: maintenance event")) == "preemption"
    assert faults.classify(KeyboardInterrupt()) == "preemption"
    assert faults.classify(ValueError("bad shape")) == "permanent"
    assert faults.classify(TypeError("no")) == "permanent"
    # A permanent-typed error whose message happens to contain a marker
    # substring ('oom' in 'bloom', 'preempt...') is still a programming
    # error: retrying or walking the ladder re-runs the same bug.
    assert faults.classify(ValueError("bad bloom_bits")) == "permanent"
    assert faults.classify(KeyError("room_id")) == "permanent"
    assert faults.classify(ValueError("preempt_queue empty")) == "permanent"
    # 'oom' counts as a whole word only — 'bloom'/'room'/'zoom' inside a
    # non-permanent-typed message must not charge the resource budget
    # (and walk the ladder); a real 'OOM when allocating' still does.
    assert faults.classify(RuntimeError("bloom filter relay failed")) \
        == "transient"
    assert faults.classify(OSError("no room in zoom buffer")) == "transient"
    assert faults.classify(RuntimeError("OOM when allocating")) == "resource"
    # Unknown -> transient: the legacy retry=N semantics retried ANY
    # exception, and the default policy must keep doing exactly that.
    assert faults.classify(RuntimeError("flaky relay")) == "transient"
    assert faults.classify(OSError("read failed")) == "transient"


@pytest.mark.smoke
def test_policy_legacy_mapping_and_validation():
    p = faults.FailurePolicy.resolve(None, retry=3)
    assert p.transient_retries == 3 and p.resource_retries == 3
    assert p.permanent_retries == 0
    assert p.budget("preemption") == 0, "preemption never retries"
    assert p.dispatch_budget == 3
    p0 = faults.FailurePolicy.resolve(None, retry=0)
    assert p0.dispatch_budget == 0
    d = faults.FailurePolicy.resolve({"transient_retries": 2,
                                      "token_timeout_s": 1.5})
    assert d.transient_retries == 2 and d.token_timeout_s == 1.5
    for bad in (dict(transient_retries=-1), dict(backoff_factor=0.5),
                dict(jitter_frac=1.5), dict(token_timeout_s=0)):
        with pytest.raises(ValueError):
            faults.FailurePolicy(**bad)
    with pytest.raises(ValueError, match="failure_policy"):
        faults.FailurePolicy.resolve("not-a-policy")


@pytest.mark.smoke
def test_backoff_hand_values_and_deterministic_jitter():
    p = faults.FailurePolicy(backoff_base_s=0.05, backoff_factor=2.0,
                             backoff_max_s=5.0, jitter_frac=0.0)
    assert [p.backoff_s("transient", a) for a in range(1, 10)] == \
        [0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 3.2, 5.0, 5.0]
    pj = faults.FailurePolicy(backoff_base_s=1.0, backoff_factor=1.0,
                              backoff_max_s=1.0, jitter_frac=0.2, seed=7)
    v = pj.backoff_s("transient", 1, seam="dispatch")
    assert v == pj.backoff_s("transient", 1, seam="dispatch")
    assert 0.8 <= v <= 1.2
    assert v != pj.backoff_s("transient", 1, seam="reader-read")


@pytest.mark.smoke
def test_plan_spec_roundtrip_and_determinism():
    plan = faults.FaultPlan.from_spec(
        "seed=9,rate=0.1,seams=dispatch+token-wait,classes=transient,"
        "max=3,at=checkpoint-save:0:resource")
    rt = faults.FaultPlan.from_spec(plan.spec)
    assert rt.spec == plan.spec and rt.events == plan.events
    # Same seed -> same firing decisions, a different seed differs.
    d1 = [plan.decide("dispatch", i) for i in range(100)]
    d2 = [faults.FaultPlan.from_spec(plan.spec).decide("dispatch", i)
          for i in range(100)]
    assert d1 == d2
    assert plan.decide("checkpoint-save", 0) == "resource", \
        "explicit events fire regardless of rate/seams"
    assert plan.decide("reader-read", 0) is None, \
        "rate only targets the plan's seams"
    for bad in ("", "rate=1.5", "at=dispatch:x:transient", "seams=warp",
                "classes=entropic", "bogus"):
        with pytest.raises(ValueError):
            faults.FaultPlan.from_spec(bad)
    assert faults.FaultPlan.resolve(None) is None, \
        "the zero-cost disabled path must stay None"


@pytest.mark.smoke
def test_degradation_ladder_walks():
    full = {"geometry": "tall512", "combiner": "hot-cache",
            "map_impl": "fused", "sort_impl": "radix"}
    assert faults.ladder_walk(full) == [
        "revert-geometry", "combiner-off", "map-split", "sort-xla"]
    assert faults.next_degrade(
        {"geometry": "default", "combiner": "off", "map_impl": "split",
         "sort_impl": "xla"}) is None


def test_config_fault_surface():
    # fault_plan validates at construction, not mid-stream.
    with pytest.raises(ValueError):
        Config(fault_plan="rate=2.0")
    with pytest.raises(ValueError, match="fault_plan"):
        Config(fault_plan=123)
    # failure_policy: dicts coerce to the frozen dataclass (Config stays
    # hashable — a static jit argument), bad types refuse.
    c = Config(failure_policy={"transient_retries": 2, "degrade": False})
    assert isinstance(c.failure_policy, faults.FailurePolicy)
    assert c.failure_policy.transient_retries == 2
    hash(c)  # must stay hashable with the policy attached
    with pytest.raises(ValueError, match="failure_policy"):
        Config(failure_policy="retry-lots")
    # The valid chaotic config round-trips its spec.
    c2 = Config(fault_plan="seed=3,rate=0.05")
    assert faults.FaultPlan.resolve(c2.fault_plan).seed == 3


# ---------------------------------------------------------------------------
# the chaos matrix: one injected fault per named seam -> typed
# classification -> policy outcome (fast tier; ISSUE 15 satellite)
# ---------------------------------------------------------------------------

#: (seam, crossing index, whether the policy outcome is a retry record).
#: ledger-append is absorbed (observing must never kill the observed
#: run); every other seam retries on the transient budget.
_SEAM_CASES = [
    ("reader-read", 1, True),
    ("stage-acquire", 1, True),
    ("h2d", 1, True),
    ("dispatch", 1, True),
    ("token-wait", 1, True),
    ("checkpoint-save", 0, True),
    ("ledger-append", 1, False),
    ("collective-finish", 0, True),
]


@pytest.mark.parametrize("seam,index,retries", _SEAM_CASES,
                         ids=[c[0] for c in _SEAM_CASES])
def test_seam_injection_classifies_and_recovers(tmp_path, rng, seam,
                                                index, retries):
    """Injected transient fault at one seam: the run records a typed
    `fault` ledger record at that seam, the policy absorbs it (retry, or
    absorption for the telemetry plane), and results stay exact."""
    corpus = make_corpus(rng, 1500, 100)
    path = _write(tmp_path, corpus)
    cfg = _chaos_cfg(f"at={seam}:{index}:transient")
    kw = {}
    if seam == "checkpoint-save":
        kw = dict(checkpoint_path=str(tmp_path / "ck.npz"),
                  checkpoint_every=2)
    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        result = executor.count_file(path, cfg, mesh=data_mesh(2),
                                     retry=2, telemetry=tel, **kw)
    assert result.as_dict() == oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)
    fault_recs = list(obs.read_ledger(led, kind="fault"))
    assert len(fault_recs) == 1, fault_recs
    f = fault_recs[0]
    assert f["seam"] == seam and f["fault_class"] == "transient"
    assert f["injected"] is True and f["index"] == index
    assert not list(obs.read_ledger(led, kind="failure"))
    retry_recs = list(obs.read_ledger(led, kind="retry"))
    if retries:
        assert retry_recs, f"seam {seam} must charge a retry"
        assert all(r["fault_class"] == "transient" for r in retry_recs)
    else:
        assert not retry_recs, "an absorbed ledger-append fault is not " \
            "a retry — the step record is simply skipped"
    # run_start names the chaos (ledger v9) with the CANONICAL spec.
    start = next(iter(obs.read_ledger(led, kind="run_start")))
    assert start["fault_plan"] \
        == faults.FaultPlan.from_spec(cfg.fault_plan).spec
    assert start["ledger_version"] == obs.LEDGER_VERSION == 10


def test_permanent_fault_fails_immediately(tmp_path, rng):
    """Permanent class: retrying re-runs the same bug, so the budget is
    never consulted — one attempt, loud failure, classified record."""
    corpus = make_corpus(rng, 1000, 80)
    path = _write(tmp_path, corpus)
    cfg = _chaos_cfg("at=dispatch:1:permanent")
    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        with pytest.raises(faults.PermanentFault):
            executor.count_file(path, cfg, mesh=data_mesh(2), retry=3,
                                telemetry=tel)
    fails = list(obs.read_ledger(led, kind="failure"))
    assert len(fails) == 1 and fails[0]["fault_class"] == "permanent"
    assert not list(obs.read_ledger(led, kind="retry")), \
        "a permanent fault must not burn the retry budget"
    assert fails[0].get("flight_dump"), "forensics must still dump"


def test_preemption_drains_checkpoints_and_resumes(tmp_path, rng):
    """Preemption: drain the in-flight window -> checkpoint -> clean exit
    with a resumable cursor (no flight dump, no failure record); a
    relaunch resumes from the snapshot and finishes exactly."""
    corpus = make_corpus(rng, 2500, 120)
    path = _write(tmp_path, corpus)
    ck = str(tmp_path / "ck.npz")
    led = str(tmp_path / "run.jsonl")
    cfg = _chaos_cfg("at=dispatch:3:preemption")
    with obs.Telemetry.create(ledger_path=led) as tel:
        with pytest.raises(faults.Preempted) as ei:
            executor.count_file(path, cfg, mesh=data_mesh(2), retry=1,
                                checkpoint_path=ck, checkpoint_every=50,
                                telemetry=tel)
    pe = ei.value
    assert pe.checkpointed and pe.checkpoint_path == ck
    assert 0 < pe.cursor_bytes < len(corpus)
    assert ckpt.exists(ck), "the preemption drain must leave a snapshot"
    assert not list(obs.read_ledger(led, kind="failure")), \
        "an orderly shutdown is not a failure"
    assert not os.path.exists(led + ".flight.json"), \
        "no flight dump on the preemption path"
    cks = list(obs.read_ledger(led, kind="checkpoint"))
    assert cks and cks[-1].get("preempt") is True
    # Relaunch (no plan) resumes from the cursor and stays exact.
    result = executor.count_file(path, CFG, mesh=data_mesh(2),
                                 checkpoint_path=ck, checkpoint_every=50)
    assert result.as_dict() == oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)


def test_real_preemption_exception_takes_drain_path(tmp_path, rng):
    """A REAL platform preemption arrives as an ordinary RuntimeError
    whose MESSAGE marks it ('maintenance event'), never as an injected
    PreemptionFault — the stream handler must catch it by CLASS and run
    the same drain -> checkpoint -> Preempted orderly exit (regression:
    the handler once caught the PreemptionFault type only, so real
    preemptions fell through to the failure path)."""
    corpus = make_corpus(rng, 2500, 120)
    path = _write(tmp_path, corpus)
    ck = str(tmp_path / "ck.npz")
    led = str(tmp_path / "run.jsonl")
    from mapreduce_tpu.parallel import mapreduce as mr

    orig_step = mr.Engine.step
    fired = []

    def preempting(self, state, chunks, step_index):
        if int(step_index) >= 3 and not fired:
            fired.append(int(step_index))
            raise RuntimeError("host preempted: maintenance event")
        return orig_step(self, state, chunks, step_index)

    mr.Engine.step = preempting
    try:
        with obs.Telemetry.create(ledger_path=led) as tel:
            with pytest.raises(faults.Preempted) as ei:
                executor.count_file(path, CFG, mesh=data_mesh(2), retry=1,
                                    checkpoint_path=ck, checkpoint_every=50,
                                    telemetry=tel)
    finally:
        mr.Engine.step = orig_step
    assert fired, "the preemption never fired; test is vacuous"
    pe = ei.value
    assert pe.checkpointed and ckpt.exists(ck)
    assert not list(obs.read_ledger(led, kind="failure")), \
        "an orderly shutdown is not a failure"
    assert not os.path.exists(led + ".flight.json"), \
        "no flight dump on the preemption path"
    # Relaunch resumes from the snapshot and finishes exactly.
    result = executor.count_file(path, CFG, mesh=data_mesh(2),
                                 checkpoint_path=ck, checkpoint_every=50)
    assert result.as_dict() == oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)


def test_token_timeout_reads_as_typed_fault(tmp_path, rng, monkeypatch):
    """A hung completion-token wait past token_timeout_s raises a typed
    TokenTimeout (transient) instead of stalling forever; the replay path
    recovers and the run stays exact."""
    corpus = make_corpus(rng, 1500, 100)
    path = _write(tmp_path, corpus)

    import time as _time

    orig_wait = executor._wait_token
    hung = []

    def slow_wait(token):
        if not hung:  # first wait hangs well past the deadline
            hung.append(True)
            _time.sleep(2.0)
        return orig_wait(token)

    monkeypatch.setattr(executor, "_wait_token", slow_wait)
    cfg = Config(chunk_bytes=512, table_capacity=2048,
                 failure_policy={"transient_retries": 2,
                                 "token_timeout_s": 0.2,
                                 "backoff_base_s": 0.0,
                                 "jitter_frac": 0.0})
    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        result = executor.count_file(path, cfg, mesh=data_mesh(2),
                                     telemetry=tel)
    assert hung, "the hang never fired; test is vacuous"
    assert result.as_dict() == oracle.word_counts(corpus)
    faults_recs = list(obs.read_ledger(led, kind="fault"))
    assert any(f["seam"] == "token-wait" and not f["injected"]
               and f["fault_class"] == "transient" for f in faults_recs), \
        faults_recs
    assert not list(obs.read_ledger(led, kind="failure"))


def test_retries_by_class_lands_in_registry(tmp_path, rng):
    """ISSUE 15 satellite: per-class retry accounting is a first-class
    registry metric."""
    corpus = make_corpus(rng, 1200, 80)
    path = _write(tmp_path, corpus)
    reg = obs.get_registry()
    before = reg.snapshot()["counters"].get(
        "executor.retries_by_class{fault_class=transient}", 0)
    cfg = _chaos_cfg("at=dispatch:1:transient")
    with obs.Telemetry.create() as tel:
        executor.count_file(path, cfg, mesh=data_mesh(2), retry=2,
                            telemetry=tel)
    after = reg.snapshot()["counters"].get(
        "executor.retries_by_class{fault_class=transient}", 0)
    assert after == before + 1, (before, after)


# ---------------------------------------------------------------------------
# degradation ladder (tentpole (3))
# ---------------------------------------------------------------------------


def test_degradation_ladder_steps_down_and_stays_exact(tmp_path, rng,
                                                       monkeypatch):
    """A persistent resource-classed failure exhausts its budget and
    steps the ladder: revert-geometry rebuilds the engine on the default
    geometry (the xla path carries the label without compiling it — the
    cheapest real ladder step to drive on CPU), a `degrade` ledger
    record lands, and the replay finishes EXACTLY."""
    from mapreduce_tpu.parallel import mapreduce as mr

    corpus = make_corpus(rng, 2000, 100)
    path = _write(tmp_path, corpus)
    orig_step = mr.Engine.step
    fired = []

    def storming(self, state, chunks, step_index):
        # A VMEM storm that only clears once the ladder reverts the
        # geometry: the job's config is the ladder's moving target.
        if self.job.config.geometry is not None and int(step_index) >= 2:
            fired.append(int(step_index))
            raise RuntimeError("RESOURCE_EXHAUSTED: injected VMEM storm")
        return orig_step(self, state, chunks, step_index)

    monkeypatch.setattr(mr.Engine, "step", storming)
    cfg = Config(chunk_bytes=512, table_capacity=2048, geometry="tall512",
                 failure_policy={"resource_retries": 1,
                                 "transient_retries": 1,
                                 "backoff_base_s": 0.0, "jitter_frac": 0.0,
                                 "degrade": True})
    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        rr = executor.run_job(WordCountJob(cfg), path, cfg,
                              mesh=data_mesh(2), telemetry=tel)
    assert fired, "the storm never fired; test is vacuous"
    assert rr.metrics.words_counted == oracle.total_count(corpus)
    assert rr.pipeline.get("degrade_steps") == ["revert-geometry"]
    degs = list(obs.read_ledger(led, kind="degrade"))
    assert len(degs) == 1, degs
    assert degs[0]["ladder_step"] == "revert-geometry"
    assert degs[0]["field"] == "geometry"
    assert degs[0]["from"] == "tall512" and degs[0]["to"] == "default"
    assert degs[0]["fault_class"] == "resource"
    assert not list(obs.read_ledger(led, kind="failure")), \
        "a degraded run is alive, not failed"
    snap = obs.get_registry().snapshot()["counters"]
    assert snap.get(
        "executor.degrade_steps{ladder_step=revert-geometry}", 0) >= 1


def test_ladder_exhausted_fails_with_resource_class(tmp_path, rng,
                                                    monkeypatch):
    """With every ladder knob already at its floor, a persistent
    resource failure surfaces as a failure record classified
    `resource` — the honest outcome when there is nothing left to give
    up."""
    from mapreduce_tpu.parallel import mapreduce as mr

    corpus = make_corpus(rng, 1000, 80)
    path = _write(tmp_path, corpus)
    orig_step = mr.Engine.step

    def storming(self, state, chunks, step_index):
        if int(step_index) >= 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: persistent OOM")
        return orig_step(self, state, chunks, step_index)

    monkeypatch.setattr(mr.Engine, "step", storming)
    cfg = Config(chunk_bytes=512, table_capacity=2048,
                 failure_policy={"resource_retries": 1,
                                 "backoff_base_s": 0.0, "jitter_frac": 0.0,
                                 "degrade": True})
    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            executor.count_file(path, cfg, mesh=data_mesh(2),
                                telemetry=tel)
    fails = list(obs.read_ledger(led, kind="failure"))
    assert len(fails) == 1 and fails[0]["fault_class"] == "resource"
    assert not list(obs.read_ledger(led, kind="degrade")), \
        "the default config has no ladder step to take"


# ---------------------------------------------------------------------------
# checkpoint integrity (ISSUE 15 satellite)
# ---------------------------------------------------------------------------


def _mini_state():
    return {"a": np.arange(8, dtype=np.int64).reshape(2, 4),
            "b": np.ones((2, 3), np.float32)}


def test_checkpoint_checksum_roundtrip(tmp_path):
    path = str(tmp_path / "ck.npz")
    ckpt.save(path, _mini_state(), 3, 4096, np.zeros((3, 2), np.int64))
    assert ckpt.verify(path) is True
    assert os.path.exists(ckpt.integrity_path(path))
    (state, step, offset, bases, fi) = ckpt.load_verified(path)
    assert step == 3 and offset == 4096
    # A flipped byte fails the checksum and load_verified names it.
    with open(path, "r+b") as f:
        f.seek(100)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    assert ckpt.verify(path) is False
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.load_verified(path)
    # No sidecar (a pre-integrity snapshot): verify is unknown (None) and
    # a parseable file still loads.
    os.unlink(ckpt.integrity_path(path))
    assert ckpt.verify(path) is None


def test_corrupt_checkpoint_falls_back_to_previous(tmp_path):
    path = str(tmp_path / "ck.npz")
    ckpt.save(path, _mini_state(), 1, 1024, np.zeros((1, 2), np.int64))
    ckpt.save(path, _mini_state(), 2, 2048, np.zeros((2, 2), np.int64))
    assert os.path.exists(ckpt.previous_path(path)), \
        "the second save must rotate the first aside as .prev"
    assert ckpt.verify(ckpt.previous_path(path)) is True
    # Tear the live snapshot; the resilient load returns the previous
    # good one and NAMES the fallback.
    with open(path, "wb") as f:
        f.write(b"torn mid-save")
    (_, step, offset, _, _), fb = ckpt.load_resilient(path)
    assert step == 1 and offset == 1024
    assert fb is not None and fb["corrupt"] == path
    assert fb["loaded"] == ckpt.previous_path(path)
    # Both torn -> CheckpointCorrupt (the caller chooses restart).
    with open(ckpt.previous_path(path), "wb") as f:
        f.write(b"also torn")
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.load_resilient(path)


def test_missing_live_snapshot_resumes_from_previous(tmp_path):
    """A kill inside save()'s rename-fallback rotation can leave `path`
    absent with a good `.prev`: the resume gate must still say yes and
    the resilient load must come back from `.prev` — not restart the
    stream from byte 0."""
    path = str(tmp_path / "ck.npz")
    ckpt.save(path, _mini_state(), 1, 1024, np.zeros((1, 2), np.int64))
    ckpt.save(path, _mini_state(), 2, 2048, np.zeros((2, 2), np.int64))
    os.unlink(path)
    os.unlink(ckpt.integrity_path(path))
    assert ckpt.exists(path), \
        "a good .prev alone must still gate resume on"
    (_, step, offset, _, _), fb = ckpt.load_resilient(path)
    assert step == 1 and offset == 1024
    assert fb is not None and fb["loaded"] == ckpt.previous_path(path)


def test_resume_from_corrupt_checkpoint_e2e(tmp_path, rng):
    """A torn live snapshot at resume falls back to the previous good one
    (ledger `fault` note at seam checkpoint-load) and the resumed run
    stays exact — the relaunch just replays a little more stream."""
    corpus = make_corpus(rng, 3000, 120)
    path = _write(tmp_path, corpus)
    ck = str(tmp_path / "ck.npz")
    from mapreduce_tpu.parallel import mapreduce as mr

    # First run crashes partway (the test_executor crash idiom) after at
    # least two checkpoints exist, so .prev is populated.
    orig_step = mr.Engine.step
    crashed = []

    def crashing(self, state, chunks, step_index):
        if int(step_index) >= 8 and not crashed:
            crashed.append(int(step_index))
            raise RuntimeError("injected crash")
        return orig_step(self, state, chunks, step_index)

    mr.Engine.step = crashing
    try:
        with pytest.raises(RuntimeError, match="injected crash"):
            executor.count_file(path, CFG, mesh=data_mesh(2),
                                checkpoint_path=ck, checkpoint_every=2)
    finally:
        mr.Engine.step = orig_step
    assert crashed and os.path.exists(ckpt.previous_path(ck))
    # Tear the live snapshot.
    with open(ck, "wb") as f:
        f.write(b"torn by a crash mid-save")
    led = str(tmp_path / "resume.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        result = executor.count_file(path, CFG, mesh=data_mesh(2),
                                     checkpoint_path=ck,
                                     checkpoint_every=2, telemetry=tel)
    assert result.as_dict() == oracle.word_counts(corpus)
    assert result.total == oracle.total_count(corpus)
    notes = [f for f in obs.read_ledger(led, kind="fault")
             if f.get("seam") == "checkpoint-load"]
    assert len(notes) == 1 and notes[0]["injected"] is False, notes
    assert notes[0]["fallback"] == ckpt.previous_path(ck)


# ---------------------------------------------------------------------------
# chaos certification (tentpole (4)): byte-identity + ledger replay
# ---------------------------------------------------------------------------

#: The fast certification trio: a mid-window async fault, a
#: checkpoint-save failure (budget exhausted -> degrade to unsaved), and
#: a seeded random plan.  The @slow sweep extends to >= 8 plans covering
#: every seam.
_FAST_PLANS = [
    "at=token-wait:1:transient,at=token-wait:2:transient",
    "at=checkpoint-save:0:transient,at=checkpoint-save:1:transient,"
    "at=checkpoint-save:2:transient",
    "seed=3,rate=0.08,classes=transient",
]

_SLOW_PLANS = _FAST_PLANS + [
    "seed=1,rate=0.05",
    "seed=2,rate=0.15,classes=transient",
    "at=reader-read:1:transient,at=reader-read:3:transient",
    "at=dispatch:0:transient,at=h2d:2:transient,"
    "at=stage-acquire:1:transient",
    "at=ledger-append:0:transient,at=collective-finish:0:transient",
    "seed=9,rate=0.3,seams=dispatch+token-wait,max=5",
]


def _certify(tmp_path, corpus, plans, inflight=3):
    """Each plan's run must be bit-identical to the fault-free run."""
    path = _write(tmp_path, corpus)
    base_cfg = Config(chunk_bytes=512, table_capacity=2048,
                      inflight_groups=inflight)
    ck = str(tmp_path / "base_ck.npz")
    base = executor.count_file(path, base_cfg, mesh=data_mesh(2), retry=3,
                               checkpoint_path=ck, checkpoint_every=3)
    os.unlink(ck)
    for i, plan in enumerate(plans):
        cfg = Config(chunk_bytes=512, table_capacity=2048,
                     inflight_groups=inflight, fault_plan=plan)
        ckp = str(tmp_path / f"ck_{i}.npz")
        chaos = executor.count_file(path, cfg, mesh=data_mesh(2), retry=3,
                                    checkpoint_path=ckp,
                                    checkpoint_every=3)
        assert chaos.as_dict() == base.as_dict(), f"plan {plan!r} diverged"
        assert chaos.total == base.total
        assert chaos.words == base.words and chaos.counts == base.counts
        assert chaos.distinct == base.distinct
    return base


@pytest.mark.slow
def test_chaos_byte_identity_trio(tmp_path, rng):
    """The three headline plans (mid-window async, checkpoint-save
    failure, seeded random) against one shared baseline.  @slow per the
    >=10s line — the fast tier keeps per-seam EXACTNESS through the
    seam-matrix tests above, which assert oracle equality under every
    injected fault."""
    corpus = make_corpus(rng, 1500, 100)
    _certify(tmp_path, corpus, _FAST_PLANS)


@pytest.mark.slow
def test_chaos_certification_eight_plans(tmp_path, rng):
    """ISSUE 15 acceptance: >= 8 distinct seeded fault plans — covering
    every injectable seam, incl. a mid-window async fault and a
    checkpoint-save failure — each bit-identical to the fault-free run."""
    assert len(_SLOW_PLANS) >= 8
    covered = set()
    for plan in _SLOW_PLANS:
        p = faults.FaultPlan.from_spec(plan)
        covered.update(p.seams if p.rate else ())
        covered.update(s for (s, _) in p.events)
    assert covered >= {s for s in faults.SEAMS
                       if s not in ("process-kill", "checkpoint-load")}, \
        covered
    corpus = make_corpus(rng, 2500, 150)
    _certify(tmp_path, corpus, _SLOW_PLANS)


@pytest.mark.slow
def test_chaos_midstream_partial_merge_identity(tmp_path, rng):
    """ISSUE 20: the collective-finish seam fires on window-boundary
    PARTIAL merges too (plan grammar unchanged).  Plans whose collective
    faults land mid-stream — on partial-merge crossings, not just the
    end-of-stream finish — must replay to counts bit-identical to the
    overlap-OFF fault-free baseline."""
    corpus = make_corpus(rng, 2000, 120)
    path = _write(tmp_path, corpus)
    base = executor.count_file(path, Config(chunk_bytes=512,
                                            table_capacity=2048,
                                            inflight_groups=2),
                               mesh=data_mesh(2))
    plans = [
        # Crossing 0 is the FIRST partial (the finish is the last
        # crossing), so both faults land mid-stream by construction.
        "at=collective-finish:0:transient,at=collective-finish:2:transient",
        "seed=11,rate=0.5,seams=collective-finish,max=4",
    ]
    for i, plan in enumerate(plans):
        # Overlap disarms window replay, so the collective retries need
        # an EXPLICIT policy (the legacy retry counter would raise);
        # budget 4 covers the seeded plan's max=4 consecutive fires.
        cfg = Config(chunk_bytes=512, table_capacity=2048,
                     inflight_groups=2, merge_overlap=True,
                     fault_plan=plan,
                     failure_policy={"transient_retries": 4})
        led = str(tmp_path / f"ov_{i}.jsonl")
        with obs.Telemetry.create(ledger_path=led) as tel:
            chaos = executor.count_file(path, cfg, mesh=data_mesh(2),
                                        retry=0, telemetry=tel)
        assert chaos.as_dict() == base.as_dict(), f"plan {plan!r} diverged"
        assert chaos.total == base.total
        colls = list(obs.read_ledger(led, kind="collective"))
        n_partial = sum(1 for c in colls if c["op"] == "partial")
        assert n_partial >= 2 and colls[-1]["op"] == "finish", colls
        hits = [f for f in obs.read_ledger(led, kind="fault")
                if f["seam"] == "collective-finish"]
        assert hits and all(f["injected"] for f in hits), (plan, hits)
        # At least one fault struck a PARTIAL crossing: crossing indices
        # below the partial count belong to partials, not the finish.
        assert min(f["index"] for f in hits) < n_partial, (plan, hits)
        end = next(iter(obs.read_ledger(led, kind="run_end")))
        assert end["pipeline"]["partial_merges"] == n_partial


@pytest.mark.slow
def test_chaos_grep_ngram_identity(tmp_path, rng):
    """The certification holds across families: streamed grep and ngram
    under a seeded plan match their fault-free runs bit-for-bit."""
    from mapreduce_tpu.models import grep

    corpus = make_corpus(rng, 2000, 120) + b"\nneedle hay needle stack\n"
    path = _write(tmp_path, corpus)
    plan = "seed=5,rate=0.1,classes=transient"

    base_n = executor.count_file(path, CFG, mesh=data_mesh(2), retry=3,
                                 ngram=2)
    cfg = _chaos_cfg(plan)
    chaos_n = executor.count_file(path, cfg, mesh=data_mesh(2), retry=3,
                                  ngram=2)
    assert chaos_n.as_dict() == base_n.as_dict()
    assert chaos_n.total == base_n.total

    base_g = grep.grep_file(path, b"needle", config=CFG,
                            mesh=data_mesh(2), retry=3)
    chaos_g = grep.grep_file(path, b"needle", config=cfg,
                             mesh=data_mesh(2), retry=3)
    assert base_g.matches >= 2
    assert (chaos_g.matches, chaos_g.lines) \
        == (base_g.matches, base_g.lines)


def test_replay_from_ledger_reproduces_fault_sequence(tmp_path, rng):
    """ISSUE 15 acceptance: a fault plan replayed from its own ledger
    records reproduces the identical fault sequence (and the identical
    results)."""
    corpus = make_corpus(rng, 1500, 100)
    path = _write(tmp_path, corpus)
    led1 = str(tmp_path / "chaotic.jsonl")
    cfg1 = _chaos_cfg("seed=11,rate=0.12,classes=transient")
    with obs.Telemetry.create(ledger_path=led1) as tel:
        r1 = executor.count_file(path, cfg1, mesh=data_mesh(2), retry=4,
                                 telemetry=tel)
    seq1 = faults.fired_sequence(obs.read_ledger(led1))
    assert seq1, "the chaotic run fired nothing; test is vacuous"
    # Rebuild the plan from the run's own ledger and replay.
    replay_plan = faults.FaultPlan.from_ledger(obs.read_ledger(led1))
    led2 = str(tmp_path / "replay.jsonl")
    cfg2 = _chaos_cfg(replay_plan.spec)
    with obs.Telemetry.create(ledger_path=led2) as tel:
        r2 = executor.count_file(path, cfg2, mesh=data_mesh(2), retry=4,
                                 telemetry=tel)
    seq2 = faults.fired_sequence(obs.read_ledger(led2))
    assert seq2 == seq1, (seq1, seq2)
    assert r2.as_dict() == r1.as_dict() and r2.total == r1.total


@pytest.mark.smoke
def test_from_ledger_filters_to_first_run_in_appended_ledger():
    """An append-mode ledger holding TWO chaotic runs: with run_id=None,
    from_ledger and fired_sequence must agree on the FIRST run's events
    only — merging both runs' schedules would replay faults the original
    run never saw."""
    records = [
        {"kind": "fault", "injected": True, "run_id": "runA",
         "seam": "reader-read", "index": 3, "fault_class": "transient"},
        {"kind": "fault", "injected": True, "run_id": "runA",
         "seam": "dispatch", "index": 7, "fault_class": "resource"},
        {"kind": "fault", "injected": True, "run_id": "runB",
         "seam": "h2d", "index": 1, "fault_class": "transient"},
    ]
    plan = faults.FaultPlan.from_ledger(records)
    want = [("reader-read", 3, "transient"), ("dispatch", 7, "resource")]
    assert sorted(plan.events.items()) \
        == sorted([((s, i), c) for s, i, c in want])
    assert faults.fired_sequence(records) == want
    # An explicit run_id selects that run, first or not.
    plan_b = faults.FaultPlan.from_ledger(records, run_id="runB")
    assert sorted(plan_b.events.items()) == [(("h2d", 1), "transient")]


def test_fault_free_run_emits_no_chaos_records(tmp_path, rng):
    """The disabled path: no fault plan -> no fault/degrade records, no
    fault_plan stamp — fault-free ledgers keep their v8 record shapes
    (plus the version bump)."""
    corpus = make_corpus(rng, 1000, 80)
    path = _write(tmp_path, corpus)
    led = str(tmp_path / "run.jsonl")
    with obs.Telemetry.create(ledger_path=led) as tel:
        executor.count_file(path, CFG, mesh=data_mesh(2), retry=1,
                            telemetry=tel)
    assert not list(obs.read_ledger(led, kind="fault"))
    assert not list(obs.read_ledger(led, kind="degrade"))
    start = next(iter(obs.read_ledger(led, kind="run_start")))
    assert "fault_plan" not in start, start
