#!/usr/bin/env python3
"""Watch a run that has not ended: tail a live (possibly sharded) run
ledger and render progress, the bound-so-far, data-health-so-far, and
fleet straggler skew (ISSUE 14 tentpole, half 2).

Every prior obs surface required a FINISHED ledger; this one reads a
ledger while the executor is still appending to it.  The executor's v8
``progress`` heartbeat (wall-clock cadence, flushed per record) carries
the stream cursor, completion fraction, throughput-so-far and the ETA
derived from the byte cursor; around it this tool reconstructs what the
partial record stream already proves:

* **progress** — the last heartbeat: cursor / total bytes, %, GB/s,
  ETA, in-flight depth, groups dispatched vs retired.  A ledger with NO
  progress records (pre-v8, or a heartbeat-less writer) degrades to the
  last step record's cursor — graceful, never an error;
* **bound so far** — the critical-path ``bottleneck`` verdict over the
  ``group`` lifecycle records retired SO FAR (``obs/timeline.py``),
  falling back to the summed step phase deltas when no groups have
  retired yet;
* **data health so far** — the per-group ``data`` counter dicts summed
  into one partial summary and classified by ``obs/datahealth.py``
  (the final per-run ``data`` record wins once it lands);
* **fleet skew so far** — when ``<ledger>.h<p>.jsonl`` shards sit next
  to the file, the per-superstep straggler skew and slowest host from
  ``obs/fleet.py`` over the groups every host has retired so far.

Follow mode polls the file on ``--interval`` until the run completes,
crashes, or ``--max-seconds`` elapses, printing one status line per
change; ``--once`` renders the current state and exits.  Works on a
finished ledger too — the same render, with the run_end facts.

Deliberately jax-free and stdlib-only (the ``obs_report`` contract):
the obs modules load by file path, so a laptop can watch a ledger
rsynced (or NFS-mounted) from the TPU box.  ``--selftest`` runs the
checked-in fixtures against hand arithmetic; wired into
``tools/tier1.sh`` and ``tools/smoke.sh``.

Usage::

    python tools/obswatch.py /path/run.jsonl            # follow
    python tools/obswatch.py /path/run.jsonl --once     # one snapshot
    python tools/obswatch.py /path/run.jsonl --json
    python tools/obswatch.py --selftest
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_OBS_MODS: dict = {}


def _obs_mod(name: str):
    """A jax-free obs module loaded by file path (the obs_report
    pattern); None when unavailable — the watcher drops that section."""
    if name not in _OBS_MODS:
        src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           os.pardir, "mapreduce_tpu", "obs", name + ".py")
        try:
            if os.path.exists(src):
                import importlib.util

                spec = importlib.util.spec_from_file_location(
                    f"_mapreduce_tpu_watch_{name}", src)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                _OBS_MODS[name] = mod
            else:
                import importlib

                _OBS_MODS[name] = importlib.import_module(
                    f"mapreduce_tpu.obs.{name}")
        except Exception:
            _OBS_MODS[name] = False
    return _OBS_MODS[name] or None


def read_ledger(path: str) -> list:
    """Tolerant JSONL read through the ONE canonical reader
    (``obs/ledger.read_ledger``: unparseable lines skip — on a live file
    a half-written last line is EXPECTED; it parses on the next poll).
    A not-yet-created file reads as empty (the watcher keeps polling)."""
    led = _obs_mod("ledger")
    if led is None:
        return []
    try:
        return list(led.read_ledger(path))
    except OSError:
        return []


class _Tail:
    """Incremental main-file reader for follow mode: each poll parses
    only the bytes appended since the last one (complete lines only — a
    torn tail stays unconsumed until its newline lands), so a
    multi-hour tail costs O(new records) per poll instead of re-parsing
    the whole ledger every ``--interval``.  Applies the canonical
    reader's skip rule (unparseable lines are forensics, not errors); a
    truncated/rotated file restarts from byte 0.  Shard files (fleet
    runs) are still re-read per snapshot — they only matter on
    multi-host watches and stay small per host."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.records: list = []

    def poll(self) -> list:
        try:
            with open(self.path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size < self.offset:  # truncation/rotation: restart
                    self.offset, self.records = 0, []
                if size == self.offset:
                    return self.records
                f.seek(self.offset)
                chunk = f.read(size - self.offset)
        except OSError:
            return self.records
        end = chunk.rfind(b"\n")
        if end < 0:
            return self.records
        self.offset += end + 1
        for line in chunk[:end].split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                continue
            if isinstance(rec, dict):
                self.records.append(rec)
        return self.records


#: Per-group `data` dict fields that SUM across retired groups (the
#: counters); `occupancy`/`top_mass` are running gauges — last wins.
_SUM_FIELDS = ("chunks", "overlong", "rescued", "dropped_tokens",
               "dropped_uniques", "rescue_invocations",
               "rescue_escalations", "fallback_chunks", "spill_rows",
               "combiner_hits", "combiner_flushes", "combiner_evicted")


def data_so_far(groups: list) -> dict | None:
    """The partial data summary: per-group counter dicts summed, running
    gauges taken from the last retired group.  None with no data dicts
    (plain-mode runs, pre-v3 ledgers)."""
    dicts = [g.get("data") for g in groups if isinstance(g.get("data"), dict)]
    if not dicts:
        return None
    out: dict = {"groups": len(dicts)}
    for f in _SUM_FIELDS:
        vals = [d.get(f) for d in dicts
                if isinstance(d.get(f), (int, float))]
        if vals:
            out[f] = sum(vals)
    for f in ("occupancy", "top_mass"):
        last = next((d.get(f) for d in reversed(dicts)
                     if d.get(f) is not None), None)
        if last is not None:
            out["table_occupancy" if f == "occupancy" else f] = last
    return out


def _num(v):
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def snapshot(ledger_path: str, run_id: str | None = None,
             records: list | None = None) -> dict | None:
    """The current state of (by default) the LAST run instance in the
    ledger — on a live file, the run being written right now.  None when
    the file holds no records yet (the watcher keeps polling).
    ``records`` lets follow mode pass the incrementally tailed stream
    (:class:`_Tail`) instead of re-reading the file."""
    hist = _obs_mod("history")
    tl = _obs_mod("timeline")
    dh = _obs_mod("datahealth")
    fl = _obs_mod("fleet")
    if records is None:
        records = read_ledger(ledger_path)
    if hist is None:
        return None
    runs = hist.split_instances(records)
    if run_id is not None:
        runs = [r for r in runs if r[0] == run_id]
    if not runs:
        return None
    rid, instance, recs = runs[-1]
    start = next((r for r in recs if r.get("kind") == "run_start"), None)
    end = next((r for r in recs if r.get("kind") == "run_end"), None)
    failures = [r for r in recs if r.get("kind") == "failure"]
    # The one completed/crashed/in-flight rule (fleet.run_status).
    status = fl.run_status(end is not None, len(failures)) \
        if fl is not None else "completed" if end is not None \
        else ("crashed" if failures else "in-flight")
    steps = [r for r in recs if r.get("kind") == "step"]
    groups = [r for r in recs if r.get("kind") == "group"]
    progress = next((r for r in reversed(recs)
                     if r.get("kind") == "progress"), None)

    # Progress: the heartbeat when one landed; else degrade to the last
    # step record's cursor (pre-v8 ledgers still watchable).
    cursor = total = frac = gbps = eta = depth = None
    if progress is not None:
        cursor = _num(progress.get("cursor_bytes"))
        total = _num(progress.get("total_bytes"))
        frac = _num(progress.get("frac"))
        gbps = _num(progress.get("gb_per_s"))
        eta = _num(progress.get("eta_s"))
        depth = _num(progress.get("inflight_depth"))
    elif steps:
        cursor = _num(steps[-1].get("cursor_bytes"))
    if end is not None:
        gbps = _num(end.get("gb_per_s")) or gbps
        frac, eta = 1.0, 0.0
        cursor = _num(end.get("bytes")) or cursor

    # Bound so far: the measured timeline over retired groups; phase
    # deltas as the fallback, through the ONE phase->lane rule table
    # (timeline.PHASE_LANE — the tuner reads the same one).
    art = tl.reconstruct(recs, run_id=rid) if tl is not None else None
    bound = source = None
    if art is not None:
        bound, source = art["bottleneck"]["resource"], "timeline"
    elif tl is not None:
        phases: dict = {}
        src = (end or {}).get("phases") if end else None
        for r in ([{"phases": src}] if src else steps):
            for k, v in (r.get("phases") or {}).items():
                if _num(v) is not None:
                    phases[k] = phases.get(k, 0.0) + float(v)
        shares: dict = {}
        for ph, lane in tl.PHASE_LANE.items():
            if phases.get(ph):
                shares[lane] = shares.get(lane, 0.0) + phases[ph]
        if shares:
            bound = max(shares, key=lambda ln: shares[ln])
            source = "phases"

    # Data health so far: the run's own `data` record once it lands,
    # else the per-group counters summed.
    data = next((r for r in recs if r.get("kind") == "data"), None)
    partial = data is None
    if data is None:
        data = data_so_far(groups)
    health = None
    if data is not None and dh is not None:
        health = dh.classify({k: v for k, v in data.items()
                              if k not in ("ts", "run_id", "kind")})

    # Fleet skew so far: shards next to the file, merged over whatever
    # every host has retired up to now.
    fleet = None
    if fl is not None:
        try:
            paths = fl.shard_paths(ledger_path)
            if paths:
                by_host = {h: fl.read_jsonl(p) for h, p in paths.items()}
                view = fl.fleet_view(by_host, rid)
                if view is not None:
                    fleet = {
                        "hosts": view["hosts"],
                        "total_skew_s":
                            view["straggler"]["total_skew_s"],
                        "supersteps": view["straggler"]["supersteps"],
                        "slowest_host":
                            view["straggler"]["slowest_host"],
                        "verdict":
                            view["fleet_bottleneck"]["verdict"],
                    }
        except Exception:
            fleet = None  # a torn shard mid-write: next poll
    return {
        "run_id": rid,
        "instance": instance,
        "status": status,
        "header": {k: (start or {}).get(k) for k in
                   ("job", "driver", "backend", "devices", "map_impl",
                    "combiner", "geometry", "ledger_version")},
        "steps": sum(int(_num(r.get("steps")) or 1) for r in steps),
        "groups_retired": len(groups),
        "cursor_bytes": int(cursor) if cursor is not None else None,
        "total_bytes": int(total) if total is not None else None,
        "frac": frac,
        "gb_per_s": gbps,
        "eta_s": eta,
        "inflight_depth": int(depth) if depth is not None else None,
        "heartbeat": progress is not None,
        "bound": bound,
        "bound_source": source,
        "bottleneck": (art or {}).get("bottleneck"),
        "data_so_far": data,
        "data_partial": partial,
        "data_health": health,
        "fleet": fleet,
    }


def _mib(n) -> str:
    return f"{n / (1 << 20):.1f} MiB" if isinstance(n, (int, float)) else "?"


def status_line(s: dict) -> str:
    """The one-line follow-mode form."""
    parts = [s["status"]]
    if s.get("frac") is not None:
        parts.append(f"{100 * s['frac']:.1f}%")
    elif s.get("cursor_bytes") is not None:
        parts.append(_mib(s["cursor_bytes"]))
    if s.get("gb_per_s") is not None:
        parts.append(f"{s['gb_per_s']:.4f} GB/s")
    if s.get("eta_s") is not None and s["status"] == "in-flight":
        parts.append(f"ETA {s['eta_s']:.1f}s")
    if s.get("inflight_depth") is not None:
        parts.append(f"inflight {s['inflight_depth']}")
    if s.get("bound"):
        parts.append(f"bound {s['bound']}")
    if s.get("data_health"):
        parts.append(f"data {s['data_health']['verdict']}")
    return "  ".join(parts)


def render(s: dict, out) -> None:
    h = s["header"]
    out.write(f"watch {s['run_id']}  "
              f"[{h.get('driver', '?')}/{h.get('job', '?')}  "
              f"backend={h.get('backend', '?')}  "
              f"map={h.get('map_impl', '?')}]  {s['status'].upper()}\n")
    out.write(f"  progress: {_mib(s['cursor_bytes'])}")
    if s.get("total_bytes"):
        out.write(f" / {_mib(s['total_bytes'])}")
    if s.get("frac") is not None:
        out.write(f" ({100 * s['frac']:.1f}%)")
    if s.get("gb_per_s") is not None:
        out.write(f"  {s['gb_per_s']:.4f} GB/s")
    if s.get("eta_s") is not None and s["status"] == "in-flight":
        out.write(f"  ETA {s['eta_s']:.1f}s")
    if s.get("inflight_depth") is not None:
        out.write(f"  inflight {s['inflight_depth']}")
    out.write(f"  ({s['steps']} steps, {s['groups_retired']} groups"
              + ("" if s["heartbeat"] else "; no progress records — "
                 "cursor from step records") + ")\n")
    if s.get("bound"):
        out.write(f"  bound so far: {s['bound']} "
                  f"(from {s['bound_source']})\n")
    if s.get("data_health"):
        tag = " (partial: per-group counters)" if s["data_partial"] else ""
        out.write(f"  data health so far: "
                  f"{s['data_health']['verdict']}{tag}\n")
        for f in s["data_health"].get("flags", []):
            out.write(f"    {f['flag']}: {f['detail']}\n")
    fl = s.get("fleet")
    if fl:
        out.write(f"  fleet so far: {len(fl['hosts'])} hosts, skew "
                  f"{fl['total_skew_s']:.3f}s over {fl['supersteps']} "
                  f"supersteps (slowest host {fl['slowest_host']}), "
                  f"verdict {fl['verdict']}\n")


def follow(ledger_path: str, run_id: str | None, interval_s: float,
           max_seconds: float, out) -> int:
    """Poll until the watched run completes/crashes or the budget runs
    out.  One line per observed change; the full block at the end."""
    deadline = time.monotonic() + max_seconds
    last_line = None
    s = None
    tail = _Tail(ledger_path)
    while time.monotonic() < deadline:
        s = snapshot(ledger_path, run_id, records=tail.poll())
        if s is not None:
            line = status_line(s)
            if line != last_line:
                out.write(f"[{time.strftime('%H:%M:%S')}] {line}\n")
                out.flush()
                last_line = line
            if s["status"] != "in-flight":
                break
        time.sleep(interval_s)
    if s is None:
        print(f"no records in {ledger_path} within {max_seconds:.0f}s",
              file=sys.stderr)
        return 1
    render(s, out)
    return 0


# -- selftest ----------------------------------------------------------------

def _fixture_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


def selftest() -> int:
    """Snapshot the checked-in fixtures and assert the hand arithmetic:
    the in-flight heartbeat math, the bound/data-so-far reconstruction,
    the growing-file replay, graceful pre-v8 degrade, fleet skew, and
    future-ledger flow-through."""
    import io
    import shutil
    import tempfile

    fdir = _fixture_dir()
    # In-flight run with heartbeats (watch_ledger.jsonl): 48 MiB of
    # 128 MiB at 16 MiB/s -> 37.5%, ETA 5.0 s; 3 groups dispatched, 2
    # retired; the two retired groups' data dicts sum to fallback 2 of 4
    # chunks -> spill-bound so far; the group timeline is device-bound.
    s = snapshot(os.path.join(fdir, "watch_ledger.jsonl"))
    assert s is not None and s["status"] == "in-flight", s
    assert s["heartbeat"] and s["frac"] == 0.375, s
    assert s["eta_s"] == 5.0 and s["gb_per_s"] == 0.016777, s
    assert s["cursor_bytes"] == 50331648, s
    assert s["total_bytes"] == 134217728, s
    assert s["bound"] == "device" and s["bound_source"] == "timeline", s
    assert s["data_partial"] is True
    assert s["data_so_far"]["fallback_chunks"] == 2, s["data_so_far"]
    assert s["data_so_far"]["chunks"] == 4, s["data_so_far"]
    assert s["data_health"]["verdict"] == "spill-bound", s["data_health"]
    buf = io.StringIO()
    render(s, buf)
    body = buf.getvalue()
    assert "IN-FLIGHT" in body and "(37.5%)" in body, body
    assert "ETA 5.0s" in body and "bound so far: device" in body, body
    assert "data health so far: spill-bound (partial" in body, body
    line = status_line(s)
    assert "37.5%" in line and "ETA 5.0s" in line, line

    # Growing-file replay: append the fixture line by line (exactly what
    # a tailer sees while the executor flushes) — the cursor must be
    # monotone, a torn half-line must parse on the next poll, and the
    # status must stay in-flight throughout.
    d = tempfile.mkdtemp(prefix="obswatch_selftest_")
    try:
        live = os.path.join(d, "live.jsonl")
        lines = open(os.path.join(fdir, "watch_ledger.jsonl"),
                     encoding="utf-8").read().splitlines()
        cursors = []
        tail = _Tail(live)  # the follow-mode incremental reader
        with open(live, "w", encoding="utf-8") as f:
            for i, ln in enumerate(lines):
                f.write(ln[:10])  # torn prefix: the reader must skip it
                f.flush()
                mid = snapshot(live)
                # The incremental tail must never consume a torn line.
                assert len(tail.poll()) == i, (i, len(tail.records))
                f.write(ln[10:] + "\n")
                f.flush()
                g = snapshot(live)
                if g is not None and g.get("cursor_bytes") is not None:
                    cursors.append(g["cursor_bytes"])
                if i and mid is not None:
                    assert mid["status"] == "in-flight", mid
        assert cursors == sorted(cursors) and cursors, cursors
        assert cursors[-1] == 50331648, cursors
        # The tail converges on exactly the full-read record stream, and
        # a snapshot over it matches the full-read snapshot.
        assert tail.poll() == read_ledger(live)
        ts = snapshot(live, records=tail.poll())
        assert ts["cursor_bytes"] == 50331648 and ts["frac"] == 0.375, ts
    finally:
        shutil.rmtree(d, ignore_errors=True)

    # A finished ledger renders the same way (the selector picks the
    # LAST instance — mini_ledger's is the in-flight fixture10 — and
    # --run-id picks a finished one).
    mini = os.path.join(fdir, "mini_ledger.jsonl")
    tail = snapshot(mini)
    assert tail["run_id"] == "fixture10", tail
    assert tail["status"] == "in-flight" and tail["frac"] == 0.5, tail
    done = snapshot(mini, run_id="fixture05")
    assert done["status"] == "completed" and done["frac"] == 1.0, done
    assert done["data_partial"] is False
    assert done["data_health"]["verdict"] == "spill-bound", done
    # Pre-v8 graceful degrade: fixture01 predates progress records AND
    # group records — cursor falls back to the step records, bound to
    # the phase deltas.
    old = snapshot(mini, run_id="fixture01")
    assert old["heartbeat"] is False, old
    assert old["cursor_bytes"] == 6 * 4 * (1 << 20), old
    assert old["bound_source"] == "phases" and old["bound"] == "device", old
    obuf = io.StringIO()
    render(old, obuf)
    assert "no progress records" in obuf.getvalue(), obuf.getvalue()

    # Fleet skew so far: the two-host shard fixtures next to
    # fleet_ledger.jsonl — 2.0 s of skew over 3 supersteps, host 1
    # slowest, straggler-bound (the fleet selftest's hand numbers).
    fs = snapshot(os.path.join(fdir, "fleet_ledger.jsonl"))
    assert fs["fleet"] is not None, fs
    assert fs["fleet"]["total_skew_s"] == 2.0, fs["fleet"]
    assert fs["fleet"]["slowest_host"] == 1, fs["fleet"]
    assert fs["fleet"]["verdict"] == "straggler-bound", fs["fleet"]
    fbuf = io.StringIO()
    render(fs, fbuf)
    assert "fleet so far: 2 hosts, skew 2.000s" in fbuf.getvalue()

    # Forward compat: the future ledger (v99, future-shaped progress
    # record with unknown fields) snapshots and renders without error.
    fut = snapshot(os.path.join(fdir, "future_ledger.jsonl"))
    assert fut["status"] == "completed" and fut["heartbeat"], fut
    render(fut, io.StringIO())

    print("obswatch selftest ok (in-flight 37.5% ETA 5.0s, device-bound, "
          "spill-bound-so-far from per-group counters, growing-file "
          "replay monotone, pre-v8 degrade, fleet skew 2.0s, "
          "future-ledger ok)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="watch a live (or finished) mapreduce_tpu run ledger")
    ap.add_argument("ledger", nargs="?", help="JSONL run-ledger path "
                    "(shards <ledger>.h*.jsonl are discovered)")
    ap.add_argument("--run-id", default=None,
                    help="watch this run instead of the last instance")
    ap.add_argument("--once", action="store_true",
                    help="render one snapshot and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable snapshot (implies "
                         "--once)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="follow-mode poll seconds (default 2)")
    ap.add_argument("--max-seconds", type=float, default=3600.0,
                    help="follow-mode budget (default 1h)")
    ap.add_argument("--selftest", action="store_true",
                    help="run against the checked-in fixtures and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.ledger:
        ap.error("a ledger path (or --selftest) is required")
    if args.json or args.once:
        s = snapshot(args.ledger, args.run_id)
        if s is None:
            print(f"no records in {args.ledger}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(s, sort_keys=True))
        else:
            render(s, sys.stdout)
        return 0
    return follow(args.ledger, args.run_id, args.interval,
                  args.max_seconds, sys.stdout)


if __name__ == "__main__":
    sys.exit(main())
