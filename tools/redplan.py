#!/usr/bin/env python3
"""Static reduction-strategy planner over the mesh link model (ISSUE 16).

The merge strategy (``Engine(merge_strategy=...)``: tree / gather /
keyrange) has been a hand-picked knob since the collectives landed.
This driver makes it a PLANNED one — the geomsearch discipline applied
to the reduction seam:

1. **Enumerate + price + rank** (default; jax-free): every feasible
   reduction strategy for a fleet shape (``--processes`` x
   ``--local-devices``, ``--capacity`` table rows), priced through the
   alpha-beta link hierarchy in ``mapreduce_tpu/analysis/meshcost.py``
   (ICI within a host, DCN across — rates from the checked-in
   ``analysis/baselines/measured_link_rates.json``), printed as one
   ranked JSON artifact.  ``--ledger`` seeds the plan from a real run
   instead: topology + incumbent strategy from its ``run_start``,
   measured key distribution (``top_mass`` derates keyrange past the
   skew-hot threshold, ``table_occupancy`` feeds the budget-spill
   check) via ``obs/history.resolve_prior``, and the PR-13
   ``fleet_bottleneck`` verdict attached so a straggler-bound fleet is
   never told to chase collective strategy first.
2. ``--gate``: certify each ranked strategy through the graphcheck
   pipeline over a fleet-twin WordCountJob (``analysis_fleet`` +
   ``analysis_merge_strategy`` — the registry-twin mechanism), the
   collective-cost pass pricing the very program the strategy builds.
   Traces on the host; no device.
3. ``--check``: modeled-vs-measured honesty — the fleet ledger's
   measured collective seconds (``obs/fleet.fleet_view``) against the
   model's price for the SAME strategy/topology/capacity, flagged (and
   exit 1) when they disagree by more than ``CHECK_RATIO``x in either
   direction.  A flagged check means the link-rate fixture does not
   describe the hardware the ledger ran on (the checked-in CPU fixture
   flags by construction — that IS the mechanism proof the selftest
   pins).

``--out tuned.json`` writes the winner as a ``tuned.json`` profile
(key ``wordcount-redplan/static/<mesh>-cap<capacity>``) next to the
autotune/geomsearch profiles, so a launcher can warm-start
``merge_strategy`` the way ``--geometry auto`` warm-starts geometry.

Usage::

    python tools/redplan.py --processes 2 --local-devices 4 \
        --capacity 32768 --top-mass 0.3
    python tools/redplan.py --ledger runs/fleet.jsonl      # measured prior
    python tools/redplan.py --gate                         # graphcheck gate
    python tools/redplan.py --check --ledger runs/fleet.jsonl
    python tools/redplan.py --selftest                     # jax-free

``--selftest`` (wired into ``tools/tier1.sh`` and ``tools/smoke.sh``
alongside the geomsearch/fleet/chaos selftests) asserts the jax-free
half against hand arithmetic: the ring-vs-tree crossover closed form
(``M* = 8 alpha beta`` at D=4 — 3.6 MB on the measured ICI rates), the
planner's ranking at the fixture shapes, keyrange's skew derating and
budget-row formula (pinned to ``key_range_merge``'s docstring
arithmetic), and the whole ledger path over the checked-in Zipf fleet
fixture: prior resolution, the straggler-bound verdict riding the
artifact, the incumbent tree strategy ranked top, and the --check flag
firing on the (deliberately disagreeing) fixture.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
FLEET_FIXTURE = os.path.join(FIXTURES, "redplan_fleet.jsonl")

#: Modeled-vs-measured disagreement past which --check flags (either
#: direction): the model is a congestion-free bound, so 2x headroom is
#: honest slack; beyond it the link-rate fixture and the hardware the
#: ledger ran on are different machines.
CHECK_RATIO = 2.0


def _load_by_path(modname: str, relpath: str):
    """Import a repo module WITHOUT executing its package __init__ (which
    pulls jax): reuse the already-imported package module when present
    (pytest, --gate), else load by file path under a private name —
    registered in sys.modules BEFORE exec (dataclass creation resolves
    the defining module through sys.modules)."""
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    path = os.path.join(REPO, *relpath.split("/"))
    spec = importlib.util.spec_from_file_location(
        "_redplan_" + modname.rsplit(".", 1)[-1], path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_meshcost():
    return _load_by_path("mapreduce_tpu.analysis.meshcost",
                         "mapreduce_tpu/analysis/meshcost.py")


def _load_fleet():
    return _load_by_path("mapreduce_tpu.obs.fleet",
                         "mapreduce_tpu/obs/fleet.py")


def _load_history():
    return _load_by_path("mapreduce_tpu.obs.history",
                         "mapreduce_tpu/obs/history.py")


# -- the measured prior: one fleet ledger -> planner inputs ------------------

def ledger_prior(ledger_path: str) -> dict:
    """A fleet ledger (sharded ``<path>.h<p>.jsonl`` or single-file) ->
    the planner's measured inputs: topology + incumbent strategy from
    ``run_start``, key distribution from the latest ``data`` record
    (``obs/history.resolve_prior`` — the ONE prior-run read), measured
    collective seconds + the ``fleet_bottleneck`` verdict from
    ``obs/fleet.fleet_view``."""
    fleet = _load_fleet()
    history = _load_history()
    paths = fleet.shard_paths(ledger_path)
    if paths:
        by_host = fleet.load_shards(paths[h] for h in sorted(paths))
    elif os.path.exists(ledger_path):
        by_host = {0: fleet.read_jsonl(ledger_path)}
    else:
        raise FileNotFoundError(
            f"no ledger at {ledger_path} (and no {ledger_path}.h*.jsonl "
            "shards next to it)")
    merged = [r for h in sorted(by_host) for r in by_host[h]]
    prior = history.resolve_prior(records=merged)
    start = next((r for r in merged if r.get("kind") == "run_start"), {})
    view = fleet.fleet_view(by_host) or {}
    data = prior.get("data_record") or {}
    bottleneck = view.get("fleet_bottleneck") or {}
    collective = view.get("collective") or {}
    return {
        "ledger": ledger_path,
        "run_id": start.get("run_id"),
        "processes": int(start.get("processes", len(by_host) or 1)),
        "local_devices": int(start.get("local_devices", 1)),
        "incumbent": start.get("merge_strategy"),
        "capacity": data.get("capacity"),
        "top_mass": data.get("top_mass"),
        "table_occupancy": data.get("table_occupancy"),
        "combiner_prior": prior.get("combiner"),
        "measured_collective_s": collective.get("mean_s"),
        "fleet_verdict": bottleneck.get("verdict"),
        "fleet_bottleneck": bottleneck,
    }


def build_plan(args, mc) -> dict:
    """CLI args (+ optional ledger prior) -> the ranked plan artifact.
    Explicit flags win over the ledger; the ledger fills the gaps."""
    prior = ledger_prior(args.ledger) if args.ledger else {}

    def pick(flag, key, default=None):
        return flag if flag is not None else prior.get(key, default) \
            if prior.get(key) is not None else default

    processes = int(pick(args.processes, "processes", 2))
    local_devices = int(pick(args.local_devices, "local_devices", 4))
    capacity = int(pick(args.capacity, "capacity", 8192))
    art = mc.plan(processes, local_devices, capacity,
                  top_mass=pick(args.top_mass, "top_mass"),
                  table_occupancy=pick(args.occupancy, "table_occupancy"),
                  incumbent=pick(args.incumbent, "incumbent"))
    if prior:
        art["prior"] = {k: prior[k] for k in
                        ("ledger", "run_id", "incumbent", "top_mass",
                         "table_occupancy", "combiner_prior",
                         "measured_collective_s", "fleet_verdict")}
        verdict = prior.get("fleet_verdict")
        if verdict and verdict != "collective-bound":
            art["note"] = (
                f"fleet verdict is {verdict!r}: the measured bottleneck is "
                "NOT the finish collective — the ranking below is the "
                "right strategy for the reduce seam, but fix the "
                "bottleneck the verdict names first")
    return art


# -- --check: modeled vs measured over a real fleet ledger -------------------

def check_disagreement(measured_s, modeled_s, ratio=CHECK_RATIO) -> dict:
    """The one --check rule, pure: measured/modeled outside
    [1/ratio, ratio] flags.  Kept separate so the selftest pins the
    mechanics without a ledger."""
    if not measured_s or not modeled_s or modeled_s <= 0:
        return {"measured_s": measured_s, "modeled_s": modeled_s,
                "ratio": None, "flag": False,
                "why": "no measured collective seconds to compare"}
    r = measured_s / modeled_s
    return {"measured_s": round(measured_s, 9),
            "modeled_s": round(modeled_s, 9),
            "ratio": round(r, 3), "flag": r > ratio or r < 1.0 / ratio}


def run_check(args, mc) -> int:
    if not args.ledger:
        print("redplan --check needs --ledger (measured collective seconds "
              "come from a fleet ledger)", file=sys.stderr)
        return 2
    prior = ledger_prior(args.ledger)
    strategy = prior.get("incumbent")
    if strategy not in mc.STRATEGIES:
        print(f"redplan --check: ledger merge_strategy {strategy!r} has no "
              "model; pricing the tree schedule instead", file=sys.stderr)
        strategy = "tree"
    rates = mc.load_link_rates()
    capacity = int(prior.get("capacity") or 8192)
    processes = int(prior.get("processes") or 1)
    local_devices = int(prior.get("local_devices") or 1)
    mesh = mc.MeshSpec.fleet(processes, local_devices) if processes > 1 \
        else mc.MeshSpec.single_host(local_devices)
    priced = mc.price_strategy(strategy, mc.table_bytes(capacity), mesh,
                               rates["levels"],
                               slack=rates["keyrange_slack"])
    res = check_disagreement(prior.get("measured_collective_s"),
                             priced["modeled_s"])
    art = {"check": res, "strategy": strategy,
           "mesh": {"processes": processes, "local_devices": local_devices,
                    "label": mesh.label()},
           "capacity": capacity, "run_id": prior.get("run_id"),
           "fleet_verdict": prior.get("fleet_verdict"),
           "check_ratio": CHECK_RATIO}
    if res["flag"]:
        art["why"] = (
            f"measured finish collective ({res['measured_s']}s mean) is "
            f"{res['ratio']}x the alpha-beta model ({res['modeled_s']}s) "
            f"for {strategy!r} over {mesh.label()}: "
            "analysis/baselines/measured_link_rates.json does not describe "
            "the links this ledger ran on — remeasure the rates (or stop "
            "trusting the plan on this hardware)")
    print(json.dumps(art, indent=1))
    return 1 if res["flag"] else 0


# -- --gate: graphcheck certification of each ranked strategy ----------------

def gate_strategies(art, log) -> list:
    """Certify each ranked strategy through the graphcheck pipeline over
    a fleet-twin WordCountJob at the planned topology — the registry-twin
    mechanism (``analysis_fleet`` + ``analysis_merge_strategy``), so the
    collective-cost pass prices the very finish program each strategy
    builds.  The baseline-keyed passes (hbm-cost, fusion-opportunity)
    stay out — ad-hoc twins have no checked-in baselines (the
    geomsearch gate discipline).  Returns the zero-error strategies."""
    from mapreduce_tpu import analysis
    from mapreduce_tpu.models import ANALYSIS_CONFIG
    from mapreduce_tpu.models.wordcount import WordCountJob

    passes = [p for p in analysis.default_pipeline()
              if p.pass_id not in ("hbm-cost", "fusion-opportunity")]
    mesh = art["mesh"]
    kept = []
    for ranked in art["ranked"]:
        name = ranked["strategy"]
        job = WordCountJob(ANALYSIS_CONFIG)
        job.analysis_fleet = {"processes": mesh["processes"],
                              "local_devices": mesh["local_devices"]}
        job.analysis_merge_strategy = name
        report = analysis.analyze_job(job, f"<redplan:{name}>",
                                      passes=passes)
        if report.errors:
            log(f"gate REJECTED {name} over {mesh['label']}:\n"
                + report.format_text("error"))
            continue
        log(f"gate ok: {name} over {mesh['label']} "
            f"(modeled {ranked['modeled_s'] * 1e6:.1f}us)")
        kept.append(name)
    return kept


# -- profile output ----------------------------------------------------------

def write_profile(art, out_path: str, log) -> str:
    """The planner's winner as a tuned.json profile (autotune's merge-
    one-key writer), so ``merge_strategy`` can warm-start from the plan
    like geometry warm-starts from geomsearch."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import autotune
    finally:
        sys.path.pop(0)
    key = (f"wordcount-redplan/static/{art['mesh']['label']}"
           f"-cap{art['capacity']}")
    entry = {"config": {"merge_strategy": art["top"]},
             "modeled_s": art["ranked"][0]["modeled_s"],
             "stopped": "planned",
             "mesh": art["mesh"],
             "ranked": [{"strategy": r["strategy"],
                         "modeled_s": r["modeled_s"]}
                        for r in art["ranked"]],
             "fleet_verdict": (art.get("prior") or {}).get("fleet_verdict"),
             "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())}
    autotune.write_profile(out_path, key, entry)
    log(f"winner {art['top']} (modeled "
        f"{art['ranked'][0]['modeled_s'] * 1e6:.1f}us) -> {out_path} "
        f"[{key}]")
    return key


# -- selftest (jax-free) -----------------------------------------------------

def selftest() -> int:
    """The jax-free planner end to end, against hand arithmetic and the
    checked-in Zipf fleet fixture — the tier-1/smoke gate."""
    import math

    had_jax = "jax" in sys.modules
    mc = _load_meshcost()

    # The measured link fixture: three levels, strictly slower outward.
    rates = mc.load_link_rates()
    levels, slack = rates["levels"], rates["keyrange_slack"]
    assert set(levels) == {"hbm", "ici", "dcn"}, sorted(levels)
    assert levels["hbm"].beta_bps > levels["ici"].beta_bps \
        > levels["dcn"].beta_bps
    assert levels["hbm"].alpha_s < levels["ici"].alpha_s \
        < levels["dcn"].alpha_s
    assert slack == 2.0, slack

    # Ring-vs-tree crossover, closed form vs hand arithmetic: at D=4 the
    # formula reduces to M* = alpha*beta*(6-2)/(2-3/2) = 8*alpha*beta —
    # 3.6 MB on the measured ICI rates (alpha 10us, beta 45 GB/s) — and
    # the two schedules price EQUAL there: ring = 6a + (3/2)M/b,
    # tree = 2a + 2M/b, both 180us.
    ici = levels["ici"]
    mstar = mc.ring_tree_crossover_bytes(4, ici)
    assert math.isclose(mstar, 8 * ici.alpha_s * ici.beta_bps), mstar
    assert math.isclose(mstar, 3.6e6), mstar
    ring_at = mc.allreduce_ring(mstar, 4, ici)
    tree_at = mc.allreduce_tree(mstar, 4, ici)
    assert math.isclose(ring_at, tree_at), (ring_at, tree_at)
    assert math.isclose(ring_at, 1.8e-4), ring_at
    # Below M* the butterfly's 2 rounds beat the ring's 6; above, the
    # ring's 1.5x byte factor beats the butterfly's 2x.
    assert mc.allreduce_tree(mstar / 4, 4, ici) \
        < mc.allreduce_ring(mstar / 4, 4, ici)
    assert mc.allreduce_ring(4 * mstar, 4, ici) \
        < mc.allreduce_tree(4 * mstar, 4, ici)
    assert mc.ring_tree_crossover_bytes(2, ici) == math.inf

    # Schedule units at D=2: one round of M for tree AND gather (they
    # coincide — the planner's ranking there is byte-for-byte honest).
    m = mc.table_bytes(8192)
    assert m == 7 * 4 * 8192 == 229376
    assert math.isclose(mc.allreduce_tree(m, 2, ici),
                        mc.allgather(m, 2, ici))

    # keyrange budget rows == key_range_merge's docstring formula
    # (B = min(cap, ceil(s*cap/D) + 8 + 4*ceil(log2 D))), pinned so the
    # planner's spill arithmetic can never drift from the runtime.
    for cap, d in ((8192, 8), (32768, 8), (512, 4), (8192, 1)):
        want = cap if d <= 1 else min(
            cap, -(-int(slack * cap) // d) + 8 + 4 * (d - 1).bit_length())
        got = mc.keyrange_budget_rows(cap, d, slack)
        assert got == want, (cap, d, got, want)
    # ceil(2*8192/8) + 8 + 4*bitlen(7) = 2048 + 8 + 12 by hand.
    assert mc.keyrange_budget_rows(8192, 8, 2.0) == 2068

    # Planner ranking at 2x4 / cap 8192 (229 KB payload): latency-bound,
    # so gather's single round per level edges out tree and keyrange
    # pays double DCN traffic — the hand-priced table.
    p = mc.plan(2, 4, 8192)
    order = [r["strategy"] for r in p["ranked"]]
    assert order == ["gather", "tree", "hier-tree-tree", "hier-kr-tree",
                     "keyrange"], order
    by = {r["strategy"]: r["modeled_s"] for r in p["ranked"]}
    assert math.isclose(by["gather"], 0.000217042, rel_tol=1e-6), by
    assert math.isclose(by["tree"], 0.000221945, rel_tol=1e-6), by
    assert math.isclose(by["keyrange"], 0.000567002, rel_tol=1e-6), by
    # hier-tree-tree prices identically to tree (same schedule, named
    # placement); declaration order keeps the incumbent ahead on the tie.
    assert by["hier-tree-tree"] == by["tree"]
    # hier-kr-tree = keyrange over the 4-wide ICI axis (dense per-owner
    # sub-tables) + one tree round over the 2-wide DCN axis.
    m8k = mc.table_bytes(8192)
    assert math.isclose(
        by["hier-kr-tree"],
        mc.keyrange(m8k, 4, levels["ici"], slack=slack)
        + mc.allreduce_tree(m8k, 2, levels["dcn"]),
        rel_tol=1e-5), by  # plan() rounds modeled_s to 9 digits
    assert p["mesh"]["label"] == "2dx4i" and p["payload_bytes"] == 229376

    # At 4x the capacity the tree's log2(D) rounds beat gather's (D-1)
    # bytes on the ICI level (crossover arithmetic again), and measured
    # Zipf skew (top_mass 0.3 > the 0.05 hot threshold) derates keyrange
    # by exactly 1.3x.
    p = mc.plan(2, 4, 32768, top_mass=0.3, table_occupancy=0.85,
                incumbent="tree")
    order = [r["strategy"] for r in p["ranked"]]
    assert order == ["tree", "hier-tree-tree", "gather", "hier-kr-tree",
                     "keyrange"], order
    by = {r["strategy"]: r for r in p["ranked"]}
    assert math.isclose(by["tree"]["modeled_s"], 0.00052778,
                        rel_tol=1e-6), by["tree"]
    assert p["incumbent_is_top"] is True
    kr = by["keyrange"]
    base = mc.keyrange(mc.table_bytes(32768), 8, levels["dcn"], slack=slack)
    assert math.isclose(kr["modeled_s"], base * 1.3, rel_tol=1e-6), kr
    assert any("skew derating" in n for n in kr["notes"]), kr["notes"]
    # No keyrange hook -> the strategy is skipped, never silently priced
    # (hier-kr-tree's inner leg is the same hook).
    p8 = mc.plan(8, 1, 8192, has_keyrange_hook=False)
    assert [s["strategy"] for s in p8["skipped"]] \
        == ["keyrange", "hier-kr-tree"]
    assert all(r["strategy"] != "keyrange" for r in p8["ranked"])
    # A single-host mesh has one link level: nothing to place over, so
    # both hierarchical compositions are skipped with the mesh reason.
    p1 = mc.plan(1, 8, 8192)
    assert [s["strategy"] for s in p1["skipped"]] \
        == ["hier-kr-tree", "hier-tree-tree"]

    # Strategy descriptors name the exact runtime builders (the pytest
    # suite asserts the full bijection against parallel/collectives.py;
    # here just the jax-free half).
    assert set(mc.STRATEGIES) == {"tree", "gather", "keyrange",
                                  "hier-kr-tree", "hier-tree-tree"}
    assert mc.STRATEGIES["hier-kr-tree"].needs_keyrange_hook
    assert mc.STRATEGIES["tree"].builder.endswith("collectives.tree_merge")
    assert mc.STRATEGIES["tree"].power_of_two_only
    assert mc.STRATEGIES["keyrange"].needs_keyrange_hook

    # The whole ledger path over the checked-in Zipf fleet fixture:
    # prior resolution (topology 2x4, cap 32768, top_mass 0.30 -> the
    # hot-cache combiner prior), the PR-13 straggler-bound verdict, and
    # the plan built FROM it — incumbent tree ranked top, which is
    # exactly what the verdict implies: the fleet's bottleneck is the
    # 2.0s host skew, not the 0.3s collective, so the planner must not
    # propose a strategy migration.
    prior = ledger_prior(FLEET_FIXTURE)
    assert prior["processes"] == 2 and prior["local_devices"] == 4
    assert prior["capacity"] == 32768 and prior["incumbent"] == "tree"
    assert math.isclose(prior["top_mass"], 0.3)
    assert prior["combiner_prior"] == "hot-cache"
    assert prior["fleet_verdict"] == "straggler-bound"
    assert math.isclose(prior["measured_collective_s"], 0.3)
    assert math.isclose(prior["fleet_bottleneck"]["straggler_s"], 2.0)
    args = argparse.Namespace(ledger=FLEET_FIXTURE, processes=None,
                              local_devices=None, capacity=None,
                              top_mass=None, occupancy=None, incumbent=None)
    art = build_plan(args, mc)
    assert art["top"] == "tree" and art["incumbent_is_top"] is True
    assert art["prior"]["fleet_verdict"] == "straggler-bound"
    assert "fix the bottleneck the verdict names first" in art["note"]
    assert art["ranked"][0]["modeled_s"] \
        < prior["fleet_bottleneck"]["straggler_s"]
    json.dumps(art)  # the artifact is JSON-clean

    # --check mechanics: the pure rule both ways, then the fixture —
    # which MUST flag (CPU-synthesized 0.3s vs the 528us TPU-link bound
    # is a ~568x disagreement: the mechanism proof that a wrong rates
    # fixture cannot slip through quietly).
    assert not check_disagreement(6e-4, 5.28e-4)["flag"]
    assert check_disagreement(3e-4, 5.28e-4)["flag"] is False
    assert check_disagreement(0.3, 5.28e-4)["flag"] is True
    assert check_disagreement(1e-4, 5.28e-4)["flag"] is True  # too FAST too
    assert check_disagreement(None, 5.28e-4)["flag"] is False
    res = check_disagreement(prior["measured_collective_s"],
                             mc.price_strategy(
                                 "tree", mc.table_bytes(32768),
                                 mc.MeshSpec.fleet(2, 4), levels,
                                 slack=slack)["modeled_s"])
    assert res["flag"] and res["ratio"] > 500, res

    # Profile write round-trip (tuned.json shape autotune/geometry read).
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        out = os.path.join(d, "tuned.json")
        key = write_profile(art, out, lambda m: None)
        with open(out, encoding="utf-8") as f:
            prof = json.load(f)["profiles"][key]
        assert prof["config"] == {"merge_strategy": "tree"}
        assert prof["stopped"] == "planned"
        assert prof["fleet_verdict"] == "straggler-bound"

    assert had_jax or "jax" not in sys.modules, \
        "selftest must stay jax-free"
    print("redplan selftest ok (crossover M*=3.6MB at D=4 ICI with "
          "ring==tree==180us, rankings 8192->gather / 32768->tree, "
          "keyrange skew derating 1.3x + budget-row parity, fixture "
          "prior straggler-bound with incumbent tree on top, --check "
          f"flags the {res['ratio']}x fixture disagreement)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="static reduction-strategy planner: jax-free ranked "
                    "plan over the ICI/DCN link model, graphcheck gate, "
                    "modeled-vs-measured ledger check")
    ap.add_argument("--processes", type=int, default=None,
                    help="fleet processes/hosts (outer DCN axis; default 2 "
                         "or the ledger's)")
    ap.add_argument("--local-devices", type=int, default=None,
                    help="devices per process (inner ICI axis; default 4 "
                         "or the ledger's)")
    ap.add_argument("--capacity", type=int, default=None,
                    help="CountTable capacity in rows (default 8192 or the "
                         "ledger's) — sets the 7-plane payload")
    ap.add_argument("--top-mass", type=float, default=None,
                    help="measured top-key mass (derates keyrange past "
                         "0.05; default: the ledger's data record)")
    ap.add_argument("--occupancy", type=float, default=None,
                    help="measured table occupancy for the keyrange "
                         "budget-spill check (default: the ledger's)")
    ap.add_argument("--incumbent", default=None,
                    help="strategy currently deployed (ranked artifact "
                         "reports whether it stays on top)")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="fleet ledger (sharded <path>.h<p>.jsonl or "
                         "single-file): topology/incumbent/key "
                         "distribution prior + fleet verdict")
    ap.add_argument("--gate", action="store_true",
                    help="certify each ranked strategy through the "
                         "graphcheck pipeline over a fleet-twin job "
                         "(host tracing; no device)")
    ap.add_argument("--check", action="store_true",
                    help="modeled vs measured collective seconds over "
                         "--ledger; exit 1 past the 2x disagreement gate")
    ap.add_argument("--out", default=None, metavar="TUNED_JSON",
                    help="also write the winner as a tuned.json profile "
                         "(wordcount-redplan/static/<mesh>-cap<capacity>)")
    ap.add_argument("--selftest", action="store_true",
                    help="run the jax-free selftest and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    mc = _load_meshcost()
    if args.check:
        return run_check(args, mc)
    art = build_plan(args, mc)

    def log(msg: str) -> None:
        print(f"[redplan] {msg}", file=sys.stderr, flush=True)

    if args.gate:
        gated = gate_strategies(art, log)
        art["gated"] = gated
        print(json.dumps(art, indent=1))
        return 0 if len(gated) == len(art["ranked"]) else 1
    if args.out:
        art["profile_key"] = write_profile(art, args.out, log)
    print(json.dumps(art, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
