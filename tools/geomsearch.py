#!/usr/bin/env python3
"""Certifier-gated kernel-geometry search driver (ISSUE 12 tentpole).

Three stages, each cheaper than the next is allowed to be:

1. **Enumerate + certify + rank** (default; jax-free): walk the candidate
   lattice in ``mapreduce_tpu/analysis/geometry.py``, drop anything the
   static vmem certifier rejects, price the survivors with the hbm-cost
   model's own arithmetic (stable2 sort rows / radix slab amplification
   re-derived from each CANDIDATE), and print the ranked shortlist as one
   JSON artifact — no jax, no device.
2. ``--gate``: run the full graphcheck pipeline (reducer-algebra,
   overflow, host-sync, sharding, **vmem-budget, kernel-race,
   spill-reachability**) over a WordCountJob built with each shortlisted
   candidate — the same baseline-free certification ``tools/autotune.py``
   applies to probe configs.  Traces on the host; still no device.
3. ``--probe``: measured on-device ranking — one telemetered streamed
   probe pass per shortlisted candidate through the PR-10 probe
   machinery (``tools/autotune.py``), winner written to ``tuned.json``
   (profile key ``<family>-geometry/<backend>/<corpus>``) and recorded
   as a value-aware ``BENCH_LAST_GOOD`` entry with the ranked trail.
   ``Config.geometry='auto'`` / CLI ``--geometry auto`` resolve from
   exactly these profiles.

Usage::

    python tools/geomsearch.py                       # jax-free shortlist
    python tools/geomsearch.py --top 8 --axis block_rows
    python tools/geomsearch.py --gate                # + graphcheck gate
    python tools/geomsearch.py --probe --mb 64       # measured ranking
    python tools/geomsearch.py --selftest            # fixture-driven, jax-free

``--selftest`` (wired into ``tools/tier1.sh`` and ``tools/smoke.sh``
alongside the obs_report/trace_export/autotune selftests) asserts the
jax-free half end to end: the default geometry reproduces the shipped
``production_plans`` footprints bit-for-bit, every emitted candidate
passes the static certifier, a known-overflow candidate is rejected, the
384-vs-512 ranking matches the PR-11 hand arithmetic, and the tuner's
geometry knob proposes/reverts/oscillation-guards over the checked-in
fixtures — all without importing jax.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


def _load_geometry():
    """Import ``mapreduce_tpu.analysis.geometry`` WITHOUT executing the
    ``mapreduce_tpu.analysis`` package __init__ (which registers the pass
    pipeline and pulls jax): the module itself imports only the jax-free
    corners (config, ops/pallas/meta), so loading it by file path keeps
    the selftest/shortlist stages genuinely jax-free.  When the package
    is already imported (pytest, --gate/--probe), reuse it."""
    mod = sys.modules.get("mapreduce_tpu.analysis.geometry")
    if mod is not None:
        return mod
    path = os.path.join(REPO, "mapreduce_tpu", "analysis", "geometry.py")
    spec = importlib.util.spec_from_file_location("_geomsearch_geometry",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    # dataclass creation resolves the defining module through sys.modules:
    # register under the private name BEFORE executing.
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


# -- stage 2: the graphcheck gate (jax; host-only) ---------------------------

def gate_candidates(cands, log) -> list:
    """Baseline-free graphcheck certification of each candidate — the
    autotune._certify discipline: vmem-budget, kernel-race (the
    revisited-ref discipline at the candidate's static shapes),
    spill-reachability, host-sync, sharding, algebra, overflow.  Returns
    the candidates whose reports carry zero errors."""
    from mapreduce_tpu import analysis
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import WordCountJob

    passes = [p for p in analysis.default_pipeline()
              if p.pass_id not in ("hbm-cost", "fusion-opportunity")]
    kept = []
    for c in cands:
        cfg = Config(chunk_bytes=128 * max(c.geometry.block_rows,
                                           c.geometry.combiner_block_rows),
                     table_capacity=512, backend="pallas",
                     map_impl="fused", geometry=c.geometry)
        report = analysis.analyze_job(WordCountJob(cfg),
                                      f"<geometry:{c.label}>",
                                      passes=passes)
        if report.errors:
            log(f"gate REJECTED {c.label}:\n"
                + report.format_text("error"))
            continue
        log(f"gate ok: {c.label}")
        kept.append(c)
    return kept


# -- stage 3: measured probe ranking (jax + device) --------------------------

def run_probe(args, geom_mod) -> int:
    import tempfile

    import bench  # repo-root module: the corpus generators

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import autotune  # the PR-10 probe machinery
    finally:
        sys.path.pop(0)

    wall0 = time.perf_counter()

    def log(msg: str) -> None:
        print(f"[geomsearch +{time.perf_counter() - wall0:6.1f}s] {msg}",
              file=sys.stderr, flush=True)

    import jax

    from mapreduce_tpu import obs
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor, profiling

    cands = geom_mod.shortlist(geom_mod.enumerate_candidates(),
                               args.top, axis=args.axis)
    # The default geometry is ALWAYS probed (the A/B baseline every
    # candidate is judged against), whether or not it made the shortlist.
    if not any(c.axis == "default" for c in cands):
        cands = [c for c in geom_mod.enumerate_candidates()
                 if c.axis == "default"] + cands

    # Drop candidates whose varied axis is INERT in the probe config
    # (fused/stable2/xla-sort/combiner-off): a radix- or sort3-axis
    # candidate resolves to the exact same program there, so probing it
    # measures run-to-run noise and can crown a no-op knob the winner.
    # Resolved-value comparison, not axis names, so the filter can never
    # drift from what Config actually reads.  Logged, never silent
    # (the no-silent-caps rule).
    def _probe_resolved(c):
        cfg = Config(backend="pallas", map_impl="fused",
                     geometry=None if c.axis == "default" else c.geometry)
        return (cfg.resolved_block_rows, cfg.resolved_compact_slots,
                cfg.resolved_pair_block_rows, cfg.resolved_aux_rows,
                cfg.resolved_radix_geometry, cfg.resolved_combiner_slots)

    default_resolved = _probe_resolved(
        next(c for c in cands if c.axis == "default"))
    kept = []
    for c in cands:
        if c.axis != "default" and _probe_resolved(c) == default_resolved:
            log(f"probe skipped {c.label}: its axis is inert in the probe "
                "config (identical resolved program) — rank it via a "
                "probe driver that exercises that axis instead")
            continue
        kept.append(c)
    cands = gate_candidates(kept, log)
    if not cands:
        print("geomsearch: no candidate survived the gate", file=sys.stderr)
        return 1

    profiling.enable_compile_cache()
    gen = {"zipf": bench.make_zipf_corpus,
           "natural": bench.make_natural_corpus,
           "webby": bench.make_webby_corpus,
           "markup": bench.make_markup_corpus}[args.corpus]
    corpus = gen(args.mb << 20)
    mesh = data_mesh()
    backend = jax.devices()[0].platform
    ledger_dir = args.keep_ledgers or tempfile.mkdtemp(prefix="geomsearch_")
    os.makedirs(ledger_dir, exist_ok=True)
    with tempfile.NamedTemporaryFile(dir="/tmp", suffix=".txt",
                                     delete=False) as f:
        f.write(corpus)
        path = f.name
    measured = []
    try:
        for i, c in enumerate(cands):
            cfg = Config(chunk_bytes=args.chunk_mb << 20,
                         table_capacity=1 << 18,
                         batch_unique_capacity=1 << 16,
                         backend="pallas", map_impl="fused",
                         geometry=None if c.axis == "default"
                         else c.geometry)
            ledger = os.path.join(ledger_dir, f"geom{i:02d}.jsonl")
            tel = obs.Telemetry.create(ledger_path=ledger)
            t0 = time.perf_counter()
            try:
                rr = executor.run_job(WordCountJob(cfg), path, config=cfg,
                                      mesh=mesh, telemetry=tel)
            finally:
                tel.close()
            dt = time.perf_counter() - t0
            gbps = round(rr.metrics.bytes_processed / 1e9 / dt, 4)
            log(f"probe {c.label}: {gbps} GB/s ({dt:.2f}s, "
                f"modeled sort_rows={c.sort_rows}, ledger {ledger})")
            measured.append((gbps, c))
    finally:
        os.unlink(path)
    measured.sort(key=lambda gc: -gc[0])
    best_gbps, best = measured[0]
    key = (f"wordcount-geometry/{backend}/"
           f"{args.corpus}-{args.mb}mb-chunk{args.chunk_mb}mb")
    entry = {"config": {"geometry": best.label
                        if best.label in ("default",)
                        or best.label in _preset_names()
                        else best.geometry.as_dict()},
             "measured_gbps": best_gbps,
             "stopped": "probed",
             "passes": len(measured),
             "backend": backend,
             "devices": int(mesh.size),
             "corpus": f"synthetic-{args.corpus}",
             "corpus_mb": args.mb,
             "trail": [{"geometry": c.label, "gbps": g,
                        "modeled_sort_rows": c.sort_rows,
                        "spill_risk": c.spill_risk}
                       for g, c in measured],
             "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())}
    autotune.write_profile(args.out, key, entry)
    recorded = autotune.record_last_good(key, entry, backend,
                                         slot="geometry")
    log(f"winner {best.label} @ {best_gbps} GB/s -> {args.out} [{key}]"
        + ("" if recorded else " (LAST_GOOD unchanged)"))
    print(json.dumps({"metric": "geomsearch_winner", "profile": key,
                      **entry}))
    return 0


def _preset_names():
    from mapreduce_tpu.config import GEOMETRY_PRESETS

    return set(GEOMETRY_PRESETS)


# -- selftest (jax-free) -----------------------------------------------------

def selftest() -> int:
    """The jax-free half end to end, against hand arithmetic and the
    checked-in fixtures — the tier-1/smoke gate."""
    had_jax = "jax" in sys.modules
    g = _load_geometry()
    from mapreduce_tpu.ops.pallas import meta  # jax-free

    # The shipped default geometries are reproduced EXACTLY by the
    # constructor: bit-identical vmem_plan footprints (the acceptance
    # criterion; the values are the pre-refactor hand-maintained list's).
    expected = [(508416, 12, 67108864), (352768, 12, 67108864),
                (475648, 8, None), (729600, 12, 67108864),
                (860672, 12, 67108864), (631296, 8, None),
                (3932160, 36, None), (3932160, 132, None)]
    plans = meta.production_plans()
    got = [(p.vmem_bytes, p.smem_bytes, p.vmem_limit_bytes) for p in plans]
    assert got == expected, f"production plans drifted: {got}"
    assert [p.as_dict() for p in plans] == \
        [p.as_dict() for p in meta.geometry_plans(g.DEFAULT_GEOMETRY)]

    # Every emitted candidate passes the static certifier by construction.
    cands = g.enumerate_candidates()
    assert len(cands) >= 30, f"lattice shrank to {len(cands)}"
    assert all(not g.certify(c.geometry) for c in cands)
    assert sum(c.axis == "default" for c in cands) == 1

    # A known-overflow candidate is rejected: radix B=32 slabs at a
    # 2048-row block are 3*32*256 double-buffered slab rows per grid step
    # — past Mosaic's 16 MB default stack budget, which the partition
    # kernel does not override.
    bad = g.Geometry(radix_bits=5, radix_block_rows=2048)
    errs = g.certify(bad)
    assert errs and any("16 MiB budget" in e for e in errs), errs
    assert not any(c.geometry == bad for c in cands)

    # Cost ranking matches the PR-11 hand arithmetic (the free oracle):
    # 384x128 -> 11,206,656 sort rows per 32 MB chunk, 512x128 ->
    # 8,404,992 (-25%), so tall512 prices BELOW the default; spill risk
    # is flagged on the 512 window without the combiner (114 ends / 384
    # bytes measured density -> 152 > 128 slots) and NOT on the default.
    assert g.stable2_sort_rows(1 << 25, 384, 128) == 11206656
    assert g.stable2_sort_rows(1 << 25, 512, 128) == 8404992
    default = next(c for c in cands if c.axis == "default")
    tall = next(c for c in cands if c.label == "tall512")
    assert tall.sort_rows < default.sort_rows
    assert tall.spill_risk and not default.spill_risk
    sl = g.shortlist(cands, 5)
    assert sl.index(tall) < len(sl), "tall512 must make the top-5"
    assert all(sl[i].sort_rows <= sl[i + 1].sort_rows
               for i in range(len(sl) - 1)), "shortlist must rank by rows"
    art = g.search_artifact(cands, 5)
    assert art["default"]["sort_rows"] == 11206656
    assert len(art["shortlist"]) == 5
    json.dumps(art)  # the artifact is JSON-clean

    # Radix slab amplification derives the round-6 slack factor from the
    # candidate, not a quote: cap*B/block == slack when unclamped.
    assert g.radix_slab_write_amplification(g.DEFAULT_GEOMETRY) == 4.0

    # The tuner's geometry knob (the second non-numeric knob): propose on
    # the device-bound-with-headroom fixture, revert on the spilling
    # tall-window fixture, and the oscillation guard stops the pair.
    from mapreduce_tpu.tuning import engine

    def fx(name):
        with open(os.path.join(FIXTURES, name + ".jsonl"),
                  encoding="utf-8") as f:
            return [json.loads(line) for line in f if line.strip()]

    geom_recs, spill_recs = fx("tuner_geometry"), fx("tuner_geomspill")
    p = engine.propose(geom_recs)
    assert p["rule"] == "try-geometry", p["rule"]
    assert p["changed"] == {"geometry": ["default", "tall512"]}, p["changed"]
    assert p["signals"]["window_occupancy"] == 0.55, p["signals"]
    engine.validate_knobs(p["proposal"])
    p2 = engine.propose(spill_recs)
    assert p2["rule"] == "revert-geometry", p2["rule"]
    assert p2["changed"] == {"geometry": ["tall512", "default"]}, p2
    engine.validate_knobs(p2["proposal"])
    r = engine.search(
        lambda k: geom_recs if k["geometry"] == "default" else spill_recs,
        {"chunk_bytes": 1 << 21, "superstep": 1, "inflight_groups": 4,
         "prefetch_depth": 4}, budget=8)
    assert r["stopped"] == "oscillation" and r["passes"] == 2, r
    assert [t["rule"] for t in r["trail"]] == \
        ["try-geometry", "revert-geometry"]
    for t in r["trail"]:
        engine.validate_knobs(t["proposal"])

    # 'auto' resolution round-trip: preset label and spec dict both
    # resolve; garbage/missing profiles degrade to 'default'.
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        prof = os.path.join(d, "tuned.json")
        with open(prof, "w", encoding="utf-8") as f:
            json.dump({"profiles": {
                "wordcount-geometry/tpu/zipf-64mb-chunk32mb": {
                    "recorded_at": "2026-08-04T00:00:00Z",
                    "config": {"geometry": "tall512"}}}}, f)
        assert g.resolve_auto(prof) == "tall512"
        spec = g.Geometry(block_rows=640).as_dict()
        with open(prof, "w", encoding="utf-8") as f:
            json.dump({"profiles": {
                "wordcount-geometry/tpu/zipf-64mb-chunk32mb": {
                    "recorded_at": "2026-08-04T00:00:00Z",
                    "config": {"geometry": spec}}}}, f)
        assert g.resolve_auto(prof) == spec
        with open(prof, "w", encoding="utf-8") as f:
            f.write("not json")
        assert g.resolve_auto(prof) == "default"
        assert g.resolve_auto(os.path.join(d, "missing.json")) == "default"

    assert had_jax or "jax" not in sys.modules, \
        "selftest must stay jax-free"
    print(f"geomsearch selftest ok ({len(cands)} candidates certified, "
          f"default {default.sort_rows} rows vs tall512 {tall.sort_rows} "
          f"(-25%), overflow rejected, tuner try/revert + oscillation "
          "guard ok, auto-resolution ok)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="certifier-gated kernel-geometry search: jax-free "
                    "shortlist, graphcheck gate, measured probe ranking")
    ap.add_argument("--top", type=int, default=5,
                    help="shortlist size (default 5)")
    ap.add_argument("--axis", default=None,
                    help="narrow the lattice to one axis family "
                         "(block_rows, sort3, radix, ...)")
    ap.add_argument("--gate", action="store_true",
                    help="run the graphcheck pipeline over the shortlist "
                         "(host tracing; no device)")
    ap.add_argument("--probe", action="store_true",
                    help="measured on-device ranking of the gated "
                         "shortlist (one streamed probe pass each)")
    ap.add_argument("--corpus", choices=("zipf", "natural", "webby",
                                         "markup"), default="zipf")
    ap.add_argument("--mb", type=int, default=32,
                    help="probe corpus size (default 32)")
    ap.add_argument("--chunk-mb", type=int, default=32,
                    help="probe chunk size in MB (default 32 — the "
                         "pricing chunk the modeled ranking uses)")
    ap.add_argument("--out", default=os.path.join(REPO, "tuned.json"),
                    help="tuned-profile JSON path (default ./tuned.json)")
    ap.add_argument("--keep-ledgers", default=None, metavar="DIR",
                    help="keep per-probe ledgers in DIR (default: tmpdir)")
    ap.add_argument("--selftest", action="store_true",
                    help="run the jax-free selftest and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    g = _load_geometry()
    if args.probe:
        return run_probe(args, g)
    cands = g.enumerate_candidates()
    if args.gate:
        short = g.shortlist(cands, args.top, axis=args.axis)
        kept = gate_candidates(
            short, lambda m: print(f"[geomsearch] {m}", file=sys.stderr))
        print(json.dumps({**g.search_artifact(cands, args.top),
                          "gated": [c.label for c in kept]}))
        return 0 if len(kept) == len(short) else 1
    print(json.dumps(g.search_artifact(cands, args.top)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
