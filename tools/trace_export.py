#!/usr/bin/env python3
"""Render a run ledger's ``group`` lifecycle records as Chrome trace-event
JSON, viewable in Perfetto (ui.perfetto.dev) or ``chrome://tracing``
(ISSUE 7).

The executor stamps every superstep group's lifecycle and writes one
``group`` ledger record per retired group; ``mapreduce_tpu/obs/timeline.py``
reconstructs those into per-resource lanes, and this tool serializes the
same reconstruction as a trace: one **pid per resource lane** (reader /
staging / h2d / device / retire), one **tid per group**, flow arrows for
the dispatch -> token-ready hand-off, and instant markers on the device
lane for every attributed idle gap.  The ``otherData.bottleneck`` dict
carries the critical-path verdict, so the trace file alone answers "what
bounded this run".

Usage::

    python tools/trace_export.py /path/run.jsonl                  # -> run.jsonl.trace.json
    python tools/trace_export.py /path/run.jsonl --out t.json
    python tools/trace_export.py /path/run.jsonl --stdout
    python tools/trace_export.py --selftest                       # fixture-driven

Deliberately jax-free and stdlib-only (like ``obs_report.py``): the
timeline module is loaded by file path from the source tree, falling back
to the installed package, so a laptop or CI box can render the forensics
of a run that happened on a TPU host.  ``--selftest`` exports the
checked-in pipelined fixture (``tools/fixtures/mini_ledger.jsonl``) and
schema-checks the result; it is wired into ``tools/tier1.sh`` and
``tools/smoke.sh``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
try:
    # Sibling tool, same stdlib-only constraint: owns the one JSONL reader
    # and the one by-path loader of obs/timeline.py, so the forward-compat
    # line-skipping rules and the source-vs-installed fallback live in
    # exactly one place.
    import obs_report
finally:
    sys.path.pop(0)

read_ledger = obs_report.read_ledger


def timeline_mod():
    """The jax-free reconstructor (see ``obs_report._timeline_mod``);
    unlike the report — which degrades to "no timeline section" — this
    tool has nothing to do without it, so absence is an error."""
    tl = obs_report._timeline_mod()
    if tl is None:
        raise RuntimeError(
            "timeline module unavailable: neither the source tree's "
            "mapreduce_tpu/obs/timeline.py nor an installed mapreduce_tpu "
            "package was found")
    return tl


# -- schema validation -------------------------------------------------------

_PHASES = {"X", "M", "s", "f", "i"}


def validate_trace(trace) -> list:
    """Structural validation of a Chrome trace-event object: returns a list
    of problems (empty = valid).  Checks the subset of the trace-event
    format this tool emits — enough for Perfetto/chrome://tracing to load
    the file: every event has a known phase and an int pid; timed events
    carry non-negative ``ts`` (and ``dur`` for complete events); every pid
    used by a slice has a ``process_name`` metadata event; flow starts and
    ends pair up by id."""
    errs = []
    if not isinstance(trace, dict) or \
            not isinstance(trace.get("traceEvents"), list):
        return ["top level must be an object with a traceEvents list"]
    named_pids, used_pids = set(), set()
    flow = {"s": set(), "f": set()}
    for i, ev in enumerate(trace["traceEvents"]):
        if not isinstance(ev, dict):
            errs.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _PHASES:
            errs.append(f"event {i}: unknown phase {ph!r}")
            continue
        if not isinstance(ev.get("pid"), int):
            errs.append(f"event {i}: pid must be an int")
            continue
        if ph == "M":
            if not isinstance(ev.get("name"), str):
                errs.append(f"event {i}: metadata without a name")
            elif ev["name"] == "process_name":
                named_pids.add(ev["pid"])
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or isinstance(ts, bool) or ts < 0:
            errs.append(f"event {i}: ts must be a non-negative number")
        if not isinstance(ev.get("name"), str):
            errs.append(f"event {i}: missing name")
        used_pids.add(ev["pid"])
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or isinstance(dur, bool) \
                    or dur < 0:
                errs.append(f"event {i}: X event needs non-negative dur")
        elif ph in ("s", "f"):
            if ev.get("id") is None:
                errs.append(f"event {i}: flow event without id")
            else:
                flow[ph].add(ev["id"])
    for pid in sorted(used_pids - named_pids):
        errs.append(f"pid {pid} has slices but no process_name metadata")
    if flow["s"] != flow["f"]:
        errs.append(f"unmatched flow ids: starts {sorted(flow['s'])} vs "
                    f"ends {sorted(flow['f'])}")
    return errs


def export(ledger_path: str, run_id=None):
    """Ledger file -> (trace dict or None, timeline artifact or None)."""
    tl = timeline_mod()
    records = read_ledger(ledger_path)
    return tl.to_chrome_trace(records, run_id), tl.reconstruct(records,
                                                               run_id)


def export_fleet(ledger_path: str, run_id=None):
    """Multi-host ledger -> (pid-per-host fleet trace or None, fleet
    artifact or None) from the ``<ledger>.h*.jsonl`` shards next to it
    (ISSUE 13).  Uses the jax-free ``obs/fleet.py`` via the same by-path
    loader as the timeline."""
    fl = obs_report._fleet_mod()
    if fl is None:
        raise RuntimeError("fleet module unavailable (mapreduce_tpu/obs/"
                           "fleet.py not found and package not installed)")
    paths = fl.shard_paths(ledger_path)
    if not paths:
        return None, None
    by_host = {h: fl.read_jsonl(p) for h, p in paths.items()}
    return fl.to_chrome_trace(by_host, run_id), fl.fleet_view(by_host,
                                                              run_id)


# -- selftest ----------------------------------------------------------------

def selftest() -> int:
    """Export the checked-in pipelined fixture and assert the trace's
    load-bearing facts (schema validity, lane/pid structure, flow pairing,
    the bottleneck verdict riding along)."""
    tl = timeline_mod()
    ledger = os.path.join(HERE, "fixtures", "mini_ledger.jsonl")
    trace, art = export(ledger)
    assert trace is not None and art is not None, \
        "fixture must carry group records (pipelined run fixture04)"
    errs = validate_trace(trace)
    assert not errs, f"schema errors: {errs}"
    evs = trace["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    pnames = {e["pid"]: e["args"]["name"] for e in evs
              if e["ph"] == "M" and e["name"] == "process_name"}
    # One pid per lane, in lane order.
    assert sorted(pnames.values()) == sorted(tl.LANES), pnames
    # One tid per group on the device lane.
    dev_pid = next(p for p, n in pnames.items() if n == "device")
    dev_tids = {e["tid"] for e in slices if e["pid"] == dev_pid}
    assert dev_tids == {0, 2, 4, 6}, dev_tids  # step_first of each group
    # The fixture's construction: 4 groups, reader-bound, 0.4 s device
    # idle across two gaps both attributed to the reader.
    assert art["groups"] == 4
    bn = art["bottleneck"]
    assert bn["resource"] == "reader", bn
    assert round(bn["projected_saving_s"], 4) == 0.28, bn
    assert round(art["device_idle"]["total_s"], 4) == 0.4
    assert [g["blocking"] for g in art["device_idle"]["gaps"]] \
        == ["reader", "reader"]
    assert round(art["overlap_s"]["staging+device"], 4) == 0.1
    assert trace["otherData"]["bottleneck"]["resource"] == "reader"
    # Flow arrows: one dispatch->token_ready pair per group.
    starts = [e for e in evs if e["ph"] == "s"]
    ends = [e for e in evs if e["ph"] == "f"]
    assert len(starts) == len(ends) == 4, (len(starts), len(ends))
    # Idle-gap instant markers land on the device lane.
    gaps = [e for e in evs if e["ph"] == "i"]
    assert len(gaps) == 2 and all(e["pid"] == dev_pid for e in gaps)
    # Round-trip: the emitted JSON parses back identically.
    assert json.loads(json.dumps(trace)) == trace
    # Data-plane annotations (ISSUE 8): the spill-heavy fixture run's
    # per-group `data` dicts ride the trace — every lifecycle slice's
    # args carry them, and fallback/escalation groups get instant
    # markers on the device lane.
    trace5, art5 = export(ledger, "fixture05")
    assert art5["groups"] == 2 and not validate_trace(trace5)
    dmarks = [e for e in trace5["traceEvents"]
              if e["ph"] == "i" and e.get("cat") == "data"]
    assert len(dmarks) == 2, dmarks
    assert all("spill fallback" in e["name"] for e in dmarks), dmarks
    assert any("rescue escalation" in e["name"] for e in dmarks), dmarks
    dev5 = next(e["pid"] for e in trace5["traceEvents"]
                if e["ph"] == "M" and e["name"] == "process_name"
                and e["args"]["name"] == "device")
    assert all(e["pid"] == dev5 for e in dmarks)
    dslices = [e for e in trace5["traceEvents"]
               if e["ph"] == "X" and "data" in e.get("args", {})]
    assert dslices and all(
        e["args"]["data"].get("chunks") == 1 for e in dslices), \
        "slice args must carry the group data dict"
    # Forward compat: the future-versioned fixture must export (or decline
    # with None) without raising, never error.
    future = os.path.join(HERE, "fixtures", "future_ledger.jsonl")
    ftrace, fart = export(future)
    assert fart is not None and fart["groups"] >= 1, fart
    assert not validate_trace(ftrace)
    # Fleet export (ISSUE 13): the two-host shard fixtures render as one
    # schema-valid trace with one pid per HOST (lanes become tids inside
    # it) and the fleet verdict in otherData; a shardless ledger declines
    # with None instead of erroring.
    fleet_trace, fleet_art = export_fleet(
        os.path.join(HERE, "fixtures", "fleet_ledger.jsonl"))
    assert fleet_trace is not None and fleet_art["hosts"] == [0, 1]
    ferrs = validate_trace(fleet_trace)
    assert not ferrs, f"fleet trace schema errors: {ferrs}"
    fnames = sorted(e["args"]["name"] for e in fleet_trace["traceEvents"]
                    if e["ph"] == "M" and e["name"] == "process_name")
    assert fnames == ["host 0", "host 1"], fnames
    assert fleet_trace["otherData"]["fleet_bottleneck"]["verdict"] \
        == "straggler-bound"
    fslices = [e for e in fleet_trace["traceEvents"] if e["ph"] == "X"]
    assert any(e["name"].startswith("collective") for e in fslices)
    assert export_fleet(ledger) == (None, None), \
        "a shardless ledger has no fleet trace"
    print(f"trace_export selftest ok ({len(slices)} slices, "
          f"{len(starts)} flows, {len(gaps)} idle markers, "
          f"{len(dmarks)} data markers, bottleneck={bn['resource']}, "
          f"fleet trace {len(fslices)} slices over "
          f"{len(fleet_art['hosts'])} hosts)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="export a mapreduce_tpu run ledger as Chrome "
                    "trace-event JSON (Perfetto / chrome://tracing)")
    ap.add_argument("ledger", nargs="?", help="JSONL run-ledger path")
    ap.add_argument("--out", default=None,
                    help="output path (default: <ledger>.trace.json)")
    ap.add_argument("--run", default=None,
                    help="run_id to export (default: first run with "
                         "group records)")
    ap.add_argument("--fleet", action="store_true",
                    help="export the multi-host fleet trace instead: merge "
                         "the <ledger>.h*.jsonl shards, one Perfetto pid "
                         "per host (default out: <ledger>.fleet.trace.json)")
    ap.add_argument("--stdout", action="store_true",
                    help="write the trace JSON to stdout instead of a file")
    ap.add_argument("--selftest", action="store_true",
                    help="run against the checked-in fixtures and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.ledger:
        ap.error("a ledger path (or --selftest) is required")
    if args.fleet:
        trace, art = export_fleet(args.ledger, args.run)
        if trace is None:
            print(f"no shard files ({args.ledger}.h*.jsonl) found — not a "
                  "multi-host ledger?", file=sys.stderr)
            return 1
    else:
        trace, art = export(args.ledger, args.run)
        if trace is None:
            print("no group records found (pre-ISSUE-7 ledger, or the run "
                  "never retired a group) — nothing to export",
                  file=sys.stderr)
            return 1
    errs = validate_trace(trace)
    if errs:  # a bug here must fail loudly, not ship a broken trace
        for e in errs:
            print(f"schema error: {e}", file=sys.stderr)
        return 2
    if args.stdout:
        json.dump(trace, sys.stdout)
        print()
    elif args.fleet:
        out = args.out or args.ledger + ".fleet.trace.json"
        with open(out, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        bn = art["fleet_bottleneck"]
        print(f"wrote {out}: {len(art['hosts'])} hosts over "
              f"{art['span_s']:.3f}s, skew "
              f"{art['straggler']['total_skew_s']:.3f}s, "
              f"fleet bottleneck {bn['verdict']} (projected saving "
              f"{bn['projected_saving_s']:.3f}s) — open in ui.perfetto.dev")
    else:
        out = args.out or args.ledger + ".trace.json"
        with open(out, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        bn = art["bottleneck"]
        print(f"wrote {out}: {art['groups']} groups over "
              f"{art['span_s']:.3f}s, device idle "
              f"{art['device_idle']['total_s']:.3f}s, bottleneck "
              f"{bn['resource']} (projected saving "
              f"{bn['projected_saving_s']:.3f}s) — open in ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
