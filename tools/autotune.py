#!/usr/bin/env python3
"""Offline window autotuner (ISSUE 10 mode (a)): walk the rule table of
``mapreduce_tpu/tuning/`` over N short streamed probe passes on a
bench-style corpus until the config converges, the oscillation guard
trips, or the pass budget runs out — then emit a ``tuned.json`` profile
keyed by (family, backend, corpus shape) and record the winner as a
value-aware ``BENCH_LAST_GOOD.json`` entry with the full decision trail.

Each probe pass streams the corpus through ``executor.run_job`` with
telemetry into its own ledger; the tuner then reads exactly what the run
recorded (the PR-7 ``bottleneck`` verdict, the PR-8 ``data_health``
verdict, the window statistics) — the same pure function the online
``--autotune`` hint uses.  Every ACCEPTED config is validated through
``Config.__post_init__`` (the engine does this) and certified by the
graphcheck/costcheck gate — the baseline-free passes (reducer-algebra,
overflow, host-sync, sharding, vmem-budget, kernel-race), which are the
geometry-dependent device-safety certification — before it is allowed to
touch a device (the per-model hbm-cost baseline regression stays
tier-1's job: probe configs are not registry models).

Usage::

    python tools/autotune.py                          # zipf, 32 MB, CPU ok
    python tools/autotune.py --corpus natural --mb 64 --chunk-mb 4
    python tools/autotune.py --out /tmp/tuned.json --budget 5
    python tools/autotune.py --selftest               # fixture-driven, jax-free

``--selftest`` drives the search loop against the checked-in synthetic
ledgers (``tools/fixtures/tuner_*.jsonl``) through simulated systems —
the reader-bound system converges to the hand-computed higher-prefetch
config, the device-bound system raises superstep and provably never
touches ``inflight_groups``, and an adversarial occupancy/table-pressure
pair terminates via the oscillation guard — all without importing jax.
Wired into ``tools/tier1.sh`` and ``tools/smoke.sh`` alongside the
obs_report/trace_export selftests.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mapreduce_tpu.tuning import engine  # noqa: E402 (jax-free)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
LAST_GOOD_PATH = os.path.join(REPO, "BENCH_LAST_GOOD.json")
#: Mirrors bench.py's value-aware discipline for the tuned record: a
#: same-profile regression this deep cannot displace the best-known entry.
REGRESSION_FRAC = 0.25


def _read_fixture(name: str) -> list:
    with open(os.path.join(FIXTURES, name + ".jsonl"), encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


# -- the probe-pass measure function (jax; offline mode only) ----------------

def _probe_config(knobs: dict):
    """The ONE knobs->Config mapping every probe-pass consumer (warm-up,
    measure, certify) builds from — bench-style table geometry included,
    so the warm-up provably compiles the same program shapes the timed
    passes run."""
    from mapreduce_tpu.config import Config

    combiner = str(knobs.get("combiner", "off"))
    geometry = knobs.get("geometry", "default")
    return Config(chunk_bytes=int(knobs["chunk_bytes"]),
                  superstep=int(knobs["superstep"]),
                  inflight_groups=int(knobs["inflight_groups"]),
                  prefetch_depth=int(knobs["prefetch_depth"]),
                  combiner=combiner,
                  # The geometry knob (ISSUE 12) round-trips as 'default'
                  # or a GEOMETRY_PRESETS name; dict-shaped candidates
                  # come from the geomsearch driver, which passes them
                  # through the same Config surface.
                  geometry=None if geometry in (None, "default")
                  else geometry,
                  # The hot-key cache only exists on the fused map path
                  # (resolved_combiner_slots is 0 elsewhere): a probe that
                  # left map_impl at 'split' would re-measure the IDENTICAL
                  # program while reporting the combiner engaged — the same
                  # pairing benchwatch._tuned_env applies to the tuned rows.
                  map_impl="fused" if combiner == "hot-cache"
                  else Config.map_impl,
                  # Placed-reduction knobs (ISSUE 20): round-trip as a
                  # MERGE_STRATEGIES name and an 'off'/'on' string.
                  merge_strategy=str(knobs.get("merge_strategy", "tree")),
                  merge_overlap=str(knobs.get("merge_overlap",
                                              "off")) == "on",
                  table_capacity=1 << 18,
                  batch_unique_capacity=1 << 16)


def _certify(knobs: dict) -> None:
    """Graphcheck gate for one ACCEPTED probe config: the baseline-free
    passes over a WordCountJob built with exactly these knobs.  An
    error-severity finding aborts the walk — a config the certifier
    rejects must never touch the device."""
    from mapreduce_tpu import analysis
    from mapreduce_tpu.models.wordcount import WordCountJob

    passes = [p for p in analysis.default_pipeline()
              if p.pass_id not in ("hbm-cost", "fusion-opportunity")]
    report = analysis.analyze_job(WordCountJob(_probe_config(knobs)),
                                  "<autotune-probe>", passes=passes)
    if report.errors:
        raise SystemExit("autotune: costcheck gate REJECTED config "
                         f"{knobs}:\n" + report.format_text("error"))


def _make_measure(corpus_path: str, mesh, ledger_dir: str,
                  log) -> "callable":
    """The real measure function: one telemetered streamed pass per call,
    returning (records, gbps) via a closure side-channel."""
    from mapreduce_tpu import obs
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.runtime import executor

    state = {"pass": 0, "gbps": None, "ledger": None}

    def measure(knobs: dict) -> list:
        _certify(knobs)
        state["pass"] += 1
        cfg = _probe_config(knobs)
        ledger = os.path.join(ledger_dir, f"probe{state['pass']:02d}.jsonl")
        tel = obs.Telemetry.create(ledger_path=ledger)
        t0 = time.perf_counter()
        try:
            rr = executor.run_job(WordCountJob(cfg), corpus_path,
                                  config=cfg, mesh=mesh, telemetry=tel)
        finally:
            tel.close()
        dt = time.perf_counter() - t0
        state["gbps"] = round(rr.metrics.bytes_processed / 1e9 / dt, 4)
        state["ledger"] = ledger
        log(f"pass {state['pass']}: {knobs} -> {state['gbps']} GB/s "
            f"({dt:.2f}s, ledger {ledger})")
        return [r for r in obs.read_ledger(ledger)
                if r.get("run_id") == tel.run_id]

    return measure, state


# -- tuned.json + BENCH_LAST_GOOD --------------------------------------------

def _trail_summary(result: dict) -> list:
    """The per-pass decision trail, compacted for the profile/record."""
    return [{"rule": p["rule"], "changed": p["changed"],
             "converged": p["converged"],
             "resource": p["signals"].get("resource"),
             "saving_frac": p["signals"].get("saving_frac"),
             "data_verdict": p["signals"].get("data_verdict")}
            for p in result["trail"]]


def write_profile(out_path: str, key: str, entry: dict) -> None:
    """Merge one (family, backend, corpus-shape)-keyed profile into the
    tuned.json file (other keys preserved)."""
    profiles = {}
    try:
        with open(out_path, encoding="utf-8") as f:
            profiles = json.load(f).get("profiles", {})
    except (OSError, ValueError):
        pass
    profiles[key] = entry
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump({"tuner_version": engine.TUNER_VERSION,
                   "profiles": profiles}, f, indent=1)
        f.write("\n")


def record_last_good(key: str, entry: dict, backend: str,
                     path: str = LAST_GOOD_PATH,
                     slot: str = "tuned") -> bool:
    """Record the tuned winner as a value-aware best-known entry under
    ``best.<slot>`` in BENCH_LAST_GOOD.json — same discipline as bench.py's
    per-metric records: CPU smoke runs refused (not TPU evidence), a
    >25% same-profile regression cannot displace the best-known record,
    every refusal leaves a stderr trace.  ``slot`` separates record
    families that must not displace each other (the geometry search's
    winner rides ``best.geometry``, ISSUE 12)."""
    def refused(msg: str) -> bool:
        print(f"[autotune] last-good write refused: {msg}", file=sys.stderr,
              flush=True)
        return False

    if backend == "cpu":
        return refused("cpu backend (smoke run, not TPU evidence)")
    try:
        with open(path, encoding="utf-8") as f:
            prev = json.load(f)
    except (OSError, ValueError):
        prev = {}
    best = dict(prev.get("best") or {})
    rec = best.get(slot)
    val = entry.get("measured_gbps")
    if val is None:
        return refused("no measured GB/s for the winner")
    if rec is not None and rec.get("profile") == key:
        old = rec.get("value", 0.0)
        if val < (1.0 - REGRESSION_FRAC) * old:
            return refused(f"tuned profile {key!r} regressed {old} -> {val} "
                           f"(> {REGRESSION_FRAC:.0%}); best-known kept")
        if val < old:
            return refused(f"tuned profile {key!r} below best-known "
                           f"({val} < {old}, within {REGRESSION_FRAC:.0%}); "
                           "best-known kept")
    best[slot] = {"value": val, "profile": key,
                     "recorded_at": entry.get("recorded_at"),
                     "config": entry.get("config"),
                     "stopped": entry.get("stopped"),
                     "trail": entry.get("trail")}
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({**prev, "best": best}, f)
            f.write("\n")
    except OSError:
        return refused("BENCH_LAST_GOOD.json not writable")
    return True


# -- offline search ----------------------------------------------------------

def run_search(args) -> int:
    import tempfile

    import bench  # repo-root module: the corpus generators

    wall0 = time.perf_counter()

    def log(msg: str) -> None:
        print(f"[autotune +{time.perf_counter() - wall0:6.1f}s] {msg}",
              file=sys.stderr, flush=True)

    import jax

    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import profiling

    profiling.enable_compile_cache()
    gen = {"zipf": bench.make_zipf_corpus,
           "natural": bench.make_natural_corpus,
           "webby": bench.make_webby_corpus,
           "markup": bench.make_markup_corpus}[args.corpus]
    corpus = gen(args.mb << 20)
    log(f"corpus ready: {len(corpus) >> 20} MB (synthetic-{args.corpus})")
    mesh = data_mesh()
    backend = jax.devices()[0].platform
    ledger_dir = args.keep_ledgers or tempfile.mkdtemp(prefix="autotune_")
    os.makedirs(ledger_dir, exist_ok=True)
    with tempfile.NamedTemporaryFile(dir="/tmp", suffix=".txt",
                                     delete=False) as f:
        f.write(corpus)
        path = f.name
    start = {"chunk_bytes": args.chunk_mb << 20,
             "superstep": args.superstep,
             "inflight_groups": args.inflight,
             "prefetch_depth": args.prefetch}
    try:
        measure, state = _make_measure(path, mesh, ledger_dir, log)
        # Warm-up: pay the XLA compiles for the starting shapes so pass 1
        # measures ingest, not compilation (chunk moves recompile anyway —
        # an accepted cost: the walk compares configs, and the persistent
        # cache converts repeat shapes into hits).
        from mapreduce_tpu.models.wordcount import WordCountJob
        from mapreduce_tpu.runtime import executor

        warm_cfg = _probe_config(start)
        warm_hi = min(len(corpus), mesh.size * warm_cfg.chunk_bytes
                      * (warm_cfg.superstep + 1))
        executor.run_job(WordCountJob(warm_cfg), path, config=warm_cfg,
                         mesh=mesh, byte_range=(0, warm_hi))
        log("warm-up done (compile paid)")
        result = engine.search(measure, start, budget=args.budget,
                               backend="auto")
    finally:
        os.unlink(path)
    key = (f"wordcount/{backend}/"
           f"{args.corpus}-{args.mb}mb-chunk{args.chunk_mb}mb")
    # The winner's OWN pass's throughput (engine.search pairs them): on an
    # oscillation stop state["gbps"] holds the losing final pass's number.
    # The ledger-derived figure is preferred; the harness wall-clock one
    # is the fallback for ledgers that carried no run_end throughput.
    winner_gbps = result.get("winner_gbps")
    entry = {"config": result["winner"],
             "measured_gbps": winner_gbps if winner_gbps is not None
             else state["gbps"],
             "stopped": result["stopped"],
             "passes": result["passes"],
             "backend": backend,
             "devices": int(mesh.size),
             "corpus": f"synthetic-{args.corpus}",
             "corpus_mb": args.mb,
             "trail": _trail_summary(result),
             "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())}
    write_profile(args.out, key, entry)
    recorded = record_last_good(key, entry, backend)
    log(f"{result['stopped']} after {result['passes']} pass(es); "
        f"winner {result['winner']} @ {entry['measured_gbps']} GB/s -> "
        f"{args.out} [{key}]"
        + ("" if recorded else " (LAST_GOOD unchanged)"))
    print(json.dumps({"metric": "autotune_winner", "profile": key, **entry}))
    return 0


# -- selftest ----------------------------------------------------------------

def selftest() -> int:
    """Drive the search loop through simulated systems built from the
    checked-in fixtures and assert the hand-computed outcomes — the whole
    ledger -> signals -> rule-table -> search path, jax-free."""
    # jax-free claim: the selftest must never ADD jax to the process (it
    # may already be loaded when invoked from inside pytest).
    had_jax = "jax" in sys.modules
    reader = _read_fixture("tuner_reader_bound")
    device = _read_fixture("tuner_device_bound")
    conv = _read_fixture("tuner_converged")
    occ = _read_fixture("tuner_occupancy")
    table = _read_fixture("tuner_tablepressure")
    skew = _read_fixture("tuner_skewhot")

    # Single-proposal rule checks against each fixture (the unit facts the
    # convergence walks below compose).
    for recs, rule, changed in [
            (reader, "raise-prefetch", {"prefetch_depth": [4, 8]}),
            (device, "try-superstep", {"superstep": [1, 2]}),
            (conv, "converged", {}),
            (occ, "grow-chunk", {"chunk_bytes": [2097152, 4194304]}),
            (table, "shrink-chunk", {"chunk_bytes": [4194304, 2097152]}),
            (skew, "enable-combiner", {"combiner": ["off", "hot-cache"]})]:
        p = engine.propose(recs)
        assert p["rule"] == rule, (rule, p["rule"])
        assert p["changed"] == changed, (rule, p["changed"])
        assert p["trail"] and all(
            set(t) == {"rule", "fired", "why"} for t in p["trail"]), \
            "decision trail must be machine-readable"
        engine.validate_knobs(p["proposal"])

    # Reader-bound system: reader-starved until prefetch reaches 16, then
    # the well-overlapped ledger.  Hand-computed walk: 4 -> 8 -> 16, then
    # converged; nothing else moves.
    sim_calls = []

    def sim_reader(knobs):
        sim_calls.append(dict(knobs))
        return reader if knobs["prefetch_depth"] < 16 else conv

    r = engine.search(sim_reader, {"chunk_bytes": 1 << 25, "superstep": 1,
                                   "inflight_groups": 4,
                                   "prefetch_depth": 4}, budget=6)
    assert r["stopped"] == "converged", r["stopped"]
    assert r["winner"] == {"chunk_bytes": 1 << 25, "superstep": 1,
                           "inflight_groups": 4, "prefetch_depth": 16,
                           "combiner": "off", "geometry": "default",
                           "merge_strategy": "tree",
                           "merge_overlap": "off"}, \
        r["winner"]
    assert [p["rule"] for p in r["trail"]] == \
        ["raise-prefetch", "raise-prefetch", "converged"], \
        [p["rule"] for p in r["trail"]]
    assert [c["prefetch_depth"] for c in sim_calls] == [4, 8, 16]

    # Skew-hot system (ISSUE 11): a Zipf-hot ledger flips the combiner on
    # in ONE pass; the combiner-on ledger then measures device-bound with
    # the window unsaturated -> converged.  The decision trail must show
    # enable-combiner firing exactly once, and no pipeline knob may move
    # while the data-shape rule is answering the skew.
    def sim_skew(knobs):
        return skew if knobs["combiner"] == "off" else conv

    rs = engine.search(sim_skew, {"chunk_bytes": 1 << 21, "superstep": 1,
                                  "inflight_groups": 4,
                                  "prefetch_depth": 4}, budget=6)
    assert rs["stopped"] == "converged", rs["stopped"]
    assert rs["winner"]["combiner"] == "hot-cache", rs["winner"]
    assert rs["winner"]["prefetch_depth"] == 4 \
        and rs["winner"]["superstep"] == 1 \
        and rs["winner"]["inflight_groups"] == 4, rs["winner"]
    assert [p["rule"] for p in rs["trail"]] == \
        ["enable-combiner", "converged"], [p["rule"] for p in rs["trail"]]
    assert rs["trail"][0]["changed"] == {"combiner": ["off", "hot-cache"]}

    # Device-bound system (window always full): superstep 1 -> 2 -> 4,
    # inflight provably NEVER raised — the "stop raising inflight" rule.
    def sim_device(knobs):
        return device if knobs["superstep"] < 4 else conv

    r2 = engine.search(sim_device, {"chunk_bytes": 1 << 25, "superstep": 1,
                                    "inflight_groups": 4,
                                    "prefetch_depth": 4}, budget=6)
    assert r2["stopped"] == "converged", r2["stopped"]
    assert r2["winner"]["superstep"] == 4 and \
        r2["winner"]["inflight_groups"] == 4, r2["winner"]
    assert not any(p["rule"] == "raise-inflight" for p in r2["trail"])
    assert [p["rule"] for p in r2["trail"]] == \
        ["try-superstep", "try-superstep", "converged"]

    # Oscillation guard: a system whose data verdict flips between
    # occupancy-starved (grow) and table-pressure (shrink) at the 2 MB
    # boundary would ping-pong forever — the guard terminates it the
    # moment a proposed config was already visited.
    def sim_osc(knobs):
        return occ if knobs["chunk_bytes"] <= (2 << 20) else table

    r3 = engine.search(sim_osc, {"chunk_bytes": 2 << 20, "superstep": 1,
                                 "inflight_groups": 4,
                                 "prefetch_depth": 4}, budget=10)
    assert r3["stopped"] == "oscillation", r3["stopped"]
    assert r3["passes"] == 2 and r3["trail"][-1].get("oscillation"), r3
    # Every proposal the walks produced passes real Config validation.
    for res in (r, r2, r3, rs):
        for p in res["trail"]:
            engine.validate_knobs(p["proposal"])

    # Profile writing + the value-aware LAST_GOOD discipline, exercised
    # against a temp file: best-known kept on a deep same-profile
    # regression, displaced by a better value, cpu refused.
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        prof = os.path.join(d, "tuned.json")
        entry = {"config": r["winner"], "measured_gbps": 0.5,
                 "stopped": "converged", "trail": _trail_summary(r),
                 "recorded_at": "2026-08-04T00:00:00Z"}
        write_profile(prof, "wordcount/tpu/zipf-32mb-chunk2mb", entry)
        write_profile(prof, "wordcount/tpu/natural-64mb-chunk4mb", entry)
        with open(prof, encoding="utf-8") as f:
            blob = json.load(f)
        assert set(blob["profiles"]) == {
            "wordcount/tpu/zipf-32mb-chunk2mb",
            "wordcount/tpu/natural-64mb-chunk4mb"}, blob
        lg = os.path.join(d, "LAST_GOOD.json")
        assert record_last_good("k", entry, "tpu", path=lg)
        assert not record_last_good("k", entry, "cpu", path=lg)
        worse = {**entry, "measured_gbps": 0.1}
        assert not record_last_good("k", worse, "tpu", path=lg)
        with open(lg, encoding="utf-8") as f:
            assert json.load(f)["best"]["tuned"]["value"] == 0.5
        better = {**entry, "measured_gbps": 0.9}
        assert record_last_good("k", better, "tpu", path=lg)
        with open(lg, encoding="utf-8") as f:
            assert json.load(f)["best"]["tuned"]["value"] == 0.9
    assert had_jax or "jax" not in sys.modules, \
        "selftest must stay jax-free"
    print("autotune selftest ok (reader walk -> prefetch 16 in "
          f"{r['passes']} passes, device walk -> superstep "
          f"{r2['winner']['superstep']} with inflight untouched, "
          f"skew walk -> combiner {rs['winner']['combiner']} in "
          f"{rs['passes']} passes, "
          f"oscillation stopped in {r3['passes']}, profiles + value-aware "
          "LAST_GOOD ok)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="offline window autotuner: probe-pass search over "
                    "inflight/prefetch/superstep/chunk via the run "
                    "ledger's own verdicts")
    ap.add_argument("--corpus", choices=("zipf", "natural", "webby",
                                         "markup"), default="zipf")
    ap.add_argument("--mb", type=int, default=32,
                    help="corpus size per probe pass (default 32)")
    ap.add_argument("--chunk-mb", type=int, default=2,
                    help="starting chunk size in MB (default 2)")
    ap.add_argument("--superstep", type=int, default=1)
    ap.add_argument("--inflight", type=int, default=4)
    ap.add_argument("--prefetch", type=int, default=4)
    ap.add_argument("--budget", type=int, default=6,
                    help="max probe passes (default 6)")
    ap.add_argument("--out", default=os.path.join(REPO, "tuned.json"),
                    help="tuned-profile JSON path (default ./tuned.json)")
    ap.add_argument("--keep-ledgers", default=None, metavar="DIR",
                    help="keep per-pass ledgers in DIR (default: tmpdir)")
    ap.add_argument("--selftest", action="store_true",
                    help="run against the checked-in fixtures and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    return run_search(args)


if __name__ == "__main__":
    sys.exit(main())
