#!/usr/bin/env python3
"""Per-family cost rows: what each shipped job family pays vs plain
word count on the SAME corpus (VERDICT r5 #5/#6).

Every family the CLI ships now gets a measured end-to-end number from one
tool, one family per invocation so benchwatch gives each its own capture
and deadline:

    python tools/familybench.py plain     # the denominator row
    python tools/familybench.py grep      # --grep the (literal pattern)
    python tools/familybench.py sample    # --sample 16 (reservoir)
    python tools/familybench.py sketch    # --distinct-sketch (HLL ride-along)
    python tools/familybench.py verify    # --verify-sample 64: K=64 byte-
                                          # exact recount against the corpus
                                          # oracle; MUST log zero mismatches

Each run streams the same cached synthetic corpus file through the real
CLI in a fresh subprocess (fresh jax, ambient platform — TPU on the chip,
CPU elsewhere) and prints one JSON line: family, wall seconds, corpus
bytes, GB/s, and the verify line when applicable.  Overhead-vs-plain is
computed by the reader from the plain row of the same session
(BENCHMARKS.md "family overhead" table).

Env knobs: FAMILY_MB (default 64), FAMILY_CORPUS (zipf|natural|webby|
markup, default zipf), FAMILY_CHUNK_MB (default 32), FAMILY_TIMEOUT_S
(default 1500).  CPU sanity: JAX_PLATFORMS=cpu FAMILY_MB=4
FAMILY_CHUNK_MB=1 python tools/familybench.py grep.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAMILIES = ("plain", "grep", "sample", "sketch", "verify")


def corpus_path(kind: str, mb: int) -> str:
    """Generate (once) and cache the bench corpus as a real file — the
    streamed CLI path reads files, and all family rows must share bytes."""
    path = f"/tmp/familybench_{kind}_{mb}mb.txt"
    if os.path.exists(path) and os.path.getsize(path) > 0:
        return path
    import bench

    maker = {"zipf": bench.make_zipf_corpus,
             "natural": bench.make_natural_corpus,
             "webby": bench.make_webby_corpus,
             "markup": bench.make_markup_corpus}[kind]
    blob = maker(mb << 20)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def family_args(family: str) -> list[str]:
    return {
        "plain": [],
        "grep": ["--grep", "the"],
        "sample": ["--sample", "16"],
        "sketch": ["--distinct-sketch"],
        "verify": ["--verify-sample", "64"],
    }[family]


def main() -> int:
    if len(sys.argv) != 2 or sys.argv[1] not in FAMILIES:
        print(f"usage: familybench.py {{{'|'.join(FAMILIES)}}}",
              file=sys.stderr)
        return 2
    family = sys.argv[1]
    mb = int(os.environ.get("FAMILY_MB", "64"))
    kind = os.environ.get("FAMILY_CORPUS", "zipf")
    chunk_mb = int(os.environ.get("FAMILY_CHUNK_MB", "32"))
    timeout_s = float(os.environ.get("FAMILY_TIMEOUT_S", "1500"))

    path = corpus_path(kind, mb)
    n_bytes = os.path.getsize(path)
    cmd = [sys.executable, "-m", "mapreduce_tpu.cli", path, "--stream",
           "--no-echo", "--format", "json",
           "--chunk-bytes", str(chunk_mb << 20)] + family_args(family)
    t0 = time.monotonic()
    proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=timeout_s)
    wall = time.monotonic() - t0
    # "verify: ok" goes to stderr (the CLI keeps stdout machine-parseable);
    # mismatches also land there before the rc=4 exit.
    verify_line = next((ln for ln in (proc.stdout + proc.stderr).splitlines()
                        if ln.startswith("verify:")), None)
    record = {
        "tool": "familybench", "family": family, "corpus": kind,
        "corpus_mb": mb, "chunk_mb": chunk_mb, "bytes": n_bytes,
        "seconds": round(wall, 3),
        "gbps": round(n_bytes / wall / 1e9, 4),
        "rc": proc.returncode,
    }
    if family == "verify":
        # The satellite's contract: a zero-mismatch K=64 byte-exact
        # recount line, machine-checkable (rc != 0 on any mismatch).
        record["verify"] = verify_line
        record["verify_ok"] = proc.returncode == 0 and \
            verify_line is not None and "ok" in verify_line
    if proc.returncode != 0:
        record["stderr_tail"] = proc.stderr[-2000:]
    print(json.dumps(record))
    return 0 if proc.returncode == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
