#!/usr/bin/env bash
# Tier-1 gate: the ROADMAP.md "Tier-1 verify" command, verbatim, as a
# committed entry point (ISSUE 2 satellite) — so drivers, CI, and humans
# run the exact same gate instead of re-typing it from the doc.
#
# Prints DOTS_PASSED=<n> (count of passing-test dots in the pytest tail)
# and exits with pytest's status.  ~12 min on a 1-core box; the full suite
# (no "-m 'not slow'") is the pre-release gate, not this one.
#
# The costcheck/graphcheck clean gate runs FIRST (ISSUE 4 satellite): every
# registry model through the full pass pipeline — algebra, overflow,
# host-sync, sharding, hbm-cost (baseline regression + the ISSUE 6
# fused-vs-split gate: wordcount_fused must price strictly below the
# split baseline + the ISSUE 8 telemetry gate: the instrumented
# wordcount_telemetry twins must price within 1% of their uninstrumented
# baselines + the ISSUE 11 combiner gate: wordcount_combiner must price
# strictly below its combiner-off twin), vmem-budget, kernel-race,
# fusion-opportunity (INFO candidates; a crash or mis-severity would
# fail here) — plus the production kernel-geometry certification (fused
# seam-aux and hot-key-combiner geometries included).  Any
# error-severity finding fails tier-1 before a single test runs.
cd "$(dirname "$0")/.." || exit 1
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m mapreduce_tpu.analysis --all-models --min-severity error || { echo "TIER1: costcheck gate FAILED"; exit 1; }
# Jax-free reporting-path gates (ISSUE 7/8 satellites): the obs_report
# and trace_export selftests run against the checked-in ledger fixtures —
# the whole ledger -> timeline -> Perfetto-trace path, the data-health
# classifier (spill-heavy fixture vs hand arithmetic), and the --compare
# A/B diff are certified before a single test runs, in seconds.
timeout -k 5 60 python tools/obs_report.py --selftest || { echo "TIER1: obs_report selftest FAILED"; exit 1; }
timeout -k 5 60 python tools/trace_export.py --selftest || { echo "TIER1: trace_export selftest FAILED"; exit 1; }
# Fleet-merge gate (ISSUE 13): the two-host shard fixtures through the
# clock-aligned merge — per-superstep skew and the straggler/collective
# fleet_bottleneck verdict asserted against hand arithmetic, merge
# byte-stability, the pid-per-host trace — jax-free, seconds.
timeout -k 5 60 python mapreduce_tpu/obs/fleet.py --selftest || { echo "TIER1: fleet selftest FAILED"; exit 1; }
# Run-history + live-watch gates (ISSUE 14): the warehouse ingest over
# the checked-in fixture zoo (drift rule table against hand arithmetic,
# byte-stable re-ingest, resolve_prior parity with the three resolvers
# it replaced) and the obswatch tailer (in-flight heartbeat math,
# growing-file replay, pre-v8 degrade, fleet skew) — jax-free, seconds.
timeout -k 5 60 python mapreduce_tpu/obs/history.py --selftest || { echo "TIER1: history selftest FAILED"; exit 1; }
timeout -k 5 60 python tools/obswatch.py --selftest || { echo "TIER1: obswatch selftest FAILED"; exit 1; }
# Autotuner gate (ISSUE 10): the rule-table/search/oscillation-guard walk
# over the checked-in tuner fixtures, hand-computed targets asserted —
# also jax-free, seconds.
timeout -k 5 60 python tools/autotune.py --selftest || { echo "TIER1: autotune selftest FAILED"; exit 1; }
# Kernel-geometry search gate (ISSUE 12): enumerate -> certify -> price ->
# rank over the tile lattice, the shipped production_plans reproduced
# bit-for-bit by the same constructor, the 384-vs-512 PR-11 arithmetic
# asserted, and the tuner's geometry knob walked over its fixtures —
# jax-free, seconds.
timeout -k 5 60 python tools/geomsearch.py --selftest || { echo "TIER1: geomsearch selftest FAILED"; exit 1; }
# Chaos gate (ISSUE 15): the failure-policy backoff/taxonomy/ladder
# arithmetic against hand-computed values, fault-plan determinism and
# spec round-trip, and the replay-from-ledger contract over the
# checked-in chaotic fixture run — jax-free, seconds.
timeout -k 5 60 python tools/chaos.py --selftest || { echo "TIER1: chaos selftest FAILED"; exit 1; }
# Reduction-strategy planner gate (ISSUE 16): the ring-vs-tree crossover
# closed form (M* = 8*alpha*beta at D=4) against the measured link
# rates, the planner rankings + keyrange skew derating, and the ledger
# path over the Zipf fleet fixture (straggler-bound verdict, incumbent
# on top, modeled-vs-measured --check flag) — jax-free, seconds.
timeout -k 5 60 python tools/redplan.py --selftest || { echo "TIER1: redplan selftest FAILED"; exit 1; }
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
