#!/usr/bin/env python3
"""Per-op profile of one word-count chunk step: where the chunk budget goes.

The round-2 verdict's sort-floor criterion is stated in op shares ("sort
share < 50% of the op profile"), and the round-1 numbers that shaped the
design (BENCHMARKS.md "Where the remaining time goes") were captured by
hand.  This tool automates that capture: it runs one map+combine step over
a device-resident chunk under ``jax.profiler``, parses the XSpace with
``jax.profiler.ProfileData``, and prints the top device ops with their
share of total device time — one line per op family (sort, fusion,
gather/scatter, pallas kernel, ...).

Run on the chip:  python tools/opshare.py          (ambient backend)
CPU sanity:       JAX_PLATFORMS=cpu python tools/opshare.py

Env knobs: OPSHARE_CHUNK_MB (default 32), OPSHARE_SORT_MODE (sort3|segmin),
OPSHARE_SORT_IMPL (xla|radix|radix_partition — the round-6 radix A/B),
OPSHARE_MERGE_EVERY (default 1), OPSHARE_STEPS (steps profiled, default 4).
Prints a final JSON line {"sort_share": ..., "top": [...]} for machines.
"""

from __future__ import annotations

import json
import os
import re
import sys
import tempfile
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    from mapreduce_tpu.runtime.platform import force_cpu

    force_cpu()

import jax
import numpy as np


def classify(name: str) -> str:
    """Map an XLA op/event name to a coarse family."""
    n = name.lower()
    if "sort" in n:
        return "sort"
    if "custom-call" in n and ("mosaic" in n or "tpu" in n) or "pallas" in n:
        return "pallas-kernel"
    if "all-gather" in n or "all-reduce" in n or "collective" in n \
            or "permute" in n:
        return "collective"
    if "scatter" in n:
        return "scatter"
    if "gather" in n:
        return "gather"
    if "fusion" in n or "loop_" in n.replace("-", "_"):
        return "fusion/elementwise"
    if "copy" in n or "transpose" in n or "reshape" in n or "bitcast" in n:
        return "copy/layout"
    if "convert" in n or "broadcast" in n or "iota" in n:
        return "fusion/elementwise"
    return "other"


def main() -> int:
    chunk_mb = int(os.environ.get("OPSHARE_CHUNK_MB", "32"))
    steps = int(os.environ.get("OPSHARE_STEPS", "4"))

    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.parallel.mapreduce import Engine
    from mapreduce_tpu.parallel.mesh import data_mesh

    sort_mode = os.environ.get("OPSHARE_SORT_MODE", Config.sort_mode)
    if sort_mode == "segmin" and jax.default_backend() == "tpu" \
            and os.environ.get("OPSHARE_FORCE", "0") != "1":
        # Measured 2026-07-31: the 16.8M-row segmented associative_scan
        # wedges the tunnel chip for >30 min (twice in sortbench, once as a
        # full bench watchdog abort) — refusing beats burning half a live
        # window.  OPSHARE_FORCE=1 overrides (e.g. direct-attached chip).
        print(json.dumps({"skipped": "segmin on tpu: giant associative_scan "
                                     "wedges the tunnel chip (BENCHMARKS.md "
                                     "round-4)"}))
        return 0
    cfg = Config(chunk_bytes=chunk_mb << 20, table_capacity=1 << 18,
                 batch_unique_capacity=1 << 16,
                 sort_mode=sort_mode,
                 sort_impl=os.environ.get("OPSHARE_SORT_IMPL",
                                          Config.sort_impl),
                 merge_every=int(os.environ.get("OPSHARE_MERGE_EVERY", "1")),
                 compact_slots=(int(os.environ["OPSHARE_COMPACT_SLOTS"])
                                if "OPSHARE_COMPACT_SLOTS" in os.environ
                                else None))
    print(f"backend={jax.default_backend()} chunk={chunk_mb}MB "
          f"sort_mode={cfg.sort_mode} sort_impl={cfg.sort_impl} "
          f"merge_every={cfg.merge_every} steps={steps}", file=sys.stderr)

    rng = np.random.default_rng(3)
    data = rng.integers(97, 123, size=(1, cfg.chunk_bytes), dtype=np.uint8)
    data[rng.random(data.shape) < 0.16] = 0x20
    engine = Engine(WordCountJob(cfg), data_mesh(1))
    state = engine.init_states()
    staged = jax.device_put(data, engine.sharding)

    # Warm up (pay compiles) outside the trace.
    state = engine.step(state, staged, 0)
    np.asarray(jax.tree.leaves(state)[0].ravel()[:1])

    tmp = tempfile.mkdtemp(prefix="opshare_")
    with jax.profiler.trace(tmp):
        for s in range(1, steps + 1):
            state = engine.step(state, staged, s)
        np.asarray(jax.tree.leaves(state)[0].ravel()[:1])

    # Find the captured XSpace and aggregate device-plane event durations.
    xspaces = []
    for root, _dirs, files in os.walk(tmp):
        xspaces += [os.path.join(root, f) for f in files
                    if f.endswith(".xplane.pb")]
    if not xspaces:
        print(json.dumps({"error": f"no xplane.pb under {tmp}"}))
        return 1
    # Wrapper spans NEST leaf ops (a cond's duration includes the sort
    # inside its taken branch; jit_<step> spans the whole program), and
    # async copy-start durations OVERLAP compute until their copy-done —
    # summing any of them double-counts.  Round-4 calibration: with them
    # included this tool reported 333 ms/chunk where the end-to-end bench
    # measured 92 ms/chunk on the same config.  Leaf, non-async events
    # only; the program span is kept separately as the honest wall anchor.
    _wrapper = re.compile(r"^%?(jit_|cond|while|call|conditional|copy-start)")
    fam_us: dict[str, float] = defaultdict(float)
    op_us: dict[str, float] = defaultdict(float)
    program_us = 0.0
    program_lines = 0  # device lines that carried jit_ spans (one per device)
    for xs in xspaces:
        pd = jax.profiler.ProfileData.from_serialized_xspace(
            open(xs, "rb").read())
        for plane in pd.planes:
            pname = plane.name.lower()
            device_plane = ("tpu" in pname or "gpu" in pname
                            or re.search(r"/device:", pname))
            for line in plane.lines:
                # TPU/GPU: every line of the device plane is op events.
                # CPU: ops live in the host plane's tf_XLA* executor lines
                # (the python line would double-count wall time).
                if not (device_plane or line.name.startswith("tf_XLA")):
                    continue
                line_program_us = 0.0
                for ev in line.events:
                    if "::" in ev.name:  # runtime infra spans nest over ops
                        continue
                    dur = ev.duration_ns / 1e3
                    if ev.name.startswith("jit_"):
                        line_program_us += dur
                    if _wrapper.match(ev.name):
                        continue
                    fam_us[classify(ev.name)] += dur
                    op_us[ev.name] += dur
                # Each device line replays the same program on a mesh run;
                # summing across lines would report D devices' spans as one
                # chunk's cost (ADVICE r4).  Average over the lines that
                # carried program spans instead (on the single-device bench
                # chip this is a no-op: one line, same number).
                if line_program_us:
                    program_us += line_program_us
                    program_lines += 1
    total = sum(fam_us.values())
    if total <= 0:
        print(json.dumps({"error": "no device events captured",
                          "planes": [p.name for xs in xspaces
                                     for p in jax.profiler.ProfileData
                                     .from_serialized_xspace(
                                         open(xs, "rb").read()).planes]}))
        return 1
    print(f"{'family':24s} {'us':>12s}  share", file=sys.stderr)
    for fam, us in sorted(fam_us.items(), key=lambda kv: -kv[1]):
        print(f"{fam:24s} {us:12.0f}  {us / total:6.1%}", file=sys.stderr)
    top = sorted(op_us.items(), key=lambda kv: -kv[1])[:12]
    for name, us in top:
        print(f"  {name[:70]:70s} {us:10.0f} us", file=sys.stderr)
    print(json.dumps({
        "backend": jax.default_backend(),
        "chunk_mb": chunk_mb, "steps": steps,
        "sort_mode": cfg.sort_mode, "sort_impl": cfg.sort_impl,
        "merge_every": cfg.merge_every,
        "compact_slots": cfg.compact_slots,
        "total_device_us": round(total, 0),
        # Per-chunk numbers are averaged over the device lines that carried
        # program spans: on a D-device mesh every line replays the same
        # program, so raw sums would report D devices' work as one chunk's
        # cost (ADVICE r4) — and the leaf total must be scaled the same way
        # as the program span or their calibration gap becomes a Dx phantom.
        # Single-device runs (the bench chip): one line, numbers unchanged.
        "us_per_chunk": round(total / steps / max(program_lines, 1), 0),
        "program_us_per_chunk": round(program_us / program_lines / steps, 0)
        if program_us else None,
        "program_device_lines": program_lines or None,
        "sort_share": round(fam_us.get("sort", 0.0) / total, 4),
        "shares": {k: round(v / total, 4) for k, v in fam_us.items()},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
