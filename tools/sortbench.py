#!/usr/bin/env python3
"""Microbenchmark: aggregation-sort variants on the real chip.

The single-chip pipeline is sort-bound (BENCHMARKS.md: the 3-array 3-key
sort over 16.8M pair-compacted rows costs 25-85 ms of the ~102 ms chunk
budget).  This script times the candidate replacements in one process so
op shares are comparable (the tunnel chip has 2-4x run-to-run variance;
never compare wall-clock across runs).

Run on the chip:  python tools/sortbench.py          (ambient axon backend)
Run on CPU:       JAX_PLATFORMS=cpu python tools/sortbench.py

Timing rules (BENCHMARKS.md "Measurement rules"): sync by fetching a real
output element (block_until_ready is not a barrier through the tunnel),
poison each iteration's input with the previous output so XLA cannot hoist
or DCE the work, best-of-k.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    # Honor the documented CPU-sanity mode even when sitecustomize pinned a
    # remote platform at interpreter startup (the env var alone is too late
    # — same escape hatch as the CLI's --platform cpu).
    from mapreduce_tpu.runtime.platform import force_cpu

    force_cpu()

import jax
import jax.numpy as jnp
import numpy as np

# 16.8M default: one 32 MB chunk's pair-compacted stream.  SORTBENCH_LOG2
# shrinks it (e.g. 20 for CPU sanity runs).
ROWS = 1 << int(os.environ.get("SORTBENCH_LOG2", "24"))


def _sync(out):
    """Fetch ONE element (sliced on device first: np.asarray on the full
    array would ship the whole 67 MB over the tunnel inside the timing)."""
    np.asarray(jax.tree.leaves(out)[0].ravel()[:1])


def bench(name, fn, args, k=5):
    fn = jax.jit(fn)
    out = fn(*args)
    _sync(out)
    best = float("inf")
    for i in range(k):
        # Poison: fold one element of the previous output into arg 0 so
        # iteration i's input depends on i-1's output (no hoisting).
        poison = jnp.asarray(jax.tree.leaves(out)[0]).ravel()[0].astype(args[0].dtype)
        a0 = args[0].at[0].set(args[0][0] ^ poison) if args[0].dtype == jnp.uint32 \
            else args[0]
        t0 = time.perf_counter()
        out = fn(a0, *args[1:])
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    print(f"{name:45s} {best * 1e3:9.2f} ms")
    return best


def main():
    print(f"backend: {jax.devices()[0].platform}, rows: {ROWS}")
    rng = np.random.default_rng(0)
    # Realistic content: ~half the rows live (Zipf-ish key skew), rest
    # sentinel, like a real pair-compacted stream.
    n_tok = ROWS // 2
    zipf = rng.zipf(1.3, size=n_tok).astype(np.uint64) % 50_000
    khi = np.full(ROWS, 0xFFFFFFFF, np.uint32)
    klo = np.full(ROWS, 0xFFFFFFFF, np.uint32)
    packed = np.full(ROWS, 0xFFFFFFFF, np.uint32)
    live_idx = np.sort(rng.choice(ROWS, size=n_tok, replace=False))
    khi[live_idx] = (zipf * 2654435761 % (1 << 32)).astype(np.uint32)
    klo[live_idx] = (zipf * 40503 % (1 << 32)).astype(np.uint32)
    packed[live_idx] = ((live_idx.astype(np.uint64) * 2 % (1 << 26)) << 6 | 5).astype(np.uint32)
    khi, klo, packed = map(jnp.asarray, (khi, klo, packed))

    bench("sort 3 arrays, 3 keys (baseline)",
          lambda a, b, c: jax.lax.sort((a, b, c), num_keys=3), (khi, klo, packed))
    bench("sort 3 arrays, 2 keys (packed as payload)",
          lambda a, b, c: jax.lax.sort((a, b, c), num_keys=2), (khi, klo, packed))
    bench("sort 3 arrays, 2 keys, stable",
          lambda a, b, c: jax.lax.sort((a, b, c), num_keys=2, is_stable=True),
          (khi, klo, packed))
    bench("sort 2 arrays, 2 keys",
          lambda a, b: jax.lax.sort((a, b), num_keys=2), (khi, klo))
    bench("sort 3 arrays, 1 key (position sort)",
          lambda c, a, b: jax.lax.sort((c, a, b), num_keys=1), (packed, khi, klo))
    bench("sort 1 array, 1 key",
          lambda a: jax.lax.sort((a,), num_keys=1), (khi,))

    # Blocked: sort rows of a [K, B] view independently (axis sort).
    for B in (1 << 10, 1 << 12, 1 << 14):
        K = ROWS // B
        bench(f"blocked sort [K={K}, B={B}] 3 arr 3 keys",
              lambda a, b, c: jax.lax.sort(
                  (a.reshape(K, B), b.reshape(K, B), c.reshape(K, B)),
                  dimension=1, num_keys=3),
              (khi, klo, packed))

    # Segmented-min alternative to carrying packed as a sort key: sorted
    # (khi, klo) + associative_scan min with boundary resets.
    def seg_min(a, b, c):
        sa, sb, sc = jax.lax.sort((a, b, c), num_keys=2)
        boundary = (sa != jnp.concatenate([sa[:1], sa[:-1]])) | \
                   (sb != jnp.concatenate([sb[:1], sb[:-1]]))

        def combine(x, y):
            xf, xv = x
            yf, yv = y
            return (xf | yf, jnp.where(yf, yv, jnp.minimum(xv, yv)))

        _, m = jax.lax.associative_scan(combine, (boundary, sc))
        return m

    # Full aggregation (sort + rank reduce + table build): the number that
    # decides config.sort_mode — and the denominator for "sort share of the
    # chunk budget" (VERDICT r2 #1).
    from mapreduce_tpu.ops import table as table_ops

    cap = 1 << 18
    n_tok_u = jnp.uint32(n_tok)
    bench("from_packed_rows[sort3] full aggregation",
          lambda a, b, c: table_ops.from_packed_rows(
              a, b, c, n_tok_u, cap, 0, sort_mode="sort3"),
          (khi, klo, packed))
    # stable2 drops the third comparator key (first occurrence from tie
    # order); on this synthetic poisoned stream the positions are already
    # ascending, so the timing is the honest production shape.
    bench("from_packed_rows[stable2] full aggregation",
          lambda a, b, c: table_ops.from_packed_rows(
              a, b, c, n_tok_u, cap, 0, sort_mode="stable2"),
          (khi, klo, packed))

    # Radix partition/sort rows (BENCHMARKS.md round-6 pricing note): the
    # Pallas MSD digit partition behind Config.sort_impl, A/B'd against the
    # raw and full-aggregation XLA sorts above.  Off-TPU the kernel runs in
    # INTERPRET mode — orders of magnitude slower and meaningless to time —
    # so the rows are chip-only unless SORTBENCH_RADIX=1 opts in (tiny
    # SORTBENCH_LOG2 sanity runs).
    if jax.default_backend() == "tpu" \
            or os.environ.get("SORTBENCH_RADIX", "0") == "1":
        from mapreduce_tpu.ops.pallas import radix as radix_ops

        bench("radix_partition (1 level, B=8, + bucket sorts)",
              lambda a, b, c: radix_ops.radix_sort3(
                  a, b, c, impl="radix_partition"), (khi, klo, packed))
        bench("radix (2 levels, B=8 each)",
              lambda a, b, c: radix_ops.radix_sort3(a, b, c, impl="radix"),
              (khi, klo, packed))
        bench("from_packed_rows[stable2, radix_partition] full aggregation",
              lambda a, b, c: table_ops.from_packed_rows(
                  a, b, c, n_tok_u, cap, 0, sort_mode="stable2",
                  sort_impl="radix_partition"),
              (khi, klo, packed))
    else:
        print("radix rows skipped (interpret mode is not a measurement; "
              "SORTBENCH_RADIX=1 opts in for sanity runs)")

    # The per-step pairwise table merge (the other half of a streaming step).
    t_a = table_ops.from_packed_rows(khi, klo, packed, n_tok_u, cap, 0)
    t_b = table_ops.from_packed_rows(klo, khi, packed, n_tok_u, cap, 1)
    bench("pairwise table merge (cap 256K)",
          lambda a_hi, ta=t_a, tb=t_b: table_ops.merge(
              ta._replace(key_hi=a_hi), tb, capacity=cap),
          (t_a.key_hi,))

    # Scan-based variants LAST, gated: the 16.8M-row associative_scan hung
    # the tunnel chip for >30 min twice (2026-07-31, both suite runs stalled
    # at exactly this point after every plain sort completed) — the same
    # giant-scan pathology that rules out the XLA tokenizer on device.
    # SORTBENCH_SCAN=1 opts in (e.g. on CPU or a direct-attached chip).
    if os.environ.get("SORTBENCH_SCAN", "0") == "1":
        bench("2-key sort + segmented scan-min of packed", seg_min,
              (khi, klo, packed))
        bench("from_packed_rows[segmin] full aggregation",
              lambda a, b, c: table_ops.from_packed_rows(
                  a, b, c, n_tok_u, cap, 0, sort_mode="segmin"),
              (khi, klo, packed))
    else:
        print("scan-based variants skipped (SORTBENCH_SCAN=1 to opt in): "
              "the 16.8M-row associative_scan wedges the tunnel chip")


if __name__ == "__main__":
    main()
