#!/usr/bin/env python3
"""Render a run-ledger (and flight-recorder dump) into a run report.

Reads the JSONL run ledger the executor writes under ``--ledger``
(schema: docs/observability.md) and prints, per run:

* the run header (driver, job, devices, chunk geometry, input);
* throughput: steps, bytes, wall seconds, GB/s;
* the phase breakdown (read_wait / stage / dispatch / drain / reduce) with
  a bound classification — **dispatch-bound** (device queue full: compute
  or link is the ceiling), **read-bound** (the reader cannot keep ahead),
  or **stage-bound** (host assembly + H2D placement dominates) — the
  question VERDICT r4's 3x streamed-vs-H2D gap needed answered;
* anomalies: step-time spikes (elapsed > 3x the median step — recompiles
  and relay stalls look exactly like this), device memory growth across
  the run (leaked live arrays), retries, failures (with the flight-dump
  path), checkpoint cadence, compile cost;
* the **timeline** section (ISSUE 7), when the ledger carries ``group``
  lifecycle records: measured per-resource busy seconds, the device-idle
  total with per-lane blame, the pairwise overlap matrix, and the
  critical-path ``bottleneck`` verdict (bounding resource + projected
  saving were it infinitely fast) — reconstructed by
  ``mapreduce_tpu/obs/timeline.py``; ``tools/trace_export.py`` renders
  the same records as a Perfetto-viewable trace;
* the **data health** section (ISSUE 8), when the ledger carries the
  per-run ``data`` record: on-device spill-fallback / rescue-escalation /
  dropped-token counters, table occupancy, top-bucket mass (key skew) and
  stable2 window occupancy, classified by ``mapreduce_tpu/obs/
  datahealth.py`` into spill-bound / rescue-heavy / skew-hot /
  occupancy-starved / table-pressure verdicts — the data-shape fitness
  signal next to the timeline's resource verdict.

``--compare A.jsonl B.jsonl`` diffs two ledgers' phase shares, bound
classifications, bottleneck verdicts and data-health dicts in one table —
the render surface for A/B rows (pipeline/nopipeline, fused/split).
``--run-id`` selects one run from an append-mode ledger (render and
compare alike) instead of always the last completed one (ISSUE 13
satellite).  When per-host shard files (``<ledger>.h*.jsonl``, ledger
v7) sit next to the analyzed ledger, the report appends the **fleet
section** — per-host busy/collective seconds, straggler skew with
slowest-host attribution, the ``fleet_bottleneck`` verdict and
host-imbalance flags (``mapreduce_tpu/obs/fleet.py``) — and
``--compare`` gains the fleet rows.

Deliberately jax-free and stdlib-only: a wedged TPU box, a laptop, or CI
can all read the forensics of a run that happened somewhere else (the
timeline module is loaded by file path, not via the package).  Unknown
record kinds and unknown fields pass through untouched (ledger forward
compat): a future-versioned ledger still renders.

Usage::

    python tools/obs_report.py /path/run.jsonl           # human report
    python tools/obs_report.py /path/run.jsonl --json    # machine-readable
    python tools/obs_report.py --flight /path/run.jsonl.flight.json
    python tools/obs_report.py --selftest                # fixture-driven

``--selftest`` analyzes the checked-in miniature ledger + flight fixtures
(``tools/fixtures/``) and asserts the report's load-bearing facts, so the
whole reporting path is exercised in tier-1 without a TPU (ISSUE 2
satellite).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

SPIKE_FACTOR = 3.0  # a step slower than 3x the median step is an anomaly
SPIKE_FLOOR_S = 0.05  # ...unless everything is sub-noise fast
MEM_GROWTH_FACTOR = 1.5  # first->last live-bytes ratio that flags growth
MEM_GROWTH_FLOOR = 32 << 20  # ...and the absolute delta that makes it real

_OBS_MODS: dict = {}


def _obs_mod(name: str):
    """A jax-free obs module (``timeline``/``datahealth``), loaded by file
    path from the source tree (importing the package would pull
    config/jax); falls back to the installed package, and to None when
    neither exists — the report then simply drops that section."""
    if name not in _OBS_MODS:
        src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           os.pardir, "mapreduce_tpu", "obs", name + ".py")
        try:
            if os.path.exists(src):
                import importlib.util

                spec = importlib.util.spec_from_file_location(
                    f"_mapreduce_tpu_obs_{name}", src)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                _OBS_MODS[name] = mod
            else:
                import importlib

                _OBS_MODS[name] = importlib.import_module(
                    f"mapreduce_tpu.obs.{name}")
        except Exception:
            _OBS_MODS[name] = False  # degraded: report without that section
    return _OBS_MODS[name] or None


def _timeline_mod():
    return _obs_mod("timeline")


def _datahealth_mod():
    return _obs_mod("datahealth")


def _fleet_mod():
    return _obs_mod("fleet")


def fleet_view_for(ledger_path: str, run_id=None):
    """The fleet artifact for a ledger with ``<ledger>.h*.jsonl`` shard
    files next to it (ISSUE 13), or None on single-host ledgers / when
    the fleet module is unavailable — the report degrades to no fleet
    section, never an error."""
    fl = _fleet_mod()
    if fl is None:
        return None
    try:
        return fl.from_ledger(ledger_path, run_id)
    except Exception:
        return None


def render_fleet(view: dict, out) -> None:
    _fleet_mod().render(view, out)


def read_ledger(path: str):
    """Parse JSONL, skipping unparseable lines (crash-truncated records are
    expected forensics).  Mirrors mapreduce_tpu.obs.ledger.read_ledger but
    stays import-free so this tool runs without the package or jax."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def _median(xs):
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2


def _mem_bytes(mem: dict):
    """The comparable memory figure of a step record: the backend's
    bytes_in_use when it reports one (TPU/GPU), else the live-array
    aggregate (the CPU backend's only signal)."""
    if not mem:
        return None
    return mem.get("bytes_in_use", mem.get("live_bytes"))


def classify(phases: dict) -> str:
    """Bound classification over the streaming phases (not the end-of-
    stream tails or reduce: they time the stream END, not the steady
    state).  ``retire_wait`` — blocked on a full dispatch window — means
    the device is the ceiling and the window is doing its job.

    The classification is map-path-agnostic by design: on a FUSED run
    (run_start ``map_impl='fused'``) the whole map chain — tokenize
    included — executes inside ``dispatch``, so a bigger dispatch share
    than the same corpus' split run is the fusion working, not a
    regression.  :func:`map_flags` owns that attribution (the split-path
    tokenize/stage boundary this classifier was first written against no
    longer holds on fused runs)."""
    streaming = {k: phases.get(k, 0.0)
                 for k in ("read_wait", "stage", "dispatch", "retire_wait")}
    total = sum(streaming.values())
    if total <= 0:
        return "unknown"
    name, val = max(streaming.items(), key=lambda kv: kv[1])
    if val / total < 0.5:
        return "mixed"
    return {"read_wait": "read-bound", "stage": "stage-bound",
            "dispatch": "dispatch-bound",
            "retire_wait": "device-bound"}[name]


# Pipelining health thresholds (ISSUE 5): a run whose end-of-stream tails
# eat this share of the stream is drain-heavy (the window stopped feeding
# the device long before the stream ended); an overlap fraction below the
# floor means the loop spent most of the stream blocked — serialized.
DRAIN_HEAVY_FRAC = 0.25
OVERLAP_FLOOR = 0.5


def pipeline_flags(phases: dict, pipeline: dict | None) -> list:
    """Window-health findings from the run-end pipeline stats + phases:
    drain-heavy / overlap-starved runs and inflight misconfiguration
    (window never filled vs always full)."""
    flags = []
    stream = phases.get("stream") or sum(
        phases.get(k, 0.0) for k in ("read_wait", "stage", "dispatch",
                                     "retire_wait", "h2d_tail",
                                     "compute_tail", "drain"))
    tails = phases.get("h2d_tail", 0.0) + phases.get("compute_tail", 0.0) \
        + phases.get("drain", 0.0)
    if stream > 0 and tails / stream > DRAIN_HEAVY_FRAC:
        flags.append({
            "flag": "drain-heavy",
            "detail": (f"end-of-stream tails are {tails:.3f}s of "
                       f"{stream:.3f}s stream "
                       f"(h2d_tail={phases.get('h2d_tail', 0.0):.3f}s, "
                       f"compute_tail={phases.get('compute_tail', 0.0):.3f}s)"
                       " — the device finished the stream long after the "
                       "reader; deepen --inflight/--prefetch-depth or "
                       "shrink the superstep")})
    overlap = (pipeline or {}).get("overlap_fraction")
    if overlap is None and stream > 0:
        blocked = sum(phases.get(k, 0.0)
                      for k in ("read_wait", "retire_wait", "snapshot",
                                "h2d_tail", "compute_tail"))
        overlap = max(0.0, 1.0 - blocked / stream)
    if overlap is not None and overlap < OVERLAP_FLOOR:
        flags.append({
            "flag": "overlap-starved",
            "detail": (f"overlap fraction {overlap:.2f} < {OVERLAP_FLOOR}: "
                       "the driver loop spent most of the stream blocked "
                       "(serialized dispatch?); check inflight_groups > 1 "
                       "and the read_wait share")})
    if pipeline:
        cap = pipeline.get("inflight_groups") or 0
        depth_max = pipeline.get("depth_max")
        if cap > 1 and depth_max is not None and depth_max < cap:
            flags.append({
                "flag": "inflight-window-never-filled",
                "detail": (f"configured inflight_groups={cap} but observed "
                           f"depth peaked at {depth_max}: the reader/"
                           "staging side never fed a full window — the "
                           "extra depth buys nothing (raise prefetch_depth "
                           "or lower inflight_groups)")})
        full_frac = pipeline.get("full_frac")
        if cap > 1 and full_frac is not None and full_frac >= 0.9:
            flags.append({
                "flag": "inflight-window-always-full",
                "detail": (f"window hit capacity on {full_frac:.0%} of "
                           "dispatches: the device is the ceiling — a "
                           "deeper window may overlap more (or this is "
                           "simply compute-bound)")})
    return flags


def map_flags(header: dict | None, classification: str) -> list:
    """Map-path attribution (ISSUE 6): a FUSED run moved the whole map
    chain into the device dispatch, so host-side ceilings mean something
    different than they did on the split path — call that out instead of
    letting the split-era reading stand."""
    impl = (header or {}).get("map_impl")
    if impl != "fused":
        return []
    flags = []
    if classification in ("stage-bound", "read-bound"):
        flags.append({
            "flag": "fused-map-host-bound",
            "detail": (f"fused map run is {classification}: the fused "
                       "kernel deleted the device-side seam fix-up and "
                       "transpose/pad work, so the HOST side (reader/"
                       "staging) is now the ceiling — raise "
                       "--prefetch-depth / chunk size before blaming the "
                       "kernel")})
    return flags


def analyze_run(records: list) -> dict:
    """Summarize one run's records (already filtered to one run_id)."""
    start = next((r for r in records if r["kind"] == "run_start"), None)
    end = next((r for r in records if r["kind"] == "run_end"), None)
    steps = [r for r in records if r["kind"] == "step"]
    retries = [r for r in records if r["kind"] == "retry"]
    failures = [r for r in records if r["kind"] == "failure"]
    checkpoints = [r for r in records if r["kind"] == "checkpoint"]
    faults = [r for r in records if r["kind"] == "fault"]
    degrades = [r for r in records if r["kind"] == "degrade"]

    n_steps = sum(r.get("steps", 1) for r in steps)
    bytes_done = sum(r.get("group_bytes", 0) for r in steps)
    phases: dict = {}
    source = end.get("phases", {}) if end else {}
    if source:
        phases = dict(source)
    else:  # crashed run: reconstruct from the step deltas that DID land
        for r in steps:
            for k, v in r.get("phases", {}).items():
                phases[k] = phases.get(k, 0.0) + v
    wall = end.get("elapsed_s") if end else None

    # Step-time spikes: elapsed_s is wall since the previous record, so a
    # recompile or a stalled relay shows as one fat step.
    elapsed = [(r.get("step_first"), r["elapsed_s"])
               for r in steps if r.get("elapsed_s") is not None]
    med = _median([e for _, e in elapsed])
    spikes = [{"step": s, "elapsed_s": e, "median_s": round(med, 6)}
              for s, e in elapsed
              if med > 0 and e > SPIKE_FACTOR * med and e > SPIKE_FLOOR_S]

    # Memory growth: compare the first and last step records' figure.
    mem_first = next((_mem_bytes(r.get("mem")) for r in steps
                      if _mem_bytes(r.get("mem")) is not None), None)
    mem_last = next((_mem_bytes(r.get("mem")) for r in reversed(steps)
                     if _mem_bytes(r.get("mem")) is not None), None)
    mem_growth = None
    if mem_first and mem_last and mem_last > mem_first * MEM_GROWTH_FACTOR \
            and mem_last - mem_first > MEM_GROWTH_FLOOR:
        mem_growth = {"first_bytes": mem_first, "last_bytes": mem_last,
                      "ratio": round(mem_last / mem_first, 2)}

    compile_s = 0.0
    for r in steps:
        evs = r.get("compile_events", {})
        if isinstance(evs, dict):
            compile_s += sum(e.get("seconds", 0.0) for e in evs.values())
        else:  # pre-aggregation record shape: a list of single events
            compile_s += sum(e.get("seconds", 0.0) for e in evs)

    gbps = None
    if wall and bytes_done:
        gbps = bytes_done / 1e9 / wall
    pipeline = end.get("pipeline") if end else None
    header = {k: start.get(k) for k in
              ("driver", "job", "devices", "chunk_bytes", "superstep",
               "backend", "map_impl", "combiner", "geometry",
               "geometry_spec", "merge_strategy", "input",
               "retry", "ledger_version", "host", "processes",
               "fault_plan")} \
        if start else None
    classification = classify(phases)
    # Measured timeline (ISSUE 7): present only when the run carries
    # `group` lifecycle records AND the reconstructor is loadable.
    timeline = None
    if any(r.get("kind") == "group" for r in records):
        tl = _timeline_mod()
        if tl is not None:
            timeline = tl.reconstruct(records,
                                      run_id=records[0].get("run_id"))
    # Data health (ISSUE 8): present only when the run carries a `data`
    # record AND the classifier is loadable.
    data = next((r for r in records if r.get("kind") == "data"), None)
    data_health = None
    if data is not None:
        data = {k: v for k, v in data.items()
                if k not in ("ts", "run_id", "kind")}
        dh = _datahealth_mod()
        if dh is not None:
            data_health = dh.classify(data)
    # Autotune recommendation (ISSUE 10): the `tune` record of hint-mode
    # runs, passed through as-is (future shapes render defensively).
    tune = next((r for r in records if r.get("kind") == "tune"), None)
    if tune is not None:
        tune = {k: v for k, v in tune.items()
                if k not in ("ts", "run_id", "kind")}
    # Live-run heartbeat (ISSUE 14, ledger v8): the LAST `progress`
    # record — an in-flight/crashed run's cursor, completion fraction
    # and ETA; tools/obswatch.py renders the same records live.
    progress = next((r for r in reversed(records)
                     if r.get("kind") == "progress"), None)
    if progress is not None:
        progress = {k: v for k, v in progress.items()
                    if k not in ("ts", "run_id", "kind")}
    # Reliability verdict (ISSUE 15, ledger v9): present only when the
    # run carries fault/degrade/retry/failure records AND the classifier
    # is loadable — a fault-free run has no reliability section, exactly
    # like a data-record-free run has no data-health section.
    reliability = None
    if faults or degrades or retries or failures:
        dh = _datahealth_mod()
        if dh is not None and hasattr(dh, "classify_reliability"):
            reliability = dh.classify_reliability(
                records, run_id=records[0].get("run_id"))
    return {
        "started_ts": start.get("ts") if start else None,
        "progress": progress,
        "failure_count": len(failures),
        "timeline": timeline,
        "data": data,
        "data_health": data_health,
        "tune": tune,
        "pipeline": pipeline,
        "overlap_fraction": (pipeline or {}).get("overlap_fraction"),
        "pipeline_flags": pipeline_flags(phases, pipeline),
        "map_flags": map_flags(header, classification),
        "run_id": records[0].get("run_id"),
        "header": header,
        "completed": end is not None,
        "step_records": len(steps),
        "steps": n_steps,
        "bytes": bytes_done,
        "wall_s": wall,
        "gb_per_s": round(gbps, 4) if gbps is not None else None,
        "phases": {k: round(v, 4) for k, v in sorted(phases.items())},
        "classification": classification,
        "spikes": spikes,
        "mem_growth": mem_growth,
        "retries": len(retries),
        "failures": [{"step": f.get("step"), "error": f.get("error"),
                      "flight_dump": f.get("flight_dump")} for f in failures],
        "checkpoints": len(checkpoints),
        "compile_s": round(compile_s, 4),
        "faults": len(faults),
        "degrades": [d.get("ladder_step") for d in degrades],
        "reliability": reliability,
    }


def analyze(path: str) -> list:
    """All run INSTANCES in a ledger file, in first-appearance order.

    Instances, not just ids (ISSUE 13): the multi-host contract passes
    one shared run_id to every process, and a crash+relaunch recovery
    appends a second run under that id — every run_start opens a new
    instance (``obs/fleet.py``'s canonical ``split_instances`` rule), so
    a crashed attempt and its recovery analyze separately instead of
    fusing into a chimera (first header + last run_end + combined
    steps)."""
    records = read_ledger(path)
    fl = _fleet_mod()
    if fl is not None:
        return [analyze_run(recs)
                for _, _, recs in fl.split_instances(records)]
    # Standalone-copy fallback (this file shipped without the obs
    # modules): the same rule, inlined — fleet.split_instances is the
    # canonical implementation.
    by_run: list = []   # (run_id, records) per instance
    current: dict = {}  # run_id -> index into by_run
    for r in records:
        rid = r.get("run_id", "?")
        if r.get("kind") == "run_start" or rid not in current:
            current[rid] = len(by_run)
            by_run.append((rid, []))
        by_run[current[rid]][1].append(r)
    return [analyze_run(rs) for _, rs in by_run]


def render_run(a: dict, out) -> None:
    h = a["header"] or {}
    out.write(f"run {a['run_id']}  [{h.get('driver', '?')}/"
              f"{h.get('job', '?')}  devices={h.get('devices', '?')}  "
              f"chunk={h.get('chunk_bytes', '?')}  "
              f"superstep={h.get('superstep', '?')}  "
              f"backend={h.get('backend', '?')}]\n")
    if h.get("input"):
        out.write(f"  input: {', '.join(map(str, h['input']))}\n")
    status = "completed" if a["completed"] else "DID NOT COMPLETE"
    out.write(f"  {status}: {a['steps']} steps "
              f"({a['step_records']} records), {a['bytes']} bytes")
    if a["wall_s"] is not None:
        out.write(f", {a['wall_s']:.3f}s")
    if a["gb_per_s"] is not None:
        out.write(f", {a['gb_per_s']:.4f} GB/s")
    out.write("\n")
    # Live-run heartbeat (ISSUE 14, ledger v8): an incomplete run's last
    # `progress` record says where the stream cursor got to — the
    # difference between "crashed at 10%" and "crashed at 99%", and what
    # tools/obswatch.py tails while the run is still going.
    p = a.get("progress")
    if p and not a["completed"]:
        out.write(f"  in flight: {p.get('cursor_bytes', '?')} bytes")
        if p.get("frac") is not None:
            out.write(f" ({100 * p['frac']:.1f}%)")
        if p.get("gb_per_s") is not None:
            out.write(f", {p['gb_per_s']:.4f} GB/s")
        if p.get("eta_s") is not None:
            out.write(f", ETA {p['eta_s']:.1f}s")
        if p.get("inflight_depth") is not None:
            out.write(f", inflight {p['inflight_depth']}")
        out.write("\n")
    if a["phases"]:
        streaming = ("read_wait", "stage", "dispatch", "retire_wait")
        total = sum(v for k, v in a["phases"].items()
                    if k in streaming) or 1.0
        parts = []
        for k, v in a["phases"].items():
            share = f" ({100 * v / total:.0f}%)" if k in streaming else ""
            parts.append(f"{k}={v:.3f}s{share}")
        out.write(f"  phases: {'  '.join(parts)}\n")
    out.write(f"  bound: {a['classification']}")
    if a["compile_s"]:
        out.write(f"  (compiles: {a['compile_s']:.2f}s)")
    out.write("\n")
    if (a["header"] or {}).get("map_impl") == "fused":
        out.write("  map: fused (whole map chain — tokenize included — "
                  "runs inside dispatch; read dispatch shares of a "
                  "fused/split A/B with that in view)\n")
    # Kernel-geometry line (ISSUE 12): which certified geometry set the
    # run compiled — rendered only when it is NOT the shipped default
    # (the map_impl/combiner precedent).  Future/custom shapes (a spec
    # dict, an unknown label) print as-is, never crash.
    geom = (a["header"] or {}).get("geometry")
    if geom not in (None, "default"):
        spec = (a["header"] or {}).get("geometry_spec")
        out.write(f"  geometry: {geom}"
                  + (f" {spec}" if spec else "") + "\n")
    # Multi-host stamp (ISSUE 13, ledger v7): which host's view this run
    # record stream is, and how many processes the fleet had — the shard
    # files next to the ledger hold the other hosts' views.
    procs = (a["header"] or {}).get("processes")
    if procs not in (None, 1):
        out.write(f"  fleet: host {(a['header'] or {}).get('host', '?')} "
                  f"of {procs} processes (per-host shards: "
                  "<ledger>.h<p>.jsonl)\n")
    p = a.get("pipeline")
    if p:
        out.write(f"  pipeline: inflight={p.get('inflight_groups')}  "
                  f"prefetch={p.get('prefetch_depth')}  "
                  f"depth mean/max={p.get('depth_mean')}/"
                  f"{p.get('depth_max')}")
        if a.get("overlap_fraction") is not None:
            out.write(f"  overlap={a['overlap_fraction']:.2f}")
        out.write("\n")
    tl = a.get("timeline")
    if tl:
        bn = tl["bottleneck"]
        idle = tl["device_idle"]
        out.write(f"  timeline: {tl['groups']} groups over "
                  f"{tl['span_s']:.3f}s  device busy "
                  f"{bn['device_busy_s']:.3f}s  idle {idle['total_s']:.3f}s")
        if idle.get("blocked_on"):
            blame = ", ".join(
                f"{k} {v:.3f}s" for k, v in
                sorted(idle["blocked_on"].items(), key=lambda kv: -kv[1]))
            out.write(f" (blocked on: {blame})")
        out.write("\n")
        out.write(f"  bottleneck: {bn['resource']} — {bn['detail']}\n")
        overlaps = {k: v for k, v in tl.get("overlap_s", {}).items() if v}
        if overlaps:
            out.write("  overlap: " + "  ".join(
                f"{k}={v:.3f}s" for k, v in
                sorted(overlaps.items(), key=lambda kv: -kv[1])[:6]) + "\n")
    d = a.get("data")
    if d:
        out.write(f"  data: {d.get('chunks', '?')} chunks, "
                  f"{d.get('tokens', '?')} tokens")
        if d.get("dropped_tokens") is not None:
            out.write(f", dropped {d['dropped_tokens']}")
        if d.get("overlong"):
            out.write(f", overlong {d['overlong']} "
                      f"(rescued {d.get('rescued', 0)})")
        if d.get("fallback_chunks"):
            out.write(f", spill fallbacks {d['fallback_chunks']}")
        if d.get("table_occupancy") is not None:
            out.write(f", table {100 * d['table_occupancy']:.1f}% full")
        if d.get("top_mass") is not None:
            out.write(f", top-mass {100 * d['top_mass']:.2f}%")
        if d.get("window_occupancy") is not None:
            out.write(f", windows {100 * d['window_occupancy']:.0f}% full")
        out.write("\n")
        # Map-side combiner line (ISSUE 11): resolved mode + what the
        # hot-key cache actually bought this run.
        mode = d.get("combiner") or (a["header"] or {}).get("combiner")
        if (mode and mode != "off") or d.get("combiner_hits"):
            out.write(f"  combiner: {mode or '?'}")
            hits = d.get("combiner_hits")
            if hits:
                hr = d.get("combiner_hit_rate")
                out.write(f" — {hits} hits"
                          + (f" ({100 * hr:.2f}% of tokens)"
                             if hr is not None else ""))
                if d.get("combiner_rows_deleted") is not None:
                    out.write(f", {d['combiner_rows_deleted']} sort rows "
                              "deleted")
                out.write(f", {d.get('combiner_flushes', 0)} flushes "
                          f"({d.get('combiner_evicted', 0)} cold)")
            elif mode == "hot-cache":
                out.write(" — no hits (cache cold or fallback-dominated)")
            out.write("\n")
    health = a.get("data_health")
    if health:
        out.write(f"  data health: {health['verdict']}\n")
        for f in health.get("flags", []):
            out.write(f"  DATA {f['flag']}: {f['detail']}\n")
    t = a.get("tune")
    if t:
        changed = t.get("changed") or {}
        moves = ", ".join(
            f"{k} {v[0]} -> {v[1]}"
            if isinstance(v, (list, tuple)) and len(v) == 2
            else f"{k}: {v}" for k, v in changed.items())
        verdict = "converged" if t.get("converged") else (moves or "no move")
        out.write(f"  tune: {t.get('rule', '?')} — {verdict}\n")
        if t.get("reason"):
            out.write(f"    {t['reason']}\n")
    for f in a.get("pipeline_flags", []):
        out.write(f"  PIPELINE {f['flag']}: {f['detail']}\n")
    for f in a.get("map_flags", []):
        out.write(f"  MAP {f['flag']}: {f['detail']}\n")
    if a["checkpoints"] or a["retries"]:
        out.write(f"  checkpoints: {a['checkpoints']}  "
                  f"retries: {a['retries']}\n")
    # Reliability section (ISSUE 15, ledger v9): a degraded-but-alive or
    # chaos-tested run is VISIBLE, not mysterious.  The header's
    # fault_plan stamp names the chaos a chaotic run ran under.
    if (a["header"] or {}).get("fault_plan"):
        out.write(f"  chaos: fault_plan={a['header']['fault_plan']}\n")
    r = a.get("reliability")
    if r and (r.get("verdict") != "clean" or a.get("faults")):
        out.write(f"  reliability: {r.get('verdict', '?')}")
        sig = r.get("signals") or {}
        if sig.get("faults_total"):
            out.write(f"  ({sig.get('faults_injected', 0)} injected / "
                      f"{sig.get('faults_real', 0)} real faults)")
        out.write("\n")
        for f in r.get("flags", []):
            out.write(f"  RELIABILITY {f.get('flag', '?')}: "
                      f"{f.get('detail', '')}\n")
    for s in a["spikes"]:
        out.write(f"  ANOMALY step-time spike: step {s['step']} took "
                  f"{s['elapsed_s']:.3f}s vs median {s['median_s']:.3f}s "
                  "(recompile? relay stall?)\n")
    if a["mem_growth"]:
        g = a["mem_growth"]
        out.write(f"  ANOMALY memory growth: {g['first_bytes']} -> "
                  f"{g['last_bytes']} bytes ({g['ratio']}x) across the run "
                  "(leaked live arrays?)\n")
    for f in a["failures"]:
        out.write(f"  FAILURE at step {f['step']}: {f['error']}\n")
        if f.get("flight_dump"):
            out.write(f"    flight dump: {f['flight_dump']}\n")


# -- run enumeration (ISSUE 14 satellite) ------------------------------------

def run_status(a: dict) -> str:
    """completed / crashed / in-flight of one analyzed run — the one
    rule lives in ``obs/fleet.py`` (``run_status``), shared with
    ``obswatch`` and the ``history`` digests; the inline expression is
    the standalone-copy fallback."""
    fl = _fleet_mod()
    if fl is not None:
        return fl.run_status(bool(a.get("completed")),
                             int(a.get("failure_count") or 0))
    if a.get("completed"):
        return "completed"
    return "crashed" if a.get("failure_count") else "in-flight"


def list_runs(path: str) -> list:
    """Enumerate the run INSTANCES of an append-mode ledger (ISSUE 14
    satellite): ``--run-id`` requires already knowing the id — this is
    where the ids come from.  One row per instance, in file order, with
    the start wall time, family/backend and the geometry/combiner/
    map-impl stamps the A/B selectors key on."""
    rows = []
    for a in analyze(path):
        h = a.get("header") or {}
        rows.append({
            "run_id": a.get("run_id"),
            "started_ts": a.get("started_ts"),
            "status": run_status(a),
            "driver": h.get("driver"),
            "family": h.get("job"),
            "backend": h.get("backend"),
            "geometry": h.get("geometry") or "default",
            "combiner": h.get("combiner") or "off",
            "map_impl": h.get("map_impl") or "split",
            "steps": a.get("steps"),
            "bytes": a.get("bytes"),
            "gb_per_s": a.get("gb_per_s"),
            "cursor_frac": (a.get("progress") or {}).get("frac"),
        })
    return rows


def render_list(rows: list, out) -> None:
    import datetime

    for r in rows:
        ts = r.get("started_ts")
        when = datetime.datetime.fromtimestamp(ts).strftime(
            "%Y-%m-%d %H:%M:%S") if isinstance(ts, (int, float)) else "?"
        geom = "" if r["geometry"] == "default" else f" geom={r['geometry']}"
        comb = "" if r["combiner"] == "off" else f" combiner={r['combiner']}"
        tail = f"  {r['gb_per_s']:.4f} GB/s" if r.get("gb_per_s") else ""
        frac = f" @{100 * r['cursor_frac']:.0f}%" \
            if r["status"] != "completed" and r.get("cursor_frac") else ""
        out.write(f"{r['run_id']}  {when}  {r['status']}{frac}  "
                  f"[{r.get('family', '?')}/{r.get('backend', '?')}"
                  f"/{r['map_impl']}{geom}{comb}]  "
                  f"{r.get('steps', '?')} steps{tail}\n")


# -- A/B ledger diffing (ISSUE 8 satellite) ----------------------------------

_STREAMING_PHASES = ("read_wait", "stage", "dispatch", "retire_wait")


def _phase_shares(phases: dict) -> dict:
    total = sum(phases.get(k, 0.0) for k in _STREAMING_PHASES)
    if total <= 0:
        return {}
    return {k: phases.get(k, 0.0) / total for k in _STREAMING_PHASES
            if phases.get(k)}


def _pick_run(runs: list, run_id: str | None = None) -> dict | None:
    """The run a compare reads from one ledger: ``run_id`` when the
    caller selects one (ISSUE 13 satellite: an append-mode ledger holds
    many runs — bench keys on run_id, humans get the same selector),
    else the LAST completed run (the most recent measurement), else the
    last run at all.  An explicit id picks its LAST instance — the same
    rule ``obs/fleet.py`` applies, so a compare's phase rows and fleet
    rows describe the same execution."""
    if run_id is not None:
        matches = [a for a in runs if a.get("run_id") == run_id]
        return matches[-1] if matches else None
    done = [a for a in runs if a.get("completed")]
    pool = done or runs
    return pool[-1] if pool else None


def compare_runs(a: dict, b: dict) -> list:
    """Two analyzed runs -> comparison rows ``[label, A, B, delta]``
    (delta empty for non-numeric rows).  One table answers the A/B
    question the queued bench rows ask: where did the seconds move, did
    the bounding resource change, and did the DATA see the same world."""
    rows: list = []

    def num(label, va, vb, fmt="{:.4f}"):
        da = fmt.format(va) if isinstance(va, (int, float)) else "-"
        db = fmt.format(vb) if isinstance(vb, (int, float)) else "-"
        dd = fmt.format(vb - va) \
            if isinstance(va, (int, float)) and isinstance(vb, (int, float)) \
            else ""
        rows.append([label, da, db, dd])

    def text(label, va, vb):
        rows.append([label, str(va if va is not None else "-"),
                     str(vb if vb is not None else "-"), ""])

    num("gb_per_s", a.get("gb_per_s"), b.get("gb_per_s"))
    num("wall_s", a.get("wall_s"), b.get("wall_s"), "{:.3f}")
    sa, sb = _phase_shares(a.get("phases", {})), \
        _phase_shares(b.get("phases", {}))
    for k in _STREAMING_PHASES:
        if k in sa or k in sb:
            num(f"{k} share", sa.get(k, 0.0), sb.get(k, 0.0), "{:.0%}")
    text("bound", a.get("classification"), b.get("classification"))
    num("overlap_fraction", a.get("overlap_fraction"),
        b.get("overlap_fraction"), "{:.2f}")
    bna = (a.get("timeline") or {}).get("bottleneck") or {}
    bnb = (b.get("timeline") or {}).get("bottleneck") or {}
    if bna or bnb:
        text("bottleneck", bna.get("resource"), bnb.get("resource"))
        num("projected_saving_s", bna.get("projected_saving_s"),
            bnb.get("projected_saving_s"), "{:.3f}")
    ha, hb = a.get("data_health") or {}, b.get("data_health") or {}
    if ha or hb:
        text("data verdict", ha.get("verdict"), hb.get("verdict"))
        siga, sigb = ha.get("signals", {}), hb.get("signals", {})
        for k in ("top_mass", "fallback_frac", "overlong_frac",
                  "dropped_frac", "table_occupancy", "window_occupancy",
                  "distinct_ratio", "combiner_hit_rate"):
            va, vb = siga.get(k), sigb.get(k)
            if va is not None or vb is not None:
                num(k, va, vb, "{:.4f}")
    ga = (a.get("header") or {}).get("geometry")
    gb = (b.get("header") or {}).get("geometry")
    if (ga not in (None, "default")) or (gb not in (None, "default")):
        # The geometry A/B row (ISSUE 12): which arm compiled which
        # certified kernel-geometry set — the benchwatch
        # bench-zipf-geom / -geom-default readout.
        text("geometry", ga or "default", gb or "default")
    da, db = a.get("data") or {}, b.get("data") or {}
    ca, cb = da.get("combiner"), db.get("combiner")
    if (ca and ca != "off") or (cb and cb != "off"):
        # The combiner A/B row (ISSUE 11): which arm ran which mode, and
        # the net sort rows each deleted — the benchwatch
        # bench-zipf-combiner / -nocombiner readout.
        text("combiner", ca, cb)
        num("combiner_rows_deleted", da.get("combiner_rows_deleted"),
            db.get("combiner_rows_deleted"), "{:.0f}")
    ra, rb = a.get("reliability") or {}, b.get("reliability") or {}
    if ra or rb:
        # The reliability A/B row (ISSUE 15): did either arm degrade,
        # absorb faults, or run under a fault plan.
        text("reliability", ra.get("verdict"), rb.get("verdict"))
        num("faults", a.get("faults"), b.get("faults"), "{:.0f}")
    return rows


def compare(path_a: str, path_b: str, out, as_json: bool = False,
            run_id: str | None = None) -> int:
    """Diff two ledgers (phase shares, verdicts, data health) in one
    table; see ``compare_runs``.  ``run_id`` selects that run on both
    sides instead of each side's last completed one."""
    a = _pick_run(analyze(path_a), run_id)
    b = _pick_run(analyze(path_b), run_id)
    if a is None or b is None:
        print("compare: no runs found in "
              f"{path_a if a is None else path_b}"
              + (f" (run_id {run_id})" if run_id else ""), file=sys.stderr)
        return 1
    rows = compare_runs(a, b)
    # Fleet rows (ISSUE 13): when either side is a sharded multi-host
    # ledger, the A/B table also answers which arm's FLEET was bound by
    # what, and by how much.  Keyed on the PICKED run's id, so the fleet
    # rows always describe the same run as the phase/verdict rows above
    # (an append-mode ledger's last completed run need not be the
    # shards' last run).
    fa = fleet_view_for(path_a, run_id or a.get("run_id"))
    fb = fleet_view_for(path_b, run_id or b.get("run_id"))
    if fa or fb:
        bna = (fa or {}).get("fleet_bottleneck") or {}
        bnb = (fb or {}).get("fleet_bottleneck") or {}
        rows.append(["fleet verdict", str(bna.get("verdict", "-")),
                     str(bnb.get("verdict", "-")), ""])
        va, vb = bna.get("projected_saving_s"), bnb.get("projected_saving_s")
        rows.append(["fleet saving_s",
                     f"{va:.3f}" if isinstance(va, (int, float)) else "-",
                     f"{vb:.3f}" if isinstance(vb, (int, float)) else "-",
                     f"{vb - va:.3f}" if isinstance(va, (int, float))
                     and isinstance(vb, (int, float)) else ""])
        rows.append(["fleet imbalance",
                     str(((fa or {}).get("imbalance") or {})
                         .get("verdict", "-")),
                     str(((fb or {}).get("imbalance") or {})
                         .get("verdict", "-")), ""])
    if as_json:
        out.write(json.dumps({
            "a": {"ledger": path_a, "run_id": a.get("run_id")},
            "b": {"ledger": path_b, "run_id": b.get("run_id")},
            "rows": rows,
            "a_run": a, "b_run": b}) + "\n")
        return 0
    name_a = f"A={a.get('run_id')}"
    name_b = f"B={b.get('run_id')}"
    out.write(f"compare  A: {path_a} ({a.get('run_id')})  "
              f"B: {path_b} ({b.get('run_id')})\n")
    widths = [max(len(r[i]) if i else len(r[0]) for r in rows)
              for i in range(4)]
    widths = [max(w, len(h)) for w, h in
              zip(widths, ("metric", name_a, name_b, "delta"))]
    header = ["metric", name_a, name_b, "delta"]
    out.write("  " + "  ".join(h.ljust(w) for h, w in
                               zip(header, widths)).rstrip() + "\n")
    for r in rows:
        out.write("  " + "  ".join(c.ljust(w) for c, w in
                                   zip(r, widths)).rstrip() + "\n")
    return 0


def render_flight(path: str, out) -> None:
    with open(path, encoding="utf-8") as f:
        dump = json.load(f)
    ctx = dump.get("context", {})
    out.write(f"flight dump {path}\n")
    out.write(f"  context: {json.dumps(ctx)}\n")
    out.write(f"  events: {dump.get('events_kept', 0)} kept of "
              f"{dump.get('events_recorded', 0)} recorded\n")
    for e in dump.get("events", [])[-10:]:
        extra = {k: v for k, v in e.items() if k not in ("ts", "kind")}
        out.write(f"    {e.get('kind')} {json.dumps(extra)}\n")
    state = dump.get("state")
    if state:
        out.write(f"  state: {state.get('n_leaves')} leaves, "
                  f"{state.get('total_nbytes')} bytes\n")


def selftest() -> int:
    """Exercise the full analysis path on the checked-in fixtures and
    assert the report's load-bearing facts."""
    fdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
    ledger = os.path.join(fdir, "mini_ledger.jsonl")
    ledger_b = os.path.join(fdir, "mini_ledger_b.jsonl")
    flight = os.path.join(fdir, "mini_flight.json")
    runs = analyze(ledger)
    assert len(runs) == 10, f"fixture holds ten runs, got {len(runs)}"
    a = runs[0]
    assert a["completed"], "fixture run has a run_end record"
    assert a["steps"] == 6 and a["step_records"] == 6, \
        f"6 step records expected, got {a['steps']}/{a['step_records']}"
    assert a["bytes"] == 6 * 4 * (1 << 20), f"bytes wrong: {a['bytes']}"
    assert a["classification"] == "dispatch-bound", a["classification"]
    assert [s["step"] for s in a["spikes"]] == [4], a["spikes"]
    assert a["mem_growth"] and a["mem_growth"]["ratio"] > 4, a["mem_growth"]
    assert a["retries"] == 1 and a["checkpoints"] == 1
    assert a["compile_s"] > 0.5, a["compile_s"]
    # Run 1: the window was configured but never filled, and the loop was
    # mostly blocked — both ISSUE 5 misconfiguration flags must fire.
    assert a["pipeline"]["inflight_groups"] == 4
    assert a["overlap_fraction"] == 0.31
    flags = {f["flag"] for f in a["pipeline_flags"]}
    assert flags == {"overlap-starved", "inflight-window-never-filled"}, flags
    # Run 2: window always full + fat end-of-stream tails -> drain-heavy
    # and always-full, but NOT never-filled.
    b = runs[1]
    assert b["classification"] == "device-bound", b["classification"]
    bflags = {f["flag"] for f in b["pipeline_flags"]}
    assert bflags == {"drain-heavy", "overlap-starved",
                      "inflight-window-always-full"}, bflags
    # Runs 1-2 predate map_impl in the ledger (split-era records): the
    # header degrades to None and no map flag may fire.
    assert a["header"]["map_impl"] is None and not a["map_flags"]
    # Run 3: a FUSED run (ISSUE 6) that is stage-bound — the split-era
    # reading ("host assembly dominates, kernel fine") is now the
    # headline fact: the fused kernel deleted device-side map work, so
    # the host IS the ceiling, and the fused-specific flag must say so.
    c = runs[2]
    assert c["header"]["map_impl"] == "fused", c["header"]
    assert c["classification"] == "stage-bound", c["classification"]
    assert not c["pipeline_flags"], c["pipeline_flags"]
    cflags = {f["flag"] for f in c["map_flags"]}
    assert cflags == {"fused-map-host-bound"}, cflags
    # Runs 1-3 predate group records: no timeline section, by design —
    # and predate data records: no data-health section either (ISSUE 8).
    assert a["timeline"] is None and c["timeline"] is None
    assert a["data"] is None and a["data_health"] is None
    # Run 4 (ISSUE 7): a pipelined run carrying `group` lifecycle records.
    # Constructed reader-bound: two 0.2 s device-idle gaps both covered by
    # the reader lane, and 0.28 s of the 2.02 s span is reader-exclusive —
    # the timeline must name the reader as the critical path with exactly
    # those measured seconds.
    d = runs[3]
    assert d["header"]["ledger_version"] == 2, d["header"]
    tl = d["timeline"]
    assert tl is not None and tl["groups"] == 4, tl
    bn = tl["bottleneck"]
    assert bn["resource"] == "reader", bn
    assert round(bn["projected_saving_s"], 4) == 0.28, bn
    assert round(tl["device_idle"]["total_s"], 4) == 0.4, tl["device_idle"]
    assert [g["blocking"] for g in tl["device_idle"]["gaps"]] \
        == ["reader", "reader"], tl["device_idle"]
    assert round(tl["overlap_s"]["staging+device"], 4) == 0.1
    assert round(tl["overlap_s"]["reader+device"], 4) == 1.1
    assert round(tl["lane_busy_s"]["device"], 4) == 1.4
    # The phase classifier agrees with the measured timeline here (both
    # say the reader) — the timeline adds the HOW MUCH the deltas cannot.
    assert d["classification"] == "read-bound", d["classification"]
    # Run 5 in file order (ISSUE 10): a ledger-v4 autotune-hint run.  The
    # `tune` record (recommendation + decision trail) must surface next to
    # the verdicts it was derived from — here a reader-bound run whose
    # hint doubles prefetch_depth — and the other runs (no tune record)
    # must carry None.  (It sits BEFORE fixture05 in the file so the
    # spill-heavy run stays the --compare pick below.)
    g7 = runs[4]
    assert g7["header"]["ledger_version"] == 4, g7["header"]
    tn = g7["tune"]
    assert tn is not None and tn["rule"] == "raise-prefetch", tn
    assert tn["changed"] == {"prefetch_depth": [4, 8]}, tn["changed"]
    assert tn["converged"] is False and tn["mode"] == "hint", tn
    assert tn["signals"]["resource"] == "reader", tn["signals"]
    assert tn["trail"], "decision trail must ride the record"
    assert g7["timeline"]["bottleneck"]["resource"] == "reader", \
        "the tune hint and the timeline verdict describe the same run"
    # Run 6 in file order (ISSUE 11): a ledger-v5 combiner-on fused run.
    # Hand arithmetic: 42000 of 60000 tokens absorbed by the hot-key
    # cache (hit rate 0.7), 2000 flush rows re-emitted -> 40000 sort rows
    # deleted net, 150 cold entries; the top key at 12000/60000 = 20% is
    # skew-hot, and the flag's detail must say the combiner is already
    # absorbing the stream instead of recommending the knob.
    h8 = runs[5]
    assert h8["header"]["ledger_version"] == 5, h8["header"]
    assert h8["header"]["combiner"] == "hot-cache", h8["header"]
    assert h8["data"]["combiner"] == "hot-cache", h8["data"]
    h8sig = h8["data_health"]["signals"]
    assert h8sig["combiner_hit_rate"] == round(42000 / 60000, 6), h8sig
    assert h8sig["combiner_rows_deleted"] == 42000 - 2000, h8sig
    assert h8["data_health"]["verdict"] == "skew-hot", h8["data_health"]
    h8flag = next(f for f in h8["data_health"]["flags"]
                  if f["flag"] == "skew-hot")
    assert "absorbing 70.0%" in h8flag["detail"], h8flag
    # Run 7 in file order (ISSUE 13): a ledger-v7 two-host run's
    # coordinator view — host-stamped records, the processes/clock
    # topology in run_start, a `collective` record.  The header must
    # surface the stamp, and the collective record must pass through
    # every consumer (it sits BEFORE fixture05 so the spill run stays
    # the --compare pick below).
    p9 = runs[6]
    assert p9["header"]["ledger_version"] == 7, p9["header"]
    assert p9["header"]["host"] == 0 and p9["header"]["processes"] == 2, \
        p9["header"]
    assert p9["completed"] and p9["timeline"]["groups"] == 2, p9["timeline"]
    # Run 8 in file order (ISSUE 15): a ledger-v9 CHAOTIC run — a fault
    # plan fired two injected faults (dispatch crossing 2, token-wait
    # crossing 1), the transient one was absorbed by a retry, and the
    # resource one stepped the degradation ladder twice (tall512 ->
    # default geometry, then combiner off).  Hand arithmetic: 2 injected
    # / 0 real faults, retries {transient: 1, resource: 1}, verdict
    # `degraded` (degraded outranks chaos-tested in RELIABILITY_ORDER),
    # and the run_start fault_plan stamp must round-trip.
    ch = runs[7]
    assert ch["header"]["ledger_version"] == 9, ch["header"]
    assert ch["header"]["fault_plan"] \
        == "at=dispatch:2:transient,at=token-wait:1:resource", ch["header"]
    assert ch["completed"], "the chaotic run finished — degraded, alive"
    assert ch["faults"] == 2, ch["faults"]
    assert ch["degrades"] == ["revert-geometry", "combiner-off"], \
        ch["degrades"]
    rel = ch["reliability"]
    assert rel is not None and rel["verdict"] == "degraded", rel
    rsig = rel["signals"]
    assert rsig["faults_injected"] == 2 and rsig["faults_real"] == 0, rsig
    assert rsig["retries"] == 2 and rsig["retries_by_class"] \
        == {"transient": 1, "resource": 1}, rsig
    assert rsig["degrade_steps"] == ["revert-geometry", "combiner-off"]
    relflags = {f["flag"] for f in rel["flags"]}
    assert relflags == {"degraded", "chaos-tested"}, relflags
    # Fault-free runs carry NO reliability section at all (the section
    # only exists when there is something to report) — except fixture01,
    # whose single pre-taxonomy retry record classifies clean.
    assert c["reliability"] is None and d["reliability"] is None
    assert a["reliability"] is not None \
        and a["reliability"]["verdict"] == "clean", a["reliability"]
    # Run 9 in file order (ISSUE 8): a spill-heavy pallas run carrying
    # per-group `data` dicts and the per-run `data` record.  Checked
    # against the arithmetic done by hand on the fixture: 3 of 6 chunks
    # took the full-resolution fallback (fallback_frac 0.5 > the 5%
    # gate), overlong is 120/60000 = 0.2% of the stream with one tier-2
    # escalation, the top key carries 1500/60000 = 2.5% (NOT skew-hot at
    # the 5% gate), and 20 distinct keys spilled — so the verdict is
    # spill-bound with rescue-heavy and table-pressure riding along, and
    # nothing else.
    e = runs[8]
    assert e["header"]["ledger_version"] == 3, e["header"]
    assert e["data"] is not None and e["data"]["fallback_chunks"] == 3
    eh = e["data_health"]
    assert eh is not None, "data record must classify"
    sig = eh["signals"]
    assert sig["fallback_frac"] == round(3 / 6, 6), sig
    assert sig["overlong_frac"] == round(120 / 60000, 6), sig
    assert sig["rescued_frac"] == round(100 / 120, 6), sig
    assert sig["top_mass"] == round(1500 / 60000, 6), sig
    assert sig["window_occupancy"] == 0.6104, sig
    eflags = {f["flag"] for f in eh["flags"]}
    assert eflags == {"spill-bound", "rescue-heavy", "table-pressure"}, eflags
    assert eh["verdict"] == "spill-bound", eh["verdict"]
    # Per-group data dicts ride the group records into the timeline args.
    egroups = [r for r in read_ledger(ledger)
               if r.get("kind") == "group" and r.get("run_id") == "fixture05"]
    assert all("data" in g for g in egroups), egroups
    assert all(runs[i]["tune"] is None
               for i in (0, 1, 2, 3, 5, 6, 7, 8, 9)), \
        "runs without a tune record must carry None"
    # Run 9 in file order (ISSUE 14): a ledger-v8 run still IN FLIGHT —
    # no run_end, but two `progress` heartbeat records.  Hand arithmetic:
    # 16 MiB of the 32 MiB corpus at 8 MiB/s -> 50.0%, ETA 2.0 s.  The
    # report must surface the last heartbeat instead of a bare DID NOT
    # COMPLETE, and the status classifier must read in-flight (no
    # failure record), not crashed.
    w = runs[9]
    assert w["header"]["ledger_version"] == 8, w["header"]
    assert not w["completed"] and w["failure_count"] == 0
    assert w["progress"]["frac"] == 0.5, w["progress"]
    assert w["progress"]["eta_s"] == 2.0, w["progress"]
    assert run_status(w) == "in-flight"
    # --list-runs (ISSUE 14 satellite): one row per instance with the
    # stamps and status — where --run-id ids come from.
    lrows = list_runs(ledger)
    assert len(lrows) == 10, lrows
    byid = {r["run_id"]: r for r in lrows}
    assert byid["fixture10"]["status"] == "in-flight"
    assert byid["fixture10"]["cursor_frac"] == 0.5
    assert byid["fixture01"]["status"] == "completed"
    assert byid["fixture08"]["combiner"] == "hot-cache", byid["fixture08"]
    assert byid["fixture03"]["map_impl"] == "fused", byid["fixture03"]
    import io

    lbuf = io.StringIO()
    render_list(lrows, lbuf)
    ltext = lbuf.getvalue()
    assert "fixture10" in ltext and "in-flight @50%" in ltext, ltext
    assert ltext.count("\n") == 10, ltext
    # --run-id (ISSUE 13 satellite): an append-mode ledger's compare pick
    # honors an explicit selector instead of always the last completed
    # run, and an absent id is an honest miss, not a silent fallback.
    assert _pick_run(runs, "fixture01")["run_id"] == "fixture01"
    assert _pick_run(runs, "no-such-run") is None
    assert _pick_run(runs)["run_id"] == "fixture05"
    # The clean A/B counterpart (mini_ledger_b): uniform corpus, no
    # fallbacks, top key at 24/60000 = 0.04% — verdict clean; the pair is
    # the checked-in proof that a hot-key corpus and a uniform one are
    # DISTINGUISHABLE from the ledger alone.
    runs_b = analyze(ledger_b)
    assert len(runs_b) == 1, runs_b
    f6 = runs_b[0]
    assert f6["data_health"]["verdict"] == "clean", f6["data_health"]
    assert not f6["data_health"]["flags"]
    assert f6["data_health"]["signals"]["top_mass"] == round(24 / 60000, 6)
    # fixture06 is also the ledger-v6 geometry-stamped run (ISSUE 12):
    # the searched 'tall512' label must surface in the header and render
    # as a geometry line, while runs with no stamp (every mini_ledger
    # run) degrade to None and render nothing.
    assert f6["header"]["ledger_version"] == 6, f6["header"]
    assert f6["header"]["geometry"] == "tall512", f6["header"]
    assert h8["header"]["geometry"] is None, h8["header"]
    # The human renderer must run over all artifacts without raising.
    import io

    buf = io.StringIO()
    render_run(a, buf)
    render_run(b, buf)
    render_run(c, buf)
    render_run(d, buf)
    render_run(e, buf)
    render_run(g7, buf)
    render_run(h8, buf)
    render_run(f6, buf)
    render_run(p9, buf)
    render_run(ch, buf)
    render_run(w, buf)
    render_flight(flight, buf)
    body = buf.getvalue()
    assert "in flight: 16777216 bytes (50.0%)" in body, body
    assert "ETA 2.0s" in body, body
    assert "fleet: host 0 of 2 processes" in body, body
    assert ("combiner: hot-cache — 42000 hits (70.00% of tokens), "
            "40000 sort rows deleted, 2000 flushes (150 cold)") in body, body
    assert "ANOMALY step-time spike" in body
    assert "ANOMALY memory growth" in body
    assert "injected device fault" in body
    assert "PIPELINE inflight-window-never-filled" in body
    assert "PIPELINE drain-heavy" in body
    assert "pipeline: inflight=4" in body
    assert "map: fused" in body
    assert "MAP fused-map-host-bound" in body
    assert "timeline: 4 groups" in body
    assert "bottleneck: reader" in body
    assert "blocked on: reader 0.400s" in body
    assert "geometry: tall512" in body
    assert "data health: spill-bound" in body
    assert "DATA spill-bound" in body and "DATA rescue-heavy" in body
    assert "spill fallbacks 3" in body
    assert "tune: raise-prefetch — prefetch_depth 4 -> 8" in body
    # The reliability section (ISSUE 15): a degraded-but-alive chaos run
    # is rendered visibly — plan stamp, verdict, the ladder walked.
    assert ("chaos: fault_plan=at=dispatch:2:transient,"
            "at=token-wait:1:resource") in body, body
    assert "reliability: degraded  (2 injected / 0 real faults)" in body, \
        body
    assert "RELIABILITY degraded" in body \
        and "revert-geometry -> combiner-off" in body, body
    assert "RELIABILITY chaos-tested" in body, body
    # A/B ledger diffing (ISSUE 8 satellite): the spill-heavy run vs the
    # clean uniform counterpart must render one table naming both data
    # verdicts, and the machine-readable form must carry the rows.
    cbuf = io.StringIO()
    assert compare(ledger, ledger_b, cbuf) == 0
    ctext = cbuf.getvalue()
    assert "A=fixture05" in ctext and "B=fixture06" in ctext, ctext
    assert "data verdict" in ctext and "spill-bound" in ctext \
        and "clean" in ctext, ctext
    assert "fallback_frac" in ctext and "top_mass" in ctext, ctext
    # The geometry A/B row (ISSUE 12): the unstamped spill run reads as
    # 'default' against fixture06's searched 'tall512'.
    grow = next(line for line in ctext.splitlines()
                if line.strip().startswith("geometry"))
    assert "default" in grow and "tall512" in grow, grow
    cjson = io.StringIO()
    assert compare(ledger, ledger_b, cjson, as_json=True) == 0
    cobj = json.loads(cjson.getvalue())
    assert cobj["a"]["run_id"] == "fixture05" \
        and cobj["b"]["run_id"] == "fixture06", cobj
    assert any(r[0] == "data verdict" for r in cobj["rows"]), cobj["rows"]
    # Fleet section (ISSUE 13): the two-host shard fixtures next to
    # fleet_ledger.jsonl merge into the cross-host view — straggler
    # verdict + host-imbalance flag rendered under the run report — and
    # the --compare table gains the fleet rows when either side shards.
    fview = fleet_view_for(os.path.join(fdir, "fleet_ledger.jsonl"))
    assert fview is not None and fview["hosts"] == [0, 1], fview
    assert fview["fleet_bottleneck"]["verdict"] == "straggler-bound", fview
    assert fview["straggler"]["total_skew_s"] == 2.0, fview["straggler"]
    assert fview["imbalance"]["verdict"] == "host-imbalance", fview
    fbuf = io.StringIO()
    render_fleet(fview, fbuf)
    fbody = fbuf.getvalue()
    assert "fleet bottleneck: straggler-bound" in fbody, fbody
    assert "FLEET host-imbalance" in fbody, fbody
    assert fleet_view_for(ledger) is None, \
        "a shardless ledger must degrade to no fleet section"
    fcmp = io.StringIO()
    assert compare(os.path.join(fdir, "fleet_ledger.jsonl"), ledger_b,
                   fcmp) == 0
    ftext = fcmp.getvalue()
    assert "fleet verdict" in ftext and "straggler-bound" in ftext, ftext
    # Ledger forward compat (ISSUE 7 satellite): a future-versioned ledger
    # with unknown kinds and unknown fields must analyze and render
    # without error, and still surface the facts it does understand —
    # including a future-shaped `data` record with extra fields (ISSUE 8).
    fruns = analyze(os.path.join(fdir, "future_ledger.jsonl"))
    assert len(fruns) == 1, fruns
    f = fruns[0]
    assert f["header"]["ledger_version"] == 99, f["header"]
    assert f["completed"] and f["steps"] == 1 and f["bytes"] == 1024
    assert f["timeline"] is not None and f["timeline"]["groups"] == 1, \
        "the malformed future group record must be skipped, not fatal"
    assert f["data"] is not None and f["data_health"] is not None, \
        "the future data record must classify (extra fields ignored)"
    assert f["data_health"]["verdict"] == "skew-hot", f["data_health"]
    # The future-shaped `tune` record (unknown rule, non-knob changes, an
    # opaque trail) must pass through and render without error (ISSUE 10
    # forward compat).
    assert f["tune"] is not None and f["tune"]["rule"] == "warp-rebalance"
    # The future-shaped fault/degrade records (unknown fault class,
    # unknown ladder step) must classify without error (ISSUE 15 forward
    # compat): an injected fault + a degrade step read `degraded`.
    assert f["reliability"] is not None \
        and f["reliability"]["verdict"] == "degraded", f["reliability"]
    # The future-shaped geometry stamp (a spec dict where the label
    # string lives today) must surface and render without error.
    assert f["header"]["geometry"] == {"block_rows": 1024,
                                       "warp_slots": 7}, f["header"]
    render_run(f, io.StringIO())
    print("obs_report selftest ok "
          f"({a['step_records']} records, {len(a['spikes'])} spike, "
          "1 memory-growth flag, "
          f"{len(a['pipeline_flags']) + len(b['pipeline_flags'])} "
          f"pipeline flags, {len(c['map_flags'])} map flag, "
          f"timeline bottleneck={bn['resource']}, "
          f"data health={eh['verdict']}, tune rule={tn['rule']}, "
          f"geometry={f6['header']['geometry']}, "
          f"fleet={fview['fleet_bottleneck']['verdict']}, "
          f"reliability={rel['verdict']}, "
          "run-id selector ok, compare ok, future-ledger ok)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a mapreduce_tpu run ledger / flight dump")
    ap.add_argument("ledger", nargs="?", help="JSONL run-ledger path")
    ap.add_argument("--flight", default=None,
                    help="flight-recorder dump to render (default: any "
                         "<ledger>.flight.json that exists)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable analysis instead")
    ap.add_argument("--run-id", default=None,
                    help="select one run from an append-mode ledger "
                         "(default: render every run; --compare defaults "
                         "to each side's last completed run)")
    ap.add_argument("--list-runs", action="store_true",
                    help="enumerate the ledger's run instances (run_id, "
                         "start time, family/backend/stamps, completed/"
                         "crashed/in-flight) — where --run-id ids come "
                         "from")
    ap.add_argument("--compare", nargs=2, metavar=("A", "B"), default=None,
                    help="diff two ledgers' phase shares, bound/bottleneck "
                         "verdicts and data-health dicts in one table "
                         "(each side uses its last completed run unless "
                         "--run-id selects one)")
    ap.add_argument("--selftest", action="store_true",
                    help="run against the checked-in fixtures and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.list_runs:
        if not args.ledger:
            ap.error("--list-runs requires a ledger path")
        rows = list_runs(args.ledger)
        if args.json:
            print(json.dumps(rows))
        else:
            render_list(rows, sys.stdout)
        if not rows:
            print("no runs found", file=sys.stderr)
            return 1
        return 0
    if args.compare:
        return compare(args.compare[0], args.compare[1], sys.stdout,
                       as_json=args.json, run_id=args.run_id)
    if not args.ledger and not args.flight:
        ap.error("a ledger path (or --flight, --compare, or --selftest) "
                 "is required")
    runs = analyze(args.ledger) if args.ledger else []
    if args.run_id is not None and args.ledger:
        # Flight-only invocations (--flight without a ledger) skip the
        # filter: there are no runs to select from.
        runs = [a for a in runs if a.get("run_id") == args.run_id]
        if not runs:
            print(f"no run {args.run_id!r} in {args.ledger}",
                  file=sys.stderr)
            return 1
    # Fleet section (ISSUE 13): a multi-host ledger's shard files merge
    # into the cross-host view right under the per-run reports.
    fleet = fleet_view_for(args.ledger, args.run_id) if args.ledger else None
    flight = args.flight
    if flight is None and args.ledger \
            and os.path.exists(args.ledger + ".flight.json"):
        flight = args.ledger + ".flight.json"
    if args.json:
        print(json.dumps({"runs": runs, "flight": flight, "fleet": fleet}))
        return 0
    for a in runs:
        render_run(a, sys.stdout)
    if fleet:
        render_fleet(fleet, sys.stdout)
    if flight:
        render_flight(flight, sys.stdout)
    if not runs and not flight:
        print("no records found", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
