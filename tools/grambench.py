#!/usr/bin/env python3
"""Microbenchmark: packed vs generic n-gram table build on the real chip.

Round 5 moved gram aggregation onto the stable2 packed path (pos<<7|len in
one uint32; ops/ngram.py `gram_table`): a 3-array 2-key stable sort instead
of the generic 7-array 4-key build (~2.3x the sorted bytes).  This script
times the whole per-chunk bigram map (fused kernel -> position sort ->
pairing -> table build) under both builds in one process, so the delta is
attributable to the build alone.

Run on the chip:  python tools/grambench.py          (ambient axon backend)
Run on CPU:       JAX_PLATFORMS=cpu GRAMBENCH_MB=1 python tools/grambench.py

Timing rules (BENCHMARKS.md "Measurement rules"): sync by fetching a real
output element, poison each iteration's input with the previous output so
XLA cannot hoist or DCE, best-of-k.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    from mapreduce_tpu.runtime.platform import force_cpu

    force_cpu()

import jax
import jax.numpy as jnp
import numpy as np

CHUNK_MB = int(os.environ.get("GRAMBENCH_MB", "32"))
REPEATS = int(os.environ.get("GRAMBENCH_REPEATS", "5"))
N = int(os.environ.get("GRAMBENCH_N", "2"))


def _sync(out):
    np.asarray(jax.tree.leaves(out)[0].ravel()[:1])


def bench(name, fn, chunk, k=REPEATS):
    fn = jax.jit(fn)
    out = fn(chunk)
    _sync(out)
    best = float("inf")
    for _ in range(k):
        poison = jnp.asarray(jax.tree.leaves(out)[0]).ravel()[0].astype(jnp.uint8)
        c0 = chunk.at[0].set(chunk[0] | (poison & jnp.uint8(0)))  # dep, no-op
        t0 = time.perf_counter()
        out = fn(c0)
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    print(f"{name:40s} {best * 1e3:9.2f} ms", flush=True)
    return best


def main():
    from bench import make_natural_corpus
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import _pad_for_backend
    from mapreduce_tpu.ops import ngram as ngram_ops
    from mapreduce_tpu.ops import table as table_ops
    from mapreduce_tpu.ops.pallas import tokenize as pallas_tok

    cfg = Config(backend="pallas", chunk_bytes=CHUNK_MB << 20)
    capacity = cfg.batch_uniques
    data = make_natural_corpus(CHUNK_MB << 20)
    chunk = jnp.asarray(_pad_for_backend(data, cfg))
    print(f"backend: {jax.devices()[0].platform}, chunk: {CHUNK_MB} MB, "
          f"n={N}, capacity={capacity}", flush=True)

    def gram_stream(c):
        col, seam, _ = pallas_tok.tokenize_split(
            c, max_token_bytes=cfg.pallas_max_token)
        stream = pallas_tok.concat_streams(col, seam)
        key_hi, key_lo, packed = ngram_ops.position_sorted(stream)
        return ngram_ops.mark_long_spans(
            ngram_ops.grams_from_sorted(key_hi, key_lo, packed, N))

    def packed_map(c):
        gs = gram_stream(c)
        return ngram_ops.gram_table(gs, capacity, 0, max_pos=c.shape[0],
                                    sort_mode="stable2")

    def generic_map(c):
        gs = gram_stream(c)
        return table_ops.from_stream(gs, capacity, pos_hi=0)

    t_packed = bench("bigram map, packed stable2 build", packed_map, chunk)
    t_generic = bench("bigram map, generic 7-array build", generic_map, chunk)
    print(json.dumps({
        "tool": "grambench", "chunk_mb": CHUNK_MB, "n": N,
        "packed_ms": round(t_packed * 1e3, 2),
        "generic_ms": round(t_generic * 1e3, 2),
        "speedup": round(t_generic / t_packed, 3),
        "backend": jax.devices()[0].platform,
    }), flush=True)


if __name__ == "__main__":
    main()
