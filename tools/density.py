#!/usr/bin/env python3
"""Token-density + overlong-token measurement over the bench corpora (CPU).

Two round-4 design questions need numbers, not guesses:

1. **Compaction slot budget** (VERDICT r3 #2): the pallas kernel's output is
   one row per 2 input bytes because that is the worst-case emission rate;
   a slot-compacted output of B slots per W-byte window is lossless only
   when no window ever holds more than B token ends.  What budget do real
   corpora need, at the kernel's (block_rows x 128-lane) window geometry?

2. **>W-token envelope** (VERDICT r3 #6): the pallas backend drops tokens
   longer than W=32 bytes into dropped_* accounting while the XLA backend
   counts them exactly.  How big is that divergence on natural-ish text?

Prints one JSON line per corpus.  Pure numpy — runs anywhere, no JAX.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mapreduce_tpu import constants  # noqa: E402


def token_ends(buf: np.ndarray) -> np.ndarray:
    """bool[n]: byte i ends a token (i non-sep, i+1 sep-or-EOF)."""
    sep = np.zeros(256, np.bool_)
    for b in constants.SEPARATOR_BYTES:
        sep[b] = True
    is_sep = sep[buf]
    nxt = np.concatenate([is_sep[1:], [True]])
    return (~is_sep) & nxt


def token_lengths(buf: np.ndarray) -> np.ndarray:
    """int array of token lengths, in order."""
    sep = np.zeros(256, np.bool_)
    for b in constants.SEPARATOR_BYTES:
        sep[b] = True
    is_sep = sep[buf]
    # Run-length over non-sep runs.
    d = np.diff(np.concatenate([[True], is_sep, [True]]).astype(np.int8))
    starts = np.flatnonzero(d == -1)
    ends = np.flatnonzero(d == 1)
    return ends - starts


def window_density(buf: np.ndarray, window: int) -> np.ndarray:
    """Token-end count per aligned `window`-byte window (the kernel's
    (block, lane) cell is exactly such a window of block_rows bytes)."""
    ends = token_ends(buf)
    n = (len(ends) // window) * window
    return ends[:n].reshape(-1, window).sum(axis=1)


def analyze(name: str, data: bytes, windows=(256, 512),
            budgets=(1 / 4, 5 / 16, 11 / 32, 3 / 8, 1 / 2)) -> dict:
    buf = np.frombuffer(data, dtype=np.uint8)
    lens = token_lengths(buf)
    n_tok = len(lens)
    out = {
        "corpus": name,
        "bytes": len(buf),
        "tokens": n_tok,
        "density": round(n_tok / len(buf), 4),
        "overlong_gt32_tokens": int((lens > 32).sum()),
        "overlong_gt32_rate": float((lens > 32).mean()),
        "overlong_gt63_tokens": int((lens > 63).sum()),
        "max_token_len": int(lens.max()),
    }
    for w in windows:
        dens = window_density(buf, w)
        row = {"max_ends": int(dens.max()),
               "p999_ends": int(np.quantile(dens, 0.999))}
        for b in budgets:
            slots = int(b * w)
            row[f"overflow_rate_b{slots}"] = float((dens > slots).mean())
        out[f"window{w}"] = row
    return out


def main() -> int:
    from bench import make_natural_corpus, make_zipf_corpus

    mb = int(os.environ.get("DENSITY_MB", "32"))
    corpora = {
        "synthetic-zipf": make_zipf_corpus(mb << 20),
        "synthetic-natural": make_natural_corpus(mb << 20),
    }
    fixture = os.path.join(REPO, "test.txt")
    if os.path.exists(fixture):
        corpora["test.txt"] = open(fixture, "rb").read()
    for name, data in corpora.items():
        print(json.dumps(analyze(name, data)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
