#!/usr/bin/env python3
"""Chaos tooling for the fault-injection harness (ISSUE 15).

Jax-free and stdlib-only: ``runtime/faults.py`` is loaded by file path
(the obs_report pattern), so this runs on boxes with neither jax nor the
package installed — CI's tier-1/smoke gates run ``--selftest`` before
pytest ever imports jax.

Usage::

    python tools/chaos.py --selftest            # hand-computed fixtures
    python tools/chaos.py --plan 'seed=7,rate=0.1' --walk 20
                                                # which crossings fire?
    python tools/chaos.py --replay run.jsonl    # ledger -> replay spec

``--replay`` is the fault-plan replay workflow (docs/robustness.md):
read a chaotic run's own ``fault`` records, rebuild the exact injection
schedule (``FaultPlan.from_ledger``), and print the canonical spec to
hand to ``--fault-plan`` / ``Config.fault_plan`` for a fault-for-fault
identical rerun.

``--selftest`` checks the module's arithmetic against values computed by
hand:

* backoff: base 0.05 s, factor 2, cap 5 s, no jitter -> 0.05, 0.1, 0.2,
  0.4, 0.8, 1.6, 3.2, 5.0 (capped), 5.0;
* jitter: deterministic per (seed, seam, class, attempt), bounded by
  ``base * (1 +/- jitter_frac)``, different across seams/seeds;
* ladder: a full-featured config walks revert-geometry -> combiner-off
  -> map-split -> sort-xla and an already-degraded config walks only its
  remaining steps;
* plan determinism: same seed -> same firing set, rate=0 never fires,
  ``max`` bounds the count, explicit ``at=`` events fire regardless;
* spec round-trip and ledger replay over the checked-in chaotic fixture
  run (tools/fixtures/mini_ledger.jsonl, fixture11).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_FAULTS = None


def faults_mod():
    """``mapreduce_tpu.runtime.faults`` loaded WITHOUT the package
    (importing it would pull config -> jax)."""
    global _FAULTS
    if _FAULTS is None:
        src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           os.pardir, "mapreduce_tpu", "runtime",
                           "faults.py")
        if os.path.exists(src):
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "_mapreduce_tpu_runtime_faults", src)
            mod = importlib.util.module_from_spec(spec)
            # dataclass processing resolves cls.__module__ through
            # sys.modules — a file-loaded module must register first.
            sys.modules[spec.name] = mod
            spec.loader.exec_module(mod)
            _FAULTS = mod
        else:
            import importlib

            _FAULTS = importlib.import_module(
                "mapreduce_tpu.runtime.faults")
    return _FAULTS


def read_jsonl(path: str) -> list:
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail / crash-truncated record
    return out


def replay(path: str, run_id=None, out=sys.stdout) -> int:
    """Ledger -> the replay plan: the canonical spec plus the fired
    sequence it encodes.  Exit 1 when the ledger holds no injected
    ``fault`` records (nothing to replay — an honest miss)."""
    fm = faults_mod()
    records = read_jsonl(path)
    seq = fm.fired_sequence(records, run_id=run_id)
    if not seq:
        print(f"chaos replay: no injected fault records in {path}"
              + (f" (run_id {run_id})" if run_id else ""), file=sys.stderr)
        return 1
    plan = fm.FaultPlan.from_ledger(records, run_id=run_id)
    out.write(f"replay plan for {path}:\n")
    out.write(f"  --fault-plan '{plan.spec}'\n")
    for seam, index, fcls in seq:
        out.write(f"  {seam} crossing {index}: {fcls}\n")
    return 0


def walk_plan(spec: str, crossings: int, out=sys.stdout) -> int:
    """Print the deterministic firing decisions of a plan's first N
    crossings per seam — what WOULD a run under this plan see."""
    fm = faults_mod()
    plan = fm.FaultPlan.from_spec(spec)
    out.write(f"plan {plan.spec}\n")
    fired = 0
    for seam in fm.SEAMS:
        for i in range(crossings):
            if plan.max_faults and fired >= plan.max_faults:
                break
            cls = plan.decide(seam, i)
            if cls is not None:
                out.write(f"  {seam} crossing {i}: {cls}\n")
                fired += 1
    out.write(f"  {fired} fault(s) over the first {crossings} crossings "
              "per seam\n")
    return 0


def selftest() -> int:
    fm = faults_mod()

    # --- backoff arithmetic, by hand (no jitter).
    p = fm.FailurePolicy(transient_retries=8, backoff_base_s=0.05,
                         backoff_factor=2.0, backoff_max_s=5.0,
                         jitter_frac=0.0)
    want = [0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 3.2, 5.0, 5.0]
    got = [p.backoff_s("transient", a) for a in range(1, 10)]
    assert got == want, got
    assert p.backoff_s("transient", 0) == 0.0, "attempt 0 never sleeps"

    # --- jitter: deterministic, bounded, seam/seed-sensitive.
    pj = fm.FailurePolicy(backoff_base_s=1.0, backoff_factor=1.0,
                          backoff_max_s=1.0, jitter_frac=0.25, seed=42)
    v1 = pj.backoff_s("transient", 1, seam="dispatch")
    v2 = pj.backoff_s("transient", 1, seam="dispatch")
    assert v1 == v2, "same identity must back off identically"
    assert 0.75 <= v1 <= 1.25, v1
    v3 = pj.backoff_s("transient", 1, seam="reader-read")
    pj2 = fm.FailurePolicy(backoff_base_s=1.0, backoff_factor=1.0,
                           backoff_max_s=1.0, jitter_frac=0.25, seed=43)
    v4 = pj2.backoff_s("transient", 1, seam="dispatch")
    assert v3 != v1 and v4 != v1, \
        "jitter must decorrelate across seams and seeds"

    # --- the legacy retry=N mapping + budgets.
    legacy = fm.FailurePolicy.resolve(None, retry=3)
    assert legacy.transient_retries == 3 and legacy.resource_retries == 3
    assert legacy.permanent_retries == 0 and legacy.budget("preemption") == 0
    assert legacy.dispatch_budget == 3
    assert fm.FailurePolicy.resolve({"transient_retries": 2}) \
        .transient_retries == 2

    # --- taxonomy: typed faults carry their class; real exceptions
    # classify by message then type; unknown -> transient (the legacy
    # retry-anything semantics).
    assert fm.classify(fm.ResourceFault("x")) == "resource"
    assert fm.classify(RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                                    "allocating")) == "resource"
    assert fm.classify(RuntimeError("preempted: maintenance event")) \
        == "preemption"
    assert fm.classify(KeyboardInterrupt()) == "preemption"
    assert fm.classify(ValueError("bad config")) == "permanent"
    assert fm.classify(RuntimeError("flaky link")) == "transient"
    assert fm.classify(fm.TokenTimeout("hung")) == "transient"

    # --- ladder walks from fixture dicts, by hand.
    full = {"geometry": "tall512", "combiner": "hot-cache",
            "map_impl": "fused", "sort_impl": "radix"}
    assert fm.ladder_walk(full) == ["revert-geometry", "combiner-off",
                                    "map-split", "sort-xla"]
    assert fm.next_degrade(full) == ("revert-geometry", "geometry",
                                     "default")
    part = {"geometry": "default", "combiner": "off",
            "map_impl": "fused", "sort_impl": "xla"}
    assert fm.ladder_walk(part) == ["map-split"]
    done = {"geometry": "default", "combiner": "off",
            "map_impl": "split", "sort_impl": "xla"}
    assert fm.next_degrade(done) is None and fm.ladder_walk(done) == []

    # --- plan determinism: same seed -> same firing set; rate=0 silent;
    # max bounds; explicit events always fire; process-kill never fires
    # from the random rate.
    def fired_set(seed, rate, n=200):
        plan = fm.FaultPlan(seed=seed, rate=rate)
        out = set()
        for seam in plan.seams:
            for i in range(n):
                if plan.decide(seam, i) is not None:
                    out.add((seam, i))
        return out

    a, b = fired_set(7, 0.05), fired_set(7, 0.05)
    assert a == b and a, "seeded plans must fire identically (and fire)"
    assert fired_set(8, 0.05) != a, "a different seed is a different run"
    assert not fired_set(7, 0.0), "rate=0 never fires"
    frac = len(a) / (200 * len(fm.FaultPlan(seed=7, rate=0.05).seams))
    assert 0.02 < frac < 0.10, f"5% rate fired {frac:.1%}"
    capped = fm.FaultPlan(seed=7, rate=1.0, max_faults=3)
    hits = 0
    for seam in capped.seams:
        for i in range(10):
            if capped.check(seam) is not None:
                hits += 1
    assert hits == 3 and len(capped.fired) == 3, hits
    assert "process-kill" not in fm.FaultPlan(seed=1, rate=1.0).seams, \
        "random chaos must never hard-kill unless asked by name"

    # --- spec grammar round-trip + explicit events.
    plan = fm.FaultPlan.from_spec(
        "seed=9,at=dispatch:3:resource,at=token-wait:1:preemption")
    assert plan.decide("dispatch", 3) == "resource"
    assert plan.decide("dispatch", 2) is None
    assert plan.decide("token-wait", 1) == "preemption"
    rt = fm.FaultPlan.from_spec(plan.spec)
    assert rt.spec == plan.spec and rt.events == plan.events
    for bad in ("", "rate=2.0", "seams=warp", "classes=entropic",
                "at=dispatch:x:resource", "nonsense"):
        try:
            fm.FaultPlan.from_spec(bad)
        except ValueError:
            pass
        else:
            raise AssertionError(f"spec {bad!r} must be rejected")

    # --- ledger replay over the checked-in chaotic fixture run: the
    # rebuilt plan fires exactly the recorded (seam, index, class)
    # sequence, and a plan replayed from its OWN fired log reproduces
    # itself (the chaos-certification replay contract).
    fdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
    records = read_jsonl(os.path.join(fdir, "mini_ledger.jsonl"))
    seq = fm.fired_sequence(records, run_id="fixture11")
    assert seq == [("dispatch", 2, "transient"),
                   ("token-wait", 1, "resource")], seq
    rebuilt = fm.FaultPlan.from_ledger(records, run_id="fixture11")
    assert rebuilt.events == {("dispatch", 2): "transient",
                              ("token-wait", 1): "resource"}
    # drive the rebuilt plan through the crossings a rerun would make:
    # the SAME faults fire at the SAME crossings, nothing else.
    refired = []
    for seam in fm.SEAMS:
        for i in range(5):
            f = rebuilt.check(seam)
            if f is not None:
                refired.append((f.seam, f.index, f.fault_class))
    assert sorted(refired) == sorted(seq), refired
    # a random plan's own fired log rebuilds a plan that re-fires it.
    wild = fm.FaultPlan(seed=5, rate=0.1, classes=("transient",
                                                   "resource"))
    for seam in wild.seams:
        for i in range(40):
            wild.check(seam)
    own_records = [dict(kind="fault", injected=True, run_id="w", **f)
                   for f in wild.fired]
    rewild = fm.FaultPlan.from_ledger(own_records)
    for seam, index, fcls in fm.fired_sequence(own_records):
        assert rewild.decide(seam, index) == fcls

    print(f"chaos selftest ok (backoff 0.05->5.0 capped x{len(want)}, "
          f"jitter bounded deterministic, 4-step ladder walk, "
          f"plan determinism {len(a)} firings @5%, spec round-trip, "
          f"fixture11 replay {len(seq)} faults, "
          f"own-ledger replay {len(wild.fired)} faults)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fault-plan chaos tooling (jax-free)")
    ap.add_argument("--selftest", action="store_true",
                    help="hand-computed backoff/ladder/plan fixtures")
    ap.add_argument("--replay", metavar="LEDGER",
                    help="rebuild a chaotic run's fault plan from its "
                         "own ledger records")
    ap.add_argument("--run-id", default=None,
                    help="with --replay: select one run of an "
                         "append-mode ledger")
    ap.add_argument("--plan", metavar="SPEC",
                    help="show the deterministic firing decisions of a "
                         "plan spec")
    ap.add_argument("--walk", type=int, default=20, metavar="N",
                    help="with --plan: crossings to evaluate per seam "
                         "(default %(default)s)")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.replay:
        return replay(args.replay, run_id=args.run_id)
    if args.plan:
        return walk_plan(args.plan, args.walk)
    ap.error("one of --selftest / --replay / --plan is required")
    return 2


if __name__ == "__main__":
    sys.exit(main())
