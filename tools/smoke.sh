#!/usr/bin/env bash
# Fast iteration gate (VERDICT r5 #7): the <5-minute smoke subset — golden
# semantics, CLI surface, table units, one pallas-interpret case, config
# validation, the costcheck known-bad fixtures, and the ISSUE 6 fused-map
# seam (stream-level fused-vs-split row-set identity, ngram bit-identity,
# the oracle-exact fused rescue+spill case, and the fused-below-split
# cost gate) — so a mid-PR edit gets a signal in minutes instead of the
# ~12-minute tier-1 run.
#
# Green here is NOT the gate: tier-1 (tools/tier1.sh) stays the merge bar
# and the full suite (no marker filter) the release bar.  Prints
# DOTS_PASSED like tier1.sh and exits with pytest's status.
cd "$(dirname "$0")/.." || exit 1
# The jax-free obs_report/trace-export selftests (ISSUE 7/8) cost well
# under a second each and catch fixture/reconstruction/data-health drift
# before any jax import.
timeout -k 5 60 python tools/obs_report.py --selftest || { echo "SMOKE: obs_report selftest FAILED"; exit 1; }
timeout -k 5 60 python tools/trace_export.py --selftest || { echo "SMOKE: trace_export selftest FAILED"; exit 1; }
timeout -k 5 60 python mapreduce_tpu/obs/fleet.py --selftest || { echo "SMOKE: fleet selftest FAILED"; exit 1; }
timeout -k 5 60 python mapreduce_tpu/obs/history.py --selftest || { echo "SMOKE: history selftest FAILED"; exit 1; }
timeout -k 5 60 python tools/obswatch.py --selftest || { echo "SMOKE: obswatch selftest FAILED"; exit 1; }
timeout -k 5 60 python tools/autotune.py --selftest || { echo "SMOKE: autotune selftest FAILED"; exit 1; }
timeout -k 5 60 python tools/geomsearch.py --selftest || { echo "SMOKE: geomsearch selftest FAILED"; exit 1; }
timeout -k 5 60 python tools/chaos.py --selftest || { echo "SMOKE: chaos selftest FAILED"; exit 1; }
timeout -k 5 60 python tools/redplan.py --selftest || { echo "SMOKE: redplan selftest FAILED"; exit 1; }
set -o pipefail; rm -f /tmp/_smoke.log; timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'smoke and not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_smoke.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_smoke.log | tr -cd . | wc -c); exit $rc
