#!/usr/bin/env python3
"""Measure the >W-byte token envelope on the corpora we can generate.

VERDICT r3 weak #4 / next #6: the pallas backend drops tokens longer than
its lookback window W (default 32) into ``dropped_*`` accounting while the
XLA backend counts them exactly, so the size of the semantic gap between
the backends on natural text was unknown.  This tool quantifies it host-side
(pure numpy, no device): token-length distribution, overlong rate at W=32
and W=63, and the per-32MB-chunk overlong-occurrence count that sizes the
rescue pass's slot budget (``Config.rescue_overlong``).

Corpora: the two bench generators (synthetic-zipf, synthetic-natural), the
bundled fixture ``test.txt``, and a "webby" proxy — natural text with ~0.3%
of words replaced by URL/path/base64-ish long tokens, the enwik/WET
statistic the other generators lack (real enwik8 is not mountable: zero
egress).  Rates go into BENCHMARKS.md.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import (  # noqa: E402
    make_natural_corpus, make_webby_corpus, make_zipf_corpus)

SEPARATORS = b" \t\n\r\x00"


def token_length_stats(data: bytes) -> dict:
    buf = np.frombuffer(data, dtype=np.uint8)
    sep = np.isin(buf, np.frombuffer(SEPARATORS, np.uint8))
    # Run-length of non-separator runs.
    idx = np.flatnonzero(np.diff(np.concatenate([[True], sep, [True]]).astype(np.int8)))
    starts, ends = idx[::2], idx[1::2]
    lengths = ends - starts
    n = len(lengths)
    if n == 0:
        return {"tokens": 0}
    over32 = int((lengths > 32).sum())
    over63 = int((lengths > 63).sum())
    over256 = int((lengths > 256).sum())
    mb = len(data) / (1 << 20)
    return {
        "bytes": len(data),
        "tokens": n,
        "max_len": int(lengths.max()),
        "p999_len": int(np.quantile(lengths, 0.999)),
        "over_w32": over32,
        "over_w32_rate": over32 / n,
        "over_w63": over63,
        "over_w63_rate": over63 / n,
        "over_256": over256,
        "over_w32_per_32mb_chunk": over32 / max(mb / 32, 1e-9),
    }


def main() -> int:
    mb = int(os.environ.get("OVERLONG_MB", "32"))
    corpora = {
        "test.txt": open(os.path.join(REPO, "test.txt"), "rb").read(),
        "synthetic-zipf": make_zipf_corpus(mb << 20),
        "synthetic-natural": make_natural_corpus(mb << 20),
        "synthetic-webby": make_webby_corpus(mb << 20),
    }
    report = {name: token_length_stats(data) for name, data in corpora.items()}
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
