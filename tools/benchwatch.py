#!/usr/bin/env python3
"""Round-long relay watcher: probe the TPU periodically, run the bench suite
on the FIRST live window, then exit.

The bench chip sits behind a shared relay that can wedge for hours (rounds 1
and 2 both lost their perf record to it).  This tool turns a brief recovery
window into numbers without a human in the loop: a bounded probe every
--interval-s; on the first success it immediately runs the suite (each step
bounded by a 1800 s abandoned-not-killed deadline, per-step output in
``<out>.<step>.out``):

  1. bench-zipf           bench.py headline (updates BENCH_LAST_GOOD.json)
  2. sortbench            tools/sortbench.py sort-floor variant timings
  3. bench-zipf-segmin    bench.py under BENCH_SORT_MODE=segmin
  4. bench-natural-100mb  enwik8-sized English-text proxy row
  5. bench-zipf-chunk64   64 MB chunks (sort cost is sublinear in rows)
  6. bench-zipf-merge8    BENCH_MERGE_EVERY=8 (K-way batched table merges)
  7. opshare-sort3/-segmin  per-op profile: sort share of the chunk budget

appending each JSON/log line to --out (default /tmp/benchwatch.log — outside
the repo tree so snapshot commits never sweep it in), then exits 0 so a
supervising session gets notified.  Exits 3 if the budget (--max-hours) runs
out without a live window.

Probe children follow the never-kill rule (see runtime/probe.py): a hung
probe is left to die on its own; each attempt spawns fresh.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(out_path: str, msg: str) -> None:
    line = f"[benchwatch {time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    with open(out_path, "a") as f:
        f.write(line + "\n")


def run_step(out_path: str, name: str, cmd: list[str], env: dict,
             timeout_s: float) -> bool:
    """Run one suite step with a deadline but NEVER kill it on timeout:
    killing a client mid-claim is what wedges the relay (runtime/probe.py).
    A stalled step is abandoned (left to finish and release its claim on
    its own) and reported as failed."""
    log(out_path, f"running {name}: {' '.join(cmd)}")
    # Each bench step writes its run ledger next to its output capture, so
    # a wedged window leaves per-step forensics (ledger + .flight.json +
    # the .trace.json Perfetto export bench derives from the ledger's
    # group records) the next session can obs_report / trace_export
    # instead of a bare timeout line.  A live step can be WATCHED from
    # another shell while it runs: python tools/obswatch.py <ledger>.
    # All steps share ONE run-history warehouse (ISSUE 14): every timed
    # pass registers into <out>.history, so the window's final
    # history-report row lands with longitudinal drift verdicts.
    env = {**env, "BENCH_LEDGER": out_path + f".{name}.ledger.jsonl",
           "BENCH_HISTORY": out_path + ".history"}
    with open(out_path + f".{name}.out", "w") as stdout_f:
        proc = subprocess.Popen(cmd, cwd=REPO, env=env, stdout=stdout_f,
                                stderr=subprocess.STDOUT, text=True)
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log(out_path, f"{name}: no completion after {timeout_s:.0f}s — "
                      "abandoned (left running, not killed)")
        return False
    with open(out_path + f".{name}.out") as f:
        body = f.read()
    with open(out_path, "a") as f:
        f.write(f"--- {name} output (tail) ---\n{body[-6000:]}\n")
    log(out_path, f"{name}: rc={proc.returncode}")
    return proc.returncode == 0


def _geom_env(profile_path: str, env: dict, log) -> dict | None:
    """Map the freshest geomsearch winner onto bench's BENCH_GEOMETRY
    knob (ISSUE 12): the geom row measures exactly the searched geometry
    through the same harness as every other row.  None (with a logged
    reason) when the probe step left no usable profile or the winner IS
    the default — the suite then skips the geom A/B instead of measuring
    a guess."""
    import json

    try:
        with open(profile_path) as f:
            profiles = json.load(f).get("profiles", {})
    except (OSError, ValueError) as e:
        log(f"geom rows skipped: no geomsearch profile ({e!r})")
        return None
    geo = {k: v for k, v in profiles.items() if "-geometry/" in k}
    if not geo:
        log(f"geom rows skipped: no geometry profile in {profile_path}")
        return None
    key, entry = max(geo.items(),
                     key=lambda kv: kv[1].get("recorded_at") or "")
    geom = (entry.get("config") or {}).get("geometry")
    if geom in (None, "default"):
        log(f"geom rows skipped: searched winner is the default [{key}]")
        return None
    log(f"geom config [{key}]: {geom} "
        f"({entry.get('measured_gbps')} GB/s in-search)")
    return {**env, "BENCH_GEOMETRY": geom if isinstance(geom, str)
            else json.dumps(geom), "BENCH_TRACE": "1"}


def _redplan_env(profile_path: str, env: dict, log) -> dict | None:
    """Map the freshest redplan winner onto bench's BENCH_MERGE_STRATEGY
    knob (ISSUE 16): the planned row measures exactly the strategy the
    static link model ranked top, through the same harness as every
    other row.  None (with a logged reason) when the plan step left no
    usable profile."""
    import json

    try:
        with open(profile_path) as f:
            profiles = json.load(f).get("profiles", {})
    except (OSError, ValueError) as e:
        log(f"redplan rows skipped: no redplan profile ({e!r})")
        return None
    planned = {k: v for k, v in profiles.items() if "-redplan/" in k}
    if not planned:
        log(f"redplan rows skipped: no redplan profile in {profile_path}")
        return None
    key, entry = max(planned.items(),
                     key=lambda kv: kv[1].get("recorded_at") or "")
    strategy = (entry.get("config") or {}).get("merge_strategy")
    if strategy not in ("tree", "gather", "keyrange"):
        log(f"redplan rows skipped: unusable winner {strategy!r} [{key}]")
        return None
    log(f"redplan winner [{key}]: {strategy} "
        f"(modeled {entry.get('modeled_s')}s)")
    return {**env, "BENCH_MERGE_STRATEGY": strategy, "BENCH_TRACE": "1"}


def _tuned_env(profile_path: str, env: dict, log) -> dict | None:
    """Map the freshest zipf autotune winner onto bench's A/B knobs
    (ISSUE 10): the tuned row measures exactly the searched config
    through the same harness as every other row.  None (with a logged
    reason) when the autotune step left no usable profile — the suite
    then simply skips the tuned rows instead of measuring a guess."""
    import json

    try:
        with open(profile_path) as f:
            profiles = json.load(f).get("profiles", {})
    except (OSError, ValueError) as e:
        log(f"tuned rows skipped: no tuned profile ({e!r})")
        return None
    zipf = {k: v for k, v in profiles.items() if "/zipf-" in k}
    if not zipf:
        log(f"tuned rows skipped: no zipf profile in {profile_path}")
        return None
    key, entry = max(zipf.items(),
                     key=lambda kv: kv[1].get("recorded_at") or "")
    cfg = entry.get("config") or {}
    log(f"tuned config [{key}]: {cfg} (stopped={entry.get('stopped')}, "
        f"{entry.get('passes')} passes, "
        f"{entry.get('measured_gbps')} GB/s in-search)")
    tuned = {**env,
             "BENCH_CHUNK_MB": str(max(1, int(cfg.get("chunk_bytes",
                                                      32 << 20)) >> 20)),
             "BENCH_STREAM_SUPERSTEP": str(cfg.get("superstep", 4)),
             "BENCH_INFLIGHT": str(cfg.get("inflight_groups", 4)),
             "BENCH_PREFETCH_DEPTH": str(cfg.get("prefetch_depth", 4)),
             "BENCH_TRACE": "1"}
    if cfg.get("combiner", "off") != "off":
        # The ISSUE 11 enable-combiner rule fired during the search: the
        # tuned row must measure exactly that config (combiner rides the
        # fused map path).
        tuned["BENCH_COMBINER"] = str(cfg["combiner"])
        tuned["BENCH_MAP_IMPL"] = "fused"
    return tuned


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval-s", type=float, default=900.0)
    ap.add_argument("--probe-timeout-s", type=float, default=120.0)
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--out", default="/tmp/benchwatch.log",
                    help="log path (outside the repo tree so round-snapshot "
                         "commits never sweep it in)")
    args = ap.parse_args()

    from mapreduce_tpu.runtime.probe import probe_once

    deadline = time.monotonic() + args.max_hours * 3600
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        platform, err = probe_once(args.probe_timeout_s)
        if platform is not None and platform != "cpu":
            log(args.out, f"attempt {attempt}: device ALIVE ({platform}) — "
                          "running bench suite")
            env = {**os.environ, "BENCH_PROBE": "1",
                   "BENCH_PROBE_BUDGET_S": "120"}
            # A/B rows skip the streamed post-phase (BENCH_STREAMED=0): it
            # costs ~5 min of window per run and only the headline and the
            # stacked-candidate rows need the end-to-end ingest number.
            ab = {**env, "BENCH_STREAMED": "0"}
            steps = [
                # Mosaic lowering surprises only show on the chip (interpret
                # validates semantics, not the target): a ~minute parity
                # smoke of the production kernel configs runs BEFORE any
                # bench spends the window (VERDICT r4 next #8).
                ("kernel-smoke", [sys.executable, "tools/kernel_smoke.py",
                                  "--geometry", "3"],
                 env),
                # ISSUE 13 pod-scale obs proof next to the multichip
                # dryrun: a 2-process gloo-CPU run_job_global leaves one
                # ledger shard per host, merged by obs/fleet.py into the
                # pid-per-host Perfetto trace + fleet_bottleneck verdict
                # (straggler/collective/balanced) — the JSON line + trace
                # land next to this window's bench rows, so the first
                # live window documents the fleet-obs stack working where
                # the numbers were taken.  CPU-hermetic (like the
                # dryrun): a wedged relay can't hang it.
                ("multichip-fleet-report",
                 [sys.executable, "tools/fleet_report.py",
                  "--out", args.out + ".fleet"], env),
                # ISSUE 20 placed-reductions trio (BENCHMARKS.md round 20
                # pre-registration).  bench-zipf-hier: the 2-process
                # fleet pair on the planner's hierarchical 2-D program
                # (keyrange on the inner pair, tree across the gloo
                # "DCN"), fleet verdict + trace attached — fleet_report
                # removes its own stale shards, and the kernel-smoke
                # sweep above has already run.  The overlap/monolithic
                # bench A/B below measures the window-boundary overlap
                # win on the streamed ingest (both keep the streamed
                # post-phase: it IS the measurement; both are A/B
                # evidence, LAST_GOOD refuses the knob).  The prediction:
                # the overlap win is bounded by the monolithic row's
                # measured collective share — a bigger "win" is noise,
                # a loss gets the dead-end-ledger entry.
                ("bench-zipf-hier",
                 [sys.executable, "tools/fleet_report.py",
                  "--out", args.out + ".hier",
                  "--merge-strategy", "hier-kr-tree", "--overlap"], env),
                ("bench-zipf-overlap", [sys.executable, "bench.py"],
                 {**env, "BENCH_MERGE_OVERLAP": "1", "BENCH_TRACE": "1"}),
                ("bench-zipf-monolithic", [sys.executable, "bench.py"],
                 {**env, "BENCH_TRACE": "1"}),
                # Defaults row = stable2 since round 5 (+5.9% measured).
                ("bench-zipf", [sys.executable, "bench.py"], env),
                # ISSUE 5 dispatch-window A/B: streamed ingest with the
                # bounded in-flight window at depth 4 vs forced-serial
                # (BENCH_INFLIGHT=1), so the first live window measures
                # the window on/off delta directly.  Both rows keep the
                # streamed post-phase — it IS the measurement — and both
                # are A/B evidence (LAST_GOOD refuses the knob).  Each
                # row's ledger (BENCH_LEDGER, set per step above) now
                # carries per-group lifecycle records, and bench exports
                # a Perfetto trace + `bottleneck` verdict next to it
                # (ISSUE 7): the first live window yields measured
                # timelines — which resource bounded each arm, and where
                # the device idled — not just two scalar ratios.
                ("bench-zipf-pipeline", [sys.executable, "bench.py"],
                 {**env, "BENCH_INFLIGHT": "4", "BENCH_TRACE": "1"}),
                ("bench-zipf-nopipeline", [sys.executable, "bench.py"],
                 {**env, "BENCH_INFLIGHT": "1", "BENCH_TRACE": "1"}),
                # ISSUE 10 offline autotune: walk the window knobs via the
                # ledger's own bottleneck/data-health verdicts on a 64 MB
                # probe corpus, emitting the tuned profile next to the log
                # (the tuned-vs-default A/B rows below read it).  Budget 4
                # keeps the probe passes inside one step deadline.
                ("autotune-zipf", [sys.executable, "tools/autotune.py",
                                   "--mb", "64", "--chunk-mb", "32",
                                   "--budget", "4",
                                   "--out", args.out + ".tuned.json",
                                   "--keep-ledgers",
                                   args.out + ".autotune-ledgers"], env),
                # ISSUE 6 fused-map A/B: one kernel pass over raw chunk
                # bytes (tokenize -> hash -> window compaction in VMEM, no
                # token-plane round-trip) vs the shipped split path.  Each
                # row's BENCH JSON carries its `cost` record, so the
                # predicted effective_input_passes delta (costcheck gates
                # fused strictly below split) sits next to the measured
                # GB/s delta in the same capture — the round-9
                # confirm-or-record-the-dead-end evidence.
                ("bench-zipf-fused", [sys.executable, "bench.py"],
                 {**ab, "BENCH_MAP_IMPL": "fused"}),
                ("bench-zipf-split", [sys.executable, "bench.py"],
                 {**ab, "BENCH_MAP_IMPL": "split"}),
                # ISSUE 11 map-side combiner A/B (BENCHMARKS.md round 11
                # pre-registration): the hot-key cache on Zipf vs the
                # same fused path without it, plus a uniform-corpus
                # CONTROL row where the combiner must be ~neutral (no hot
                # keys to absorb; the taller windows ride the exact spill
                # fallback if natural density exceeds them).  Each row's
                # ledger carries the combiner counters, trace, bottleneck
                # and data-health verdicts, and its BENCH JSON the
                # certified combiner_vs_off pricing — prediction and
                # measurement in one capture.
                ("bench-zipf-combiner", [sys.executable, "bench.py"],
                 {**ab, "BENCH_MAP_IMPL": "fused",
                  "BENCH_COMBINER": "hot-cache", "BENCH_TRACE": "1"}),
                ("bench-zipf-nocombiner", [sys.executable, "bench.py"],
                 {**ab, "BENCH_MAP_IMPL": "fused", "BENCH_TRACE": "1"}),
                ("bench-uniform-combiner", [sys.executable, "bench.py"],
                 {**ab, "BENCH_CORPUS": "natural", "BENCH_MB": "64",
                  "BENCH_MAP_IMPL": "fused",
                  "BENCH_COMBINER": "hot-cache", "BENCH_TRACE": "1"}),
                # ISSUE 12 kernel-geometry search: jax-free shortlist ->
                # graphcheck gate -> measured probe ranking, winner to
                # the .geom.json profile the A/B rows below read.  The
                # shortlist's Mosaic surfaces were smoked by the
                # kernel-smoke --geometry step before this spends probe
                # passes on them (BENCHMARKS.md round 12
                # pre-registration: searched beats shipped on Zipf or
                # the shipped constants get the dead-end-ledger entry).
                ("geomsearch-zipf", [sys.executable, "tools/geomsearch.py",
                                     "--probe", "--top", "3",
                                     "--mb", "64",
                                     "--out", args.out + ".geom.json",
                                     "--keep-ledgers",
                                     args.out + ".geom-ledgers"], env),
                # ISSUE 16 reduction-strategy plan: the jax-free link-model
                # ranking at this window's single-host shape + the bench
                # table capacity, winner to the .redplan.json profile the
                # planned/scatter A/B rows below read.  Static (no device
                # time spent planning); the A/B rows are the measured
                # falsification of the model's ranking.
                ("redplan-zipf", [sys.executable, "tools/redplan.py",
                                  "--processes", "1",
                                  "--local-devices", "4",
                                  "--capacity", str(1 << 18),
                                  "--incumbent", "tree",
                                  "--out", args.out + ".redplan.json"], env),
                # Regression A/B rows: the previous default (sort3) and the
                # uncompacted path.  segmin's stream-sized associative_scan
                # wedges the chip (3 observations, BENCHMARKS.md round 4) —
                # no bench row; sortbench's gated SORTBENCH_SCAN=1 path
                # covers it off-TPU.
                ("bench-zipf-sort3", [sys.executable, "bench.py"],
                 {**ab, "BENCH_SORT_MODE": "sort3"}),
                ("bench-zipf-nocompact", [sys.executable, "bench.py"],
                 {**ab, "BENCH_COMPACT_SLOTS": "0",
                  "BENCH_SORT_MODE": "sort3"}),
                ("sortbench", [sys.executable, "tools/sortbench.py"], env),
                # Round-6 radix A/B (BENCHMARKS.md pricing note predicts
                # BOTH lose 2-3x to the XLA sort; these rows falsify or
                # confirm that arithmetic on the chip — bit-identical
                # results either way, spill falls back exactly).
                ("bench-zipf-radixpart", [sys.executable, "bench.py"],
                 {**ab, "BENCH_SORT_IMPL": "radix_partition"}),
                ("bench-zipf-radix", [sys.executable, "bench.py"],
                 {**ab, "BENCH_SORT_IMPL": "radix"}),
                # Round-5 packed gram build vs the generic 7-array build
                # (ops/ngram.py gram_table; +21% on CPU, expect more where
                # the sort is the floor).
                ("grambench", [sys.executable, "tools/grambench.py"], env),
                ("bench-natural-100mb", [sys.executable, "bench.py"],
                 {**ab, "BENCH_CORPUS": "natural", "BENCH_MB": "100"}),
                ("bench-webby", [sys.executable, "bench.py"],
                 {**ab, "BENCH_CORPUS": "webby", "BENCH_MB": "64",
                  "BENCH_REPEATS": "4"}),
                ("bench-markup", [sys.executable, "bench.py"],
                 {**ab, "BENCH_CORPUS": "markup", "BENCH_MB": "64",
                  "BENCH_REPEATS": "4"}),
                ("opshare-default", [sys.executable, "tools/opshare.py"],
                 env),
                ("opshare-sort3", [sys.executable, "tools/opshare.py"],
                 {**env, "OPSHARE_SORT_MODE": "sort3"}),
                # Re-profile under the radix partition: where the chunk
                # budget moves when the XLA sort is replaced (partition
                # kernel vs bucket sorts vs compaction shares).
                ("opshare-radixpart", [sys.executable, "tools/opshare.py"],
                 {**env, "OPSHARE_SORT_IMPL": "radix_partition"}),
                # Family overhead rows (VERDICT r5 #5): every shipped
                # family measured against plain wordcount on the SAME
                # streamed corpus file — the BENCHMARKS.md overhead table.
                ("family-plain", [sys.executable, "tools/familybench.py",
                                  "plain"], env),
                ("family-grep", [sys.executable, "tools/familybench.py",
                                 "grep"], env),
                ("family-sample", [sys.executable, "tools/familybench.py",
                                   "sample"], env),
                ("family-sketch", [sys.executable, "tools/familybench.py",
                                   "sketch"], env),
                # --verify-sample row (VERDICT r5 #6): K=64 byte-exact
                # recount against the real bench corpus; the JSON line
                # must carry verify_ok=true (zero mismatches, rc 0).
                ("family-verify", [sys.executable, "tools/familybench.py",
                                   "verify"], env),
                # ISSUE 14 run-history report, LAST on purpose: every
                # streamed row above registered its timed pass into the
                # shared <out>.history warehouse, so this row renders
                # the window's per-key series + drift verdicts
                # (regressing / improving / steady / config-drift) —
                # the chip window lands with a longitudinal verdict
                # attached, not just point measurements.  Jax-free and
                # read-only; rc 1 just means no streamed row landed.
                ("history-report",
                 [sys.executable, "mapreduce_tpu/obs/history.py",
                  "--index", args.out + ".history", "--drift"], env),
            ]
            results = {}
            for name, cmd, e in steps:
                if name == "geomsearch-zipf":
                    # Stale-profile discipline (the autotune-zipf rule):
                    # an earlier session's winner must never pose as this
                    # window's.
                    try:
                        os.remove(args.out + ".geom.json")
                    except OSError:
                        pass
                    results[name] = run_step(args.out, name, cmd, e, 1800)
                    if not results[name]:
                        log(args.out, "geom rows skipped: geomsearch-zipf "
                                      "step failed or was abandoned")
                        continue
                    # ISSUE 12 searched-vs-shipped A/B, back-to-back for
                    # temporal adjacency; both rows are A/B evidence
                    # (LAST_GOOD refuses BENCH_GEOMETRY; the default row
                    # carries no knob and may update the headline).
                    geom = _geom_env(args.out + ".geom.json", env,
                                     lambda m: log(args.out, m))
                    if geom is None:
                        continue
                    results["bench-zipf-geom"] = run_step(
                        args.out, "bench-zipf-geom",
                        [sys.executable, "bench.py"], geom, 1800)
                    results["bench-zipf-geom-default"] = run_step(
                        args.out, "bench-zipf-geom-default",
                        [sys.executable, "bench.py"],
                        {**env, "BENCH_TRACE": "1"}, 1800)
                    continue
                if name == "redplan-zipf":
                    # Stale-profile discipline (the autotune-zipf rule).
                    try:
                        os.remove(args.out + ".redplan.json")
                    except OSError:
                        pass
                    results[name] = run_step(args.out, name, cmd, e, 300)
                    if not results[name]:
                        log(args.out, "redplan rows skipped: redplan-zipf "
                                      "step failed or was abandoned")
                        continue
                    # ISSUE 16 planned-vs-scatter A/B, back-to-back for
                    # temporal adjacency: the link model's winner against
                    # the keyrange all-to-all alternative it priced.
                    # Both rows are A/B evidence (LAST_GOOD refuses
                    # BENCH_MERGE_STRATEGY).
                    planned = _redplan_env(args.out + ".redplan.json", env,
                                           lambda m: log(args.out, m))
                    if planned is None:
                        continue
                    results["bench-zipf-planned"] = run_step(
                        args.out, "bench-zipf-planned",
                        [sys.executable, "bench.py"],
                        {**planned, "BENCH_STREAMED": "0"}, 1800)
                    results["bench-zipf-scatter"] = run_step(
                        args.out, "bench-zipf-scatter",
                        [sys.executable, "bench.py"],
                        {**ab, "BENCH_MERGE_STRATEGY": "keyrange",
                         "BENCH_TRACE": "1"}, 1800)
                    continue
                if name == "autotune-zipf":
                    # A stale profile from an earlier session at the same
                    # --out path must never pose as this window's winner
                    # (the abandoned-step case below would read it).
                    try:
                        os.remove(args.out + ".tuned.json")
                    except OSError:
                        pass
                results[name] = run_step(args.out, name, cmd, e, 1800)
                if name != "autotune-zipf":
                    continue
                if not results[name]:
                    log(args.out, "tuned rows skipped: autotune-zipf step "
                                  "failed or was abandoned")
                    continue
                # ISSUE 10 tuned-vs-default A/B: measure the profile the
                # autotune step just emitted against the shipped defaults
                # BACK-TO-BACK (temporal adjacency: relay weather moves
                # both rows together).  The tuned config is logged next to
                # the row above; both rows keep the streamed phase (it IS
                # the measurement) and both are A/B evidence — LAST_GOOD
                # refuses the knobs (the default row carries none and may
                # update the headline records, which it IS).
                tuned = _tuned_env(args.out + ".tuned.json", env,
                                   lambda m: log(args.out, m))
                if tuned is None:
                    continue
                results["bench-zipf-tuned"] = run_step(
                    args.out, "bench-zipf-tuned",
                    [sys.executable, "bench.py"], tuned, 1800)
                results["bench-zipf-default"] = run_step(
                    args.out, "bench-zipf-default",
                    [sys.executable, "bench.py"],
                    {**env, "BENCH_TRACE": "1"}, 1800)
            log(args.out, f"suite done: {results}")
            return 0 if any(results.values()) else 2
        if platform == "cpu":
            log(args.out, f"attempt {attempt}: probe resolved cpu (no TPU "
                          "platform configured?) — not a live TPU window")
        else:
            log(args.out, f"attempt {attempt}: not alive ({err})")
        time.sleep(max(0.0, min(args.interval_s,
                                deadline - time.monotonic())))
    log(args.out, f"budget exhausted after {attempt} attempts; no live window")
    return 3


if __name__ == "__main__":
    sys.exit(main())
