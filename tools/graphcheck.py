#!/usr/bin/env python3
"""graphcheck: certify map/reduce programs before they hit the TPU.

Thin launcher for :mod:`mapreduce_tpu.analysis.cli` (also reachable as
``python -m mapreduce_tpu.analysis``), runnable from a source checkout
without installation.  Exits non-zero on any error-severity finding.

Usage::

    python tools/graphcheck.py --all-models
    python tools/graphcheck.py wordcount grep --json
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mapreduce_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
