#!/usr/bin/env python3
"""graphcheck: certify map/reduce programs before they hit the TPU.

Thin launcher for :mod:`mapreduce_tpu.analysis.cli` (also reachable as
``python -m mapreduce_tpu.analysis``), runnable from a source checkout
without installation.  Exits non-zero on any error-severity finding.

``--json`` emits the full machine-readable report for CI: structured
findings plus the ``artifacts`` section (per-model HBM cost reports, the
certified sort-pricing numbers, kernel VMEM footprints) — see
docs/analysis.md for the schema.

Usage::

    python tools/graphcheck.py --all-models          # the CI gate
    python tools/graphcheck.py wordcount grep --json # machine-readable
    python tools/graphcheck.py --all-models --write-baselines
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mapreduce_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
