#!/usr/bin/env python3
"""On-chip Mosaic kernel parity smoke: one tiny chunk, real kernel vs oracle.

Interpret mode validates kernel *semantics* only — the VMEM-stack limit, i1
vector-register shifts, 8-bit compares, and unsigned reductions all passed
interpret and failed only on the chip (BENCHMARKS.md round 4, "interpret
validates semantics, not the target").  This smoke costs ~seconds of a live
window and catches the next Mosaic lowering surprise BEFORE a bench run
spends the window: it compiles and runs the production kernel configs on the
real device over a 1 MB corpus slice and bit-compares the resulting tables
against the XLA-scan oracle.

Prints ONE JSON line: {"kernel_parity_ok": bool, "modes": {...}, ...}.
Exit 0 when every mode agrees, 1 otherwise, 3 when the device is
unreachable.  VERDICT r4 weak #5 / next #8.
"""

from __future__ import annotations

import json
import os
import sys
import time


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="on-chip Mosaic kernel parity smoke (production "
                    "configs; --geometry sweeps shortlist candidates)")
    ap.add_argument("--geometry", type=int, default=0, metavar="K",
                    help="ISSUE 12 sweep mode: smoke the top-K certified "
                         "geometry-search candidates' Mosaic surfaces "
                         "(stable2 + fused paths at each candidate's "
                         "windows) BEFORE any probe pass spends device "
                         "time — the PR-11 kernel-smoke discipline, "
                         "generalized (0 = the production configs only)")
    args = ap.parse_args(argv)
    budget = float(os.environ.get("BENCH_PROBE_BUDGET_S", "120"))
    if os.environ.get("BENCH_PROBE", "1") != "0":
        from mapreduce_tpu.runtime.probe import probe_once

        platform, err = probe_once(budget)
        if platform is None or platform == "cpu":
            print(json.dumps({"kernel_parity_ok": None,
                              "error": f"device unreachable ({err})"}))
            return 3

    import jax

    from bench import make_zipf_corpus
    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models import wordcount

    t0 = time.perf_counter()
    data = make_zipf_corpus(1 << 20)
    # A few overlong runs so the poison/rescue path is exercised on-chip.
    data = data[: 1 << 19] + b" " + b"u" * 40 + b" " + data[1 << 19:]

    # The XLA-scan oracle runs on CPU (it compiles pathologically slowly on
    # TPU at MB sizes — the reason the pallas path exists).
    cpus = jax.devices("cpu")
    with jax.default_device(cpus[0]):
        oracle_r = wordcount.count_words(
            data, Config(backend="xla", chunk_bytes=1 << 20,
                         table_capacity=1 << 16))

    modes = {}
    ok = True
    configs = {
        "sort3_compact88": Config(backend="pallas", chunk_bytes=1 << 20,
                                  table_capacity=1 << 16, sort_mode="sort3"),
        "stable2_lane_major": Config(backend="pallas", chunk_bytes=1 << 20,
                                     table_capacity=1 << 16,
                                     sort_mode="stable2"),
        # The round-6 radix partition kernel (ops/pallas/radix.py): its
        # Mosaic surface — SMEM (1, B) histogram blocks, 3*B+2 output
        # refs, uint32 digit shifts — has never lowered on a real chip;
        # smoking it here is what lets the benchwatch radix A/B rows
        # spend a window on MEASUREMENT instead of discovering a
        # lowering failure (the interpret suite validates semantics
        # only).
        "stable2_radix_partition": Config(backend="pallas",
                                          chunk_bytes=1 << 20,
                                          table_capacity=1 << 16,
                                          sort_impl="radix_partition"),
        "stable2_radix": Config(backend="pallas", chunk_bytes=1 << 20,
                                table_capacity=1 << 16, sort_impl="radix"),
        # ISSUE 11 map-side combiner: the hot-key cache's Mosaic surface
        # — four revisited (8, 128) output refs, axis-0 sublane
        # reductions, masked one-hot selects — has never lowered on a
        # real chip; smoke it before the bench-zipf-combiner rows spend
        # a window on it.  'salt' exercises the de-salting re-reduce.
        "fused_combiner": Config(backend="pallas", chunk_bytes=1 << 20,
                                 table_capacity=1 << 16, map_impl="fused",
                                 combiner="hot-cache"),
        "fused_salt": Config(backend="pallas", chunk_bytes=1 << 20,
                             table_capacity=1 << 16, map_impl="fused",
                             combiner="salt"),
    }
    if args.geometry:
        # ISSUE 12 sweep: every shortlisted candidate's Mosaic surface —
        # new window heights move BlockSpec shapes and grid sizes, the
        # exact class of lowering surprise interpret mode cannot see —
        # smoked on the stable2 AND fused paths before tools/geomsearch.py
        # --probe spends a measurement window on any of them.
        from mapreduce_tpu.analysis import geometry as geom_mod

        short = geom_mod.shortlist(geom_mod.enumerate_candidates(),
                                   args.geometry)
        for c in short:
            if c.axis == "default":
                continue  # the production configs above already cover it
            configs[f"geom_{c.label}"] = Config(
                backend="pallas", chunk_bytes=1 << 20,
                table_capacity=1 << 16, geometry=c.geometry)
            configs[f"geom_{c.label}_fused"] = Config(
                backend="pallas", chunk_bytes=1 << 20,
                table_capacity=1 << 16, map_impl="fused",
                geometry=c.geometry)
    for name, cfg in configs.items():
        try:
            r = wordcount.count_words(data, cfg)
            same = (r.words == oracle_r.words and r.counts == oracle_r.counts
                    and r.total == oracle_r.total)
            modes[name] = "ok" if same else "MISMATCH"
            ok = ok and same
        except Exception as e:  # compile/runtime lowering failure
            modes[name] = f"ERROR: {type(e).__name__}: {e}"[:300]
            ok = False
    print(json.dumps({
        "kernel_parity_ok": ok,
        "modes": modes,
        "backend": jax.default_backend(),
        "elapsed_s": round(time.perf_counter() - t0, 1),
    }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
