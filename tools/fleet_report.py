#!/usr/bin/env python3
"""Two-process CPU fleet dryrun + merged fleet report (ISSUE 13).

The multichip dryrun proves the collective geometry compiles and runs;
this tool proves the POD-SCALE OBSERVABILITY stack end to end on the same
box, without a TPU: it spawns 2 worker processes that join one JAX
runtime over gloo CPU collectives, drives ``executor.run_job_global``
with telemetry at a shared ledger path (so every process writes its
``<ledger>.h<p>.jsonl`` shard and the coordinator the main file), then —
jax-free, in the parent — merges the shards via ``mapreduce_tpu/obs/
fleet.py``, writes the pid-per-host Perfetto trace next to the ledger,
and prints ONE JSON line with the ``fleet_bottleneck`` verdict, the
per-superstep skew total, and the artifact paths.

``tools/benchwatch.py`` runs this as the chip-gated
``multichip-fleet-report`` row: the first live window leaves a merged
fleet trace + verdict next to the multichip dryrun's numbers.

Usage::

    python tools/fleet_report.py [--out /tmp/fleet] [--mb 1] [--chunk 4096]

(the ``--worker`` form is internal: the parent spawns itself twice).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HERE = os.path.dirname(os.path.abspath(__file__))

N_PROC = 2
DEV_PER_PROC = 2


def _worker(pid: int, n_proc: int, port: str, corpus: str, chunk: int,
            ledger: str) -> int:
    """One fleet process: gloo init, run_job_global with telemetry at the
    shared ledger path + a shared run_id (explicit shard pairing)."""
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={DEV_PER_PROC}")
    sys.path.insert(0, REPO)
    from mapreduce_tpu.runtime.platform import force_cpu

    jax = force_cpu(verify=False)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from mapreduce_tpu.parallel import distributed as dist

    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=n_proc, process_id=pid, timeout_s=60)

    from mapreduce_tpu.config import Config
    from mapreduce_tpu.models.wordcount import WordCountJob
    from mapreduce_tpu.obs import Telemetry
    from mapreduce_tpu.runtime import executor

    # Placed-reduction knobs (ISSUE 20), env-carried so the internal
    # --worker argv stays stable: the merge strategy the global finish
    # builds, and window-boundary overlap (partial merges ride inside
    # the map stream; the ledger then carries op="partial" collective
    # records and the fleet verdict charges only the visible share).
    merge = os.environ.get("FLEET_MERGE_STRATEGY", "tree")
    overlap = os.environ.get("FLEET_MERGE_OVERLAP") == "1"
    cfg = Config(chunk_bytes=chunk, table_capacity=1 << 12,
                 merge_strategy=merge, merge_overlap=overlap)
    mesh = None
    if merge.startswith("hier-"):
        # The hier-* 2-D programs need the process-major two-level mesh
        # (outer axis rides the gloo "DCN", inner the per-process pair).
        from mapreduce_tpu.parallel.mesh import two_level_mesh

        mesh = two_level_mesh(n_proc, DEV_PER_PROC, devices=jax.devices())
    tel = Telemetry.create(ledger_path=ledger, run_id="fleetreport")
    try:
        rr = executor.run_job_global(WordCountJob(cfg), corpus, config=cfg,
                                     mesh=mesh, telemetry=tel)
    finally:
        tel.close()
    if dist.is_coordinator():
        print(json.dumps({"worker_total": int(rr.metrics.words_counted)}))
    return 0


def _make_corpus(path: str, mb: float) -> None:
    import random

    rng = random.Random(7)
    words = [f"w{i:04d}" for i in range(400)]
    target = int(mb * (1 << 20))
    with open(path, "w", encoding="utf-8") as f:
        n = 0
        while n < target:
            line = " ".join(rng.choice(words) for _ in range(12)) + "\n"
            f.write(line)
            n += len(line)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", nargs=6, default=None,
                    help=argparse.SUPPRESS)  # internal spawn form
    ap.add_argument("--out", default=None,
                    help="artifact prefix (default: a temp dir); the "
                         "ledger lands at <out>.ledger.jsonl")
    ap.add_argument("--corpus", default=None,
                    help="existing corpus file (default: generated)")
    ap.add_argument("--mb", type=float, default=1.0)
    ap.add_argument("--chunk", type=int, default=4096)
    ap.add_argument("--timeout-s", type=float, default=300.0)
    ap.add_argument("--merge-strategy", default="tree",
                    help="collective merge strategy for the global finish "
                         "(hier-* builds the 2-process x 2-device "
                         "two-level mesh)")
    ap.add_argument("--overlap", action="store_true",
                    help="window-boundary partial merges (ISSUE 20): "
                         "op='partial' collective records land in the "
                         "shards and the fleet verdict splits "
                         "visible/hidden collective time")
    args = ap.parse_args()
    if args.worker:
        w = args.worker
        return _worker(int(w[0]), int(w[1]), w[2], w[3], int(w[4]), w[5])

    out = args.out or os.path.join(tempfile.mkdtemp(prefix="fleetrep-"),
                                   "fleet")
    corpus = args.corpus
    if corpus is None:
        corpus = out + ".corpus.txt"
        _make_corpus(corpus, args.mb)
    ledger = out + ".ledger.jsonl"
    stale = [ledger, ledger + ".flight.json",
             *(f"{ledger}.h{i}.jsonl" for i in range(N_PROC)),
             *(f"{ledger}.h{i}.flight.json" for i in range(N_PROC))]
    for p in stale:
        # Append-mode ledgers: a stale run must not merge in — and a
        # prior crash's flight dumps must not read as THIS run's
        # forensics (obs_report auto-picks the adjacent .flight.json).
        try:
            os.remove(p)
        except OSError:
            pass

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS")}
    env["PYTHONPATH"] = REPO
    env["FLEET_MERGE_STRATEGY"] = args.merge_strategy
    if args.overlap:
        env["FLEET_MERGE_OVERLAP"] = "1"
    else:
        env.pop("FLEET_MERGE_OVERLAP", None)
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker",
         str(p), str(N_PROC), str(port), corpus, str(args.chunk), ledger],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for p in range(N_PROC)]
    fail = None
    for p in procs:
        try:
            _, err = p.communicate(timeout=args.timeout_s)
        except subprocess.TimeoutExpired:
            p.kill()
            _, err = p.communicate()
            fail = fail or f"worker timed out after {args.timeout_s:.0f}s"
        if p.returncode != 0:
            fail = fail or f"worker rc={p.returncode}: {err[-2000:]}"
    if fail:
        print(json.dumps({"ok": False, "error": fail}))
        return 1

    # Merge + report, jax-free (the parent never imports jax): the same
    # by-path module loading the report tools use.
    sys.path.insert(0, HERE)
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    fl = obs_report._fleet_mod()
    by_host = {h: fl.read_jsonl(p)
               for h, p in fl.shard_paths(ledger).items()}
    selected = fl._select_aligned(by_host)
    view = fl.fleet_view(by_host, selected=selected)
    if view is None or len(view["hosts"]) != N_PROC:
        print(json.dumps({"ok": False,
                          "error": f"expected {N_PROC} shards, got "
                                   f"{sorted(by_host)} -> {view}"}))
        return 1
    trace_path = ledger + ".fleet.trace.json"
    with open(trace_path, "w", encoding="utf-8") as f:
        json.dump(fl.to_chrome_trace(by_host, selected=selected,
                                     view=view), f)
    merged_path = ledger + ".fleet.jsonl"
    with open(merged_path, "w", encoding="utf-8") as f:
        for r in fl.merged_records(by_host, selected=selected, view=view):
            f.write(json.dumps(r, sort_keys=True) + "\n")
    print(json.dumps({
        "ok": True,
        "hosts": view["hosts"],
        "aligned": view["aligned"],
        "span_s": view["span_s"],
        "merge_strategy": args.merge_strategy,
        "merge_overlap": bool(args.overlap),
        "fleet_bottleneck": view["fleet_bottleneck"],
        "collective": view["collective"],
        "straggler_skew_s": view["straggler"]["total_skew_s"],
        "imbalance": view["imbalance"]["verdict"],
        "ledger": ledger,
        "merged": merged_path,
        "trace": trace_path,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
