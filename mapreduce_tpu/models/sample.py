"""Uniform token sampling: a bottom-k sketch as a MapReduce job.

A fifth model family (after word count, n-grams, HLL/CMS sketches, and
grep) with yet another accumulator shape: a fixed-k *reservoir* of token
occurrences.  The reference has nothing comparable (its map UDF emits only
word counts, ``mapper`` ``main.cu:37-54``); uniform sampling is the classic
MapReduce companion for "show me representative records" at corpus scale.

TPU formulation — the mergeable form of reservoir sampling is the
**bottom-k sketch**: every token occurrence gets an i.i.d. pseudo-uniform
64-bit priority (a hash of its global identity: chunk_id and byte offset),
and the sample is the k smallest priorities.  Bottom-k of a union is the
bottom-k of the parts' bottom-k's, so:

  * map     = tokenize + hash priorities + one sort, slice ``[:k]``;
  * combine = concat [2k] + sort + slice ``[:k]`` — tiny, fixed-size;
  * merge   = same op: associative AND commutative, so it rides the same
    collective tree-merge as every other family.

The result is an exact uniform k-sample *without replacement* over token
occurrences (frequent words appear proportionally more often — sampling
occurrences, not distinct words).  Strings are recovered host-side from
(chunk_id, pos, len) exactly like word count's first-occurrence recovery.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu import constants
from mapreduce_tpu.config import Config, DEFAULT_CONFIG
from mapreduce_tpu.ops import tokenize as tok_ops


class ReservoirState(NamedTuple):
    """Bottom-k sample (a pytree; all fields [k] device arrays)."""

    prio_hi: jax.Array  # uint32[k]: priority high word (max = empty slot)
    prio_lo: jax.Array  # uint32[k]: priority low word
    pos_hi: jax.Array  # uint32[k]: chunk id of the sampled occurrence
    pos_lo: jax.Array  # uint32[k]: byte offset within the chunk
    length: jax.Array  # uint32[k]: token length in bytes
    total_lo: jax.Array  # uint32: population size seen (64-bit lo/hi)
    total_hi: jax.Array


_MAXU = np.uint32(0xFFFFFFFF)


def _empty(k: int) -> ReservoirState:
    full = jnp.full((k,), _MAXU)
    zero = jnp.zeros((), jnp.uint32)
    return ReservoirState(full, jnp.array(full), jnp.array(full),
                          jnp.array(full), jnp.zeros((k,), jnp.uint32),
                          zero, jnp.array(zero))


def _bottom_k(state_parts, k: int) -> tuple[jax.Array, ...]:
    """Sort by 64-bit priority (then position, for determinism under the
    astronomically-unlikely tie) and keep the k smallest."""
    prio_hi, prio_lo, pos_hi, pos_lo, length = jax.lax.sort(
        state_parts, num_keys=4)
    return (prio_hi[:k], prio_lo[:k], pos_hi[:k], pos_lo[:k], length[:k])


class ReservoirSampleJob:
    """Uniform bottom-k token sampling as a MapReduceJob (duck-typed)."""

    def __init__(self, k: int, config: Config = DEFAULT_CONFIG):
        if k < 1:
            raise ValueError(f"sample size must be >= 1, got {k}")
        self.k = k
        self.config = config

    def init_state(self) -> ReservoirState:
        return _empty(self.k)

    def _priorities(self, pos: jax.Array, is_tok: jax.Array,
                    chunk_id: jax.Array) -> tuple[jax.Array, jax.Array]:
        """Two pseudo-uniform priority lanes from the occurrence's global
        identity (chunk_id, byte offset); fmix32 avalanches, the odd
        multipliers decorrelate.  Backend-independent by construction: both
        backends see the same (chunk_id, pos) pairs for any <=W token, so
        the bottom-k selection — and therefore the sample — is identical."""
        cid = jnp.asarray(chunk_id, jnp.uint32)
        seed1 = pos * jnp.uint32(constants.HASH_BASE_1) ^ \
            tok_ops._fmix32(cid + jnp.uint32(0x9E3779B9))
        seed2 = pos * jnp.uint32(constants.HASH_BASE_2) ^ \
            tok_ops._fmix32(cid ^ jnp.uint32(0x85EBCA6B))
        prio_hi = tok_ops._fmix32(seed1)
        # Clamp away from the all-ones empty-slot sentinel (2**-32 per
        # token), mirroring the tokenizer's sentinel clamp convention.
        prio_hi = jnp.where(prio_hi == _MAXU, prio_hi - jnp.uint32(1), prio_hi)
        prio_hi = jnp.where(is_tok, prio_hi, _MAXU)
        prio_lo = jnp.where(is_tok, tok_ops._fmix32(seed2), _MAXU)
        return prio_hi, prio_lo

    def map_chunk(self, chunk: jax.Array, chunk_id: jax.Array) -> ReservoirState:
        if self.config.resolved_backend() == "pallas":
            return self._map_chunk_pallas(chunk, chunk_id)
        stream = tok_ops.tokenize(chunk)
        is_tok = stream.count > 0
        cid = jnp.asarray(chunk_id, jnp.uint32)
        prio_hi, prio_lo = self._priorities(stream.pos, is_tok, chunk_id)
        pos_hi = jnp.where(is_tok, cid, _MAXU)
        parts = _bottom_k((prio_hi, prio_lo, pos_hi, stream.pos,
                           stream.length), self.k)
        n = jnp.sum(is_tok.astype(jnp.uint32))
        return ReservoirState(*parts, n, jnp.zeros((), jnp.uint32))

    def _map_chunk_pallas(self, chunk: jax.Array,
                          chunk_id: jax.Array) -> ReservoirState:
        """Fused-kernel map: priorities derive from the packed plane (pos in
        the payload's high bits), so sampling rides the single-pass pallas
        kernel instead of the XLA associative scan — which compiles
        pathologically slowly at production chunk sizes (VERDICT r2 #6) —
        and the bottom-k sorts HALF the rows (pair-compacted planes), with
        (pos, len) carried through the sort as ONE packed payload lane.

        Same sample as the XLA path for any corpus of <=W-byte tokens
        (priorities depend only on (chunk_id, pos)).  Tokens longer than W
        are excluded from both the sample and the reported population —
        the family-wide pallas >W contract.
        """
        from mapreduce_tpu.ops.pallas import tokenize as pallas_tok

        col, seam, _overlong = pallas_tok.tokenize_split(
            chunk, max_token_bytes=self.config.pallas_max_token)
        stream = pallas_tok.concat_streams(col, seam)
        # Poison rows (overlong ends, zero length bits) are not samples.
        is_tok = stream.count > 0
        prio_hi, prio_lo = self._priorities(stream.pos, is_tok, chunk_id)
        packed = jnp.where(is_tok, stream.packed, _MAXU)
        # One sort, 3 arrays: ties (64-bit priority collisions) break by
        # packed = pos<<6|len — the same within-chunk position order the
        # XLA path's (pos_hi, pos_lo) tiebreak yields.
        prio_hi, prio_lo, packed = jax.lax.sort(
            (prio_hi, prio_lo, packed), num_keys=3)
        prio_hi, prio_lo, packed = prio_hi[:self.k], prio_lo[:self.k], packed[:self.k]
        live = prio_hi != _MAXU
        cid = jnp.asarray(chunk_id, jnp.uint32)
        return ReservoirState(
            prio_hi=prio_hi, prio_lo=prio_lo,
            pos_hi=jnp.where(live, cid, _MAXU),
            pos_lo=jnp.where(live, packed >> 6, _MAXU),
            length=jnp.where(live, packed & jnp.uint32(63), jnp.uint32(0)),
            total_lo=stream.total, total_hi=jnp.zeros((), jnp.uint32))

    # -- data-plane telemetry (ISSUE 11 satellite: sample previously ran
    # -- telemetered streams in plain mode — the classifier and the
    # -- combiner 'auto' switch now cover every shipped family) -----------

    def map_chunk_stats_sharded(self, chunk, chunk_id, axis, device_index):
        """Stats-mode map: the reservoir has no spill/rescue machinery —
        counters are structurally zero; the gauges carry the population
        and reservoir fill."""
        from mapreduce_tpu.ops import datastats

        del axis, device_index  # the bottom-k map is axis-free
        return self.map_chunk(chunk, chunk_id), datastats.map_stats()

    def state_stats(self, state: ReservoirState, stats):
        """Gauges: population size as the ``tokens`` lane, live reservoir
        slots as ``table_valid`` (a full reservoir at k slots reads as
        occupancy k/table_capacity — honest, if dimensionless: the
        reservoir IS this family's table)."""
        live = jnp.sum((state.prio_hi != _MAXU).astype(jnp.uint32))
        return stats._replace(table_valid=live,
                              total_lo=state.total_lo,
                              total_hi=state.total_hi)

    def combine(self, state: ReservoirState, update: ReservoirState) -> ReservoirState:
        cat = lambda f: jnp.concatenate(f)
        parts = _bottom_k(
            (cat((state.prio_hi, update.prio_hi)),
             cat((state.prio_lo, update.prio_lo)),
             cat((state.pos_hi, update.pos_hi)),
             cat((state.pos_lo, update.pos_lo)),
             cat((state.length, update.length))), self.k)
        lo = state.total_lo + update.total_lo
        carry = (lo < state.total_lo).astype(jnp.uint32)
        return ReservoirState(*parts, lo,
                              state.total_hi + update.total_hi + carry)

    def merge(self, a: ReservoirState, b: ReservoirState) -> ReservoirState:
        return self.combine(a, b)

    def finalize(self, state: ReservoirState) -> ReservoirState:
        return state

    def identity(self) -> str:
        # k shapes the state, but identity documents intent anyway.
        return f"sample{self.k}"


class SampleResult(NamedTuple):
    """Host-side result: sampled token occurrences + population size."""

    tokens: list[bytes]
    total: int  # population size the sample was drawn from


import functools


@functools.partial(jax.jit, static_argnames=("k", "config"))
def _sample_step(buf: jax.Array, k: int, config: Config) -> ReservoirState:
    return ReservoirSampleJob(k, config).map_chunk(buf, jnp.uint32(0))


def sample_bytes(data: bytes, k: int,
                 config: Config = DEFAULT_CONFIG) -> SampleResult:
    """One-call API: uniform k-sample of token occurrences in a buffer."""
    from mapreduce_tpu.models.wordcount import _pad_for_backend

    ReservoirSampleJob(k, config)  # validate before any device work
    padded = _pad_for_backend(data, config)
    st = jax.tree.map(np.asarray, _sample_step(jax.device_put(padded), k, config))
    live = st.prio_hi != 0xFFFFFFFF
    # Ascending priority = unbiased order; position recovery is direct.
    spans = [(int(p), int(ln)) for p, ln in
             zip(st.pos_lo[live], st.length[live])]
    return SampleResult([bytes(data[o: o + ln]) for o, ln in spans],
                        int((int(st.total_hi) << 32) | int(st.total_lo)))


def sample_file(path, k: int, config: Config = DEFAULT_CONFIG,
                mesh=None, **kw) -> SampleResult:
    """Uniform k-sample over a file via the streaming sharded pipeline."""
    from mapreduce_tpu.data import reader
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    mesh = mesh if mesh is not None else data_mesh()
    rr = executor.run_job(ReservoirSampleJob(k, config), path, config=config,
                          mesh=mesh, **kw)
    st = jax.tree.map(np.asarray, rr.value)
    live = st.prio_hi != 0xFFFFFFFF
    chunk_id = st.pos_hi[live].astype(np.int64)
    pos = st.pos_lo[live].astype(np.int64)
    length = st.length[live].astype(np.int64)
    absolute = executor.absolute_offsets(chunk_id, pos, rr.bases, mesh.size)
    spans = [(int(a), int(ln)) for a, ln in zip(absolute, length)]
    return SampleResult(reader.read_words_at_multi(path, spans),
                        int((int(st.total_hi) << 32) | int(st.total_lo)))
