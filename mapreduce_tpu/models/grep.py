"""Distributed grep: count occurrences / matching lines of a fixed pattern.

A third model family (after word count and the sketches) riding the same
Engine/collective machinery.  The reference has nothing comparable — its only
workload is word count (``main.cu``) — but pattern search is *the* canonical
MapReduce companion workload, and it exercises a different accumulator shape:
tiny scalar states instead of capacity-sized tables, so the collective merge
is a pure ``psum``-style reduction.

TPU formulation: for a pattern of m bytes, the match mask over a chunk is the
AND of m shifted byte-equality planes — static shapes, no data-dependent
control flow, fully fused by XLA into one elementwise pass over the chunk.
Matching-*line* counting reuses the tokenizer's segmented-scan trick with
newline as the reset class: a match's line has counted it iff an earlier
match shares the line, computed by an exclusive segmented prefix-OR.

Envelope (documented, tested):
  * occurrences are **overlapping** (pattern ``aa`` occurs twice in ``aaa``);
  * a pattern containing separator bytes never matches across a chunk seam
    (the reader cuts at separators), mirroring the n-gram per-chunk envelope;
  * a logical line split across two chunk rows may count as matching in each
    row, so ``lines`` is exact within rows and an upper bound across them
    (off by at most chunks - 1);
  * accumulators are 64-bit (uint32 lo/hi pairs with explicit carry — JAX
    default-x64 is off, so device uint64 is unavailable): counts stay exact
    past 2**32 occurrences, where a single uint32 would silently wrap on
    corpus-scale single-byte patterns.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu.config import Config, DEFAULT_CONFIG
from mapreduce_tpu.parallel.mapreduce import MapReduceJob


class GrepState(NamedTuple):
    """Scalar accumulators (a pytree; merged by 64-bit carry addition)."""

    matches_lo: jax.Array  # uint32: overlapping occurrences, low word
    matches_hi: jax.Array  # uint32: high word
    lines_lo: jax.Array  # uint32: lines containing >= 1 occurrence, low word
    lines_hi: jax.Array  # uint32: high word


def _add64(a_lo, a_hi, b_lo, b_hi):
    """(lo, hi) + (lo, hi) with carry: exact uint64 in two uint32 lanes."""
    lo = a_lo + b_lo
    carry = (lo < a_lo).astype(jnp.uint32)
    return lo, a_hi + b_hi + carry


def _match_mask(chunk: jax.Array, pattern: np.ndarray) -> jax.Array:
    """bool[n]: True where an occurrence of ``pattern`` starts."""
    m, n = pattern.shape[0], chunk.shape[0]
    if m > n:
        return jnp.zeros((n,), jnp.bool_)
    hit = jnp.ones((n - m + 1,), jnp.bool_)
    for i, b in enumerate(pattern.tolist()):  # m is static: unrolled ANDs
        hit = hit & (chunk[i: n - m + 1 + i] == jnp.uint8(b))
    return jnp.concatenate([hit, jnp.zeros((m - 1,), jnp.bool_)]) if m > 1 else hit


def _or_reset_combine(a, b):
    """Segmented prefix-OR: (reset, value); reset discards the left prefix."""
    a_f, a_v = a
    b_f, b_v = b
    return (a_f | b_f, jnp.where(b_f, b_v, a_v | b_v))


def count_matches_in_chunk(chunk: jax.Array, pattern: np.ndarray) -> GrepState:
    """One chunk's (occurrences, matching lines), as a GrepState."""
    hit = _match_mask(chunk, pattern)
    newline = chunk == jnp.uint8(0x0A)
    # Exclusive segmented prefix-OR of `hit` with newline resets: True where
    # an earlier position in the SAME line already matched.
    _, inc = jax.lax.associative_scan(_or_reset_combine, (newline, hit))
    seen_before = jnp.concatenate([jnp.zeros((1,), jnp.bool_), inc[:-1]])
    # (a newline position itself resets, so inc at the newline is False for
    # the next line's first position after the shift — line state never leaks)
    first_in_line = hit & ~seen_before
    zero = jnp.zeros((), jnp.uint32)
    # Per-chunk sums fit uint32 by construction (a chunk holds < 2**32 bytes).
    return GrepState(matches_lo=jnp.sum(hit).astype(jnp.uint32), matches_hi=zero,
                     lines_lo=jnp.sum(first_in_line).astype(jnp.uint32), lines_hi=zero)


class GrepJob(MapReduceJob):
    """Pattern-occurrence counting as a :class:`MapReduceJob`.

    The accumulator is four uint32 scalars, so the global reduction is the
    degenerate (and fastest) case of the collective tree-merge: effectively
    a ``psum`` over the mesh.
    """

    def __init__(self, pattern: bytes):
        if not pattern:
            raise ValueError("grep pattern must be non-empty")
        if len(pattern) > 256:
            raise ValueError(f"grep pattern of {len(pattern)} bytes exceeds "
                             "the 256-byte limit (the match mask unrolls one "
                             "fused comparison per pattern byte)")
        if 0 in pattern:
            # NUL is the chunk padding byte: a NUL-bearing pattern would
            # count phantom matches in padding tails.
            raise ValueError("grep pattern must not contain NUL bytes")
        self.pattern = np.frombuffer(pattern, dtype=np.uint8)

    def init_state(self) -> GrepState:
        zero = jnp.zeros((), jnp.uint32)
        return GrepState(zero, zero, zero, zero)

    def map_chunk(self, chunk: jax.Array, chunk_id: jax.Array) -> GrepState:
        return count_matches_in_chunk(chunk, self.pattern)

    def combine(self, state: GrepState, update: GrepState) -> GrepState:
        m_lo, m_hi = _add64(state.matches_lo, state.matches_hi,
                            update.matches_lo, update.matches_hi)
        l_lo, l_hi = _add64(state.lines_lo, state.lines_hi,
                            update.lines_lo, update.lines_hi)
        return GrepState(m_lo, m_hi, l_lo, l_hi)

    def merge(self, a: GrepState, b: GrepState) -> GrepState:
        return self.combine(a, b)

    def identity(self) -> str:
        # The pattern IS the job: a different pattern's snapshot has the
        # same state shape but means different counts.
        import hashlib

        return "grep:" + hashlib.sha256(self.pattern.tobytes()).hexdigest()[:16]


class GrepResult(NamedTuple):
    """Host-side result."""

    pattern: bytes
    matches: int  # overlapping occurrences
    lines: int  # matching lines (exact within chunks; see module envelope)


def _state_result(pattern: bytes, state) -> GrepResult:
    lo, hi = int(np.asarray(state.matches_lo)), int(np.asarray(state.matches_hi))
    llo, lhi = int(np.asarray(state.lines_lo)), int(np.asarray(state.lines_hi))
    return GrepResult(pattern, (hi << 32) | lo, (lhi << 32) | llo)


@functools.lru_cache(maxsize=64)
def _jitted_counter(pattern: bytes):
    """One compiled counter per pattern (jit caches per buffer shape)."""
    pat = np.frombuffer(pattern, dtype=np.uint8)
    return jax.jit(lambda c: count_matches_in_chunk(c, pat))


def grep_bytes(data: bytes, pattern: bytes) -> GrepResult:
    """One-call API: pattern counts for an in-memory buffer."""
    from mapreduce_tpu.ops import tokenize as tok_ops

    GrepJob(pattern)  # validate pattern via the single owner of the rules
    buf = np.frombuffer(data, dtype=np.uint8)
    padded = tok_ops.pad_to(buf, max(128, -(-max(buf.shape[0], 1) // 128) * 128))
    return _state_result(pattern, _jitted_counter(pattern)(padded))


def grep_file(path, pattern: bytes, config: Config = DEFAULT_CONFIG,
              mesh=None, **kw) -> GrepResult:
    """Pattern counts over a file via the streaming sharded pipeline."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    mesh = mesh if mesh is not None else data_mesh()
    rr = executor.run_job(GrepJob(pattern), path, config=config,
                          mesh=mesh, **kw)
    return _state_result(pattern, rr.value)
