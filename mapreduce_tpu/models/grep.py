"""Distributed grep: count occurrences / matching lines of a fixed pattern.

A third model family (after word count and the sketches) riding the same
Engine/collective machinery.  The reference has nothing comparable — its only
workload is word count (``main.cu``) — but pattern search is *the* canonical
MapReduce companion workload, and it exercises a different accumulator shape:
tiny scalar states instead of capacity-sized tables, so the collective merge
is a pure ``psum``-style reduction.

TPU formulation: for a pattern of m bytes, the match mask over a chunk is the
AND of m shifted byte-equality planes — static shapes, no data-dependent
control flow, fully fused by XLA into one elementwise pass over the chunk.
Matching-*line* counting reuses the tokenizer's segmented-scan trick with
newline as the reset class: a match's line has counted it iff an earlier
match shares the line, computed by an exclusive segmented prefix-OR.

Envelope (documented, tested):
  * occurrences are **overlapping** (pattern ``aa`` occurs twice in ``aaa``);
  * a pattern containing separator bytes never matches across a chunk seam
    (the reader cuts at separators), mirroring the n-gram per-chunk envelope;
  * ``lines`` is **exact**, including logical lines split across chunk rows:
    every row also emits a tiny line-boundary summary (has-newline,
    first/last segment matched), the devices share their summaries with one
    ``all_gather`` per step (a few bytes over ICI), and a carry bit in the
    state threads the "current open line already matched" chain across
    steps, so a line counted in one row's trailing segment is not recounted
    by its continuation rows.  Only the bare per-device
    :meth:`GrepJob.map_chunk` fallback (no mesh axis available) keeps the
    old per-row upper bound;
  * accumulators are 64-bit (uint32 lo/hi pairs with explicit carry — JAX
    default-x64 is off, so device uint64 is unavailable): counts stay exact
    past 2**32 occurrences, where a single uint32 would silently wrap on
    corpus-scale single-byte patterns.

Exact-line math: rows (in file order) form a monoid chain for the one bit c =
"the currently open line has matched so far".  A row with a newline maps any
incoming c to its own trailing-segment match; a newline-free row is
*transparent*: c' = c OR (row matched).  Per row, segments-with-matches
over-counts the truth by exactly [leading segment matched AND incoming c].
Each transfer function has the boolean-affine form c' = a | (b & c), which
composes associatively, so each device recovers its incoming c (and its
correction if the step's incoming carry turns out to be 1) from the gathered
per-row (a, b) pairs with static-shape prefix products — no sequential host
pass, no per-step device->host sync.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu.config import Config, DEFAULT_CONFIG
from mapreduce_tpu.parallel.mapreduce import MapReduceJob


class GrepState(NamedTuple):
    """Scalar accumulators (a pytree; merged by 64-bit carry addition)."""

    matches_lo: jax.Array  # uint32: overlapping occurrences, low word
    matches_hi: jax.Array  # uint32: high word
    lines_lo: jax.Array  # uint32: lines containing >= 1 occurrence, low word
    lines_hi: jax.Array  # uint32: high word
    line_carry: jax.Array = np.uint32(0)  # uint32 0/1: open line matched so
    # far at this device's stream position (identical on every device — the
    # per-step block transfer is computed from the gathered summaries)


class GrepUpdate(NamedTuple):
    """One row's contribution plus the seam-correction terms (all uint32).

    ``lines`` assumes the step's incoming line carry is 0; ``delta`` is how
    much to subtract if it is 1.  ``blk_a``/``blk_b`` are the whole step's
    composed transfer c' = blk_a | (blk_b & c), identical on every device.
    """

    matches_lo: jax.Array
    matches_hi: jax.Array
    lines: jax.Array
    delta: jax.Array
    blk_a: jax.Array
    blk_b: jax.Array


def _add64(a_lo, a_hi, b_lo, b_hi):
    """(lo, hi) + (lo, hi) with carry: exact uint64 in two uint32 lanes."""
    lo = a_lo + b_lo
    carry = (lo < a_lo).astype(jnp.uint32)
    return lo, a_hi + b_hi + carry


def _match_mask(chunk: jax.Array, pattern: np.ndarray) -> jax.Array:
    """bool[n]: True where an occurrence of ``pattern`` starts."""
    m, n = pattern.shape[0], chunk.shape[0]
    if m > n:
        return jnp.zeros((n,), jnp.bool_)
    hit = jnp.ones((n - m + 1,), jnp.bool_)
    for i, b in enumerate(pattern.tolist()):  # m is static: unrolled ANDs
        hit = hit & (chunk[i: n - m + 1 + i] == jnp.uint8(b))
    return jnp.concatenate([hit, jnp.zeros((m - 1,), jnp.bool_)]) if m > 1 else hit


def _or_reset_combine(a, b):
    """Segmented prefix-OR: (reset, value); reset discards the left prefix."""
    a_f, a_v = a
    b_f, b_v = b
    return (a_f | b_f, jnp.where(b_f, b_v, a_v | b_v))


def _row_summary(chunk: jax.Array, pattern: np.ndarray):
    """(matches, seg_cnt, nl, first_m, last_m) for one row, all scalar.

    ``seg_cnt`` counts newline-delimited segments containing >= 1 match
    (leading and trailing partial segments included); ``nl`` = row has a
    newline; ``first_m``/``last_m`` = the leading/trailing segment matched.
    Padding NULs extend the trailing segment but contain no matches (NUL is
    rejected in patterns) and no newlines, so summaries are computable on
    the padded row directly.
    """
    hit = _match_mask(chunk, pattern)
    newline = chunk == jnp.uint8(0x0A)
    # Exclusive segmented prefix-OR of `hit` with newline resets: True where
    # an earlier position in the SAME line already matched.
    _, inc = jax.lax.associative_scan(_or_reset_combine, (newline, hit))
    seen_before = jnp.concatenate([jnp.zeros((1,), jnp.bool_), inc[:-1]])
    # (a newline position itself resets, so inc at the newline is False for
    # the next line's first position after the shift — line state never leaks)
    first_in_line = hit & ~seen_before
    nl_before = jnp.cumsum(newline) > 0  # inclusive: any newline in [0, i]
    in_first_seg = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), ~nl_before[:-1]])  # no newline in [0, i)
    nl_at_or_after = jnp.flip(jnp.cumsum(jnp.flip(newline)) > 0)
    in_last_seg = ~nl_at_or_after  # no newline in [i, n)
    # Per-chunk sums fit uint32 by construction (a chunk holds < 2**32 bytes).
    return (jnp.sum(hit).astype(jnp.uint32),
            jnp.sum(first_in_line).astype(jnp.uint32),
            jnp.any(newline).astype(jnp.uint32),
            jnp.any(hit & in_first_seg).astype(jnp.uint32),
            jnp.any(hit & in_last_seg).astype(jnp.uint32))


def count_matches_in_chunk(chunk: jax.Array, pattern: np.ndarray) -> GrepState:
    """One chunk's (occurrences, matching lines), as a GrepState.

    Treats the chunk as a whole corpus: ``lines`` is the exact per-chunk
    segment count and ``line_carry`` is the trailing open line's match bit.
    """
    matches, seg_cnt, nl, first_m, last_m = _row_summary(chunk, pattern)
    zero = jnp.zeros((), jnp.uint32)
    return GrepState(matches_lo=matches, matches_hi=zero,
                     lines_lo=seg_cnt, lines_hi=zero,
                     line_carry=jnp.where(nl > 0, last_m, first_m))


class GrepJob(MapReduceJob):
    """Pattern-occurrence counting as a :class:`MapReduceJob`.

    The accumulator is four uint32 scalars, so the global reduction is the
    degenerate (and fastest) case of the collective tree-merge: effectively
    a ``psum`` over the mesh.
    """

    def __init__(self, pattern: bytes):
        if not pattern:
            raise ValueError("grep pattern must be non-empty")
        if len(pattern) > 256:
            raise ValueError(f"grep pattern of {len(pattern)} bytes exceeds "
                             "the 256-byte limit (the match mask unrolls one "
                             "fused comparison per pattern byte)")
        if 0 in pattern:
            # NUL is the chunk padding byte: a NUL-bearing pattern would
            # count phantom matches in padding tails.
            raise ValueError("grep pattern must not contain NUL bytes")
        self.pattern = np.frombuffer(pattern, dtype=np.uint8)

    def init_state(self) -> GrepState:
        zero = jnp.zeros((), jnp.uint32)
        return GrepState(zero, zero, zero, zero, zero)

    def map_chunk(self, chunk: jax.Array, chunk_id: jax.Array) -> GrepUpdate:
        """Per-device fallback (no mesh axis): exact within the row, the old
        upper bound across rows (delta=0 disables the seam correction)."""
        matches, seg_cnt, _nl, _fm, _lm = _row_summary(chunk, self.pattern)
        z = jnp.zeros((), jnp.uint32)
        return GrepUpdate(matches, z, seg_cnt, z, z, z)

    def map_chunk_sharded(self, chunk: jax.Array, chunk_id: jax.Array,
                          axis, device_index: jax.Array) -> GrepUpdate:
        """Exact matching-line counting across row seams (module docstring).

        One ``all_gather`` of a 3-word summary per step; everything else is
        static-shape elementwise math over the [D, 3] gathered block.
        """
        matches, seg_cnt, nl, first_m, last_m = _row_summary(chunk, self.pattern)
        idx = device_index  # row order of the gather == Engine's row order
        gathered = jax.lax.all_gather(
            jnp.stack([nl, first_m, last_m]), axis_name=axis)  # [D, 3]
        nl_g, fm_g, lm_g = gathered[:, 0], gathered[:, 1], gathered[:, 2]
        # Row transfer c' = a | (b & c): a newline row pins c to its trailing
        # match; a newline-free row is transparent (ORs its own match in —
        # for such a row first==last==any match, so a = fm works for both).
        a_row = jnp.where(nl_g > 0, lm_g, fm_g)
        b_row = (nl_g == 0).astype(jnp.uint32)

        def compose(x, y):  # y applied after x
            ax, bx = x
            ay, by = y
            return (ay | (by & ax), bx & by)

        a_incl, b_incl = jax.lax.associative_scan(compose, (a_row, b_row))
        one = jnp.ones((1,), jnp.uint32)
        zero1 = jnp.zeros((1,), jnp.uint32)
        a_excl = jnp.concatenate([zero1, a_incl[:-1]])
        b_excl = jnp.concatenate([one, b_incl[:-1]])
        c_d = jnp.take(a_excl, idx)  # my incoming bit, assuming step carry 0
        corrected = seg_cnt - (first_m & c_d)
        # If the step's incoming carry is 1, rows whose whole prefix is
        # transparent (b_excl) and unmatched (~a_excl) additionally see c=1.
        delta = first_m & jnp.take(b_excl, idx) & (1 - jnp.take(a_excl, idx))
        zero = jnp.zeros((), jnp.uint32)
        return GrepUpdate(matches, zero, corrected, delta,
                          a_incl[-1], b_incl[-1])

    def combine(self, state: GrepState, update: GrepUpdate) -> GrepState:
        m_lo, m_hi = _add64(state.matches_lo, state.matches_hi,
                            update.matches_lo, update.matches_hi)
        zero = jnp.zeros((), jnp.uint32)
        l_lo, l_hi = _add64(state.lines_lo, state.lines_hi,
                            update.lines - (state.line_carry & update.delta),
                            zero)
        carry = update.blk_a | (update.blk_b & state.line_carry)
        return GrepState(m_lo, m_hi, l_lo, l_hi, carry)

    def on_input_boundary(self, state: GrepState) -> GrepState:
        """Executor hook at a corpus-member (file) boundary: files are
        independent line streams, so the open-line carry must not leak from
        one file's unterminated last line into the next file's first line
        (the non-stream path greps files separately; this keeps the streamed
        path's semantics identical)."""
        return state._replace(line_carry=jnp.zeros_like(state.line_carry))

    def merge(self, a: GrepState, b: GrepState) -> GrepState:
        m_lo, m_hi = _add64(a.matches_lo, a.matches_hi,
                            b.matches_lo, b.matches_hi)
        l_lo, l_hi = _add64(a.lines_lo, a.lines_hi, b.lines_lo, b.lines_hi)
        # Every device's carry is identical (the block transfer comes from
        # the gathered summaries), so either operand's is fine.
        return GrepState(m_lo, m_hi, l_lo, l_hi, a.line_carry)

    def identity(self) -> str:
        # The pattern IS the job: a different pattern's snapshot has the
        # same state shape but means different counts.
        import hashlib

        return "grep:" + hashlib.sha256(self.pattern.tobytes()).hexdigest()[:16]


class GrepResult(NamedTuple):
    """Host-side result."""

    pattern: bytes
    matches: int  # overlapping occurrences
    lines: int  # matching lines (exact, incl. lines split across rows)


def _state_result(pattern: bytes, state) -> GrepResult:
    lo, hi = int(np.asarray(state.matches_lo)), int(np.asarray(state.matches_hi))
    llo, lhi = int(np.asarray(state.lines_lo)), int(np.asarray(state.lines_hi))
    return GrepResult(pattern, (hi << 32) | lo, (lhi << 32) | llo)


@functools.lru_cache(maxsize=64)
def _jitted_counter(pattern: bytes):
    """One compiled counter per pattern (jit caches per buffer shape)."""
    pat = np.frombuffer(pattern, dtype=np.uint8)
    return jax.jit(lambda c: count_matches_in_chunk(c, pat))


def grep_bytes(data: bytes, pattern: bytes) -> GrepResult:
    """One-call API: pattern counts for an in-memory buffer."""
    from mapreduce_tpu.ops import tokenize as tok_ops

    GrepJob(pattern)  # validate pattern via the single owner of the rules
    buf = np.frombuffer(data, dtype=np.uint8)
    padded = tok_ops.pad_to(buf, max(128, -(-max(buf.shape[0], 1) // 128) * 128))
    return _state_result(pattern, _jitted_counter(pattern)(padded))


def grep_file(path, pattern: bytes, config: Config = DEFAULT_CONFIG,
              mesh=None, **kw) -> GrepResult:
    """Pattern counts over a file via the streaming sharded pipeline."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    mesh = mesh if mesh is not None else data_mesh()
    rr = executor.run_job(GrepJob(pattern), path, config=config,
                          mesh=mesh, **kw)
    return _state_result(pattern, rr.value)
