"""Distributed grep: count occurrences / matching lines of a fixed pattern.

A third model family (after word count and the sketches) riding the same
Engine/collective machinery.  The reference has nothing comparable — its only
workload is word count (``main.cu``) — but pattern search is *the* canonical
MapReduce companion workload, and it exercises a different accumulator shape:
tiny scalar states instead of capacity-sized tables, so the collective merge
is a pure ``psum``-style reduction.

TPU formulation: for a pattern of m bytes, the match mask over a chunk is the
AND of m shifted byte-equality planes — static shapes, no data-dependent
control flow, fully fused by XLA into one elementwise pass over the chunk.
Matching-*line* counting reuses the tokenizer's segmented-scan trick with
newline as the reset class: a match's line has counted it iff an earlier
match shares the line, computed by an exclusive segmented prefix-OR.

Envelope (documented, tested):
  * occurrences are **overlapping** (pattern ``aa`` occurs twice in ``aaa``);
  * a pattern containing separator bytes never matches across a chunk seam
    (the reader cuts at separators), mirroring the n-gram per-chunk envelope;
  * ``lines`` is **exact**, including logical lines split across chunk rows:
    every row also emits a tiny line-boundary summary (has-newline,
    first/last segment matched), the devices share their summaries with one
    ``all_gather`` per step (a few bytes over ICI), and a carry bit in the
    state threads the "current open line already matched" chain across
    steps, so a line counted in one row's trailing segment is not recounted
    by its continuation rows.  The bare per-device :meth:`GrepJob.map_chunk`
    fallback emits the same transfer terms from its own row summary, so
    sequential no-axis use (a 1-device mesh, or the protocol driven by hand)
    is exact too; only mapping rows on parallel devices *without* a mesh
    axis leaves inter-device seams at the documented upper-bound envelope
    (off by at most devices-1, like cross-host ``byte_range`` merges);
  * accumulators are 64-bit (uint32 lo/hi pairs with explicit carry — JAX
    default-x64 is off, so device uint64 is unavailable): counts stay exact
    past 2**32 occurrences, where a single uint32 would silently wrap on
    corpus-scale single-byte patterns.

Exact-line math: rows (in file order) form a monoid chain for the one bit c =
"the currently open line has matched so far".  A row with a newline maps any
incoming c to its own trailing-segment match; a newline-free row is
*transparent*: c' = c OR (row matched).  Per row, segments-with-matches
over-counts the truth by exactly [leading segment matched AND incoming c].
Each transfer function has the boolean-affine form c' = a | (b & c), which
composes associatively, so each device recovers its incoming c (and its
correction if the step's incoming carry turns out to be 1) from the gathered
per-row (a, b) pairs with static-shape prefix products — no sequential host
pass, no per-step device->host sync.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_tpu.config import Config, DEFAULT_CONFIG
from mapreduce_tpu.ops.table import add64 as _add64
from mapreduce_tpu.parallel.mapreduce import MapReduceJob


class GrepState(NamedTuple):
    """Scalar accumulators (a pytree; merged by 64-bit carry addition)."""

    matches_lo: jax.Array  # uint32: overlapping occurrences, low word
    matches_hi: jax.Array  # uint32: high word
    lines_lo: jax.Array  # uint32: lines containing >= 1 occurrence, low word
    lines_hi: jax.Array  # uint32: high word
    line_carry: jax.Array = np.uint32(0)  # uint32 0/1: open line matched so
    # far at this device's stream position (identical on every device — the
    # per-step block transfer is computed from the gathered summaries)


class GrepUpdate(NamedTuple):
    """One row's contribution plus the seam-correction terms (all uint32).

    ``lines`` assumes the step's incoming line carry is 0; ``delta`` is how
    much to subtract if it is 1.  ``blk_a``/``blk_b`` are the whole step's
    composed transfer c' = blk_a | (blk_b & c), identical on every device.
    """

    matches_lo: jax.Array
    matches_hi: jax.Array
    lines: jax.Array
    delta: jax.Array
    blk_a: jax.Array
    blk_b: jax.Array


class ClassPattern:
    """Regex-lite pattern: one allowed byte-SET per position (ROADMAP #3).

    Syntax: plain bytes match themselves; ``.`` matches any byte except
    newline (and the NUL pad); ``[abc]`` / ``[a-z0-9]`` are classes with
    ranges; ``[^...]`` negates (NUL stays excluded so padding can never
    match); ``\\x`` escapes the next byte anywhere.  No repetition or
    alternation — the pattern length is fixed, so the match mask stays ONE
    fused elementwise pass with a couple of compares per class range
    instead of one equality (same TPU cost shape as a literal).
    """

    def __init__(self, spec: bytes):
        self.spec = bytes(spec)
        self.classes: list[tuple[bool, tuple[tuple[int, int], ...]]] = []
        i, n = 0, len(self.spec)
        while i < n:
            b = self.spec[i]
            if b == 0x5C:  # backslash escape
                if i + 1 >= n:
                    raise ValueError("grep pattern ends with a dangling '\\'")
                self.classes.append((False, ((self.spec[i + 1],) * 2,)))
                i += 2
            elif b == 0x2E:  # '.': any byte but newline (NUL auto-excluded)
                self.classes.append((True, ((0x0A, 0x0A),)))
                i += 1
            elif b == 0x5B:  # '[' class
                j = i + 1
                negated = j < n and self.spec[j] == 0x5E
                if negated:
                    j += 1
                ranges: list[tuple[int, int]] = []
                while j < n and self.spec[j] != 0x5D:
                    c = self.spec[j]
                    if c == 0x5C and j + 1 < n:
                        j += 1
                        c = self.spec[j]
                    if (j + 2 < n and self.spec[j + 1] == 0x2D
                            and self.spec[j + 2] != 0x5D):
                        hi = self.spec[j + 2]
                        if hi == 0x5C and j + 3 < n:
                            j += 1
                            hi = self.spec[j + 2]
                        if hi < c:
                            raise ValueError(
                                f"empty range {chr(c)}-{chr(hi)} in grep class")
                        ranges.append((c, hi))
                        j += 3
                    else:
                        ranges.append((c, c))
                        j += 1
                if j >= n:
                    raise ValueError("unterminated '[' class in grep pattern")
                if not ranges:
                    raise ValueError("empty [] class in grep pattern")
                self.classes.append((negated, tuple(ranges)))
                i = j + 1
            else:
                self.classes.append((False, ((b,) * 2,)))
                i += 1
        if not self.classes:
            raise ValueError("grep pattern must be non-empty")
        if len(self.classes) > 256:
            raise ValueError(f"grep pattern of {len(self.classes)} positions "
                             "exceeds the 256-position limit")
        for neg, ranges in self.classes:
            if not neg and any(lo <= 0 <= hi for lo, hi in ranges):
                raise ValueError("grep pattern must not match NUL bytes "
                                 "(the chunk padding byte)")

    def __len__(self) -> int:
        return len(self.classes)

    def tobytes(self) -> bytes:
        """Canonical serialization (job identity / checkpoint fingerprints)."""
        out = [b"C1"]
        for neg, ranges in self.classes:
            out.append(bytes([1 if neg else 0, len(ranges)]))
            out.extend(bytes([lo, hi]) for lo, hi in ranges)
        return b"".join(out)


def _position_hits(window: jax.Array, cls) -> jax.Array:
    """bool mask: window bytes allowed by one (negated, ranges) class."""
    neg, ranges = cls
    m = jnp.zeros(window.shape, jnp.bool_)
    for lo, hi in ranges:
        m = m | (window == jnp.uint8(lo)) if lo == hi else \
            m | ((window >= jnp.uint8(lo)) & (window <= jnp.uint8(hi)))
    if neg:
        m = ~m & (window != jnp.uint8(0))  # padding can never match
    return m


def _match_mask(chunk: jax.Array, pattern) -> jax.Array:
    """bool[n]: True where an occurrence of ``pattern`` starts.

    ``pattern`` is a uint8 array (literal) or a :class:`ClassPattern`.
    """
    classes = pattern.classes if isinstance(pattern, ClassPattern) \
        else [(False, ((int(b),) * 2,)) for b in pattern.tolist()]
    m, n = len(classes), chunk.shape[0]
    if m > n:
        return jnp.zeros((n,), jnp.bool_)
    hit = jnp.ones((n - m + 1,), jnp.bool_)
    for i, cls in enumerate(classes):  # m is static: unrolled ANDs
        hit = hit & _position_hits(chunk[i: n - m + 1 + i], cls)
    return jnp.concatenate([hit, jnp.zeros((m - 1,), jnp.bool_)]) if m > 1 else hit


def _or_reset_combine(a, b):
    """Segmented prefix-OR: (reset, value); reset discards the left prefix."""
    a_f, a_v = a
    b_f, b_v = b
    return (a_f | b_f, jnp.where(b_f, b_v, a_v | b_v))


def _row_summary(chunk: jax.Array, pattern: np.ndarray):
    """(matches, seg_cnt, nl, first_m, last_m) for one row, all scalar —
    the P=1 case of :func:`_row_summary_multi` (single owner of the
    segmented-scan math)."""
    return tuple(x[0] for x in _row_summary_multi(chunk, [pattern]))


def _row_summary_multi(chunk: jax.Array, patterns: list[np.ndarray]):
    """Per-row line-boundary summaries for a static pattern list.

    One pass over the chunk: the P match masks are shifted-equality ANDs
    over the same byte planes, so XLA fuses them into a single read of the
    chunk ("one pass, many masks").  Returns [P]-shaped arrays
    (matches, seg_cnt, nl, first_m, last_m): ``seg_cnt`` counts
    newline-delimited segments with >= 1 match (leading/trailing partial
    segments included), ``nl`` = row has a newline (pattern-independent,
    broadcast to [P]), ``first_m``/``last_m`` = the leading/trailing
    segment matched.  Padding NULs extend the trailing segment but contain
    no matches (NUL is rejected in patterns) and no newlines, so summaries
    are computable on the padded row directly.  The ``seen_before``
    exclusive segmented prefix-OR marks positions whose line already
    matched earlier (a newline resets at its own position, so line state
    never leaks across the shift).
    """
    hits = jnp.stack([_match_mask(chunk, p) for p in patterns])  # [P, n]
    newline = chunk == jnp.uint8(0x0A)  # [n]
    nl_b = jnp.broadcast_to(newline, hits.shape)
    _, inc = jax.lax.associative_scan(_or_reset_combine, (nl_b, hits), axis=1)
    p = hits.shape[0]
    seen_before = jnp.concatenate(
        [jnp.zeros((p, 1), jnp.bool_), inc[:, :-1]], axis=1)
    first_in_line = hits & ~seen_before
    nl_before = jnp.cumsum(newline) > 0
    in_first_seg = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), ~nl_before[:-1]])
    in_last_seg = ~jnp.flip(jnp.cumsum(jnp.flip(newline)) > 0)
    any_nl = jnp.broadcast_to(jnp.any(newline), (p,)).astype(jnp.uint32)
    return (jnp.sum(hits, axis=1).astype(jnp.uint32),
            jnp.sum(first_in_line, axis=1).astype(jnp.uint32),
            any_nl,
            jnp.any(hits & in_first_seg, axis=1).astype(jnp.uint32),
            jnp.any(hits & in_last_seg, axis=1).astype(jnp.uint32))


def _whole_buffer_state(chunk: jax.Array,
                        patterns: list[np.ndarray]) -> GrepState:
    """[P]-leaf GrepState treating the chunk as a whole corpus: ``lines``
    is the exact segment count and ``line_carry`` the trailing open line's
    match bit."""
    matches, seg_cnt, nl, first_m, last_m = _row_summary_multi(chunk, patterns)
    zero = jnp.zeros_like(matches)
    return GrepState(matches_lo=matches, matches_hi=zero,
                     lines_lo=seg_cnt, lines_hi=zero,
                     line_carry=jnp.where(nl > 0, last_m, first_m))


def count_matches_in_chunk(chunk: jax.Array, pattern: np.ndarray) -> GrepState:
    """One chunk's (occurrences, matching lines): the P=1 case of
    :func:`_whole_buffer_state`, as scalar leaves."""
    return jax.tree.map(lambda x: x[0], _whole_buffer_state(chunk, [pattern]))


def _validate_pattern(pattern: bytes) -> np.ndarray:
    """Single owner of the literal-pattern rules; returns the uint8 view."""
    if not pattern:
        raise ValueError("grep pattern must be non-empty")
    if len(pattern) > 256:
        raise ValueError(f"grep pattern of {len(pattern)} bytes exceeds "
                         "the 256-byte limit (the match mask unrolls one "
                         "fused comparison per pattern byte)")
    if 0 in pattern:
        # NUL is the chunk padding byte: a NUL-bearing pattern would
        # count phantom matches in padding tails.
        raise ValueError("grep pattern must not contain NUL bytes")
    return np.frombuffer(pattern, dtype=np.uint8)


def compile_pattern(pattern: bytes, syntax: str = "literal"):
    """Compile a pattern spec: 'literal' -> uint8 view, 'class' ->
    :class:`ClassPattern` (regex-lite byte classes)."""
    if syntax == "class":
        return ClassPattern(pattern)
    if syntax != "literal":
        raise ValueError(f"unknown grep syntax {syntax!r} "
                         "(expected 'literal' or 'class')")
    return _validate_pattern(pattern)


def _single_row_update(matches, seg_cnt, nl, first_m, last_m) -> "GrepUpdate":
    """Package one row's summary as its own boolean-affine transfer (the
    no-axis fallback): ``a`` = trailing (or, newline-free, only) segment's
    match, ``b`` = row has no newline, ``delta`` = leading-segment-matched.
    Shape-polymorphic: scalar ([]-leaf) and multi-pattern ([P]-leaf)
    summaries alike."""
    return GrepUpdate(matches, jnp.zeros_like(matches), seg_cnt, first_m,
                      jnp.where(nl > 0, last_m, first_m),
                      (nl == 0).astype(jnp.uint32))


def _compose_transfer(x, y):
    """Boolean-affine composition: y applied after x (module docstring)."""
    ax, bx = x
    ay, by = y
    return (ay | (by & ax), bx & by)


def _seam_corrected_update(matches, seg_cnt, nl, first_m, last_m,
                           axis, device_index) -> "GrepUpdate":
    """Shared seam-correction core for single ([] summaries) and multi
    ([P] summaries) pattern jobs: all_gather the row summaries over the
    mesh axis, recover this device's incoming carry bit by prefix
    composition, and package the corrected contribution."""
    gathered = jax.lax.all_gather(
        jnp.stack([nl, first_m, last_m]), axis_name=axis)  # [D, 3, ...]
    nl_g, fm_g, lm_g = gathered[:, 0], gathered[:, 1], gathered[:, 2]
    # Row transfer c' = a | (b & c): a newline row pins c to its trailing
    # match; a newline-free row is transparent (ORs its own match in —
    # for such a row first==last==any match, so a = fm works for both).
    a_row = jnp.where(nl_g > 0, lm_g, fm_g)
    b_row = (nl_g == 0).astype(jnp.uint32)
    a_incl, b_incl = jax.lax.associative_scan(
        _compose_transfer, (a_row, b_row), axis=0)
    pad = (1,) + a_incl.shape[1:]
    a_excl = jnp.concatenate([jnp.zeros(pad, jnp.uint32), a_incl[:-1]], axis=0)
    b_excl = jnp.concatenate([jnp.ones(pad, jnp.uint32), b_incl[:-1]], axis=0)
    c_d = jnp.take(a_excl, device_index, axis=0)  # incoming bit, step carry 0
    corrected = seg_cnt - (first_m & c_d)
    # If the step's incoming carry is 1, rows whose whole prefix is
    # transparent (b_excl) and unmatched (~a_excl) additionally see c=1.
    delta = first_m & jnp.take(b_excl, device_index, axis=0) \
        & (jnp.uint32(1) - c_d)
    return GrepUpdate(matches, jnp.zeros_like(matches), corrected, delta,
                      a_incl[-1], b_incl[-1])


class GrepJob(MapReduceJob):
    """Pattern-occurrence counting as a :class:`MapReduceJob`.

    The accumulator is a handful of uint32 scalars, so the global reduction
    is the degenerate (and fastest) case of the collective tree-merge:
    effectively a ``psum`` over the mesh.
    """

    def __init__(self, pattern: bytes, syntax: str = "literal"):
        self.pattern = compile_pattern(pattern, syntax)

    def init_state(self) -> GrepState:
        zero = jnp.zeros((), jnp.uint32)
        return GrepState(zero, zero, zero, zero, zero)

    def map_chunk(self, chunk: jax.Array, chunk_id: jax.Array) -> GrepUpdate:
        """Per-device fallback (no mesh axis): the single-row transfer.

        Exactness needs no collective here — one row's boolean-affine
        transfer ``c' = a | (b & c)`` (module docstring) is computable from
        its own summary: ``a`` = the trailing (or, newline-free, only)
        segment's match, ``b`` = row has no newline, and the over-count
        correction ``delta`` = leading-segment-matched, applied by
        ``combine`` against the carry threaded through the state.  Driving
        rows *sequentially* through map_chunk+combine (a 1-device mesh, or
        the job protocol by hand) is therefore exactly as accurate as the
        sharded path.  Only when a caller maps rows on PARALLEL devices
        without a mesh axis do seams between devices degrade ``lines`` to
        an upper bound (off by at most devices-1) — the same documented
        envelope as merging independent per-host ``byte_range`` runs
        (:meth:`merge`)."""
        return _single_row_update(*_row_summary(chunk, self.pattern))

    def map_chunk_sharded(self, chunk: jax.Array, chunk_id: jax.Array,
                          axis, device_index: jax.Array) -> GrepUpdate:
        """Exact matching-line counting across row seams (module docstring).

        One ``all_gather`` of a 3-word summary per step; everything else is
        static-shape elementwise math over the [D, 3] gathered block.
        """
        summaries = _row_summary(chunk, self.pattern)
        return _seam_corrected_update(*summaries, axis, device_index)

    def combine(self, state: GrepState, update: GrepUpdate) -> GrepState:
        m_lo, m_hi = _add64(state.matches_lo, state.matches_hi,
                            update.matches_lo, update.matches_hi)
        zero = jnp.zeros((), jnp.uint32)
        l_lo, l_hi = _add64(state.lines_lo, state.lines_hi,
                            update.lines - (state.line_carry & update.delta),
                            zero)
        carry = update.blk_a | (update.blk_b & state.line_carry)
        return GrepState(m_lo, m_hi, l_lo, l_hi, carry)

    def on_input_boundary(self, state: GrepState) -> GrepState:
        """Executor hook at a corpus-member (file) boundary: files are
        independent line streams, so the open-line carry must not leak from
        one file's unterminated last line into the next file's first line
        (the non-stream path greps files separately; this keeps the streamed
        path's semantics identical)."""
        return state._replace(line_carry=jnp.zeros_like(state.line_carry))

    def partial_reset(self, local: GrepState) -> GrepState:
        """Post-partial-merge reset (ISSUE 20 leg 2): the counters were
        shipped into the resident accumulator, but ``line_carry`` is
        CROSS-STEP context — the open line at this device's stream
        position — which the next step's combine still corrects against.
        Called per device inside shard_map on the LOCAL state."""
        init = self.init_state()
        return init._replace(line_carry=local.line_carry)

    # -- data-plane telemetry (ISSUE 11 satellite: grep previously forced
    # -- telemetered runs into plain mode, leaving the classifier — and the
    # -- combiner's 'auto' switch — blind to this family) -----------------

    def map_chunk_stats_sharded(self, chunk, chunk_id, axis, device_index):
        """Stats-mode map: grep has no kernel window, rescue tier, or
        count table, so the chunk counters are structurally zero — the
        value is the chunks-mapped accounting plus the running gauges
        ``state_stats`` fills, which complete the data record every
        shipped family now emits."""
        from mapreduce_tpu.ops import datastats

        return self.map_chunk_sharded(chunk, chunk_id, axis, device_index), \
            datastats.map_stats()

    def state_stats(self, state: GrepState, stats):
        """Fill the running gauges: grep's data volume is its match count
        (the ``tokens`` lane of the data record — the classifier's ratios
        all divide by it, and zero matches degrade every signal to None,
        never to an error)."""
        from mapreduce_tpu.ops.table import sum64

        m_lo, m_hi = state.matches_lo, state.matches_hi
        if getattr(m_lo, "ndim", 0):  # MultiGrep: [P] leaves fold to totals
            m_lo, m_hi = sum64(m_lo, m_hi)
        return stats._replace(total_lo=m_lo, total_hi=m_hi)

    def analysis_observables(self, state: GrepState):
        """graphcheck metadata: the result-bearing leaves the randomized
        merge property check compares.  ``line_carry`` is a coordination
        bit — identical on every device within one run (computed from the
        gathered summaries), so ``merge`` keeping either operand's is
        correct — but states built from DIFFERENT chunks disagree on it,
        which a bitwise commutativity check would misread as a reducer
        bug."""
        return (state.matches_lo, state.matches_hi,
                state.lines_lo, state.lines_hi)

    def merge(self, a: GrepState, b: GrepState) -> GrepState:
        """Merge two accumulated states (collective finish, or cross-host).

        Within one ``run_job`` invocation every device's carry is identical
        (the block transfer comes from the gathered summaries), so summing
        lines is exact and either operand's carry is fine.  Merging states
        from INDEPENDENT per-host ``byte_range`` runs is different: host
        ranges are aligned to token separators (any whitespace), so a
        logical line straddling two ranges appears in both and ``lines``
        degrades to an upper bound (off by at most hosts-1).  For exact
        cross-host lines, align the ranges to newlines
        (``align_range_to_separator(..., separators=b"\\n")``) so no line
        straddles a seam.  ``matches`` is exact either way.
        """
        m_lo, m_hi = _add64(a.matches_lo, a.matches_hi,
                            b.matches_lo, b.matches_hi)
        l_lo, l_hi = _add64(a.lines_lo, a.lines_hi, b.lines_lo, b.lines_hi)
        return GrepState(m_lo, m_hi, l_lo, l_hi, a.line_carry)

    def identity(self) -> str:
        # The pattern IS the job: a different pattern's snapshot has the
        # same state shape but means different counts.  Class patterns get
        # a distinct prefix so a literal spelling the same bytes as a
        # class's canonical form cannot cross-resume.
        import hashlib

        kind = "grepc" if isinstance(self.pattern, ClassPattern) else "grep"
        return f"{kind}:" + hashlib.sha256(
            self.pattern.tobytes()).hexdigest()[:16]


class MultiGrepJob(GrepJob):
    """P patterns counted in ONE pass over the corpus (ROADMAP r1 #6).

    The P match masks are shifted-equality tests over the same byte planes,
    so XLA fuses them into a single chunk read — P patterns cost barely more
    than one.  State leaves are [P]-shaped; since :class:`GrepState`'s
    combine/merge/boundary math is shape-polymorphic elementwise code, the
    accumulation, 64-bit carries, exact line counting, and collective merge
    are all inherited unchanged.
    """

    def __init__(self, patterns, syntax: str = "literal"):
        if not patterns:
            raise ValueError("need at least one grep pattern")
        self.patterns = [compile_pattern(p, syntax) for p in patterns]

    def init_state(self) -> GrepState:
        z = jnp.zeros((len(self.patterns),), jnp.uint32)
        return GrepState(z, jnp.array(z), jnp.array(z), jnp.array(z),
                         jnp.array(z))

    def map_chunk(self, chunk: jax.Array, chunk_id: jax.Array) -> GrepUpdate:
        """Single-row transfer, [P]-shaped: see :meth:`GrepJob.map_chunk`."""
        return _single_row_update(*_row_summary_multi(chunk, self.patterns))

    def map_chunk_sharded(self, chunk: jax.Array, chunk_id: jax.Array,
                          axis, device_index: jax.Array) -> GrepUpdate:
        summaries = _row_summary_multi(chunk, self.patterns)
        return _seam_corrected_update(*summaries, axis, device_index)

    def identity(self) -> str:
        import hashlib

        h = hashlib.sha256()
        kinds = ""
        for p in self.patterns:
            kinds += "c" if isinstance(p, ClassPattern) else "l"
            h.update(len(p.tobytes()).to_bytes(4, "little") + p.tobytes())
        return f"grep{len(self.patterns)}{kinds[:8]}:" + h.hexdigest()[:16]


class GrepResult(NamedTuple):
    """Host-side result."""

    pattern: bytes
    matches: int  # overlapping occurrences
    lines: int  # matching lines (exact, incl. lines split across rows)


def _state_result(pattern: bytes, state) -> GrepResult:
    lo, hi = int(np.asarray(state.matches_lo)), int(np.asarray(state.matches_hi))
    llo, lhi = int(np.asarray(state.lines_lo)), int(np.asarray(state.lines_hi))
    return GrepResult(pattern, (hi << 32) | lo, (lhi << 32) | llo)


@functools.lru_cache(maxsize=64)
def _jitted_counter(pattern: bytes, syntax: str):
    """One compiled counter per pattern (jit caches per buffer shape)."""
    pat = compile_pattern(pattern, syntax)
    return jax.jit(lambda c: count_matches_in_chunk(c, pat))


def grep_bytes(data: bytes, pattern: bytes,
               syntax: str = "literal") -> GrepResult:
    """One-call API: pattern counts for an in-memory buffer."""
    from mapreduce_tpu.ops import tokenize as tok_ops

    GrepJob(pattern, syntax)  # validate via the single owner of the rules
    buf = np.frombuffer(data, dtype=np.uint8)
    padded = tok_ops.pad_to(buf, max(128, -(-max(buf.shape[0], 1) // 128) * 128))
    return _state_result(pattern, _jitted_counter(pattern, syntax)(padded))


def grep_file(path, pattern: bytes, config: Config = DEFAULT_CONFIG,
              mesh=None, syntax: str = "literal", **kw) -> GrepResult:
    """Pattern counts over a file via the streaming sharded pipeline."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    mesh = mesh if mesh is not None else data_mesh()
    rr = executor.run_job(GrepJob(pattern, syntax), path, config=config,
                          mesh=mesh, **kw)
    return _state_result(pattern, rr.value)


def _multi_results(patterns: list[bytes], state) -> list[GrepResult]:
    """Split a [P]-leaf state into per-pattern results."""
    m_lo = np.asarray(state.matches_lo).astype(np.int64)
    m_hi = np.asarray(state.matches_hi).astype(np.int64)
    l_lo = np.asarray(state.lines_lo).astype(np.int64)
    l_hi = np.asarray(state.lines_hi).astype(np.int64)
    return [GrepResult(p, int(m_hi[i] << 32 | m_lo[i]),
                       int(l_hi[i] << 32 | l_lo[i]))
            for i, p in enumerate(patterns)]


@functools.lru_cache(maxsize=16)
def _jitted_multi_counter(patterns: tuple[bytes, ...], syntax: str):
    pats = [compile_pattern(p, syntax) for p in patterns]
    return jax.jit(lambda chunk: _whole_buffer_state(chunk, pats))


def grep_bytes_multi(data: bytes, patterns: list[bytes],
                     syntax: str = "literal") -> list[GrepResult]:
    """One-call multi-pattern API: P patterns, one pass over the buffer."""
    from mapreduce_tpu.ops import tokenize as tok_ops

    MultiGrepJob(patterns, syntax)  # validate via the single owner
    buf = np.frombuffer(data, dtype=np.uint8)
    padded = tok_ops.pad_to(buf, max(128, -(-max(buf.shape[0], 1) // 128) * 128))
    state = _jitted_multi_counter(tuple(patterns), syntax)(padded)
    return _multi_results(patterns, state)


def grep_file_multi(path, patterns: list[bytes],
                    config: Config = DEFAULT_CONFIG, mesh=None,
                    syntax: str = "literal", **kw) -> list[GrepResult]:
    """Multi-pattern counts over a file via the streaming sharded pipeline:
    one ingest, one fused device pass, P exact (matches, lines) pairs."""
    from mapreduce_tpu.parallel.mesh import data_mesh
    from mapreduce_tpu.runtime import executor

    mesh = mesh if mesh is not None else data_mesh()
    rr = executor.run_job(MultiGrepJob(patterns, syntax), path, config=config,
                          mesh=mesh, **kw)
    return _multi_results(patterns, rr.value)
