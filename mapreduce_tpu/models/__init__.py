"""Built-in model registry.

One place that names every shipped model family and builds a
representative job for it — the surface the graphcheck CLI (and any future
model-zoo tooling) enumerates.  Factories take a
:class:`~mapreduce_tpu.config.Config` and return a fully-constructed job;
models whose jobs are config-free by construction (grep: the pattern IS
the job, there is no sizing to configure) accept and ignore it, so the
registry surface stays uniform.  The default analysis config keeps shapes
small (tracing and the randomized property checks run on the host in
seconds, not minutes).
"""

from __future__ import annotations

from typing import Callable, Dict

from mapreduce_tpu.config import Config

# Small shapes for static analysis / smoke tracing: the jaxprs are the
# same graphs as production, just with smaller static dimensions.
ANALYSIS_CONFIG = Config(chunk_bytes=1 << 10, table_capacity=512,
                         backend="xla")

# Radix-sort-impl wordcount (round 6): the production-shaped pallas program
# with the Pallas radix partition in the aggregation seam, at the smallest
# chunk the pallas backend admits (whole lane segments of 2W+2 bytes) —
# registered so the graphcheck gate (hostsync / sharding / overflow /
# algebra passes) certifies the radix program before dispatch like every
# other shipped family.
RADIX_ANALYSIS_CONFIG = Config(chunk_bytes=128 * 66, table_capacity=512,
                               backend="pallas",
                               sort_impl="radix_partition")

# Production-shaped pallas wordcount (the shipped default path: stable2
# lane-major compact kernel + XLA aggregation sort), at one full 384-row
# kernel window per lane.  Registered for the costcheck passes: the cost
# pass re-derives the round-6 sort pricing (2.6-3.4 effective HBM passes)
# from THIS program's traced sort equation, and the vmem/kernelrace passes
# certify the stable2 kernel geometry from its pallas_call bindings.  The
# jaxprs are the production graphs with a smaller grid.
PALLAS_ANALYSIS_CONFIG = Config(chunk_bytes=128 * 384, table_capacity=512,
                                backend="pallas")

# Fused map path (ISSUE 6): the same production-shaped stable2 pallas
# program with Config.map_impl='fused' — tokenize -> hash -> window
# compaction in ONE pallas_call, no token-plane round-trip to HBM.  Same
# chunk geometry as PALLAS_ANALYSIS_CONFIG so the hbm-cost pass's
# `effective_input_passes` is directly comparable: the cost pass ERROR-
# gates this model strictly below the split-path wordcount_pallas
# baseline (the machine-checked before/after of the fusion).
FUSED_ANALYSIS_CONFIG = Config(chunk_bytes=128 * 384, table_capacity=512,
                               backend="pallas", map_impl="fused")

# Skew-adaptive map-side combiner pair (ISSUE 11): the Zipf-shaped model
# with the hot-key cache ON vs its combiner-off twin, both fused/stable2
# at one shared chunk geometry so the hbm-cost combiner gate compares
# like with like.  The chunk is 128 * 512 — the analyzer's 64 KiB
# tracing cap, and one lane segment spanning a whole combiner window —
# so the sort-row delta is exact window arithmetic: nocombiner grids 3
# 384-row windows of 128 slots (49152 sort rows), the combiner 2
# 512-row windows (32768 rows, −33%; −25% at the 32 MB production chunk
# where the padding window amortizes away).
COMBINER_ANALYSIS_CONFIG = Config(chunk_bytes=128 * 512, table_capacity=512,
                                  backend="pallas", map_impl="fused",
                                  combiner="hot-cache")
NOCOMBINER_ANALYSIS_CONFIG = Config(chunk_bytes=128 * 512,
                                    table_capacity=512,
                                    backend="pallas", map_impl="fused")


def _wordcount(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    return WordCountJob(config)


def _grep(config: Config):
    from mapreduce_tpu.models.grep import GrepJob

    del config  # GrepJob is config-free: the pattern is the whole job
    return GrepJob(b"the")


def _sample(config: Config):
    from mapreduce_tpu.models.sample import ReservoirSampleJob

    return ReservoirSampleJob(16, config)


def _ngram(config: Config):
    from mapreduce_tpu.models.wordcount import NGramCountJob

    return NGramCountJob(2, config)


def _sketch(config: Config):
    from mapreduce_tpu.models.wordcount import (SketchedWordCountJob,
                                                WordCountJob)

    return SketchedWordCountJob(WordCountJob(config))


def _wordcount_radix(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config like grep's pinned pattern: the model EXISTS to put the
    # radix program in front of the analysis passes, so the caller's sizing
    # config is deliberately ignored.
    del config
    return WordCountJob(RADIX_ANALYSIS_CONFIG)


def _wordcount_pallas(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config (see _wordcount_radix): the model exists to put the
    # shipped stable2 pallas program in front of the costcheck passes.
    del config
    return WordCountJob(PALLAS_ANALYSIS_CONFIG)


def _wordcount_fused(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config (see _wordcount_radix): the model exists to put the
    # fused map program in front of the full graphcheck/costcheck gate,
    # with its cost baseline error-gated below the split path's.
    del config
    return WordCountJob(FUSED_ANALYSIS_CONFIG)


def _wordcount_combiner(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config (see _wordcount_radix): the Zipf-shaped combiner-ON
    # program — the hbm-cost combiner gate prices it strictly below its
    # combiner-off twin, and the vmem/kernelrace passes certify the
    # hot-key cache's revisited-output discipline.
    del config
    return WordCountJob(COMBINER_ANALYSIS_CONFIG)


def _wordcount_nocombiner(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config: the combiner-off twin at the SAME chunk geometry —
    # the baseline the combiner gate compares against.
    del config
    return WordCountJob(NOCOMBINER_ANALYSIS_CONFIG)


def _instrumented(job):
    """Mark a job so ``analysis.trace.trace_engine`` builds the Engine in
    data-stats mode (ISSUE 8): the traced step program is the INSTRUMENTED
    one telemetered runs dispatch — map counters + state gauges returned
    next to the state — so the hbm-cost pass prices exactly what
    observability costs (ERROR-gated within 1% of the uninstrumented
    twin's baseline) and the host-sync pass certifies the stats path adds
    no host coupling."""
    job.analysis_data_stats = True
    return job


def _wordcount_telemetry(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config (see _wordcount_pallas): the data-stats twin of the
    # shipped stable2 pallas program, priced against it at 1%.
    del config
    return _instrumented(WordCountJob(PALLAS_ANALYSIS_CONFIG))


def _wordcount_fused_telemetry(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config: the data-stats twin of the fused map program.
    del config
    return _instrumented(WordCountJob(FUSED_ANALYSIS_CONFIG))


def _fleet(job, processes: int, local_devices: int, merge: str = "tree"):
    """Mark a job so analysis certifies it over a SIMULATED fleet
    topology (ISSUE 16): ``analysis_fleet`` makes ``AnalysisContext``
    build the process-major mesh (outer axis rides DCN) and lets the
    collective-cost pass attribute link levels; ``analysis_merge_strategy``
    selects the Engine merge the traced finish program builds."""
    job.analysis_fleet = {"processes": processes,
                          "local_devices": local_devices}
    job.analysis_merge_strategy = merge
    return job


def _wordcount_fleet2(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config (see _wordcount_radix): the 2-host x 4-device fleet
    # twin — the hierarchical tree merge's butterfly runs per level
    # (inner ICI axis first, one merged payload across DCN), so the
    # collective-cost pass prices a real 2-D ICI/DCN program in CI.
    del config
    return _fleet(WordCountJob(ANALYSIS_CONFIG), processes=2,
                  local_devices=4)


def _wordcount_fleet2x4(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config: the 2-host x 4-device twin on the PLACED hierarchical
    # merge (ISSUE 20) — key-range all_to_all + owner-reduce + all_gather
    # confined to the inner ICI axis, then one butterfly tree leg across
    # DCN — so the collective-cost pass prices the planner's 2-D
    # skew-sensitive program (hier-kr-tree) in CI next to the per-level
    # tree twin (_wordcount_fleet2) over the identical topology.
    del config
    return _fleet(WordCountJob(ANALYSIS_CONFIG), processes=2,
                  local_devices=4, merge="hier-kr-tree")


def _wordcount_fleet8(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    # Pinned config: the 8-host x 1-device twin on the keyrange merge —
    # the budgeted all_to_all + owner-reduce + all_gather program over a
    # flattened all-DCN axis, the other end of the planner's tradeoff.
    del config
    return _fleet(WordCountJob(ANALYSIS_CONFIG), processes=8,
                  local_devices=1, merge="keyrange")


_REGISTRY: Dict[str, Callable[[Config], object]] = {
    "wordcount": _wordcount,
    "grep": _grep,
    "sample": _sample,
    "ngram": _ngram,
    "sketch": _sketch,
    "wordcount_radix": _wordcount_radix,
    "wordcount_pallas": _wordcount_pallas,
    "wordcount_fused": _wordcount_fused,
    "wordcount_combiner": _wordcount_combiner,
    "wordcount_nocombiner": _wordcount_nocombiner,
    "wordcount_telemetry": _wordcount_telemetry,
    "wordcount_fused_telemetry": _wordcount_fused_telemetry,
    "wordcount_fleet2": _wordcount_fleet2,
    "wordcount_fleet2x4": _wordcount_fleet2x4,
    "wordcount_fleet8": _wordcount_fleet8,
}


def model_names() -> list[str]:
    return list(_REGISTRY)


def build_model(name: str, config: Config = ANALYSIS_CONFIG):
    """Construct the named built-in model's job."""
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; "
                         f"known: {', '.join(_REGISTRY)}") from None
    return factory(config)


__all__ = ["ANALYSIS_CONFIG", "COMBINER_ANALYSIS_CONFIG",
           "FUSED_ANALYSIS_CONFIG", "NOCOMBINER_ANALYSIS_CONFIG",
           "PALLAS_ANALYSIS_CONFIG", "RADIX_ANALYSIS_CONFIG",
           "build_model", "model_names"]
