"""Built-in model registry.

One place that names every shipped model family and builds a
representative job for it — the surface the graphcheck CLI (and any future
model-zoo tooling) enumerates.  Factories take a
:class:`~mapreduce_tpu.config.Config` and return a fully-constructed job;
models whose jobs are config-free by construction (grep: the pattern IS
the job, there is no sizing to configure) accept and ignore it, so the
registry surface stays uniform.  The default analysis config keeps shapes
small (tracing and the randomized property checks run on the host in
seconds, not minutes).
"""

from __future__ import annotations

from typing import Callable, Dict

from mapreduce_tpu.config import Config

# Small shapes for static analysis / smoke tracing: the jaxprs are the
# same graphs as production, just with smaller static dimensions.
ANALYSIS_CONFIG = Config(chunk_bytes=1 << 10, table_capacity=512,
                         backend="xla")


def _wordcount(config: Config):
    from mapreduce_tpu.models.wordcount import WordCountJob

    return WordCountJob(config)


def _grep(config: Config):
    from mapreduce_tpu.models.grep import GrepJob

    del config  # GrepJob is config-free: the pattern is the whole job
    return GrepJob(b"the")


def _sample(config: Config):
    from mapreduce_tpu.models.sample import ReservoirSampleJob

    return ReservoirSampleJob(16, config)


def _ngram(config: Config):
    from mapreduce_tpu.models.wordcount import NGramCountJob

    return NGramCountJob(2, config)


def _sketch(config: Config):
    from mapreduce_tpu.models.wordcount import (SketchedWordCountJob,
                                                WordCountJob)

    return SketchedWordCountJob(WordCountJob(config))


_REGISTRY: Dict[str, Callable[[Config], object]] = {
    "wordcount": _wordcount,
    "grep": _grep,
    "sample": _sample,
    "ngram": _ngram,
    "sketch": _sketch,
}


def model_names() -> list[str]:
    return list(_REGISTRY)


def build_model(name: str, config: Config = ANALYSIS_CONFIG):
    """Construct the named built-in model's job."""
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; "
                         f"known: {', '.join(_REGISTRY)}") from None
    return factory(config)


__all__ = ["ANALYSIS_CONFIG", "build_model", "model_names"]
